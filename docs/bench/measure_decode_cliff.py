"""Quantify the release-and-reuse decode fix on the bench host.

Measures the streamed-decode consumer's sustained rate at the 5k-node
shape with the process pushed past the host's ~8 GB page-backing cliff
(docs/bench/r04-host-page-backing.json), in three regimes:
  hold      — every pod's annotation strings kept live (the old bench
              consumer; every page is a fresh fault)
  release   — strings dropped after size-accounting (reference reflector
              semantics) with default glibc (munmap on free -> re-fault)
  release+mallopt — plus tune_host_allocator() (arena reuse, no faults)

Writes docs/bench/r05-decode-cliff.json.  Run on an idle host.
"""

import json
import resource
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[2]))

from kube_scheduler_simulator_tpu.utils.platform import force_cpu

force_cpu()

import numpy as np

from kube_scheduler_simulator_tpu.framework.replay import replay
from kube_scheduler_simulator_tpu.models.workloads import baseline_config
from kube_scheduler_simulator_tpu.state.compile import compile_workload
from kube_scheduler_simulator_tpu.store.decode import decode_chunk_into
from kube_scheduler_simulator_tpu.utils.platform import tune_host_allocator

N_PODS = 600

nodes, pods, cfg = baseline_config(4, scale=0.06, seed=0, node_scale=1.0)
cw = compile_workload(nodes, pods, cfg)
rr = replay(cw, chunk=512)
ballast = np.ones(int(8.3e9 // 8), dtype=np.float64)  # touch past the cliff
rss0 = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1e6


def run(tag, hold):
    kept = []
    t0 = time.time()
    total = 0
    n = min(N_PODS, len(pods))
    for lo in range(0, n, 512):
        hi = min(lo + 512, n)
        sink = [None] * (hi - lo)
        decode_chunk_into(rr, lo, hi, sink, base=lo)
        total += sum(sum(len(v) for v in a.values()) for a in sink if a)
        if hold:
            kept.append(sink)
    dt = time.time() - t0
    rate = n / dt
    print(f"{tag}: {dt:.2f}s -> {rate:.0f} pods/s ({total/1e9:.2f} GB built)",
          flush=True)
    return round(rate, 1)


out = {"rss_gb_before": round(rss0, 2), "pods": min(N_PODS, len(pods)),
       "nodes": len(nodes)}
out["hold_pods_per_sec"] = run("hold           ", hold=True)
out["release_pods_per_sec"] = run("release        ", hold=False)
out["mallopt_applied"] = tune_host_allocator()
out["release_mallopt_pods_per_sec"] = run("release+mallopt", hold=False)
out["release_mallopt_pass2"] = run("release+mallopt (pass 2)", hold=False)

Path(__file__).with_name("r05-decode-cliff.json").write_text(
    json.dumps(out, indent=1))
print(json.dumps(out))
