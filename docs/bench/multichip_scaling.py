"""Multichip scaling artifact: sharded replay at production node shape.

Round-3 verdict weak #4: multichip evidence was smoke-depth.  This runs a
>=1k-pod replay at the full 5k-node config-4 shape on a virtual device
mesh, asserts byte-parity of every annotation vs the unsharded replay,
and records shard-count-vs-throughput plus a dp-speculative engine wave.

On the virtual CPU mesh all "devices" share host cores, so the
throughput CURVE shows SPMD structure (the program builds, shards, and
executes at every mesh size), not hardware speedup — on real multi-chip
the same code lays the node axis over ICI (parallel/mesh.py).

The --scale mode runs the columnar data-plane curve instead: 25k/50k/
100k-node waves on the ColumnarStatusStore (cluster/columnar.py), each
point parity-pinned against the dict data plane (same bank rows
materialized through the pre-columnar path), with an interleaved
same-process workload-build A/B at 100k, per-point host RSS +
HBM/D2H gauges, and TRACER counters proving an unchanged node set
never rebuilds the node table (docs/data-plane.md).

Usage:
  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
      python docs/bench/multichip_scaling.py [outfile]
  JAX_PLATFORMS=cpu python docs/bench/multichip_scaling.py --scale [outfile]
"""

from __future__ import annotations

import json
import sys
import time

sys.path.insert(0, ".")
from kube_scheduler_simulator_tpu.utils.platform import force_cpu

force_cpu()

import jax


def _rss_mb() -> float:
    """Current (not peak) resident set of this process, in MB."""
    import os
    try:
        with open("/proc/self/statm") as f:
            pages = int(f.read().split()[1])
        return pages * os.sysconf("SC_PAGE_SIZE") / 1e6
    except (OSError, ValueError, IndexError):
        import resource
        return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1e3


def _tree_equal(a, b) -> bool:
    import numpy as np

    la, ta = jax.tree_util.tree_flatten(a)
    lb, tb = jax.tree_util.tree_flatten(b)
    if str(ta) != str(tb) or len(la) != len(lb):
        return False
    for x, y in zip(la, lb):
        try:
            ok = np.array_equal(np.asarray(x), np.asarray(y))
        except Exception:
            ok = x == y
        if not ok:
            return False
    return True


def scale_curve(out_path: str):
    """25k/50k/100k-node columnar data-plane curve (see module docstring)."""
    import copy
    import os

    import numpy as np

    from kube_scheduler_simulator_tpu.cluster.store import ObjectStore
    from kube_scheduler_simulator_tpu.framework.engine import SchedulerEngine
    from kube_scheduler_simulator_tpu.models.workloads import (
        make_nodes_columnar, make_pods_columnar, make_pods)
    from kube_scheduler_simulator_tpu.plugins.registry import PluginSetConfig
    from kube_scheduler_simulator_tpu.state.compile import compile_workload
    from kube_scheduler_simulator_tpu.utils.blackbox import TELEMETRY
    from kube_scheduler_simulator_tpu.utils.tracing import TRACER

    POINTS = (25_000, 50_000, 100_000)
    PODS = 400
    cfg = PluginSetConfig(enabled=[
        "NodeResourcesFit", "NodeResourcesBalancedAllocation",
        "TaintToleration"])

    def counters():
        return dict(TRACER.summary()["counters"])

    def delta(c_after, c_before, key):
        return c_after.get(key, 0) - c_before.get(key, 0)

    points = []
    for n in POINTS:
        node_bank = make_nodes_columnar(n, seed=5, taint_fraction=0.02)
        pod_bank = make_pods_columnar(PODS, seed=6)
        store = ObjectStore()
        store.load_columnar("nodes", node_bank)
        store.load_columnar("pods", pod_bank)
        shared_nodes, _ = store.list("nodes", copy_objects=False)
        shared_pods, _ = store.list("pods", copy_objects=False)
        # the dict baseline is THIS bank's rows materialized to plain
        # dicts (LazyManifest.__deepcopy__), so both arms compile the
        # byte-identical population — parity, not generator agreement
        dict_nodes = [copy.deepcopy(o) for o in shared_nodes]
        dict_pods = [copy.deepcopy(o) for o in shared_pods]

        # interleaved same-process build A/B: dict, columnar, dict,
        # columnar — min of each arm, so warmup hits both arms equally
        t_dict, t_col = [], []
        cw_d = cw_c = None
        for _ in range(2):
            t0 = time.perf_counter()
            cw_d = compile_workload(dict_nodes, dict_pods, cfg)
            t_dict.append(time.perf_counter() - t0)
            t0 = time.perf_counter()
            cw_c = compile_workload(
                shared_nodes, shared_pods, cfg,
                pod_columns=getattr(shared_pods, "columns", None))
            t_col.append(time.perf_counter() - t0)
        build_dict, build_col = min(t_dict), min(t_col)

        parity_ok = (
            list(cw_d.node_table.names) == list(cw_c.node_table.names)
            and np.array_equal(cw_d.node_table.allocatable,
                               cw_c.node_table.allocatable)
            and _tree_equal(cw_d.statics, cw_c.statics)
            and _tree_equal(cw_d.xs, cw_c.xs)
            and _tree_equal(cw_d.init_carry, cw_c.init_carry))

        # wave 1: schedule the full queue on the columnar store
        c0 = counters()
        eng = SchedulerEngine(store, plugin_config=cfg, chunk=128)
        t0 = time.perf_counter()
        bound = eng.schedule_pending()
        wave_s = time.perf_counter() - t0
        c1 = counters()

        # wave 2: new pods, UNCHANGED node set -> the node table must be
        # reused, never rebuilt
        extra = make_pods(50, seed=97)
        for i, p in enumerate(extra):
            p["metadata"]["name"] = f"extra-{i:04d}"
            store.create("pods", p)
        bound2 = eng.schedule_pending()
        c2 = counters()

        # wave 3: touch a bounded node subset -> delta patch, no rebuild
        touched = 16
        for i in range(touched):
            nd = store.get("nodes", f"node-{i:05d}")
            nd["metadata"].setdefault("labels", {})["kss.io/touched"] = "y"
            store.update("nodes", nd)
        extra2 = make_pods(50, seed=98)
        for i, p in enumerate(extra2):
            p["metadata"]["name"] = f"extra2-{i:04d}"
            store.create("pods", p)
        bound3 = eng.schedule_pending()
        c3 = counters()

        hbm = TELEMETRY.sample_once()
        point = {
            "nodes": n,
            "pods": PODS,
            "bound": [bound, bound2, bound3],
            "build_dict_seconds": round(build_dict, 3),
            "build_columnar_seconds": round(build_col, 3),
            "build_speedup_vs_dict": round(build_dict / build_col, 2),
            "parity_ok": parity_ok,
            "wave_seconds": round(wave_s, 2),
            "cycles_per_sec": round(PODS / wave_s, 1),
            "node_table_builds": delta(c3, c0, "node_table_builds_total"),
            "node_table_reuses": delta(c3, c1, "node_table_reuse_total"),
            "delta_patches": delta(c3, c2, "node_table_delta_patches_total"),
            "delta_rows": delta(c3, c2, "node_table_delta_rows_total"),
            "never_rebuilt_on_unchanged_nodes":
                delta(c2, c1, "node_table_builds_total") == 0
                and delta(c3, c2, "node_table_builds_total") == 0
                and delta(c2, c1, "node_table_reuse_total") >= 1,
            "delta_patched_not_rebuilt":
                delta(c3, c2, "node_table_delta_patches_total") >= 1
                and delta(c3, c2, "node_table_delta_rows_total") == touched,
            "wave_d2h_bytes": delta(c1, c0, "wave_d2h_bytes_total"),
            "host_rss_mb": round(_rss_mb(), 1),
            "hbm_bytes_in_use": hbm.get("bytes_in_use"),
            "hbm_stats_available": bool(hbm.get("available")),
        }

        if n == POINTS[0]:
            # end-to-end bind parity at the smallest point: a dict-plane
            # store (KSS_TPU_COLUMNAR=0) scheduling the same population
            # must place every pod on the same node
            os.environ["KSS_TPU_COLUMNAR"] = "0"
            try:
                dstore = ObjectStore()
            finally:
                os.environ.pop("KSS_TPU_COLUMNAR", None)
            for nd in dict_nodes:
                dstore.create("nodes", copy.deepcopy(nd))
            for p in dict_pods:
                dstore.create("pods", copy.deepcopy(p))
            SchedulerEngine(dstore, plugin_config=cfg,
                            chunk=128).schedule_pending()

            def binds(s):
                pods_all, _ = s.list("pods")
                return {p["metadata"]["name"]:
                        (p.get("spec") or {}).get("nodeName")
                        for p in pods_all
                        if p["metadata"]["name"].startswith("pod-")}

            point["binds_parity_ok"] = binds(store) == binds(dstore)

        points.append(point)
        print(f"scale {n}: build dict {build_dict:.2f}s vs columnar "
              f"{build_col:.2f}s ({point['build_speedup_vs_dict']}x), "
              f"wave {wave_s:.1f}s ({point['cycles_per_sec']} c/s), "
              f"parity={parity_ok} "
              f"reuse={point['never_rebuilt_on_unchanged_nodes']} "
              f"delta={point['delta_patched_not_rebuilt']} "
              f"rss={point['host_rss_mb']}MB", flush=True)

    p100k = points[-1]
    artifact = {
        "mode": "scale",
        "platform": jax.devices()[0].platform,
        "plugins": cfg.enabled,
        "points": points,
        "all_parity_ok": all(
            p["parity_ok"] and p.get("binds_parity_ok", True)
            for p in points),
        "never_rebuilt_on_unchanged_nodes": all(
            p["never_rebuilt_on_unchanged_nodes"] for p in points),
        "all_delta_patched": all(
            p["delta_patched_not_rebuilt"] for p in points),
        "scale_100k_cycles_per_sec": p100k["cycles_per_sec"],
        "scale_100k_build_seconds": p100k["build_columnar_seconds"],
        "scale_100k_build_speedup_vs_dict": p100k["build_speedup_vs_dict"],
        "scale_100k_host_rss_mb": p100k["host_rss_mb"],
    }
    with open(out_path, "w") as f:
        json.dump(artifact, f, indent=2)
    print(f"wrote {out_path}; all_parity_ok={artifact['all_parity_ok']} "
          f"100k: {p100k['build_speedup_vs_dict']}x build, "
          f"{p100k['cycles_per_sec']} c/s, {p100k['host_rss_mb']}MB RSS",
          flush=True)
    return artifact


def main():
    if len(sys.argv) > 1 and sys.argv[1] == "--scale":
        scale_curve(sys.argv[2] if len(sys.argv) > 2
                    else "docs/bench/r06-columnar-scale.json")
        return
    out_path = (sys.argv[1] if len(sys.argv) > 1
                else "docs/bench/r04-multichip-scaling.json")
    from kube_scheduler_simulator_tpu.framework.replay import replay
    from kube_scheduler_simulator_tpu.models.workloads import baseline_config
    from kube_scheduler_simulator_tpu.parallel.mesh import make_mesh
    from kube_scheduler_simulator_tpu.state.compile import compile_workload
    from kube_scheduler_simulator_tpu.store.decode import decode_pod_result

    n_dev = len(jax.devices())
    print(f"devices: {n_dev} ({jax.devices()[0].platform})", flush=True)

    # config-4 plugin set at the full 5k-node shape, 1k-pod queue
    nodes, pods, cfg = baseline_config(4, scale=0.1, node_scale=1.0, seed=0)
    print(f"{len(pods)} pods x {len(nodes)} nodes, plugins={cfg.enabled}",
          flush=True)
    cw = compile_workload(nodes, pods, cfg)

    t0 = time.time()
    base = replay(cw, chunk=256)
    base_s = time.time() - t0
    t0 = time.time()
    base = replay(cw, chunk=256)
    base_warm = time.time() - t0
    # observed residency, captured BEFORE any decode materializes the
    # chunks: `make bench-multichip` asserts the device-resident path
    # actually ran, not just that no env var was set
    cc = getattr(base, "_compact", None)
    device_resident_observed = bool(
        cc is not None and cc.packed and cc.is_device(0))
    print(f"unsharded: cold {base_s:.1f}s warm {base_warm:.1f}s "
          f"scheduled {base.scheduled} "
          f"device_resident={device_resident_observed}", flush=True)

    shard_counts = [s for s in (2, 4, 8) if s <= n_dev and len(nodes) % s == 0]
    curve = []
    parity_pods = len(pods)
    for shards in shard_counts:
        mesh = make_mesh(shards, dp=1)
        t0 = time.time()
        rr = replay(cw, chunk=256, mesh=mesh)
        cold = time.time() - t0
        t0 = time.time()
        rr = replay(cw, chunk=256, mesh=mesh)
        warm = time.time() - t0
        # residency must be observed on the SHARDED runs too (captured
        # before the parity decode below materializes them): a mesh-only
        # fallback to host fetch would otherwise pass the gate
        scc = getattr(rr, "_compact", None)
        device_resident_observed &= bool(
            scc is not None and scc.packed and scc.is_device(0))
        mism = 0
        for i in range(parity_pods):
            if decode_pod_result(rr, i) != decode_pod_result(base, i):
                mism += 1
        curve.append({
            "nodes_shards": shards,
            "cold_seconds": round(cold, 2),
            "warm_seconds": round(warm, 2),
            "warm_cycles_per_sec": round(len(pods) / warm, 1),
            "scheduled": rr.scheduled,
            "annotation_mismatches_vs_unsharded": mism,
        })
        print(f"shards={shards}: warm {warm:.1f}s "
              f"({len(pods)/warm:,.0f} c/s), parity mismatches {mism}",
              flush=True)

    # dp-speculative engine wave at 5k nodes (safe plugin subset)
    from kube_scheduler_simulator_tpu.cluster.store import ObjectStore
    from kube_scheduler_simulator_tpu.framework.engine import SchedulerEngine
    from kube_scheduler_simulator_tpu.models.workloads import make_nodes, make_pods
    from kube_scheduler_simulator_tpu.plugins.registry import PluginSetConfig
    from kube_scheduler_simulator_tpu.utils.tracing import TRACER

    spec_result = None
    if n_dev >= 4:
        # the CONFIG-4 plugin lineup — PodTopologySpread rides the
        # interaction rule (round-4 extension)
        s_nodes = make_nodes(len(nodes), seed=2, taint_fraction=0.1)
        s_pods = make_pods(1000, seed=3, with_affinity=True,
                           with_tolerations=True, with_spread=True)
        s_cfg = PluginSetConfig(enabled=[
            "NodeResourcesFit", "NodeResourcesBalancedAllocation",
            "NodeAffinity", "TaintToleration", "PodTopologySpread"])

        def engine_run(mesh_arg):
            store = ObjectStore()
            for nd in s_nodes:
                store.create("nodes", nd)
            for pd in s_pods:
                store.create("pods", pd)
            eng = SchedulerEngine(store, plugin_config=s_cfg, mesh=mesh_arg,
                                  chunk=256)
            t0 = time.time()
            bound = eng.schedule_pending()
            dt = time.time() - t0
            out_pods, _ = store.list("pods")
            binds = {p["metadata"]["name"]: (p.get("spec") or {}).get("nodeName")
                     for p in out_pods}
            return bound, dt, binds

        mesh = make_mesh(n_dev, dp=2)
        TRACER.reset()
        b_spec, t_spec, binds_spec = engine_run(mesh)
        spans = TRACER.summary()["spans"]
        used_spec = "speculative_replay" in spans
        b_base, t_base, binds_base = engine_run(None)
        spec_result = {
            "mesh": {"dp": 2, "nodes": n_dev // 2},
            "pods": len(s_pods), "nodes": len(s_nodes),
            "bound": b_spec, "seconds": round(t_spec, 2),
            "speculative_path_used": used_spec,
            "binds_equal_unsharded_engine": binds_spec == binds_base,
            "unsharded_seconds": round(t_base, 2),
            "speculative_rounds": TRACER.summary()["counters"].get(
                "speculative_rounds_total"),
        }
        print(f"engine dp-wave: bound {b_spec}/{len(s_pods)} in {t_spec:.1f}s "
              f"(speculative={used_spec}, equal={spec_result['binds_equal_unsharded_engine']})",
              flush=True)

    artifact = {
        "devices": n_dev,
        "platform": jax.devices()[0].platform,
        # replay() here runs with no on_chunk consumer, so the default
        # is the device-resident result path (framework/replay.py).
        # Recorded from OBSERVED chunk residency, not env vars, so
        # `make bench-multichip` fails if the path silently degrades
        "result_mode": ("device_resident" if device_resident_observed
                        else "host_resident"),
        "note": ("virtual mesh shares host cores: the curve demonstrates "
                 "SPMD structure + byte-parity at production node shape, "
                 "not hardware speedup"),
        "workload": {"pods": len(pods), "nodes": len(nodes),
                     "plugins": cfg.enabled},
        "unsharded_warm_seconds": round(base_warm, 2),
        "curve": curve,
        "engine_dp_speculative": spec_result,
    }
    # `all_parity_ok: true` from a run that never sharded anything is
    # vacuous (VERDICT r5 on the committed r05 artifact: 1 device, empty
    # curve).  Only claim parity when >=2 devices produced a non-empty
    # shard curve; otherwise record an explicit skip with the reason.
    if n_dev < 2 or not curve:
        artifact["skipped"] = True
        artifact["skip_reason"] = (
            f"{n_dev} device(s) visible, {len(curve)} shard point(s): "
            "multichip parity was not exercised (set XLA_FLAGS="
            "--xla_force_host_platform_device_count=8 and a node count "
            "divisible by the shard sizes)")
        print(f"wrote {out_path}; skipped={artifact['skip_reason']}",
              flush=True)
    else:
        artifact["all_parity_ok"] = all(
            c["annotation_mismatches_vs_unsharded"] == 0 for c in curve)
        print(f"wrote {out_path}; all_parity_ok={artifact['all_parity_ok']}",
              flush=True)
    with open(out_path, "w") as f:
        json.dump(artifact, f, indent=2)


if __name__ == "__main__":
    main()
