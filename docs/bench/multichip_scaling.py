"""Multichip scaling artifact: sharded replay at production node shape.

Round-3 verdict weak #4: multichip evidence was smoke-depth.  This runs a
>=1k-pod replay at the full 5k-node config-4 shape on a virtual device
mesh, asserts byte-parity of every annotation vs the unsharded replay,
and records shard-count-vs-throughput plus a dp-speculative engine wave.

On the virtual CPU mesh all "devices" share host cores, so the
throughput CURVE shows SPMD structure (the program builds, shards, and
executes at every mesh size), not hardware speedup — on real multi-chip
the same code lays the node axis over ICI (parallel/mesh.py).

Usage:
  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
      python docs/bench/multichip_scaling.py [outfile]
"""

from __future__ import annotations

import json
import sys
import time

sys.path.insert(0, ".")
from kube_scheduler_simulator_tpu.utils.platform import force_cpu

force_cpu()

import jax


def main():
    out_path = (sys.argv[1] if len(sys.argv) > 1
                else "docs/bench/r04-multichip-scaling.json")
    from kube_scheduler_simulator_tpu.framework.replay import replay
    from kube_scheduler_simulator_tpu.models.workloads import baseline_config
    from kube_scheduler_simulator_tpu.parallel.mesh import make_mesh
    from kube_scheduler_simulator_tpu.state.compile import compile_workload
    from kube_scheduler_simulator_tpu.store.decode import decode_pod_result

    n_dev = len(jax.devices())
    print(f"devices: {n_dev} ({jax.devices()[0].platform})", flush=True)

    # config-4 plugin set at the full 5k-node shape, 1k-pod queue
    nodes, pods, cfg = baseline_config(4, scale=0.1, node_scale=1.0, seed=0)
    print(f"{len(pods)} pods x {len(nodes)} nodes, plugins={cfg.enabled}",
          flush=True)
    cw = compile_workload(nodes, pods, cfg)

    t0 = time.time()
    base = replay(cw, chunk=256)
    base_s = time.time() - t0
    t0 = time.time()
    base = replay(cw, chunk=256)
    base_warm = time.time() - t0
    # observed residency, captured BEFORE any decode materializes the
    # chunks: `make bench-multichip` asserts the device-resident path
    # actually ran, not just that no env var was set
    cc = getattr(base, "_compact", None)
    device_resident_observed = bool(
        cc is not None and cc.packed and cc.is_device(0))
    print(f"unsharded: cold {base_s:.1f}s warm {base_warm:.1f}s "
          f"scheduled {base.scheduled} "
          f"device_resident={device_resident_observed}", flush=True)

    shard_counts = [s for s in (2, 4, 8) if s <= n_dev and len(nodes) % s == 0]
    curve = []
    parity_pods = len(pods)
    for shards in shard_counts:
        mesh = make_mesh(shards, dp=1)
        t0 = time.time()
        rr = replay(cw, chunk=256, mesh=mesh)
        cold = time.time() - t0
        t0 = time.time()
        rr = replay(cw, chunk=256, mesh=mesh)
        warm = time.time() - t0
        # residency must be observed on the SHARDED runs too (captured
        # before the parity decode below materializes them): a mesh-only
        # fallback to host fetch would otherwise pass the gate
        scc = getattr(rr, "_compact", None)
        device_resident_observed &= bool(
            scc is not None and scc.packed and scc.is_device(0))
        mism = 0
        for i in range(parity_pods):
            if decode_pod_result(rr, i) != decode_pod_result(base, i):
                mism += 1
        curve.append({
            "nodes_shards": shards,
            "cold_seconds": round(cold, 2),
            "warm_seconds": round(warm, 2),
            "warm_cycles_per_sec": round(len(pods) / warm, 1),
            "scheduled": rr.scheduled,
            "annotation_mismatches_vs_unsharded": mism,
        })
        print(f"shards={shards}: warm {warm:.1f}s "
              f"({len(pods)/warm:,.0f} c/s), parity mismatches {mism}",
              flush=True)

    # dp-speculative engine wave at 5k nodes (safe plugin subset)
    from kube_scheduler_simulator_tpu.cluster.store import ObjectStore
    from kube_scheduler_simulator_tpu.framework.engine import SchedulerEngine
    from kube_scheduler_simulator_tpu.models.workloads import make_nodes, make_pods
    from kube_scheduler_simulator_tpu.plugins.registry import PluginSetConfig
    from kube_scheduler_simulator_tpu.utils.tracing import TRACER

    spec_result = None
    if n_dev >= 4:
        # the CONFIG-4 plugin lineup — PodTopologySpread rides the
        # interaction rule (round-4 extension)
        s_nodes = make_nodes(len(nodes), seed=2, taint_fraction=0.1)
        s_pods = make_pods(1000, seed=3, with_affinity=True,
                           with_tolerations=True, with_spread=True)
        s_cfg = PluginSetConfig(enabled=[
            "NodeResourcesFit", "NodeResourcesBalancedAllocation",
            "NodeAffinity", "TaintToleration", "PodTopologySpread"])

        def engine_run(mesh_arg):
            store = ObjectStore()
            for nd in s_nodes:
                store.create("nodes", nd)
            for pd in s_pods:
                store.create("pods", pd)
            eng = SchedulerEngine(store, plugin_config=s_cfg, mesh=mesh_arg,
                                  chunk=256)
            t0 = time.time()
            bound = eng.schedule_pending()
            dt = time.time() - t0
            out_pods, _ = store.list("pods")
            binds = {p["metadata"]["name"]: (p.get("spec") or {}).get("nodeName")
                     for p in out_pods}
            return bound, dt, binds

        mesh = make_mesh(n_dev, dp=2)
        TRACER.reset()
        b_spec, t_spec, binds_spec = engine_run(mesh)
        spans = TRACER.summary()["spans"]
        used_spec = "speculative_replay" in spans
        b_base, t_base, binds_base = engine_run(None)
        spec_result = {
            "mesh": {"dp": 2, "nodes": n_dev // 2},
            "pods": len(s_pods), "nodes": len(s_nodes),
            "bound": b_spec, "seconds": round(t_spec, 2),
            "speculative_path_used": used_spec,
            "binds_equal_unsharded_engine": binds_spec == binds_base,
            "unsharded_seconds": round(t_base, 2),
            "speculative_rounds": TRACER.summary()["counters"].get(
                "speculative_rounds_total"),
        }
        print(f"engine dp-wave: bound {b_spec}/{len(s_pods)} in {t_spec:.1f}s "
              f"(speculative={used_spec}, equal={spec_result['binds_equal_unsharded_engine']})",
              flush=True)

    artifact = {
        "devices": n_dev,
        "platform": jax.devices()[0].platform,
        # replay() here runs with no on_chunk consumer, so the default
        # is the device-resident result path (framework/replay.py).
        # Recorded from OBSERVED chunk residency, not env vars, so
        # `make bench-multichip` fails if the path silently degrades
        "result_mode": ("device_resident" if device_resident_observed
                        else "host_resident"),
        "note": ("virtual mesh shares host cores: the curve demonstrates "
                 "SPMD structure + byte-parity at production node shape, "
                 "not hardware speedup"),
        "workload": {"pods": len(pods), "nodes": len(nodes),
                     "plugins": cfg.enabled},
        "unsharded_warm_seconds": round(base_warm, 2),
        "curve": curve,
        "engine_dp_speculative": spec_result,
    }
    # `all_parity_ok: true` from a run that never sharded anything is
    # vacuous (VERDICT r5 on the committed r05 artifact: 1 device, empty
    # curve).  Only claim parity when >=2 devices produced a non-empty
    # shard curve; otherwise record an explicit skip with the reason.
    if n_dev < 2 or not curve:
        artifact["skipped"] = True
        artifact["skip_reason"] = (
            f"{n_dev} device(s) visible, {len(curve)} shard point(s): "
            "multichip parity was not exercised (set XLA_FLAGS="
            "--xla_force_host_platform_device_count=8 and a node count "
            "divisible by the shard sizes)")
        print(f"wrote {out_path}; skipped={artifact['skip_reason']}",
              flush=True)
    else:
        artifact["all_parity_ok"] = all(
            c["annotation_mismatches_vs_unsharded"] == 0 for c in curve)
        print(f"wrote {out_path}; all_parity_ok={artifact['all_parity_ok']}",
              flush=True)
    with open(out_path, "w") as f:
        json.dump(artifact, f, indent=2)


if __name__ == "__main__":
    main()
