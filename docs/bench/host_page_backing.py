"""Measure this host's first-touch page-backing bandwidth vs resident set.

Round-4 finding: full materialization of the 10k x 5k annotation product
(~13 GB of live strings) is bounded not by the decoder (~600-1000 pods/s
single-core) but by the HOST: beyond ~8 GB resident, first-touch page
faults collapse from ~2.2 GB/s to ~200 MB/s on this (virtualized) bench
machine, independent of allocator (reproduced with GC off, pinned glibc
mmap threshold, mallopt arena recycling, and a raw numpy touch loop —
this script).  At that rate the 13 GB product carries a ~29 s
page-backing floor: ~10000/(29s + 17s decode compute) ~= 220 pods/s,
which is exactly what the full-scale decode measures.  The cliff follows
the process's total touched memory, not pod content (decoding the second
half of the queue first is equally fast).

Usage: python docs/bench/host_page_backing.py [max_gb] [outfile]
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np


def main():
    max_gb = int(sys.argv[1]) if len(sys.argv) > 1 else 14
    out_path = (sys.argv[2] if len(sys.argv) > 2
                else "docs/bench/r04-host-page-backing.json")
    bufs = []
    curve = []
    for g in range(max_gb):
        t0 = time.time()
        a = np.empty(1 << 30, np.uint8)
        a[::4096] = 1  # touch every 4 KiB page once
        dt = time.time() - t0
        bufs.append(a)
        curve.append({"resident_gb": g + 1,
                      "first_touch_mb_per_s": round(1024 / dt, 1)})
        print(f"GB {g+1}: {1024/dt:,.0f} MB/s", flush=True)
    fast = max(c["first_touch_mb_per_s"] for c in curve[:6])
    slow = min(c["first_touch_mb_per_s"] for c in curve[8:]) if max_gb > 9 else None
    with open(out_path, "w") as f:
        json.dump({
            "note": ("first-touch page-fault bandwidth vs resident set; "
                     "the >8 GB collapse bounds any process materializing "
                     "the full 10k x 5k annotation product on this host"),
            "curve": curve,
            "below_cliff_mb_per_s": fast,
            "above_cliff_mb_per_s": slow,
        }, f, indent=2)
    print(f"wrote {out_path}")


if __name__ == "__main__":
    main()
