"""Device-only replay rate vs lax.scan unroll, config 4, on the live
backend.  Run by tpu_watch.sh after a successful bench so the unroll
choice (bench.py --unroll default) is grounded on-device, not on the CPU
backend.  Writes r04-unroll-sweep.json next to this file."""

import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[2]))

import jax

from kube_scheduler_simulator_tpu.framework.replay import replay
from kube_scheduler_simulator_tpu.models.workloads import baseline_config
from kube_scheduler_simulator_tpu.state.compile import compile_workload

print("devices:", jax.devices(), flush=True)
nodes, pods, cfg = baseline_config(4, scale=1.0, seed=0)
cw = compile_workload(nodes, pods, cfg)
out = {"pods": len(pods), "nodes": len(nodes),
       "backend": jax.default_backend(), "rates": {}}
for unroll in (1, 2, 4, 8):
    t0 = time.time()
    rr = replay(cw, chunk=1024, collect=False, unroll=unroll)  # compile+run
    warm_s = time.time() - t0
    t0 = time.time()
    rr = replay(cw, chunk=1024, collect=False, unroll=unroll)
    dt = time.time() - t0
    rate = round(len(pods) / dt, 1)
    out["rates"][str(unroll)] = {"cycles_per_sec": rate,
                                 "compile_plus_run_s": round(warm_s, 1)}
    print(f"unroll {unroll}: {rate} cycles/s (first run {warm_s:.1f}s)",
          flush=True)
Path(__file__).with_name("r04-unroll-sweep.json").write_text(
    json.dumps(out, indent=1))
