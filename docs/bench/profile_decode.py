"""Profile the annotation decode path at the config-4 node shape.

Usage: python docs/bench/profile_decode.py [n_pods] [config_idx]
Runs on the CPU XLA backend (force_cpu) so it never touches the tunnel.
"""
import sys
import time

sys.path.insert(0, ".")
from kube_scheduler_simulator_tpu.utils.platform import force_cpu

force_cpu()

import numpy as np

from kube_scheduler_simulator_tpu.framework.replay import replay
from kube_scheduler_simulator_tpu.models.workloads import baseline_config
from kube_scheduler_simulator_tpu.state.compile import compile_workload
from kube_scheduler_simulator_tpu.store import decode

n_pods = int(sys.argv[1]) if len(sys.argv) > 1 else 256
idx = int(sys.argv[2]) if len(sys.argv) > 2 else 4

nodes, pods, cfg = baseline_config(idx, scale=n_pods / 10000, node_scale=1.0)
print(f"{len(pods)} pods x {len(nodes)} nodes, plugins={cfg.enabled}")
cw = compile_workload(nodes, pods, cfg)
rr = replay(cw, chunk=256)
print("replay done")

# warm (native ctx build, first chunk recon)
decode.decode_pod_result(rr, 0)

t0 = time.time()
anns = decode.decode_all_parallel(rr, n_pods)
dt = time.time() - t0
total_bytes = sum(len(v) for a in anns for v in a.values())
print(f"decode_all_parallel: {dt:.2f}s -> {n_pods/dt:.1f} pods/s, "
      f"{total_bytes/n_pods/1024:.0f} KiB/pod, {total_bytes/dt/1e6:.0f} MB/s")

# cProfile on the serial path
import cProfile
import pstats

pr = cProfile.Profile()
pr.enable()
for i in range(min(64, n_pods)):
    decode.decode_pod_result(rr, i)
pr.disable()
st = pstats.Stats(pr)
st.sort_stats("cumulative").print_stats(25)
