"""Measure the compact replay's D2H payload per pod, per config.

The tunneled TPU link (~8-35 MB/s) makes device->host transfer the
end-to-end bottleneck, so every byte per (pod, node) matters.  This
script builds each BASELINE config at a reduced queue (payload per pod is
queue-length independent: [N]-shaped rows) and sums the actual transferred
chunk bytes, splitting out rows that stayed host-resident
("host" score group, framework/replay.py) as the saving.

Usage: python docs/bench/payload_bytes.py  (hermetically CPU-backed)
Writes docs/bench/r04-payload-bytes.json.
"""

import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[2]))

from kube_scheduler_simulator_tpu.utils.platform import force_cpu  # noqa: E402

force_cpu()  # the axon sitecustomize hook ignores JAX_PLATFORMS=cpu

from kube_scheduler_simulator_tpu.framework.replay import replay  # noqa: E402
from kube_scheduler_simulator_tpu.models.workloads import baseline_config  # noqa: E402
from kube_scheduler_simulator_tpu.state.compile import compile_workload  # noqa: E402


def measure(idx: int, scale: float = 0.02) -> dict:
    nodes, pods, cfg = baseline_config(idx, scale=scale, seed=0, node_scale=1.0)
    cw = compile_workload(nodes, pods, cfg)
    rr = replay(cw, chunk=64)
    cc = rr._compact
    p = len(pods)
    n = len(nodes)
    # per-POD bytes = per-row bytes: the last chunk is padded to the full
    # chunk size, so divide by the padded row count, not by p
    total_rows = sum(a.shape[0] for a in cc.packed)
    transferred = round(sum(
        a.nbytes for group in (cc.packed, cc.raw8, cc.raw16, cc.raw32)
        for a in group) * p / max(total_rows, 1))
    host_rows = [name for g, name in cc.score_cols if g == "host"]
    # bytes those rows would have cost at their narrowest transfer dtype
    # (the pre-change behavior: bound-derived i8/i16/i32/i64)
    saved = 0
    for name in host_rows:
        src = cw.host["static_score_rows"][name]
        bound = max(int(src.max(initial=0)), -int(src.min(initial=0)))
        width = 1 if bound <= 0x7F else 2 if bound <= 0x7FFF else 4 if bound <= 0x7FFFFFFF else 8
        saved += p * n * width
    return {
        "pods": p, "nodes": n, "plugins": cfg.enabled,
        "transferred_bytes_per_pod": round(transferred / p),
        "host_resident_rows": host_rows,
        "saved_bytes_per_pod": round(saved / p),
        "saving_fraction": round(saved / (saved + transferred), 3),
        "full_scale_transfer_gb": round(
            transferred / p * {1: 100, 2: 1000, 3: 5000, 4: 10000, 5: 10000}[idx]
            / 1e9, 2),
    }


def main():
    out = {f"config{i}": measure(i) for i in (1, 2, 3, 4, 5)}
    path = Path(__file__).parent / "r04-payload-bytes.json"
    path.write_text(json.dumps(out, indent=1))
    print(json.dumps(out, indent=1))


if __name__ == "__main__":
    main()
