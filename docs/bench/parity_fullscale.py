"""Full-scale parity artifact: byte-identical annotations at 10k x 5k.

Round-3 verdict missing #5: the parity gate only ever ran at reduced
scale; this script executes configs 4 and 5 at the FULL benchmark shape
(10,000 pods x 5,000 nodes) against the sequential CPU oracle and records
a committed artifact under docs/bench/.

Every one of the 13 per-pod result annotations (filter-result,
score-result, finalscore-result, selected-node, ...) must match the
oracle byte-for-byte for every pod.  Runs on the CPU XLA backend so it
never depends on the accelerator tunnel; wall times are recorded but are
NOT benchmark figures (the run may share the host with other work).

Usage: python docs/bench/parity_fullscale.py [outfile]
"""

from __future__ import annotations

import hashlib
import json
import sys
import time

sys.path.insert(0, ".")
from kube_scheduler_simulator_tpu.utils.platform import force_cpu

force_cpu()


def run_config(idx: int, seed: int = 0) -> dict:
    from kube_scheduler_simulator_tpu.framework.replay import replay
    from kube_scheduler_simulator_tpu.models.workloads import baseline_config
    from kube_scheduler_simulator_tpu.reference_impl.sequential import SequentialScheduler
    from kube_scheduler_simulator_tpu.state.compile import compile_workload
    from kube_scheduler_simulator_tpu.store.decode import decode_pod_result

    nodes, pods, cfg = baseline_config(idx, scale=1.0, seed=seed)
    print(f"config {idx}: {len(pods)} pods x {len(nodes)} nodes "
          f"plugins={cfg.enabled}", flush=True)

    t0 = time.time()
    oracle = SequentialScheduler(nodes, pods, cfg).schedule_all()
    t_oracle = time.time() - t0
    print(f"  oracle: {t_oracle:.0f}s", flush=True)

    t0 = time.time()
    cw = compile_workload(nodes, pods, cfg)
    rr = replay(cw, chunk=512)
    t_replay = time.time() - t0
    print(f"  replay: {t_replay:.0f}s, scheduled {rr.scheduled}", flush=True)

    mismatches = 0
    first_mismatch = None
    h = hashlib.sha256()
    keys_checked = 0
    t0 = time.time()
    for i, (sa, _sel) in enumerate(oracle):
        da = decode_pod_result(rr, i)
        for k, v in sa.items():
            keys_checked += 1
            if da.get(k) != v:
                mismatches += 1
                if first_mismatch is None:
                    first_mismatch = {"pod": i, "key": k,
                                      "oracle": v[:200], "tpu_path": da.get(k, "")[:200]}
            h.update(v.encode())
        oracle[i] = None  # free as we go
        if i % 2000 == 1999:
            print(f"  compared {i + 1} pods", flush=True)
    t_compare = time.time() - t0
    return {
        "config": idx, "pods": len(pods), "nodes": len(nodes),
        "plugins": cfg.enabled,
        "mismatches": mismatches, "keys_compared": keys_checked,
        "first_mismatch": first_mismatch,
        "oracle_annotations_sha256": h.hexdigest(),
        "wall_seconds": {"oracle": round(t_oracle, 1),
                         "replay_and_transfer": round(t_replay, 1),
                         "decode_and_compare": round(t_compare, 1)},
    }


def main():
    out_path = sys.argv[1] if len(sys.argv) > 1 else "docs/bench/r04-parity-fullscale.json"
    import subprocess

    rev = subprocess.run(["git", "rev-parse", "--short", "HEAD"],
                         capture_output=True, text=True).stdout.strip()
    results = []
    for idx in (4, 5):
        results.append(run_config(idx))
        ok = results[-1]["mismatches"] == 0
        print(f"config {idx}: {'BYTE-PARITY OK' if ok else 'MISMATCHES'} "
              f"({results[-1]['keys_compared']} annotation values)", flush=True)
    artifact = {"rev": rev, "backend": "cpu-xla",
                "protocol": "BASELINE.md measurement protocol, full scale",
                "results": results,
                "all_parity_ok": all(r["mismatches"] == 0 for r in results)}
    with open(out_path, "w") as f:
        json.dump(artifact, f, indent=2)
    print(f"wrote {out_path}; all_parity_ok={artifact['all_parity_ok']}", flush=True)


if __name__ == "__main__":
    main()
