"""Full-scale parity artifact: byte-identical annotations at 10k x 5k.

Round-3 verdict missing #5 (and round-4 #3: run it ON DEVICE): the
parity gate only ever ran at reduced scale; this script executes configs
4 and 5 at the FULL benchmark shape (10,000 pods x 5,000 nodes) against
the sequential CPU oracle and records a committed artifact under
docs/bench/.

Every one of the 13 per-pod result annotations (filter-result,
score-result, finalscore-result, selected-node, ...) must match the
oracle byte-for-byte for every pod.  Both sides stream
(bench.stream_oracle_parity): the oracle runs in a separate CPU-forced
RLIMIT-capped subprocess emitting one pod per line, and the comparison
holds one pod at a time — the full ~13 GB annotation product is never
resident, so the script fits the memory-starved TPU host (round 4's
in-process oracle was OOM-killed there, docs/bench/r04-tpu-bench.err).

By default forces the CPU XLA backend (never depends on the accelerator
tunnel); with --device it uses whatever backend jax initializes (the
TPU when the tunnel is alive) so the artifact proves DEVICE-layout
parity at full scale.  Wall times are recorded but are NOT benchmark
figures (the run may share the host with other work).

Usage: python docs/bench/parity_fullscale.py [outfile] [--device]
       [--configs 4,5] [--scale 1.0]
"""

from __future__ import annotations

import argparse
import json
import sys
import time

sys.path.insert(0, ".")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("outfile", nargs="?",
                    default="docs/bench/r05-parity-fullscale.json")
    ap.add_argument("--device", action="store_true",
                    help="use the default jax backend (TPU when alive) "
                         "instead of forcing CPU")
    ap.add_argument("--configs", default="4,5")
    ap.add_argument("--scale", type=float, default=1.0)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()

    if not args.device:
        from kube_scheduler_simulator_tpu.utils.platform import force_cpu

        force_cpu()
    import jax

    import bench

    backend = jax.devices()[0].platform
    print(f"backend: {backend} ({jax.devices()})", flush=True)

    results = []
    for idx in [int(x) for x in args.configs.split(",") if x]:
        t0 = time.time()
        last = {"n": 0}

        def hb(i, _last=last):
            if i - _last["n"] >= 2000:
                _last["n"] = i
                print(f"  compared {i} pods", flush=True)

        r = bench.stream_oracle_parity(idx, args.scale, args.seed,
                                       chunk=512, want_digest=True,
                                       heartbeat=hb)
        ok = r["ok"]
        print(f"config {idx}: {'BYTE-PARITY OK' if ok else 'FAILED'} "
              f"({r['keys_checked']} annotation values, "
              f"{time.time() - t0:.0f}s)", flush=True)
        results.append({
            "config": idx, "pods": r["pods"],
            "mismatches": r["mismatches"],
            "keys_compared": r["keys_checked"],
            "first_mismatch": r["first_mismatch"],
            "oracle_completed": r["compared"] == r["pods"],
            "oracle_rc": r["oracle_rc"],
            "oracle_annotations_sha256": r["sha256"],
            "wall_seconds": {"oracle_stream_and_compare": r["oracle_seconds"],
                             "replay_and_transfer": r["replay_seconds"]},
        })

    import subprocess

    rev = subprocess.run(["git", "rev-parse", "--short", "HEAD"],
                         capture_output=True, text=True).stdout.strip()
    artifact = {"rev": rev, "backend": backend,
                "protocol": "BASELINE.md measurement protocol, full scale",
                "scale": args.scale,
                "results": results,
                "all_parity_ok": all(
                    r["mismatches"] == 0 and r["oracle_completed"]
                    for r in results)}
    with open(args.outfile, "w") as f:
        json.dump(artifact, f, indent=2)
    print(f"wrote {args.outfile}; all_parity_ok={artifact['all_parity_ok']}",
          flush=True)


if __name__ == "__main__":
    main()
