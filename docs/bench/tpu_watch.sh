#!/bin/bash
# TPU-tunnel recovery watcher (round 4).
#
# The axon tunnel wedges server-side for hours after a client dies mid-run
# (see BASELINE.md / round-3 notes), and can also wedge MID-CALL (bench.py
# now carries a hang watchdog that re-execs the CPU fallback).  This loop
# probes device init in a subprocess every ~10 min and, while the probe
# succeeds, runs bench.py; it exits only once a NON-fallback real-TPU
# artifact exists, so an unattended recovery still produces the number.
cd /root/repo || exit 1
LOG=docs/bench/r04-tpu-watch.log
while true; do
  ts=$(date -u +%FT%TZ)
  if timeout 240 python -c "import jax; jax.devices()" >/dev/null 2>&1; then
    echo "$ts probe: ALIVE -> running bench.py" >> "$LOG"
    # write to temp files and promote the json+err PAIR only on non-empty
    # JSON, so a later SIGKILLed run cannot truncate or mismatch an
    # already-captured artifact pair; a failed attempt's stderr is kept
    # separately for diagnosis
    python bench.py > docs/bench/r04-tpu-bench.json.tmp 2> docs/bench/r04-tpu-bench.err.tmp
    rc=$?
    if [ -s docs/bench/r04-tpu-bench.json.tmp ]; then
      mv docs/bench/r04-tpu-bench.json.tmp docs/bench/r04-tpu-bench.json
      mv docs/bench/r04-tpu-bench.err.tmp docs/bench/r04-tpu-bench.err
    else
      rm -f docs/bench/r04-tpu-bench.json.tmp
      mv docs/bench/r04-tpu-bench.err.tmp docs/bench/r04-tpu-bench-lastfail.err
    fi
    echo "$(date -u +%FT%TZ) bench rc=$rc (json+err under docs/bench/)" >> "$LOG"
    # success = non-empty, not a CPU-fallback run, and not a parity-gate
    # failure line (those emit "value": 0.0 and must be retried, not
    # recorded as the round's TPU artifact)
    if [ -s docs/bench/r04-tpu-bench.json ] && \
       ! grep -q cpu_fallback docs/bench/r04-tpu-bench.json && \
       ! grep -q '"value": 0.0' docs/bench/r04-tpu-bench.json; then
      echo "$(date -u +%FT%TZ) non-fallback TPU artifact captured" >> "$LOG"
      timeout 1800 python docs/bench/unroll_sweep.py > docs/bench/r04-unroll-sweep.log 2>&1
      echo "$(date -u +%FT%TZ) unroll sweep rc=$?; watcher done" >> "$LOG"
      exit 0
    fi
  else
    echo "$ts probe: dead" >> "$LOG"
  fi
  sleep 600
done
