#!/bin/bash
# TPU-tunnel recovery watcher (round 4).
#
# The axon tunnel wedges server-side for hours after a client dies mid-run
# (see BASELINE.md / round-3 notes).  This loop probes device init in a
# subprocess every ~25 min and, on first success, runs bench.py once so a
# real-TPU artifact exists even if the recovery happens unattended.
cd /root/repo || exit 1
LOG=docs/bench/r04-tpu-watch.log
while true; do
  ts=$(date -u +%FT%TZ)
  if timeout 240 python -c "import jax; jax.devices()" >/dev/null 2>&1; then
    echo "$ts probe: ALIVE -> running bench.py" >> "$LOG"
    python bench.py > docs/bench/r04-tpu-bench.json 2> docs/bench/r04-tpu-bench.err
    echo "$(date -u +%FT%TZ) bench rc=$? (json+err under docs/bench/)" >> "$LOG"
    timeout 1800 python docs/bench/unroll_sweep.py > docs/bench/r04-unroll-sweep.log 2>&1
    echo "$(date -u +%FT%TZ) unroll sweep rc=$?" >> "$LOG"
    exit 0
  fi
  echo "$ts probe: dead" >> "$LOG"
  sleep 1500
done
