#!/bin/bash
# TPU-tunnel recovery watcher (round 5).
#
# The axon tunnel wedges server-side for hours after a client dies mid-run
# (see BASELINE.md / round-3 notes), and can also wedge MID-CALL (bench.py
# carries a hang watchdog that re-execs the CPU fallback).  This loop
# probes device init in a subprocess every ~10 min and, while the probe
# succeeds, runs bench.py; once a NON-fallback real-TPU artifact exists it
# also captures the full-scale ON-DEVICE parity artifact (round-4 verdict
# #5), then exits.
#
# Round-5 hygiene (the round-4 OOM post-mortem, r04-tpu-bench.err): a
# previous wedged bench left running can hold GBs while a new bench
# starts, inviting the kernel OOM killer.  The watcher therefore (a)
# kills ITS OWN previous bench (tracked by pidfile) once the probe shows
# the tunnel alive again, (b) bounds each bench with a hard timeout, and
# (c) skips the attempt when MemAvailable is too low for the full shape.
cd /root/repo || exit 1
LOG=docs/bench/r05-tpu-watch.log
PIDFILE=/tmp/kss_tpu_watch_bench.pid

avail_gb() { awk '/MemAvailable/{printf "%d", $2/1048576}' /proc/meminfo; }

kill_leftover() {
  if [ -f "$PIDFILE" ]; then
    oldpid=$(cat "$PIDFILE")
    # probe the GROUP as well as the leader: an OOM-killed timeout
    # wrapper leaves grandchildren alive in the group, and those are
    # exactly the orphans this sweep exists to reap
    if kill -0 "$oldpid" 2>/dev/null || kill -0 -- -"$oldpid" 2>/dev/null; then
      echo "$(date -u +%FT%TZ) killing leftover bench pid $oldpid" >> "$LOG"
      # the bench runs in its own process group (setsid at spawn, so
      # PGID == $oldpid): kill the GROUP, not just the timeout(1)
      # wrapper — pkill -P only reached direct children, and bench.py's
      # own subprocesses (the RLIMIT-capped oracle child, under-cliff /
      # engine-wave subprocesses) are grandchildren that survived the
      # sweep while holding the memory this script protects against
      kill -- -"$oldpid" 2>/dev/null || kill "$oldpid" 2>/dev/null
      sleep 10
      kill -9 -- -"$oldpid" 2>/dev/null
      kill -9 "$oldpid" 2>/dev/null
      sleep 2
    fi
    rm -f "$PIDFILE"
  fi
}

while true; do
  ts=$(date -u +%FT%TZ)
  if timeout 240 python -c "import jax; jax.devices()" >/dev/null 2>&1; then
    kill_leftover
    if [ "$(avail_gb)" -lt 6 ]; then
      echo "$ts probe: ALIVE but only $(avail_gb) GiB available; waiting" >> "$LOG"
      sleep 300
      continue
    fi
    echo "$ts probe: ALIVE -> running bench.py" >> "$LOG"
    # write to temp files and promote the json+err PAIR only on non-empty
    # JSON, so a later SIGKILLed run cannot truncate or mismatch an
    # already-captured artifact pair; a failed attempt's stderr is kept
    # separately for diagnosis.  Hard 2h cap: bench's internal hang
    # watchdog should re-exec its own fallback long before this fires.
    # setsid: the bench (timeout wrapper + python + its grandchildren)
    # gets its OWN process group, so kill_leftover can sweep the whole
    # tree with one group kill.  Backgrounded from a script the child is
    # not a group leader, so setsid execs in place without forking and
    # $! is the group leader (PGID == $!).
    setsid timeout -k 60 7200 python bench.py \
      > docs/bench/r05-tpu-bench.json.tmp \
      2> docs/bench/r05-tpu-bench.err.tmp &
    echo $! > "$PIDFILE"
    wait $!
    rc=$?
    rm -f "$PIDFILE"
    if [ -s docs/bench/r05-tpu-bench.json.tmp ]; then
      mv docs/bench/r05-tpu-bench.json.tmp docs/bench/r05-tpu-bench.json
      mv docs/bench/r05-tpu-bench.err.tmp docs/bench/r05-tpu-bench.err
    else
      rm -f docs/bench/r05-tpu-bench.json.tmp
      mv docs/bench/r05-tpu-bench.err.tmp docs/bench/r05-tpu-bench-lastfail.err
    fi
    echo "$(date -u +%FT%TZ) bench rc=$rc (json+err under docs/bench/)" >> "$LOG"
    # success = non-empty, not a CPU-fallback run, and not a parity-gate
    # failure line (those emit "value": 0.0 and must be retried, not
    # recorded as the round's TPU artifact)
    if [ -s docs/bench/r05-tpu-bench.json ] && \
       ! grep -q cpu_fallback docs/bench/r05-tpu-bench.json && \
       ! grep -q '"value": 0.0' docs/bench/r05-tpu-bench.json; then
      echo "$(date -u +%FT%TZ) non-fallback TPU artifact captured" >> "$LOG"
      # round-4 verdict #5: full-scale parity ON DEVICE (config 4, then 5
      # if the tunnel holds).  Streamed both sides, so it fits this host.
      timeout -k 60 14400 python docs/bench/parity_fullscale.py \
        docs/bench/r05-parity-fullscale-tpu.json --device --configs 4,5 \
        > docs/bench/r05-parity-fullscale-tpu.log 2>&1
      echo "$(date -u +%FT%TZ) device parity rc=$? ; watcher done" >> "$LOG"
      exit 0
    fi
  else
    echo "$ts probe: dead" >> "$LOG"
  fi
  sleep 600
done
