#!/usr/bin/env python
"""`make bench-check`: CI-enforceable perf trajectory.

Compares the newest committed bench round (`BENCH_*.json` at the repo
root) against the previous one on the key serving metrics and exits
nonzero when any regressed more than the threshold (default 15%):

  * `decode_pods_per_sec`      — annotation decode rate (higher better);
  * `commit_stream_overlap_seconds` of the engine_2k_1k wave — commit
    work hidden inside the replay window (higher better,
    docs/wave-pipeline.md);
  * engine_2k_1k *wave wall* (pods / cycles_per_sec, lower better);
  * the headline e2e `value` (higher better).

A metric missing on either side (e.g. a CPU-fallback round that skipped
an engine phase, or rounds predating a counter) is reported as SKIP and
never fails the check — the gate enforces "no silent regression", not
"every round measures everything".
"""

from __future__ import annotations

import json
import re
import sys
from pathlib import Path

DEFAULT_THRESHOLD = 0.15


def extract_bench_line(doc: dict) -> dict | None:
    """The bench.py one-JSON-line result from a BENCH_*.json round
    artifact ({n, cmd, rc, tail}) or from a raw bench line itself."""
    if "metric" in doc:
        return doc
    tail = doc.get("tail") or ""
    for line in reversed(tail.splitlines()):
        line = line.strip()
        if line.startswith('{"metric"'):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                return None
    return None


def key_metrics(bench: dict) -> dict[str, tuple[float | None, str]]:
    """{metric: (value or None, direction)} — direction 'higher' means
    bigger is better, 'lower' the reverse."""
    extra = bench.get("extra") or {}
    eng = extra.get("engine_2k_1k") or {}
    counters = eng.get("counters") or {}
    wall = None
    if eng.get("cycles_per_sec") and eng.get("pods"):
        wall = eng["pods"] / eng["cycles_per_sec"]
    return {
        "decode_pods_per_sec": (extra.get("decode_pods_per_sec"), "higher"),
        "commit_stream_overlap_seconds":
            (counters.get("commit_stream_overlap_seconds"), "higher"),
        "engine_2k_1k_wave_wall_seconds": (wall, "lower"),
        "headline_e2e_cycles_per_sec": (bench.get("value"), "higher"),
    }


def compare(prev: dict, new: dict,
            threshold: float = DEFAULT_THRESHOLD) -> list[dict]:
    """[{metric, old, new, ratio, status}] — status ok|regression|skip."""
    rows = []
    old_m, new_m = key_metrics(prev), key_metrics(new)
    for name, (old_v, direction) in old_m.items():
        new_v = new_m[name][0]
        if not old_v or new_v is None:
            rows.append({"metric": name, "old": old_v, "new": new_v,
                         "ratio": None, "status": "skip"})
            continue
        ratio = new_v / old_v
        if direction == "higher":
            bad = ratio < 1 - threshold
        else:
            bad = ratio > 1 + threshold
        rows.append({"metric": name, "old": old_v, "new": new_v,
                     "ratio": round(ratio, 3),
                     "status": "regression" if bad else "ok"})
    return rows


def _round_files(root: Path) -> list[Path]:
    files = [p for p in root.glob("BENCH_*.json")
             if re.fullmatch(r"BENCH_r?\d+\.json", p.name)]

    def order(p: Path):
        try:
            return (json.loads(p.read_text()).get("n", 0), p.name)
        except (OSError, json.JSONDecodeError):
            return (-1, p.name)

    return sorted(files, key=order)


def main(argv: list[str]) -> int:
    import argparse

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--dir", default=str(Path(__file__).parents[2]),
                    help="directory holding the BENCH_*.json rounds")
    ap.add_argument("--threshold", type=float, default=DEFAULT_THRESHOLD)
    args = ap.parse_args(argv)
    files = _round_files(Path(args.dir))
    if len(files) < 2:
        print(f"bench-check: fewer than two BENCH_*.json rounds in "
              f"{args.dir} — nothing to compare")
        return 0
    prev_p, new_p = files[-2], files[-1]
    prev = extract_bench_line(json.loads(prev_p.read_text()))
    new = extract_bench_line(json.loads(new_p.read_text()))
    if prev is None or new is None:
        bad = prev_p.name if prev is None else new_p.name
        print(f"bench-check: no bench JSON line found in {bad}")
        return 2
    analysis = (new.get("extra") or {}).get("analysis") or {}
    if analysis.get("new_findings"):
        print(f"bench-check: REFUSING to compare — {new_p.name} was "
              f"produced from a tree with {analysis['new_findings']} "
              f"outstanding kss-analyze finding(s); a hot-path or lock "
              f"violation invalidates the round (run `make analyze`)")
        for line in analysis.get("findings") or []:
            print(f"  {line}")
        return 2
    print(f"bench-check: {prev_p.name} -> {new_p.name} "
          f"(threshold {args.threshold:.0%})")
    rc = 0
    for row in compare(prev, new, args.threshold):
        mark = {"ok": "OK  ", "skip": "SKIP", "regression": "FAIL"}[row["status"]]
        ratio = f'{row["ratio"]:.3f}' if row["ratio"] is not None else "-"
        print(f"  {mark} {row['metric']}: {row['old']} -> {row['new']} "
              f"(x{ratio})")
        if row["status"] == "regression":
            rc = 1
    if rc:
        print("bench-check: REGRESSION above threshold — see FAIL rows")
    return rc


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
