#!/usr/bin/env python
"""`make bench-check`: CI-enforceable perf trajectory.

Compares the newest committed bench round (`BENCH_*.json` at the repo
root) against the previous one on the key serving metrics and exits
nonzero when any regressed more than the threshold (default 15%):

  * `decode_pods_per_sec`      — annotation decode rate (higher better);
  * `commit_stream_overlap_seconds` of the engine_2k_1k wave — commit
    work hidden inside the replay window (higher better,
    docs/wave-pipeline.md);
  * engine_2k_1k *wave wall* (pods / cycles_per_sec, lower better);
  * the headline e2e `value` (higher better).

A metric missing on either side (e.g. a CPU-fallback round that skipped
an engine phase, or rounds predating a counter) is reported as SKIP and
never fails the check — the gate enforces "no silent regression", not
"every round measures everything".
"""

from __future__ import annotations

import json
import re
import sys
from pathlib import Path

DEFAULT_THRESHOLD = 0.15


def extract_bench_line(doc: dict) -> dict | None:
    """The bench.py one-JSON-line result from a BENCH_*.json round
    artifact ({n, cmd, rc, tail}) or from a raw bench line itself."""
    if "metric" in doc:
        return doc
    tail = doc.get("tail") or ""
    for line in reversed(tail.splitlines()):
        line = line.strip()
        if line.startswith('{"metric"'):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                return None
    return None


def key_metrics(bench: dict) -> dict[str, tuple[float | None, str]]:
    """{metric: (value or None, direction)} — direction 'higher' means
    bigger is better, 'lower' the reverse."""
    extra = bench.get("extra") or {}
    eng = extra.get("engine_2k_1k") or {}
    counters = eng.get("counters") or {}
    wall = None
    if eng.get("cycles_per_sec") and eng.get("pods"):
        wall = eng["pods"] / eng["cycles_per_sec"]
    eng10k = extra.get("engine_10k_5k") or {}
    lazy = eng.get("lazy") or {}
    lazy10k = eng10k.get("lazy") or {}
    serve = extra.get("serve") or {}
    spec = (extra.get("speculative") or {}).get("low_contention") or {}
    bbox = extra.get("blackbox") or {}
    hist = extra.get("history") or {}
    fuse = extra.get("fuse") or {}
    spans10k = eng10k.get("spans") or {}
    return {
        "decode_pods_per_sec": (extra.get("decode_pods_per_sec"), "higher"),
        "commit_stream_overlap_seconds":
            (counters.get("commit_stream_overlap_seconds"), "higher"),
        "engine_2k_1k_wave_wall_seconds": (wall, "lower"),
        "headline_e2e_cycles_per_sec": (bench.get("value"), "higher"),
        # lazy-decode era metrics (absent from pre-PR-9 rounds: the
        # union/skip semantics of compare() carry them)
        "engine_10k_5k_cycles_per_sec":
            (eng10k.get("cycles_per_sec"), "higher"),
        "lazy_cold_first_read_seconds":
            (lazy.get("cold_read_seconds"), "lower"),
        # device-residency era metrics (absent from pre-PR-10 rounds):
        # bytes the 10k x 5k wave itself moved device->host (decision
        # rows only when device-resident — a regression here means the
        # heavy tensors started crossing in-wave again), the replay
        # stream span the residency shrinks, and the cold first read
        # that now includes the on-demand D2H
        "engine_10k_5k_wave_d2h_bytes":
            (lazy10k.get("wave_d2h_bytes"), "lower"),
        "engine_10k_5k_replay_stream_seconds":
            (spans10k.get("replay_and_decode_stream"), "lower"),
        "engine_10k_5k_cold_read_with_d2h_seconds":
            (lazy10k.get("cold_read_seconds"), "lower"),
        # multi-session serving era metrics (absent from pre-session
        # rounds — the union/skip semantics carry them): warm-round
        # aggregate and slowest-session throughput across K concurrent
        # sessions, and the cross-session compile-cache hit rate (a drop
        # means sessions started recompiling shapes they used to share)
        "serve_aggregate_cycles_per_sec":
            ((serve.get("warm") or {}).get("aggregate_cycles_per_sec"),
             "higher"),
        "serve_p99_session_cycles_per_sec":
            ((serve.get("warm") or {}).get("p99_session_cycles_per_sec"),
             "higher"),
        "serve_compile_cache_hit_rate":
            ((serve.get("compile_cache") or {}).get("hit_rate"), "higher"),
        # speculative-wave era metrics (absent from pre-speculative
        # rounds — union/skip carries them): the default wave's
        # cycles/s and accept rate on the low-contention reserved-slot
        # scenario at the 10k x 5k shape, and the measured speedup over
        # the KSS_TPU_SPECULATIVE=0 sequential scan in the same process
        # (a drop means the conflict oracle started rejecting work or
        # the batched rounds got slower)
        "engine_10k_5k_speculative_cycles_per_sec":
            (spec.get("speculative_cycles_per_sec"), "higher"),
        "engine_10k_5k_speculative_accept_rate":
            (spec.get("accept_rate"), "higher"),
        "engine_10k_5k_speculative_speedup_vs_scan":
            (spec.get("speedup"), "higher"),
        # wave black-box era metric (absent from pre-blackbox rounds —
        # union/skip carries them): on/off cycles/s ratio of the
        # always-on event ring's A/B; a drop means recording stopped
        # being free (the <=2% acceptance bar, noise-bound)
        "blackbox_overhead_ratio":
            (bbox.get("overhead_ratio"), "higher"),
        # telemetry-history era metric (absent from pre-history rounds —
        # union/skip carries them): on/off cycles/s ratio of the
        # columnar ring + trace-scope A/B; a drop means the always-on
        # causal plane stopped being free (the <=1.05x acceptance bar)
        "history_overhead_ratio":
            (hist.get("overhead_ratio"), "higher"),
        # cross-session fused dispatch era metrics (absent from pre-fuse
        # rounds — union/skip carries them): the K=4 fused arm's
        # aggregate and slowest-session cycles/s; a drop means the fused
        # batches stopped forming (window/rung divergence) or the
        # stacked executable got slower than time-sharing
        "fuse_aggregate_cycles_per_sec":
            (fuse.get("fuse_aggregate_cycles_per_sec"), "higher"),
        "fuse_p99_session_cycles_per_sec":
            (fuse.get("fuse_p99_session_cycles_per_sec"), "higher"),
    }


def compare(prev: dict, new: dict,
            threshold: float = DEFAULT_THRESHOLD) -> list[dict]:
    """[{metric, old, new, ratio, status}] — status ok|regression|skip.

    Iterates the UNION of both rounds' metric keys with missing entries
    treated as None (SKIP): a metric added after the older round — or
    dropped in a newer one — must never KeyError the gate, only a
    present-on-both-sides regression fails it."""
    rows = []
    old_m, new_m = key_metrics(prev), key_metrics(new)
    names = list(old_m) + [n for n in new_m if n not in old_m]
    for name in names:
        old_v = old_m.get(name, (None, "higher"))[0]
        new_v, direction = new_m.get(name, (None, "higher"))
        if name in old_m:
            direction = old_m[name][1]
        if not old_v or new_v is None:
            rows.append({"metric": name, "old": old_v, "new": new_v,
                         "ratio": None, "status": "skip"})
            continue
        ratio = new_v / old_v
        if direction == "higher":
            bad = ratio < 1 - threshold
        else:
            bad = ratio > 1 + threshold
        rows.append({"metric": name, "old": old_v, "new": new_v,
                     "ratio": round(ratio, 3),
                     "status": "regression" if bad else "ok"})
    return rows


def _round_files(root: Path, prefix: str = "BENCH") -> list[Path]:
    files = [p for p in root.glob(f"{prefix}_*.json")
             if re.fullmatch(rf"{prefix}_r?\d+\.json", p.name)]

    def order(p: Path):
        try:
            return (json.loads(p.read_text()).get("n", 0), p.name)
        except (OSError, json.JSONDecodeError):
            return (-1, p.name)

    return sorted(files, key=order)


def check_multichip(root: Path) -> str | None:
    """Sanity gate on the newest MULTICHIP_*.json round: the 8-virtual-
    device scaling harness must actually RUN (ok=true, skipped=false) —
    a round that silently degraded back to 'skipped' would invalidate
    the sharded-replay trajectory while the BENCH gate stayed green.
    Returns an error string, or None when fine (or no rounds exist)."""
    rounds = _round_files(root, prefix="MULTICHIP")
    if not rounds:
        return None
    newest = rounds[-1]
    try:
        doc = json.loads(newest.read_text())
    except (OSError, json.JSONDecodeError) as e:
        return f"{newest.name}: unreadable ({e})"
    if doc.get("skipped"):
        return (f"{newest.name}: skipped=true "
                f"(reason: {doc.get('reason') or 'unspecified'}) — the "
                "multichip harness must shard, not skip")
    if not doc.get("ok"):
        return f"{newest.name}: ok!=true"
    return None


# columnar scale-curve keys gated across SCALE_*.json rounds (the
# bench-scale artifact, docs/data-plane.md): throughput and build time at
# the 100k-node point, and the host RSS the columnar plane is supposed to
# hold down.  Same union/skip semantics as the BENCH keys: a key missing
# on either side SKIPs, only a present-on-both-sides regression fails.
SCALE_KEYS: list[tuple[str, str]] = [
    ("scale_100k_cycles_per_sec", "higher"),
    ("scale_100k_build_seconds", "lower"),
    ("scale_100k_host_rss_mb", "lower"),
]


def check_scale(root: Path,
                threshold: float = DEFAULT_THRESHOLD) -> tuple[str | None,
                                                               list[dict]]:
    """(sanity error or None, trajectory rows) over SCALE_*.json rounds.

    Sanity: the newest round must have run parity-pinned (all_parity_ok)
    and never rebuilt the node table on an unchanged node set — a round
    that lost either invalidates the scale trajectory outright.
    Trajectory: SCALE_KEYS compared newest-vs-previous with union/skip
    semantics; fewer than two rounds yields no rows."""
    rounds = _round_files(root, prefix="SCALE")
    if not rounds:
        return None, []
    try:
        new = json.loads(rounds[-1].read_text())
    except (OSError, json.JSONDecodeError) as e:
        return f"{rounds[-1].name}: unreadable ({e})", []
    if not new.get("all_parity_ok"):
        return (f"{rounds[-1].name}: all_parity_ok!=true — the columnar "
                "data plane diverged from the dict baseline"), []
    if not new.get("never_rebuilt_on_unchanged_nodes"):
        return (f"{rounds[-1].name}: an unchanged node set rebuilt the "
                "node table (reuse/delta path regressed)"), []
    if len(rounds) < 2:
        return None, []
    try:
        prev = json.loads(rounds[-2].read_text())
    except (OSError, json.JSONDecodeError) as e:
        return f"{rounds[-2].name}: unreadable ({e})", []
    rows = []
    for key, direction in SCALE_KEYS:
        old_v, new_v = prev.get(key), new.get(key)
        if not old_v or new_v is None:
            rows.append({"metric": key, "old": old_v, "new": new_v,
                         "ratio": None, "status": "skip"})
            continue
        ratio = new_v / old_v
        bad = (ratio < 1 - threshold if direction == "higher"
               else ratio > 1 + threshold)
        rows.append({"metric": key, "old": old_v, "new": new_v,
                     "ratio": round(ratio, 3),
                     "status": "regression" if bad else "ok"})
    return None, rows


# autopilot soak keys gated across SOAK_*.json rounds (the bench-soak
# artifact, docs/autopilot.md): the standard tenant's churn p99 and the
# fraction of overload submissions shed.  Same union/skip semantics.
SOAK_KEYS: list[tuple[str, str]] = [
    ("soak_p99_wave_seconds", "lower"),
    ("soak_shed_rate", "lower"),
]


def check_soak(root: Path,
               threshold: float = DEFAULT_THRESHOLD) -> tuple[str | None,
                                                              list[dict]]:
    """(sanity error or None, trajectory rows) over SOAK_*.json rounds.

    Sanity: the newest round must be green end to end — ok=true, every
    shed response carried Retry-After, and the degradation ladder
    recovered to rung 0.  A soak that lost any of those invalidates the
    trajectory outright.  Trajectory: SOAK_KEYS newest-vs-previous with
    union/skip semantics; fewer than two rounds yields no rows."""
    rounds = _round_files(root, prefix="SOAK")
    if not rounds:
        return None, []
    try:
        new = json.loads(rounds[-1].read_text())
    except (OSError, json.JSONDecodeError) as e:
        return f"{rounds[-1].name}: unreadable ({e})", []
    if not new.get("ok"):
        return (f"{rounds[-1].name}: ok!=true — "
                f"{(new.get('failures') or ['unspecified'])[0]}"), []
    if not new.get("all_shed_had_retry_after"):
        return (f"{rounds[-1].name}: a shed response was missing the "
                "Retry-After contract (or nothing was ever shed)"), []
    if not new.get("soak_recovered_to_rung0"):
        return (f"{rounds[-1].name}: the degradation ladder ended the "
                "soak off rung 0 — the autopilot pinned a session "
                "degraded"), []
    if len(rounds) < 2:
        return None, []
    try:
        prev = json.loads(rounds[-2].read_text())
    except (OSError, json.JSONDecodeError) as e:
        return f"{rounds[-2].name}: unreadable ({e})", []
    rows = []
    for key, direction in SOAK_KEYS:
        old_v, new_v = prev.get(key), new.get(key)
        if not old_v or new_v is None:
            rows.append({"metric": key, "old": old_v, "new": new_v,
                         "ratio": None, "status": "skip"})
            continue
        ratio = new_v / old_v
        bad = (ratio < 1 - threshold if direction == "higher"
               else ratio > 1 + threshold)
        rows.append({"metric": key, "old": old_v, "new": new_v,
                     "ratio": round(ratio, 3),
                     "status": "regression" if bad else "ok"})
    return None, rows


def main(argv: list[str]) -> int:
    import argparse

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--dir", default=str(Path(__file__).parents[2]),
                    help="directory holding the BENCH_*.json rounds")
    ap.add_argument("--threshold", type=float, default=DEFAULT_THRESHOLD)
    args = ap.parse_args(argv)
    mc_err = check_multichip(Path(args.dir))
    if mc_err is not None:
        print(f"bench-check: MULTICHIP sanity failed — {mc_err}")
        return 2
    sc_err, scale_rows = check_scale(Path(args.dir), args.threshold)
    if sc_err is not None:
        print(f"bench-check: SCALE sanity failed — {sc_err}")
        return 2
    soak_err, soak_rows = check_soak(Path(args.dir), args.threshold)
    if soak_err is not None:
        print(f"bench-check: SOAK sanity failed — {soak_err}")
        return 2
    files = _round_files(Path(args.dir))
    if len(files) < 2:
        print(f"bench-check: fewer than two BENCH_*.json rounds in "
              f"{args.dir} — nothing to compare")
        return 0
    prev_p, new_p = files[-2], files[-1]
    prev = extract_bench_line(json.loads(prev_p.read_text()))
    new = extract_bench_line(json.loads(new_p.read_text()))
    if prev is None or new is None:
        bad = prev_p.name if prev is None else new_p.name
        print(f"bench-check: no bench JSON line found in {bad}")
        return 2
    chaos = (new.get("extra") or {}).get("chaos") or {}
    if chaos and chaos.get("ok") is False:
        print(f"bench-check: REFUSING to compare — {new_p.name}'s chaos "
              f"verdict failed (seeds {chaos.get('seeds')}): waves no "
              "longer survive injected faults with bit-identical results "
              "(run `make chaos` to reproduce with the printed seed)")
        for line in (chaos.get("failures") or [])[:10]:
            print(f"  {line}")
        return 2
    if chaos.get("error"):
        # the harness itself died (import breakage, internal error):
        # that is a FAILED chaos run, not a skippable metric — a gate
        # that goes silently vacuous would defeat its purpose
        print(f"bench-check: REFUSING to compare — {new_p.name}'s chaos "
              f"harness errored instead of running: {chaos['error']} "
              "(run `make chaos`)")
        return 2
    bbox = (new.get("extra") or {}).get("blackbox") or {}
    if bbox.get("error") or bbox.get("annotations_identical") is False:
        # the black-box A/B either raised (annotation divergence is a
        # RuntimeError) or reported non-identical bytes: the recorder
        # touched the product — refuse the round rather than letting the
        # union/skip semantics wave it through as a missing metric
        print(f"bench-check: REFUSING to compare — {new_p.name}'s "
              f"blackbox A/B failed: "
              f"{bbox.get('error') or 'annotations diverged'} "
              "(run bench.py and see extra.blackbox)")
        return 2
    analysis = (new.get("extra") or {}).get("analysis") or {}
    if analysis.get("new_findings"):
        print(f"bench-check: REFUSING to compare — {new_p.name} was "
              f"produced from a tree with {analysis['new_findings']} "
              f"outstanding kss-analyze finding(s); a hot-path or lock "
              f"violation invalidates the round (run `make analyze`)")
        for line in analysis.get("findings") or []:
            print(f"  {line}")
        return 2
    print(f"bench-check: {prev_p.name} -> {new_p.name} "
          f"(threshold {args.threshold:.0%})")
    rc = 0
    for row in compare(prev, new, args.threshold) + scale_rows + soak_rows:
        mark = {"ok": "OK  ", "skip": "SKIP", "regression": "FAIL"}[row["status"]]
        ratio = f'{row["ratio"]:.3f}' if row["ratio"] is not None else "-"
        print(f"  {mark} {row['metric']}: {row['old']} -> {row['new']} "
              f"(x{ratio})")
        if row["status"] == "regression":
            rc = 1
    if rc:
        print("bench-check: REGRESSION above threshold — see FAIL rows")
    return rc


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
