"""Resource-watcher + StreamWriter error tables, mirroring the reference's
eventproxy/streamwriter suites (resourcewatcher/eventproxy_test.go:71-600,
streamwriter/streamwriter_test.go): initial-list delivery, event-sequence
ordering, write-failure teardown, and watcher-registration cleanup.
"""

import json
import threading
import time

from kube_scheduler_simulator_tpu.cluster.store import ObjectStore
from kube_scheduler_simulator_tpu.services.resourcewatcher import (
    ResourceWatcherService,
    StreamWriter,
)


def pod(name, ns="default"):
    return {"metadata": {"name": name, "namespace": ns}, "spec": {}}


def node(name):
    return {"metadata": {"name": name}, "spec": {}}


class SinkStream:
    """Collects decoded events; can be armed to fail after N writes
    (eventproxy_test.go:219 'should return an error when the Write method
    returns an error')."""

    def __init__(self, fail_after=None):
        self.events = []
        self.fail_after = fail_after
        self._lock = threading.Lock()

    def write(self, data: bytes):
        with self._lock:
            if self.fail_after is not None and len(self.events) >= self.fail_after:
                raise BrokenPipeError("client went away")
            self.events.append(json.loads(data))


def run_list_watch(svc, stream, lrv=None, settle=0.3):
    stop = threading.Event()
    t = threading.Thread(
        target=svc.list_watch, args=(StreamWriter(stream.write), lrv, stop),
        daemon=True)
    t.start()
    time.sleep(settle)
    return stop, t


def finish(stop, t):
    stop.set()
    t.join(timeout=2)
    assert not t.is_alive()


class TestStreamWriter:
    # streamwriter_test.go "should call Write method" / "twice"
    def test_send_writes_one_json_line_per_event(self):
        sink = SinkStream()
        w = StreamWriter(sink.write)
        assert w.send("Pod", "ADDED", pod("a"))
        assert w.send("Pod", "MODIFIED", pod("a"))
        assert [e["eventType"] for e in sink.events] == ["ADDED", "MODIFIED"]
        assert sink.events[0] == {
            "kind": "Pod", "eventType": "ADDED", "obj": pod("a")}

    # "should return an error when the Write method returns an error"
    def test_send_reports_write_failure(self):
        sink = SinkStream(fail_after=1)
        w = StreamWriter(sink.write)
        assert w.send("Pod", "ADDED", pod("a"))
        assert not w.send("Pod", "ADDED", pod("b"))

    def test_concurrent_sends_serialized(self):
        chunks = []
        in_flight = threading.Semaphore(1)
        overlapped = []

        def write(data):
            # a second writer entering while one is mid-write proves the
            # StreamWriter lock failed to serialize the send
            if not in_flight.acquire(blocking=False):
                overlapped.append(True)
            time.sleep(0.001)
            chunks.append(data)
            in_flight.release()

        w = StreamWriter(write)
        threads = [threading.Thread(
            target=lambda i=i: [w.send("Pod", "ADDED", pod(f"p{i}-{j}"))
                                for j in range(20)])
            for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert len(chunks) == 80
        assert not overlapped
        for c in chunks:
            json.loads(c)  # every chunk is one complete JSON document


class TestListWatch:
    # eventproxy_test.go:71 "should list the resource and update the
    # lastResourceVersion": initial listing arrives as ADDED events
    def test_initial_list_as_added_events(self):
        s = ObjectStore()
        s.create("nodes", node("n1"))
        s.create("pods", pod("p1"))
        svc = ResourceWatcherService(s, resources=["nodes", "pods"])
        sink = SinkStream()
        stop, t = run_list_watch(svc, sink)
        finish(stop, t)
        got = {(e["kind"], e["obj"]["metadata"]["name"]) for e in sink.events}
        assert got == {("Node", "n1"), ("Pod", "p1")}
        assert all(e["eventType"] == "ADDED" for e in sink.events)

    # eventproxy_test.go:266-527 event sequences: ADDED / MODIFIED /
    # DELETED arrive in order on the live stream
    def test_live_event_sequence_in_order(self):
        s = ObjectStore()
        svc = ResourceWatcherService(s, resources=["pods"])
        sink = SinkStream()
        stop, t = run_list_watch(svc, sink)
        s.create("pods", pod("a"))
        time.sleep(0.1)
        s.update("pods", s.get("pods", "a"))
        time.sleep(0.1)
        s.delete("pods", "a")
        time.sleep(0.3)
        finish(stop, t)
        assert [e["eventType"] for e in sink.events] == [
            "ADDED", "MODIFIED", "DELETED"]

    # handler/watcher.go:23-45 lastResourceVersion: nonzero rv skips the
    # initial listing and replays only newer events
    def test_resume_from_rv_skips_initial_list(self):
        s = ObjectStore()
        s.create("pods", pod("old"))
        _, rv = s.list("pods")
        svc = ResourceWatcherService(s, resources=["pods"])
        sink = SinkStream()
        stop, t = run_list_watch(svc, sink, lrv={"pods": rv})
        s.create("pods", pod("new"))
        time.sleep(0.3)
        finish(stop, t)
        names = [e["obj"]["metadata"]["name"] for e in sink.events]
        assert names == ["new"]

    # eventproxy_test.go:219: a dead client mid-initial-list aborts the
    # stream AND unregisters every watch queue (no leak)
    def test_write_failure_mid_list_cleans_up_watchers(self):
        s = ObjectStore()
        for i in range(5):
            s.create("pods", pod(f"p{i}"))
        svc = ResourceWatcherService(s, resources=["pods"])
        sink = SinkStream(fail_after=2)
        stop = threading.Event()
        svc.list_watch(StreamWriter(sink.write), None, stop)  # returns, no hang
        assert len(sink.events) == 2
        assert s._watchers["pods"] == []

    def test_write_failure_on_live_stream_stops_pumps(self):
        s = ObjectStore()
        svc = ResourceWatcherService(s, resources=["pods"])
        sink = SinkStream(fail_after=1)
        stop, t = run_list_watch(svc, sink)
        s.create("pods", pod("a"))   # delivered
        time.sleep(0.1)
        s.create("pods", pod("b"))   # write raises -> dead
        t.join(timeout=2)
        assert not t.is_alive()
        assert len(sink.events) == 1

    def test_stop_unregisters_all_watch_queues(self):
        s = ObjectStore()
        svc = ResourceWatcherService(s)  # all 7 default kinds
        sink = SinkStream()
        stop, t = run_list_watch(svc, sink, settle=0.2)
        assert sum(len(qs) for qs in s._watchers.values()) >= 7
        finish(stop, t)
        assert sum(len(qs) for qs in s._watchers.values()) == 0

    def test_two_clients_independent_streams(self):
        s = ObjectStore()
        svc = ResourceWatcherService(s, resources=["pods"])
        a, b = SinkStream(), SinkStream()
        stop_a, ta = run_list_watch(svc, a, settle=0.1)
        stop_b, tb = run_list_watch(svc, b, settle=0.1)
        s.create("pods", pod("x"))
        time.sleep(0.3)
        finish(stop_a, ta)
        finish(stop_b, tb)
        for sink in (a, b):
            assert [e["obj"]["metadata"]["name"] for e in sink.events] == ["x"]
