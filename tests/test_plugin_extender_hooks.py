"""Per-extension-point plugin-extender Before/After hooks — the
reference's PluginExtenders contract (wrappedplugin.go:159-171, ordering
tested in wrappedplugin_test.go):

  * Before* runs before the original plugin; a non-success short-circuits
    — the plugin never runs and NOTHING is recorded for it;
  * the store records the ORIGINAL plugin's result;
  * After* rewrites what the framework sees (placement), not the record.
"""

import json

from kube_scheduler_simulator_tpu.cluster.store import ObjectStore
from kube_scheduler_simulator_tpu.framework.engine import SchedulerEngine
from kube_scheduler_simulator_tpu.plugins.custom import CustomPlugin
from kube_scheduler_simulator_tpu.plugins.registry import PluginSetConfig
from kube_scheduler_simulator_tpu.scheduler.debuggable import PluginExtender
from kube_scheduler_simulator_tpu.store import annotations as ann


class IndexScore(CustomPlugin):
    """Filter passes everywhere; score = node index * 10."""

    name = "IndexScore"
    default_weight = 1

    def filter(self, pod, node):
        return None

    def score(self, pod, node):
        return int(node["metadata"]["name"].rsplit("-", 1)[1]) * 10


def _nodes(n):
    return [
        {"metadata": {"name": f"node-{i:05d}"},
         "status": {"allocatable": {"cpu": "8", "memory": "32Gi", "pods": "50"}}}
        for i in range(n)
    ]


def _pod(name="pod-a"):
    return {"kind": "Pod", "metadata": {"name": name}, "spec": {"containers": [
        {"name": "c", "resources": {"requests": {"cpu": "1", "memory": "1Gi"}}}]}}


def _engine(extenders, plugins=None, n_nodes=3):
    store = ObjectStore()
    for n in _nodes(n_nodes):
        store.create("nodes", n)
    store.create("pods", _pod())
    plugins = plugins if plugins is not None else [IndexScore()]
    cfg = PluginSetConfig(
        enabled=["NodeResourcesFit"] + [p.name for p in plugins],
        custom={p.name: p for p in plugins},
    )
    engine = SchedulerEngine(store, plugin_config=cfg)
    engine.plugin_extenders = extenders
    return engine, store


def _annos(store, name="pod-a"):
    return store.get("pods", name)["metadata"].get("annotations") or {}


def test_before_filter_failure_suppresses_record_and_node():
    calls = []

    class Ext(PluginExtender):
        def before_filter(self, pod, node_name):
            calls.append(("before", node_name))
            return "vetoed by hook" if node_name == "node-00002" else None

    engine, store = _engine({"IndexScore": Ext()})
    assert engine._needs_host_path()
    assert engine.schedule_pending() == 1
    annos = _annos(store)
    fr = json.loads(annos[ann.FILTER_RESULT])
    # node-00002: NodeResourcesFit (earlier in order) recorded, IndexScore
    # NOT recorded (Before short-circuited before the plugin ran)
    assert fr["node-00002"] == {"NodeResourcesFit": "passed"}
    assert fr["node-00000"]["IndexScore"] == "passed"
    # the vetoed node lost: IndexScore alone would pick the highest index
    assert annos[ann.SELECTED_NODE] == "node-00001"
    assert ("before", "node-00002") in calls


def test_after_filter_fail_hides_node_but_record_shows_passed():
    class Ext(PluginExtender):
        def after_filter(self, pod, node_name, msg):
            if node_name == "node-00002":
                return "hook says no"
            return msg

    engine, store = _engine({"IndexScore": Ext()})
    assert engine.schedule_pending() == 1
    annos = _annos(store)
    fr = json.loads(annos[ann.FILTER_RESULT])
    # record keeps the plugin's own result (AddFilterResult runs before
    # AfterFilter), but the framework never considers the node
    assert fr["node-00002"]["IndexScore"] == "passed"
    assert annos[ann.SELECTED_NODE] == "node-00001"
    assert "node-00002" not in json.loads(annos[ann.SCORE_RESULT])


def test_after_filter_pass_resurrects_node_and_later_plugins_record():
    class Veto(CustomPlugin):
        name = "Veto"

        def filter(self, pod, node):
            return ("no" if node["metadata"]["name"] == "node-00002" else None)

    class Tail(CustomPlugin):
        name = "Tail"

        def filter(self, pod, node):
            return None

        def score(self, pod, node):
            return int(node["metadata"]["name"].rsplit("-", 1)[1]) * 10

    class Ext(PluginExtender):
        def after_filter(self, pod, node_name, msg):
            return None  # everything passes as far as the framework knows

    engine, store = _engine({"Veto": Ext()}, plugins=[Veto(), Tail()])
    assert engine.schedule_pending() == 1
    annos = _annos(store)
    fr = json.loads(annos[ann.FILTER_RESULT])
    # record keeps Veto's own failure, AND later plugins ran + recorded on
    # that node because the framework continued past the rewritten status
    assert fr["node-00002"]["Veto"] == "no"
    assert fr["node-00002"]["Tail"] == "passed"
    # the resurrected highest-index node wins on Tail's score
    assert annos[ann.SELECTED_NODE] == "node-00002"


def test_after_score_changes_selection_but_not_score_record():
    class Ext(PluginExtender):
        def after_score(self, pod, node_name, score):
            # invert the ranking
            return 1000 - score

    engine, store = _engine({"IndexScore": Ext()})
    assert engine.schedule_pending() == 1
    annos = _annos(store)
    sc = json.loads(annos[ann.SCORE_RESULT])
    # score-result keeps the ORIGINAL raw scores
    assert sc["node-00002"]["IndexScore"] == "20"
    # but the framework ranked on the inverted values -> lowest index wins
    assert annos[ann.SELECTED_NODE] == "node-00000"
    # finalscore reflects normalize(modified raw) x weight: IndexScore has
    # no ScoreExtensions, so final = modified raw x 1
    fs = json.loads(annos[ann.FINAL_SCORE_RESULT])
    assert fs["node-00000"]["IndexScore"] == "1000"
    assert fs["node-00002"]["IndexScore"] == "980"


def test_before_score_failure_fails_the_cycle():
    class Ext(PluginExtender):
        def before_score(self, pod, node_name):
            return "scoring disabled"

    engine, store = _engine({"IndexScore": Ext()})
    assert engine.schedule_pending() == 0
    pod = store.get("pods", "pod-a")
    assert not pod["spec"].get("nodeName")
    conds = {c["type"]: c for c in pod["status"]["conditions"]}
    assert conds["PodScheduled"]["reason"] == "Unschedulable"


def test_after_normalize_changes_selection_not_record():
    class Ext(PluginExtender):
        def after_normalize(self, pod, scores):
            # force node-00000 to the top for the framework only
            out = dict(scores)
            out["node-00000"] = 10_000
            return out

    engine, store = _engine({"IndexScore": Ext()})
    assert engine.schedule_pending() == 1
    annos = _annos(store)
    assert annos[ann.SELECTED_NODE] == "node-00000"
    fs = json.loads(annos[ann.FINAL_SCORE_RESULT])
    # record written before AfterNormalizeScore upstream
    assert fs["node-00000"]["IndexScore"] == "0"
    assert fs["node-00002"]["IndexScore"] == "20"


class LifecyclePlugin(CustomPlugin):
    name = "LC"

    def __init__(self, log):
        self.log = log

    def filter(self, pod, node):
        return None

    def reserve(self, pod, node):
        self.log.append("reserve")
        return None

    def unreserve(self, pod, node):
        self.log.append("unreserve")

    def permit(self, pod, node):
        self.log.append("permit")
        return None

    def pre_bind(self, pod, node):
        self.log.append("pre_bind")
        return None


def test_before_reserve_failure_skips_plugin_and_record():
    log = []

    class Ext(PluginExtender):
        def before_reserve(self, pod, node):
            return "reservation vetoed"

    engine, store = _engine({"LC": Ext()}, plugins=[LifecyclePlugin(log)])
    assert engine.schedule_pending() == 0
    assert "reserve" not in log          # plugin skipped
    assert "unreserve" in log            # unreserve still runs
    annos = _annos(store)
    assert json.loads(annos.get(ann.RESERVE_RESULT, "{}")) == {}  # no record


def test_after_permit_deny_overrides_allow():
    log = []

    class Ext(PluginExtender):
        def after_permit(self, pod, node, out):
            return "denied by hook"

    engine, store = _engine({"LC": Ext()}, plugins=[LifecyclePlugin(log)])
    assert engine.schedule_pending() == 0
    assert "permit" in log
    annos = _annos(store)
    # record keeps the plugin's own allow; the framework obeyed the hook
    assert json.loads(annos[ann.PERMIT_STATUS_RESULT])["LC"] == "success"
    assert not store.get("pods", "pod-a")["spec"].get("nodeName")


def test_after_pre_bind_failure_unreserves():
    log = []

    class Ext(PluginExtender):
        def after_pre_bind(self, pod, node, msg):
            return "prebind vetoed"

    engine, store = _engine({"LC": Ext()}, plugins=[LifecyclePlugin(log)])
    assert engine.schedule_pending() == 0
    assert "pre_bind" in log and "unreserve" in log
    annos = _annos(store)
    assert json.loads(annos[ann.PRE_BIND_RESULT])["LC"] == "success"


def test_custom_normalize_with_preemption_does_not_crash():
    """Preemption's fit oracle replays with the same plugin config; the
    replay() NormalizeScore guard must not fire for that filter-only
    caller (regression: ValueError aborted the whole wave)."""
    class Norm(IndexScore):
        name = "Norm"

        def normalize(self, scores):
            return list(scores)

    store = ObjectStore()
    store.create("nodes", {
        "metadata": {"name": "node-00000"},
        "status": {"allocatable": {"cpu": "2", "memory": "4Gi", "pods": "10"}}})
    # a low-priority victim occupying the node
    store.create("pods", {
        "kind": "Pod", "metadata": {"name": "victim"},
        "spec": {"priority": 0, "nodeName": "node-00000", "containers": [
            {"name": "c", "resources": {"requests": {"cpu": "2", "memory": "3Gi"}}}]}})
    store.create("pods", {
        "kind": "Pod", "metadata": {"name": "urgent"},
        "spec": {"priority": 100, "containers": [
            {"name": "c", "resources": {"requests": {"cpu": "2", "memory": "3Gi"}}}]}})
    cfg = PluginSetConfig(
        enabled=["NodeResourcesFit", "DefaultPreemption", "Norm"],
        custom={"Norm": Norm()},
    )
    engine = SchedulerEngine(store, plugin_config=cfg)
    assert engine._needs_host_path()
    assert engine.schedule_pending() == 1
    assert store.get("pods", "urgent")["spec"].get("nodeName") == "node-00000"


def test_hooks_only_apply_to_their_plugin():
    """An extender registered for a DISABLED plugin name must not force
    the host path or fire."""
    fired = []

    class Ext(PluginExtender):
        def before_filter(self, pod, node_name):
            fired.append(node_name)
            return "nope"

    engine, store = _engine({"NotEnabled": Ext()})
    assert not engine._needs_host_path()
    assert engine.schedule_pending() == 1
    assert fired == []
