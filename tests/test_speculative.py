"""Speculative dp-batch scheduling: bit-parity with the sequential scan
and the CPU oracle (parallel/speculative.py exactness argument)."""

from __future__ import annotations

import numpy as np
import pytest

from kube_scheduler_simulator_tpu.framework.replay import replay
from kube_scheduler_simulator_tpu.models.workloads import make_nodes, make_pods
from kube_scheduler_simulator_tpu.parallel.mesh import make_mesh
from kube_scheduler_simulator_tpu.parallel.speculative import (
    SAFE_SPECULATIVE, replay_speculative, speculation_ok)
from kube_scheduler_simulator_tpu.plugins.registry import PluginSetConfig
from kube_scheduler_simulator_tpu.state.compile import compile_workload
from kube_scheduler_simulator_tpu.store.decode import decode_pod_result

SAFE_CFG = ["NodeResourcesFit", "NodeResourcesBalancedAllocation",
            "NodeAffinity", "TaintToleration"]


def _workload(n_nodes=24, n_pods=60, seed=9):
    # tight capacity so pods contend for the same nodes — the acceptance
    # walk must actually cut batches, not rubber-stamp them
    nodes = make_nodes(n_nodes, seed=seed, taint_fraction=0.2)
    pods = make_pods(n_pods, seed=seed + 1, with_affinity=True,
                     with_tolerations=True)
    return nodes, pods


def test_speculation_ok_classifier():
    assert speculation_ok(PluginSetConfig(enabled=SAFE_CFG))
    # label-coupled plugins qualify WITH manifests (interaction rule),
    # node-local NodePorts under the dirty-node rule alone
    assert speculation_ok(PluginSetConfig(
        enabled=SAFE_CFG + ["PodTopologySpread"]))
    assert speculation_ok(PluginSetConfig(
        enabled=SAFE_CFG + ["InterPodAffinity"]))
    assert speculation_ok(PluginSetConfig(enabled=["NodePorts"]))
    # the volume family's cluster-wide PV/PVC bind state stays excluded
    assert not speculation_ok(PluginSetConfig(
        enabled=SAFE_CFG + ["VolumeBinding"]))
    assert not speculation_ok(PluginSetConfig(
        enabled=SAFE_CFG + ["VolumeRestrictions"]))


@pytest.mark.parametrize("dp,batch", [(1, 4), (2, 8), (4, 16)])
def test_speculative_matches_scan(dp, batch):
    nodes, pods = _workload()
    cfg = PluginSetConfig(enabled=SAFE_CFG)
    cw = compile_workload(nodes, pods, cfg)
    base = replay(cw, chunk=16)

    cw2 = compile_workload(nodes, pods, cfg)
    mesh = make_mesh(dp * 2, dp=dp) if dp > 1 else None
    rr, stats = replay_speculative(cw2, mesh, batch=batch)

    np.testing.assert_array_equal(rr.selected, base.selected)
    np.testing.assert_array_equal(rr.feasible_count, base.feasible_count)
    assert stats["rounds"] >= (len(pods) + batch - 1) // batch
    # full annotation byte-parity, not just selections
    for i in range(len(pods)):
        a = decode_pod_result(rr, i)
        b = decode_pod_result(base, i)
        assert a == b, f"pod {i}"


def test_speculative_under_contention_still_exact():
    """2 nodes, many pods: almost every batch is cut at the first
    interference; parity must survive the worst acceptance pattern."""
    nodes = make_nodes(2, seed=3)
    pods = make_pods(30, seed=4)
    cfg = PluginSetConfig(enabled=["NodeResourcesFit",
                                   "NodeResourcesBalancedAllocation"])
    base = replay(compile_workload(nodes, pods, cfg), chunk=8)
    rr, stats = replay_speculative(compile_workload(nodes, pods, cfg),
                                   None, batch=8)
    np.testing.assert_array_equal(rr.selected, base.selected)
    assert stats["mean_accept"] < 8  # contention actually cut batches


def test_speculative_oracle_parity():
    from kube_scheduler_simulator_tpu.reference_impl.sequential import (
        SequentialScheduler)

    nodes, pods = _workload(n_nodes=12, n_pods=24, seed=21)
    cfg = PluginSetConfig(enabled=SAFE_CFG)
    oracle = SequentialScheduler(nodes, pods, cfg).schedule_all()
    rr, _ = replay_speculative(compile_workload(nodes, pods, cfg),
                               None, batch=6)
    for i, (sa, _sel) in enumerate(oracle):
        da = decode_pod_result(rr, i)
        for key, v in sa.items():
            assert da[key] == v, f"pod {i} {key}"


def test_engine_uses_speculative_path_with_dp_mesh(monkeypatch):
    from kube_scheduler_simulator_tpu.cluster.store import ObjectStore
    from kube_scheduler_simulator_tpu.framework.engine import SchedulerEngine
    from kube_scheduler_simulator_tpu.utils.tracing import TRACER

    nodes, pods = _workload(n_nodes=16, n_pods=24, seed=31)
    mesh = make_mesh(4, dp=2)

    def run(mesh_arg):
        store = ObjectStore()
        for n in nodes:
            store.create("nodes", n)
        for p in pods:
            store.create("pods", p)
        eng = SchedulerEngine(store, plugin_config=PluginSetConfig(
            enabled=SAFE_CFG), mesh=mesh_arg, chunk=16)
        eng.schedule_pending()
        out, _ = store.list("pods")
        return {(p["metadata"]["name"]): (
            p["spec"].get("nodeName"),
            (p["metadata"].get("annotations") or {}).get(
                "kube-scheduler-simulator.sigs.k8s.io/finalscore-result"))
            for p in out}

    TRACER.reset()
    spec_out = run(mesh)
    spans = TRACER.summary()["spans"]
    assert "speculative_round" in spans, sorted(spans)
    # the sequential-scan parity baseline (KSS_TPU_SPECULATIVE=0)
    monkeypatch.setenv("KSS_TPU_SPECULATIVE", "0")
    base_out = run(None)
    assert spec_out == base_out


def test_point_enabled_unsafe_plugin_blocks_speculation():
    """point_enabled can add a plugin cfg.enabled never lists; the gate
    must look at the ACTIVE set (review finding: a point-enabled coupled
    plugin silently corrupted speculative state).  VolumeBinding is the
    representative excluded plugin now that spread/interpod qualify."""
    cfg = PluginSetConfig(enabled=["NodeResourcesFit"],
                          point_enabled={"filter": ["VolumeBinding"]})
    assert not speculation_ok(cfg)
    # and a point-enabled LABEL_COUPLED plugin without manifests
    cfg2 = PluginSetConfig(enabled=["NodeResourcesFit"],
                           point_enabled={"score": ["PodTopologySpread"]})
    assert not speculation_ok(cfg2, have_manifests=False)
    assert speculation_ok(cfg2, have_manifests=True)


def test_init_carry_survives_speculative_replay():
    """commit() donates its carry; the workload's init_carry must be
    copied first so the SAME cw can replay again (review finding)."""
    nodes, pods = _workload(n_nodes=8, n_pods=10, seed=41)
    cfg = PluginSetConfig(enabled=SAFE_CFG)
    cw = compile_workload(nodes, pods, cfg)
    rr1, _ = replay_speculative(cw, None, batch=4)
    rr2, _ = replay_speculative(cw, None, batch=4)  # reuses cw.init_carry
    np.testing.assert_array_equal(rr1.selected, rr2.selected)
    base = replay(cw, chunk=4)  # the scan also reuses it
    np.testing.assert_array_equal(rr1.selected, base.selected)


COUPLED_CFG = SAFE_CFG + ["PodTopologySpread"]


def _coupled_workload(n_nodes=20, n_pods=48, seed=13, interpod=False):
    nodes = make_nodes(n_nodes, seed=seed, taint_fraction=0.2)
    pods = make_pods(n_pods, seed=seed + 1, with_affinity=True,
                     with_tolerations=True, with_spread=True,
                     with_interpod=interpod)
    return nodes, pods


@pytest.mark.parametrize("interpod", [False, True])
def test_speculative_label_coupled_matches_scan(interpod):
    """Configs 4/5 plugin sets (spread / interpod) under the interaction
    rule: byte-parity with the scan down to full annotations."""
    nodes, pods = _coupled_workload(interpod=interpod)
    cfg = PluginSetConfig(enabled=COUPLED_CFG
                          + (["InterPodAffinity"] if interpod else []))
    assert speculation_ok(cfg)
    base = replay(compile_workload(nodes, pods, cfg), chunk=16)
    rr, stats = replay_speculative(compile_workload(nodes, pods, cfg),
                                   None, batch=8, pods=pods)
    np.testing.assert_array_equal(rr.selected, base.selected)
    for i in range(len(pods)):
        assert decode_pod_result(rr, i) == decode_pod_result(base, i), i
    # interactions must actually cut batches on this workload (app-group
    # selectors overlap), or the rule is vacuous
    assert stats["mean_accept"] < stats["batch"]


def test_speculative_label_coupled_oracle_parity():
    from kube_scheduler_simulator_tpu.reference_impl.sequential import (
        SequentialScheduler)

    nodes, pods = _coupled_workload(n_nodes=10, n_pods=20, seed=29,
                                    interpod=True)
    cfg = PluginSetConfig(enabled=COUPLED_CFG + ["InterPodAffinity"])
    oracle = SequentialScheduler(nodes, pods, cfg).schedule_all()
    rr, _ = replay_speculative(compile_workload(nodes, pods, cfg),
                               None, batch=6, pods=pods)
    for i, (sa, _sel) in enumerate(oracle):
        da = decode_pod_result(rr, i)
        for key, v in sa.items():
            assert da[key] == v, f"pod {i} {key}"


def test_speculative_nodeports_exact():
    """NodePorts rides the dirty-node rule: port conflicts are node-local
    and monotone; parity with the scan under hostPort contention."""
    nodes = make_nodes(6, seed=7)
    pods = []
    for i in range(18):
        p = {"metadata": {"name": f"hp-{i}", "namespace": "default"},
             "spec": {"containers": [{
                 "name": "c",
                 "resources": {"requests": {"cpu": "100m"}},
                 "ports": [{"hostPort": 8000 + (i % 3),
                            "protocol": "TCP"}]}]}}
        pods.append(p)
    cfg = PluginSetConfig(enabled=["NodeResourcesFit", "NodePorts"])
    assert speculation_ok(cfg)
    base = replay(compile_workload(nodes, pods, cfg), chunk=8)
    rr, _ = replay_speculative(compile_workload(nodes, pods, cfg),
                               None, batch=6)
    np.testing.assert_array_equal(rr.selected, base.selected)
    for i in range(len(pods)):
        assert decode_pod_result(rr, i) == decode_pod_result(base, i), i


def test_label_coupled_requires_manifests():
    nodes, pods = _coupled_workload(n_nodes=6, n_pods=6)
    cfg = PluginSetConfig(enabled=COUPLED_CFG)
    assert not speculation_ok(cfg, have_manifests=False)
    with pytest.raises(ValueError):
        replay_speculative(compile_workload(nodes, pods, cfg), None, batch=4)


def test_namespace_selector_interaction_detected():
    """Review counterexample: a cross-namespace required anti-affinity via
    namespaceSelector must register as an interaction (the hand-rolled
    term extraction missed it; the oracle now reuses
    plugins/interpod.effective_terms with the namespace manifests)."""
    def node(name, zone, cpu):
        return {"metadata": {"name": name, "labels":
                             {"topology.kubernetes.io/zone": zone,
                              "kubernetes.io/hostname": name}},
                "status": {"allocatable": {"cpu": cpu, "memory": "8Gi",
                                           "pods": "10"}}}

    nodes = [node("n0", "A", "300m"), node("n1", "A", "4"),
             node("n2", "B", "4")]
    namespaces = [{"metadata": {"name": "a", "labels": {"team": "x"}}},
                  {"metadata": {"name": "b", "labels": {"team": "y"}}}]
    p0 = {"metadata": {"name": "p0", "namespace": "a",
                       "labels": {"app": "x"}},
          "spec": {"containers": [{"name": "c", "resources":
                                   {"requests": {"cpu": "200m"}}}]}}
    p1 = {"metadata": {"name": "p1", "namespace": "b",
                       "labels": {"app": "y"}},
          "spec": {"containers": [{"name": "c", "resources":
                                   {"requests": {"cpu": "1"}}}],
                   "affinity": {"podAntiAffinity": {
                       "requiredDuringSchedulingIgnoredDuringExecution": [{
                           "labelSelector": {"matchLabels": {"app": "x"}},
                           "namespaceSelector": {},
                           "topologyKey": "topology.kubernetes.io/zone"}]}}}}
    pods = [p0, p1]
    cfg = PluginSetConfig(enabled=["NodeResourcesFit", "InterPodAffinity"])
    base = replay(compile_workload(nodes, pods, cfg, namespaces=namespaces),
                  chunk=2)
    rr, stats = replay_speculative(
        compile_workload(nodes, pods, cfg, namespaces=namespaces),
        None, batch=2, pods=pods, namespaces=namespaces)
    np.testing.assert_array_equal(rr.selected, base.selected)
    for i in range(2):
        assert decode_pod_result(rr, i) == decode_pod_result(base, i), i
    # the interaction must have cut the first batch to 1
    assert stats["rounds"] == 2 and stats["mean_accept"] == 1.0


def test_sparse_tail_mixed_with_dense_fallback_rounds(monkeypatch):
    """KSS_TPU_SPECULATIVE_CANDIDATES below the cluster size engages the
    sparse score/select tail; pods whose feasible set exceeds the cap
    must push their round onto the dense eval — BOTH kinds of round in
    one stream, byte-identical to the scan."""
    from kube_scheduler_simulator_tpu.models.workloads import (
        make_slot_pinned_workload)

    monkeypatch.setenv("KSS_TPU_SPECULATIVE_CANDIDATES", "4")
    nodes, pinned = make_slot_pinned_workload(20, 16, seed=71)
    broad = make_pods(10, seed=72)  # feasible on ~all 16 nodes ( > 4 )
    pods = pinned[:10] + broad + pinned[10:]
    cfg = PluginSetConfig(enabled=["NodeResourcesFit",
                                   "NodeResourcesBalancedAllocation",
                                   "NodeAffinity"])
    base = replay(compile_workload(nodes, pods, cfg), chunk=8)
    rr, stats = replay_speculative(compile_workload(nodes, pods, cfg),
                                   None, batch=8)
    np.testing.assert_array_equal(rr.selected, base.selected)
    np.testing.assert_array_equal(rr.feasible_count, base.feasible_count)
    for i in range(len(pods)):
        assert decode_pod_result(rr, i) == decode_pod_result(base, i), i


def test_wide_i64_tier_keeps_width_through_the_stream(monkeypatch):
    """Compile-proven i64 scores skip straight to the widest tier: the
    stream's eval must receive the tier STRING (review finding: a
    bool(wide) coercion disabled overflow detection and stacked the
    i64 tier's raw32 as int32) and the chunk-grid buffers must hold
    int64 — byte parity with the equally-forced scan, through both
    accumulator rounds (mixed acceptance) and direct-ingest rounds."""
    from kube_scheduler_simulator_tpu.models.workloads import (
        make_slot_pinned_workload)

    monkeypatch.setenv("KSS_TPU_SPECULATIVE_CANDIDATES", "4")
    nodes, pinned = make_slot_pinned_workload(20, 16, seed=81)
    pods = pinned[:10] + make_pods(8, seed=82) + pinned[10:]
    cfg = PluginSetConfig(enabled=["NodeResourcesFit",
                                   "NodeResourcesBalancedAllocation"])

    def force_i64(cw):
        cw.host["score_dtypes"] = tuple(
            "i64" for _ in cw.config.scorers())
        return cw

    base = replay(force_i64(compile_workload(nodes, pods, cfg)), chunk=8)
    rr, _ = replay_speculative(force_i64(compile_workload(nodes, pods, cfg)),
                               None, batch=8)
    assert rr._compact.raw32, "i64 tier must pool scorers into raw32"
    import jax.numpy as jnp
    for a in rr._compact.raw32:
        assert jnp.asarray(a).dtype == jnp.int64, a.dtype
    np.testing.assert_array_equal(rr.selected, base.selected)
    for i in range(len(pods)):
        assert decode_pod_result(rr, i) == decode_pod_result(base, i), i


def test_adaptive_batch_ladder_stays_exact():
    """batch=None engages the adaptive ladder (grow on full accept,
    shrink on early cuts); results stay bit-identical to the scan."""
    nodes, pods = _coupled_workload(n_nodes=24, n_pods=80, seed=51)
    cfg = PluginSetConfig(enabled=COUPLED_CFG)
    base = replay(compile_workload(nodes, pods, cfg), chunk=16)
    rr, stats = replay_speculative(compile_workload(nodes, pods, cfg),
                                   None, pods=pods)
    assert stats["adaptive"]
    np.testing.assert_array_equal(rr.selected, base.selected)
    for i in range(len(pods)):
        assert decode_pod_result(rr, i) == decode_pod_result(base, i), i


def test_adaptive_ladder_climbs_on_sparse_feasibility():
    """Disjoint feasible sets (per-node affinity pins) fully accept every
    round, so the ladder must actually climb its rungs (review finding:
    the climb condition was computed after `lo` moved and never fired)."""
    nodes = make_nodes(80, seed=61)
    pods = []
    for i in range(80):
        pods.append({
            "metadata": {"name": f"pin-{i:03d}", "namespace": "default"},
            "spec": {
                "containers": [{"name": "c", "resources":
                                {"requests": {"cpu": "100m"}}}],
                "affinity": {"nodeAffinity": {
                    "requiredDuringSchedulingIgnoredDuringExecution": {
                        "nodeSelectorTerms": [{"matchExpressions": [{
                            "key": "kubernetes.io/hostname",
                            "operator": "In",
                            "values": [f"node-{i:05d}"]}]}]}}},
            }})
    cfg = PluginSetConfig(enabled=["NodeResourcesFit", "NodeAffinity"])
    base = replay(compile_workload(nodes, pods, cfg), chunk=16)
    rr, stats = replay_speculative(compile_workload(nodes, pods, cfg), None)
    np.testing.assert_array_equal(rr.selected, base.selected)
    # the x4 ladder must actually climb off its bottom rung (8 -> 32)
    assert max(stats["round_batches"]) > stats["round_batches"][0], stats
    assert stats["round_batches"][:2] == [8, 32], stats["round_batches"]
    assert stats["accepted_first_try"] == stats["rounds"]
    assert stats["fallback_at"] is None and stats["accept_rate"] == 1.0
