"""Codec suite under AddressSanitizer + UBSan (slow; `make test-asan`).

The C++ surface of the annotation codec keeps growing (per-pod fused
decode, chunk-granular decode with a worker pool and arena) and hands raw
pointers across the ctypes boundary; this runs the whole codec/chunk test
suite against a `-fsanitize=address,undefined` build of the library in a
subprocess (KSS_TPU_NATIVE_SO points the loader at the sanitizer build,
LD_PRELOAD injects the ASan runtime ahead of an uninstrumented Python).
Any heap overflow / UB the normal suite would silently survive fails the
subprocess here.
"""

import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.slow

_SUITE = ["tests/test_native_codec.py", "tests/test_chunk_decode.py"]


def _toolchain_lib(name: str) -> str | None:
    try:
        out = subprocess.run(["gcc", f"-print-file-name={name}"],
                             capture_output=True, text=True, timeout=30)
    except (OSError, subprocess.TimeoutExpired):
        return None
    path = (out.stdout or "").strip()
    return path if path and os.path.isabs(path) and os.path.exists(path) else None


def test_codec_suite_under_asan(tmp_path):
    from kube_scheduler_simulator_tpu.native import ASAN_FLAGS, build_codec

    libasan = _toolchain_lib("libasan.so")
    # libstdc++ must be in the preload set too: ASan resolves its
    # __cxa_throw interceptor at init, and an uninstrumented Python only
    # loads libstdc++ with the first C++ extension — without it, the
    # first C++ exception out of jaxlib aborts on a null real_cxa_throw
    libstdcpp = _toolchain_lib("libstdc++.so.6")
    if libasan is None or libstdcpp is None:
        pytest.skip("no libasan/libstdc++ on this toolchain")
    so = str(tmp_path / "_annotation_codec_asan.so")
    try:
        build_codec(so, extra_flags=ASAN_FLAGS)
    except subprocess.CalledProcessError as e:
        pytest.skip(f"sanitizer build unavailable: {e.stderr!r:.200}")

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(
        os.environ,
        KSS_TPU_NATIVE_SO=so,
        LD_PRELOAD=f"{libasan} {libstdcpp}",
        # Python "leaks" interned state by design; halt hard on real UB
        ASAN_OPTIONS="detect_leaks=0:abort_on_error=1",
        UBSAN_OPTIONS="halt_on_error=1:print_stacktrace=1",
        JAX_PLATFORMS="cpu",
    )
    r = subprocess.run(
        [sys.executable, "-m", "pytest", *_SUITE, "-q", "-p",
         "no:cacheprovider"],
        cwd=repo, env=env, capture_output=True, text=True, timeout=1800)
    tail = (r.stdout + "\n" + r.stderr)[-4000:]
    assert r.returncode == 0, f"codec suite under ASan failed:\n{tail}"
