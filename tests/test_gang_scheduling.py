"""Gang scheduling: PodGroup co-scheduling with vectorized
all-or-nothing admission (docs/gang-scheduling.md).

End-to-end semantics under test: with a PodGroup of minMember=k, fewer
than k feasible members ⇒ ZERO binds (members parked in
engine.waiting_pods, then timeout-rejected with the recorder-shaped
permit-result / permit-result-timeout annotations); ≥ k feasible
members ⇒ every feasible member binds in the same wave epoch — under
BOTH pipeline_commit=True (gang-boundary streaming cuts) and False
(sequential post-pass), with the quorum computed by the vectorized
segment-reduction (framework/gang.py quorum_slice)."""

from __future__ import annotations

import json
import time

import pytest

from kube_scheduler_simulator_tpu.cluster.store import ObjectStore
from kube_scheduler_simulator_tpu.framework.engine import SchedulerEngine
from kube_scheduler_simulator_tpu.framework.gang import (
    POD_GROUP_LABEL,
    GangDirectory,
    group_key_of,
    quorum_slice,
)
from kube_scheduler_simulator_tpu.models.workloads import (
    make_gang_workload,
    make_nodes,
    make_pods,
)
from kube_scheduler_simulator_tpu.plugins.coscheduling import (
    Coscheduling,
    ensure_podgroup_resource,
)
from kube_scheduler_simulator_tpu.plugins.registry import PluginSetConfig
from kube_scheduler_simulator_tpu.store import annotations as ann


def _store(n_nodes=4, seed=1):
    store = ObjectStore()
    ensure_podgroup_resource(store)
    for n in make_nodes(n_nodes, seed=seed):
        store.create("nodes", n)
    return store


def _engine(store, pipeline=True, extra_plugins=(), chunk=512):
    plugins = {"Coscheduling": Coscheduling()}
    enabled = ["NodeResourcesFit", "Coscheduling"]
    for p in extra_plugins:
        plugins[p.name] = p
        enabled.append(p.name)
    cfg = PluginSetConfig(enabled=enabled, custom=plugins)
    return SchedulerEngine(store, plugin_config=cfg, chunk=chunk,
                           pipeline_commit=pipeline)


def _annos(store, name, namespace="default"):
    return store.get("pods", name, namespace)["metadata"].get("annotations") or {}


def _gang(store, members=3, min_member=None, timeout=30, infeasible=(),
          name_prefix="gang"):
    pgs, pods = make_gang_workload(1, members, min_member=min_member,
                                   seed=2, timeout_seconds=timeout,
                                   name_prefix=name_prefix)
    for i in infeasible:
        pods[i]["spec"]["containers"][0]["resources"]["requests"]["cpu"] = \
            "9999999m"
    for pg in pgs:
        store.create("podgroups", pg)
    for p in pods:
        store.create("pods", p)
    return [p["metadata"]["name"] for p in pods]


# --------------------------------------------------------------- admission


def test_full_gang_binds_in_one_wave_with_permit_records():
    store = _store()
    names = _gang(store, members=3)
    engine = _engine(store)
    assert engine.schedule_pending() == 3
    statuses = {}
    for nm in names:
        pod = store.get("pods", nm)
        assert pod["spec"].get("nodeName"), nm
        a = pod["metadata"]["annotations"]
        statuses[nm] = (json.loads(a[ann.PERMIT_STATUS_RESULT]),
                        json.loads(a[ann.PERMIT_TIMEOUT_RESULT]))
    # members below quorum rank record "wait" (parked, then group-wide
    # allow); the quorum-completing member records "success"
    assert statuses[names[0]] == ({"Coscheduling": "wait"},
                                  {"Coscheduling": "30s"})
    assert statuses[names[1]] == ({"Coscheduling": "wait"},
                                  {"Coscheduling": "30s"})
    assert statuses[names[2]] == ({"Coscheduling": "success"},
                                  {"Coscheduling": "0s"})
    assert engine.waiting_pods == {} and engine.gang_parked == {}


def test_below_quorum_parks_all_members_zero_binds():
    store = _store()
    names = _gang(store, members=3, infeasible=(2,))
    engine = _engine(store)
    assert engine.schedule_pending() == 0
    for p in store.list("pods")[0]:
        assert not p["spec"].get("nodeName"), p["metadata"]["name"]
    # the two feasible members rolled back to waiting; the infeasible
    # one went unschedulable through the normal path
    parked = sorted(k[1] for k in engine.gang_parked)
    assert parked == [names[0], names[1]]
    assert sorted(k[1] for k in engine.waiting_pods) == parked
    # parked pods have NO store write yet (no PodScheduled condition)
    for nm in parked:
        assert not (store.get("pods", nm).get("status") or {}).get("conditions")


def test_quorum_completes_across_waves_at_assumed_nodes():
    store = _store()
    names = _gang(store, members=3, infeasible=(2,))
    engine = _engine(store)
    assert engine.schedule_pending() == 0
    assumed = {(r.ns, r.name): r.node for r in engine.gang_parked.values()}
    # fix the infeasible member: delete + recreate with a small request
    bad = names[2]
    pod = store.get("pods", bad)
    store.delete("pods", bad, "default")
    pod["metadata"].pop("resourceVersion", None)
    pod["metadata"].pop("uid", None)
    pod["spec"]["containers"][0]["resources"]["requests"]["cpu"] = "100m"
    store.create("pods", pod)
    assert engine.schedule_pending() == 3
    for nm in names:
        assert store.get("pods", nm)["spec"].get("nodeName"), nm
    # the parked members bound exactly at their assumed nodes
    for (ns, nm), node in assumed.items():
        assert store.get("pods", nm, ns)["spec"]["nodeName"] == node
    assert engine.gang_parked == {} and engine.waiting_pods == {}


def test_timeout_rejects_whole_gang_with_annotations():
    store = _store()
    names = _gang(store, members=3, timeout=0.15, infeasible=(2,))
    engine = _engine(store)
    assert engine.schedule_pending() == 0
    assert len(engine.gang_parked) == 2
    time.sleep(0.25)
    engine._gang_maintain()  # what the next schedule_pending runs first
    assert engine.gang_parked == {} and engine.waiting_pods == {}
    from kube_scheduler_simulator_tpu.utils.tracing import TRACER

    assert TRACER.summary()["counters"].get("gang_timeout_rejects_total")
    # deterministic trigger: the earliest-parked member records
    # "timeout", the sibling records the gang rejection; both carry the
    # group timeout string and the Unschedulable condition
    a0, a1 = _annos(store, names[0]), _annos(store, names[1])
    assert json.loads(a0[ann.PERMIT_STATUS_RESULT]) == \
        {"Coscheduling": "timeout"}
    assert "timed out" in json.loads(a1[ann.PERMIT_STATUS_RESULT])["Coscheduling"]
    for a in (a0, a1):
        assert json.loads(a[ann.PERMIT_TIMEOUT_RESULT]) == \
            {"Coscheduling": "0.15s"}
    for nm in names[:2]:
        conds = {c["type"]: c for c in
                 store.get("pods", nm)["status"]["conditions"]}
        assert conds["PodScheduled"]["reason"] == "Unschedulable"


def test_prefilter_rejects_group_that_cannot_reach_quorum():
    store = _store()
    # 2 member pods exist, minMember=5: quorum is impossible
    names = _gang(store, members=2, min_member=5)
    engine = _engine(store)
    assert engine.schedule_pending() == 0
    assert engine.gang_parked == {} and engine.waiting_pods == {}
    for nm in names:
        a = _annos(store, nm)
        status = json.loads(a[ann.PRE_FILTER_STATUS_RESULT])
        assert "cannot reach quorum" in status["Coscheduling"]
        # PreFilter aborted the cycle: no filter/score results
        assert a.get(ann.FILTER_RESULT, "{}") == "{}"
        conds = {c["type"]: c for c in
                 store.get("pods", nm)["status"]["conditions"]}
        assert conds["PodScheduled"]["reason"] == "Unschedulable"


def test_prefilter_rejects_unsatisfiable_min_resources():
    store = _store(n_nodes=2)
    pgs, pods = make_gang_workload(1, 2, seed=3, timeout_seconds=30)
    pgs[0]["spec"]["minResources"] = {"cpu": "100000", "memory": "1Ti"}
    for pg in pgs:
        store.create("podgroups", pg)
    for p in pods:
        store.create("pods", p)
    engine = _engine(store)
    assert engine.schedule_pending() == 0
    a = _annos(store, pods[0]["metadata"]["name"])
    assert "minResources" in \
        json.loads(a[ann.PRE_FILTER_STATUS_RESULT])["Coscheduling"]


def test_label_without_podgroup_schedules_as_ordinary_pod():
    store = _store()
    p = make_pods(1, seed=5)[0]
    p["metadata"]["labels"][POD_GROUP_LABEL] = "no-such-group"
    store.create("pods", p)
    engine = _engine(store)
    assert engine.schedule_pending() == 1
    assert store.get("pods", p["metadata"]["name"])["spec"].get("nodeName")


def test_assumed_capacity_reserved_while_parked():
    """A parked gang's speculative assignments consume node capacity in
    later waves (the upstream assumed-pod state): an ordinary pod that
    only fits where the gang is assumed must go elsewhere/unschedulable."""
    store = ObjectStore()
    ensure_podgroup_resource(store)
    store.create("nodes", {
        "metadata": {"name": "only"},
        "status": {"allocatable": {"cpu": "2", "memory": "8Gi",
                                   "pods": "10"}},
    })
    pgs, pods = make_gang_workload(1, 3, seed=2, timeout_seconds=30,
                                   cpu_milli=900)
    pods[2]["spec"]["containers"][0]["resources"]["requests"]["cpu"] = \
        "9999999m"  # below quorum: the two feasible members park
    for pg in pgs:
        store.create("podgroups", pg)
    for p in pods:
        store.create("pods", p)
    engine = _engine(store)
    assert engine.schedule_pending() == 0
    assert len(engine.gang_parked) == 2  # 2 x 900m assumed on "only"
    filler = make_pods(1, seed=7)[0]
    filler["spec"]["containers"][0]["resources"]["requests"]["cpu"] = "500m"
    store.create("pods", filler)
    assert engine.schedule_pending() == 0  # 2000m - 1800m assumed < 500m
    assert not store.get("pods", filler["metadata"]["name"])["spec"].get(
        "nodeName")


# --------------------------------------------------------------- vectorized


def test_quorum_slice_segment_reduction_semantics():
    import numpy as np

    # groups: 0 (3 members, min 3, all feasible), 1 (2 members, min 3,
    # feasible -> parks), ungrouped pod, group 2 admitted via `already`
    gid = np.array([0, 0, 0, 1, 1, -1, 2], dtype=np.int32)
    sel = np.array([1, 2, 0, 1, 1, 3, 2], dtype=np.int32)
    already = np.array([0, 0, 2], dtype=np.int32)
    minm = np.array([3, 3, 3], dtype=np.int32)
    admit, wave, wait = quorum_slice(gid, sel, already, minm)
    assert admit.tolist() == [True, False, True]
    assert wave.tolist() == [3, 2, 1]
    # ranks 1,2 of group 0 waited; rank 3 completed quorum; group 1's
    # two feasible members waited; group 2's member had already>=min
    assert wait.tolist() == [True, True, False, True, True, False, False]


def test_quorum_pass_counter_reported():
    store = _store()
    _gang(store, members=3)
    engine = _engine(store)
    from kube_scheduler_simulator_tpu.utils.tracing import TRACER

    TRACER.reset()
    engine.schedule_pending()
    counters = TRACER.summary()["counters"]
    assert counters.get("gang_quorum_pass_seconds", 0) > 0
    assert counters.get("gang_groups_admitted_total") == 1


def test_gang_counters_rollback_and_admit():
    store = _store(n_nodes=8)
    _gang(store, members=3, name_prefix="ok")
    _gang(store, members=3, infeasible=(0,), name_prefix="parked")
    engine = _engine(store)
    from kube_scheduler_simulator_tpu.utils.tracing import TRACER

    TRACER.reset()
    assert engine.schedule_pending() == 3
    counters = TRACER.summary()["counters"]
    assert counters.get("gang_groups_admitted_total") == 1
    assert counters.get("gang_quorum_rollbacks_total") == 1


# --------------------------------------------------------------- ordering


def test_pending_order_groups_gang_members_contiguously():
    store = _store()
    plain = make_pods(4, seed=11)
    pgs, gpods = make_gang_workload(1, 2, seed=2)
    for p in gpods:
        p["spec"]["priority"] = 0  # equal footing: FIFO decides
    # interleave creations: plain0, member0, plain1, member1, plain2...
    store.create("pods", plain[0])
    store.create("podgroups", pgs[0])
    store.create("pods", gpods[0])
    store.create("pods", plain[1])
    store.create("pods", gpods[1])
    store.create("pods", plain[2])
    engine = _engine(store)
    order = [p["metadata"]["name"] for p in engine.pending_pods()]
    i0, i1 = order.index(gpods[0]["metadata"]["name"]), \
        order.index(gpods[1]["metadata"]["name"])
    # members contiguous, anchored at the first member's position
    assert i1 == i0 + 1
    assert order.index(plain[0]["metadata"]["name"]) < i0
    assert order.index(plain[1]["metadata"]["name"]) > i1


def test_pending_index_and_legacy_sort_agree_on_gangs():
    from kube_scheduler_simulator_tpu.framework.pending import (
        PendingPodIndex, gang_sorted)

    store = _store()
    pgs, gpods = make_gang_workload(2, 3, seed=2)
    for pg in pgs:
        store.create("podgroups", pg)
    plain = make_pods(5, seed=13)
    for i, p in enumerate(plain[:3]):
        store.create("pods", p)
    for p in gpods:
        store.create("pods", p)
    for p in plain[3:]:
        store.create("pods", p)
    idx = PendingPodIndex(store)
    try:
        via_index = [p["metadata"]["name"] for p in idx.pending()]
    finally:
        idx.close()
    from kube_scheduler_simulator_tpu.cluster.store import list_shared

    via_sort = [p["metadata"]["name"]
                for p in gang_sorted(list_shared(store, "pods"))]
    assert via_index == via_sort


def test_pending_index_survives_member_lowering_group_min():
    """Regression (review finding): a gang member arriving with a sort
    key BELOW its group's resident min used to crash the index's
    reposition (KeyError on the not-yet-inserted member)."""
    from kube_scheduler_simulator_tpu.framework.pending import PendingPodIndex

    store = _store()
    store.create("podgroups", {
        "metadata": {"name": "g", "namespace": "default"},
        "spec": {"minMember": 2},
    })
    idx = PendingPodIndex(store)
    try:
        store.create("pods", {
            "metadata": {"name": "m0", "namespace": "default",
                         "labels": {POD_GROUP_LABEL: "g"}},
            "spec": {"priority": 0, "containers": [{"name": "c"}]},
        })
        assert [p["metadata"]["name"] for p in idx.pending()] == ["m0"]
        # higher priority -> lower sort key than the resident min
        store.create("pods", {
            "metadata": {"name": "m1", "namespace": "default",
                         "labels": {POD_GROUP_LABEL: "g"}},
            "spec": {"priority": 10, "containers": [{"name": "c"}]},
        })
        order = [p["metadata"]["name"] for p in idx.pending()]  # no KeyError
        assert order == ["m1", "m0"]
        # and the group stays contiguous against an interleaving pod
        store.create("pods", {
            "metadata": {"name": "plain", "namespace": "default"},
            "spec": {"priority": 5, "containers": [{"name": "c"}]},
        })
        assert [p["metadata"]["name"] for p in idx.pending()] == \
            ["m1", "m0", "plain"]
    finally:
        idx.close()


def test_custom_queue_sort_routes_gangs_through_permit_machinery():
    """Regression (review finding): a custom QueueSort order breaks the
    gang-contiguity invariant, so the engine must NOT run the
    vectorized pass — gangs go through the per-pod Permit machinery
    and still admit all-or-nothing."""
    from kube_scheduler_simulator_tpu.plugins.custom import CustomPlugin

    class NameSort(CustomPlugin):
        name = "NameSort"

        def less(self, a, b):
            # interleaves gang members with everything else
            return a["metadata"]["name"][::-1] < b["metadata"]["name"][::-1]

    store = _store()
    names = _gang(store, members=3)
    engine = _engine(store, extra_plugins=(NameSort(),))
    assert not engine._gang_vectorized()
    assert engine.schedule_pending() == 3
    for nm in names:
        assert store.get("pods", nm)["spec"].get("nodeName"), nm
    assert engine.waiting_pods == {} and engine.gang_parked == {}


def test_sort_key_tolerates_non_integer_resource_versions():
    """Regression (PR 3's kubeapi _rv_int synthesizes non-integer rvs):
    _sort_key/gang_sorted must not raise ValueError on them."""
    from kube_scheduler_simulator_tpu.framework.pending import (
        _sort_key, gang_sorted)

    pods = [
        {"metadata": {"name": "a", "resourceVersion": "12abc"},
         "spec": {"priority": 0}},
        {"metadata": {"name": "b", "resourceVersion": "7"},
         "spec": {"priority": 0}},
        {"metadata": {"name": "c", "resourceVersion": "etag-xyz"},
         "spec": {"priority": 10}},
        {"metadata": {"name": "d"}, "spec": {}},
    ]
    keys = [_sort_key(p) for p in pods]  # must not raise
    assert keys[1] == (0, 7, "")
    order = [p["metadata"]["name"] for p in gang_sorted(pods)]
    # priority 10 first; non-integer rvs sort as 0 (before rv 7),
    # lexicographic among themselves
    assert order == ["c", "d", "a", "b"]


# --------------------------------------------------------------- parity


def test_gang_streaming_cuts_match_sequential_with_straddling_gangs():
    """Gangs of 5 with chunk=4 force every gang to straddle a chunk
    boundary: the streaming committer's gang-boundary cuts must produce
    the same binds, bind order and bit-identical annotations as the
    sequential post-pass.  (The full mixed-workload gate lives in
    tests/test_golden_annotations.py.)"""
    import copy
    import queue as queue_mod

    nodes = make_nodes(10, seed=7)
    pgs, gpods = make_gang_workload(3, 5, seed=9)
    for p in gpods:
        if (p["metadata"]["labels"][POD_GROUP_LABEL] == "gang-0001"
                and p["metadata"]["name"].endswith("004")):
            p["spec"]["containers"][0]["resources"]["requests"]["cpu"] = \
                "9999999m"

    def run(pipeline):
        store = ObjectStore()
        ensure_podgroup_resource(store)
        for n in nodes:
            store.create("nodes", copy.deepcopy(n))
        for pg in pgs:
            store.create("podgroups", copy.deepcopy(pg))
        for p in gpods:
            store.create("pods", copy.deepcopy(p))
        q = store.watch("pods")
        engine = _engine(store, pipeline=pipeline, chunk=4)
        bound = engine.schedule_pending()
        order, seen = [], set()
        while True:
            try:
                _rv, et, obj = q.get_nowait()
            except queue_mod.Empty:
                break
            nm = obj["metadata"]["name"]
            if (et == "MODIFIED" and (obj.get("spec") or {}).get("nodeName")
                    and nm not in seen):
                seen.add(nm)
                order.append(nm)
        store.unwatch("pods", q)
        anns = {p["metadata"]["name"]: p["metadata"].get("annotations") or {}
                for p in store.list("pods")[0]}
        return bound, order, anns, sorted(k for k in engine.gang_parked)

    bound_p, order_p, anns_p, parked_p = run(True)
    bound_s, order_s, anns_s, parked_s = run(False)
    assert bound_p == bound_s == 10  # gangs 0 and 2 admit, gang 1 parks
    assert order_p == order_s
    assert parked_p == parked_s and len(parked_p) == 4
    assert anns_p == anns_s


# --------------------------------------------------------------- fallbacks


def test_gang_through_per_pod_permit_machinery_with_other_lifecycle():
    """Another custom lifecycle plugin forces the per-pod Permit path:
    the Coscheduling plugin's own permit()/unreserve() carry the gang —
    same-wave quorum admission still binds everyone."""
    from kube_scheduler_simulator_tpu.plugins.custom import CustomPlugin

    log = []

    class Observer(CustomPlugin):
        name = "Observer"

        def reserve(self, pod, node):
            log.append(pod["metadata"]["name"])
            return None

        def unreserve(self, pod, node):
            return None

    store = _store()
    names = _gang(store, members=3)
    engine = _engine(store, extra_plugins=(Observer(),))
    assert engine._custom_lifecycle_plugins()  # per-pod machinery active
    assert engine.schedule_pending() == 3
    for nm in names:
        assert store.get("pods", nm)["spec"].get("nodeName"), nm
    assert sorted(log) == sorted(names)
    assert engine.waiting_pods == {}


def test_gang_timeout_through_per_pod_permit_machinery():
    from kube_scheduler_simulator_tpu.plugins.custom import CustomPlugin

    class Observer(CustomPlugin):
        def __init__(self):
            self.name = "Observer"

        def reserve(self, pod, node):
            return None

        def unreserve(self, pod, node):
            return None

    store = _store()
    names = _gang(store, members=3, timeout=0.2, infeasible=(2,))
    engine = _engine(store, extra_plugins=(Observer(),))
    # the per-pod path resolves waits inside the call (waiter threads)
    assert engine.schedule_pending() == 0
    assert engine.waiting_pods == {}
    for nm in names:
        assert not store.get("pods", nm)["spec"].get("nodeName")


# --------------------------------------------------------------- preemption


def test_preemption_never_drops_running_gang_below_quorum():
    from kube_scheduler_simulator_tpu.framework.preemption import Preemptor

    store = ObjectStore()
    ensure_podgroup_resource(store)
    store.create("nodes", {
        "metadata": {"name": "n1"},
        "status": {"allocatable": {"cpu": "2", "memory": "8Gi",
                                   "pods": "10"}},
    })
    store.create("podgroups", {
        "metadata": {"name": "job", "namespace": "default"},
        "spec": {"minMember": 2},
    })
    # both gang members bound on n1 (quota: 2 bound - 2 minMember = 0
    # removable), plus one plain low-priority pod
    for i in range(2):
        store.create("pods", {
            "metadata": {"name": f"job-{i}", "namespace": "default",
                         "labels": {POD_GROUP_LABEL: "job"}},
            "spec": {"priority": 0, "nodeName": "n1",
                     "containers": [{"name": "c", "resources": {
                         "requests": {"cpu": "700m", "memory": "1Gi"}}}]},
        })
    store.create("pods", {
        "metadata": {"name": "plain", "namespace": "default"},
        "spec": {"priority": 0, "nodeName": "n1",
                 "containers": [{"name": "c", "resources": {
                     "requests": {"cpu": "600m", "memory": "1Gi"}}}]},
    })
    preemptor_pod = {
        "metadata": {"name": "vip", "namespace": "default"},
        "spec": {"priority": 100,
                 "containers": [{"name": "c", "resources": {
                     "requests": {"cpu": "600m", "memory": "1Gi"}}}]},
    }
    cfg = PluginSetConfig(enabled=["NodeResourcesFit"])
    out = Preemptor(store, cfg).preempt(
        preemptor_pod, [("n1", "NodeResourcesFit")])
    # evicting "plain" frees 600m — enough for the preemptor — and the
    # gang members are protected, so they must not appear as victims
    assert out.nominated_node == "n1"
    victims = {(v["metadata"] or {}).get("name") for v in out.victims}
    assert victims == {"plain"}

    # a preemptor that could only fit by evicting a protected member
    # finds no candidate at all
    big = dict(preemptor_pod)
    big["spec"] = {"priority": 100, "containers": [{"name": "c", "resources": {
        "requests": {"cpu": "1500m", "memory": "1Gi"}}}]}
    out2 = Preemptor(store, cfg).preempt(big, [("n1", "NodeResourcesFit")])
    assert out2.nominated_node == ""


# --------------------------------------------------------------- scenario


def test_gang_scenario_e2e_from_example_file():
    """examples/gang_scenario.json: a PodGroup + 3 members created over
    scenario steps end Succeeded with all-or-nothing binds."""
    from pathlib import Path

    from kube_scheduler_simulator_tpu.scenario.runner import ScenarioService

    scenario = json.loads(
        (Path(__file__).parent.parent / "examples" / "gang_scenario.json")
        .read_text())
    store = ObjectStore()
    ensure_podgroup_resource(store)
    engine = _engine(store)
    svc = ScenarioService(store, engine)
    svc.create(scenario, run=False)
    result = svc.run("gang-demo")
    assert result["status"]["phase"] == "Succeeded", result["status"]
    bound = [p["metadata"]["name"] for p in store.list("pods")[0]
             if p["spec"].get("nodeName")]
    assert sorted(bound) == ["train-job-0", "train-job-1", "train-job-2"]
    timeline = result["status"]["scenarioResult"]["timeline"]
    scheduled = [e for evs in timeline.values() for e in evs
                 if "podScheduled" in e]
    assert len(scheduled) == 3


# --------------------------------------------------------------- soak


def test_gang_soak_staggered_arrival_no_parked_leak():
    """N groups with staggered member arrival: some complete quorum
    across calls, some time out; no parked-pod leak remains in
    engine.waiting_pods / engine.gang_parked."""
    store = _store(n_nodes=8, seed=3)
    n_groups = 6
    pgs, pods = make_gang_workload(n_groups, 3, seed=4, timeout_seconds=0.4)
    for pg in pgs:
        store.create("podgroups", pg)
    by_group: dict[str, list[dict]] = {}
    for p in pods:
        by_group.setdefault(
            p["metadata"]["labels"][POD_GROUP_LABEL], []).append(p)
    groups = sorted(by_group)
    # groups 0-3: members arrive over three rounds (complete); groups
    # 4-5: the third member is infeasible from the start (time out)
    for g in groups[4:]:
        by_group[g][2]["spec"]["containers"][0]["resources"]["requests"][
            "cpu"] = "9999999m"
        for p in by_group[g]:
            store.create("pods", p)
    engine = _engine(store)
    for round_ in range(3):
        for g in groups[:4]:
            store.create("pods", by_group[g][round_])
        engine.schedule_pending()
        if round_ < 2:
            # staggered groups can't reach quorum yet (fewer than
            # minMember pods exist): PreFilter rejects them — only the
            # infeasible-member groups 4-5 hold parks, and nothing from
            # groups 0-3 binds
            assert {k[1].rsplit("-member-", 1)[0]
                    for k in engine.gang_parked} == set(groups[4:])
            for g in groups[:4]:
                for p in by_group[g][:round_ + 1]:
                    assert not store.get(
                        "pods", p["metadata"]["name"])["spec"].get("nodeName")
    # groups 0-3 fully admitted once every member exists
    for g in groups[:4]:
        for p in by_group[g]:
            assert store.get("pods", p["metadata"]["name"])["spec"].get(
                "nodeName"), p["metadata"]["name"]
    # expire the doomed groups; their members reject (and would re-park
    # on further attempts — delete them to settle)
    time.sleep(0.5)
    engine._gang_maintain()
    assert engine.gang_parked == {} and engine.waiting_pods == {}
    for g in groups[4:]:
        for p in by_group[g][:2]:
            a = _annos(store, p["metadata"]["name"])
            assert ann.PERMIT_STATUS_RESULT in a
        for p in by_group[g]:
            store.delete("pods", p["metadata"]["name"], "default")
    assert engine.schedule_pending() == 0
    assert engine.gang_parked == {} and engine.waiting_pods == {}


def test_deleted_podgroup_releases_parked_members():
    store = _store()
    names = _gang(store, members=3, infeasible=(2,))
    engine = _engine(store)
    assert engine.schedule_pending() == 0
    assert len(engine.gang_parked) == 2
    store.delete("podgroups", "gang-0000", "default")
    # next call reconciles: the park dissolves, members reschedule as
    # ordinary pods
    bound = engine.schedule_pending()
    assert engine.gang_parked == {} and engine.waiting_pods == {}
    assert bound == 2
    for nm in names[:2]:
        assert store.get("pods", nm)["spec"].get("nodeName"), nm
