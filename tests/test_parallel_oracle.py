"""The parallel CPU baseline must reproduce the sequential oracle exactly
(same upstream semantics, node loops fanned across worker processes —
upstream's 16-goroutine Parallelizer model, SURVEY.md §6)."""

import pytest

from kube_scheduler_simulator_tpu.models.workloads import baseline_config
from kube_scheduler_simulator_tpu.reference_impl.parallel import ParallelScheduler
from kube_scheduler_simulator_tpu.reference_impl.sequential import SequentialScheduler


@pytest.mark.parametrize("idx", [1, 2, 3, 4, 5])
def test_parallel_matches_sequential(idx):
    nodes, pods, cfg = baseline_config(idx, scale=0.01, seed=7)
    seq = SequentialScheduler(nodes, pods, cfg).schedule_all()
    par = ParallelScheduler(nodes, pods, cfg, parallelism=4).schedule_all()
    assert len(seq) == len(par)
    for i, ((sa, ssel), (pa, psel)) in enumerate(zip(seq, par)):
        assert ssel == psel, f"pod {i}: selected {psel} != {ssel}"
        assert sa == pa, f"pod {i}: annotations differ"


def test_parallel_rejects_custom_plugins():
    from kube_scheduler_simulator_tpu.plugins.custom import CustomPlugin
    from kube_scheduler_simulator_tpu.plugins.registry import PluginSetConfig

    class P(CustomPlugin):
        name = "X"

    nodes, pods, _ = baseline_config(1, scale=0.01, seed=0)
    cfg = PluginSetConfig(enabled=["NodeResourcesFit", "X"], custom={"X": P()})
    with pytest.raises(ValueError):
        ParallelScheduler(nodes, pods, cfg)
