"""Multi-session serving (server/sessions.py, docs/api.md).

Covers the session subsystem end to end: manager admission/eviction,
the HTTP CRUD + per-session routing surface (bare paths aliasing the
pinned default session), hard isolation between co-resident sessions
(bit-identical annotations, no cross-session reads), the cross-session
compiled-scan registry (session B's first wave at session A's shape
skips compile), the per-session device-result budget shares (a fat
session spills only its own chunks), loop-crash observability on
/readyz, and prompt stream teardown on shutdown/eviction.
"""

from __future__ import annotations

import copy
import gc
import json
import threading
import time
import urllib.request

import pytest

from kube_scheduler_simulator_tpu.cluster.store import ApiError
from kube_scheduler_simulator_tpu.config.config import SimulatorConfiguration
from kube_scheduler_simulator_tpu.framework.replay import (
    _DEVICE_BUDGET, scan_cache_stats)
from kube_scheduler_simulator_tpu.models.workloads import make_nodes, make_pods
from kube_scheduler_simulator_tpu.plugins.registry import PluginSetConfig
from kube_scheduler_simulator_tpu.server.di import DIContainer
from kube_scheduler_simulator_tpu.server.server import SimulatorServer
from kube_scheduler_simulator_tpu.server.sessions import (
    DEFAULT_SESSION, SessionCapacity, SessionManager)
from kube_scheduler_simulator_tpu.utils.tracing import TRACER

ENABLED = ["NodeResourcesFit", "NodeResourcesBalancedAllocation",
           "NodeAffinity", "TaintToleration", "PodTopologySpread"]


def _mgr(**kw) -> SessionManager:
    kw.setdefault("cfg", SimulatorConfiguration(port=0))
    kw.setdefault("start_scheduler", False)
    kw.setdefault("idle_ttl", 0)
    return SessionManager(**kw)


def _load(sess, nodes, pods, chunk: int | None = None):
    """Same-shape workload into a session's private store, with the
    fixed plugin lineup (profiles off: shape determinism)."""
    sess.di.engine.set_profiles(None)
    sess.di.engine.plugin_config = PluginSetConfig(enabled=list(ENABLED))
    if chunk is not None:
        sess.di.engine.chunk = chunk
    for n in nodes:
        sess.di.store.create("nodes", copy.deepcopy(n))
    for p in pods:
        sess.di.store.create("pods", copy.deepcopy(p))


def _annotations(sess) -> dict[str, dict]:
    return {p["metadata"]["name"]: dict(p["metadata"].get("annotations") or {})
            for p in sess.di.store.list("pods")[0]}


def _lcounter(name: str, **labels) -> float:
    """Sum of a labeled counter's series matching the given labels."""
    snap = TRACER.snapshot()
    total = 0.0
    for s in snap["labeled_counters"].get(name, []):
        if all(s["labels"].get(k) == v for k, v in labels.items()):
            total += s["value"]
    return total


# ------------------------------------------------------------- manager


def test_manager_create_get_delete_default_pinned():
    mgr = _mgr(max_sessions=4)
    try:
        assert mgr.default.id == DEFAULT_SESSION
        a = mgr.create("alpha")
        assert mgr.get("alpha") is a
        assert {s["id"] for s in mgr.list_sessions()} == {"default", "alpha"}
        info = a.info()
        assert info["pods"] == 0 and not info["default"]
        with pytest.raises(ApiError) as ei:
            mgr.create("alpha")
        assert ei.value.status == 409
        with pytest.raises(ApiError) as ei:
            mgr.create("bad id!")
        assert ei.value.status == 400
        with pytest.raises(ApiError) as ei:
            mgr.delete(DEFAULT_SESSION)
        assert ei.value.status == 400
        mgr.delete("alpha")
        with pytest.raises(ApiError) as ei:
            mgr.get("alpha")
        assert ei.value.status == 404
        # clean teardown went through the scheduling loop's stop path
        assert a.di.scheduling_loop._stop.is_set()
    finally:
        mgr.shutdown()


def test_manager_lru_capacity_eviction():
    mgr = _mgr(max_sessions=3)  # default + 2
    try:
        a, b = mgr.create("a"), mgr.create("b")
        a.touch()  # b is now the LRU victim
        b.last_used -= 1
        c = mgr.create("c")
        ids = {s["id"] for s in mgr.list_sessions()}
        assert ids == {"default", "a", "c"}
        assert b.di.scheduling_loop._stop.is_set(), "eviction must shut down"
        assert _lcounter("sessions_evicted_total", reason="capacity") >= 1
        assert c is mgr.get("c")
    finally:
        mgr.shutdown()


def test_manager_capacity_error_when_nothing_evictable():
    mgr = _mgr(max_sessions=1)  # the pinned default fills the only slot
    try:
        with pytest.raises(SessionCapacity) as ei:
            mgr.create("x")
        assert ei.value.status == 429
    finally:
        mgr.shutdown()


def test_manager_idle_ttl_sweep():
    mgr = _mgr(max_sessions=4, idle_ttl=3600)
    try:
        stale = mgr.create("stale")
        fresh = mgr.create("fresh")
        watched = mgr.create("watched")
        stale.last_used = time.time() - 7200
        # an attached stream marks a session busy: idle by the clock,
        # but a client is plainly connected — the sweep must skip it
        watched.last_used = time.time() - 7200
        live = threading.Event()
        watched.streams.register(live)
        assert mgr.sweep_idle() == 1
        ids = {s["id"] for s in mgr.list_sessions()}
        assert ids == {"default", "fresh", "watched"}
        assert stale.di.scheduling_loop._stop.is_set()
        assert not live.is_set()
        assert _lcounter("sessions_evicted_total", reason="idle") >= 1
        assert fresh is mgr.get("fresh")
        # stream gone -> the next sweep may evict it
        watched.streams.unregister(live)
        assert mgr.sweep_idle() == 1
    finally:
        mgr.shutdown()


def test_manager_create_after_shutdown_refused():
    mgr = _mgr(max_sessions=4)
    mgr.shutdown()
    with pytest.raises(ApiError) as ei:
        mgr.create("late")
    assert ei.value.status == 400


# ----------------------------------------------------------- isolation


def test_two_sessions_bit_identical_and_isolated(monkeypatch):
    monkeypatch.delenv("KSS_TPU_EAGER_DECODE", raising=False)
    nodes = make_nodes(8, seed=3, taint_fraction=0.25)
    pods = make_pods(24, seed=4, with_affinity=True, with_tolerations=True,
                     with_spread=True)
    mgr = _mgr(max_sessions=4)
    try:
        a, b = mgr.create("iso-a"), mgr.create("iso-b")
        _load(a, nodes, pods)
        _load(b, nodes, pods)
        # concurrent waves: isolation must hold under contention
        results = {}
        t = threading.Thread(
            target=lambda: results.update(b=b.di.engine.schedule_pending()),
            daemon=True)
        t.start()
        results["a"] = a.di.engine.schedule_pending()
        t.join(timeout=120)
        assert results["a"] == results["b"] > 0
        ann_a, ann_b = _annotations(a), _annotations(b)
        assert ann_a.keys() == ann_b.keys()
        for name in ann_a:
            assert ann_a[name] == ann_b[name], f"pod {name} diverged"
        # no cross-session reads: each store holds exactly its own pods,
        # each result store answers only for its own session
        assert len(a.di.store.list("pods")[0]) == len(pods)
        assert len(b.di.store.list("pods")[0]) == len(pods)
        assert any(ann_a.values()), "wave must have annotated its pods"
        # per-session metric views are disjoint and complete
        snap_a = TRACER.snapshot(session="iso-a")
        snap_b = TRACER.snapshot(session="iso-b")
        assert snap_a["counters"]["pods_scheduled_total"] == results["a"]
        assert snap_b["counters"]["pods_scheduled_total"] == results["b"]
        assert snap_a["session"] == "iso-a"
    finally:
        mgr.shutdown()


def test_compile_cache_shared_across_sessions():
    """Session B's first wave at session A's exact shape must reuse the
    process-level compiled scan: hits only, zero new misses — counted,
    not wall-clocked."""
    nodes = make_nodes(6, seed=5)
    pods = make_pods(16, seed=6)
    mgr = _mgr(max_sessions=4)
    try:
        a = mgr.create("cc-a")
        _load(a, nodes, pods)
        a.di.engine.schedule_pending()
        after_a = scan_cache_stats()
        b = mgr.create("cc-b")
        _load(b, nodes, pods)
        b.di.engine.schedule_pending()
        after_b = scan_cache_stats()
        assert after_b["misses"] == after_a["misses"], (
            "same-shape session recompiled instead of hitting the shared "
            "registry")
        assert after_b["hits"] > after_a["hits"]
        # the flight recorder sees it per session
        assert _lcounter("scan_compile_cache_total", result="hit",
                         session="cc-b") >= 1
        assert _lcounter("scan_compile_cache_total", result="miss",
                         session="cc-b") == 0
    finally:
        mgr.shutdown()


# ------------------------------------------------- per-session budgets


def test_per_session_budget_spills_only_the_fat_session(monkeypatch):
    """Under a constrained global KSS_TPU_DEVICE_RESULT_BUDGET_MB pool,
    a session exceeding its per-session share spills ITS OWN chunks
    (device_chunks_spilled_total{session=...}) while a small co-resident
    session's device-resident chunks stay put and its warm reads stay
    D2H-free."""
    monkeypatch.delenv("KSS_TPU_EAGER_DECODE", raising=False)
    monkeypatch.delenv("KSS_TPU_HOST_RESIDENT", raising=False)
    gc.collect()  # drop other tests' dead budget entries (weakref-kept)
    monkeypatch.setenv("KSS_TPU_DEVICE_RESULT_BUDGET_MB", "1")
    mgr = _mgr(max_sessions=4)
    try:
        small = mgr.create("small")
        _load(small, make_nodes(40, seed=7), make_pods(48, seed=8),
              chunk=16)
        small.di.engine.schedule_pending()
        retained = _DEVICE_BUDGET.retained_by_session()
        assert retained.get("small", (0, 0))[0] > 0, (
            "small session should retain device-resident chunks")
        fat = mgr.create("fat")
        _load(fat, make_nodes(400, seed=9), make_pods(512, seed=10),
              chunk=64)
        fat.di.engine.schedule_pending()
        _DEVICE_BUDGET.drain()
        # the fat session overflowed ITS share and spilled — with its
        # session label on every spill
        assert _lcounter("device_chunks_spilled_total", session="fat") > 0
        assert _lcounter("device_chunks_spilled_total", session="small") == 0
        retained = _DEVICE_BUDGET.retained_by_session()
        assert retained.get("small", (0, 0))[0] > 0, (
            "the neighbor's chunks must never be evicted by the fat "
            "session's overflow")
        # fat is now within its share of the 1MB pool
        buckets = max(len(retained), 1)
        assert retained.get("fat", (0, 0))[1] <= (1 << 20) // buckets
        # warm reads on the small session stay D2H-free: one cold read
        # materializes its chunk, the re-read and a chunk-mate add zero
        # on-demand D2H
        names = [p["metadata"] for p in
                 small.di.store.list("pods", copy_objects=False)[0][:2]]
        small.di.store.get("pods", names[0]["name"], names[0].get("namespace"))
        d2h0 = TRACER.summary()["counters"].get("d2h_on_demand_bytes_total", 0)
        small.di.store.get("pods", names[0]["name"], names[0].get("namespace"))
        small.di.store.get("pods", names[1]["name"], names[1].get("namespace"))
        d2h1 = TRACER.summary()["counters"].get("d2h_on_demand_bytes_total", 0)
        assert d2h1 == d2h0, "warm chunk-mate reads must not pay D2H"
    finally:
        mgr.shutdown()


# ------------------------------------------------------------- HTTP api


@pytest.fixture()
def server():
    cfg = SimulatorConfiguration(port=0)
    di = DIContainer(cfg)
    srv = SimulatorServer(di, port=0)
    srv.start(block=False)
    yield srv
    srv.shutdown()


def req(srv, method, path, body=None):
    url = f"http://127.0.0.1:{srv.port}{path}"
    data = json.dumps(body).encode() if body is not None else None
    r = urllib.request.Request(url, data=data, method=method,
                               headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(r, timeout=10) as resp:
            raw = resp.read()
            return resp.status, json.loads(raw) if raw else None
    except urllib.error.HTTPError as e:
        raw = e.read()
        return e.code, json.loads(raw) if raw else None


def test_http_sessions_crud_and_routing(server):
    code, listing = req(server, "GET", "/api/v1/sessions")
    assert code == 200
    assert [s["id"] for s in listing["items"]] == ["default"]
    assert "compileCache" in listing and listing["maxSessions"] >= 1
    code, created = req(server, "POST", "/api/v1/sessions", {"id": "s1"})
    assert code == 201 and created["id"] == "s1"
    code, _ = req(server, "POST", "/api/v1/sessions", {"id": "s1"})
    assert code == 409
    code, minted = req(server, "POST", "/api/v1/sessions")
    assert code == 201 and minted["id"].startswith("s-")
    # session-scoped CRUD is isolated from the default session
    code, _ = req(server, "POST", "/api/v1/sessions/s1/nodes",
                  make_nodes(1, seed=11)[0])
    assert code == 201
    assert len(req(server, "GET", "/api/v1/sessions/s1/nodes")[1]["items"]) == 1
    assert req(server, "GET", "/api/v1/nodes")[1]["items"] == []
    # every aliased route resolves (config surface spot-check)
    code, cfg = req(server, "GET",
                    "/api/v1/sessions/s1/schedulerconfiguration")
    assert code == 200 and cfg["kind"] == "KubeSchedulerConfiguration"
    code, _ = req(server, "GET", "/api/v1/sessions/nosuch/pods")
    assert code == 404
    code, _ = req(server, "DELETE", "/api/v1/sessions/s1")
    assert code == 200
    assert req(server, "GET", "/api/v1/sessions/s1")[0] == 404
    assert req(server, "DELETE", "/api/v1/sessions/default")[0] == 400


def test_http_session_scheduling_e2e_and_metrics_filter(server):
    req(server, "POST", "/api/v1/sessions", {"id": "e2e"})
    for n in make_nodes(2, seed=12):
        req(server, "POST", "/api/v1/sessions/e2e/nodes", n)
    pod = {"metadata": {"name": "web", "namespace": "default"},
           "spec": {"containers": [{"name": "c", "resources": {
               "requests": {"cpu": "100m"}}}]}}
    code, _ = req(server, "POST", "/api/v1/sessions/e2e/pods", pod)
    assert code == 201
    deadline = time.time() + 20
    bound = None
    while time.time() < deadline:
        _, got = req(server, "GET", "/api/v1/sessions/e2e/pods/default/web")
        if (got.get("spec") or {}).get("nodeName"):
            bound = got
            break
        time.sleep(0.1)
    assert bound, "session-scoped scheduling loop did not bind the pod"
    # the default session never saw it
    assert req(server, "GET", "/api/v1/pods")[1]["items"] == []
    # per-session observability: both the alias and ?session= filter
    _, m = req(server, "GET", "/api/v1/sessions/e2e/metrics")
    assert m["session"] == "e2e"
    assert m["counters"].get("pods_scheduled_total", 0) >= 1
    _, m2 = req(server, "GET", "/api/v1/metrics?session=e2e")
    assert m2["counters"].get("pods_scheduled_total", 0) >= 1
    _, t = req(server, "GET", "/api/v1/sessions/e2e/trace")
    names = {e["name"] for e in t["traceEvents"] if e.get("ph") == "X"}
    assert "compile_workload" in names
    for e in t["traceEvents"]:
        if e.get("ph") == "X":
            assert e["args"].get("session") == "e2e"
    # the aggregate view still carries everything
    _, agg = req(server, "GET", "/api/v1/metrics")
    assert agg["counters"].get("pods_scheduled_total", 0) >= 1


def test_http_namespaced_update_guard(server):
    """Regression (the dead `pass` fallthrough): a namespaced PUT/DELETE
    with only a name must 400 with a pointed message, not silently act
    cluster-scoped; cluster-scoped single-name CRUD stays intact."""
    pod = {"metadata": {"name": "guarded", "namespace": "default"},
           "spec": {"containers": [{"name": "c"}]}}
    code, created = req(server, "POST", "/api/v1/pods", pod)
    assert code == 201
    code, body = req(server, "PUT", "/api/v1/pods/guarded", created)
    assert code == 400 and "namespaced" in body["message"]
    code, body = req(server, "DELETE", "/api/v1/pods/guarded")
    assert code == 400 and "/api/v1/pods/<namespace>/<name>" in body["message"]
    # the namespaced form still works...
    code, _ = req(server, "DELETE", "/api/v1/pods/default/guarded")
    assert code == 200
    # ...and cluster-scoped single-name CRUD is untouched
    node = make_nodes(1, seed=13)[0]
    code, created = req(server, "POST", "/api/v1/nodes", node)
    assert code == 201
    code, _ = req(server, "PUT", f"/api/v1/nodes/{node['metadata']['name']}",
                  created)
    assert code == 200


def test_scheduling_loop_crash_surfaces_on_readyz(server):
    """Satellite: a wave that raises must not wedge silently — the crash
    counter increments (session-labeled) and /readyz carries the last
    crash while the loop itself stays alive."""
    def boom():
        raise RuntimeError("injected wave failure")

    engine = server.di.engine
    orig = engine.schedule_pending
    engine.schedule_pending = boom
    try:
        before = _lcounter("scheduling_loop_crashes_total", session="default")
        req(server, "POST", "/api/v1/pods",
            {"metadata": {"name": "crash-me", "namespace": "default"},
             "spec": {"containers": [{"name": "c"}]}})
        deadline = time.time() + 10
        crash = None
        while time.time() < deadline:
            code, body = req(server, "GET", "/readyz")
            if body.get("lastCrash"):
                crash = (code, body)
                break
            time.sleep(0.05)
        assert crash, "/readyz never surfaced the injected crash"
        code, body = crash
        assert code == 200, "the loop survives a crash (alive => ready)"
        assert "injected wave failure" in body["lastCrash"]["error"]
        assert _lcounter("scheduling_loop_crashes_total",
                         session="default") > before
    finally:
        engine.schedule_pending = orig


# ------------------------------------------------------ stream teardown


def _open_stream(port: str | int, path: str, events: list, errors: list):
    def run():
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}{path}", timeout=30) as resp:
                while True:
                    chunk = resp.read1(65536)
                    if not chunk:
                        return
                    events.append(chunk)
        except Exception as e:  # noqa: BLE001 — surfaced by the test
            errors.append(e)

    t = threading.Thread(target=run, daemon=True)
    t.start()
    return t


def test_sse_and_listwatch_close_on_shutdown():
    """Satellite: long-lived responses must not outlive shutdown()
    sleeping on their interval — the server-level stop event ends them
    promptly."""
    srv = SimulatorServer(DIContainer(SimulatorConfiguration(port=0)), port=0)
    srv.start(block=False)
    sse_events, lw_events, errors = [], [], []
    sse = _open_stream(srv.port, "/api/v1/metrics/stream?interval=600",
                       sse_events, errors)
    lw = _open_stream(srv.port, "/api/v1/listwatchresources",
                      lw_events, errors)
    deadline = time.time() + 5
    while time.time() < deadline and not sse_events:
        time.sleep(0.05)
    assert sse_events, "SSE stream never produced its first snapshot"
    t0 = time.time()
    srv.shutdown()
    sse.join(timeout=5)
    lw.join(timeout=5)
    took = time.time() - t0
    assert not sse.is_alive(), "SSE handler outlived shutdown"
    assert not lw.is_alive(), "list-watch handler outlived shutdown"
    assert took < 5, f"stream teardown took {took:.1f}s"


def test_session_eviction_closes_its_streams():
    srv = SimulatorServer(DIContainer(SimulatorConfiguration(port=0)), port=0)
    srv.start(block=False)
    try:
        code, _ = req(srv, "POST", "/api/v1/sessions", {"id": "streamy"})
        assert code == 201
        events, errors = [], []
        t = _open_stream(
            srv.port, "/api/v1/sessions/streamy/metrics/stream?interval=600",
            events, errors)
        deadline = time.time() + 5
        while time.time() < deadline and not events:
            time.sleep(0.05)
        assert events, "session SSE stream never started"
        code, _ = req(srv, "DELETE", "/api/v1/sessions/streamy")
        assert code == 200
        t.join(timeout=5)
        assert not t.is_alive(), "evicting a session must close its streams"
    finally:
        srv.shutdown()
