"""scoringStrategy pluginConfig args: LeastAllocated weights,
MostAllocated, RequestedToCapacityRatio, balanced-allocation resources,
InterPodAffinity hardPodAffinityWeight — tensor path vs sequential
oracle parity plus hand-computed goldens."""

import json

from kube_scheduler_simulator_tpu.framework.replay import replay
from kube_scheduler_simulator_tpu.models.workloads import make_nodes, make_pods
from kube_scheduler_simulator_tpu.plugins.fitscoring import (
    FitStrategy, parse_fit_strategy, score_resource)
from kube_scheduler_simulator_tpu.plugins.registry import PluginSetConfig
from kube_scheduler_simulator_tpu.reference_impl.sequential import SequentialScheduler
from kube_scheduler_simulator_tpu.state.compile import compile_workload
from kube_scheduler_simulator_tpu.store import annotations as ann
from kube_scheduler_simulator_tpu.store.decode import decode_pod_result


def _nodes():
    return [
        {"metadata": {"name": "node-a"},
         "status": {"allocatable": {"cpu": "2", "memory": "4Gi", "pods": "10"}}},
        {"metadata": {"name": "node-b"},
         "status": {"allocatable": {"cpu": "4", "memory": "8Gi", "pods": "10"}}},
    ]


def _pod():
    return [{"kind": "Pod", "metadata": {"name": "p"}, "spec": {"containers": [
        {"name": "c", "resources": {"requests": {"cpu": "1", "memory": "2Gi"}}}]}}]


def _run(cfg):
    rr = replay(compile_workload(_nodes(), _pod(), cfg), chunk=2)
    scores = json.loads(decode_pod_result(rr, 0)[ann.SCORE_RESULT])
    return scores, rr


def _assert_parity(nodes, pods, cfg):
    seq = SequentialScheduler(nodes, pods, cfg).schedule_all()
    rr = replay(compile_workload(nodes, pods, cfg), chunk=max(len(pods), 1))
    for i, (sa, ss) in enumerate(seq):
        da = decode_pod_result(rr, i)
        assert int(rr.selected[i]) == ss
        for k in sa:
            assert da[k] == sa[k], f"pod {i} {k}"


def test_score_resource_scalar_goldens():
    least = FitStrategy("LeastAllocated", (("cpu", 1),), ())
    most = FitStrategy("MostAllocated", (("cpu", 1),), ())
    assert score_resource(least, 500, 2000) == 75
    assert score_resource(most, 500, 2000) == 25
    assert score_resource(least, 3000, 2000) == 0
    # shape: score already x10 after parsing; raw (u=0,s=0),(u=100,s=10)
    r2c = parse_fit_strategy({"scoringStrategy": {
        "type": "RequestedToCapacityRatio",
        "resources": [{"name": "cpu", "weight": 1}],
        "requestedToCapacityRatio": {"shape": [
            {"utilization": 0, "score": 0}, {"utilization": 100, "score": 10}]}}})
    assert score_resource(r2c, 500, 2000) == 25   # util 25 -> 25
    assert score_resource(r2c, 3000, 2000) == 100  # over capacity -> f(100)


def test_most_allocated_prefers_packed_node():
    cfg = PluginSetConfig(enabled=["NodeResourcesFit"], args={
        "NodeResourcesFit": {"scoringStrategy": {"type": "MostAllocated"}}})
    scores, rr = _run(cfg)
    # node-a util: cpu 50, mem 50 -> 50; node-b: 25 -> selected node-a
    assert scores["node-a"]["NodeResourcesFit"] == "50"
    assert scores["node-b"]["NodeResourcesFit"] == "25"
    assert rr.selected_node_name(0) == "node-a"


def test_least_allocated_custom_weights():
    cfg = PluginSetConfig(enabled=["NodeResourcesFit"], args={
        "NodeResourcesFit": {"scoringStrategy": {
            "type": "LeastAllocated",
            "resources": [{"name": "cpu", "weight": 3}, {"name": "memory", "weight": 1}]}}})
    scores, _ = _run(cfg)
    # node-a: (50*3 + 50*1)//4 = 50; node-b: (75*3+75)//4 = 75
    assert scores["node-a"]["NodeResourcesFit"] == "50"
    assert scores["node-b"]["NodeResourcesFit"] == "75"


def test_requested_to_capacity_ratio_tensor():
    cfg = PluginSetConfig(enabled=["NodeResourcesFit"], args={
        "NodeResourcesFit": {"scoringStrategy": {
            "type": "RequestedToCapacityRatio",
            "resources": [{"name": "cpu", "weight": 1}],
            "requestedToCapacityRatio": {"shape": [
                {"utilization": 0, "score": 10},
                {"utilization": 100, "score": 0}]}}}})
    scores, rr = _run(cfg)
    # spread-out shape (prefer empty): node-a util 50 -> 50; node-b 25 -> 75
    assert scores["node-a"]["NodeResourcesFit"] == "50"
    assert scores["node-b"]["NodeResourcesFit"] == "75"
    assert rr.selected_node_name(0) == "node-b"


def test_strategy_parity_random_workload():
    nodes = make_nodes(6, seed=90)
    pods = make_pods(10, seed=91)
    for args in (
        {"NodeResourcesFit": {"scoringStrategy": {"type": "MostAllocated"}}},
        {"NodeResourcesFit": {"scoringStrategy": {
            "type": "LeastAllocated",
            "resources": [{"name": "cpu", "weight": 2}, {"name": "memory", "weight": 5}]}}},
        {"NodeResourcesFit": {"scoringStrategy": {
            "type": "RequestedToCapacityRatio",
            "resources": [{"name": "cpu", "weight": 1}, {"name": "memory", "weight": 2}],
            "requestedToCapacityRatio": {"shape": [
                {"utilization": 0, "score": 0},
                {"utilization": 40, "score": 9},
                {"utilization": 100, "score": 3}]}}}},
    ):
        cfg = PluginSetConfig(
            enabled=["NodeResourcesFit", "NodeResourcesBalancedAllocation"],
            args=args)
        _assert_parity(nodes, pods, cfg)


def test_hard_pod_affinity_weight_parity():
    nodes = make_nodes(4, seed=92)
    pods = make_pods(8, seed=93, with_interpod=True)
    cfg = PluginSetConfig(
        enabled=["NodeResourcesFit", "InterPodAffinity"],
        args={"InterPodAffinity": {"hardPodAffinityWeight": 50}})
    _assert_parity(nodes, pods, cfg)


def _gpu_nodes():
    return [
        {"metadata": {"name": "node-gpu"},
         "status": {"allocatable": {"cpu": "2", "memory": "4Gi", "pods": "10",
                                    "nvidia.com/gpu": "4"}}},
        {"metadata": {"name": "node-plain"},
         "status": {"allocatable": {"cpu": "2", "memory": "4Gi", "pods": "10"}}},
    ]


_GPU_STRATEGY = {"NodeResourcesFit": {"scoringStrategy": {
    "type": "LeastAllocated",
    "resources": [{"name": "cpu", "weight": 1}, {"name": "memory", "weight": 1},
                  {"name": "nvidia.com/gpu", "weight": 3}]}}}


def test_unrequested_extended_resource_excluded_from_weight_sum():
    """resource_allocation.go: a scalar resource the pod does not request
    is bypassed — its weight must not enter the denominator (and a node
    without the resource must not score it at all)."""
    pods = _pod()  # requests cpu 1, memory 2Gi, no gpu
    cfg = PluginSetConfig(enabled=["NodeResourcesFit"], args=_GPU_STRATEGY)
    rr = replay(compile_workload(_gpu_nodes(), pods, cfg), chunk=1)
    scores = json.loads(decode_pod_result(rr, 0)[ann.SCORE_RESULT])
    # (50·1 + 50·1) // 2 = 50 on BOTH nodes; with the bug the gpu node
    # got (50+50+100·3)//5 = 80
    assert scores["node-gpu"]["NodeResourcesFit"] == "50"
    assert scores["node-plain"]["NodeResourcesFit"] == "50"
    _assert_parity(_gpu_nodes(), pods, cfg)


def test_requested_extended_resource_scored_where_present():
    nodes = _gpu_nodes() + [
        {"metadata": {"name": "node-gpu2"},
         "status": {"allocatable": {"cpu": "2", "memory": "4Gi", "pods": "10",
                                    "nvidia.com/gpu": "2"}}}]
    pods = [{"kind": "Pod", "metadata": {"name": "p"}, "spec": {"containers": [
        {"name": "c", "resources": {"requests": {
            "cpu": "1", "memory": "2Gi", "nvidia.com/gpu": "1"}}}]}}]
    cfg = PluginSetConfig(enabled=["NodeResourcesFit"], args=_GPU_STRATEGY)
    rr = replay(compile_workload(nodes, pods, cfg), chunk=1)
    da = decode_pod_result(rr, 0)
    scores = json.loads(da[ann.SCORE_RESULT])
    # node-gpu:  (50·1 + 50·1 + 75·3) // 5 = 65   (gpu 1 of 4 -> 75)
    # node-gpu2: (50·1 + 50·1 + 50·3) // 5 = 50   (gpu 1 of 2 -> 50)
    assert scores["node-gpu"]["NodeResourcesFit"] == "65"
    assert scores["node-gpu2"]["NodeResourcesFit"] == "50"
    fr = json.loads(da[ann.FILTER_RESULT])
    assert "Insufficient nvidia.com/gpu" in fr["node-plain"]["NodeResourcesFit"]
    _assert_parity(nodes, pods, cfg)


def test_rtcr_rounds_to_nearest_and_drops_zero_scores():
    """requestedToCapacityRatioScorer: int64(math.Round(score/weightSum))
    — not truncation — and a resourceScore of 0 excludes that resource's
    weight from the sum (unlike Least/MostAllocated)."""
    nodes = [
        {"metadata": {"name": "node-a"},
         "status": {"allocatable": {"cpu": "2", "memory": "20Gi", "pods": "10"}}},
        {"metadata": {"name": "node-b"},
         "status": {"allocatable": {"cpu": "64", "memory": "2Gi", "pods": "10"}}},
    ]
    pods = [{"kind": "Pod", "metadata": {"name": "p"}, "spec": {"containers": [
        {"name": "c", "resources": {"requests": {"cpu": "1", "memory": "1Gi"}}}]}}]
    cfg = PluginSetConfig(enabled=["NodeResourcesFit"], args={
        "NodeResourcesFit": {"scoringStrategy": {
            "type": "RequestedToCapacityRatio",
            "resources": [{"name": "cpu", "weight": 1}, {"name": "memory", "weight": 1}],
            "requestedToCapacityRatio": {"shape": [
                {"utilization": 0, "score": 0}, {"utilization": 100, "score": 10}]}}}})
    rr = replay(compile_workload(nodes, pods, cfg), chunk=1)
    scores = json.loads(decode_pod_result(rr, 0)[ann.SCORE_RESULT])
    # node-a: cpu util 50 -> 50, mem util 1Gi/20Gi = 5 -> 5;
    #   round((50+5)/2) = round(27.5) = 28 (truncation would give 27)
    assert scores["node-a"]["NodeResourcesFit"] == "28"
    # node-b: cpu util 1000m*100//64000 = 1 -> 1; mem util 50 -> 50;
    #   round(51/2) = 26 — but drop-zero matters with scores of 0:
    assert scores["node-b"]["NodeResourcesFit"] == "26"
    _assert_parity(nodes, pods, cfg)

    # zero-score drop: cpu resourceScore 0 must not dilute the mean
    nodes2 = [
        {"metadata": {"name": "node-a"},
         "status": {"allocatable": {"cpu": "200", "memory": "2Gi", "pods": "10"}}},
        {"metadata": {"name": "node-b"},
         "status": {"allocatable": {"cpu": "200", "memory": "4Gi", "pods": "10"}}},
    ]
    pods2 = [{"kind": "Pod", "metadata": {"name": "p"}, "spec": {"containers": [
        {"name": "c", "resources": {"requests": {"cpu": "1", "memory": "1Gi"}}}]}}]
    rr2 = replay(compile_workload(nodes2, pods2, cfg), chunk=1)
    scores2 = json.loads(decode_pod_result(rr2, 0)[ann.SCORE_RESULT])
    # cpu util 1000*100//200000 = 0 -> score 0 -> dropped;
    # node-a mem util 50 -> 50/1 = 50 (diluted would be 25)
    assert scores2["node-a"]["NodeResourcesFit"] == "50"
    assert scores2["node-b"]["NodeResourcesFit"] == "25"
    _assert_parity(nodes2, pods2, cfg)


def test_rtcr_uses_raw_requests_not_nonzero_defaults():
    """RTCR is built with useRequested=true upstream: the raw Requested
    accumulators and raw pod requests — no 100m/200Mi non-zero defaults."""
    nodes = [
        {"metadata": {"name": "node-a"},
         "status": {"allocatable": {"cpu": "2", "memory": "4Gi", "pods": "10"}}},
        {"metadata": {"name": "node-b"},
         "status": {"allocatable": {"cpu": "2", "memory": "8Gi", "pods": "10"}}},
    ]
    # no cpu request at all: raw cpu requested stays 0 -> util 0
    pods = [{"kind": "Pod", "metadata": {"name": "p"}, "spec": {"containers": [
        {"name": "c", "resources": {"requests": {"memory": "1Gi"}}}]}}]
    cfg = PluginSetConfig(enabled=["NodeResourcesFit"], args={
        "NodeResourcesFit": {"scoringStrategy": {
            "type": "RequestedToCapacityRatio",
            "resources": [{"name": "cpu", "weight": 1}, {"name": "memory", "weight": 1}],
            "requestedToCapacityRatio": {"shape": [
                {"utilization": 0, "score": 10}, {"utilization": 100, "score": 0}]}}}})
    rr = replay(compile_workload(nodes, pods, cfg), chunk=1)
    scores = json.loads(decode_pod_result(rr, 0)[ann.SCORE_RESULT])
    # node-a: cpu raw util 0 -> 100 (nonzero default 100m would give 95);
    #   mem util 25 -> 75; round((100+75)/2) = 88
    assert scores["node-a"]["NodeResourcesFit"] == "88"
    # node-b: mem util 12 -> 88; round((100+88)/2) = 94
    assert scores["node-b"]["NodeResourcesFit"] == "94"
    _assert_parity(nodes, pods, cfg)


def test_balanced_allocation_top_level_resources_wire_format():
    """NodeResourcesBalancedAllocationArgs carries `resources` at the top
    level (no scoringStrategy wrapper) — reference
    plugins_test.go:922-929; previously these were silently ignored."""
    from kube_scheduler_simulator_tpu.plugins.fitscoring import parse_balanced_resources

    assert parse_balanced_resources({"resources": [
        {"name": "cpu", "weight": 1}, {"name": "nvidia.com/gpu", "weight": 1},
    ]}) == ("cpu", "nvidia.com/gpu")
    # fallback shape still honored, default when absent
    assert parse_balanced_resources({"scoringStrategy": {"resources": [
        {"name": "cpu"}]}}) == ("cpu",)
    assert parse_balanced_resources(None) == ("cpu", "memory")

    nodes = _gpu_nodes() + [
        {"metadata": {"name": "node-gpu2"},
         "status": {"allocatable": {"cpu": "2", "memory": "4Gi", "pods": "10",
                                    "nvidia.com/gpu": "2"}}}]
    pods = [{"kind": "Pod", "metadata": {"name": "p"}, "spec": {"containers": [
        {"name": "c", "resources": {"requests": {
            "cpu": "1", "memory": "2Gi", "nvidia.com/gpu": "2"}}}]}}]
    cfg = PluginSetConfig(
        enabled=["NodeResourcesFit", "NodeResourcesBalancedAllocation"],
        args={"NodeResourcesBalancedAllocation": {"resources": [
            {"name": "cpu", "weight": 1}, {"name": "memory", "weight": 1},
            {"name": "nvidia.com/gpu", "weight": 1}]}})
    rr = replay(compile_workload(nodes, pods, cfg), chunk=1)
    scores = json.loads(decode_pod_result(rr, 0)[ann.SCORE_RESULT])
    # fractions on node-gpu: cpu 0.5, mem 0.5, gpu 0.5 -> std 0 -> 100;
    # node-gpu2: gpu fraction 1.0 -> population std of (.5,.5,1) -> 76
    assert scores["node-gpu"]["NodeResourcesBalancedAllocation"] == "100"
    assert scores["node-gpu2"]["NodeResourcesBalancedAllocation"] == "76"
    _assert_parity(nodes, pods, cfg)


def test_args_flow_from_scheduler_config():
    from kube_scheduler_simulator_tpu.scheduler.convert import parse_plugin_set

    cfg = parse_plugin_set({"profiles": [{
        "plugins": {"multiPoint": {"enabled": [{"name": "NodeResourcesFit"}],
                                   "disabled": [{"name": "*"}]}},
        "pluginConfig": [
            {"name": "NodeResourcesFitWrapped",
             "args": {"scoringStrategy": {"type": "MostAllocated"}}}],
    }]})
    assert cfg.args["NodeResourcesFit"]["scoringStrategy"]["type"] == "MostAllocated"


def test_added_affinity_filters_and_scores():
    """NodeAffinityArgs.addedAffinity: ANDed required selector + added
    preferred terms apply to EVERY pod (pods with no affinity of their
    own included)."""
    nodes = [
        {"metadata": {"name": "gold", "labels": {"tier": "gold"}},
         "status": {"allocatable": {"cpu": "8", "memory": "32Gi", "pods": "10"}}},
        {"metadata": {"name": "plain", "labels": {"tier": "plain"}},
         "status": {"allocatable": {"cpu": "8", "memory": "32Gi", "pods": "10"}}},
    ]
    pods = [{"kind": "Pod", "metadata": {"name": "p"}, "spec": {"containers": [
        {"name": "c", "resources": {"requests": {"cpu": "1", "memory": "1Gi"}}}]}}]
    cfg = PluginSetConfig(
        enabled=["NodeResourcesFit", "NodeAffinity"],
        args={"NodeAffinity": {"addedAffinity": {
            "requiredDuringSchedulingIgnoredDuringExecution": {
                "nodeSelectorTerms": [{"matchExpressions": [
                    {"key": "tier", "operator": "In", "values": ["gold", "plain"]}]}]},
            "preferredDuringSchedulingIgnoredDuringExecution": [
                {"weight": 30, "preference": {"matchExpressions": [
                    {"key": "tier", "operator": "In", "values": ["gold"]}]}}],
        }}})
    _assert_parity(nodes, pods, cfg)
    rr = replay(compile_workload(nodes, pods, cfg), chunk=1)
    assert rr.selected_node_name(0) == "gold"
    da = decode_pod_result(rr, 0)
    scores = json.loads(da[ann.SCORE_RESULT])
    assert scores["gold"]["NodeAffinity"] == "30"
    assert scores["plain"]["NodeAffinity"] == "0"

    # required part actually rejects
    cfg2 = PluginSetConfig(
        enabled=["NodeResourcesFit", "NodeAffinity"],
        args={"NodeAffinity": {"addedAffinity": {
            "requiredDuringSchedulingIgnoredDuringExecution": {
                "nodeSelectorTerms": [{"matchExpressions": [
                    {"key": "tier", "operator": "In", "values": ["gold"]}]}]},
        }}})
    _assert_parity(nodes, pods, cfg2)
    rr2 = replay(compile_workload(nodes, pods, cfg2), chunk=1)
    fr = json.loads(decode_pod_result(rr2, 0)[ann.FILTER_RESULT])
    assert fr["plain"]["NodeAffinity"] == "node(s) didn't match Pod's node affinity/selector"
    assert rr2.selected_node_name(0) == "gold"
