"""scoringStrategy pluginConfig args: LeastAllocated weights,
MostAllocated, RequestedToCapacityRatio, balanced-allocation resources,
InterPodAffinity hardPodAffinityWeight — tensor path vs sequential
oracle parity plus hand-computed goldens."""

import json

from kube_scheduler_simulator_tpu.framework.replay import replay
from kube_scheduler_simulator_tpu.models.workloads import make_nodes, make_pods
from kube_scheduler_simulator_tpu.plugins.fitscoring import (
    FitStrategy, parse_fit_strategy, score_resource)
from kube_scheduler_simulator_tpu.plugins.registry import PluginSetConfig
from kube_scheduler_simulator_tpu.reference_impl.sequential import SequentialScheduler
from kube_scheduler_simulator_tpu.state.compile import compile_workload
from kube_scheduler_simulator_tpu.store import annotations as ann
from kube_scheduler_simulator_tpu.store.decode import decode_pod_result


def _nodes():
    return [
        {"metadata": {"name": "node-a"},
         "status": {"allocatable": {"cpu": "2", "memory": "4Gi", "pods": "10"}}},
        {"metadata": {"name": "node-b"},
         "status": {"allocatable": {"cpu": "4", "memory": "8Gi", "pods": "10"}}},
    ]


def _pod():
    return [{"kind": "Pod", "metadata": {"name": "p"}, "spec": {"containers": [
        {"name": "c", "resources": {"requests": {"cpu": "1", "memory": "2Gi"}}}]}}]


def _run(cfg):
    rr = replay(compile_workload(_nodes(), _pod(), cfg), chunk=2)
    scores = json.loads(decode_pod_result(rr, 0)[ann.SCORE_RESULT])
    return scores, rr


def _assert_parity(nodes, pods, cfg):
    seq = SequentialScheduler(nodes, pods, cfg).schedule_all()
    rr = replay(compile_workload(nodes, pods, cfg), chunk=max(len(pods), 1))
    for i, (sa, ss) in enumerate(seq):
        da = decode_pod_result(rr, i)
        assert int(rr.selected[i]) == ss
        for k in sa:
            assert da[k] == sa[k], f"pod {i} {k}"


def test_score_resource_scalar_goldens():
    least = FitStrategy("LeastAllocated", (("cpu", 1),), ())
    most = FitStrategy("MostAllocated", (("cpu", 1),), ())
    assert score_resource(least, 500, 2000) == 75
    assert score_resource(most, 500, 2000) == 25
    assert score_resource(least, 3000, 2000) == 0
    # shape: score already x10 after parsing; raw (u=0,s=0),(u=100,s=10)
    r2c = parse_fit_strategy({"scoringStrategy": {
        "type": "RequestedToCapacityRatio",
        "resources": [{"name": "cpu", "weight": 1}],
        "requestedToCapacityRatio": {"shape": [
            {"utilization": 0, "score": 0}, {"utilization": 100, "score": 10}]}}})
    assert score_resource(r2c, 500, 2000) == 25   # util 25 -> 25
    assert score_resource(r2c, 3000, 2000) == 100  # over capacity -> f(100)


def test_most_allocated_prefers_packed_node():
    cfg = PluginSetConfig(enabled=["NodeResourcesFit"], args={
        "NodeResourcesFit": {"scoringStrategy": {"type": "MostAllocated"}}})
    scores, rr = _run(cfg)
    # node-a util: cpu 50, mem 50 -> 50; node-b: 25 -> selected node-a
    assert scores["node-a"]["NodeResourcesFit"] == "50"
    assert scores["node-b"]["NodeResourcesFit"] == "25"
    assert rr.selected_node_name(0) == "node-a"


def test_least_allocated_custom_weights():
    cfg = PluginSetConfig(enabled=["NodeResourcesFit"], args={
        "NodeResourcesFit": {"scoringStrategy": {
            "type": "LeastAllocated",
            "resources": [{"name": "cpu", "weight": 3}, {"name": "memory", "weight": 1}]}}})
    scores, _ = _run(cfg)
    # node-a: (50*3 + 50*1)//4 = 50; node-b: (75*3+75)//4 = 75
    assert scores["node-a"]["NodeResourcesFit"] == "50"
    assert scores["node-b"]["NodeResourcesFit"] == "75"


def test_requested_to_capacity_ratio_tensor():
    cfg = PluginSetConfig(enabled=["NodeResourcesFit"], args={
        "NodeResourcesFit": {"scoringStrategy": {
            "type": "RequestedToCapacityRatio",
            "resources": [{"name": "cpu", "weight": 1}],
            "requestedToCapacityRatio": {"shape": [
                {"utilization": 0, "score": 10},
                {"utilization": 100, "score": 0}]}}}})
    scores, rr = _run(cfg)
    # spread-out shape (prefer empty): node-a util 50 -> 50; node-b 25 -> 75
    assert scores["node-a"]["NodeResourcesFit"] == "50"
    assert scores["node-b"]["NodeResourcesFit"] == "75"
    assert rr.selected_node_name(0) == "node-b"


def test_strategy_parity_random_workload():
    nodes = make_nodes(6, seed=90)
    pods = make_pods(10, seed=91)
    for args in (
        {"NodeResourcesFit": {"scoringStrategy": {"type": "MostAllocated"}}},
        {"NodeResourcesFit": {"scoringStrategy": {
            "type": "LeastAllocated",
            "resources": [{"name": "cpu", "weight": 2}, {"name": "memory", "weight": 5}]}}},
        {"NodeResourcesFit": {"scoringStrategy": {
            "type": "RequestedToCapacityRatio",
            "resources": [{"name": "cpu", "weight": 1}, {"name": "memory", "weight": 2}],
            "requestedToCapacityRatio": {"shape": [
                {"utilization": 0, "score": 0},
                {"utilization": 40, "score": 9},
                {"utilization": 100, "score": 3}]}}}},
    ):
        cfg = PluginSetConfig(
            enabled=["NodeResourcesFit", "NodeResourcesBalancedAllocation"],
            args=args)
        _assert_parity(nodes, pods, cfg)


def test_hard_pod_affinity_weight_parity():
    nodes = make_nodes(4, seed=92)
    pods = make_pods(8, seed=93, with_interpod=True)
    cfg = PluginSetConfig(
        enabled=["NodeResourcesFit", "InterPodAffinity"],
        args={"InterPodAffinity": {"hardPodAffinityWeight": 50}})
    _assert_parity(nodes, pods, cfg)


def test_args_flow_from_scheduler_config():
    from kube_scheduler_simulator_tpu.scheduler.convert import parse_plugin_set

    cfg = parse_plugin_set({"profiles": [{
        "plugins": {"multiPoint": {"enabled": [{"name": "NodeResourcesFit"}],
                                   "disabled": [{"name": "*"}]}},
        "pluginConfig": [
            {"name": "NodeResourcesFitWrapped",
             "args": {"scoringStrategy": {"type": "MostAllocated"}}}],
    }]})
    assert cfg.args["NodeResourcesFit"]["scoringStrategy"]["type"] == "MostAllocated"


def test_added_affinity_filters_and_scores():
    """NodeAffinityArgs.addedAffinity: ANDed required selector + added
    preferred terms apply to EVERY pod (pods with no affinity of their
    own included)."""
    nodes = [
        {"metadata": {"name": "gold", "labels": {"tier": "gold"}},
         "status": {"allocatable": {"cpu": "8", "memory": "32Gi", "pods": "10"}}},
        {"metadata": {"name": "plain", "labels": {"tier": "plain"}},
         "status": {"allocatable": {"cpu": "8", "memory": "32Gi", "pods": "10"}}},
    ]
    pods = [{"kind": "Pod", "metadata": {"name": "p"}, "spec": {"containers": [
        {"name": "c", "resources": {"requests": {"cpu": "1", "memory": "1Gi"}}}]}}]
    cfg = PluginSetConfig(
        enabled=["NodeResourcesFit", "NodeAffinity"],
        args={"NodeAffinity": {"addedAffinity": {
            "requiredDuringSchedulingIgnoredDuringExecution": {
                "nodeSelectorTerms": [{"matchExpressions": [
                    {"key": "tier", "operator": "In", "values": ["gold", "plain"]}]}]},
            "preferredDuringSchedulingIgnoredDuringExecution": [
                {"weight": 30, "preference": {"matchExpressions": [
                    {"key": "tier", "operator": "In", "values": ["gold"]}]}}],
        }}})
    _assert_parity(nodes, pods, cfg)
    rr = replay(compile_workload(nodes, pods, cfg), chunk=1)
    assert rr.selected_node_name(0) == "gold"
    da = decode_pod_result(rr, 0)
    scores = json.loads(da[ann.SCORE_RESULT])
    assert scores["gold"]["NodeAffinity"] == "30"
    assert scores["plain"]["NodeAffinity"] == "0"

    # required part actually rejects
    cfg2 = PluginSetConfig(
        enabled=["NodeResourcesFit", "NodeAffinity"],
        args={"NodeAffinity": {"addedAffinity": {
            "requiredDuringSchedulingIgnoredDuringExecution": {
                "nodeSelectorTerms": [{"matchExpressions": [
                    {"key": "tier", "operator": "In", "values": ["gold"]}]}]},
        }}})
    _assert_parity(nodes, pods, cfg2)
    rr2 = replay(compile_workload(nodes, pods, cfg2), chunk=1)
    fr = json.loads(decode_pod_result(rr2, 0)[ann.FILTER_RESULT])
    assert fr["plain"]["NodeAffinity"] == "node(s) didn't match Pod's node affinity/selector"
    assert rr2.selected_node_name(0) == "gold"
