"""HTTP API tests — the real server on an ephemeral port, driven over HTTP.

Route-parity checks against reference simulator/server/server.go:42-61.
"""

import json
import threading
import time
import urllib.request

import pytest

from kube_scheduler_simulator_tpu.config.config import SimulatorConfiguration
from kube_scheduler_simulator_tpu.models.workloads import make_nodes
from kube_scheduler_simulator_tpu.server.di import DIContainer
from kube_scheduler_simulator_tpu.server.server import SimulatorServer
from kube_scheduler_simulator_tpu.store import annotations as ann


@pytest.fixture()
def server():
    cfg = SimulatorConfiguration(port=0)
    di = DIContainer(cfg)
    srv = SimulatorServer(di, port=0)
    srv.start(block=False)
    yield srv
    srv.shutdown()


def req(srv, method, path, body=None):
    url = f"http://127.0.0.1:{srv.port}{path}"
    data = json.dumps(body).encode() if body is not None else None
    r = urllib.request.Request(url, data=data, method=method,
                               headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(r, timeout=10) as resp:
            raw = resp.read()
            return resp.status, json.loads(raw) if raw else None
    except urllib.error.HTTPError as e:
        raw = e.read()
        return e.code, json.loads(raw) if raw else None


def test_scheduler_configuration_roundtrip(server):
    code, cfg = req(server, "GET", "/api/v1/schedulerconfiguration")
    assert code == 200 and cfg["kind"] == "KubeSchedulerConfiguration"
    code, _ = req(server, "POST", "/api/v1/schedulerconfiguration", {
        "profiles": [{"schedulerName": "default-scheduler", "plugins": {
            "multiPoint": {"enabled": [{"name": "NodeResourcesFit", "weight": 9}],
                           "disabled": [{"name": "*"}]}}}],
    })
    assert code == 202
    code, cfg = req(server, "GET", "/api/v1/schedulerconfiguration")
    assert cfg["profiles"][0]["plugins"]["multiPoint"]["enabled"][0]["weight"] == 9


def test_resource_crud_and_scheduling_e2e(server):
    for n in make_nodes(3, seed=2):
        code, _ = req(server, "POST", "/api/v1/nodes", n)
        assert code == 201
    pod = {"metadata": {"name": "web", "namespace": "default"},
           "spec": {"containers": [{"name": "c", "resources": {"requests": {"cpu": "500m"}}}]}}
    code, created = req(server, "POST", "/api/v1/pods", pod)
    assert code == 201 and created["metadata"]["uid"]
    # the scheduling loop should bind + annotate it
    deadline = time.time() + 10
    bound = None
    while time.time() < deadline:
        _, got = req(server, "GET", "/api/v1/pods/default/web")
        if (got.get("spec") or {}).get("nodeName"):
            bound = got
            break
        time.sleep(0.1)
    assert bound, "pod was not scheduled by the scheduling loop"
    annos = bound["metadata"]["annotations"]
    assert annos[ann.SELECTED_NODE] == bound["spec"]["nodeName"]
    assert ann.FINAL_SCORE_RESULT in annos
    assert bound["status"]["phase"] == "Running"


def test_export_import_reset(server):
    req(server, "POST", "/api/v1/nodes", make_nodes(1, seed=3)[0])
    code, snap = req(server, "GET", "/api/v1/export")
    assert code == 200 and len(snap["nodes"]) == 1
    code, _ = req(server, "PUT", "/api/v1/reset")
    assert code == 202
    _, after = req(server, "GET", "/api/v1/export")
    assert after["nodes"] == []
    code, _ = req(server, "POST", "/api/v1/import", snap)
    assert code == 200
    _, back = req(server, "GET", "/api/v1/export")
    assert len(back["nodes"]) == 1


def test_listwatch_stream(server):
    req(server, "POST", "/api/v1/nodes", make_nodes(1, seed=4)[0])
    url = f"http://127.0.0.1:{server.port}/api/v1/listwatchresources"
    events = []

    def read_stream():
        with urllib.request.urlopen(url, timeout=5) as resp:
            dec = json.JSONDecoder()
            buf = ""
            while len(events) < 2:
                chunk = resp.read1(65536).decode()
                if not chunk:
                    break
                buf += chunk
                while buf:
                    try:
                        obj, end = dec.raw_decode(buf)
                    except json.JSONDecodeError:
                        break
                    events.append(obj)
                    buf = buf[end:]

    t = threading.Thread(target=read_stream, daemon=True)
    t.start()
    time.sleep(0.3)
    req(server, "POST", "/api/v1/nodes", {"metadata": {"name": "late-node"},
                                          "status": {"allocatable": {"cpu": "1"}}})
    t.join(timeout=5)
    kinds = [(e["kind"], e["eventType"]) for e in events]
    assert ("Node", "ADDED") in kinds
    names = [e["obj"]["metadata"]["name"] for e in events if e["kind"] == "Node"]
    assert "late-node" in names or len(names) >= 1


def test_extender_route_without_extenders(server):
    code, body = req(server, "POST", "/api/v1/extender/filter/0", {"Nodes": None})
    assert code == 400


def test_unknown_route_404(server):
    code, _ = req(server, "GET", "/api/v1/nosuch")
    assert code == 404


def test_web_ui_served(server):
    url = f"http://127.0.0.1:{server.port}/"
    with urllib.request.urlopen(url, timeout=10) as resp:
        assert resp.status == 200
        assert resp.headers["Content-Type"].startswith("text/html")
        body = resp.read().decode()
    # the SPA loads its modules (api/store/components split like the
    # reference's web/ layout); fetch them and check load-bearing hooks
    for asset in ("yaml.js", "api.js", "store.js", "components.js", "app.js"):
        assert f"/web/{asset}" in body, asset
        with urllib.request.urlopen(f"http://127.0.0.1:{server.port}/web/{asset}",
                                    timeout=10) as resp:
            assert resp.status == 200
            body += resp.read().decode()
    for needle in ("listwatchresources", "finalscore-result", "schedulerconfiguration",
                   "watchLoop", "api/v1/scenarios"):
        assert needle in body, needle


def test_listwatch_resume_skips_old_events(server):
    """The reconnect contract (reference handler/watcher.go takes
    *LastResourceVersion form values): a client resuming with the RV it
    already saw gets no replayed ADDED for old objects, only newer
    events."""
    _, created = req(server, "POST", "/api/v1/nodes",
                     {"metadata": {"name": "old-node"},
                      "status": {"allocatable": {"cpu": "1"}}})
    rv = created["metadata"]["resourceVersion"]
    url = (f"http://127.0.0.1:{server.port}/api/v1/listwatchresources"
           f"?nodesLastResourceVersion={rv}")
    events = []

    def read_stream():
        with urllib.request.urlopen(url, timeout=5) as resp:
            dec = json.JSONDecoder()
            buf = ""
            while not any(e["kind"] == "Node" for e in events):
                chunk = resp.read1(65536).decode()
                if not chunk:
                    break
                buf += chunk
                while buf:
                    try:
                        obj, end = dec.raw_decode(buf)
                    except json.JSONDecodeError:
                        break
                    events.append(obj)
                    buf = buf[end:]

    t = threading.Thread(target=read_stream, daemon=True)
    t.start()
    time.sleep(0.3)
    req(server, "POST", "/api/v1/nodes", {"metadata": {"name": "new-node"},
                                          "status": {"allocatable": {"cpu": "1"}}})
    t.join(timeout=5)
    node_names = [e["obj"]["metadata"]["name"] for e in events if e["kind"] == "Node"]
    assert "new-node" in node_names
    assert "old-node" not in node_names  # resumed past it
