"""Multiple scheduler profiles: pods routed by spec.schedulerName, each
profile with its own plugin set/args (upstream builds one framework per
profile, reference simulator/scheduler/scheduler.go:141-173; round-1
VERDICT missing #5: only profiles[0] was parsed)."""

import copy

from kube_scheduler_simulator_tpu.cluster.store import ObjectStore
from kube_scheduler_simulator_tpu.framework.engine import SchedulerEngine
from kube_scheduler_simulator_tpu.scheduler.convert import (
    default_scheduler_config, parse_profiles)
from kube_scheduler_simulator_tpu.scheduler.service import SchedulerService


def _nodes():
    # node-big has more headroom; MostAllocated prefers node-small
    return [
        {"metadata": {"name": "node-big"},
         "status": {"allocatable": {"cpu": "16", "memory": "64Gi", "pods": "100"}}},
        {"metadata": {"name": "node-small"},
         "status": {"allocatable": {"cpu": "2", "memory": "8Gi", "pods": "100"}}},
    ]


def _pod(name, scheduler_name=None):
    spec = {"containers": [{"name": "c", "resources": {
        "requests": {"cpu": "1", "memory": "2Gi"}}}]}
    if scheduler_name:
        spec["schedulerName"] = scheduler_name
    return {"kind": "Pod", "metadata": {"name": name}, "spec": spec}


def _two_profile_config():
    cfg = default_scheduler_config()
    spread = copy.deepcopy(cfg["profiles"][0])
    binpack = copy.deepcopy(cfg["profiles"][0])
    spread["schedulerName"] = "default-scheduler"
    binpack["schedulerName"] = "bin-packing"
    binpack["pluginConfig"] = [{
        "name": "NodeResourcesFit",
        "args": {"scoringStrategy": {"type": "MostAllocated"}}}]
    cfg["profiles"] = [spread, binpack]
    return cfg


def _service_with(cfg, nodes):
    store = ObjectStore()
    for n in nodes:
        store.create("nodes", n)
    engine = SchedulerEngine(store)
    svc = SchedulerService(engine, initial_config=cfg)
    return svc, engine, store


def test_parse_profiles_reads_every_profile():
    profs = parse_profiles(_two_profile_config())
    assert list(profs) == ["default-scheduler", "bin-packing"]
    # the default profile carries the scheme-defaulted args (LeastAllocated)
    assert (profs["default-scheduler"].args["NodeResourcesFit"]
            ["scoringStrategy"]["type"] == "LeastAllocated")
    assert (profs["bin-packing"].args["NodeResourcesFit"]
            ["scoringStrategy"]["type"] == "MostAllocated")


def test_same_pod_schedules_differently_per_profile():
    cfg = _two_profile_config()
    svc, engine, store = _service_with(cfg, _nodes())
    store.create("pods", _pod("p-default"))                 # default profile
    store.create("pods", _pod("p-packed", "bin-packing"))   # second profile
    assert engine.schedule_pending() == 2
    # LeastAllocated prefers the big node; MostAllocated the small one
    assert store.get("pods", "p-default")["spec"]["nodeName"] == "node-big"
    assert store.get("pods", "p-packed")["spec"]["nodeName"] == "node-small"


def test_unknown_scheduler_name_is_left_alone():
    cfg = _two_profile_config()
    svc, engine, store = _service_with(cfg, _nodes())
    store.create("pods", _pod("p-foreign", "someone-elses-scheduler"))
    assert engine.schedule_pending() == 0
    pod = store.get("pods", "p-foreign")
    assert not pod["spec"].get("nodeName")
    # untouched: no Unschedulable condition — no scheduler owns it
    conds = (pod.get("status") or {}).get("conditions") or []
    assert not any(c.get("type") == "PodScheduled" for c in conds)


def test_unset_scheduler_name_falls_back_to_first_profile():
    cfg = _two_profile_config()
    cfg["profiles"][0]["schedulerName"] = "primary"  # no default-scheduler
    svc, engine, store = _service_with(cfg, _nodes())
    store.create("pods", _pod("p-unset"))
    assert engine.schedule_pending() == 1
    assert store.get("pods", "p-unset")["spec"].get("nodeName")


def test_global_priority_order_across_profiles():
    """Upstream pops one shared activeQ: a high-priority pod of profile B
    must win contended capacity over a low-priority pod of profile A even
    though A comes first in the profile list."""
    nodes = [{"metadata": {"name": "only"},
              "status": {"allocatable": {"cpu": "1", "memory": "2Gi", "pods": "10"}}}]
    cfg = _two_profile_config()
    svc, engine, store = _service_with(cfg, nodes)
    lo = _pod("p-low")  # default profile (first), priority 0
    hi = _pod("p-high", "bin-packing")
    hi["spec"]["priority"] = 1000
    store.create("pods", lo)
    store.create("pods", hi)
    assert engine.schedule_pending() == 1
    assert store.get("pods", "p-high")["spec"].get("nodeName") == "only"
    assert not store.get("pods", "p-low")["spec"].get("nodeName")


def test_duplicate_profile_names_rejected_with_rollback():
    import pytest

    cfg = _two_profile_config()
    cfg["profiles"][1]["schedulerName"] = "default-scheduler"
    with pytest.raises(ValueError, match="duplicated profile"):
        parse_profiles(cfg)
    svc, engine, store = _service_with(default_scheduler_config(), _nodes())
    with pytest.raises(ValueError):
        svc.restart_scheduler(cfg)
    # rollback kept the old config current and the engine consistent
    assert svc.get_config()["profiles"][0]["schedulerName"] == "default-scheduler"
    store.create("pods", _pod("p-after"))
    assert engine.schedule_pending() == 1


def test_engine_less_service_still_validates():
    import pytest

    svc = SchedulerService(engine=None)
    bad = _two_profile_config()
    bad["profiles"][1]["schedulerName"] = "default-scheduler"
    with pytest.raises(ValueError):
        svc.restart_scheduler(bad)
    assert len(svc.get_config()["profiles"]) == 1  # old config kept


def test_legacy_set_plugin_config_clears_routing():
    from kube_scheduler_simulator_tpu.plugins.registry import PluginSetConfig

    svc, engine, store = _service_with(_two_profile_config(), _nodes())
    assert engine.profiles is not None
    engine.set_plugin_config(PluginSetConfig(enabled=["NodeResourcesFit"]))
    assert engine.profiles is None  # legacy API takes over completely
    store.create("pods", _pod("p-any", "whatever-name"))
    assert engine.schedule_pending() == 1  # no routing: every pod scheduled


def test_config_apply_updates_profiles():
    svc, engine, store = _service_with(default_scheduler_config(), _nodes())
    store.create("pods", _pod("p-early", "bin-packing"))
    assert engine.schedule_pending() == 0  # profile doesn't exist yet
    svc.restart_scheduler(_two_profile_config())
    assert engine.schedule_pending() == 1
    assert store.get("pods", "p-early")["spec"]["nodeName"] == "node-small"
