"""Wave black box: crash-consistent post-mortem capture + device
telemetry (utils/blackbox.py, docs/metrics.md).

Covers the acceptance criteria end to end:

  * a fault-injected wave (KSS_TPU_FAULT_PLAN semantics via an armed
    plan) produces a schema-valid dump carrying the speculative round
    history, the fault trip (seam + classification + protocol action)
    and the wave's counter deltas;
  * black-box-on vs off produces byte-identical annotations (the
    recorder never touches the product) and records nothing when off;
  * HBM gauges appear in /api/v1/metrics with an EXPLICIT
    hbm_stats_available=0 no-op on the CPU backend;
  * per-session SLO (p50/p99 wave latency, cycles/s) appears on
    /api/v1/sessions and /readyz;
  * the live /metrics exposition stays validator-clean after a full
    engine wave AND after a fault-injected wave (the satellite: the
    validator must run against the real route, not synthetic tracers);
  * GET /api/v1/debug/dump (+ the per-session alias) serves a live
    bundle.
"""

import glob
import json
import urllib.request

import pytest

from kube_scheduler_simulator_tpu.cluster.store import ObjectStore
from kube_scheduler_simulator_tpu.framework.engine import SchedulerEngine
from kube_scheduler_simulator_tpu.models.workloads import make_nodes, make_pods
from kube_scheduler_simulator_tpu.plugins.registry import PluginSetConfig
from kube_scheduler_simulator_tpu.utils import blackbox, faults
from kube_scheduler_simulator_tpu.utils.blackbox import (
    BLACKBOX, SLO, SLOTracker, TELEMETRY, validate_dump)
from kube_scheduler_simulator_tpu.utils.tracing import (
    TRACER, validate_exposition)


@pytest.fixture(autouse=True)
def _clean_blackbox():
    BLACKBOX.reset()
    yield
    BLACKBOX.reset()
    blackbox.set_enabled(True)


def _cluster(n_nodes=6, n_pods=24, seed=1):
    store = ObjectStore()
    for n in make_nodes(n_nodes, seed=seed):
        store.create("nodes", n)
    for p in make_pods(n_pods, seed=seed + 1):
        store.create("pods", p)
    return store


def _engine(store, chunk=8):
    return SchedulerEngine(
        store, plugin_config=PluginSetConfig(enabled=["NodeResourcesFit"]),
        chunk=chunk)


def _state(store):
    out = {}
    for p in store.list("pods")[0]:
        meta = p.get("metadata") or {}
        out[meta.get("name", "")] = (
            (p.get("spec") or {}).get("nodeName"),
            dict(meta.get("annotations") or {}))
    return out


# ---------------------------------------------------------------- dumps


def test_fault_injected_wave_writes_schema_valid_dump(monkeypatch, tmp_path):
    """The headline acceptance: a transient fault with the retry budget
    exhausted aborts the wave and auto-writes a post-mortem dump with
    the round history, the classified trip, the protocol action, and
    the wave's counter deltas."""
    monkeypatch.setenv("KSS_TPU_WAVE_MAX_RETRIES", "0")
    monkeypatch.setenv("KSS_TPU_BLACKBOX_DIR", str(tmp_path))
    engine = _engine(_cluster())
    plan = faults.FaultPlan(
        [faults.FaultRule("replay.decision_fetch", nth=2, error="runtime")],
        seed=3)
    with faults.armed(plan):
        with pytest.raises(faults.InjectedFault):
            engine.schedule_pending()
    engine.close()
    files = sorted(glob.glob(str(tmp_path / "blackbox-*.json")))
    assert files, "no dump auto-written on wave abort"
    doc = json.loads(open(files[-1]).read())
    res = validate_dump(doc, require_fault=True, require_rounds=True)
    assert doc["reason"] == "wave_abort"
    assert doc["cause"]["seam"] == "replay.decision_fetch"
    assert doc["cause"]["classification"] == "transient"
    assert res["kinds"]["speculative.round"] >= 1
    assert res["kinds"]["wave.abort"] == 1
    # counter deltas are for THIS wave (baseline pinned at wave.start)
    assert any(k.startswith("fault_injected_total")
               for k in doc["counter_deltas"])
    # the armed plan ships in the bundle
    assert doc["fault_plan"]["rules"][0]["seam"] == "replay.decision_fetch"
    assert doc["fault_plan"]["rules"][0]["trips"] == 1
    # open spans AT fault time survived the unwind
    assert "replay_and_decode_stream" in [
        s["name"] for s in doc["open_spans"]]
    # the in-memory ring kept the dump too
    assert BLACKBOX.last_dump()["reason"] == "wave_abort"
    assert BLACKBOX.recent_dumps()[-1]["path"] == files[-1]


def test_transient_retry_records_action_and_heals(monkeypatch):
    """With budget left the same fault heals via suffix retry — the ring
    must show trip -> wave.retry -> wave.end, and no abort dump."""
    monkeypatch.setenv("KSS_TPU_WAVE_MAX_RETRIES", "3")
    store = _cluster()
    engine = _engine(store)
    plan = faults.FaultPlan(
        [faults.FaultRule("replay.decision_fetch", nth=2, error="runtime")],
        seed=3)
    with faults.armed(plan):
        bound = engine.schedule_pending()
    engine.close()
    assert bound > 0
    kinds = {}
    for ev in BLACKBOX.events():
        kinds[ev["kind"]] = kinds.get(ev["kind"], 0) + 1
    assert kinds.get("fault.trip") == 1
    assert kinds.get("wave.retry") == 1
    assert kinds.get("wave.end", 0) >= 1
    assert not kinds.get("wave.abort")
    assert BLACKBOX.last_dump() is None


def test_structural_fault_degradation_dumps_in_memory(monkeypatch):
    """A structural (memory) fault steps the ladder down; the black box
    records the degrade transition and snapshots a degradation bundle
    without needing a dump dir."""
    monkeypatch.delenv("KSS_TPU_BLACKBOX_DIR", raising=False)
    store = _cluster()
    engine = _engine(store)
    plan = faults.FaultPlan(
        [faults.FaultRule("replay.scan_dispatch", nth=1, error="memory")],
        seed=5)
    with faults.armed(plan):
        bound = engine.schedule_pending()
    assert bound > 0
    assert engine.result_mode() == "host_resident"
    engine.close()
    evs = [e for e in BLACKBOX.events() if e["kind"] == "degrade"]
    assert evs and evs[0]["from_mode"] == "device_resident"
    assert evs[0]["to_mode"] == "host_resident"
    dump = BLACKBOX.last_dump()
    assert dump is not None and dump["reason"] == "degradation"
    assert dump["path"] is None  # in-memory only, no dir set
    validate_dump(dump)


def test_disabled_blackbox_records_nothing_and_bytes_match(monkeypatch):
    """KSS_TPU_BLACKBOX=0 A/B: identical annotations, zero events."""
    results = {}
    for arm in (True, False):
        blackbox.set_enabled(arm)
        BLACKBOX.reset()
        store = _cluster(seed=11)
        engine = _engine(store)
        engine.schedule_pending()
        results[arm] = _state(store)
        if arm is False:
            assert BLACKBOX.events() == []
        else:
            assert any(e["kind"] == "wave.start" for e in BLACKBOX.events())
        engine.close()
    assert results[True] == results[False]


def test_session_scoped_bundle_excludes_neighbor_events():
    """A session-scoped dump must not leak a neighbor's activity; the
    sessionless bundle keeps the whole ring."""
    with TRACER.session_scope("tenant-a"):
        BLACKBOX.record("wave.start", pods=1)
    with TRACER.session_scope("tenant-b"):
        BLACKBOX.record("wave.start", pods=2)
    a = BLACKBOX.bundle("request", session="tenant-a")
    assert {e.get("session") for e in a["events"]} == {"tenant-a"}
    full = BLACKBOX.bundle("request", session=None)
    assert {e.get("session") for e in full["events"]} == {
        "tenant-a", "tenant-b"}
    # eviction releases the per-session baseline
    BLACKBOX.wave_start("tenant-a", pods=1)
    assert "tenant-a" in BLACKBOX._baselines
    BLACKBOX.drop_session("tenant-a")
    assert "tenant-a" not in BLACKBOX._baselines


def test_disabled_blackbox_skips_open_span_registry():
    from kube_scheduler_simulator_tpu.utils import tracing

    blackbox.set_enabled(False)
    try:
        assert tracing.BLACKBOX_OPEN_SPANS is False
        with TRACER.span("gated"):
            assert TRACER.open_spans() == []
    finally:
        blackbox.set_enabled(True)
    assert tracing.BLACKBOX_OPEN_SPANS is True


def test_counter_deltas_reset_per_wave():
    store = _cluster(n_pods=8, seed=21)
    engine = _engine(store)
    engine.schedule_pending()
    first = BLACKBOX.counter_deltas(None)
    assert first  # the wave moved counters
    # a fresh wave_start re-pins the baseline: deltas go back to ~zero
    BLACKBOX.wave_start(None, pods=0, mode="device_resident")
    assert BLACKBOX.counter_deltas(None) == {}
    engine.close()


# ------------------------------------------------------------- SLO plane


def test_slo_tracker_percentiles_and_window():
    t = SLOTracker(window=8)
    for i in range(20):  # only the last 8 stay in the window
        t.observe_wave("s1", seconds=0.1 * (i + 1), pods=10)
    s = t.stats("s1")
    assert s["waves"] == 8
    assert s["p50WaveSeconds"] == pytest.approx(1.7)
    assert s["p99WaveSeconds"] == pytest.approx(2.0)
    assert s["cyclesPerSec"] == pytest.approx(80 / sum(
        0.1 * (i + 1) for i in range(12, 20)), abs=0.06)
    assert t.stats("nobody") is None
    assert "s1" in t.snapshot()


def test_engine_wave_feeds_slo():
    SLO.reset()
    store = _cluster(n_pods=8, seed=31)
    engine = _engine(store)
    engine.schedule_pending()
    engine.close()
    s = SLO.stats(None)
    assert s is not None and s["waves"] >= 1
    assert s["p99WaveSeconds"] > 0 and s["cyclesPerSec"] > 0


# -------------------------------------------------------- HTTP surfaces


@pytest.fixture()
def server():
    from kube_scheduler_simulator_tpu.config.config import (
        SimulatorConfiguration)
    from kube_scheduler_simulator_tpu.server.di import DIContainer
    from kube_scheduler_simulator_tpu.server.server import SimulatorServer

    di = DIContainer(SimulatorConfiguration(port=0))
    srv = SimulatorServer(di, port=0)
    srv.start(block=False)
    yield srv
    srv.shutdown()


def _get(srv, path):
    url = f"http://127.0.0.1:{srv.port}{path}"
    with urllib.request.urlopen(url, timeout=10) as r:
        raw = r.read()
        ctype = r.headers.get("Content-Type", "")
        return (json.loads(raw) if ctype.startswith("application/json")
                else raw.decode())


def _post(srv, path, body):
    req = urllib.request.Request(
        f"http://127.0.0.1:{srv.port}{path}",
        data=json.dumps(body).encode(), method="POST",
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=10) as r:
        return json.loads(r.read() or b"null")


def _schedule_via_server(srv, n_nodes=3, n_pods=5, seed=41):
    import time

    for n in make_nodes(n_nodes, seed=seed):
        _post(srv, "/api/v1/nodes", n)
    for p in make_pods(n_pods, seed=seed + 1):
        _post(srv, "/api/v1/pods", p)
    deadline = time.time() + 30
    while time.time() < deadline:
        pods = _get(srv, "/api/v1/pods")["items"]
        if all((p.get("spec") or {}).get("nodeName") for p in pods):
            return
        time.sleep(0.1)
    raise AssertionError("pods never scheduled")


def test_live_metrics_route_validates_after_full_and_faulted_waves(server):
    """Satellite: validate_exposition against the REAL /metrics route —
    after a full engine wave, and again after a fault-injected wave
    exercised the wave_faults/retry families."""
    _schedule_via_server(server)
    fams = validate_exposition(_get(server, "/metrics"))
    assert "kss_tpu_pods_scheduled_total" in fams
    # HBM gauges: the sampler ran at server start; on the CPU backend
    # the EXPLICIT no-op marker is exported instead of silent absence
    assert fams["kss_tpu_hbm_stats_available"]["type"] == "gauge"
    assert fams["kss_tpu_hbm_stats_available"]["samples"][0][2] == "0"
    snap = _get(server, "/api/v1/metrics")
    assert snap["gauges"].get("hbm_stats_available") == 0
    assert "time_split" in snap

    # fault-injected wave through the same live engine
    plan = faults.FaultPlan(
        [faults.FaultRule("replay.decision_fetch", nth=1, error="runtime",
                          sessions=["default"])], seed=9)
    with faults.armed(plan):
        for p in make_pods(4, seed=77):
            p["metadata"]["name"] = "faulted-" + p["metadata"]["name"]
            _post(server, "/api/v1/pods", p)
        import time

        deadline = time.time() + 30
        while time.time() < deadline:
            snap = _get(server, "/api/v1/metrics")
            lc = snap["labeled_counters"].get("fault_injected_total") or []
            if lc:
                break
            time.sleep(0.1)
        assert lc, "the armed fault never fired through the live loop"
    fams = validate_exposition(_get(server, "/metrics"))
    assert "kss_tpu_fault_injected_total" in fams
    assert "kss_tpu_wave_faults_total" in fams


def test_debug_dump_route_and_session_alias(server):
    _schedule_via_server(server, seed=51)
    body = _get(server, "/api/v1/debug/dump")
    dump = body["dump"]
    validate_dump(dump)
    assert dump["reason"] == "request"
    kinds = {e["kind"] for e in dump["events"]}
    assert "wave.start" in kinds and "wave.end" in kinds
    assert dump["device"]["hbm_available"] is False  # CPU backend
    assert "KSS_TPU" not in dump["env"] or isinstance(dump["env"], dict)
    # per-session alias pins the session filter: only that session's
    # events (and open spans / recent dumps) appear in the bundle
    body2 = _get(server, "/api/v1/sessions/default/debug/dump")
    assert body2["dump"]["session"] == "default"
    assert body2["dump"]["events"], "default session's own events missing"
    assert {e.get("session") for e in body2["dump"]["events"]} == {"default"}
    assert all(s.get("session") == "default"
               for s in body2["dump"]["open_spans"])
    assert all(d.get("session") == "default" for d in body2["recent"])
    assert isinstance(body["recent"], list)


def test_slo_on_sessions_and_readyz(server):
    SLO.reset()
    _schedule_via_server(server, seed=61)
    sessions = _get(server, "/api/v1/sessions")["items"]
    default = [s for s in sessions if s["id"] == "default"][0]
    assert default["slo"] is not None
    assert default["slo"]["waves"] >= 1
    assert default["slo"]["p99WaveSeconds"] > 0
    ready = _get(server, "/readyz")
    assert ready["slo"]["default"]["p99WaveSeconds"] > 0
    assert ready["slo"]["default"]["cyclesPerSec"] > 0


# ------------------------------------------------- compile observability


def test_compile_build_histogram_and_cache_gauge():
    TRACER.reset()
    # an odd shape this process has not compiled: forces a cache miss
    store = ObjectStore()
    for n in make_nodes(7, seed=71):
        store.create("nodes", n)
    for p in make_pods(9, seed=72):
        store.create("pods", p)
    engine = _engine(store, chunk=4)
    engine.schedule_pending()
    engine.close()
    snap = TRACER.snapshot()
    hist = snap["histograms"].get("scan_compile_build_seconds")
    assert hist is not None and hist["series"], "no build histogram"
    assert all("key" in s["labels"] and s["labels"]["result"] == "ok"
               for s in hist["series"])
    assert snap["gauges"].get("scan_compile_cache_entries", 0) >= 1
    builds = [e for e in BLACKBOX.events() if e["kind"] == "compile.build"]
    assert builds and builds[0]["seconds"] >= 0


def test_device_telemetry_explicit_noop_on_cpu():
    out = TELEMETRY.sample_once()
    assert out["available"] is False  # CPU backend has no memory_stats
    assert out["bytes_in_use"] is None
    snap = TRACER.snapshot()
    assert snap["gauges"]["hbm_stats_available"] == 0
    assert "hbm_bytes_in_use" not in snap["gauges"]
