"""Host-static score rows never travel from the device.

NodeAffinity's raw score is a precompiled [P, N] row (plugins/affinity.py
score_kernel is a pure pass-through of pref_raw), and custom plugins'
scores are precompiled the same way — so the compact replay tags them
"host" (state/compile.py _score_dtype), excludes them from the device
outputs (framework/pipeline.py build_step), and the decoder reads the
host copy (framework/replay.py / store/native_decode.py).  D2H payload on
the tunneled TPU link is the end-to-end bottleneck, so every byte that
can stay on host matters.

Parity coverage for the actual annotation bytes lives in tests/test_parity.py
(configs 3-5 all carry NodeAffinity scoring); these tests pin the layout
contract itself plus byte-parity on the skip edge cases.
"""

import numpy as np

from kube_scheduler_simulator_tpu.framework.replay import replay
from kube_scheduler_simulator_tpu.models.workloads import baseline_config
from kube_scheduler_simulator_tpu.reference_impl.sequential import SequentialScheduler
from kube_scheduler_simulator_tpu.state.compile import compile_workload
from kube_scheduler_simulator_tpu.store.decode import decode_pod_result


def _assert_host_layout(cw, rr, must_include):
    scorers = cw.config.scorers()
    static = set(cw.host["static_score_rows"]) & set(scorers)
    assert must_include <= static
    for name in static:
        assert cw.host["score_dtypes"][scorers.index(name)] == "host"
    dynamic = [n for n in scorers if n not in static]
    assert dynamic, "workload must still carry dynamic scorers"
    cc = rr._compact
    host_cols = {name for g, name in cc.score_cols if g == "host"}
    assert host_cols == static
    n_transferred = sum(1 for g, _ in cc.score_cols if g != "host")
    assert n_transferred == len(dynamic)
    rows = {g: arr.shape[1] for g, arr in (
        ("raw8", cc.raw8[0]), ("raw16", cc.raw16[0]), ("raw32", cc.raw32[0]))}
    assert sum(rows.values()) == n_transferred


def test_static_rows_are_host_tagged():
    """Every scorer whose raw is a precompiled pass-through row rides the
    "host" group; dynamic scorers (carry-dependent) still travel."""
    nodes, pods, cfg = baseline_config(3, scale=0.02, seed=7)
    cw = compile_workload(nodes, pods, cfg)
    rr = replay(cw, chunk=16)
    _assert_host_layout(cw, rr, {"NodeAffinity", "TaintToleration"})


def test_imagelocality_volumebinding_rows_are_host_tagged():
    """The default-lineup statics: ImageLocality's precompiled row and
    VolumeBinding's constant-zero score stay host-resident too."""
    from kube_scheduler_simulator_tpu.models.workloads import make_nodes, make_pods
    from kube_scheduler_simulator_tpu.plugins.registry import PluginSetConfig

    nodes = make_nodes(10, seed=5)
    pods = make_pods(20, seed=6, with_affinity=True, with_tolerations=True)
    cfg = PluginSetConfig(enabled=[
        "NodeResourcesFit", "NodeResourcesBalancedAllocation", "NodeAffinity",
        "TaintToleration", "ImageLocality", "VolumeBinding"])
    cw = compile_workload(nodes, pods, cfg)
    rr = replay(cw, chunk=8)
    _assert_host_layout(
        cw, rr,
        {"NodeAffinity", "TaintToleration", "ImageLocality", "VolumeBinding"})
    assert not cw.host["static_score_rows"]["VolumeBinding"].any()


def test_host_row_parity_including_score_skip():
    """Pods WITHOUT preferred terms (score_skip) and WITH them must both
    decode byte-identically to the sequential oracle when the NodeAffinity
    raw comes from the host copy."""
    nodes, pods, cfg = baseline_config(3, scale=0.02, seed=11)
    seq = SequentialScheduler(nodes, pods, cfg).schedule_all()
    cw = compile_workload(nodes, pods, cfg)
    skip = np.asarray(cw.host["score_skip"]["NodeAffinity"])
    assert skip.any() and (~skip).any(), (
        "workload must exercise both skip branches; adjust seed/scale")
    rr = replay(cw, chunk=16)
    for i, (seq_ann, seq_sel) in enumerate(seq):
        assert int(rr.selected[i]) == seq_sel
        dev_ann = decode_pod_result(rr, i)
        for key in seq_ann:
            assert dev_ann[key] == seq_ann[key], f"pod {i} key {key}"


def test_host_row_raw_of_masks_skipped_pods():
    """raw_of keeps the pre-change contract: 0 where score_skip holds."""
    nodes, pods, cfg = baseline_config(3, scale=0.02, seed=11)
    cw = compile_workload(nodes, pods, cfg)
    rr = replay(cw, chunk=16)
    na_pos = cw.config.scorers().index("NodeAffinity")
    skip = np.asarray(cw.host["score_skip"]["NodeAffinity"])
    static = cw.host["static_score_rows"]["NodeAffinity"]
    for i in range(len(pods)):
        row = rr.raw_of(i)[na_pos]
        if skip[i]:
            assert not row.any()
        else:
            assert (row == static[i]).all()


def test_custom_plugin_scores_are_host_static():
    from kube_scheduler_simulator_tpu.models.workloads import make_nodes, make_pods
    from kube_scheduler_simulator_tpu.plugins.custom import CustomPlugin
    from kube_scheduler_simulator_tpu.plugins.registry import PluginSetConfig

    class NameLen(CustomPlugin):
        name = "NameLen"

        def score(self, pod, node):
            return len(node["metadata"]["name"])

    nodes = make_nodes(8, seed=3)
    pods = make_pods(12, seed=4)
    cfg = PluginSetConfig(enabled=["NodeResourcesFit", "NameLen"],
                          custom={"NameLen": NameLen()})
    cw = compile_workload(nodes, pods, cfg)
    assert "NameLen" in cw.host["static_score_rows"]
    rr = replay(cw, chunk=8)
    assert ("host", "NameLen") in rr._compact.score_cols
    pos = cw.config.scorers().index("NameLen")
    expect = np.asarray([len(n["metadata"]["name"]) for n in nodes])
    for i in range(len(pods)):
        assert (rr.raw_of(i)[pos] == expect).all()
