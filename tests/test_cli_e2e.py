"""Process-level round trip through the real CLIs: record a live
simulator with cmd/sched_recorder, then boot a second simulator that
replays the record file (the reference's record-and-replay workflow,
recorder.go + replayer.go, driven end-to-end)."""

import json
import os
import signal
import subprocess
import sys
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _api(port, method, path, body=None):
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", data=data, method=method,
        headers={"Content-Type": "application/json"} if data else {})
    with urllib.request.urlopen(req, timeout=10) as r:
        raw = r.read()
        return json.loads(raw) if raw else None


def _wait_up(port, timeout=60):
    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            _api(port, "GET", "/api/v1/nodes")
            return
        except Exception:
            time.sleep(0.3)
    raise TimeoutError(f"simulator on :{port} never came up")


def _env(**extra):
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)
    env.update({k: str(v) for k, v in extra.items()})
    return env


def test_record_then_replay_roundtrip(tmp_path):
    record = tmp_path / "record.jsonl"
    port_a, port_b = 18231, 18232

    sim_a = subprocess.Popen(
        [sys.executable, "-m", "kube_scheduler_simulator_tpu.cmd.simulator"],
        env=_env(PORT=port_a), cwd=str(tmp_path),
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    rec = None
    sim_b = None
    try:
        _wait_up(port_a)
        rec = subprocess.Popen(
            [sys.executable, "-m", "kube_scheduler_simulator_tpu.cmd.sched_recorder",
             "--path", str(record), "--kubeconfig", f"http://127.0.0.1:{port_a}"],
            env=_env(), cwd=str(tmp_path),
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
        time.sleep(1.5)  # recorder subscribes

        _api(port_a, "POST", "/api/v1/nodes", {
            "metadata": {"name": "rec-node"},
            "status": {"allocatable": {"cpu": "8", "memory": "32Gi", "pods": "110"}}})
        _api(port_a, "POST", "/api/v1/pods", {
            "metadata": {"name": "rec-pod"},
            "spec": {"containers": [{"name": "c", "resources": {
                "requests": {"cpu": "1", "memory": "1Gi"}}}]}})

        # wait until the live scheduler binds the pod, then let the
        # recorder flush (its interval is 5s; SIGTERM also flushes)
        deadline = time.time() + 60
        while time.time() < deadline:
            pod = _api(port_a, "GET", "/api/v1/pods/default/rec-pod")
            if (pod.get("spec") or {}).get("nodeName"):
                break
            time.sleep(0.5)
        assert pod["spec"]["nodeName"] == "rec-node"
        time.sleep(1)
        rec.send_signal(signal.SIGINT)
        rec.wait(timeout=30)

        lines = [json.loads(l) for l in record.read_text().splitlines()]
        assert any(l["event"] == "Add" and l["resource"]["kind"] == "Node"
                   for l in lines)
        assert any(l["event"] == "Add" and l["resource"]["kind"] == "Pod"
                   for l in lines)

        # boot a fresh simulator that replays the record; its own
        # scheduler re-schedules the (scheduled-pod-filtered) pods
        sim_b = subprocess.Popen(
            [sys.executable, "-m", "kube_scheduler_simulator_tpu.cmd.simulator"],
            env=_env(PORT=port_b, REPLAYER_ENABLED="1",
                     RECORD_FILE_PATH=str(record)),
            cwd=str(tmp_path),
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
        _wait_up(port_b, timeout=90)
        nodes = _api(port_b, "GET", "/api/v1/nodes")["items"]
        assert [n["metadata"]["name"] for n in nodes] == ["rec-node"]
        deadline = time.time() + 60
        pod_b = {}
        while time.time() < deadline:
            items = _api(port_b, "GET", "/api/v1/pods")["items"]
            if items and (items[0].get("spec") or {}).get("nodeName") \
                    and (items[0]["metadata"].get("annotations") or {}):
                pod_b = items[0]
                break
            time.sleep(0.5)
        assert pod_b.get("spec", {}).get("nodeName") == "rec-node"
        assert "kube-scheduler-simulator.sigs.k8s.io/selected-node" in \
            pod_b["metadata"]["annotations"]
    finally:
        for proc in (rec, sim_a, sim_b):
            if proc is not None and proc.poll() is None:
                proc.terminate()
                try:
                    proc.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    proc.kill()


def test_external_scheduler_mode(tmp_path):
    """KWOK disableKubeScheduler analogue: the simulator boots with its
    in-process scheduling loop OFF (EXTERNAL_SCHEDULER_ENABLED), and a
    standalone cmd/scheduler process drives scheduling over the HTTP API
    (--once), writing the result annotations back through the remote
    store."""
    port = 18233
    sim = subprocess.Popen(
        [sys.executable, "-m", "kube_scheduler_simulator_tpu.cmd.simulator"],
        env=_env(PORT=port, EXTERNAL_SCHEDULER_ENABLED="1"),
        cwd=str(tmp_path),
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    try:
        _wait_up(port)
        _api(port, "POST", "/api/v1/nodes", {
            "metadata": {"name": "ext-node"},
            "status": {"allocatable": {"cpu": "8", "memory": "32Gi",
                                       "pods": "110"}}})
        _api(port, "POST", "/api/v1/pods", {
            "metadata": {"name": "ext-pod"},
            "spec": {"containers": [{"name": "c", "resources": {
                "requests": {"cpu": "1", "memory": "1Gi"}}}]}})
        time.sleep(2)
        pod = _api(port, "GET", "/api/v1/pods/default/ext-pod")
        assert not (pod.get("spec") or {}).get("nodeName"), \
            "loop must be off in external-scheduler mode"

        r = subprocess.run(
            [sys.executable, "-m", "kube_scheduler_simulator_tpu.cmd.scheduler",
             "--master", f"http://127.0.0.1:{port}", "--once"],
            env=_env(), cwd=str(tmp_path), timeout=240,
            capture_output=True, text=True)
        assert r.returncode == 0, r.stderr[-2000:]

        pod = _api(port, "GET", "/api/v1/pods/default/ext-pod")
        assert pod["spec"].get("nodeName") == "ext-node"
        anns = pod["metadata"].get("annotations") or {}
        key = "kube-scheduler-simulator.sigs.k8s.io/selected-node"
        assert anns.get(key) == "ext-node"
    finally:
        sim.terminate()
        sim.wait(timeout=15)
