"""Concurrency soak: the scheduling loop, API-style writers, watchers and
snapshot readers hammer one store at once.

The store's shared-listing / copy-on-write write path (informer-cache
contract) must hold under real thread interleavings: no exceptions on
any thread, resourceVersions strictly increasing per object update,
watch streams parse and stay causally consistent, and every surviving
pod ends bound or cleanly pending.  (SURVEY.md §5 concurrency safety —
the reference relies on mutexes + apiserver optimistic concurrency; we
additionally share read snapshots, so this is OUR race surface.)
"""

import json
import queue
import threading
import time

from kube_scheduler_simulator_tpu.cluster.store import Conflict, NotFound, ObjectStore
from kube_scheduler_simulator_tpu.framework.engine import SchedulerEngine
from kube_scheduler_simulator_tpu.models.workloads import make_nodes
from kube_scheduler_simulator_tpu.plugins.registry import PluginSetConfig
from kube_scheduler_simulator_tpu.services.snapshot import SnapshotService


class _Sched:
    def get_config(self):
        return {"profiles": []}

    def restart_scheduler(self, cfg):
        pass


def test_soak_writers_watchers_scheduler(duration=4.0):
    store = ObjectStore()
    for n in make_nodes(8, seed=3):
        store.create("nodes", n)
    engine = SchedulerEngine(store, plugin_config=PluginSetConfig(
        enabled=["NodeResourcesFit", "NodeResourcesBalancedAllocation"]))
    snap = SnapshotService(store, _Sched())

    stop = threading.Event()
    errors: list[BaseException] = []

    def guarded(fn):
        def run():
            try:
                fn()
            except BaseException as e:  # noqa: BLE001 — the assertion surface
                errors.append(e)
        return run

    counter = {"i": 0}
    counter_lock = threading.Lock()

    def writer():
        while not stop.is_set():
            with counter_lock:
                i = counter["i"]
                counter["i"] += 1
            name = f"soak-{i}"
            store.create("pods", {"metadata": {"name": name},
                                  "spec": {"containers": [{"name": "c",
                                           "resources": {"requests": {
                                               "cpu": "100m"}}}]}})
            if i % 3 == 0:
                # label churn through the conflict-checked update path
                for _ in range(20):
                    try:
                        cur = store.get("pods", name, "default")
                        cur["metadata"].setdefault("labels", {})["touch"] = str(i)
                        store.update("pods", cur)
                        break
                    except Conflict:
                        continue
                    except NotFound:
                        break
            if i % 5 == 0 and i > 10:
                try:
                    store.delete("pods", f"soak-{i - 10}", "default")
                except NotFound:
                    pass
            time.sleep(0.002)

    def scheduler():
        while not stop.is_set():
            engine.schedule_pending()
            time.sleep(0.01)

    def snapshotter():
        while not stop.is_set():
            s = snap.snap()
            json.dumps(s)  # the export handler's serialization
            time.sleep(0.02)

    watch_events: list = []

    def watcher():
        q = store.watch("pods")
        try:
            while not stop.is_set():
                try:
                    rv, et, obj = q.get(timeout=0.1)
                except queue.Empty:
                    continue
                # events must be JSON-serializable, carry identity, and
                # arrive in rv order
                json.dumps(obj)
                assert obj["metadata"]["name"]
                if watch_events:
                    assert rv > watch_events[-1]
                watch_events.append(rv)
        finally:
            store.unwatch("pods", q)

    threads = [threading.Thread(target=guarded(f), daemon=True)
               for f in (writer, writer, scheduler, snapshotter, watcher)]
    for t in threads:
        t.start()
    time.sleep(duration)
    stop.set()
    for t in threads:
        t.join(10)
        assert not t.is_alive(), "thread failed to stop (deadlock?)"
    assert not errors, errors[:3]

    # settle and check end-state consistency
    engine.schedule_pending()
    pods, _ = store.list("pods")
    assert counter["i"] > 20, "soak produced too little traffic"
    assert watch_events, "watcher saw no events"
    for p in pods:
        nn = (p.get("spec") or {}).get("nodeName")
        if nn:
            store.get("nodes", nn)  # bound to a real node
    # resourceVersions unique across live objects
    rvs = [p["metadata"]["resourceVersion"] for p in pods]
    assert len(rvs) == len(set(rvs))


def test_soak_external_writes_during_streaming_commit():
    """External store writers (creates, label churn, deletes) interleave
    with chunk-pipelined commit waves: the commit worker's apply_batch
    writes and the writers' conflict-checked updates share the store,
    and every invariant of the per-pod path must hold — no thread
    raises, rvs stay unique, bound pods reference real nodes, and every
    pod the engine looked at ends bound or cleanly pending."""
    from tests.test_engine_soak import check_invariants

    store = ObjectStore()
    for n in make_nodes(10, seed=5):
        store.create("nodes", n)
    # no PostFilter in the lineup -> the wave takes the pipelined path
    engine = SchedulerEngine(store, plugin_config=PluginSetConfig(
        enabled=["NodeResourcesFit", "NodeResourcesBalancedAllocation",
                 "TaintToleration"]), chunk=8)
    assert engine._can_stream_commit()
    from kube_scheduler_simulator_tpu.utils.tracing import TRACER

    waves_before = TRACER.summary()["counters"].get(
        "commit_stream_waves_total", 0)

    stop = threading.Event()
    errors: list[BaseException] = []

    def guarded(fn):
        def run():
            try:
                fn()
            except BaseException as e:  # noqa: BLE001 — the assertion surface
                errors.append(e)
        return run

    counter = {"i": 0}
    counter_lock = threading.Lock()

    def writer():
        while not stop.is_set():
            with counter_lock:
                i = counter["i"]
                counter["i"] += 1
            name = f"stream-{i}"
            store.create("pods", _pod(name))
            if i % 3 == 0:
                for _ in range(20):
                    try:
                        cur = store.get("pods", name, "default")
                        cur["metadata"].setdefault("labels", {})["touch"] = str(i)
                        store.update("pods", cur)
                        break
                    except Conflict:
                        continue
                    except NotFound:
                        break
            if i % 7 == 0 and i > 14:
                try:
                    store.delete("pods", f"stream-{i - 14}", "default")
                except NotFound:
                    pass
            time.sleep(0.001)

    def scheduler():
        while not stop.is_set():
            engine.schedule_pending()
            time.sleep(0.005)

    threads = [threading.Thread(target=guarded(f), daemon=True)
               for f in (writer, writer, scheduler)]
    for t in threads:
        t.start()
    time.sleep(3.0)
    stop.set()
    for t in threads:
        t.join(10)
        assert not t.is_alive(), "thread failed to stop (deadlock?)"
    assert not errors, errors[:3]

    engine.schedule_pending()  # settle
    check_invariants(store)
    pods, _ = store.list("pods")
    assert counter["i"] > 20, "soak produced too little traffic"
    rvs = [p["metadata"]["resourceVersion"] for p in pods]
    assert len(rvs) == len(set(rvs))
    # the streaming waves actually ran (not the sequential fallback) —
    # delta against the suite-global counter, which other tests bump
    assert TRACER.summary()["counters"].get(
        "commit_stream_waves_total", 0) > waves_before


def _pod(name: str) -> dict:
    return {"metadata": {"name": name, "namespace": "default"},
            "spec": {"containers": [
                {"name": "c", "resources": {"requests": {"cpu": "100m"}}}]}}


def test_update_pod_survives_forced_conflicts():
    """The engine's bind/status writes retry under the shared exponential
    backoff (100ms x 3^n, 6 steps) instead of a bounded 5 x 1ms loop that
    silently dropped the write (round-3 verdict weak #6): with the first
    4 update() calls per pod forced to Conflict, every bind still lands."""
    store = ObjectStore()
    for n in make_nodes(4, seed=11):
        store.create("nodes", n)
    for i in range(6):
        store.create("pods", _pod(f"soak-{i}"))
    # pin the sequential post-pass: this test exercises _update_pod's
    # conflict-retry machinery, which the pipelined wave's apply_batch
    # path bypasses by construction (single lock hold, no conflicts)
    engine = SchedulerEngine(store, plugin_config=PluginSetConfig(
        enabled=["NodeResourcesFit"]), pipeline_commit=False)
    sleeps: list[float] = []
    engine._retry_sleep = sleeps.append  # no real waiting

    fails = {}
    real_update = store.update

    def flaky_update(kind, obj, **kw):
        if kind == "pods":
            name = obj["metadata"]["name"]
            fails[name] = fails.get(name, 0) + 1
            if fails[name] <= 4:
                raise Conflict(f"forced conflict #{fails[name]} for {name}")
        return real_update(kind, obj, **kw)

    store.update = flaky_update
    try:
        engine.schedule_pending()
    finally:
        store.update = real_update

    pods, _ = store.list("pods")
    assert all(p["spec"].get("nodeName") for p in pods), \
        [p["metadata"]["name"] for p in pods if not p["spec"].get("nodeName")]
    # the backoff schedule ran (4 forced conflicts -> sleeps 0.1, 0.3, 0.9,
    # 2.7 for the first pod's bind)
    import pytest

    assert sleeps[:4] == pytest.approx([0.1, 0.3, 0.9, 2.7])


def test_update_pod_surfaces_exhaustion():
    """A write that cannot land after 6 conflict rounds raises RetryTimeout
    instead of silently dropping the bind."""
    import pytest

    from kube_scheduler_simulator_tpu.utils.retry import RetryTimeout

    store = ObjectStore()
    for n in make_nodes(2, seed=12):
        store.create("nodes", n)
    store.create("pods", _pod("doomed"))
    engine = SchedulerEngine(store, plugin_config=PluginSetConfig(
        enabled=["NodeResourcesFit"]), pipeline_commit=False)
    engine._retry_sleep = lambda s: None

    real_update = store.update

    def always_conflict(kind, obj, **kw):
        if kind == "pods":
            raise Conflict("permanent conflict")
        return real_update(kind, obj, **kw)

    store.update = always_conflict
    try:
        with pytest.raises(RetryTimeout):
            engine.schedule_pending()
    finally:
        store.update = real_update
