"""Regression tests for the kss-analyze serialize-under-lock fixes
(docs/static-analysis.md).

The lock-discipline analyzer flagged the O(object) deep copies and JSON
marshal work `ObjectStore` and `ResultStore` used to run inside their
lock holds.  The fixes snapshot references under the lock and run the
heavy serialization after release; these tests pin that contract — the
copy/encode must never observe the lock held — plus the snapshot
semantics that make releasing early safe.
"""

import copy
import json
import threading

import pytest

from kube_scheduler_simulator_tpu.cluster.store import ObjectStore
from kube_scheduler_simulator_tpu.models.workloads import make_nodes, make_pods
from kube_scheduler_simulator_tpu.store import annotations as ann
from kube_scheduler_simulator_tpu.store.resultstore import ResultStore


def _held(lock) -> bool:
    """True when `lock` cannot be acquired from a fresh thread, i.e.
    someone (the caller) holds it right now.  The probe thread is the
    point: a same-thread try-acquire on the store's RLock would always
    succeed reentrantly and prove nothing."""
    out = {}

    def probe():
        got = lock.acquire(blocking=False)
        if got:
            lock.release()
        out["held"] = not got

    t = threading.Thread(target=probe)
    t.start()
    t.join()
    return out["held"]


@pytest.fixture
def seeded_store():
    store = ObjectStore()
    for n in make_nodes(3, seed=21):
        store.create("nodes", n)
    for p in make_pods(5, seed=22):
        store.create("pods", p)
    return store


def _spy_deepcopy(monkeypatch, lock):
    """Route copy.deepcopy through a wrapper that records whether `lock`
    was held at call time."""
    held_at_call: list[bool] = []
    real = copy.deepcopy

    def spy(obj, *a, **kw):
        held_at_call.append(_held(lock))
        return real(obj, *a, **kw)

    monkeypatch.setattr(copy, "deepcopy", spy)
    return held_at_call


def test_objectstore_get_copies_outside_lock(seeded_store, monkeypatch):
    store = seeded_store
    name = store.list("pods")[0][0]["metadata"]["name"]
    held = _spy_deepcopy(monkeypatch, store._lock)
    pod = store.get("pods", name)
    assert held and not any(held), "get() deep-copied under the store lock"
    # releasing early is safe because the copy is still a snapshot: a
    # caller-side mutation must not reach stored state
    pod["metadata"]["labels"] = {"mutated": "yes"}
    assert "mutated" not in (
        store.get("pods", name)["metadata"].get("labels") or {})


def test_objectstore_list_copies_outside_lock(seeded_store, monkeypatch):
    store = seeded_store
    held = _spy_deepcopy(monkeypatch, store._lock)
    pods, _rv = store.list("pods")
    assert len(pods) == 5
    assert len(held) == 5 and not any(held), \
        "list() ran its O(N x object) copies under the store lock"
    pods[0]["spec"]["nodeName"] = "mutated-node"
    fresh, _ = store.list("pods")
    assert all(p["spec"].get("nodeName") != "mutated-node" for p in fresh)


def test_objectstore_dump_restore_copy_outside_lock(seeded_store, monkeypatch):
    store = seeded_store
    held = _spy_deepcopy(monkeypatch, store._lock)
    kvs = store.dump()
    assert held and not any(held), "dump() deep-copied under the store lock"

    held.clear()
    store.restore(kvs)
    assert held and not any(held), \
        "restore() deep-copied the incoming keyspace under the write lock"
    # restore still detaches from the caller's dicts (the reason the
    # deepcopy exists at all): mutating the input afterwards must not
    # reach stored state
    res = next(r for r, objs in kvs.items() if objs)
    key = next(iter(kvs[res]))
    kvs[res][key]["metadata"]["name"] = "clobbered"
    stored = store.dump()
    assert stored[res][key]["metadata"]["name"] != "clobbered"


def test_resultstore_encode_runs_outside_lock(monkeypatch):
    rs = ResultStore()
    rs.put_decoded("default", "p0", {
        ann.FILTER_RESULT: json.dumps({"nodeA": {"InTree": "fail"}})})
    rs.add_filter_result("default", "p0", "nodeB", "Custom", "ok")
    rs.add_score_result("default", "p0", "nodeB", "Custom", 7)

    held_at_marshal: list[bool] = []
    real = ann.marshal

    def spy(obj):
        held_at_marshal.append(rs._mu.locked())
        return real(obj)

    monkeypatch.setattr(ann, "marshal", spy)
    out = rs.get_stored_result(
        {"metadata": {"namespace": "default", "name": "p0"}})
    assert held_at_marshal and not any(held_at_marshal), \
        "get_stored_result marshalled annotation blobs under _mu"
    # the merge semantics survived the move: granular adds layer OVER
    # the decoded blob without erasing other plugins' entries
    merged = json.loads(out[ann.FILTER_RESULT])
    assert merged["nodeA"]["InTree"] == "fail"
    assert merged["nodeB"]["Custom"] == "ok"


def test_resultstore_snapshot_isolates_concurrent_adds():
    """The under-lock part of get_stored_result is a two-level reference
    snapshot; the marshal outside the lock must therefore never iterate
    a dict a concurrent granular add is mutating (pre-fix this raced
    'dictionary changed size during iteration')."""
    rs = ResultStore()
    rs.put_decoded("default", "p0", {ann.FILTER_RESULT: ann.marshal({})})
    pod = {"metadata": {"namespace": "default", "name": "p0"}}
    stop = threading.Event()
    errs: list[BaseException] = []

    def hammer():
        i = 0
        try:
            while not stop.is_set():
                rs.add_filter_result("default", "p0",
                                     f"node-{i % 37}", "Hammer", "x")
                rs.add_score_result("default", "p0",
                                    f"node-{i % 37}", "Hammer", i % 100)
                i += 1
        except BaseException as e:  # surfaced in the main thread
            errs.append(e)

    t = threading.Thread(target=hammer)
    t.start()
    try:
        for _ in range(300):
            out = rs.get_stored_result(pod)
            json.loads(out[ann.FILTER_RESULT])  # always a complete doc
    finally:
        stop.set()
        t.join()
    assert not errs
