"""Failure-path tables for the parity-critical services.

The reference carries its deepest tests exactly here: snapshot list/apply
error injection (reference: simulator/snapshot/snapshot_test.go:585-964,
via k8stesting reaction hooks), reflector conflict exhaustion
(storereflector/storereflector_test.go), and result-store edge tables
(resultstore/store_test.go).  This module is the analogue: a FaultyStore
injects per-(op, resource) errors like reaction hooks do.
"""

import json

import pytest

from kube_scheduler_simulator_tpu.cluster.store import (
    AlreadyExists, ApiError, Conflict, NotFound, ObjectStore,
)
from kube_scheduler_simulator_tpu.services.snapshot import (
    SnapshotOptions, SnapshotService,
)
from kube_scheduler_simulator_tpu.store import annotations as ann
from kube_scheduler_simulator_tpu.store.reflector import (
    StoreReflector, update_result_history,
)
from kube_scheduler_simulator_tpu.store.resultstore import ResultStore


class FaultyStore(ObjectStore):
    """Reaction-hook analogue: fail selected (op, resource) calls."""

    def __init__(self):
        super().__init__()
        self.fail: dict[tuple[str, str], Exception] = {}
        self.conflict_times: int = 0  # fail the next N updates w/ Conflict
        self.calls: list[tuple[str, str, str]] = []

    def create(self, resource, obj, **kwargs):
        self.calls.append(("create", resource,
                           (obj.get("metadata") or {}).get("name", "")))
        err = self.fail.get(("create", resource))
        if err is not None:
            raise err
        return super().create(resource, obj, **kwargs)

    def update(self, resource, obj, **kwargs):
        if self.conflict_times > 0:
            self.conflict_times -= 1
            raise Conflict(f"injected conflict for {resource}")
        err = self.fail.get(("update", resource))
        if err is not None:
            raise err
        return super().update(resource, obj, **kwargs)

    def list(self, resource, *args, **kwargs):
        err = self.fail.get(("list", resource))
        if err is not None:
            raise err
        return super().list(resource, *args, **kwargs)


class FakeScheduler:
    def __init__(self, fail=False):
        self.fail = fail
        self.restarts: list = []

    def get_config(self):
        return {"profiles": []}

    def restart_scheduler(self, cfg):
        if self.fail:
            raise ApiError("scheduler restart failed")
        self.restarts.append(cfg)


def _obj(name, namespace=None, **spec):
    meta = {"name": name}
    if namespace:
        meta["namespace"] = namespace
    return {"metadata": meta, **({"spec": spec} if spec else {})}


def _snapshot():
    return {
        "namespaces": [_obj("team-a")],
        "priorityClasses": [_obj("high")],
        "storageClasses": [_obj("fast")],
        "pvcs": [_obj("claim-0", namespace="team-a")],
        "nodes": [_obj("node-0")],
        "pods": [_obj("pod-0", namespace="team-a")],
        "pvs": [],
        "schedulerConfig": {"profiles": []},
    }


# ---------------------------------------------------------- snapshot load

def test_load_apply_error_aborts_without_ignore_err():
    store = FaultyStore()
    store.fail[("create", "nodes")] = ApiError("injected: node create fails")
    svc = SnapshotService(store, FakeScheduler())
    with pytest.raises(ApiError, match="node create fails"):
        svc.load(_snapshot())
    # the earlier barrier group (namespaces) still landed
    assert store.get("namespaces", "team-a")


def test_load_apply_error_collected_with_ignore_err():
    store = FaultyStore()
    store.fail[("create", "nodes")] = ApiError("injected")
    svc = SnapshotService(store, FakeScheduler())
    svc.load(_snapshot(), SnapshotOptions(ignore_err=True))
    # everything except the failing resource applied
    assert store.get("pods", "pod-0", "team-a")
    assert store.get("priorityclasses", "high")
    with pytest.raises(NotFound):
        store.get("nodes", "node-0")


def test_load_tolerates_already_exists():
    store = FaultyStore()
    store.create("nodes", _obj("node-0"))
    svc = SnapshotService(store, FakeScheduler())
    svc.load(_snapshot())  # no raise
    assert store.get("pods", "pod-0", "team-a")


def test_load_scheduler_restart_failure_aborts_before_apply():
    store = FaultyStore()
    svc = SnapshotService(store, FakeScheduler(fail=True))
    with pytest.raises(ApiError):
        svc.load(_snapshot())
    with pytest.raises(NotFound):
        store.get("nodes", "node-0")  # nothing applied


def test_load_ignore_scheduler_configuration_skips_restart():
    store = FaultyStore()
    sched = FakeScheduler(fail=True)  # would raise if called
    svc = SnapshotService(store, sched)
    svc.load(_snapshot(), SnapshotOptions(ignore_scheduler_configuration=True))
    assert sched.restarts == []
    assert store.get("nodes", "node-0")


def test_load_reresolves_pv_claim_uid():
    store = FaultyStore()
    svc = SnapshotService(store, FakeScheduler())
    snap = _snapshot()
    snap["pvs"] = [{
        "metadata": {"name": "pv-0"},
        "spec": {"claimRef": {"name": "claim-0", "namespace": "team-a",
                              "uid": "stale-uid"}},
    }]
    svc.load(snap)
    pv = store.get("persistentvolumes", "pv-0")
    fresh = store.get("persistentvolumeclaims", "claim-0", "team-a")
    assert pv["spec"]["claimRef"]["uid"] == fresh["metadata"]["uid"]
    assert pv["spec"]["claimRef"]["uid"] != "stale-uid"


def test_load_drops_claim_uid_when_pvc_missing():
    store = FaultyStore()
    svc = SnapshotService(store, FakeScheduler())
    snap = _snapshot()
    snap["pvcs"] = []
    snap["pvs"] = [{
        "metadata": {"name": "pv-0"},
        "spec": {"claimRef": {"name": "claim-0", "namespace": "team-a",
                              "uid": "stale-uid"}},
    }]
    svc.load(snap)
    assert "uid" not in store.get("persistentvolumes", "pv-0")["spec"]["claimRef"]


def test_load_skips_system_priority_classes_and_kube_namespaces():
    store = FaultyStore()
    svc = SnapshotService(store, FakeScheduler())
    snap = _snapshot()
    snap["namespaces"].append(_obj("kube-system"))
    snap["priorityClasses"].append(_obj("system-cluster-critical"))
    svc.load(snap)
    with pytest.raises(NotFound):
        store.get("namespaces", "kube-system")
    with pytest.raises(NotFound):
        store.get("priorityclasses", "system-cluster-critical")


# ------------------------------------------------------------- reflector

def _reflector_fixture(conflicts: int):
    store = FaultyStore()
    store.create("pods", _obj("pod-0", namespace="default"))
    rs = ResultStore()
    rs.add_selected_node("default", "pod-0", "node-7")
    refl = StoreReflector(store, sleep=lambda _t: None)
    refl.add_result_store(rs, "k")
    store.conflict_times = conflicts
    return store, rs, refl


def test_reflector_retries_through_transient_conflicts():
    store, rs, refl = _reflector_fixture(conflicts=3)
    refl.reflect("default", "pod-0")
    pod = store.get("pods", "pod-0", "default")
    assert pod["metadata"]["annotations"][ann.SELECTED_NODE] == "node-7"
    # store entry deleted only after the successful write
    assert rs.get_stored_result(pod) is None or ann.SELECTED_NODE not in (
        rs.get_stored_result(pod) or {})


def test_reflector_conflict_exhaustion_keeps_store_data():
    from kube_scheduler_simulator_tpu.utils.retry import RetryTimeout

    store, rs, refl = _reflector_fixture(conflicts=10**6)
    with pytest.raises(RetryTimeout):
        refl.reflect("default", "pod-0")
    # the write never landed and the result data was NOT deleted
    pod = store.get("pods", "pod-0", "default")
    assert not (pod["metadata"].get("annotations") or {})
    assert ann.SELECTED_NODE in (rs.get_stored_result(pod) or {})


def test_reflector_pod_deleted_is_not_an_error():
    store, rs, refl = _reflector_fixture(conflicts=0)
    store.delete("pods", "pod-0", "default")
    refl.reflect("default", "pod-0")  # no raise


def test_result_history_trims_oldest_to_fit_limit():
    pod = _obj("pod-0", namespace="default")
    big = "x" * 60_000
    for i in range(6):
        update_result_history(pod, {"k": f"{i}-{big}"})
    history = json.loads(pod["metadata"]["annotations"][ann.RESULT_HISTORY])
    # 6 x 60KB > 256KiB: the oldest entries were dropped, newest kept
    assert len(history) == 4
    assert history[-1]["k"].startswith("5-")
    assert history[0]["k"].startswith("2-")


def test_result_history_single_oversized_entry_raises():
    pod = _obj("pod-0", namespace="default")
    with pytest.raises(ValueError):
        update_result_history(pod, {"k": "x" * 300_000})


# ----------------------------------------------------------- result store

def test_result_store_empty_pod_returns_nothing():
    rs = ResultStore()
    assert rs.get_stored_result(_obj("ghost", namespace="default")) is None


def test_result_store_isolates_pods_and_delete_data():
    rs = ResultStore()
    rs.add_selected_node("default", "a", "node-1")
    rs.add_selected_node("default", "b", "node-2")
    pa, pb = _obj("a", namespace="default"), _obj("b", namespace="default")
    assert rs.get_stored_result(pa)[ann.SELECTED_NODE] == "node-1"
    rs.delete_data(pa)
    assert rs.get_stored_result(pa) is None
    assert rs.get_stored_result(pb)[ann.SELECTED_NODE] == "node-2"


def test_result_store_final_score_applies_weight():
    rs = ResultStore(score_plugin_weight={"P": 3})
    rs.add_score_result("default", "a", "node-1", "P", 50)
    rs.add_normalized_score_result("default", "a", "node-1", "P", 80)
    out = rs.get_stored_result(_obj("a", namespace="default"))
    assert json.loads(out[ann.SCORE_RESULT])["node-1"]["P"] == "50"
    # finalscore = normalized x weight (resultstore/store.go:488-507)
    assert json.loads(out[ann.FINAL_SCORE_RESULT])["node-1"]["P"] == "240"


def test_reflect_uid_mismatch_drops_stale_record():
    """A pod deleted and recreated under the same name between scheduling
    and reflect must NOT inherit the old record (reference
    storereflector.go:107-109 aborts on UID mismatch)."""
    from kube_scheduler_simulator_tpu.store.reflector import StoreReflector
    from kube_scheduler_simulator_tpu.store.resultstore import ResultStore

    store = ObjectStore()
    store.create("pods", {"metadata": {"name": "p", "namespace": "default"},
                          "spec": {}})
    old_uid = store.get("pods", "p")["metadata"]["uid"]
    rs = ResultStore()
    rs.put_decoded("default", "p", {
        "kube-scheduler-simulator.sigs.k8s.io/selected-node": "n1"})
    refl = StoreReflector(store)
    refl.add_result_store(rs, "k")

    # recreate under the same name -> new uid
    store.delete("pods", "p")
    store.create("pods", {"metadata": {"name": "p", "namespace": "default"},
                          "spec": {}})
    assert store.get("pods", "p")["metadata"]["uid"] != old_uid

    refl.reflect("default", "p", uid=old_uid)
    fresh = store.get("pods", "p")
    assert "kube-scheduler-simulator.sigs.k8s.io/selected-node" not in (
        fresh["metadata"].get("annotations") or {})
    # the stale record was purged: a later reflect (no uid hint) finds
    # nothing to write, so the recreated pod stays uncontaminated
    refl.reflect("default", "p")
    assert "kube-scheduler-simulator.sigs.k8s.io/selected-node" not in (
        store.get("pods", "p")["metadata"].get("annotations") or {})


def test_snap_list_error_aborts_without_ignore_err():
    """snapshot_test.go Snap error tables: a failing kind list fails the
    whole export unless IgnoreErr."""
    from kube_scheduler_simulator_tpu.services.snapshot import SnapshotService

    store = FaultyStore()
    store.create("nodes", {"metadata": {"name": "n1"}, "spec": {}})
    store.create("pods", {"metadata": {"name": "p1", "namespace": "default"},
                          "spec": {}})
    svc = SnapshotService(store, FakeScheduler())
    store.fail[("list", "pods")] = ApiError("injected list failure")
    with pytest.raises(ApiError):
        svc.snap()


def test_snap_list_error_degrades_with_ignore_err():
    """With IgnoreErr the failing kind exports as an empty list and every
    other kind still snapshots (reference snapshot.go:221-227)."""
    from kube_scheduler_simulator_tpu.services.snapshot import (
        SnapshotOptions, SnapshotService)

    store = FaultyStore()
    store.create("nodes", {"metadata": {"name": "n1"}, "spec": {}})
    store.create("pods", {"metadata": {"name": "p1", "namespace": "default"},
                          "spec": {}})
    svc = SnapshotService(store, FakeScheduler())
    store.fail[("list", "pods")] = ApiError("injected list failure")
    snap = svc.snap(SnapshotOptions(ignore_err=True))
    assert snap["pods"] == []
    assert [n["metadata"]["name"] for n in snap["nodes"]] == ["n1"]
    assert "schedulerConfig" in snap


def test_informer_mode_reflects_externally_bound_pod():
    """The reference's informer wiring (storereflector.go:56-81): an
    EXTERNAL bind through the store (no engine reflect() call) still gets
    its stored results written back by the pod-update watcher."""
    import threading
    import time as _time

    from kube_scheduler_simulator_tpu.store.reflector import StoreReflector
    from kube_scheduler_simulator_tpu.store.resultstore import ResultStore

    store = ObjectStore()
    store.create("pods", {"metadata": {"name": "p", "namespace": "default"},
                          "spec": {}})
    rs = ResultStore()
    rs.put_decoded("default", "p", {
        "kube-scheduler-simulator.sigs.k8s.io/selected-node": "n1"})
    refl = StoreReflector(store)
    refl.add_result_store(rs, "k")
    stop = threading.Event()
    refl.register_result_saving_to_informer(stop)
    try:
        # an external scheduler binds the pod via a plain store update
        p = store.get("pods", "p")
        p["spec"]["nodeName"] = "n1"
        store.update("pods", p)
        deadline = _time.time() + 3
        while _time.time() < deadline:
            anns = (store.get("pods", "p")["metadata"].get("annotations")
                    or {})
            if "kube-scheduler-simulator.sigs.k8s.io/selected-node" in anns:
                break
            _time.sleep(0.02)
        anns = store.get("pods", "p")["metadata"].get("annotations") or {}
        assert anns.get(
            "kube-scheduler-simulator.sigs.k8s.io/selected-node") == "n1"
        # store entry deleted after the successful write (reference
        # storereflector.go:156-159): a later unrelated update no-ops
        assert rs.get_stored_result({"metadata": {
            "namespace": "default", "name": "p"}}) is None
    finally:
        stop.set()
        refl.stop_informer()


def test_informer_mode_skips_deleting_pods():
    """The reference's FilterFunc excludes pods carrying a
    deletionTimestamp (storereflector.go:61-68): no result write races a
    graceful deletion.  Deterministic: the pump is one FIFO thread, so
    once a LATER sentinel pod's reflect has landed, the dying pod's event
    has definitely been processed (and must have been skipped)."""
    import threading
    import time as _time

    from kube_scheduler_simulator_tpu.store.reflector import StoreReflector

    SEL = "kube-scheduler-simulator.sigs.k8s.io/selected-node"
    store = ObjectStore()
    for name in ("dying", "sentinel"):
        store.create("pods", {"metadata": {"name": name,
                                           "namespace": "default"},
                              "spec": {}})
    rs = ResultStore()
    rs.put_decoded("default", "dying", {SEL: "n1"})
    rs.put_decoded("default", "sentinel", {SEL: "n2"})
    refl = StoreReflector(store)
    refl.add_result_store(rs, "k")
    stop = threading.Event()
    refl.register_result_saving_to_informer(stop)
    try:
        p = store.get("pods", "dying")
        p["metadata"]["deletionTimestamp"] = "2026-01-01T00:00:00Z"
        p["spec"]["nodeName"] = "n1"
        store.update("pods", p)
        s = store.get("pods", "sentinel")
        s["spec"]["nodeName"] = "n2"
        store.update("pods", s)
        deadline = _time.time() + 5
        while _time.time() < deadline:
            anns = (store.get("pods", "sentinel")["metadata"]
                    .get("annotations") or {})
            if SEL in anns:
                break
            _time.sleep(0.02)
        assert SEL in (store.get("pods", "sentinel")["metadata"]
                       .get("annotations") or {}), "sentinel never reflected"
        anns = store.get("pods", "dying")["metadata"].get("annotations") or {}
        assert SEL not in anns
        # the stored result is NOT consumed either (the reference never
        # reaches the delete-on-success path for filtered pods)
        assert rs.get_stored_result({"metadata": {
            "namespace": "default", "name": "dying"}}) is not None
    finally:
        stop.set()
        refl.stop_informer()


def test_update_result_history_reference_table():
    """The reference's Test_updateResultHistory table
    (storereflector_test.go:83-150) ported verbatim: empty -> one record,
    append preserves order, and the oldest record is trimmed when the
    encoded history exceeds the 256 KiB annotation limit."""
    from kube_scheduler_simulator_tpu.store.reflector import (
        update_result_history)

    HIST = "kube-scheduler-simulator.sigs.k8s.io/result-history"
    pod = {"metadata": {}}
    update_result_history(pod, {"result1": "fuga", "result2": "hoge"})
    assert pod["metadata"]["annotations"][HIST] == \
        '[{"result1":"fuga","result2":"hoge"}]'
    update_result_history(pod, {"result1": "fuga2", "result2": "hoge2"})
    assert pod["metadata"]["annotations"][HIST] == \
        '[{"result1":"fuga","result2":"hoge"},{"result1":"fuga2","result2":"hoge2"}]'

    pod = {"metadata": {"annotations": {HIST: '[{"result":"%s"}]' % ("a" * 200000)}}}
    update_result_history(pod, {"result": "b" * 200000})
    assert pod["metadata"]["annotations"][HIST] == \
        '[{"result":"%s"}]' % ("b" * 200000)


def test_informer_mode_purges_results_of_deleted_pods():
    """A DELETED event purges unreflected store entries so a long-lived
    informer process doesn't leak per-pod result maps (review finding on
    the deletionTimestamp filter; the reference leaks here)."""
    import threading
    import time as _time

    from kube_scheduler_simulator_tpu.store.reflector import StoreReflector

    SEL = "kube-scheduler-simulator.sigs.k8s.io/selected-node"
    store = ObjectStore()
    for name in ("goner", "sentinel"):
        store.create("pods", {"metadata": {"name": name,
                                           "namespace": "default"},
                              "spec": {}})
    rs = ResultStore()
    rs.put_decoded("default", "goner", {SEL: "n1"})
    rs.put_decoded("default", "sentinel", {SEL: "n2"})
    refl = StoreReflector(store)
    refl.add_result_store(rs, "k")
    stop = threading.Event()
    refl.register_result_saving_to_informer(stop)
    try:
        store.delete("pods", "goner", "default")
        s = store.get("pods", "sentinel")
        s["spec"]["nodeName"] = "n2"
        store.update("pods", s)
        deadline = _time.time() + 5
        while _time.time() < deadline:
            anns = (store.get("pods", "sentinel")["metadata"]
                    .get("annotations") or {})
            if SEL in anns:
                break
            _time.sleep(0.02)
        # FIFO pump: sentinel reflected => the DELETED event was handled
        assert rs.get_stored_result({"metadata": {
            "namespace": "default", "name": "goner"}}) is None
    finally:
        stop.set()
        refl.stop_informer()


def test_result_history_broken_annotation_raises():
    """A broken existing result-history errors (reference
    storereflector.go:169-171 surfaces the json.Unmarshal failure) instead
    of silently resetting the history; reflect() downgrades it to
    log-and-continue like the oversized-record case."""
    from kube_scheduler_simulator_tpu.store.reflector import update_result_history

    for broken in ("broken", "{}", '{"a":"b"}', "[1,2", "[oops]",
                   "[truncated", '[{"k":"v"}'):
        pod = {"metadata": {"annotations": {ann.RESULT_HISTORY: broken}}}
        with pytest.raises(ValueError):
            update_result_history(pod, {"k": "v"})
        # the broken value is left in place for inspection
        assert pod["metadata"]["annotations"][ann.RESULT_HISTORY] == broken


def test_reflect_continues_past_broken_history():
    """End-to-end: a pod whose history annotation is corrupt still gets
    its fresh result annotations written back."""
    from kube_scheduler_simulator_tpu.cluster.store import ObjectStore
    from kube_scheduler_simulator_tpu.framework.engine import SchedulerEngine

    s = ObjectStore()
    s.create("nodes", {"metadata": {"name": "n1"},
                       "status": {"allocatable": {"cpu": "4", "memory": "8Gi",
                                                  "pods": "10"}}})
    s.create("pods", {"metadata": {"name": "p1", "namespace": "default",
                                   "annotations": {ann.RESULT_HISTORY: "broken"}},
                      "spec": {"containers": [{"name": "c", "resources": {
                          "requests": {"cpu": "1", "memory": "1Gi"}}}]}})
    eng = SchedulerEngine(s)
    assert eng.schedule_pending() == 1
    pod = s.get("pods", "p1", "default")
    assert pod["spec"]["nodeName"] == "n1"
    assert pod["metadata"]["annotations"][ann.SELECTED_NODE] == "n1"
    assert pod["metadata"]["annotations"][ann.RESULT_HISTORY] == "broken"
