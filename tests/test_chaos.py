"""Tier-2 chaos gate (slow; `make chaos` runs the same harness as a
standalone command with the lock witness armed).

tools/chaos.py drives concurrent multi-session waves under seeded fault
plans covering every seam and asserts the wave-failure-protocol
invariants: completion via retry/degradation, bit-identical annotations
vs the fault-free run, gang atomicity, per-session isolation, and a
consistent session registry under create/evict faults.  A failing seed
reproduces with `python -m tools.chaos --seeds 1 --seed-base <seed>`.
"""

from __future__ import annotations

import pytest

from tools.chaos import FULL_SHAPE, chaos_verdict, run_seed

pytestmark = pytest.mark.slow


def test_chaos_gate_three_seeds():
    verdict = chaos_verdict(seeds=3, seed_base=1)
    assert verdict["ok"], "\n".join(verdict["failures"])
    assert verdict["injected_total"] >= 3, \
        "the plans barely fired — the gate would be vacuous"


def test_chaos_single_seed_reports_failures_shape():
    r = run_seed(11, FULL_SHAPE)
    assert r["ok"], r["failures"]
    assert r["injected"] >= 1
    assert set(r["modes"]) == {"chaos-a", "chaos-b"}
    # the unfaulted neighbor must never have been degraded
    assert r["modes"]["chaos-b"] == "device_resident"
