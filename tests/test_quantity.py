from kube_scheduler_simulator_tpu.utils.quantity import (
    parse_cpu_milli,
    parse_memory_bytes,
    parse_quantity,
)


def test_cpu_milli():
    assert parse_cpu_milli("100m") == 100
    assert parse_cpu_milli("1") == 1000
    assert parse_cpu_milli("1.5") == 1500
    assert parse_cpu_milli("0.1") == 100
    assert parse_cpu_milli(2) == 2000
    assert parse_cpu_milli("2500u") == 3  # ceil of 2.5m


def test_memory_bytes():
    assert parse_memory_bytes("1Ki") == 1024
    assert parse_memory_bytes("1Mi") == 1 << 20
    assert parse_memory_bytes("1.5Gi") == 3 << 29
    assert parse_memory_bytes("100M") == 100_000_000
    assert parse_memory_bytes("128974848") == 128974848
    assert parse_memory_bytes("1k") == 1000


def test_exponent_and_suffix():
    assert parse_quantity("1Gi") == 1 << 30
    assert parse_quantity("500m") * 2 == 1
