"""Scenario-based simulation (KEP-140): step clock, operations, timeline,
phases — against the live engine and over the HTTP API."""

import json
import urllib.request

import pytest

from kube_scheduler_simulator_tpu.cluster.store import ObjectStore
from kube_scheduler_simulator_tpu.framework.engine import SchedulerEngine
from kube_scheduler_simulator_tpu.models.workloads import make_nodes, make_pods
from kube_scheduler_simulator_tpu.scenario import ScenarioService, merge_patch


def _scenario(ops, name="s1"):
    return {"metadata": {"name": name}, "spec": {"operations": ops}}


def _pod(name):
    return {"kind": "Pod", "apiVersion": "v1",
            "metadata": {"name": name}, "spec": {"containers": [
                {"name": "c", "resources": {"requests": {"cpu": "1", "memory": "1Gi"}}}]}}


def test_merge_patch_rfc7386():
    assert merge_patch({"a": 1, "b": {"c": 2}}, {"b": {"c": None, "d": 3}}) == \
        {"a": 1, "b": {"d": 3}}
    assert merge_patch({"a": 1}, {"a": [1, 2]}) == {"a": [1, 2]}
    assert merge_patch("x", {"a": 1}) == {"a": 1}


def test_scenario_steps_schedule_and_succeed():
    store = ObjectStore()
    engine = SchedulerEngine(store)
    svc = ScenarioService(store, engine)

    node = make_nodes(1, seed=40)[0]
    ops = [
        {"step": 0, "createOperation": {"object": node}},
        {"step": 0, "createOperation": {"object": _pod("p0")}},
        {"step": 1, "createOperation": {"object": _pod("p1")}},
        {"step": 1, "doneOperation": {}},
    ]
    svc.create(_scenario(ops), run=False)
    sc = svc.run("s1")

    assert sc["status"]["phase"] == "Succeeded"
    tl = sc["status"]["scenarioResult"]["timeline"]
    # step 0: node + pod creates + a generated PodScheduled event
    kinds0 = [next(k for k in e if k not in ("id", "step")) for e in tl["0"]]
    assert kinds0.count("create") == 2 and "podScheduled" in kinds0
    sched0 = [e for e in tl["0"] if "podScheduled" in e][0]
    assert sched0["podScheduled"]["pod"] == "default/p0"
    assert sched0["podScheduled"]["node"] == node["metadata"]["name"]
    # step 1: create + done + another PodScheduled
    assert any("done" in e for e in tl["1"])
    assert any("podScheduled" in e for e in tl["1"])
    # both pods actually bound in the store
    for pname in ("p0", "p1"):
        assert store.get("pods", pname, "default")["spec"].get("nodeName")


def test_scenario_patch_delete_and_paused():
    store = ObjectStore()
    svc = ScenarioService(store)  # no engine: pure state manipulation
    node = make_nodes(1, seed=41)[0]
    ops = [
        {"step": 0, "createOperation": {"object": node}},
        {"step": 1, "patchOperation": {
            "typeMeta": {"kind": "Node"},
            "objectMeta": {"name": node["metadata"]["name"]},
            "patch": json.dumps({"metadata": {"labels": {"zone": "z9"}}}),
        }},
        {"step": 2, "deleteOperation": {
            "typeMeta": {"kind": "Node"},
            "objectMeta": {"name": node["metadata"]["name"]},
        }},
    ]
    svc.create(_scenario(ops), run=False)
    sc = svc.run("s1")
    # no doneOperation -> Paused (more operations may be added)
    assert sc["status"]["phase"] == "Paused"
    tl = sc["status"]["scenarioResult"]["timeline"]
    assert tl["1"][0]["patch"]["result"]["metadata"]["labels"]["zone"] == "z9"
    assert "delete" in tl["2"][0]
    assert store.list("nodes")[0] == []


def test_scenario_invalid_operation_fails():
    store = ObjectStore()
    svc = ScenarioService(store)
    svc.create(_scenario([{"step": 0}]), run=False)  # no op field set
    sc = svc.run("s1")
    assert sc["status"]["phase"] == "Failed"
    assert "exactly one" in sc["status"]["message"]

    svc.create(_scenario([{"step": 0, "createOperation": {"object": _pod("x")},
                           "doneOperation": {}}], name="s2"), run=False)
    assert svc.run("s2")["status"]["phase"] == "Failed"


def test_scenario_delete_cancels_and_recreate_is_clean():
    """Deleting a running scenario orphans its worker: the old thread
    neither applies further operations nor writes into a recreated
    same-name scenario."""
    import threading
    import time

    store = ObjectStore()
    svc = ScenarioService(store)
    gate = threading.Event()

    class GateStore:
        """Store proxy whose create blocks until released."""
        def __getattr__(self, a):
            return getattr(store, a)
        def create(self, resource, obj, **kwargs):
            gate.wait(5)
            return store.create(resource, obj, **kwargs)

    svc.store = GateStore()
    node1 = make_nodes(2, seed=43)[0]
    node2 = make_nodes(2, seed=43)[1]
    svc.create(_scenario([
        {"step": 0, "createOperation": {"object": node1}},
        {"step": 1, "createOperation": {"object": node2}},
    ], name="doomed"))
    t = svc._threads["doomed"]
    svc.delete("doomed")          # while the worker blocks in step 0
    fresh = svc.create(_scenario([], name="doomed"), run=False)
    gate.set()
    t.join(10)
    final = svc.run("doomed")
    # the recreated scenario is untouched by the old worker
    assert final["status"]["phase"] == "Paused"
    assert final["status"]["scenarioResult"]["timeline"] == {}
    # the old worker stopped at the first step boundary: step-1 node never
    # created (step-0's in-flight create may have completed)
    deadline = time.time() + 2
    while time.time() < deadline:
        time.sleep(0.05)
    names = [n["metadata"]["name"] for n in store.list("nodes")[0]]
    assert node2["metadata"]["name"] not in names


def test_scenario_http_api():
    from kube_scheduler_simulator_tpu.config.config import SimulatorConfiguration
    from kube_scheduler_simulator_tpu.server.di import DIContainer
    from kube_scheduler_simulator_tpu.server.server import SimulatorServer

    di = DIContainer(SimulatorConfiguration(port=0))
    srv = SimulatorServer(di, port=0)
    srv.start(block=False)
    try:
        base = f"http://127.0.0.1:{srv.port}/api/v1/scenarios"
        node = make_nodes(1, seed=42)[0]
        body = json.dumps(_scenario([
            {"step": 0, "createOperation": {"object": node}},
            {"step": 0, "createOperation": {"object": _pod("hp")}},
            {"step": 0, "doneOperation": {}},
        ], name="web")).encode()
        req = urllib.request.Request(base, data=body, method="POST",
                                     headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=10) as r:
            assert r.status == 201
        di.scenario_service.wait("web")
        with urllib.request.urlopen(f"{base}/web", timeout=10) as r:
            sc = json.load(r)
        assert sc["status"]["phase"] == "Succeeded"
        with urllib.request.urlopen(base, timeout=10) as r:
            assert len(json.load(r)["items"]) == 1
    finally:
        srv.shutdown()


def test_done_operation_skips_later_steps():
    """doneOperation ends the scenario at its step's boundary — later
    majors never run (KEP-140 done semantics)."""
    store = ObjectStore()
    engine = SchedulerEngine(store)
    svc = ScenarioService(store, engine)
    node = make_nodes(1, seed=44)[0]
    ops = [
        {"step": 0, "createOperation": {"object": node}},
        {"step": 0, "doneOperation": {}},
        {"step": 3, "createOperation": {"object": _pod("never")}},
    ]
    svc.create(_scenario(ops, name="sdone"), run=False)
    sc = svc.run("sdone")
    assert sc["status"]["phase"] == "Succeeded"
    import pytest as _pytest

    from kube_scheduler_simulator_tpu.cluster.store import NotFound
    with _pytest.raises(NotFound):
        store.get("pods", "never", "default")
    assert "3" not in sc["status"]["scenarioResult"]["timeline"]


def test_sparse_major_steps_execute_in_sorted_order():
    """Step majors need not be contiguous; execution is ordered by major
    and the step clock reflects each boundary."""
    store = ObjectStore()
    engine = SchedulerEngine(store)
    svc = ScenarioService(store, engine)
    node = make_nodes(1, seed=45)[0]
    ops = [
        {"step": 7, "createOperation": {"object": _pod("late")}},
        {"step": 0, "createOperation": {"object": node}},
        {"step": 2, "createOperation": {"object": _pod("mid")}},
    ]
    svc.create(_scenario(ops, name="ssparse"), run=False)
    sc = svc.run("ssparse")
    # no doneOperation: the scenario PAUSES after its last step (KEP-140)
    assert sc["status"]["phase"] == "Paused"
    tl = sc["status"]["scenarioResult"]["timeline"]
    assert sorted(tl, key=int) == ["0", "2", "7"]
    # the controller ran to quiescence after each step: both pods bound
    assert store.get("pods", "mid", "default")["spec"].get("nodeName")
    assert store.get("pods", "late", "default")["spec"].get("nodeName")
    assert sc["status"]["stepStatus"]["step"]["major"] == 7
