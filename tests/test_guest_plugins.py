"""Guest plugins (the wasm-extension analogue, scheduler/guest.py):
config-declared out-of-tree plugins loaded from a file at restart, parity
with reference RegisterWasmPlugins semantics (wasm.go:14-58)."""

import json

import pytest

from kube_scheduler_simulator_tpu.cluster.store import ObjectStore
from kube_scheduler_simulator_tpu.framework.engine import SchedulerEngine
from kube_scheduler_simulator_tpu.models.workloads import make_nodes, make_pods
from kube_scheduler_simulator_tpu.scheduler.guest import collect_guest_plugins
from kube_scheduler_simulator_tpu.scheduler.service import SchedulerService
from kube_scheduler_simulator_tpu.store import annotations as ann

GUEST_SRC = '''
from kube_scheduler_simulator_tpu.plugins.custom import CustomPlugin

class Plugin(CustomPlugin):
    default_weight = 1
    def filter(self, pod, node):
        idx = int(node["metadata"]["name"].rsplit("-", 1)[1])
        return None if idx == 0 else "guest says no"
'''

GUEST_FACTORY_SRC = '''
from kube_scheduler_simulator_tpu.plugins.custom import CustomPlugin

def plugin(name, args):
    class P(CustomPlugin):
        def score(self, pod, node):
            return int(args.get("bonus", 0))
    return P()
'''


def _cfg_with_guest(path, name="MyGuest", enabled=True, args_extra=None):
    mp = {"enabled": ([{"name": name}] if enabled else [])}
    return {
        "apiVersion": "kubescheduler.config.k8s.io/v1",
        "kind": "KubeSchedulerConfiguration",
        "profiles": [{
            "schedulerName": "default-scheduler",
            "plugins": {"multiPoint": mp},
            "pluginConfig": [
                {"name": name,
                 "args": {"guestURL": str(path), **(args_extra or {})}},
            ],
        }],
    }


def test_collect_only_enabled(tmp_path):
    guest = tmp_path / "guest.py"
    guest.write_text(GUEST_SRC)
    out = collect_guest_plugins(_cfg_with_guest(guest, enabled=True))
    assert list(out) == ["MyGuest"] and out["MyGuest"].name == "MyGuest"
    # not multiPoint-enabled -> not registered (wasm.go:46-55)
    assert collect_guest_plugins(_cfg_with_guest(guest, enabled=False)) == {}
    # non-guest pluginConfig entries are skipped, not errors
    assert collect_guest_plugins({"profiles": [{"pluginConfig": [
        {"name": "NodeResourcesFit", "args": {"scoringStrategy": {}}}]}]}) == {}


def test_guest_factory_and_args(tmp_path):
    guest = tmp_path / "guest_factory.py"
    guest.write_text(GUEST_FACTORY_SRC)
    out = collect_guest_plugins(
        _cfg_with_guest(guest, name="Bonus", args_extra={"bonus": 7}))
    p = out["Bonus"]
    assert p.name == "Bonus" and p.score({}, {}) == 7 and p.has_score


def test_network_guest_url_rejected(tmp_path):
    cfg = _cfg_with_guest("http://evil.example/p.py")
    with pytest.raises(ValueError, match="file"):
        collect_guest_plugins(cfg)


def test_guest_end_to_end_and_rollback(tmp_path):
    guest = tmp_path / "guest.py"
    guest.write_text(GUEST_SRC)

    store = ObjectStore()
    engine = SchedulerEngine(store)
    svc = SchedulerService(engine)
    svc.restart_scheduler(_cfg_with_guest(guest))
    assert "MyGuest" in engine.plugin_config.enabled

    for n in make_nodes(3, seed=30):
        store.create("nodes", n)
    pod = make_pods(1, seed=31)[0]
    store.create("pods", pod)
    assert engine.schedule_pending() == 1
    got = store.get("pods", pod["metadata"]["name"], pod["metadata"].get("namespace"))
    # guest vetoes all but node 0, and its message lands in filter-result
    assert got["spec"]["nodeName"] == "node-00000"
    fr = json.loads(got["metadata"]["annotations"][ann.FILTER_RESULT])
    assert fr["node-00001"]["MyGuest"] == "guest says no"

    # a broken guest path fails the restart and rolls back (scheduler.go:102-108)
    with pytest.raises(Exception):
        svc.restart_scheduler(_cfg_with_guest(tmp_path / "missing.py"))
    assert "MyGuest" in engine.plugin_config.enabled
    pcs = {p["name"]: p["args"]
           for p in svc.get_config()["profiles"][0]["pluginConfig"]}
    assert pcs["MyGuest"]["guestURL"] == str(guest)
