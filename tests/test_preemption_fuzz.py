"""Preemption chain fuzz: random tight clusters with mixed priorities,
checked against upstream invariants on the END STATE rather than an
oracle (the scalar oracle does not model PostFilter):

  1. capacity: every node's bound pods fit its allocatable (cpu, memory,
     pod count) — binds and victim evictions never oversubscribe;
  2. priority: every evicted victim had strictly lower priority than
     some pod that still wanted a node at eviction time (upstream
     DefaultPreemption only preempts lower-priority pods,
     pkg/scheduler/framework/preemption);
  3. records: a preemptor that got a nomination carries the
     postfilter-result "preemption victim" message on its nominated
     node and eventually binds there or stays nominated.
"""

import json

import numpy as np
import pytest

from kube_scheduler_simulator_tpu.cluster.store import ObjectStore
from kube_scheduler_simulator_tpu.framework.engine import SchedulerEngine
from kube_scheduler_simulator_tpu.plugins.registry import PluginSetConfig
from kube_scheduler_simulator_tpu.store import annotations as ann

MILLI = {"cpu": 1000}


def _cpu_m(v: str) -> int:
    return int(float(v[:-1])) if v.endswith("m") else int(float(v) * 1000)


def _mem_b(v: str) -> int:
    units = {"Ki": 1 << 10, "Mi": 1 << 20, "Gi": 1 << 30}
    for u, m in units.items():
        if v.endswith(u):
            return int(float(v[: -len(u)]) * m)
    return int(float(v))


def _requests(pod):
    cpu = mem = 0
    for c in pod["spec"].get("containers", []):
        r = (c.get("resources") or {}).get("requests") or {}
        cpu += _cpu_m(r.get("cpu", "0"))
        mem += _mem_b(r.get("memory", "0"))
    return cpu, mem


@pytest.mark.parametrize("seed", [5, 17, 29])
def test_preemption_chain_invariants(seed):
    rng = np.random.default_rng(seed)
    store = ObjectStore()
    n_nodes = int(rng.integers(3, 6))
    for j in range(n_nodes):
        store.create("nodes", {
            "metadata": {"name": f"n{j}"},
            "status": {"allocatable": {"cpu": "4", "memory": "8Gi",
                                       "pods": "6"}}})
    engine = SchedulerEngine(store, plugin_config=PluginSetConfig(
        enabled=["NodeResourcesFit", "NodeResourcesBalancedAllocation",
                 "DefaultPreemption"]))

    deleted: list[str] = []
    q = store.watch("pods")

    def drain_deletes():
        while not q.empty():
            _, et, obj = q.get()
            if et == "DELETED":
                deleted.append(obj["metadata"]["name"])

    # low-priority filler that mostly fills the cluster
    pods_by_name = {}
    for i in range(n_nodes * 3):
        p = {"metadata": {"name": f"low-{i}"},
             "spec": {"priority": 0, "containers": [{"name": "c", "resources": {
                 "requests": {"cpu": "1", "memory": "1Gi"}}}]}}
        pods_by_name[p["metadata"]["name"]] = p
        store.create("pods", p)
    engine.schedule_pending()
    drain_deletes()
    assert not deleted  # same priority: nothing to preempt

    # high-priority arrivals that cannot fit without evictions
    for i in range(n_nodes):
        p = {"metadata": {"name": f"high-{i}"},
             "spec": {"priority": 100, "containers": [{"name": "c", "resources": {
                 "requests": {"cpu": "3", "memory": "2Gi"}}}]}}
        pods_by_name[p["metadata"]["name"]] = p
        store.create("pods", p)
    engine.schedule_pending()
    drain_deletes()

    pods, _ = store.list("pods")
    by_node: dict[str, list] = {}
    for p in pods:
        nn = p["spec"].get("nodeName")
        if nn:
            by_node.setdefault(nn, []).append(p)

    # 1. capacity invariant on the end state
    for nn, bound in by_node.items():
        node = store.get("nodes", nn)
        alloc = node["status"]["allocatable"]
        cpu = sum(_requests(p)[0] for p in bound)
        mem = sum(_requests(p)[1] for p in bound)
        assert cpu <= _cpu_m(alloc["cpu"]), f"{nn} cpu oversubscribed"
        assert mem <= _mem_b(alloc["memory"]), f"{nn} memory oversubscribed"
        assert len(bound) <= int(alloc["pods"])

    # 2. only the low-priority filler may have been evicted
    assert deleted, "tight cluster with priority gap must preempt"
    for name in deleted:
        assert name.startswith("low-"), f"evicted {name} (priority 100?)"

    # 3. every high pod either bound or carries a nomination + postfilter
    #    record from its preemption attempt
    for i in range(n_nodes):
        p = store.get("pods", f"high-{i}", "default")
        a = p["metadata"].get("annotations", {})
        if p["spec"].get("nodeName"):
            continue
        nominated = (p.get("status") or {}).get("nominatedNodeName")
        if nominated:
            pf = json.loads(a[ann.POST_FILTER_RESULT])
            assert pf.get(nominated, {}).get("DefaultPreemption") == \
                "preemption victim"
