"""Tracing/metrics subsystem: span aggregation, counters, Prometheus
exposition, engine instrumentation, HTTP endpoints."""

import json
import urllib.request

from kube_scheduler_simulator_tpu.cluster.store import ObjectStore
from kube_scheduler_simulator_tpu.framework.engine import SchedulerEngine
from kube_scheduler_simulator_tpu.models.workloads import make_nodes, make_pods
from kube_scheduler_simulator_tpu.utils.tracing import TRACER, Tracer


def test_tracer_spans_and_counters():
    t = Tracer()
    with t.span("phase", pods=3):
        pass
    with t.span("phase"):
        pass
    t.count("things_total", 5)
    s = t.summary()
    assert s["spans"]["phase"]["count"] == 2
    assert s["spans"]["phase"]["total_seconds"] >= 0
    assert s["counters"]["things_total"] == 5
    text = t.prometheus_text()
    assert "kss_tpu_things_total 5" in text
    assert "kss_tpu_span_phase_count 2" in text
    assert t.events()[-1]["name"] == "phase"
    t.reset()
    assert t.summary() == {"spans": {}, "counters": {}}


def test_engine_emits_spans_and_counts():
    TRACER.reset()
    store = ObjectStore()
    engine = SchedulerEngine(store)
    for n in make_nodes(2, seed=70):
        store.create("nodes", n)
    for p in make_pods(3, seed=71):
        store.create("pods", p)
    engine.schedule_pending()
    s = TRACER.summary()
    for span in ("compile_workload", "replay_and_decode_stream",
                 "commit_and_reflect"):
        assert s["spans"][span]["count"] >= 1, span
    assert s["counters"]["pods_scheduled_total"] == 3
    assert s["counters"]["scheduling_waves_total"] >= 1


def test_metrics_http_endpoints():
    from kube_scheduler_simulator_tpu.config.config import SimulatorConfiguration
    from kube_scheduler_simulator_tpu.server.di import DIContainer
    from kube_scheduler_simulator_tpu.server.server import SimulatorServer

    di = DIContainer(SimulatorConfiguration(port=0), start_scheduler=False)
    srv = SimulatorServer(di, port=0)
    srv.start(block=False)
    try:
        base = f"http://127.0.0.1:{srv.port}"
        with urllib.request.urlopen(base + "/api/v1/metrics", timeout=10) as r:
            s = json.load(r)
            assert "spans" in s and "counters" in s
        with urllib.request.urlopen(base + "/metrics", timeout=10) as r:
            assert r.headers["Content-Type"].startswith("text/plain")
            r.read()
        with urllib.request.urlopen(base + "/healthz", timeout=10) as r:
            assert r.status == 200
        # scheduler loop not started -> not ready
        try:
            urllib.request.urlopen(base + "/readyz", timeout=10)
            assert False, "expected 503"
        except urllib.error.HTTPError as e:
            assert e.code == 503
        req = urllib.request.Request(
            base + "/api/v1/profile", data=json.dumps({"action": "nope"}).encode(),
            method="POST", headers={"Content-Type": "application/json"})
        try:
            urllib.request.urlopen(req, timeout=10)
            assert False, "expected 400"
        except urllib.error.HTTPError as e:
            assert e.code == 400
    finally:
        srv.shutdown()
