"""Tracing/metrics subsystem: span aggregation, counters, Prometheus
exposition, engine instrumentation, HTTP endpoints."""

import json
import urllib.request

from kube_scheduler_simulator_tpu.cluster.store import ObjectStore
from kube_scheduler_simulator_tpu.framework.engine import SchedulerEngine
from kube_scheduler_simulator_tpu.models.workloads import make_nodes, make_pods
from kube_scheduler_simulator_tpu.utils.tracing import TRACER, Tracer


def test_tracer_spans_and_counters():
    t = Tracer()
    with t.span("phase", pods=3):
        pass
    with t.span("phase"):
        pass
    t.count("things_total", 5)
    s = t.summary()
    assert s["spans"]["phase"]["count"] == 2
    assert s["spans"]["phase"]["total_seconds"] >= 0
    assert s["counters"]["things_total"] == 5
    text = t.prometheus_text()
    assert "kss_tpu_things_total 5" in text
    assert "kss_tpu_span_phase_count 2" in text
    assert t.events()[-1]["name"] == "phase"
    t.reset()
    assert t.summary() == {"spans": {}, "counters": {}}


def test_event_ring_counts_drops():
    """A full span ring must not lose its tail silently: every evicted
    event counts in tracer_events_dropped_total, surfaced by summary()
    and the Prometheus exposition."""
    t = Tracer(capacity=4)
    for _ in range(4):
        with t.span("s"):
            pass
    assert "tracer_events_dropped_total" not in t.summary()["counters"]
    for _ in range(3):
        with t.span("s"):
            pass
    assert t.summary()["counters"]["tracer_events_dropped_total"] == 3
    assert "kss_tpu_tracer_events_dropped_total 3" in t.prometheus_text()
    assert len(t.events(limit=100)) == 4  # the ring itself stays bounded


def test_gauge_session_scope_and_labels():
    """Gauges honor the session scope (mirrored into the per-session
    snapshot view) and accept labels (the HBM sampler's per-device
    series), folding the active session label in like inc() does."""
    from kube_scheduler_simulator_tpu.utils.tracing import validate_exposition

    t = Tracer()
    t.gauge("plain_g", 7)
    with t.session_scope("sa"):
        t.gauge("scoped_g", 3)
        t.gauge("labeled_g", 11, device="0")
    with t.session_scope("sb"):
        t.gauge("scoped_g", 5)
    snap = t.snapshot()
    assert snap["gauges"]["plain_g"] == 7
    assert snap["gauges"]["scoped_g"] == 5  # last write wins aggregate
    assert snap["labeled_gauges"]["labeled_g"] == [
        {"labels": {"device": "0", "session": "sa"}, "value": 11}]
    sa = t.snapshot(session="sa")
    assert sa["gauges"]["scoped_g"] == 3
    assert sa["gauges"]["labeled_g"] == 11
    assert sa["labeled_gauges"]["labeled_g"][0]["value"] == 11
    sb = t.snapshot(session="sb")
    assert sb["gauges"] == {"scoped_g": 5}
    assert "labeled_g" not in sb["labeled_gauges"]
    # one family per gauge name even when plain + labeled series mix
    t.gauge("labeled_g", 20)
    fams = validate_exposition(t.prometheus_text())
    assert fams["kss_tpu_labeled_g"]["type"] == "gauge"
    assert len(fams["kss_tpu_labeled_g"]["samples"]) == 2


def test_open_spans_and_time_split():
    t = Tracer()
    with t.span("replay_and_decode_stream"):
        with t.span("inner"):
            open_now = t.open_spans()
    names = [s["name"] for s in open_now]
    assert names == ["replay_and_decode_stream", "inner"]
    assert all(s["seconds_so_far"] >= 0 for s in open_now)
    assert t.open_spans() == []
    with t.span("commit_and_reflect"):
        pass
    split = t.time_split()
    assert split["waves"] == 1
    assert split["device_window_seconds"] >= 0
    assert split["host_seconds"] >= 0
    assert "time_split" in t.snapshot()


def test_engine_emits_spans_and_counts():
    TRACER.reset()
    store = ObjectStore()
    engine = SchedulerEngine(store)
    for n in make_nodes(2, seed=70):
        store.create("nodes", n)
    for p in make_pods(3, seed=71):
        store.create("pods", p)
    engine.schedule_pending()
    s = TRACER.summary()
    for span in ("compile_workload", "replay_and_decode_stream",
                 "commit_and_reflect"):
        assert s["spans"][span]["count"] >= 1, span
    assert s["counters"]["pods_scheduled_total"] == 3
    assert s["counters"]["scheduling_waves_total"] >= 1


def test_metrics_http_endpoints():
    from kube_scheduler_simulator_tpu.config.config import SimulatorConfiguration
    from kube_scheduler_simulator_tpu.server.di import DIContainer
    from kube_scheduler_simulator_tpu.server.server import SimulatorServer

    di = DIContainer(SimulatorConfiguration(port=0), start_scheduler=False)
    srv = SimulatorServer(di, port=0)
    srv.start(block=False)
    try:
        base = f"http://127.0.0.1:{srv.port}"
        with urllib.request.urlopen(base + "/api/v1/metrics", timeout=10) as r:
            s = json.load(r)
            assert "spans" in s and "counters" in s
        with urllib.request.urlopen(base + "/metrics", timeout=10) as r:
            assert r.headers["Content-Type"].startswith("text/plain")
            r.read()
        with urllib.request.urlopen(base + "/healthz", timeout=10) as r:
            assert r.status == 200
        # scheduler loop not started -> not ready
        try:
            urllib.request.urlopen(base + "/readyz", timeout=10)
            assert False, "expected 503"
        except urllib.error.HTTPError as e:
            assert e.code == 503
        req = urllib.request.Request(
            base + "/api/v1/profile", data=json.dumps({"action": "nope"}).encode(),
            method="POST", headers={"Content-Type": "application/json"})
        try:
            urllib.request.urlopen(req, timeout=10)
            assert False, "expected 400"
        except urllib.error.HTTPError as e:
            assert e.code == 400
    finally:
        srv.shutdown()
