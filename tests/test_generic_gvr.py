"""Generic GVR registration: config-declared extra resource kinds ride the
store, applier, importer, syncer, recorder, watcher, snapshot and HTTP
CRUD — the declarative RESTMapper analogue of the reference's dynamic
client (reference: resourceapplier/resourceapplier.go:91-194,268-276;
round-3 verdict missing #4)."""

from __future__ import annotations

import json

from kube_scheduler_simulator_tpu.cluster.store import NotFound, ObjectStore
from kube_scheduler_simulator_tpu.config.config import SimulatorConfiguration
from kube_scheduler_simulator_tpu.server.di import DIContainer
from kube_scheduler_simulator_tpu.services.importer import OneShotImporter
from kube_scheduler_simulator_tpu.services.resourceapplier import ResourceApplier
from kube_scheduler_simulator_tpu.services.snapshot import SnapshotService

FOO_GVR = {"resource": "foos", "kind": "Foo",
           "namespaced": True, "apiVersion": "example.com/v1"}


def _foo(name: str, spec=None) -> dict:
    return {"metadata": {"name": name, "namespace": "default"},
            "spec": spec or {"width": 3}}


def test_store_crud_watch_for_registered_kind():
    store = ObjectStore(extra_resources=[FOO_GVR])
    q = store.watch("foos")
    created = store.create("foos", _foo("a"))
    assert created["kind"] == "Foo"
    assert created["apiVersion"] == "example.com/v1"
    rv, ev, obj = q.get(timeout=1)
    assert ev == "ADDED" and obj["metadata"]["name"] == "a"
    got = store.get("foos", "a", "default")
    got["spec"]["width"] = 5
    store.update("foos", got)
    items, _ = store.list("foos")
    assert items[0]["spec"]["width"] == 5
    store.delete("foos", "a", "default")
    import pytest

    with pytest.raises(NotFound):
        store.get("foos", "a", "default")


def test_unregistered_kind_stays_unknown():
    import pytest

    store = ObjectStore()
    with pytest.raises(NotFound):
        store.create("foos", _foo("a"))
    with pytest.raises(NotFound):
        store.list("foos")


def test_crd_roundtrips_import_to_export_untouched():
    """A registered CRD object imports from a source cluster, is never
    touched by scheduling, and exports byte-identical spec via snapshot."""
    source = ObjectStore(extra_resources=[FOO_GVR])
    source.create("nodes", {"metadata": {"name": "n1"},
                            "status": {"allocatable": {"cpu": "4", "memory": "8Gi",
                                                       "pods": "10"}}})
    source.create("foos", _foo("imported", {"nested": {"a": [1, 2, 3]}}))

    dest = ObjectStore(extra_resources=[FOO_GVR])
    applier = ResourceApplier(dest)
    importer = OneShotImporter(source, applier,
                               resources=["nodes", "foos"])
    n = importer.import_cluster_resources()
    assert n == 2

    class _Sched:
        def get_config(self):
            return {"profiles": []}

        def restart_scheduler(self, cfg):
            pass

    snap = SnapshotService(dest, _Sched()).snap()
    assert [o["metadata"]["name"] for o in snap["foos"]] == ["imported"]
    assert snap["foos"][0]["spec"] == {"nested": {"a": [1, 2, 3]}}

    # load into a third cluster: the CRD comes back
    third = ObjectStore(extra_resources=[FOO_GVR])
    SnapshotService(third, _Sched()).load(snap)
    assert third.get("foos", "imported", "default")["spec"] == \
        {"nested": {"a": [1, 2, 3]}}


def test_dump_restore_carries_extras_and_infers_registration():
    store = ObjectStore(extra_resources=[FOO_GVR])
    store.create("foos", _foo("x"))
    kvs = store.dump()
    fresh = ObjectStore()  # no registration: restore infers it
    fresh.restore(kvs)
    assert fresh.get("foos", "x", "default")["spec"]["width"] == 3
    assert fresh.resources["foos"] == ("Foo", True)


def test_di_and_http_crud_for_extra_resource():
    cfg = SimulatorConfiguration(extra_resources=[FOO_GVR])
    di = DIContainer(cfg, start_scheduler=False)
    try:
        assert "foos" in di.store.resources
        assert "foos" in di.watcher_service.resources
        # HTTP CRUD routes through the store registry
        import urllib.request

        from kube_scheduler_simulator_tpu.server.server import SimulatorServer

        srv = SimulatorServer(di, port=0)
        srv.start(block=False)
        base = f"http://localhost:{srv.port}/api/v1/foos"
        try:
            req = urllib.request.Request(
                base, data=json.dumps(_foo("via-http")).encode(),
                method="POST", headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=5) as r:
                created = json.loads(r.read())
            assert created["kind"] == "Foo"
            with urllib.request.urlopen(f"{base}/default/via-http", timeout=5) as r:
                got = json.loads(r.read())
            assert got["spec"]["width"] == 3
        finally:
            srv.httpd.shutdown()
    finally:
        di.shutdown()


def test_recorder_records_extra_resource(tmp_path):
    cfg = SimulatorConfiguration(extra_resources=[FOO_GVR])
    di = DIContainer(cfg, start_scheduler=False)
    try:
        rec = di.new_recorder(str(tmp_path / "rec.jsonl"), flush_interval=0.05)
        rec.run()
        di.store.create("foos", _foo("recorded"))
        import time

        time.sleep(0.3)
        rec.stop()
        lines = [json.loads(ln) for ln in
                 open(tmp_path / "rec.jsonl").read().splitlines() if ln]
        assert any((r.get("resource") or {}).get("kind") == "Foo"
                   for r in lines), lines
    finally:
        di.shutdown()


def test_watch_stream_carries_extra_gvr():
    """list_watch must resolve extra kinds via the store registry, not the
    module table (review finding: KeyError broke the stream for ALL
    resources when any extra GVR was configured)."""
    import threading

    from kube_scheduler_simulator_tpu.services.resourcewatcher import (
        ResourceWatcherService, StreamWriter)

    store = ObjectStore(extra_resources=[FOO_GVR])
    store.create("foos", _foo("streamed"))
    svc = ResourceWatcherService(store, resources=["nodes", "foos"])
    got: list[bytes] = []
    stream = StreamWriter(got.append)
    stop = threading.Event()
    t = threading.Thread(target=svc.list_watch, args=(stream, None, stop),
                         daemon=True)
    t.start()
    import time

    time.sleep(0.3)
    stop.set()
    t.join(timeout=2)
    text = b"".join(got).decode()
    assert '"kind":"Foo"' in text or '"kind": "Foo"' in text, text[:400]


def test_import_skips_gvr_absent_at_source():
    """A CRD registered in the simulator but not installed at the source
    must not abort the import (review finding: NotFound propagated)."""
    source = ObjectStore()  # no foos here
    source.create("nodes", {"metadata": {"name": "n1"},
                            "status": {"allocatable": {"cpu": "4", "memory": "8Gi",
                                                       "pods": "10"}}})
    dest = ObjectStore(extra_resources=[FOO_GVR])
    n = OneShotImporter(source, ResourceApplier(dest),
                        resources=["nodes", "foos"]).import_cluster_resources()
    assert n == 1


def test_syncer_skips_gvr_absent_at_source():
    from kube_scheduler_simulator_tpu.services.syncer import SyncerService

    source = ObjectStore()
    dest = ObjectStore(extra_resources=[FOO_GVR])
    sync = SyncerService(source, ResourceApplier(dest),
                         resources=["nodes", "foos"])
    sync.run()  # must not raise
    sync.stop()


def test_load_registers_unknown_snapshot_gvrs():
    """Loading a snapshot that carries a GVR the target store has not
    registered must register + apply it, not silently drop it (review
    finding)."""

    class _Sched:
        def get_config(self):
            return {"profiles": []}

        def restart_scheduler(self, cfg):
            pass

    src = ObjectStore(extra_resources=[FOO_GVR])
    src.create("foos", _foo("carried"))
    snap = SnapshotService(src, _Sched()).snap()

    plain = ObjectStore()  # no registration
    SnapshotService(plain, _Sched()).load(snap)
    assert plain.get("foos", "carried", "default")["spec"]["width"] == 3
    assert plain.resources["foos"] == ("Foo", True)
