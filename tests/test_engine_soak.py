"""Randomized end-to-end engine soak: random nodes/pods, config
churn (weights, point overrides, profiles), repeated waves — asserting the
invariants that hold regardless of workload:

  * schedule_pending never raises;
  * every bound pod's node exists and its filter-result shows no failure
    message for the chosen node;
  * every annotation blob parses as JSON with the exact key set;
  * unschedulable pods carry the PodScheduled=False condition;
  * node capacity is never exceeded by the bound set.
"""

import json

import numpy as np
import pytest

from kube_scheduler_simulator_tpu.cluster.store import ObjectStore
from kube_scheduler_simulator_tpu.framework.engine import SchedulerEngine
from kube_scheduler_simulator_tpu.models.workloads import make_nodes, make_pods
from kube_scheduler_simulator_tpu.scheduler.service import SchedulerService
from kube_scheduler_simulator_tpu.store import annotations as ann
from kube_scheduler_simulator_tpu.utils.quantity import parse_quantity

ALL_KEYS = {
    ann.PRE_FILTER_STATUS_RESULT, ann.PRE_FILTER_RESULT, ann.FILTER_RESULT,
    ann.POST_FILTER_RESULT, ann.PRE_SCORE_RESULT, ann.SCORE_RESULT,
    ann.FINAL_SCORE_RESULT, ann.RESERVE_RESULT, ann.PERMIT_STATUS_RESULT,
    ann.PERMIT_TIMEOUT_RESULT, ann.PRE_BIND_RESULT, ann.BIND_RESULT,
}


def check_invariants(store: ObjectStore):
    nodes = {n["metadata"]["name"]: n for n in store.list("nodes")[0]}
    used = {n: [0.0, 0.0, 0] for n in nodes}  # cpu, mem, pods
    for p in store.list("pods")[0]:
        meta, spec = p["metadata"], p.get("spec") or {}
        anns = meta.get("annotations") or {}
        nn = spec.get("nodeName")
        scheduled_keys = ALL_KEYS & set(anns)
        for k in scheduled_keys:
            v = anns[k]
            parsed = json.loads(v)
            assert isinstance(parsed, dict), k
        if nn:
            assert nn in nodes, f"bound to unknown node {nn}"
            fr = json.loads(anns.get(ann.FILTER_RESULT, "{}"))
            for plugin, msg in (fr.get(nn) or {}).items():
                assert msg == "passed", (
                    f"{meta['name']} bound to {nn} but {plugin} said {msg!r}")
            for c in spec.get("containers") or []:
                req = (c.get("resources") or {}).get("requests") or {}
                used[nn][0] += parse_quantity(req.get("cpu", "0"))
                used[nn][1] += parse_quantity(req.get("memory", "0"))
            used[nn][2] += 1
        else:
            conds = (p.get("status") or {}).get("conditions") or []
            if anns:  # a pod the scheduler actually looked at
                assert any(c.get("type") == "PodScheduled"
                           and c.get("status") == "False" for c in conds), (
                    f"{meta['name']} unbound without Unschedulable condition")
    for n, (cpu, mem, cnt) in used.items():
        alloc = (nodes[n].get("status") or {}).get("allocatable") or {}
        assert cpu <= parse_quantity(alloc.get("cpu", "0")) + 1e-9, n
        assert mem <= parse_quantity(alloc.get("memory", "0")) + 1e-9, n
        assert cnt <= int(alloc.get("pods", "110")), n


@pytest.mark.parametrize("seed", [31, 67])
def test_engine_soak(seed):
    rng = np.random.default_rng(seed)
    store = ObjectStore()
    for n in make_nodes(int(rng.integers(6, 14)), seed=seed,
                        taint_fraction=0.25):
        store.create("nodes", n)
    engine = SchedulerEngine(store)
    svc = SchedulerService(engine)

    for round_ in range(4):
        pods = make_pods(int(rng.integers(4, 14)), seed=seed * 10 + round_,
                         with_affinity=True, with_tolerations=True,
                         with_spread=True,
                         with_interpod=bool(round_ % 2))
        for p in pods:
            p["metadata"]["name"] = f"r{round_}-{p['metadata']['name']}"
            p["spec"]["priority"] = int(rng.integers(0, 3)) * 50
            store.create("pods", p)

        if round_ == 1:
            cfg = svc.get_config()
            cfg["profiles"][0]["plugins"] = {
                "score": {"disabled": [{"name": "TaintToleration"}]},
                "filter": {"disabled": [{"name": "PodTopologySpread"}]},
            }
            svc.restart_scheduler(cfg)
        elif round_ == 2:
            cfg = svc.get_config()
            cfg["profiles"][0]["plugins"] = {}
            cfg["profiles"][0]["pluginConfig"] = [
                {"name": "NodeResourcesFit",
                 "args": {"scoringStrategy": {"type": "MostAllocated"}}}]
            svc.restart_scheduler(cfg)

        engine.schedule_pending()
        check_invariants(store)

        # random deletions free capacity for the next round
        bound = [p for p in store.list("pods")[0]
                 if (p.get("spec") or {}).get("nodeName")]
        rng.shuffle(bound)
        for p in bound[: len(bound) // 3]:
            store.delete("pods", p["metadata"]["name"],
                         p["metadata"].get("namespace"))
    # final wave picks up any pods that became schedulable after deletes
    engine.schedule_pending()
    check_invariants(store)


@pytest.mark.parametrize("seed", [3, 11])
def test_engine_soak_dp_mesh(seed):
    """The soak's config churn / priority mix / deletion rounds, run on a
    dp>1 mesh: waves route through the speculative path when the active
    plugin set qualifies and must land in the same invariant-clean state
    as the scan engine on an identical store."""
    from kube_scheduler_simulator_tpu.parallel.mesh import make_mesh

    rng = np.random.default_rng(seed)
    nodes = make_nodes(int(rng.integers(6, 14)), seed=seed,
                       taint_fraction=0.25)
    pod_rounds = [
        make_pods(int(rng.integers(4, 14)), seed=seed * 10 + r,
                  with_affinity=True, with_tolerations=True, with_spread=True)
        for r in range(3)
    ]

    def run(mesh):
        store = ObjectStore()
        for n in nodes:
            store.create("nodes", n)
        engine = SchedulerEngine(store, mesh=mesh, chunk=16)
        for r, pods in enumerate(pod_rounds):
            for p in pods:
                q = {"metadata": dict(p["metadata"]), "spec": dict(p["spec"])}
                q["metadata"]["name"] = f"r{r}-{p['metadata']['name']}"
                store.create("pods", q)
            engine.schedule_pending()
            check_invariants(store)
        return {p["metadata"]["name"]: (p.get("spec") or {}).get("nodeName")
                for p in store.list("pods")[0]}

    mesh_out = run(make_mesh(4, dp=2))
    base_out = run(None)
    assert mesh_out == base_out


@pytest.mark.parametrize("seed", [5, 23])
def test_engine_soak_streaming_commit(seed):
    """The randomized soak on the chunk-pipelined commit path: a
    no-postfilter lineup with chunk=8 forces multi-chunk streaming waves
    (the commit worker runs while the device scans), across creation /
    priority-churn / deletion rounds.  End state must satisfy the same
    invariants as the sequential engine, and a pipelined run must land
    the exact same placement as a sequential run of the same rounds."""
    from kube_scheduler_simulator_tpu.plugins.registry import PluginSetConfig

    rng = np.random.default_rng(seed)
    nodes = make_nodes(int(rng.integers(6, 14)), seed=seed,
                       taint_fraction=0.25)
    pod_rounds = []
    for r in range(3):
        pods = make_pods(int(rng.integers(8, 20)), seed=seed * 10 + r,
                         with_affinity=True, with_tolerations=True,
                         with_spread=True)
        for p in pods:
            p["spec"]["priority"] = int(rng.integers(0, 3)) * 50
        pod_rounds.append(pods)
    cfg_kw = dict(enabled=[
        "NodeResourcesFit", "NodeResourcesBalancedAllocation",
        "NodeAffinity", "TaintToleration", "PodTopologySpread",
    ])

    def run(pipeline):
        store = ObjectStore()
        for n in nodes:
            store.create("nodes", n)
        engine = SchedulerEngine(
            store, plugin_config=PluginSetConfig(**cfg_kw), chunk=8,
            pipeline_commit=pipeline)
        assert engine._can_stream_commit() == pipeline
        for r, pods in enumerate(pod_rounds):
            for p in pods:
                q = {"metadata": dict(p["metadata"]), "spec": dict(p["spec"])}
                q["metadata"]["name"] = f"r{r}-{p['metadata']['name']}"
                store.create("pods", q)
            engine.schedule_pending()
            check_invariants(store)
            # deterministic deletions free capacity for the next round
            bound = sorted(
                p["metadata"]["name"] for p in store.list("pods")[0]
                if (p.get("spec") or {}).get("nodeName"))
            for name in bound[: len(bound) // 3]:
                store.delete("pods", name, "default")
        engine.schedule_pending()
        check_invariants(store)
        return {p["metadata"]["name"]: (p.get("spec") or {}).get("nodeName")
                for p in store.list("pods")[0]}

    assert run(True) == run(False)
