"""InterPodAffinity term normalization: namespaceSelector (v1.24+) and
matchLabelKeys / mismatchLabelKeys (MatchLabelKeysInPodAffinity, beta
default-on since v1.31).  Tensor replay vs sequential oracle parity plus
hand-computed placements."""

from kube_scheduler_simulator_tpu.framework.replay import replay
from kube_scheduler_simulator_tpu.plugins.registry import PluginSetConfig
from kube_scheduler_simulator_tpu.reference_impl.sequential import SequentialScheduler
from kube_scheduler_simulator_tpu.state.compile import compile_workload
from kube_scheduler_simulator_tpu.store.decode import decode_pod_result


def node(name, zone):
    return {
        "apiVersion": "v1", "kind": "Node",
        "metadata": {"name": name,
                     "labels": {"kubernetes.io/hostname": name, "zone": zone}},
        "spec": {},
        "status": {"allocatable": {"cpu": "8", "memory": "32Gi", "pods": "110"},
                   "capacity": {"cpu": "8", "memory": "32Gi", "pods": "110"}},
    }


def pod(name, namespace="default", labels=None, affinity=None, anti=None):
    p = {
        "apiVersion": "v1", "kind": "Pod",
        "metadata": {"name": name, "namespace": namespace, "labels": labels or {}},
        "spec": {"containers": [{"name": "c",
                                 "resources": {"requests": {"cpu": "100m"}}}]},
    }
    aff = {}
    if affinity:
        aff["podAffinity"] = affinity
    if anti:
        aff["podAntiAffinity"] = anti
    if aff:
        p["spec"]["affinity"] = aff
    return p


def ns(name, labels=None):
    return {"apiVersion": "v1", "kind": "Namespace",
            "metadata": {"name": name, "labels": labels or {}}}


def assert_parity(nodes, pods, namespaces=None):
    cfg = PluginSetConfig(enabled=["NodeResourcesFit", "InterPodAffinity"])
    seq = SequentialScheduler(nodes, pods, cfg, namespaces=namespaces).schedule_all()
    cw = compile_workload(nodes, pods, cfg, namespaces=namespaces)
    rr = replay(cw, chunk=8)
    for i, (sa, ss) in enumerate(seq):
        da = decode_pod_result(rr, i)
        assert int(rr.selected[i]) == ss, f"pod {i} selected"
        for k in sa:
            assert da[k] == sa[k], f"pod {i} {k}"
    return [s for _, s in seq], rr


def test_namespace_selector_expands_anti_affinity_scope():
    """Anti-affinity with a namespaceSelector matching team namespaces:
    a pod in team-b repels the new team-a pod from its zone (the plain
    namespaces default would only see team-a)."""
    nodes = [node("n0", "a"), node("n1", "b")]
    namespaces = [ns("team-a", {"team": "yes"}), ns("team-b", {"team": "yes"}),
                  ns("other")]
    anti_term = {"topologyKey": "zone",
                 "labelSelector": {"matchLabels": {"app": "web"}},
                 "namespaceSelector": {"matchLabels": {"team": "yes"}}}
    first = pod("w0", namespace="team-b", labels={"app": "web"})
    second = pod("w1", namespace="team-a", labels={"app": "web"},
                 anti={"requiredDuringSchedulingIgnoredDuringExecution": [anti_term]})
    sels, rr = assert_parity(nodes, [first, second], namespaces=namespaces)
    assert sels[0] == 0          # w0 -> zone a
    assert sels[1] == 1          # w1 repelled cross-namespace -> zone b
    assert int(rr.feasible_count[1]) == 1  # zone a infeasible for w1


def test_without_namespace_selector_cross_namespace_invisible():
    nodes = [node("n0", "a"), node("n1", "b")]
    anti_term = {"topologyKey": "zone",
                 "labelSelector": {"matchLabels": {"app": "web"}}}
    first = pod("w0", namespace="team-b", labels={"app": "web"})
    second = pod("w1", namespace="team-a", labels={"app": "web"},
                 anti={"requiredDuringSchedulingIgnoredDuringExecution": [anti_term]})
    sels, rr = assert_parity(nodes, [first, second])
    # w1 only sees team-a pods: nothing repels it — both zones feasible
    assert sels[0] == 0
    assert int(rr.feasible_count[1]) == 2


def test_empty_namespace_selector_matches_all_known_namespaces():
    nodes = [node("n0", "a"), node("n1", "b")]
    namespaces = [ns("team-a"), ns("team-b")]
    anti_term = {"topologyKey": "zone",
                 "labelSelector": {"matchLabels": {"app": "web"}},
                 "namespaceSelector": {}}
    first = pod("w0", namespace="team-b", labels={"app": "web"})
    second = pod("w1", namespace="team-a", labels={"app": "web"},
                 anti={"requiredDuringSchedulingIgnoredDuringExecution": [anti_term]})
    sels, rr = assert_parity(nodes, [first, second], namespaces=namespaces)
    assert sels[0] == 0 and sels[1] == 1
    assert int(rr.feasible_count[1]) == 1


def test_match_label_keys_scopes_anti_affinity_to_generation():
    """Self-anti-affinity with matchLabelKeys on pod-template-hash: only
    same-generation replicas repel each other."""
    nodes = [node("n0", "a"), node("n1", "b")]
    anti = {"requiredDuringSchedulingIgnoredDuringExecution": [{
        "topologyKey": "zone",
        "labelSelector": {"matchLabels": {"app": "web"}},
        "matchLabelKeys": ["pod-template-hash"],
    }]}
    v1 = pod("v1-0", labels={"app": "web", "pod-template-hash": "v1"}, anti=anti)
    v2a = pod("v2-0", labels={"app": "web", "pod-template-hash": "v2"}, anti=anti)
    v2b = pod("v2-1", labels={"app": "web", "pod-template-hash": "v2"}, anti=anti)
    sels, rr = assert_parity(nodes, [v1, v2a, v2b])
    # v2-0 may land anywhere (different hash doesn't repel it from v1);
    # v2-1 is repelled by v2-0 from ITS zone
    assert int(rr.feasible_count[1]) == 2   # v1 doesn't repel v2-0
    assert int(rr.feasible_count[2]) == 1   # v2-0 repels v2-1
    assert sels[2] != sels[1]


def test_mismatch_label_keys_repels_other_generations():
    """mismatchLabelKeys inverts the scope: the term targets pods with a
    DIFFERENT value of the key."""
    nodes = [node("n0", "a"), node("n1", "b")]
    anti = {"requiredDuringSchedulingIgnoredDuringExecution": [{
        "topologyKey": "zone",
        "labelSelector": {"matchLabels": {"app": "web"}},
        "mismatchLabelKeys": ["pod-template-hash"],
    }]}
    v1 = pod("v1-0", labels={"app": "web", "pod-template-hash": "v1"})
    v2 = pod("v2-0", labels={"app": "web", "pod-template-hash": "v2"}, anti=anti)
    sels, rr = assert_parity(nodes, [v1, v2])
    # v2 avoids zones holding OTHER generations of web -> zone b
    assert sels[0] == 0 and sels[1] == 1
    assert int(rr.feasible_count[1]) == 1


def test_unmatched_namespace_selector_matches_nothing():
    """A namespaceSelector matching NO known namespace resolves to an
    empty set, which must match no pods — not fall back to the owner
    namespace (review r3 finding)."""
    nodes = [node("n0", "a"), node("n1", "b")]
    namespaces = [ns("team-a")]  # no labels
    anti_term = {"topologyKey": "zone",
                 "labelSelector": {"matchLabels": {"app": "web"}},
                 "namespaceSelector": {"matchLabels": {"team": "nope"}}}
    first = pod("w0", namespace="team-a", labels={"app": "web"})
    second = pod("w1", namespace="team-a", labels={"app": "web"},
                 anti={"requiredDuringSchedulingIgnoredDuringExecution": [anti_term]})
    sels, rr = assert_parity(nodes, [first, second], namespaces=namespaces)
    assert int(rr.feasible_count[1]) == 2  # nothing repels w1
