"""Golden annotation fixtures: exact JSON strings for a hand-computed
cluster, pinning the wire format itself (the parity suite only proves
the tensor path and the sequential oracle agree with EACH OTHER).

Hand-derivation (upstream v1.32 semantics):
  node-a 2cpu/4Gi, node-b 4cpu/8Gi; pod requests 1cpu/2Gi.
  NodeResourcesFit LeastAllocated = mean over resources of
    (allocatable-requested)*100/allocatable -> a: (50+50)/2=50,
    b: (75+75)/2=75.
  BalancedAllocation: cpu/mem fractions equal on both -> std 0 -> 100.
  Scores marshal as strconv.FormatInt strings (store.go:474,501); maps
  marshal compact with sorted keys (Go encoding/json).
"""

import json

from kube_scheduler_simulator_tpu.cluster.store import ObjectStore
from kube_scheduler_simulator_tpu.framework.engine import SchedulerEngine
from kube_scheduler_simulator_tpu.plugins.registry import PluginSetConfig
from kube_scheduler_simulator_tpu.store import annotations as ann

GOLDEN = {
    ann.PRE_FILTER_STATUS_RESULT: '{"NodeResourcesFit":"success"}',
    ann.PRE_FILTER_RESULT: "{}",
    ann.FILTER_RESULT:
        '{"node-a":{"NodeResourcesFit":"passed"},"node-b":{"NodeResourcesFit":"passed"}}',
    ann.POST_FILTER_RESULT: "{}",
    ann.PRE_SCORE_RESULT:
        '{"NodeResourcesBalancedAllocation":"success","NodeResourcesFit":"success"}',
    ann.SCORE_RESULT:
        '{"node-a":{"NodeResourcesBalancedAllocation":"100","NodeResourcesFit":"50"},'
        '"node-b":{"NodeResourcesBalancedAllocation":"100","NodeResourcesFit":"75"}}',
    ann.FINAL_SCORE_RESULT:
        '{"node-a":{"NodeResourcesBalancedAllocation":"100","NodeResourcesFit":"50"},'
        '"node-b":{"NodeResourcesBalancedAllocation":"100","NodeResourcesFit":"75"}}',
    ann.RESERVE_RESULT: "{}",
    ann.PERMIT_STATUS_RESULT: "{}",
    ann.PERMIT_TIMEOUT_RESULT: "{}",
    ann.PRE_BIND_RESULT: "{}",
    ann.BIND_RESULT: '{"DefaultBinder":"success"}',
    ann.SELECTED_NODE: "node-b",
}


def test_golden_annotation_strings():
    store = ObjectStore()
    store.create("nodes", {"metadata": {"name": "node-a"},
                           "status": {"allocatable": {"cpu": "2", "memory": "4Gi",
                                                      "pods": "10"}}})
    store.create("nodes", {"metadata": {"name": "node-b"},
                           "status": {"allocatable": {"cpu": "4", "memory": "8Gi",
                                                      "pods": "10"}}})
    engine = SchedulerEngine(store)
    engine.set_plugin_config(PluginSetConfig(
        enabled=["NodeResourcesFit", "NodeResourcesBalancedAllocation"]))
    store.create("pods", {"metadata": {"name": "p1"}, "spec": {"containers": [
        {"name": "c", "resources": {"requests": {"cpu": "1", "memory": "2Gi"}}}]}})
    assert engine.schedule_pending() == 1

    anns = store.get("pods", "p1", "default")["metadata"]["annotations"]
    for key, want in GOLDEN.items():
        assert anns[key] == want, f"{key}\n  got:  {anns[key]}\n  want: {want}"

    # result-history holds exactly these blobs as its first record
    hist = json.loads(anns[ann.RESULT_HISTORY])
    assert len(hist) == 1
    for key, want in GOLDEN.items():
        assert hist[0][key] == want, f"history {key}"


def test_golden_unschedulable_filter_message():
    """Infeasible pod records the upstream Insufficient-cpu message and
    an empty selected-node."""
    store = ObjectStore()
    store.create("nodes", {"metadata": {"name": "node-a"},
                           "status": {"allocatable": {"cpu": "2", "memory": "4Gi",
                                                      "pods": "10"}}})
    engine = SchedulerEngine(store)
    engine.set_plugin_config(PluginSetConfig(enabled=["NodeResourcesFit"]))
    store.create("pods", {"metadata": {"name": "big"}, "spec": {"containers": [
        {"name": "c", "resources": {"requests": {"cpu": "16", "memory": "2Gi"}}}]}})
    assert engine.schedule_pending() == 0
    anns = store.get("pods", "big", "default")["metadata"]["annotations"]
    fr = json.loads(anns[ann.FILTER_RESULT])
    assert fr["node-a"]["NodeResourcesFit"] == "Insufficient cpu"
    assert anns[ann.SELECTED_NODE] == ""


def _schedule(nodes, pods, enabled, weights=None):
    store = ObjectStore()
    for n in nodes:
        store.create("nodes", n)
    engine = SchedulerEngine(store)
    engine.set_plugin_config(PluginSetConfig(enabled=enabled,
                                             weights=weights or {}))
    for p in pods:
        store.create("pods", p)
    engine.schedule_pending()
    return {p["metadata"]["name"]:
            p["metadata"].get("annotations", {})
            for p in store.list("pods")[0]}


def _assert_golden(anns: dict, golden: dict):
    for key, want in golden.items():
        assert anns[key] == want, f"{key}\n  got:  {anns[key]}\n  want: {want}"


# Integer-division rounding, hand-derived from upstream v1.32 semantics
# (noderesources/least_allocated + balanced_allocation, int64 math):
#   node-a 4cpu/8Gi, node-b 2cpu/4Gi; pod requests 1cpu/1Gi.
#   LeastAllocated per resource = (allocatable-req)*100/allocatable, int64 div:
#     a.cpu (4000-1000)*100/4000 = 75
#     a.mem 7516192768*100/8589934592 = 87.4999... -> 87   (the rounding case)
#     a = (75+87)/2 = 81
#     b.cpu (2000-1000)*100/2000 = 50;  b.mem 3Gi*100/4Gi = 75 exact
#     b = (50+75)/2 = 125/2 -> 62                           (odd-sum division)
#   BalancedAllocation (2 resources): std = |f_cpu - f_mem|/2,
#   score = int64((1-std)*100):
#     a: |0.25-0.125|/2 = 0.0625 -> 93.75 -> 93
#     b: |0.5-0.25|/2   = 0.125  -> 87.5  -> 87
#   Totals: a 81+93=174 > b 62+87=149 -> node-a selected.
GOLDEN_ROUNDING = {
    ann.PRE_FILTER_STATUS_RESULT: '{"NodeResourcesFit":"success"}',
    ann.PRE_FILTER_RESULT: "{}",
    ann.FILTER_RESULT:
        '{"node-a":{"NodeResourcesFit":"passed"},"node-b":{"NodeResourcesFit":"passed"}}',
    ann.PRE_SCORE_RESULT:
        '{"NodeResourcesBalancedAllocation":"success","NodeResourcesFit":"success"}',
    ann.SCORE_RESULT:
        '{"node-a":{"NodeResourcesBalancedAllocation":"93","NodeResourcesFit":"81"},'
        '"node-b":{"NodeResourcesBalancedAllocation":"87","NodeResourcesFit":"62"}}',
    ann.FINAL_SCORE_RESULT:
        '{"node-a":{"NodeResourcesBalancedAllocation":"93","NodeResourcesFit":"81"},'
        '"node-b":{"NodeResourcesBalancedAllocation":"87","NodeResourcesFit":"62"}}',
    ann.BIND_RESULT: '{"DefaultBinder":"success"}',
    ann.SELECTED_NODE: "node-a",
}


def test_golden_integer_division_rounding():
    anns = _schedule(
        nodes=[
            {"metadata": {"name": "node-a"},
             "status": {"allocatable": {"cpu": "4", "memory": "8Gi", "pods": "10"}}},
            {"metadata": {"name": "node-b"},
             "status": {"allocatable": {"cpu": "2", "memory": "4Gi", "pods": "10"}}},
        ],
        pods=[{"metadata": {"name": "p1"}, "spec": {"containers": [
            {"name": "c", "resources": {"requests": {"cpu": "1", "memory": "1Gi"}}}]}}],
        enabled=["NodeResourcesFit", "NodeResourcesBalancedAllocation"],
    )
    _assert_golden(anns["p1"], GOLDEN_ROUNDING)


# TaintToleration, hand-derived from upstream v1.32 semantics
# (tainttoleration.go + helper.DefaultNormalizeScore reverse=true, weight 3):
#   node-a PreferNoSchedule dedicated=gpu (intolerable but not filtering),
#   node-b untainted, node-c NoSchedule dedicated=gpu (filters the pod).
#   Raw score = count of intolerable PreferNoSchedule taints: a=1, b=0.
#   Reverse-normalize over feasible nodes, max=1:
#     a: 100 - 100*1/1 = 0;  b: 100 - 100*0/1 = 100
#   finalscore = normalized x weight(3): a "0", b "300"; raw score-result
#   keeps the UN-normalized counts ("1"/"0") per AddScoreResult.
GOLDEN_TAINTS = {
    ann.PRE_FILTER_STATUS_RESULT: "{}",
    ann.PRE_FILTER_RESULT: "{}",
    ann.FILTER_RESULT:
        '{"node-a":{"TaintToleration":"passed"},'
        '"node-b":{"TaintToleration":"passed"},'
        '"node-c":{"TaintToleration":'
        '"node(s) had untolerated taint {dedicated: gpu}"}}',
    ann.PRE_SCORE_RESULT: '{"TaintToleration":"success"}',
    ann.SCORE_RESULT:
        '{"node-a":{"TaintToleration":"1"},"node-b":{"TaintToleration":"0"}}',
    ann.FINAL_SCORE_RESULT:
        '{"node-a":{"TaintToleration":"0"},"node-b":{"TaintToleration":"300"}}',
    ann.BIND_RESULT: '{"DefaultBinder":"success"}',
    ann.SELECTED_NODE: "node-b",
}


def test_golden_taint_reverse_normalize_weight():
    anns = _schedule(
        nodes=[
            {"metadata": {"name": "node-a"},
             "spec": {"taints": [{"key": "dedicated", "value": "gpu",
                                  "effect": "PreferNoSchedule"}]},
             "status": {"allocatable": {"cpu": "4", "memory": "8Gi", "pods": "10"}}},
            {"metadata": {"name": "node-b"},
             "status": {"allocatable": {"cpu": "4", "memory": "8Gi", "pods": "10"}}},
            {"metadata": {"name": "node-c"},
             "spec": {"taints": [{"key": "dedicated", "value": "gpu",
                                  "effect": "NoSchedule"}]},
             "status": {"allocatable": {"cpu": "4", "memory": "8Gi", "pods": "10"}}},
        ],
        pods=[{"metadata": {"name": "p1"},
               "spec": {"containers": [{"name": "c"}]}}],
        enabled=["TaintToleration"],
    )
    _assert_golden(anns["p1"], GOLDEN_TAINTS)


# NodeAffinity preferred terms, hand-derived from upstream v1.32 semantics
# (node_affinity.go Score = sum of matching preferred-term weights;
# NormalizeScore = DefaultNormalizeScore reverse=false; plugin weight 2):
#   node-a disk=ssd, node-b disk=hdd; preferred terms weight 5 (ssd) and
#   3 (hdd); required term disk In [ssd,hdd] matches both (keeps PreFilter
#   from skipping).  Raw: a=5, b=3; normalize max=5: a=100, b=100*3/5=60;
#   x2 -> "200"/"120".
GOLDEN_AFFINITY = {
    ann.PRE_FILTER_STATUS_RESULT: '{"NodeAffinity":"success"}',
    ann.PRE_FILTER_RESULT: "{}",
    ann.FILTER_RESULT:
        '{"node-a":{"NodeAffinity":"passed"},"node-b":{"NodeAffinity":"passed"}}',
    ann.PRE_SCORE_RESULT: '{"NodeAffinity":"success"}',
    ann.SCORE_RESULT:
        '{"node-a":{"NodeAffinity":"5"},"node-b":{"NodeAffinity":"3"}}',
    ann.FINAL_SCORE_RESULT:
        '{"node-a":{"NodeAffinity":"200"},"node-b":{"NodeAffinity":"120"}}',
    ann.BIND_RESULT: '{"DefaultBinder":"success"}',
    ann.SELECTED_NODE: "node-a",
}


def test_golden_node_affinity_preferred_weights():
    affinity = {"nodeAffinity": {
        "requiredDuringSchedulingIgnoredDuringExecution": {
            "nodeSelectorTerms": [{"matchExpressions": [
                {"key": "disk", "operator": "In", "values": ["ssd", "hdd"]}]}]},
        "preferredDuringSchedulingIgnoredDuringExecution": [
            {"weight": 5, "preference": {"matchExpressions": [
                {"key": "disk", "operator": "In", "values": ["ssd"]}]}},
            {"weight": 3, "preference": {"matchExpressions": [
                {"key": "disk", "operator": "In", "values": ["hdd"]}]}},
        ]}}
    anns = _schedule(
        nodes=[
            {"metadata": {"name": "node-a", "labels": {"disk": "ssd"}},
             "status": {"allocatable": {"cpu": "4", "memory": "8Gi", "pods": "10"}}},
            {"metadata": {"name": "node-b", "labels": {"disk": "hdd"}},
             "status": {"allocatable": {"cpu": "4", "memory": "8Gi", "pods": "10"}}},
        ],
        pods=[{"metadata": {"name": "p1"},
               "spec": {"containers": [{"name": "c"}], "affinity": affinity}}],
        enabled=["NodeAffinity"],
    )
    _assert_golden(anns["p1"], GOLDEN_AFFINITY)


def test_pipelined_commit_parity_with_sequential_postpass():
    """The chunk-pipelined commit (engine pipeline_commit=True, the
    default) must be indistinguishable from the sequential post-pass:
    bit-identical annotations (including result-history), the same bind
    count, and the same bind order as observed by watch subscribers —
    chunk=16 over ~7 chunks so the commit worker genuinely runs while
    later chunks stream in, with a priority mix so queue order matters."""
    import copy
    import queue as queue_mod

    from kube_scheduler_simulator_tpu.models.workloads import make_nodes, make_pods

    nodes = make_nodes(20, seed=7, taint_fraction=0.2)
    pods = make_pods(110, seed=8, with_affinity=True, with_tolerations=True,
                     with_spread=True)
    for i, p in enumerate(pods):
        p["spec"]["priority"] = (i % 3) * 100
    cfg_kw = dict(enabled=[
        "NodeResourcesFit", "NodeResourcesBalancedAllocation", "NodeAffinity",
        "TaintToleration", "PodTopologySpread",
    ])

    def run(pipeline):
        store = ObjectStore()
        for n in nodes:
            store.create("nodes", copy.deepcopy(n))
        for p in pods:
            store.create("pods", copy.deepcopy(p))
        q = store.watch("pods")
        engine = SchedulerEngine(store, plugin_config=PluginSetConfig(**cfg_kw),
                                 chunk=16, pipeline_commit=pipeline)
        assert engine._can_stream_commit() == pipeline
        bound = engine.schedule_pending()
        bind_order, seen = [], set()
        while True:
            try:
                _rv, event_type, obj = q.get_nowait()
            except queue_mod.Empty:
                break
            name = obj["metadata"]["name"]
            if (event_type == "MODIFIED"
                    and (obj.get("spec") or {}).get("nodeName")
                    and name not in seen):
                seen.add(name)
                bind_order.append(name)
        store.unwatch("pods", q)
        anns = {p["metadata"]["name"]: p["metadata"].get("annotations") or {}
                for p in store.list("pods")[0]}
        return bound, bind_order, anns

    bound_p, order_p, anns_p = run(True)
    bound_s, order_s, anns_s = run(False)
    assert bound_p == bound_s
    assert order_p == order_s
    assert anns_p.keys() == anns_s.keys()
    for name in anns_s:
        for key in set(anns_s[name]) | set(anns_p[name]):
            # resourceVersion never appears in annotations, so exact
            # string equality holds for every blob INCLUDING the
            # result-history append
            assert anns_p[name].get(key) == anns_s[name].get(key), (
                f"pod {name} key {key} diverged between pipelined and "
                "sequential commit")


def test_gang_pipelined_commit_parity_with_sequential_postpass():
    """The parity gate extended to gang scheduling
    (docs/gang-scheduling.md): a mixed wave of PodGroups (one admitted,
    one below quorum), gang-labeled pods and plain pods must produce
    bit-identical annotations (permit-result / permit-result-timeout /
    result-history included), the same bind count, the same bind order
    AND the same parked set between pipeline_commit=True (gang-boundary
    streaming cuts, chunk=8 so gangs of 5 straddle chunks) and False
    (the sequential post-pass with the same vectorized quorum pass)."""
    import copy
    import queue as queue_mod

    from kube_scheduler_simulator_tpu.framework.gang import POD_GROUP_LABEL
    from kube_scheduler_simulator_tpu.models.workloads import (
        make_gang_workload, make_nodes, make_pods)
    from kube_scheduler_simulator_tpu.plugins.coscheduling import (
        Coscheduling, ensure_podgroup_resource)

    nodes = make_nodes(14, seed=21, taint_fraction=0.2)
    pgs, gpods = make_gang_workload(3, 5, seed=22)
    for p in gpods:
        # one gang below quorum: two members infeasible
        if (p["metadata"]["labels"][POD_GROUP_LABEL] == "gang-0001"
                and p["metadata"]["name"].endswith(("003", "004"))):
            p["spec"]["containers"][0]["resources"]["requests"]["cpu"] = \
                "9999999m"
    plain = make_pods(40, seed=23, with_affinity=True, with_tolerations=True)
    for i, p in enumerate(plain):
        p["spec"]["priority"] = (i % 3) * 100

    def run(pipeline):
        store = ObjectStore()
        ensure_podgroup_resource(store)
        for n in nodes:
            store.create("nodes", copy.deepcopy(n))
        for pg in pgs:
            store.create("podgroups", copy.deepcopy(pg))
        for p in gpods + plain:
            store.create("pods", copy.deepcopy(p))
        q = store.watch("pods")
        cfg = PluginSetConfig(
            enabled=["NodeResourcesFit", "NodeResourcesBalancedAllocation",
                     "NodeAffinity", "TaintToleration", "Coscheduling"],
            custom={"Coscheduling": Coscheduling()},
        )
        engine = SchedulerEngine(store, plugin_config=cfg, chunk=8,
                                 pipeline_commit=pipeline)
        bound = engine.schedule_pending()
        bind_order, seen = [], set()
        while True:
            try:
                _rv, event_type, obj = q.get_nowait()
            except queue_mod.Empty:
                break
            name = obj["metadata"]["name"]
            if (event_type == "MODIFIED"
                    and (obj.get("spec") or {}).get("nodeName")
                    and name not in seen):
                seen.add(name)
                bind_order.append(name)
        store.unwatch("pods", q)
        anns = {p["metadata"]["name"]: p["metadata"].get("annotations") or {}
                for p in store.list("pods")[0]}
        parked = sorted(k for k in engine.gang_parked)
        return bound, bind_order, anns, parked

    bound_p, order_p, anns_p, parked_p = run(True)
    bound_s, order_s, anns_s, parked_s = run(False)
    assert bound_p == bound_s
    assert order_p == order_s
    assert parked_p == parked_s and len(parked_p) == 3
    assert anns_p.keys() == anns_s.keys()
    for name in anns_s:
        for key in set(anns_s[name]) | set(anns_p[name]):
            assert anns_p[name].get(key) == anns_s[name].get(key), (
                f"pod {name} key {key} diverged between pipelined and "
                "sequential gang commit")
