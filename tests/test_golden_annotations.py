"""Golden annotation fixtures: exact JSON strings for a hand-computed
cluster, pinning the wire format itself (the parity suite only proves
the tensor path and the sequential oracle agree with EACH OTHER).

Hand-derivation (upstream v1.32 semantics):
  node-a 2cpu/4Gi, node-b 4cpu/8Gi; pod requests 1cpu/2Gi.
  NodeResourcesFit LeastAllocated = mean over resources of
    (allocatable-requested)*100/allocatable -> a: (50+50)/2=50,
    b: (75+75)/2=75.
  BalancedAllocation: cpu/mem fractions equal on both -> std 0 -> 100.
  Scores marshal as strconv.FormatInt strings (store.go:474,501); maps
  marshal compact with sorted keys (Go encoding/json).
"""

import json

from kube_scheduler_simulator_tpu.cluster.store import ObjectStore
from kube_scheduler_simulator_tpu.framework.engine import SchedulerEngine
from kube_scheduler_simulator_tpu.plugins.registry import PluginSetConfig
from kube_scheduler_simulator_tpu.store import annotations as ann

GOLDEN = {
    ann.PRE_FILTER_STATUS_RESULT: '{"NodeResourcesFit":"success"}',
    ann.PRE_FILTER_RESULT: "{}",
    ann.FILTER_RESULT:
        '{"node-a":{"NodeResourcesFit":"passed"},"node-b":{"NodeResourcesFit":"passed"}}',
    ann.POST_FILTER_RESULT: "{}",
    ann.PRE_SCORE_RESULT:
        '{"NodeResourcesBalancedAllocation":"success","NodeResourcesFit":"success"}',
    ann.SCORE_RESULT:
        '{"node-a":{"NodeResourcesBalancedAllocation":"100","NodeResourcesFit":"50"},'
        '"node-b":{"NodeResourcesBalancedAllocation":"100","NodeResourcesFit":"75"}}',
    ann.FINAL_SCORE_RESULT:
        '{"node-a":{"NodeResourcesBalancedAllocation":"100","NodeResourcesFit":"50"},'
        '"node-b":{"NodeResourcesBalancedAllocation":"100","NodeResourcesFit":"75"}}',
    ann.RESERVE_RESULT: "{}",
    ann.PERMIT_STATUS_RESULT: "{}",
    ann.PERMIT_TIMEOUT_RESULT: "{}",
    ann.PRE_BIND_RESULT: "{}",
    ann.BIND_RESULT: '{"DefaultBinder":"success"}',
    ann.SELECTED_NODE: "node-b",
}


def test_golden_annotation_strings():
    store = ObjectStore()
    store.create("nodes", {"metadata": {"name": "node-a"},
                           "status": {"allocatable": {"cpu": "2", "memory": "4Gi",
                                                      "pods": "10"}}})
    store.create("nodes", {"metadata": {"name": "node-b"},
                           "status": {"allocatable": {"cpu": "4", "memory": "8Gi",
                                                      "pods": "10"}}})
    engine = SchedulerEngine(store)
    engine.set_plugin_config(PluginSetConfig(
        enabled=["NodeResourcesFit", "NodeResourcesBalancedAllocation"]))
    store.create("pods", {"metadata": {"name": "p1"}, "spec": {"containers": [
        {"name": "c", "resources": {"requests": {"cpu": "1", "memory": "2Gi"}}}]}})
    assert engine.schedule_pending() == 1

    anns = store.get("pods", "p1", "default")["metadata"]["annotations"]
    for key, want in GOLDEN.items():
        assert anns[key] == want, f"{key}\n  got:  {anns[key]}\n  want: {want}"

    # result-history holds exactly these blobs as its first record
    hist = json.loads(anns[ann.RESULT_HISTORY])
    assert len(hist) == 1
    for key, want in GOLDEN.items():
        assert hist[0][key] == want, f"history {key}"


def test_golden_unschedulable_filter_message():
    """Infeasible pod records the upstream Insufficient-cpu message and
    an empty selected-node."""
    store = ObjectStore()
    store.create("nodes", {"metadata": {"name": "node-a"},
                           "status": {"allocatable": {"cpu": "2", "memory": "4Gi",
                                                      "pods": "10"}}})
    engine = SchedulerEngine(store)
    engine.set_plugin_config(PluginSetConfig(enabled=["NodeResourcesFit"]))
    store.create("pods", {"metadata": {"name": "big"}, "spec": {"containers": [
        {"name": "c", "resources": {"requests": {"cpu": "16", "memory": "2Gi"}}}]}})
    assert engine.schedule_pending() == 0
    anns = store.get("pods", "big", "default")["metadata"]["annotations"]
    fr = json.loads(anns[ann.FILTER_RESULT])
    assert fr["node-a"]["NodeResourcesFit"] == "Insufficient cpu"
    assert anns[ann.SELECTED_NODE] == ""
