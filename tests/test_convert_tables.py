"""Table-driven config-conversion tests.

Modeled on the reference's deepest config tables: mergePluginSet
(reference: simulator/scheduler/plugin/plugins.go:230-285, exercised by
plugins_test.go) and ConvertConfigurationForSimulator
(scheduler/scheduler.go:141-173, scheduler_test.go:24-80).
"""

import pytest

from kube_scheduler_simulator_tpu.scheduler.convert import (
    parse_profile,
    _merge_plugin_set,
    convert_configuration_for_simulator,
    default_scheduler_config,
    parse_profiles,
)

DEFAULTS = {"enabled": [{"name": "A", "weight": 1}, {"name": "B"},
                        {"name": "C", "weight": 3}]}

MERGE_TABLE = [
    # (name, default_set, custom_set, expected enabled names, expected weights)
    ("no customization keeps defaults",
     DEFAULTS, {}, ["A", "B", "C"], {"A": 1, "C": 3}),
    ("disable one default",
     DEFAULTS, {"disabled": [{"name": "B"}]}, ["A", "C"], {}),
    ("disable star drops all defaults",
     DEFAULTS, {"disabled": [{"name": "*"}], "enabled": [{"name": "X"}]},
     ["X"], {}),
    ("custom replaces same-named default in place",
     DEFAULTS, {"enabled": [{"name": "B", "weight": 9}]},
     ["A", "B", "C"], {"B": 9}),
    ("new custom plugin appends after defaults",
     DEFAULTS, {"enabled": [{"name": "X", "weight": 2}]},
     ["A", "B", "C", "X"], {"X": 2}),
    ("replacement and append together",
     DEFAULTS, {"enabled": [{"name": "C", "weight": 7}, {"name": "X"}]},
     ["A", "B", "C", "X"], {"C": 7}),
    ("disabled default plus custom enable of another",
     DEFAULTS, {"disabled": [{"name": "A"}], "enabled": [{"name": "X"}]},
     ["B", "C", "X"], {}),
    ("custom enable of a disabled name still appends",
     # upstream: disabled suppresses the DEFAULT entry; the custom enabled
     # list is honored independently
     DEFAULTS, {"disabled": [{"name": "B"}], "enabled": [{"name": "B", "weight": 5}]},
     ["A", "C", "B"], {"B": 5}),
]


@pytest.mark.parametrize("name,dset,cset,want,weights", MERGE_TABLE,
                         ids=[t[0] for t in MERGE_TABLE])
def test_merge_plugin_set(name, dset, cset, want, weights):
    out = _merge_plugin_set(dset, cset)
    got = [p["name"] for p in out["enabled"]]
    assert got == want
    for n, w in weights.items():
        assert next(p for p in out["enabled"] if p["name"] == n)["weight"] == w


def test_merge_does_not_mutate_inputs():
    dset = {"enabled": [{"name": "A", "weight": 1}]}
    cset = {"enabled": [{"name": "A", "weight": 9}]}
    out = _merge_plugin_set(dset, cset)
    out["enabled"][0]["weight"] = 42
    assert dset["enabled"][0]["weight"] == 1
    assert cset["enabled"][0]["weight"] == 9


# ------------------------------------------------- conversion tables

def _mp(cfg, profile=0):
    return cfg["profiles"][profile]["plugins"]["multiPoint"]


def test_convert_empty_config_wraps_full_default_lineup():
    cfg = convert_configuration_for_simulator({})
    default_names = [
        p["name"] for p in
        default_scheduler_config()["profiles"][0]["plugins"]["multiPoint"]["enabled"]
    ]
    got = [p["name"] for p in _mp(cfg)["enabled"]]
    assert got == [n + "Wrapped" for n in default_names]
    assert _mp(cfg)["disabled"] == [{"name": "*"}]


def test_convert_preserves_weights_through_wrapping():
    cfg = convert_configuration_for_simulator({"profiles": [{
        "plugins": {"multiPoint": {"enabled": [
            {"name": "NodeAffinity", "weight": 11},
        ]}},
    }]})
    na = next(p for p in _mp(cfg)["enabled"] if p["name"] == "NodeAffinityWrapped")
    assert na["weight"] == 11


def test_convert_each_extension_point_wrapped():
    cfg = convert_configuration_for_simulator({"profiles": [{
        "plugins": {
            "filter": {"enabled": [{"name": "NodeName"}]},
            "score": {"enabled": [{"name": "ImageLocality", "weight": 4}],
                      "disabled": [{"name": "TaintToleration"}]},
        },
    }]})
    plugins = cfg["profiles"][0]["plugins"]
    assert plugins["filter"]["enabled"] == [{"name": "NodeNameWrapped"}]
    assert plugins["score"]["enabled"] == [{"name": "ImageLocalityWrapped", "weight": 4}]
    assert {"name": "TaintTolerationWrapped"} in plugins["score"]["disabled"]


def test_convert_multiple_profiles_independently():
    cfg = convert_configuration_for_simulator({"profiles": [
        {"schedulerName": "a", "plugins": {"multiPoint": {
            "enabled": [{"name": "NodeResourcesFit", "weight": 2}]}}},
        {"schedulerName": "b", "plugins": {"multiPoint": {
            "disabled": [{"name": "*"}],
            "enabled": [{"name": "TaintToleration", "weight": 6}]}}},
    ]})
    a = [p["name"] for p in _mp(cfg, 0)["enabled"]]
    b = [p["name"] for p in _mp(cfg, 1)["enabled"]]
    assert "NodeResourcesFitWrapped" in a and len(a) > 1  # merged with defaults
    assert b == ["TaintTolerationWrapped"]                # star-disabled defaults


def test_convert_keeps_scheduler_names_and_extenders():
    cfg = convert_configuration_for_simulator({
        "profiles": [{"schedulerName": "custom-sched"}],
        "extenders": [{"urlPrefix": "http://e1", "filterVerb": "filter"}],
    })
    assert cfg["profiles"][0]["schedulerName"] == "custom-sched"
    assert cfg["extenders"][0]["urlPrefix"] == "http://e1"


# --------------------------------------------- score plugin weight tables
#
# Mirrors getScorePluginWeight (reference plugins.go:289-304, tables at
# plugins_test.go:1096-1200): union of score.enabled + multiPoint.enabled,
# explicit weight wins, weight 0 means 1, "Wrapped" suffix trimmed.

WEIGHT_TABLE = [
    # plugins_test.go:1104 "score and multipoint plugins"
    ("score and multipoint plugins",
     {"plugins": {
         "multiPoint": {"disabled": [{"name": "*"}],
                        "enabled": [{"name": "TaintToleration", "weight": 4}]},
         "score": {"enabled": [{"name": "ImageLocality", "weight": 2}]},
     }},
     {"TaintToleration": 4, "ImageLocality": 2}),
    # plugins_test.go:1145 "only score plugins"
    ("only score plugins",
     {"plugins": {
         "multiPoint": {"disabled": [{"name": "*"}]},
         "score": {"enabled": [{"name": "NodeResourcesBalancedAllocation",
                                "weight": 7}]},
     }},
     {"NodeResourcesBalancedAllocation": 7}),
    # plugins_test.go:1172 "only multipoint plugins"
    ("only multipoint plugins",
     {"plugins": {
         "multiPoint": {"disabled": [{"name": "*"}],
                        "enabled": [{"name": "NodeAffinity", "weight": 5}]},
     }},
     {"NodeAffinity": 5}),
    # "a weight of zero is not permitted" -> 1 (plugins.go:297-301)
    ("explicit zero weight becomes one",
     {"plugins": {
         "multiPoint": {"disabled": [{"name": "*"}]},
         "score": {"enabled": [{"name": "ImageLocality", "weight": 0}]},
     }},
     {"ImageLocality": 1}),
    # suffix trimmed: config written against the converted (Wrapped) names
    ("wrapped suffix trimmed",
     {"plugins": {
         "multiPoint": {"disabled": [{"name": "*"}],
                        "enabled": [{"name": "TaintTolerationWrapped",
                                     "weight": 6}]},
     }},
     {"TaintToleration": 6}),
]


@pytest.mark.parametrize("name,profile,want", WEIGHT_TABLE,
                         ids=[t[0] for t in WEIGHT_TABLE])
def test_score_plugin_weight_tables(name, profile, want):
    ps = parse_profile(profile)
    for plugin, w in want.items():
        assert ps.weight(plugin) == w, plugin
    # nothing beyond the expected score plugins carries a custom weight
    assert set(ps.weights) == set(want)


def test_default_lineup_weights_match_registry_defaults():
    """With no user config, every score plugin's weight is its upstream
    default (the defaulted MultiPoint entries carry those weights)."""
    from kube_scheduler_simulator_tpu.plugins.registry import PLUGIN_REGISTRY

    ps = parse_profile({})
    for name, desc in PLUGIN_REGISTRY.items():
        if desc.has_score:
            assert ps.weight(name) == desc.default_weight, name


def test_specific_score_point_weight_wins_over_multipoint():
    """DOCUMENTED DELTA (docs/SEMANTICS.md): when a plugin is listed at
    BOTH score.enabled and multiPoint.enabled with different weights, we
    use the score-point weight for selection AND annotations (upstream
    framework semantics: the specific extension point wins). The
    reference's getScorePluginWeight quirkily lets the multiPoint entry
    clobber the score entry (plugins.go:292-293 appends MultiPoint last)
    for its ANNOTATION math only, diverging from its own selection."""
    ps = parse_profile({"plugins": {
        "multiPoint": {"disabled": [{"name": "*"}],
                       "enabled": [{"name": "ImageLocality", "weight": 3}]},
        "score": {"enabled": [{"name": "ImageLocality", "weight": 9}]},
    }})
    assert ps.weight("ImageLocality") == 9


# --------------------------------------------- pluginConfig tables
#
# NewPluginConfig (reference plugins.go:96-171): per-plugin args keyed by
# name, later entries for the same plugin override earlier ones (the map
# write at plugins.go:138), unknown plugins' args carried through.

def test_plugin_config_last_entry_wins():
    ps = parse_profile({"pluginConfig": [
        {"name": "NodeResourcesFit", "args": {"scoringStrategy": {"type": "LeastAllocated"}}},
        {"name": "NodeResourcesFit", "args": {"scoringStrategy": {"type": "MostAllocated"}}},
    ]})
    assert ps.args["NodeResourcesFit"]["scoringStrategy"]["type"] == "MostAllocated"


def test_plugin_config_wrapped_name_normalized():
    ps = parse_profile({"pluginConfig": [
        {"name": "PodTopologySpreadWrapped",
         "args": {"defaultingType": "List"}},
    ]})
    assert ps.args["PodTopologySpread"] == {"defaultingType": "List"}


def test_plugin_config_unknown_plugin_args_kept():
    """Out-of-tree plugin args must survive parsing (plugins.go:109-112
    keeps non-in-tree configs verbatim) so custom plugins can read them."""
    ps = parse_profile({"pluginConfig": [
        {"name": "MyCustomPlugin", "args": {"favor": "node-a"}},
    ]})
    assert ps.args["MyCustomPlugin"] == {"favor": "node-a"}


def test_plugin_config_empty_args_ignored():
    ps = parse_profile({"pluginConfig": [{"name": "NodeResourcesFit"}]})
    assert "NodeResourcesFit" not in ps.args


# --------------------------------------------- out-of-tree conversion

def test_convert_wraps_out_of_tree_plugins_too():
    """plugins_test.go:377 'success with non in-tree plugins': custom
    plugin names get the Wrapped suffix and ride the same merge."""
    cfg = convert_configuration_for_simulator({"profiles": [{
        "plugins": {"multiPoint": {
            "disabled": [{"name": "*"}],
            "enabled": [{"name": "CustomPlugin", "weight": 2}]}},
    }]})
    assert _mp(cfg)["enabled"] == [{"name": "CustomPluginWrapped", "weight": 2}]
    assert _mp(cfg)["disabled"] == [{"name": "*"}]


def test_parse_profiles_routes_by_scheduler_name():
    profiles = parse_profiles({"profiles": [
        {"schedulerName": "a", "plugins": {"multiPoint": {
            "disabled": [{"name": "*"}],
            "enabled": [{"name": "NodeResourcesFit"}]}}},
        {"schedulerName": "b", "plugins": {"multiPoint": {
            "disabled": [{"name": "*"}],
            "enabled": [{"name": "NodeResourcesFit"},
                        {"name": "TaintToleration", "weight": 9}]}}},
    ]})
    assert set(profiles) == {"a", "b"}
    assert profiles["a"].enabled == ["NodeResourcesFit"]
    assert profiles["b"].weight("TaintToleration") == 9

def test_default_preemption_args_validation():
    """Upstream ValidateDefaultPreemptionArgs: pct in [0,100], abs >= 0,
    not both (effectively) zero; a rejected config rolls back."""
    ok = {"pluginConfig": [{"name": "DefaultPreemption",
                            "args": {"minCandidateNodesPercentage": 0,
                                     "minCandidateNodesAbsolute": 5}}]}
    parse_profile(ok)  # zero pct alone is valid ("use only the other knob")
    import pytest as _pytest

    for bad in (
        {"minCandidateNodesPercentage": 101},
        {"minCandidateNodesPercentage": -1},
        {"minCandidateNodesAbsolute": -5},
        {"minCandidateNodesPercentage": 0, "minCandidateNodesAbsolute": 0},
    ):
        with _pytest.raises(ValueError):
            parse_profile({"pluginConfig": [
                {"name": "DefaultPreemption", "args": bad}]})


def test_default_config_carries_scheme_defaulted_plugin_args():
    """The defaulted KubeSchedulerConfiguration exposes per-plugin default
    args exactly like the reference's GET /api/v1/schedulerconfiguration
    (DefaultPreemptionArgs 10/100, LeastAllocated cpu/memory, etc.)."""
    cfg = default_scheduler_config()
    pcs = {p["name"]: p["args"] for p in cfg["profiles"][0]["pluginConfig"]}
    assert set(pcs) == {
        "DefaultPreemption", "InterPodAffinity", "NodeAffinity",
        "NodeResourcesBalancedAllocation", "NodeResourcesFit",
        "PodTopologySpread", "VolumeBinding"}
    assert pcs["DefaultPreemption"]["minCandidateNodesPercentage"] == 10
    assert pcs["DefaultPreemption"]["minCandidateNodesAbsolute"] == 100
    assert pcs["NodeResourcesFit"]["scoringStrategy"]["type"] == "LeastAllocated"
    assert pcs["InterPodAffinity"]["hardPodAffinityWeight"] == 1
    assert pcs["PodTopologySpread"]["defaultingType"] == "System"
    assert pcs["VolumeBinding"]["bindTimeoutSeconds"] == 600
    for args in pcs.values():
        assert args["apiVersion"] == "kubescheduler.config.k8s.io/v1"
        assert args["kind"].endswith("Args")


def test_apply_scheme_defaults_on_user_config():
    """A user-applied config gains the scheme defaults the reference's
    decode would attach: missing plugins get full default args; a user
    entry keeps its fields and inherits the rest; unknown plugins pass
    verbatim."""
    from kube_scheduler_simulator_tpu.scheduler.convert import (
        apply_scheme_defaults)

    cfg = apply_scheme_defaults({"profiles": [{
        "schedulerName": "s",
        "pluginConfig": [
            {"name": "DefaultPreemption",
             "args": {"minCandidateNodesAbsolute": 7}},
            {"name": "MyPlugin", "args": {"x": 1}},
        ]}]})
    pcs = {p["name"]: p["args"] for p in cfg["profiles"][0]["pluginConfig"]}
    # user field kept, sibling default filled in
    assert pcs["DefaultPreemption"]["minCandidateNodesAbsolute"] == 7
    assert pcs["DefaultPreemption"]["minCandidateNodesPercentage"] == 10
    # untouched plugins fully defaulted; unknown plugin untouched
    assert pcs["NodeResourcesFit"]["scoringStrategy"]["type"] == "LeastAllocated"
    assert pcs["MyPlugin"] == {"x": 1}
    assert cfg["parallelism"] == 16
    # user entries keep their position; missing defaults append after
    names = [p["name"] for p in cfg["profiles"][0]["pluginConfig"]]
    assert names[:2] == ["DefaultPreemption", "MyPlugin"]
    assert set(names[2:]) == {
        "InterPodAffinity", "NodeAffinity", "NodeResourcesBalancedAllocation",
        "NodeResourcesFit", "PodTopologySpread", "VolumeBinding"}
