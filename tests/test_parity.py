"""CPU-sequential vs TPU-tensor bit-parity over the BASELINE configs.

The correctness gate of BASELINE.md: every result annotation — most
importantly finalscore-result — must be byte-identical between the scalar
sequential reference (reference_impl/sequential.py) and the scan engine
(framework/replay.py + store/decode.py), on every pod of the queue.
"""

import pytest

from kube_scheduler_simulator_tpu.framework.replay import replay
from kube_scheduler_simulator_tpu.models.workloads import baseline_config
from kube_scheduler_simulator_tpu.reference_impl.sequential import SequentialScheduler
from kube_scheduler_simulator_tpu.state.compile import compile_workload
from kube_scheduler_simulator_tpu.store.decode import decode_pod_result


def run_both(idx: int, scale: float, seed: int = 0):
    nodes, pods, cfg = baseline_config(idx, scale=scale, seed=seed)
    seq = SequentialScheduler(nodes, pods, cfg)
    seq_results = seq.schedule_all()

    cw = compile_workload(nodes, pods, cfg)
    rr = replay(cw, chunk=64)
    return seq_results, rr


def assert_parity(seq_results, rr):
    for i, (seq_ann, seq_sel) in enumerate(seq_results):
        dev_ann = decode_pod_result(rr, i)
        dev_sel = int(rr.selected[i])
        assert dev_sel == seq_sel, (
            f"pod {i}: selected node mismatch device={dev_sel} seq={seq_sel}"
        )
        for key in seq_ann:
            assert dev_ann[key] == seq_ann[key], (
                f"pod {i}: annotation {key} mismatch\n device={dev_ann[key][:500]}\n"
                f"    seq={seq_ann[key][:500]}"
            )


@pytest.mark.parametrize("idx,scale", [(1, 1.0), (2, 0.1), (3, 0.02), (4, 0.01), (5, 0.01)])
def test_baseline_config_parity(idx, scale):
    seq_results, rr = run_both(idx, scale)
    assert_parity(seq_results, rr)


def test_some_pods_schedule():
    seq_results, rr = run_both(1, 1.0)
    assert rr.scheduled > 0
    assert (rr.selected >= 0).sum() == sum(1 for _, s in seq_results if s >= 0)
