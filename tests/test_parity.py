"""CPU-sequential vs TPU-tensor bit-parity over the BASELINE configs.

The correctness gate of BASELINE.md: every result annotation — most
importantly finalscore-result — must be byte-identical between the scalar
sequential reference (reference_impl/sequential.py) and the scan engine
(framework/replay.py + store/decode.py), on every pod of the queue.
"""

import pytest

from kube_scheduler_simulator_tpu.framework.replay import replay
from kube_scheduler_simulator_tpu.models.workloads import baseline_config
from kube_scheduler_simulator_tpu.reference_impl.sequential import SequentialScheduler
from kube_scheduler_simulator_tpu.state.compile import compile_workload
from kube_scheduler_simulator_tpu.store.decode import decode_pod_result


def run_both(idx: int, scale: float, seed: int = 0):
    nodes, pods, cfg = baseline_config(idx, scale=scale, seed=seed)
    seq = SequentialScheduler(nodes, pods, cfg)
    seq_results = seq.schedule_all()

    cw = compile_workload(nodes, pods, cfg)
    rr = replay(cw, chunk=64)
    return seq_results, rr


def assert_parity(seq_results, rr):
    for i, (seq_ann, seq_sel) in enumerate(seq_results):
        dev_ann = decode_pod_result(rr, i)
        dev_sel = int(rr.selected[i])
        assert dev_sel == seq_sel, (
            f"pod {i}: selected node mismatch device={dev_sel} seq={seq_sel}"
        )
        for key in seq_ann:
            assert dev_ann[key] == seq_ann[key], (
                f"pod {i}: annotation {key} mismatch\n device={dev_ann[key][:500]}\n"
                f"    seq={seq_ann[key][:500]}"
            )


@pytest.mark.parametrize("idx,scale", [(1, 1.0), (2, 0.1), (3, 0.02), (4, 0.01), (5, 0.01)])
def test_baseline_config_parity(idx, scale):
    seq_results, rr = run_both(idx, scale)
    assert_parity(seq_results, rr)


def test_some_pods_schedule():
    seq_results, rr = run_both(1, 1.0)
    assert rr.scheduled > 0
    assert (rr.selected >= 0).sum() == sum(1 for _, s in seq_results if s >= 0)


@pytest.mark.parametrize("seed", [11, 23, 47])
def test_full_plugin_set_fuzz_parity(seed):
    """Catch-all: the WHOLE default filter/score plugin lineup (all 14
    tensorized plugins incl. the volume family), randomized pods with
    affinity + tolerations + spread + interpod terms, volumes, namespaces
    and a mixed node fleet — every annotation byte-identical between the
    scalar oracle and the scan."""
    import numpy as np

    from kube_scheduler_simulator_tpu.models.workloads import make_nodes, make_pods
    from kube_scheduler_simulator_tpu.plugins.registry import PluginSetConfig

    rng = np.random.default_rng(seed)
    nodes = make_nodes(16, seed=seed, taint_fraction=0.3)
    pods = make_pods(24, seed=seed + 1, with_affinity=True,
                     with_tolerations=True, with_spread=True,
                     with_interpod=True)
    # sprinkle hostPorts and nodeName pins for NodePorts/NodeName coverage
    for p in pods:
        if rng.random() < 0.2:
            p["spec"]["containers"][0]["ports"] = [
                {"hostPort": int(rng.integers(30000, 30006))}]
        if rng.random() < 0.05:
            p["spec"]["nodeName"] = f"node-{int(rng.integers(16)):05d}"
    scs = [{"metadata": {"name": "standard"},
            "provisioner": "x", "volumeBindingMode": "WaitForFirstConsumer"}]
    pvcs, pvs = [], []
    for i in range(6):
        pvcs.append({"metadata": {"name": f"claim-{i}", "namespace": "default",
                                  "uid": f"uid-{i}"},
                     "spec": {"storageClassName": "standard",
                              "accessModes": ["ReadWriteOnce"],
                              "resources": {"requests": {"storage": "1Gi"}}}})
        pvs.append({"metadata": {"name": f"pv-{i}"},
                    "spec": {"capacity": {"storage": "2Gi"},
                             "accessModes": ["ReadWriteOnce"],
                             "storageClassName": "standard"}})
    for i, p in enumerate(pods[:6]):
        p["spec"]["volumes"] = [{"name": "v",
                                 "persistentVolumeClaim": {"claimName": f"claim-{i}"}}]
    volumes = {"pvcs": pvcs, "pvs": pvs, "storageclasses": scs}
    cfg = PluginSetConfig(enabled=[
        "NodeUnschedulable", "NodeName", "TaintToleration", "NodeAffinity",
        "NodePorts", "NodeResourcesFit", "VolumeRestrictions", "VolumeZone",
        "NodeVolumeLimits", "VolumeBinding", "PodTopologySpread",
        "InterPodAffinity", "NodeResourcesBalancedAllocation", "ImageLocality",
    ])
    seq_results = SequentialScheduler(nodes, pods, cfg, volumes=volumes).schedule_all()
    cw = compile_workload(nodes, pods, cfg, volumes=volumes)
    rr = replay(cw, chunk=8)
    assert_parity(seq_results, rr)
