"""NodePorts, ImageLocality, SchedulingGates tests.

Semantics sources: upstream v1.32 nodeports/imagelocality/schedulinggates
plugins, recorded via the reference shim
(reference: simulator/scheduler/plugin/wrappedplugin.go:420-445,523-548).
"""

import json

from kube_scheduler_simulator_tpu.cluster.store import ObjectStore
from kube_scheduler_simulator_tpu.framework.engine import SchedulerEngine
from kube_scheduler_simulator_tpu.framework.replay import replay
from kube_scheduler_simulator_tpu.plugins import imagelocality
from kube_scheduler_simulator_tpu.plugins.registry import PluginSetConfig
from kube_scheduler_simulator_tpu.reference_impl.sequential import SequentialScheduler
from kube_scheduler_simulator_tpu.state.compile import compile_workload
from kube_scheduler_simulator_tpu.store import annotations as ann
from kube_scheduler_simulator_tpu.store.decode import decode_pod_result


def node(name, cpu="4", images=None):
    n = {
        "apiVersion": "v1", "kind": "Node",
        "metadata": {"name": name, "labels": {"kubernetes.io/hostname": name}},
        "spec": {},
        "status": {
            "allocatable": {"cpu": cpu, "memory": "8Gi", "pods": "110"},
            "capacity": {"cpu": cpu, "memory": "8Gi", "pods": "110"},
        },
    }
    if images:
        n["status"]["images"] = images
    return n


def pod(name, ports=None, image="app:v1", gates=None, node_name=None):
    p = {
        "apiVersion": "v1", "kind": "Pod",
        "metadata": {"name": name, "namespace": "default"},
        "spec": {
            "containers": [{
                "name": "c", "image": image,
                "resources": {"requests": {"cpu": "100m"}},
            }],
        },
        "status": {},
    }
    if ports:
        p["spec"]["containers"][0]["ports"] = ports
    if gates:
        p["spec"]["schedulingGates"] = gates
    if node_name:
        p["spec"]["nodeName"] = node_name
        p["status"]["phase"] = "Running"
    return p


def parity_check(nodes, pods, cfg):
    seq = SequentialScheduler(nodes, pods, cfg).schedule_all()
    rr = replay(compile_workload(nodes, pods, cfg), chunk=8)
    for i, (sa, ssel) in enumerate(seq):
        da = decode_pod_result(rr, i)
        assert int(rr.selected[i]) == ssel, f"pod {i} selection"
        for k, v in sa.items():
            assert da[k] == v, f"pod {i} {k}:\n dev={da[k]}\n seq={v}"


# ---------------------------------------------------------------- NodePorts

def test_nodeports_conflict_blocks_node():
    s = ObjectStore()
    s.create("nodes", node("n1"))
    s.create("pods", pod("a", ports=[{"containerPort": 80, "hostPort": 8080}], node_name="n1"))
    s.create("pods", pod("b", ports=[{"containerPort": 80, "hostPort": 8080}]))
    engine = SchedulerEngine(s)
    assert engine.schedule_pending() == 0
    annos = s.get("pods", "b")["metadata"]["annotations"]
    fr = json.loads(annos[ann.FILTER_RESULT])
    assert fr["n1"]["NodePorts"] == "node(s) didn't have free ports for the requested pod ports"


def test_nodeports_protocol_and_ip_rules():
    from kube_scheduler_simulator_tpu.plugins.ports import sequential_conflict

    # same port different protocol: no conflict
    assert not sequential_conflict([("UDP", 80, "0.0.0.0")], [("TCP", 80, "0.0.0.0")])
    # specific IPs differ: no conflict
    assert not sequential_conflict([("TCP", 80, "10.0.0.1")], [("TCP", 80, "10.0.0.2")])
    # wildcard vs specific: conflict
    assert sequential_conflict([("TCP", 80, "0.0.0.0")], [("TCP", 80, "10.0.0.2")])
    assert sequential_conflict([("TCP", 80, "10.0.0.2")], [("TCP", 80, "0.0.0.0")])


def test_nodeports_sequence_parity():
    nodes = [node("a"), node("b")]
    pods = [
        pod("p0", ports=[{"containerPort": 80, "hostPort": 8080}]),
        pod("p1", ports=[{"containerPort": 80, "hostPort": 8080}]),
        pod("p2", ports=[{"containerPort": 80, "hostPort": 8080}]),  # no node left
        pod("p3"),  # no ports: PreFilter Skip
        pod("p4", ports=[{"containerPort": 80, "hostPort": 9090, "hostIP": "10.0.0.1"}]),
    ]
    cfg = PluginSetConfig(enabled=[
        "NodeUnschedulable", "NodeName", "NodePorts", "NodeResourcesFit",
        "NodeResourcesBalancedAllocation",
    ])
    parity_check(nodes, pods, cfg)


def test_nodeports_prefilter_skip_recorded():
    nodes = [node("a"), node("b")]
    pods = [pod("p", ports=None)]
    cfg = PluginSetConfig(enabled=["NodePorts", "NodeResourcesFit"])
    rr = replay(compile_workload(nodes, pods, cfg), chunk=1)
    da = decode_pod_result(rr, 0)
    pf = json.loads(da[ann.PRE_FILTER_STATUS_RESULT])
    assert pf["NodePorts"] == ""  # Skip
    fr = json.loads(da[ann.FILTER_RESULT])
    assert "NodePorts" not in fr.get("a", {})


# ---------------------------------------------------------------- ImageLocality

IMAGES_A = [{"names": ["app:v1"], "sizeBytes": 500 * 1024 * 1024}]


def test_imagelocality_prefers_node_with_image():
    nodes = [node("a", images=IMAGES_A), node("b")]
    pods = [pod("p", image="app:v1")]
    cfg = PluginSetConfig(enabled=["NodeResourcesFit", "ImageLocality"])
    rr = replay(compile_workload(nodes, pods, cfg), chunk=1)
    assert int(rr.selected[0]) == 0
    da = decode_pod_result(rr, 0)
    sc = json.loads(da[ann.SCORE_RESULT])
    # 500MB * (1/2 nodes having it) = 250MB -> (250-23)/(1000-23) * 100 = 23
    assert sc["a"]["ImageLocality"] == "23"
    assert sc["b"]["ImageLocality"] == "0"


def test_imagelocality_untagged_normalizes_to_latest():
    assert imagelocality.normalized_image_name("nginx") == "nginx:latest"
    assert imagelocality.normalized_image_name("nginx:1.2") == "nginx:1.2"
    assert imagelocality.normalized_image_name("repo/img@sha256:ab") == "repo/img@sha256:ab"
    assert imagelocality.normalized_image_name("host:5000/img") == "host:5000/img:latest"


def test_imagelocality_sequence_parity():
    nodes = [node("a", images=IMAGES_A), node("b"), node("c", images=IMAGES_A)]
    pods = [pod(f"p{i}", image="app:v1") for i in range(4)] + [pod("q", image="other:v2")]
    cfg = PluginSetConfig(enabled=[
        "NodeResourcesFit", "NodeResourcesBalancedAllocation", "ImageLocality",
    ])
    parity_check(nodes, pods, cfg)


# ---------------------------------------------------------------- SchedulingGates

def test_gated_pod_not_scheduled():
    s = ObjectStore()
    s.create("nodes", node("n1"))
    s.create("pods", pod("gated", gates=[{"name": "example.com/hold"}]))
    s.create("pods", pod("free"))
    engine = SchedulerEngine(s)
    assert engine.schedule_pending() == 1
    g = s.get("pods", "gated")
    assert not g["spec"].get("nodeName")
    cond = g["status"]["conditions"][0]
    assert cond["reason"] == "SchedulingGated"
    assert s.get("pods", "free")["spec"]["nodeName"] == "n1"
    # no scheduling-cycle annotations for a gated pod (it never enqueued)
    assert ann.SELECTED_NODE not in (g["metadata"].get("annotations") or {})


def test_gate_removal_unblocks():
    s = ObjectStore()
    s.create("nodes", node("n1"))
    s.create("pods", pod("gated", gates=[{"name": "example.com/hold"}]))
    engine = SchedulerEngine(s)
    assert engine.schedule_pending() == 0
    g = s.get("pods", "gated")
    g["spec"]["schedulingGates"] = []
    s.update("pods", g)
    assert engine.schedule_pending() == 1
    assert s.get("pods", "gated")["spec"]["nodeName"] == "n1"


def test_fit_ignored_resources_and_groups():
    """NodeResourcesFitArgs.ignoredResources / ignoredResourceGroups skip
    extended resources in the fit check (upstream fitsRequest); native
    resources are never ignorable. Tensor path and oracle agree."""
    from kube_scheduler_simulator_tpu.framework.replay import replay
    from kube_scheduler_simulator_tpu.plugins.registry import PluginSetConfig
    from kube_scheduler_simulator_tpu.reference_impl.sequential import (
        SequentialScheduler)
    from kube_scheduler_simulator_tpu.state.compile import compile_workload
    from kube_scheduler_simulator_tpu.store.decode import decode_pod_result

    nodes = [{"metadata": {"name": "n1"},
              "status": {"allocatable": {
                  "cpu": "4", "memory": "8Gi", "pods": "10",
                  "example.com/gpu": "1", "other.io/fpga": "1"}}}]
    pods = [{"metadata": {"name": "p", "namespace": "default"},
             "spec": {"containers": [{"name": "c", "resources": {"requests": {
                 "cpu": "1", "memory": "1Gi",
                 "example.com/gpu": "2",       # over capacity but ignored
                 "other.io/fpga": "2",         # over capacity, group-ignored
             }}}]}}]
    cfg = PluginSetConfig(
        enabled=["NodeResourcesFit"],
        args={"NodeResourcesFit": {
            "ignoredResources": ["example.com/gpu"],
            "ignoredResourceGroups": ["other.io"]}})
    seq = SequentialScheduler(nodes, pods, cfg).schedule_all()
    rr = replay(compile_workload(nodes, pods, cfg), chunk=2)
    assert int(rr.selected[0]) == 0          # schedules despite the overask
    assert decode_pod_result(rr, 0) == seq[0][0]
    assert seq[0][1] == 0

    # without the ignore args the same pod is rejected with both reasons
    cfg2 = PluginSetConfig(enabled=["NodeResourcesFit"])
    rr2 = replay(compile_workload(nodes, pods, cfg2), chunk=2)
    assert int(rr2.selected[0]) == -1
    import json

    from kube_scheduler_simulator_tpu.store import annotations as ann

    fr = json.loads(decode_pod_result(rr2, 0)[ann.FILTER_RESULT])
    assert "Insufficient example.com/gpu" in fr["n1"]["NodeResourcesFit"]
