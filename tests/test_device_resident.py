"""Device-resident replay results (framework/replay.py device-residency
stage): decision-only in-wave fetch, on-demand D2H materialization.

The parity rule extends PR 9's (docs/wave-pipeline.md): whatever a
reader observes — pod annotations, result-history, bind order,
attribution tallies — must be bit-identical across the three residency
rungs: the device-resident default, KSS_TPU_HOST_RESIDENT=1 (lazy
decode, in-wave host fetch — the PR 9 behavior) and
KSS_TPU_EAGER_DECODE=1 (full eager), including waves run on a mesh and
chunks spilled to host by the KSS_TPU_DEVICE_RESULT_BUDGET_MB budget.
"""

from __future__ import annotations

import copy
import queue as queue_mod
import sys
import threading

import numpy as np
import pytest

from kube_scheduler_simulator_tpu.cluster.store import ObjectStore, list_shared
from kube_scheduler_simulator_tpu.framework.engine import SchedulerEngine
from kube_scheduler_simulator_tpu.framework.replay import (
    _DEVICE_BUDGET, plugin_attribution, replay)
from kube_scheduler_simulator_tpu.models.workloads import (
    baseline_config, make_nodes, make_pods)
from kube_scheduler_simulator_tpu.plugins.registry import PluginSetConfig
from kube_scheduler_simulator_tpu.state.compile import compile_workload
from kube_scheduler_simulator_tpu.store.decode import decode_pod_result
from kube_scheduler_simulator_tpu.utils.tracing import TRACER

ENABLED = ["NodeResourcesFit", "NodeResourcesBalancedAllocation",
           "NodeAffinity", "TaintToleration", "PodTopologySpread"]

replay_mod = sys.modules["kube_scheduler_simulator_tpu.framework.replay"]


def _mode(monkeypatch, mode: str) -> None:
    monkeypatch.delenv("KSS_TPU_EAGER_DECODE", raising=False)
    monkeypatch.delenv("KSS_TPU_HOST_RESIDENT", raising=False)
    monkeypatch.delenv("KSS_TPU_DEVICE_RESULT_BUDGET_MB", raising=False)
    if mode == "eager":
        monkeypatch.setenv("KSS_TPU_EAGER_DECODE", "1")
    elif mode == "host":
        monkeypatch.setenv("KSS_TPU_HOST_RESIDENT", "1")
    else:
        assert mode == "device"


def _mixed_workload():
    """Taints, affinity/toleration pods, host score columns (spread) and
    two prefilter-rejected pods mid-queue — the chunk-decode special
    cases (tests/test_lazy_decode.py recipe; 16 nodes so an 8-way mesh
    divides the node axis)."""
    nodes = make_nodes(16, seed=3, taint_fraction=0.3)
    pods = make_pods(50, seed=4, with_affinity=True, with_tolerations=True,
                     with_spread=True)
    for j, at in enumerate((7, 33)):
        pods.insert(at, {
            "metadata": {"name": f"pvc-pod-{j}", "namespace": "default"},
            "spec": {
                "containers": [{"name": "c", "resources": {
                    "requests": {"cpu": "100m"}}}],
                "volumes": [{"name": "v", "persistentVolumeClaim": {
                    "claimName": f"missing-{j}"}}],
            },
        })
    for i, p in enumerate(pods):
        p["spec"]["priority"] = (i % 3) * 100
    return nodes, pods


def _run_wave(nodes, pods, pipeline=True, chunk=16, mesh=None):
    """Schedule once; -> (engine, store, bound, bind_order)."""
    store = ObjectStore()
    for n in nodes:
        store.create("nodes", copy.deepcopy(n))
    for p in pods:
        store.create("pods", copy.deepcopy(p))
    q = store.watch("pods")
    engine = SchedulerEngine(store, plugin_config=PluginSetConfig(
        enabled=list(ENABLED)), chunk=chunk, pipeline_commit=pipeline,
        mesh=mesh)
    bound = engine.schedule_pending()
    bind_order, seen = [], set()
    while True:
        try:
            _rv, event_type, obj = q.get_nowait()
        except queue_mod.Empty:
            break
        name = obj["metadata"]["name"]
        if (event_type == "MODIFIED"
                and (obj.get("spec") or {}).get("nodeName")
                and name not in seen):
            seen.add(name)
            bind_order.append(name)
    store.unwatch("pods", q)
    return engine, store, bound, bind_order


def _read_all(store) -> dict[str, dict]:
    return {p["metadata"]["name"]: p["metadata"].get("annotations") or {}
            for p in store.list("pods")[0]}


def _assert_same(anns_a: dict, anns_b: dict, what: str) -> None:
    assert anns_a.keys() == anns_b.keys()
    for name in anns_a:
        for key in set(anns_a[name]) | set(anns_b[name]):
            assert anns_a[name].get(key) == anns_b[name].get(key), (
                f"pod {name} key {key} diverged ({what})")


# ----------------------------------------------------- three-rung parity


@pytest.mark.parametrize("pipeline", [True, False])
def test_three_rung_byte_parity(monkeypatch, pipeline):
    """Device-resident (default), host-resident-lazy and eager runs of
    the same mixed wave are byte-identical in annotations,
    result-history, bind count and bind order — streaming commit and
    sequential post-pass both."""
    nodes, pods = _mixed_workload()
    results = {}
    for mode in ("device", "host", "eager"):
        _mode(monkeypatch, mode)
        TRACER.reset()
        engine, store, bound, order = _run_wave(nodes, pods,
                                                pipeline=pipeline)
        if mode == "device":
            # residency really happened: the wave itself moved only
            # decision rows, and chunks are registered with the budget
            wave_bytes = TRACER.summary()["counters"].get(
                "wave_d2h_bytes_total", 0)
            assert _DEVICE_BUDGET.retained_chunks() > 0
            assert wave_bytes < 64 * len(pods) + 4096, wave_bytes
        results[mode] = (bound, order, _read_all(store))
    b0, o0, a0 = results["eager"]
    for mode in ("device", "host"):
        b, o, a = results[mode]
        assert b == b0 and o == o0
        _assert_same(a, a0, f"{mode} vs eager")


def test_mesh_sharded_wave_parity(monkeypatch):
    """A device-resident wave run on an 8-virtual-device mesh (node axis
    sharded) reads back bit-identical to the eager unsharded wave — the
    cold read's materialization gathers the shards."""
    from kube_scheduler_simulator_tpu.parallel.mesh import make_mesh

    nodes, pods = _mixed_workload()
    _mode(monkeypatch, "eager")
    _, store_e, bound_e, _ = _run_wave(nodes, pods)
    baseline = _read_all(store_e)

    _mode(monkeypatch, "device")
    mesh = make_mesh(8, dp=1)
    engine, store, bound, _ = _run_wave(nodes, pods, mesh=mesh)
    assert bound == bound_e
    _assert_same(_read_all(store), baseline, "mesh device-resident vs eager")


def test_replay_level_mesh_attribution_parity(monkeypatch):
    """plugin_attribution over a mesh-sharded device-resident replay
    equals the host tally of a host-resident replay — the jit'd
    reduction's cross-shard sums ride GSPMD collectives."""
    from kube_scheduler_simulator_tpu.parallel.mesh import make_mesh

    nodes, pods = _mixed_workload()
    cfg = PluginSetConfig(enabled=list(ENABLED))
    cw = compile_workload(nodes, pods, cfg)
    _mode(monkeypatch, "device")
    rr_mesh = replay(cw, chunk=16, mesh=make_mesh(8, dp=1))
    att_mesh = plugin_attribution(rr_mesh)
    _mode(monkeypatch, "host")
    rr_host = replay(cw, chunk=16)
    att_host = plugin_attribution(rr_host)
    assert att_mesh == att_host
    # and the device fold really was the source: no chunk materialized
    assert all(rr_mesh._compact.is_device(ci)
               for ci in range(len(rr_mesh._compact.packed)))


def test_attribution_device_fold_matches_host_tally(monkeypatch):
    """The on-device reduction (limb-recombined score sums, bitmap-fed
    host columns) is bit-identical to the host tally over the same
    replay values, and computing it never materializes a chunk."""
    nodes, pods = _mixed_workload()
    cfg = PluginSetConfig(enabled=list(ENABLED))
    cw = compile_workload(nodes, pods, cfg)
    _mode(monkeypatch, "device")
    rr = replay(cw, chunk=16)
    cc = rr._compact
    assert any(a is not None for a in cc.att)
    att_dev = plugin_attribution(rr)
    assert all(cc.is_device(ci) for ci in range(len(cc.packed)))
    # force the host tally over the SAME result: drop the device sums
    cc.att = [None] * len(cc.att)
    att_host = plugin_attribution(rr)
    assert att_dev == att_host


# ------------------------------------------------- width-tier re-runs


def test_width_tier_rerun_with_device_chunks(monkeypatch):
    """An injected score-width overflow re-runs the scan wider while the
    first tier's chunks were retained on device; the final result's
    annotations stay identical to pure Python and the first tier's
    retained chunks release their budget accounting."""
    nodes, pods, cfg = baseline_config(4, scale=0.02, seed=11)
    cw = compile_workload(nodes, pods, cfg)
    _mode(monkeypatch, "device")

    real_fetch = replay_mod._fetch_decisions
    state = {"fired": False, "count": 0}

    def inject_overflow(out_dev, att):
        c = real_fetch(out_dev, att)
        state["count"] += 1
        if not state["fired"] and state["count"] == 3:
            c["raw_overflow"] = np.asarray(True)
            state["fired"] = True
        return c

    monkeypatch.setattr(replay_mod, "_fetch_decisions", inject_overflow)
    before = TRACER.summary()["counters"].get("replay_width_retries_total", 0)
    retained0 = _DEVICE_BUDGET.retained_chunks()
    rr = replay(cw, chunk=32)
    retries = TRACER.summary()["counters"].get(
        "replay_width_retries_total", 0) - before
    assert retries >= 1, "no width retry triggered"
    import gc

    gc.collect()  # the abandoned first-tier compact drops its entries
    final_chunks = len(rr._compact.packed)
    assert _DEVICE_BUDGET.retained_chunks() - retained0 <= final_chunks

    out = [decode_pod_result(rr, i) for i in range(len(pods))]
    monkeypatch.setenv("KSS_TPU_DISABLE_NATIVE", "1")
    try:
        pure = [decode_pod_result(rr, i) for i in range(len(pods))]
    finally:
        monkeypatch.delenv("KSS_TPU_DISABLE_NATIVE")
    assert out == pure


# -------------------------------------------------- concurrent cold reads


def test_concurrent_cold_reads_one_d2h_per_chunk(monkeypatch):
    """8-thread cold-read soak over a device-resident wave: every read
    returns eager-identical bytes, and each chunk crosses the
    host/device boundary EXACTLY once (one d2h_fetch span per chunk;
    concurrent readers wait on the materialize owner)."""
    nodes, pods = _mixed_workload()
    _mode(monkeypatch, "eager")
    _, store_e, _, _ = _run_wave(nodes, pods)
    baseline = _read_all(store_e)

    _mode(monkeypatch, "device")
    engine, store, _, _ = _run_wave(nodes, pods, chunk=16)
    n_chunks = (len(pods) + 15) // 16
    TRACER.reset()

    names = [p["metadata"]["name"] for p in list_shared(store, "pods")]
    errors: list = []
    results: dict[str, dict] = {}
    res_mu = threading.Lock()
    start = threading.Barrier(8)

    def reader(k):
        try:
            start.wait()
            for name in names[k::2]:
                a = store.get("pods", name, "default")["metadata"] \
                    .get("annotations") or {}
                with res_mu:
                    prev = results.setdefault(name, a)
                assert prev == a
        except BaseException as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=reader, args=(k % 2,))
               for k in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors
    for name, a in results.items():
        for key in baseline[name]:
            assert a.get(key) == baseline[name][key], (name, key)
    spans = TRACER.summary()["spans"]
    assert spans.get("d2h_fetch", {}).get("count") == n_chunks, (
        f"expected exactly {n_chunks} chunk materializations, got "
        f"{spans.get('d2h_fetch')}")
    assert spans.get("decode_lazy", {}).get("count") == n_chunks


# ------------------------------------------------------- retention budget


def test_spill_then_read_round_trip(monkeypatch):
    """KSS_TPU_DEVICE_RESULT_BUDGET_MB=0 spills every retained chunk to
    host on the background writer; reads after the spill return the
    eager bytes, and the spill taps record."""
    nodes, pods = _mixed_workload()
    _mode(monkeypatch, "eager")
    _, store_e, _, _ = _run_wave(nodes, pods)
    baseline = _read_all(store_e)

    _mode(monkeypatch, "device")
    monkeypatch.setenv("KSS_TPU_DEVICE_RESULT_BUDGET_MB", "0")
    TRACER.reset()
    engine, store, _, _ = _run_wave(nodes, pods, chunk=16)
    _DEVICE_BUDGET.drain()
    counters = TRACER.summary()["counters"]
    assert counters.get("device_chunks_spilled_total", 0) >= 1
    snap = TRACER.snapshot()
    assert snap["gauges"].get("device_chunks_retained") == 0
    # spilled chunks are plain host chunks now: reads bit-identical,
    # and cold reads do NOT pay (or count) an on-demand D2H
    _assert_same(_read_all(store), baseline, "spill round-trip vs eager")
    assert "d2h_fetch" not in TRACER.summary()["spans"]


def test_budget_taps_and_exposition(monkeypatch):
    """The d2h taps (bytes counter + latency histogram + span) record on
    a cold read of a device-resident wave, the retained gauge tracks,
    and the exposition stays strictly valid."""
    from kube_scheduler_simulator_tpu.utils.tracing import validate_exposition

    nodes, pods = _mixed_workload()
    _mode(monkeypatch, "device")
    engine, store, _, _ = _run_wave(nodes, pods, chunk=16)
    TRACER.reset()
    store.get("pods", pods[0]["metadata"]["name"], "default")   # cold
    counters = TRACER.summary()["counters"]
    assert counters.get("d2h_on_demand_bytes_total", 0) > 0
    snap = TRACER.snapshot()
    assert snap["histograms"]["d2h_on_demand_seconds"]["series"][0]["count"] >= 1
    assert "d2h_fetch" in snap["spans"]
    assert "device_chunks_retained" in snap["gauges"]
    validate_exposition(TRACER.prometheus_text())


# -------------------------------------------------------- scan-cache LRU


def test_scan_cache_lru_alternating_shapes(monkeypatch):
    """_SCAN_CACHE is LRU, not insertion-order FIFO: two alternating
    workload shapes at capacity keep their compiled scans while a third
    evicts only the least-recently-USED entry."""
    nodes = make_nodes(4, seed=1)
    pods = make_pods(6, seed=2)
    cfg = PluginSetConfig(enabled=["NodeResourcesFit"])
    cw = compile_workload(nodes, pods, cfg)

    cache = replay_mod._SCAN_CACHE
    monkeypatch.setattr(cache, "max_entries", 2)
    saved = dict(cache._entries)
    cache._entries.clear()
    try:
        from kube_scheduler_simulator_tpu.framework.replay import _scan_for

        a = _scan_for(cw, chunk=2)   # shape A
        b = _scan_for(cw, chunk=3)   # shape B — cache full
        assert _scan_for(cw, chunk=2) is a   # hit moves A to recent end
        c = _scan_for(cw, chunk=4)   # evicts B (LRU), NOT A
        assert _scan_for(cw, chunk=2) is a, \
            "LRU must keep the just-hit entry on eviction"
        assert _scan_for(cw, chunk=4) is c
        assert _scan_for(cw, chunk=3) is not b, "B was the LRU victim"
    finally:
        cache._entries.clear()
        cache._entries.update(saved)


def test_scan_cache_interleave_beyond_capacity(monkeypatch):
    """_SCAN_CACHE_MAX+1 interleaved shapes: the hot alternating pair
    survives a full interleave cycle (the FIFO behavior this replaces
    evicted whichever entry was INSERTED first, recompiling the hot
    shapes every pass)."""
    nodes = make_nodes(4, seed=1)
    pods = make_pods(6, seed=2)
    cfg = PluginSetConfig(enabled=["NodeResourcesFit"])
    cw = compile_workload(nodes, pods, cfg)

    cache = replay_mod._SCAN_CACHE
    monkeypatch.setattr(cache, "max_entries", 3)
    saved = dict(cache._entries)
    cache._entries.clear()
    try:
        from kube_scheduler_simulator_tpu.framework.replay import _scan_for

        hot = [_scan_for(cw, chunk=2), _scan_for(cw, chunk=3)]
        for cold_chunk in (4, 5, 6, 7):  # max_entries+1 shapes total
            # touch the hot pair, then one cold shape — the cold shapes
            # must evict each other, never the just-touched pair
            assert _scan_for(cw, chunk=2) is hot[0]
            assert _scan_for(cw, chunk=3) is hot[1]
            _scan_for(cw, chunk=cold_chunk)
        assert _scan_for(cw, chunk=2) is hot[0]
        assert _scan_for(cw, chunk=3) is hot[1]
    finally:
        cache._entries.clear()
        cache._entries.update(saved)
