"""Per-extension-point enable/disable semantics: upstream profiles can
disable a plugin at ONE point while it stays active at the others
(scheduler_test.go:401 'disable a specific default multipoint plugin on a
extension point'), or enable a plugin only at one point.
"""

import json

from kube_scheduler_simulator_tpu.cluster.store import ObjectStore
from kube_scheduler_simulator_tpu.framework.engine import SchedulerEngine
from kube_scheduler_simulator_tpu.scheduler.convert import parse_profile
from kube_scheduler_simulator_tpu.store import annotations as ann


def test_score_point_disable_keeps_filtering():
    ps = parse_profile({"plugins": {
        "score": {"disabled": [{"name": "TaintToleration"}]}}})
    assert "TaintToleration" in ps.filters()
    assert "TaintToleration" not in ps.scorers()
    # untouched points keep the full lineup
    assert "TaintToleration" in ps.prescorers()


def test_filter_point_disable_keeps_scoring():
    ps = parse_profile({"plugins": {
        "filter": {"disabled": [{"name": "TaintToleration"}]}}})
    assert "TaintToleration" not in ps.filters()
    assert "TaintToleration" in ps.scorers()


def test_star_disable_with_point_enable():
    ps = parse_profile({"plugins": {
        "filter": {"disabled": [{"name": "*"}],
                   "enabled": [{"name": "NodeResourcesFit"}]}}})
    assert ps.filters() == ["NodeResourcesFit"]
    # scoring untouched by the filter-point wipe
    assert "NodeResourcesBalancedAllocation" in ps.scorers()


def test_wrapped_names_accepted_in_point_sets():
    ps = parse_profile({"plugins": {
        "score": {"disabled": [{"name": "TaintTolerationWrapped"}]}}})
    assert "TaintToleration" not in ps.scorers()


def test_prescore_point_disable():
    ps = parse_profile({"plugins": {
        "preScore": {"disabled": [{"name": "PodTopologySpread"}]}}})
    assert "PodTopologySpread" not in ps.prescorers()
    assert "PodTopologySpread" in ps.filters()


def test_postfilter_disable_turns_off_preemption():
    ps = parse_profile({"plugins": {
        "postFilter": {"disabled": [{"name": "DefaultPreemption"}]}}})
    assert ps.postfilters() == []


def test_point_disable_flows_to_annotations_and_matches_oracle():
    """A score-point disable changes both the tensor path's annotations
    and the oracle identically: the plugin appears in filter-result but
    not in score/finalscore."""
    from kube_scheduler_simulator_tpu.models.workloads import make_nodes, make_pods
    from kube_scheduler_simulator_tpu.reference_impl.sequential import (
        SequentialScheduler)
    from kube_scheduler_simulator_tpu.state.compile import compile_workload
    from kube_scheduler_simulator_tpu.framework.replay import replay
    from kube_scheduler_simulator_tpu.store.decode import decode_pod_result

    cfg = parse_profile({"plugins": {
        "multiPoint": {"disabled": [{"name": "*"}],
                       "enabled": [{"name": "NodeResourcesFit"},
                                   {"name": "TaintToleration", "weight": 3},
                                   {"name": "NodeResourcesBalancedAllocation"}]},
        "score": {"disabled": [{"name": "TaintToleration"}]},
    }})
    nodes = make_nodes(6, seed=3, taint_fraction=0.3)
    pods = make_pods(8, seed=4, with_tolerations=True)

    seq = SequentialScheduler(nodes, pods, cfg).schedule_all()
    rr = replay(compile_workload(nodes, pods, cfg), chunk=8)
    for i, (seq_anns, seq_sel) in enumerate(seq):
        tensor_anns = decode_pod_result(rr, i)
        assert int(rr.selected[i]) == seq_sel, f"pod {i} selection diverged"
        assert tensor_anns == seq_anns, f"pod {i} diverged"
        fs = json.loads(tensor_anns[ann.FINAL_SCORE_RESULT])
        for per_plugin in fs.values():
            assert "TaintToleration" not in per_plugin
        fr = json.loads(tensor_anns[ann.FILTER_RESULT])
        assert any("TaintToleration" in m for m in fr.values())


def test_engine_point_disable_end_to_end():
    store = ObjectStore()
    store.create("nodes", {
        "metadata": {"name": "tainted"},
        "spec": {"taints": [{"key": "dedicated", "value": "x",
                             "effect": "NoSchedule"}]},
        "status": {"allocatable": {"cpu": "8", "memory": "16Gi", "pods": "10"}}})
    store.create("nodes", {
        "metadata": {"name": "clean"},
        "status": {"allocatable": {"cpu": "8", "memory": "16Gi", "pods": "10"}}})
    store.create("pods", {"metadata": {"name": "p", "namespace": "default"},
                          "spec": {"containers": [{"name": "c", "resources": {
                              "requests": {"cpu": "1", "memory": "1Gi"}}}]}})
    engine = SchedulerEngine(store)
    from kube_scheduler_simulator_tpu.scheduler.service import SchedulerService

    svc = SchedulerService(engine)
    cfg = svc.get_config()
    # disable TaintToleration at the FILTER point only: the untolerated
    # taint no longer excludes the node
    cfg["profiles"][0]["plugins"] = {
        "filter": {"disabled": [{"name": "TaintToleration"}]}}
    svc.restart_scheduler(cfg)
    assert engine.schedule_pending() == 1
    p = store.get("pods", "p")
    fr = json.loads(p["metadata"]["annotations"][ann.FILTER_RESULT])
    assert all("TaintToleration" not in m for m in fr.values())
    assert "tainted" in fr  # the node was NOT filtered out by the taint


def test_score_only_enable_does_not_filter():
    """A plugin enabled only at the score point must not also filter
    (upstream per-point semantics)."""
    ps = parse_profile({"plugins": {
        "multiPoint": {"disabled": [{"name": "*"}],
                       "enabled": [{"name": "NodeName"}]},
        "score": {"enabled": [{"name": "NodeResourcesFit", "weight": 2}]},
    }})
    assert "NodeResourcesFit" not in ps.filters()
    assert "NodeResourcesFit" in ps.scorers()
    assert ps.weight("NodeResourcesFit") == 2
    assert "NodeResourcesFit" in ps.active_plugins()


def test_enable_and_disable_same_point_enable_wins():
    """mergePluginSet: disables suppress the DEFAULT entry; an explicit
    enable re-appends the plugin (it runs, last)."""
    ps = parse_profile({"plugins": {
        "filter": {"disabled": [{"name": "TaintToleration"}],
                   "enabled": [{"name": "TaintToleration"}]}}})
    assert ps.filters()[-1] == "TaintToleration"


def test_star_disable_keeps_user_enable_order():
    ps = parse_profile({"plugins": {
        "filter": {"disabled": [{"name": "*"}],
                   "enabled": [{"name": "NodeResourcesFit"},
                               {"name": "NodeUnschedulable"}]}}})
    assert ps.filters() == ["NodeResourcesFit", "NodeUnschedulable"]


def test_point_enable_requires_capability():
    """Enabling a plugin at a point it does not implement is ignored
    (upstream rejects the profile; we drop the entry)."""
    ps = parse_profile({"plugins": {
        "filter": {"enabled": [{"name": "ImageLocality"}]}}})
    assert "ImageLocality" not in ps.filters()


def test_point_only_enable_schedules_and_matches_oracle():
    """A filter-point-only enable of a plugin outside the global set
    compiles (active_plugins covers it) and stays bit-parity with the
    oracle."""
    from kube_scheduler_simulator_tpu.models.workloads import make_nodes, make_pods
    from kube_scheduler_simulator_tpu.reference_impl.sequential import (
        SequentialScheduler)
    from kube_scheduler_simulator_tpu.state.compile import compile_workload
    from kube_scheduler_simulator_tpu.framework.replay import replay
    from kube_scheduler_simulator_tpu.store.decode import decode_pod_result

    cfg = parse_profile({"plugins": {
        "multiPoint": {"disabled": [{"name": "*"}],
                       "enabled": [{"name": "NodeResourcesFit"}]},
        "filter": {"enabled": [{"name": "TaintToleration"}]},
    }})
    assert "TaintToleration" in cfg.filters()
    assert "TaintToleration" not in cfg.scorers()
    nodes = make_nodes(5, seed=9, taint_fraction=0.5)
    pods = make_pods(6, seed=10, with_tolerations=True)
    seq = SequentialScheduler(nodes, pods, cfg).schedule_all()
    rr = replay(compile_workload(nodes, pods, cfg), chunk=8)
    for i, (seq_anns, seq_sel) in enumerate(seq):
        assert int(rr.selected[i]) == seq_sel
        assert decode_pod_result(rr, i) == seq_anns
