"""bench.py machinery the driver depends on: the streamed parity check,
the oracle-child failure handling, the fallback command construction,
and the stdout-owner claim protocol.  These paths decide whether the
driver gets one honest JSON line out of every bench run (BASELINE.md),
so they get unit coverage even though bench.py is not part of the
package."""

from __future__ import annotations

import argparse
import sys
import threading

import pytest

import bench  # conftest.py puts the repo root on sys.path


@pytest.fixture(autouse=True)
def _reset_heartbeat():
    saved = dict(bench._HEARTBEAT)
    bench._HEARTBEAT.clear()
    bench._HEARTBEAT["t"] = saved.get("t", 0)
    yield
    bench._HEARTBEAT.clear()
    bench._HEARTBEAT.update(saved)


def _args(**over):
    base = dict(config=4, scale=1.0, cpu_scale=0.05, cpu_node_scale=1.0,
                seed=0, smoke=False, skip_engine=False, skip_parity=False,
                skip_config5=False)
    base.update(over)
    return argparse.Namespace(**base)


def test_fallback_cmd_forwards_flags():
    cmd = bench._fallback_cmd(_args(config=5, smoke=True, skip_engine=True))
    assert cmd[0] == sys.executable
    joined = " ".join(cmd)
    assert "--config 5" in joined
    assert "--assume-fallback" in joined
    assert "--smoke" in joined and "--skip-engine" in joined
    assert "--gate-configs 5" in joined  # one gate config bounds the cost
    assert "--skip-parity" not in joined


def test_stdout_claim_first_owner_wins():
    assert bench._try_claim("run") == "run"
    assert bench._try_claim("crash") == "run"  # first claim sticks
    # a later "crash" claim after "run" must NOT park (the final print
    # itself may have raised; parking would hang with no child running).
    # Run in a helper thread with a bounded join so a parking regression
    # shows up as a red test, not a wedged suite.
    t = threading.Thread(target=bench._claim_stdout_or_park,
                         args=("crash",), daemon=True)
    t.start()
    t.join(timeout=10)
    assert not t.is_alive(), "_claim_stdout_or_park parked a crash claim"


def test_stream_oracle_parity_ok_and_digest():
    r = bench.stream_oracle_parity(1, 0.02, 0, want_digest=True)
    assert r["ok"] is True
    assert r["compared"] == r["pods"] > 0
    assert r["keys_checked"] == 13 * r["pods"]
    assert r["mismatches"] == 0 and r["first_mismatch"] is None
    assert len(r["sha256"]) == 64
    assert r["oracle_rc"] == 0


def test_stream_oracle_parity_heartbeat_fires():
    beats = []
    r = bench.stream_oracle_parity(1, 0.02, 0, heartbeat=beats.append)
    assert r["ok"] and len(beats) >= r["pods"]


def test_oracle_child_death_is_not_a_parity_failure(monkeypatch):
    # a dying child (the round-4 OOM shape) must be reported as an
    # environment failure, not as mismatches
    monkeypatch.setattr(
        bench, "_ORACLE_CHILD",
        "import sys\nsys.exit(137)\n" + "# {repo} {idx} {scale} {seed}\n")
    r = bench.stream_oracle_parity(1, 0.02, 0)
    assert r["ok"] is False
    assert r.get("oracle_died") is True
    assert r["mismatches"] == 0
    assert r["oracle_rc"] == 137


def test_run_parity_gate_retries_smaller_on_child_death(monkeypatch):
    calls = []
    real = bench.stream_oracle_parity

    def fake(idx, scale, seed, chunk=64, want_digest=False, heartbeat=None):
        calls.append(scale)
        if len(calls) == 1:
            return {"ok": False, "pods": 10, "compared": 3,
                    "keys_checked": 39, "mismatches": 0,
                    "first_mismatch": None, "sha256": None,
                    "oracle_rc": -9, "oracle_err": "Killed",
                    "oracle_died": True, "replay_seconds": 0,
                    "oracle_seconds": 0}
        return real(idx, scale, seed, chunk=chunk, heartbeat=heartbeat)

    monkeypatch.setattr(bench, "stream_oracle_parity", fake)
    assert bench.run_parity_gate(1, 0.08, 0) is True
    assert calls == [0.08, 0.02]  # retried once at a quarter of the scale


def test_run_parity_gate_mismatch_fails(monkeypatch):
    def fake(idx, scale, seed, chunk=64, want_digest=False, heartbeat=None):
        return {"ok": False, "pods": 10, "compared": 10, "keys_checked": 130,
                "mismatches": 1, "sha256": None, "oracle_rc": 0,
                "oracle_err": "", "replay_seconds": 0, "oracle_seconds": 0,
                "first_mismatch": {"pod": 3, "key": "k", "dev": "a",
                                   "oracle": "b"}}

    monkeypatch.setattr(bench, "stream_oracle_parity", fake)
    assert bench.run_parity_gate(1, 0.08, 0) is False


def test_available_gb_positive():
    assert bench._available_gb() > 0


def test_host_phase_ticker_lifecycle():
    with bench._host_phase_ticker() as tk:
        assert tk._t.is_alive()
    # exit must stop the ticker promptly (a leak would keep it alive in
    # stop.wait(60) forever)
    tk._t.join(timeout=5)
    assert not tk._t.is_alive(), "ticker thread leaked past __exit__"


def test_measure_engine_reports_pipeline_spans():
    """measure_engine surfaces the wave-pipeline observability bench.py
    reports (docs/wave-pipeline.md): the commit_and_reflect span plus the
    commit_stream_overlap_seconds / store_batch_writes_total counters on
    a pipelined wave — and no stream counters when the sequential
    post-pass is forced."""
    r = bench.measure_engine(24, 6, seed=0)
    assert r["bound"] > 0
    assert "commit_and_reflect" in r["spans"]
    assert "replay_and_decode_stream" in r["spans"]
    assert r["counters"]["commit_stream_waves_total"] >= 1
    assert "commit_stream_overlap_seconds" in r["counters"]
    # binds land in-wave; the reflect write-backs defer with the lazy
    # decode (docs/wave-pipeline.md lazy-decode stage) and the bench
    # reports what was deferred plus the first-read latencies
    assert r["counters"]["store_batch_writes_total"] >= 24
    assert r["lazy"]["deferred_pods"] == 24
    assert r["lazy"]["cold_read_seconds"] > 0
    assert r["lazy"]["warm_read_seconds"] > 0

    r_seq = bench.measure_engine(24, 6, seed=0, pipeline=False)
    assert r_seq["bound"] == r["bound"]
    assert "commit_stream_waves_total" not in r_seq["counters"]


def test_measure_engine_reports_gang_counters():
    """With gang_groups mixed into the queue, measure_engine reports the
    vectorized quorum pass (gang_quorum_pass_seconds) and admission
    counters alongside the wave-pipeline ones (docs/gang-scheduling.md)."""
    r = bench.measure_engine(16, 6, seed=0, gang_groups=3, gang_members=4)
    assert r["bound"] > 0
    assert r["counters"].get("gang_quorum_pass_seconds", 0) > 0
    assert r["counters"].get("gang_groups_admitted_total", 0) >= 1


def test_measure_gang_shape_reports_counters():
    """The make bench-gang entry: admitted + rolled-back groups both
    show up in the counters, and parked members are reported."""
    r = bench.measure_gang(3, 3, 8, seed=0, plain_pods=4, park_groups=1)
    assert r["counters"].get("gang_groups_admitted_total") == 3
    assert r["counters"].get("gang_quorum_rollbacks_total", 0) >= 1
    assert r["parked"] == 2
    assert r["bound"] == 3 * 3 + 4


# ------------------------------------------------------- bench-check


def _bench_check():
    """Load docs/bench/bench_check.py (make bench-check) as a module."""
    import importlib.util
    from pathlib import Path

    path = Path(bench.__file__).parent / "docs" / "bench" / "bench_check.py"
    spec = importlib.util.spec_from_file_location("bench_check", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _bench_line(value=900.0, decode=1500.0, overlap=1.4, eng_cps=870.0):
    return {"metric": "m", "value": value, "unit": "cycles/s",
            "extra": {"decode_pods_per_sec": decode,
                      "engine_2k_1k": {
                          "pods": 2000, "cycles_per_sec": eng_cps,
                          "counters": {
                              "commit_stream_overlap_seconds": overlap}}}}


def test_bench_check_ok_and_regressions():
    bc = _bench_check()
    rows = bc.compare(_bench_line(), _bench_line())
    # metrics present on both sides are ok; lazy-era keys these synthetic
    # rounds don't carry SKIP instead of KeyError-ing (union semantics)
    assert all(r["status"] == "ok" for r in rows if r["old"] is not None)
    by = {r["metric"]: r for r in rows}
    assert by["engine_10k_5k_cycles_per_sec"]["status"] == "skip"
    assert by["lazy_cold_first_read_seconds"]["status"] == "skip"
    # >15% drop of a higher-is-better metric fails
    rows = {r["metric"]: r for r in bc.compare(
        _bench_line(), _bench_line(decode=1500.0 * 0.8))}
    assert rows["decode_pods_per_sec"]["status"] == "regression"
    # a 15%-tolerated drift passes
    rows = {r["metric"]: r for r in bc.compare(
        _bench_line(), _bench_line(decode=1500.0 * 0.9))}
    assert rows["decode_pods_per_sec"]["status"] == "ok"
    # wave wall is lower-is-better: slower engine (lower cps -> higher
    # wall) regresses
    rows = {r["metric"]: r for r in bc.compare(
        _bench_line(), _bench_line(eng_cps=870.0 * 0.8))}
    assert rows["engine_2k_1k_wave_wall_seconds"]["status"] == "regression"


def test_bench_check_skips_missing_metrics():
    bc = _bench_check()
    old = _bench_line()
    new = _bench_line()
    del new["extra"]["engine_2k_1k"]  # e.g. a fallback round
    rows = {r["metric"]: r for r in bc.compare(old, new)}
    assert rows["engine_2k_1k_wave_wall_seconds"]["status"] == "skip"
    assert rows["commit_stream_overlap_seconds"]["status"] == "skip"
    assert rows["headline_e2e_cycles_per_sec"]["status"] == "ok"


def test_bench_check_tolerates_keys_missing_from_older_rounds():
    """A metric introduced AFTER the previous round (the lazy-era keys)
    must compare as SKIP against the old round — never KeyError — and
    regress normally once both rounds carry it."""
    bc = _bench_check()
    old = _bench_line()
    new = _bench_line()
    new["extra"]["engine_10k_5k"] = {"pods": 10000, "cycles_per_sec": 1200.0}
    new["extra"]["engine_2k_1k"]["lazy"] = {"cold_read_seconds": 0.02}
    rows = {r["metric"]: r for r in bc.compare(old, new)}
    assert rows["engine_10k_5k_cycles_per_sec"]["status"] == "skip"
    assert rows["lazy_cold_first_read_seconds"]["status"] == "skip"
    # both rounds carrying the key: a >15% slowdown of the cold read
    # (lower-is-better) regresses
    older = _bench_line()
    older["extra"]["engine_2k_1k"]["lazy"] = {"cold_read_seconds": 0.02}
    newer = _bench_line()
    newer["extra"]["engine_2k_1k"]["lazy"] = {"cold_read_seconds": 0.05}
    rows = {r["metric"]: r for r in bc.compare(older, newer)}
    assert rows["lazy_cold_first_read_seconds"]["status"] == "regression"


def test_bench_check_multichip_sanity():
    """check_multichip: the newest MULTICHIP round must have run
    (ok=true, skipped=false); a skipped round fails the gate."""
    import json as json_mod
    import tempfile
    from pathlib import Path

    bc = _bench_check()
    with tempfile.TemporaryDirectory() as d:
        root = Path(d)
        assert bc.check_multichip(root) is None  # no rounds: nothing to gate
        (root / "MULTICHIP_r01.json").write_text(json_mod.dumps(
            {"n": 1, "ok": True, "skipped": False, "n_devices": 8}))
        assert bc.check_multichip(root) is None
        (root / "MULTICHIP_r02.json").write_text(json_mod.dumps(
            {"n": 2, "ok": True, "skipped": True, "reason": "1 device"}))
        err = bc.check_multichip(root)
        assert err is not None and "skipped" in err


def test_bench_check_scale_sanity_and_trajectory(tmp_path):
    """check_scale: the newest SCALE round must be parity-pinned and
    reuse-clean (sanity), and the 100k keys compare newest-vs-previous
    with union/skip semantics — a missing key SKIPs, a present-on-both
    regression fails."""
    import json

    bc = _bench_check()
    assert bc.check_scale(tmp_path) == (None, [])  # no rounds

    good = {"n": 1, "all_parity_ok": True,
            "never_rebuilt_on_unchanged_nodes": True,
            "scale_100k_cycles_per_sec": 12.0,
            "scale_100k_build_seconds": 0.25,
            "scale_100k_host_rss_mb": 9000.0}
    (tmp_path / "SCALE_r01.json").write_text(json.dumps(good))
    err, rows = bc.check_scale(tmp_path)
    assert err is None and rows == []  # one round: sanity only

    # second round: throughput collapsed, build time fine, RSS key absent
    bad = dict(good, n=2, scale_100k_cycles_per_sec=4.0)
    del bad["scale_100k_host_rss_mb"]
    (tmp_path / "SCALE_r02.json").write_text(json.dumps(bad))
    err, rows = bc.check_scale(tmp_path)
    assert err is None
    by = {r["metric"]: r["status"] for r in rows}
    assert by["scale_100k_cycles_per_sec"] == "regression"
    assert by["scale_100k_build_seconds"] == "ok"
    assert by["scale_100k_host_rss_mb"] == "skip"

    # a parity-broken newest round fails sanity outright
    (tmp_path / "SCALE_r03.json").write_text(json.dumps(
        dict(good, n=3, all_parity_ok=False)))
    err, rows = bc.check_scale(tmp_path)
    assert err is not None and "parity" in err and rows == []


def test_bench_check_soak_sanity_and_trajectory(tmp_path):
    """check_soak: the newest SOAK round must be green end to end
    (ok, Retry-After on every shed, ladder back on rung 0), and the
    p99/shed-rate keys compare newest-vs-previous with union/skip
    semantics."""
    import json

    bc = _bench_check()
    assert bc.check_soak(tmp_path) == (None, [])  # no rounds

    good = {"n": 1, "ok": True, "all_shed_had_retry_after": True,
            "soak_recovered_to_rung0": True,
            "soak_p99_wave_seconds": 0.12, "soak_shed_rate": 0.5}
    (tmp_path / "SOAK_r01.json").write_text(json.dumps(good))
    err, rows = bc.check_soak(tmp_path)
    assert err is None and rows == []  # one round: sanity only

    # second round: p99 doubled, shed-rate key absent
    bad = dict(good, n=2, soak_p99_wave_seconds=0.24)
    del bad["soak_shed_rate"]
    (tmp_path / "SOAK_r02.json").write_text(json.dumps(bad))
    err, rows = bc.check_soak(tmp_path)
    assert err is None
    by = {r["metric"]: r["status"] for r in rows}
    assert by["soak_p99_wave_seconds"] == "regression"
    assert by["soak_shed_rate"] == "skip"

    # a round whose ladder ended degraded fails sanity outright
    (tmp_path / "SOAK_r03.json").write_text(json.dumps(
        dict(good, n=3, soak_recovered_to_rung0=False)))
    err, rows = bc.check_soak(tmp_path)
    assert err is not None and "rung 0" in err and rows == []

    # a shed contract violation is also terminal
    (tmp_path / "SOAK_r03.json").write_text(json.dumps(
        dict(good, n=3, all_shed_had_retry_after=False)))
    err, _rows = bc.check_soak(tmp_path)
    assert err is not None and "Retry-After" in err


def test_bench_check_extracts_line_from_round_tail():
    import json

    bc = _bench_check()
    line = _bench_line()
    doc = {"n": 6, "cmd": "python bench.py", "rc": 0,
           "tail": "noise\nmore noise\n" + json.dumps(line) + "\n"}
    assert bc.extract_bench_line(doc) == line
    assert bc.extract_bench_line({"tail": "no json here"}) is None


def test_bench_check_main_exit_codes(tmp_path):
    import json

    bc = _bench_check()
    (tmp_path / "BENCH_r01.json").write_text(json.dumps(
        {"n": 1, "tail": json.dumps(_bench_line()) + "\n"}))
    (tmp_path / "BENCH_r02.json").write_text(json.dumps(
        {"n": 2, "tail": json.dumps(_bench_line(decode=100.0)) + "\n"}))
    assert bc.main(["--dir", str(tmp_path)]) == 1
    (tmp_path / "BENCH_r02.json").write_text(json.dumps(
        {"n": 2, "tail": json.dumps(_bench_line(decode=1600.0)) + "\n"}))
    assert bc.main(["--dir", str(tmp_path)]) == 0
    # a single round: nothing to compare, success
    (tmp_path / "BENCH_r02.json").unlink()
    assert bc.main(["--dir", str(tmp_path)]) == 0


def test_bench_check_refuses_tainted_round(tmp_path, capsys):
    """A round produced from a tree with outstanding kss-analyze
    findings recorded in its JSON invalidates the comparison
    (docs/static-analysis.md): refuse, don't gate on skewed numbers."""
    import json

    bc = _bench_check()
    (tmp_path / "BENCH_r01.json").write_text(json.dumps(
        {"n": 1, "tail": json.dumps(_bench_line()) + "\n"}))
    tainted = _bench_line()
    tainted["extra"]["analysis"] = {
        "new_findings": 2, "grandfathered": 29,
        "findings": ["pkg/mod.py:3: [pod-loop] f: loop over pods"]}
    (tmp_path / "BENCH_r02.json").write_text(json.dumps(
        {"n": 2, "tail": json.dumps(tainted) + "\n"}))
    assert bc.main(["--dir", str(tmp_path)]) == 2
    out = capsys.readouterr().out
    assert "REFUSING" in out and "pod-loop" in out
    # a recorded clean verdict (and rounds predating the field) compare
    clean = _bench_line()
    clean["extra"]["analysis"] = {"new_findings": 0, "grandfathered": 29}
    (tmp_path / "BENCH_r02.json").write_text(json.dumps(
        {"n": 2, "tail": json.dumps(clean) + "\n"}))
    assert bc.main(["--dir", str(tmp_path)]) == 0


def test_bench_check_refuses_round_with_failed_chaos(tmp_path, capsys):
    """A round whose embedded chaos verdict failed invalidates the
    comparison (docs/fault-injection.md): the tree no longer survives
    injected faults with bit-identical results — refuse, and point at
    the reproducing seed."""
    import json

    bc = _bench_check()
    (tmp_path / "BENCH_r01.json").write_text(json.dumps(
        {"n": 1, "tail": json.dumps(_bench_line()) + "\n"}))
    bad = _bench_line()
    bad["extra"]["chaos"] = {
        "ok": False, "seeds": [1],
        "failures": ["seed 1: chaos-a: state diverged from fault-free "
                     "run at ['p003']"]}
    (tmp_path / "BENCH_r02.json").write_text(json.dumps(
        {"n": 2, "tail": json.dumps(bad) + "\n"}))
    assert bc.main(["--dir", str(tmp_path)]) == 2
    out = capsys.readouterr().out
    assert "chaos" in out and "REFUSING" in out and "diverged" in out
    # a green verdict (and rounds predating the field) compare normally
    ok = _bench_line()
    ok["extra"]["chaos"] = {"ok": True, "seeds": [1], "failures": []}
    (tmp_path / "BENCH_r02.json").write_text(json.dumps(
        {"n": 2, "tail": json.dumps(ok) + "\n"}))
    assert bc.main(["--dir", str(tmp_path)]) == 0


def test_measure_engine_emits_metrics_snapshot():
    """The BENCH artifact carries the flight-recorder families
    (docs/metrics.md): upstream-named histograms + per-plugin labeled
    counters ride every measure_engine result."""
    r = bench.measure_engine(24, 6, seed=0)
    hists = r["metrics"]["histograms"]
    assert "scheduling_attempt_duration_seconds" in hists
    assert "plugin_execution_duration_seconds" in hists
    lc = r["metrics"]["labeled_counters"]
    assert "plugin_pods_nodes_evaluated_total" in lc
    assert "decode_path_total" in lc
