"""Native C++ annotation codec vs pure-Python encoder: byte identity."""

import os

import pytest

from kube_scheduler_simulator_tpu.framework.replay import replay
from kube_scheduler_simulator_tpu.models.workloads import baseline_config
from kube_scheduler_simulator_tpu.native import get_lib
from kube_scheduler_simulator_tpu.state.compile import compile_workload
from kube_scheduler_simulator_tpu.store.decode import decode_pod_result

pytestmark = pytest.mark.skipif(get_lib() is None, reason="no native toolchain")


@pytest.mark.parametrize("idx,scale", [(3, 0.02), (5, 0.01)])
def test_native_matches_python(idx, scale, monkeypatch):
    nodes, pods, cfg = baseline_config(idx, scale=scale, seed=42)
    cw = compile_workload(nodes, pods, cfg)
    rr = replay(cw, chunk=64)

    native = [decode_pod_result(rr, i) for i in range(len(pods))]

    monkeypatch.setenv("KSS_TPU_DISABLE_NATIVE", "1")
    pure = [decode_pod_result(rr, i) for i in range(len(pods))]

    for i, (na, pa) in enumerate(zip(native, pure)):
        for k in pa:
            assert na[k] == pa[k], f"pod {i} key {k}\n native={na[k][:300]}\n python={pa[k][:300]}"


def test_native_escaping():
    """Message content with JSON-special and HTML-escaped characters."""
    nodes = [
        {"metadata": {"name": 'n"0'},
         "spec": {"taints": [{"key": 'a<b&"c', "value": "x\\y", "effect": "NoSchedule"}]},
         "status": {"allocatable": {"cpu": "2", "memory": "2Gi", "pods": "10"}}},
        {"metadata": {"name": "n1"},
         "status": {"allocatable": {"cpu": "2", "memory": "2Gi", "pods": "10"}}},
    ]
    pods = [{"metadata": {"name": "p", "namespace": "default"},
             "spec": {"containers": [{"name": "c", "resources": {"requests": {"cpu": "1"}}}]}}]
    from kube_scheduler_simulator_tpu.plugins.registry import PluginSetConfig

    cfg = PluginSetConfig(enabled=["TaintToleration", "NodeResourcesFit"])
    cw = compile_workload(nodes, pods, cfg)
    rr = replay(cw)
    native = decode_pod_result(rr, 0)
    os.environ["KSS_TPU_DISABLE_NATIVE"] = "1"
    try:
        pure = decode_pod_result(rr, 0)
    finally:
        del os.environ["KSS_TPU_DISABLE_NATIVE"]
    assert native == pure

def test_codec_rebuilds_from_source(tmp_path):
    """`make codec` recipe: a fresh clone (no .so, or a foreign-platform
    one) must rebuild from annotation_codec.cpp and match the loader's
    library output (VERDICT r2 #10)."""
    import ctypes

    from kube_scheduler_simulator_tpu.native import build_codec

    so = str(tmp_path / "_annotation_codec.so")
    built = build_codec(so)
    assert os.path.exists(built)
    lib = ctypes.CDLL(built)
    assert lib.encode_filter_result is not None
    assert lib.encode_score_result is not None
    assert lib.codec_free is not None


def test_encode_string_map_matches_marshal():
    """The native history-record encoder is byte-identical to marshal()
    on quotes, backslashes, control chars, HTML-escaped chars, unicode."""
    import json

    from kube_scheduler_simulator_tpu.store.annotations import marshal
    from kube_scheduler_simulator_tpu.store.native_decode import encode_string_map

    cases = [
        {},
        {"k": "v"},
        {"b-key": "1", "a-key": "2"},  # sorted output
        {"blob": '{"n1":{"P":"passed"}}'},
        {"nasty": 'q"uo\\te <&> \t\n\r\b\f \x01\x1f'},
        {"uni": "üñíçødé ✓ 漢"},
    ]
    for d in cases:
        fast = encode_string_map(d)
        if fast is None:  # codec unavailable on this platform
            return
        assert fast == marshal(d)
        assert json.loads(fast) == d


def test_history_splice_matches_full_marshal():
    """Textual history append produces the same bytes as re-marshalling
    the whole parsed array."""
    import json

    from kube_scheduler_simulator_tpu.store import annotations as ann
    from kube_scheduler_simulator_tpu.store.reflector import update_result_history

    pod = {"metadata": {"name": "p"}}
    records = [
        {ann.SELECTED_NODE: "n1", ann.FILTER_RESULT: '{"n1":{"P":"passed"}}'},
        {ann.SELECTED_NODE: "", ann.FILTER_RESULT: '{"n1":{"P":"Insufficient cpu"}}'},
        {ann.SELECTED_NODE: "n2"},
    ]
    for r in records:
        update_result_history(pod, r)
    got = pod["metadata"]["annotations"][ann.RESULT_HISTORY]
    assert got == ann.marshal(records)
    assert json.loads(got) == records


def test_fused_decode_on_device_layout_strides(monkeypatch):
    """TPU fetches can return host arrays in the DEVICE layout (non-C
    strides); the fused decoder hands raw pointers to C, so a strided
    compact chunk must be renormalized, not walked as-if-contiguous
    (round-4 real-TPU parity failure: score-result read the next pod's
    value)."""
    import numpy as np

    nodes, pods, cfg = baseline_config(1, scale=0.05, seed=0)
    cw = compile_workload(nodes, pods, cfg)
    rr = replay(cw, chunk=64)
    cc = rr._compact

    def restride(a):
        # transpose-copy-transpose: same values, F-order memory like a
        # TPU minor-to-major fetch
        return np.asfortranarray(a)

    for field in ("packed", "raw8", "raw16", "raw32"):
        setattr(cc, field, [restride(x) for x in getattr(cc, field)])
        for x in getattr(cc, field):
            assert x.size == 0 or not x.flags["C_CONTIGUOUS"] or x.ndim < 2

    strided = [decode_pod_result(rr, i) for i in range(len(pods))]

    monkeypatch.setenv("KSS_TPU_DISABLE_NATIVE", "1")
    pure = [decode_pod_result(rr, i) for i in range(len(pods))]
    for i, (sa, pa) in enumerate(zip(strided, pure)):
        assert sa == pa, f"pod {i}: strided fused decode diverged"


def test_decode_chunk_into_base_offset():
    """decode_chunk_into with a chunk-local sink (base=lo) fills the same
    annotations as the whole-queue list — the bench's release-after-build
    consumer depends on it."""
    nodes, pods, cfg = baseline_config(1, scale=0.05, seed=1)
    cw = compile_workload(nodes, pods, cfg)
    rr = replay(cw, chunk=4)
    from kube_scheduler_simulator_tpu.store.decode import decode_chunk_into

    whole: list = [None] * len(pods)
    decode_chunk_into(rr, 0, len(pods), whole)
    for lo in range(0, len(pods), 4):
        hi = min(lo + 4, len(pods))
        sink = [None] * (hi - lo)
        decode_chunk_into(rr, lo, hi, sink, base=lo)
        assert sink == whole[lo:hi]


def test_decode_release_batches_aligns_to_compact_chunks():
    """The release-style consumer never straddles a compact chunk (pool
    workers would thrash the single-slot recon cache) and decodes every
    pod byte-identically to decode_pod_result."""
    from kube_scheduler_simulator_tpu.store.decode import decode_release_batches

    nodes, pods, cfg = baseline_config(2, scale=0.06, seed=9)
    cw = compile_workload(nodes, pods, cfg)
    rr = replay(cw, chunk=10)  # chunk NOT a multiple of the 64 batch
    got: dict = {}
    decode_release_batches(rr, 0, len(pods), on_pod=got.__setitem__)
    assert sorted(got) == list(range(len(pods)))
    for i in (0, 9, 10, len(pods) - 1):
        assert got[i] == decode_pod_result(rr, i)


def test_empty_active_mask_on_reused_cache_slot():
    """build_filter_frags must reset any_active per call: FilterFrags
    lives inside reused FilterCache slots (round-robin eviction at 8
    entries), so an empty-active-mask pod that lands on a reused slot
    used to inherit any_active=true, emit {"node":{},...} instead of {}
    — and cache the wrong blob for every later empty-mask pod of that
    ctx on that thread (ADVICE round-5 medium)."""
    import numpy as np

    from kube_scheduler_simulator_tpu.models.workloads import make_nodes, make_pods
    from kube_scheduler_simulator_tpu.plugins.registry import PluginSetConfig
    from kube_scheduler_simulator_tpu.store.native_decode import (
        build_context, encode_filter)

    nodes = make_nodes(3, seed=1)
    pods = make_pods(2, seed=2)
    cfg = PluginSetConfig(enabled=[
        "NodeUnschedulable", "NodeName", "TaintToleration", "NodeAffinity"])
    cw = compile_workload(nodes, pods, cfg)
    ctx = build_context(cw)
    f = len(cw.config.filters())
    codes = np.zeros((f, cw.node_table.n), np.int32)
    # churn 8 distinct non-empty masks (fills the thread-local cache),
    # so the 9th — the empty mask — lands on a round-robin-evicted slot
    for m in range(1, 9):
        active = np.array([(m >> b) & 1 for b in range(f)], np.uint8)
        assert encode_filter(ctx, codes, active).startswith("{\"")
    assert encode_filter(ctx, codes, np.zeros(f, np.uint8)) == "{}"
    # the (now-correct) cached entry serves later empty-mask pods too
    assert encode_filter(ctx, codes, np.zeros(f, np.uint8)) == "{}"
