"""Syncer src->dst state tables, mirroring the reference's scenario
structure (syncer/syncer_test.go:27-496): initial / created / updated /
deleted objects in the source cluster -> expected final state in the
destination, including the scheduled-pod-update mandatory filter and
NotFound-tolerant deletes.
"""

import time

import pytest

from kube_scheduler_simulator_tpu.cluster.store import NotFound, ObjectStore
from kube_scheduler_simulator_tpu.services.resourceapplier import ResourceApplier
from kube_scheduler_simulator_tpu.services.syncer import SyncerService


def pod(name, ns="default", node_name=None, labels=None):
    p = {"metadata": {"name": name, "namespace": ns}, "spec": {}}
    if node_name:
        p["spec"]["nodeName"] = node_name
    if labels:
        p["metadata"]["labels"] = dict(labels)
    return p


def node(name, labels=None):
    n = {"metadata": {"name": name}, "spec": {}}
    if labels:
        n["metadata"]["labels"] = dict(labels)
    return n


def wait_for(fn, timeout=2.0):
    deadline = time.time() + timeout
    last = None
    while time.time() < deadline:
        try:
            out = fn()
            if out:
                return out
        except NotFound as e:
            last = e
        time.sleep(0.01)
    if last:
        raise last
    return fn()


def settle():
    time.sleep(0.25)


# Each case: (name, resource, initial objs, scenario(src) steps,
#             expected final names in dst, extra assertion)
SYNC_TABLE = [
    # syncer_test.go:39 "unscheduled pod is created in src cluster"
    ("initial unscheduled pod lands in dst", "pods",
     [pod("pod-1")], lambda src: None, {"pod-1"}, None),
    # syncer_test.go:150 "pod is created and deleted in src cluster"
    ("created then deleted pod ends absent", "pods",
     [], lambda src: (src.create("pods", pod("pod-1")),
                      settle(),
                      src.delete("pods", "pod-1")),
     set(), None),
    # syncer_test.go:227 "unscheduled pod is updated in src cluster"
    ("unscheduled pod update propagates", "pods",
     [pod("pod-1")],
     lambda src: src.update("pods", dict(
         src.get("pods", "pod-1"), metadata={
             "name": "pod-1", "namespace": "default",
             "labels": {"stage": "v2"}})),
     {"pod-1"},
     lambda dst: dst.get("pods", "pod-1")["metadata"]["labels"] == {"stage": "v2"}),
    # nodes sync like pods but with no scheduling filter
    ("node create update delete", "nodes",
     [node("n1"), node("n2")],
     lambda src: (src.update("nodes", dict(
         src.get("nodes", "n1"), metadata={"name": "n1", "labels": {"zone": "z1"}})),
         settle(),
         src.delete("nodes", "n2")),
     {"n1"},
     lambda dst: dst.get("nodes", "n1")["metadata"]["labels"] == {"zone": "z1"}),
]


@pytest.mark.parametrize("name,resource,initial,scenario,want,extra", SYNC_TABLE,
                         ids=[c[0] for c in SYNC_TABLE])
def test_sync_scenarios(name, resource, initial, scenario, want, extra):
    src, dst = ObjectStore(), ObjectStore()
    for obj in initial:
        src.create(resource, obj)
    syncer = SyncerService(src, ResourceApplier(dst))
    syncer.run()
    try:
        scenario(src)
        settle()
        if want:
            for n in want:
                wait_for(lambda n=n: dst.get(resource, n))
        else:
            settle()
        got = {o["metadata"]["name"] for o in dst.list(resource)[0]}
        assert got == want
        if extra:
            assert wait_for(lambda: extra(dst))
    finally:
        syncer.stop()


def test_scheduled_pod_update_not_synced():
    """syncer_test.go:293 'scheduled pod is NOT updated in src cluster':
    an update whose INCOMING pod carries spec.nodeName (a source-side
    bind) is dropped by the applier's mandatory filterPodsForUpdating
    hook (resourceapplier/resource.go:85-100) — placement in the
    simulator belongs to the simulator's own scheduler."""
    src, dst = ObjectStore(), ObjectStore()
    src.create("pods", pod("pod-1"))
    syncer = SyncerService(src, ResourceApplier(dst))
    syncer.run()
    try:
        wait_for(lambda: dst.get("pods", "pod-1"))
        # the SOURCE cluster's scheduler binds the pod and labels it; the
        # update reaching the syncer carries nodeName -> filtered out
        sp = src.get("pods", "pod-1")
        sp["spec"]["nodeName"] = "src-node"
        sp["metadata"]["labels"] = {"overwrite": "attempt"}
        src.update("pods", sp)
        settle()
        after = dst.get("pods", "pod-1")
        assert after["spec"].get("nodeName") is None
        assert after["metadata"].get("labels", {}) != {"overwrite": "attempt"}
    finally:
        syncer.stop()


def test_unscheduled_update_racing_simulator_bind_loses():
    """Defense in depth behind the filter hook: even an update WITHOUT a
    source-side nodeName cannot clobber a binding the simulator already
    wrote — the store's write-once nodeName validation rejects it
    (cluster/store.py) and the syncer tolerates the error."""
    src, dst = ObjectStore(), ObjectStore()
    src.create("pods", pod("pod-1"))
    syncer = SyncerService(src, ResourceApplier(dst))
    syncer.run()
    try:
        wait_for(lambda: dst.get("pods", "pod-1"))
        bound = dst.get("pods", "pod-1")
        bound["spec"]["nodeName"] = "node-a"   # simulator scheduled it
        dst.update("pods", bound)
        sp = src.get("pods", "pod-1")
        sp["metadata"]["labels"] = {"overwrite": "attempt"}
        src.update("pods", sp)                 # unscheduled in src
        settle()
        after = dst.get("pods", "pod-1")
        assert after["spec"].get("nodeName") == "node-a"
        assert after["metadata"].get("labels", {}) != {"overwrite": "attempt"}
    finally:
        syncer.stop()


def test_scheduled_pod_delete_still_synced():
    """Deletion is not filtered: a pod removed from the source disappears
    from the simulator even after binding (only *updates* of scheduled
    pods are skipped)."""
    src, dst = ObjectStore(), ObjectStore()
    src.create("pods", pod("pod-1"))
    syncer = SyncerService(src, ResourceApplier(dst))
    syncer.run()
    try:
        wait_for(lambda: dst.get("pods", "pod-1"))
        bound = dst.get("pods", "pod-1")
        bound["spec"]["nodeName"] = "node-a"
        dst.update("pods", bound)
        src.delete("pods", "pod-1")
        deadline = time.time() + 2
        while time.time() < deadline:
            try:
                dst.get("pods", "pod-1")
                time.sleep(0.01)
            except NotFound:
                break
        with pytest.raises(NotFound):
            dst.get("pods", "pod-1")
    finally:
        syncer.stop()


def test_delete_of_never_synced_object_tolerated():
    """Delete events for objects the destination never saw must not kill
    the sync loop (NotFound tolerated, syncer.go Add/Update/Delete)."""
    src, dst = ObjectStore(), ObjectStore()
    src.create("pods", pod("ghost"))
    syncer = SyncerService(src, ResourceApplier(dst))
    syncer.run()
    try:
        wait_for(lambda: dst.get("pods", "ghost"))
        dst.delete("pods", "ghost")       # dst-side deletion out of band
        src.delete("pods", "ghost")       # syncer's delete now hits NotFound
        settle()
        # loop still alive: a fresh create must still sync
        src.create("pods", pod("after"))
        assert wait_for(lambda: dst.get("pods", "after"))
    finally:
        syncer.stop()
