"""Speculative default wave vs the sequential scan baseline
(KSS_TPU_SPECULATIVE=0): engine-level golden byte-identity — annotation
bytes, bind order, result history, parked gangs — plus the PR 12
composition (mid-round fault -> uncommitted-suffix retry) and the
contention scan-fallback (docs/wave-pipeline.md speculative-wave
stage)."""

from __future__ import annotations

import json

import pytest

from kube_scheduler_simulator_tpu.cluster.store import ObjectStore
from kube_scheduler_simulator_tpu.framework.engine import SchedulerEngine
from kube_scheduler_simulator_tpu.models.workloads import (
    make_nodes, make_pods, make_slot_pinned_workload)
from kube_scheduler_simulator_tpu.plugins.registry import PluginSetConfig
from kube_scheduler_simulator_tpu.utils.tracing import TRACER

DEFAULT_ENABLED = [
    "NodeResourcesFit", "NodeResourcesBalancedAllocation", "NodeAffinity",
    "TaintToleration", "PodTopologySpread",
]


def _run_wave(nodes, pods, enabled, monkeypatch, speculative: bool,
              chunk: int = 16, pgs=(), custom=None, env=()):
    """One engine pass; returns (state, bind_order, parked) where state
    maps pod name -> (nodeName, ALL annotations — result history
    included)."""
    monkeypatch.setenv("KSS_TPU_SPECULATIVE", "1" if speculative else "0")
    for k, v in env:
        monkeypatch.setenv(k, v)
    store = ObjectStore()
    if pgs:
        from kube_scheduler_simulator_tpu.plugins.coscheduling import (
            ensure_podgroup_resource)

        ensure_podgroup_resource(store)
        for pg in pgs:
            store.create("podgroups", pg)
    for n in nodes:
        store.create("nodes", n)
    for p in pods:
        store.create("pods", p)
    engine = SchedulerEngine(store, plugin_config=PluginSetConfig(
        enabled=list(enabled), custom=dict(custom or {})), chunk=chunk)

    # bind ORDER: every bind funnels through _commit_pod_batch on the
    # batched paths and _bind on the post-pass/gang-release paths
    order: list[tuple[str, str, str]] = []
    orig_batch = engine._commit_pod_batch
    orig_bind = engine._bind

    def batch_spy(items):
        order.extend((ns, name, node) for ns, name, node in items if node)
        return orig_batch(items)

    def bind_spy(ns, name, node):
        order.append((ns, name, node))
        return orig_bind(ns, name, node)

    engine._commit_pod_batch = batch_spy
    engine._bind = bind_spy
    engine.schedule_pending()
    state = {}
    for p in store.list("pods")[0]:
        meta = p.get("metadata") or {}
        state[meta.get("name", "")] = (
            (p.get("spec") or {}).get("nodeName"),
            dict(meta.get("annotations") or {}))
    parked = sorted(engine.gang_parked)
    engine.close()
    return state, order, parked


def _assert_identical(a, b):
    sa, oa, pa = a
    sb, ob, pb = b
    diff = sorted(k for k in sb if sb[k] != sa.get(k))
    assert sa == sb, f"state diverged at {diff[:4]}"
    assert oa == ob, "bind order diverged"
    assert pa == pb, "parked gang set diverged"


def test_default_wave_is_speculative_and_byte_identical(monkeypatch):
    """The flagship parity gate: the DEFAULT wave (speculative) against
    KSS_TPU_SPECULATIVE=0, on the broad default workload (label-coupled
    spread constraints active — the dense eval + contention controller
    path)."""
    nodes = make_nodes(12, seed=5, taint_fraction=0.2)
    pods = make_pods(40, seed=6, with_affinity=True, with_tolerations=True,
                     with_spread=True)
    TRACER.reset()
    spec = _run_wave(nodes, pods, DEFAULT_ENABLED, monkeypatch, True)
    assert TRACER.summary()["counters"].get("speculative_rounds_total", 0) > 0
    seq = _run_wave(nodes, pods, DEFAULT_ENABLED, monkeypatch, False)
    _assert_identical(spec, seq)


def test_tie_score_pods_bind_identically(monkeypatch):
    """Identical nodes x identical pods: every node ties on every score,
    so selection rides the argmax first-max tie-break — pinned to be
    bit-identical between the batched rounds and the scan."""
    nodes = []
    for i in range(6):
        nodes.append({"metadata": {"name": f"tie-{i}"},
                      "status": {"allocatable": {"cpu": "8", "memory": "16Gi",
                                                 "pods": "20"}}})
    pods = [{"metadata": {"name": f"twin-{i:02d}", "namespace": "default"},
             "spec": {"containers": [{
                 "name": "c",
                 "resources": {"requests": {"cpu": "500m",
                                            "memory": "1Gi"}}}]}}
            for i in range(18)]
    enabled = ["NodeResourcesFit", "NodeResourcesBalancedAllocation"]
    spec = _run_wave(nodes, pods, enabled, monkeypatch, True, chunk=8)
    seq = _run_wave(nodes, pods, enabled, monkeypatch, False, chunk=8)
    _assert_identical(spec, seq)
    assert all(s[0] for s in spec[0].values())  # everything bound


def test_gang_wave_with_parked_members_matches_sequential(monkeypatch):
    """Gangs through the speculative stream: an admitted group and a
    below-quorum group (one member infeasible) — admission, parking and
    annotation bytes identical to the scan baseline."""
    from kube_scheduler_simulator_tpu.models.workloads import (
        make_gang_workload)
    from kube_scheduler_simulator_tpu.plugins.coscheduling import Coscheduling

    nodes = make_nodes(8, seed=11)
    pgs, gpods = make_gang_workload(2, 3, seed=12)
    # park gang-0001: one member requests more cpu than any node has
    for p in gpods:
        if p["metadata"]["name"] == "gang-0001-member-000":
            p["spec"]["containers"][0]["resources"]["requests"]["cpu"] = "9999"
    pods = make_pods(10, seed=13) + gpods
    enabled = ["NodeResourcesFit", "Coscheduling"]

    def run(spec_on):
        return _run_wave(nodes, pods, enabled, monkeypatch, spec_on,
                         chunk=8, pgs=pgs,
                         custom={"Coscheduling": Coscheduling()})

    spec = run(True)
    seq = run(False)
    _assert_identical(spec, seq)
    assert spec[2], "below-quorum gang should have parked members"
    bound_gang0 = [n for n, (node, _a) in spec[0].items()
                   if n.startswith("gang-0000-") and node]
    assert len(bound_gang0) == 3, "admitted gang must bind whole"


def test_mid_round_fault_retries_suffix_and_stays_identical(monkeypatch):
    """PR 12 composition: a transient fault at the speculative.round
    seam mid-wave — committed round chunks stand, the uncommitted
    suffix retries recompiled against current store state, and the
    final state is byte-identical to the fault-free run."""
    from kube_scheduler_simulator_tpu.utils import faults

    nodes = make_nodes(10, seed=21)
    pods = make_pods(30, seed=22, with_affinity=True)
    enabled = ["NodeResourcesFit", "NodeResourcesBalancedAllocation",
               "NodeAffinity"]
    clean = _run_wave(nodes, pods, enabled, monkeypatch, True, chunk=8)
    TRACER.reset()
    plan = faults.FaultPlan([
        faults.FaultRule("speculative.round", nth=2, error="runtime"),
    ], seed=7)
    with faults.armed(plan):
        faulted = _run_wave(nodes, pods, enabled, monkeypatch, True, chunk=8)
    assert plan.stats()["rules"][0]["trips"] == 1, "fault never fired"
    counters = TRACER.summary()["counters"]
    assert counters.get("wave_retries_total", 0) >= 1
    _assert_identical(faulted, clean)


def test_contended_wave_falls_back_to_scan_and_matches(monkeypatch):
    """Broad feasibility collapses byte-exact acceptance: the contention
    controller must hand the wave to the sequential chunked scan (the
    fallback tap fires) and results stay byte-identical."""
    nodes = make_nodes(16, seed=31)
    pods = make_pods(60, seed=32)  # every pod fits everywhere
    enabled = ["NodeResourcesFit", "NodeResourcesBalancedAllocation"]
    TRACER.reset()
    spec = _run_wave(nodes, pods, enabled, monkeypatch, True, chunk=16)
    fallbacks = sum(TRACER.labeled_totals(
        "speculative_fallbacks_total", "session").values())
    assert fallbacks >= 1, "contended wave never engaged the scan fallback"
    seq = _run_wave(nodes, pods, enabled, monkeypatch, False, chunk=16)
    _assert_identical(spec, seq)


def test_sparse_candidate_eval_through_engine(monkeypatch):
    """KSS_TPU_SPECULATIVE_CANDIDATES pins a small candidate cap so the
    sparse score/select tail actually runs (slot-pinned pods: 2 feasible
    nodes each) — engine results byte-identical to the scan baseline."""
    nodes, pods = make_slot_pinned_workload(24, 12, seed=41)
    enabled = ["NodeResourcesFit", "NodeResourcesBalancedAllocation",
               "NodeAffinity"]
    env = (("KSS_TPU_SPECULATIVE_CANDIDATES", "4"),)
    TRACER.reset()
    spec = _run_wave(nodes, pods, enabled, monkeypatch, True, chunk=8,
                     env=env)
    accepted = sum(TRACER.labeled_totals(
        "speculative_accepted_total", "session").values())
    assert accepted == 24, "slot workload should accept every pod"
    seq = _run_wave(nodes, pods, enabled, monkeypatch, False, chunk=8,
                    env=env)
    _assert_identical(spec, seq)
    assert all(s[0] for s in spec[0].values())


def test_accept_rate_surfaces_per_session(monkeypatch):
    """The speculative_commit_rates surface /api/v1/sessions and
    `bench --serve` report: accepted/rolledBack per session label."""
    from kube_scheduler_simulator_tpu.server.sessions import (
        speculative_commit_rates)

    nodes, pods = make_slot_pinned_workload(12, 8, seed=51)
    monkeypatch.setenv("KSS_TPU_SPECULATIVE", "1")
    store = ObjectStore()
    for n in nodes:
        store.create("nodes", n)
    for p in pods:
        store.create("pods", p)
    engine = SchedulerEngine(store, plugin_config=PluginSetConfig(
        enabled=["NodeResourcesFit", "NodeAffinity"]), chunk=8)
    engine.session = "rate-test"
    TRACER.reset()
    engine.schedule_pending()
    rates = speculative_commit_rates(TRACER)
    assert "rate-test" in rates, rates
    ent = rates["rate-test"]
    assert ent["accepted"] == 12
    assert ent["acceptRate"] == pytest.approx(
        ent["accepted"] / (ent["accepted"] + ent["rolledBack"]))
    engine.close()


def test_result_history_across_waves_identical(monkeypatch):
    """Two waves over the same pods (second wave re-schedules after a
    delete/recreate) — the RESULT_HISTORY annotation accumulates
    byte-identically on both paths."""
    from kube_scheduler_simulator_tpu.store import annotations as ann

    nodes = make_nodes(6, seed=61)
    base_pods = make_pods(10, seed=62)

    def run(spec_on):
        monkeypatch.setenv("KSS_TPU_SPECULATIVE", "1" if spec_on else "0")
        store = ObjectStore()
        for n in nodes:
            store.create("nodes", n)
        engine = SchedulerEngine(store, plugin_config=PluginSetConfig(
            enabled=["NodeResourcesFit",
                     "NodeResourcesBalancedAllocation"]), chunk=4)
        for p in base_pods:
            store.create("pods", p)
        engine.schedule_pending()
        # unbind and re-run: the second wave's records append to history
        for p in store.list("pods", copy_objects=False)[0][:]:
            name = p["metadata"]["name"]
            store.delete("pods", name, "default")
        for p in base_pods:
            store.create("pods", p)
        engine.schedule_pending()
        hist = {}
        for p in store.list("pods")[0]:
            anns = (p["metadata"].get("annotations") or {})
            hist[p["metadata"]["name"]] = anns.get(ann.RESULT_HISTORY)
        engine.close()
        return hist

    spec, seq = run(True), run(False)
    assert spec == seq
    assert all(h and len(json.loads(h)) >= 1 for h in spec.values())
