"""Fault injection + wave failure protocol (docs/fault-injection.md).

Covers the deterministic seam layer (utils/faults.py), the engine's
wave failure protocol (uncommitted-suffix retry, the device->host->eager
degradation ladder with probe recovery, compile quarantine), the decode
failure visibility/heal satellite, the interruptible retry backoff, and
the session create/evict seams.  The tier-2 chaos suite
(tests/test_chaos.py, `make chaos`) composes all of this concurrently;
these tests pin each mechanism in isolation.
"""

from __future__ import annotations

import json
import threading
import time

import pytest

from kube_scheduler_simulator_tpu.cluster.store import Conflict, ObjectStore
from kube_scheduler_simulator_tpu.framework.engine import SchedulerEngine
from kube_scheduler_simulator_tpu.utils import faults
from kube_scheduler_simulator_tpu.utils.faults import (
    FaultPlan, FaultRule, InjectedFault, classify_fault, fault_point,
)
from kube_scheduler_simulator_tpu.utils.retry import (
    RetryAborted, RetryTimeout, retry_with_exponential_backoff,
)
from kube_scheduler_simulator_tpu.utils.tracing import TRACER


def _counter(name: str, **labels) -> float:
    snap = TRACER.snapshot()
    if not labels:
        return (snap.get("counters") or {}).get(name, 0)
    for e in (snap.get("labeled_counters") or {}).get(name, []):
        if all(e["labels"].get(k) == v for k, v in labels.items()):
            return e["value"]
    return 0


def _cluster(n_nodes=3, n_pods=20):
    s = ObjectStore()
    for i in range(n_nodes):
        s.create("nodes", {
            "metadata": {"name": f"n{i}"},
            "status": {"allocatable": {"cpu": "8", "memory": "16Gi",
                                       "pods": "110"}}})
    for i in range(n_pods):
        s.create("pods", {
            "metadata": {"name": f"p{i:03d}", "namespace": "default"},
            "spec": {"containers": [{"name": "c", "resources": {
                "requests": {"cpu": "100m", "memory": "64Mi"}}}]}})
    return s


def _engine(store, chunk=8):
    eng = SchedulerEngine(store, chunk=chunk)
    eng._retry_sleep = lambda _d: None  # no real backoff in tests
    return eng


def _state(store):
    out = {}
    for p in store.list("pods")[0]:
        meta = p["metadata"]
        out[meta["name"]] = ((p.get("spec") or {}).get("nodeName"),
                             dict(meta.get("annotations") or {}))
    return out


def _reference(n_nodes=3, n_pods=20, chunk=8):
    s = _cluster(n_nodes, n_pods)
    assert _engine(s, chunk).schedule_pending() == n_pods
    return _state(s)


# ------------------------------------------------------------ plan core


def test_plan_is_deterministic_per_seed():
    def trips(seed):
        plan = FaultPlan([FaultRule("decode.chunk", p=0.3, times=None)],
                         seed=seed)
        hits = []
        for i in range(200):
            try:
                with faults.armed(plan):
                    fault_point("decode.chunk")
            except InjectedFault:
                hits.append(i)
        return hits

    assert trips(7) == trips(7)
    assert trips(7) != trips(8)
    assert trips(7)  # p=0.3 over 200 hits: fires


def test_nth_trips_exactly_once_and_times_bounds():
    plan = FaultPlan([FaultRule("decode.chunk", nth=3)], seed=0)
    fired = []
    with faults.armed(plan):
        for i in range(1, 8):
            try:
                fault_point("decode.chunk")
            except InjectedFault:
                fired.append(i)
    assert fired == [3]
    stats = plan.stats()["rules"][0]
    assert (stats["hits"], stats["trips"]) == (7, 1)


def test_session_filter_scopes_rules():
    plan = FaultPlan([FaultRule("decode.chunk", nth=1,
                                sessions=["tenant-a"])], seed=0)
    with faults.armed(plan):
        fault_point("decode.chunk")  # unscoped hit: no match, no count
        with TRACER.session_scope("tenant-b"):
            fault_point("decode.chunk")
        with TRACER.session_scope("tenant-a"):
            with pytest.raises(InjectedFault):
                fault_point("decode.chunk")


def test_plan_from_env_and_validation(monkeypatch):
    doc = {"seed": 9, "rules": [
        {"seam": "replay.scan_dispatch", "nth": 2, "error": "memory"}]}
    monkeypatch.setenv("KSS_TPU_FAULT_PLAN", json.dumps(doc))
    plan = FaultPlan.from_env()
    assert plan.seed == 9 and plan.rules[0].error == "memory"
    monkeypatch.delenv("KSS_TPU_FAULT_PLAN")
    assert FaultPlan.from_env() is None
    with pytest.raises(ValueError, match="unknown fault seam"):
        FaultRule("not.a.seam", nth=1)
    with pytest.raises(ValueError, match="error type"):
        FaultRule("decode.chunk", nth=1, error="kaboom")
    with pytest.raises(ValueError, match="exactly one"):
        FaultRule("decode.chunk")


def test_unarmed_fault_point_is_noop():
    assert faults.current_plan() is None
    for seam in faults.SEAMS:
        fault_point(seam)  # no plan: must never raise


def test_classification():
    assert classify_fault(faults.InjectedRuntimeFault("x")) == "transient"
    assert classify_fault(faults.InjectedOOM("x")) == "structural"
    assert classify_fault(MemoryError()) == "structural"
    assert classify_fault(RuntimeError()) == "transient"
    assert classify_fault(RetryTimeout()) == "fatal"
    assert classify_fault(KeyboardInterrupt()) == "fatal"


# ------------------------------------------------- wave failure protocol


def test_transient_scan_fault_retries_suffix_bit_identical():
    ref = _reference()
    s = _cluster()
    eng = _engine(s)
    before = _counter("wave_retries_total")
    plan = FaultPlan([FaultRule("replay.scan_dispatch", nth=2,
                                error="runtime")], seed=1)
    with faults.armed(plan):
        assert eng.schedule_pending() == 20
    assert plan.stats()["rules"][0]["trips"] == 1
    assert _counter("wave_retries_total") > before
    assert _counter("wave_faults_total", seam="replay.scan_dispatch",
                    action="retried") >= 1
    assert _state(s) == ref  # bit-identical to the fault-free run


def test_transient_fetch_fault_retries_bit_identical():
    ref = _reference()
    s = _cluster()
    eng = _engine(s)
    plan = FaultPlan([FaultRule("replay.decision_fetch", nth=2,
                                error="io")], seed=1)
    with faults.armed(plan):
        assert eng.schedule_pending() == 20
    assert _state(s) == ref


def test_retry_suffix_aligns_with_filtered_pending():
    """The retry suffix indexes the attempt's FILTERED pending list
    (scheduling gates, excludes, gang prescreen drop pods before the
    commit watermark is cut) — a fault + gated pods must not shift the
    suffix onto the wrong pods."""
    def cluster_with_gated():
        s = _cluster()
        for i in (2, 9):  # gated pods interleaved in queue order
            p = s.get("pods", f"p{i:03d}", "default")
            p["spec"]["schedulingGates"] = [{"name": "hold"}]
            s.update("pods", p)
        return s

    ref_s = cluster_with_gated()
    assert _engine(ref_s).schedule_pending() == 18
    ref = _state(ref_s)
    s = cluster_with_gated()
    eng = _engine(s)
    plan = FaultPlan([FaultRule("replay.scan_dispatch", nth=2,
                                error="runtime")], seed=3)
    with faults.armed(plan):
        assert eng.schedule_pending() == 18
    assert plan.stats()["rules"][0]["trips"] == 1
    assert _state(s) == ref


def test_structural_fault_steps_down_ladder_losslessly():
    ref = _reference()
    s = _cluster()
    eng = _engine(s)
    plan = FaultPlan([FaultRule("replay.scan_dispatch", nth=1,
                                error="memory")], seed=1)
    with faults.armed(plan):
        assert eng.schedule_pending() == 20
    assert eng.result_mode() == "host_resident"
    assert _counter("wave_degradations_total",
                    **{"from": "device_resident",
                       "to": "host_resident"}) >= 1
    assert _state(s) == ref  # the rungs are parity gates: lossless


def test_double_structural_fault_reaches_eager():
    ref = _reference()
    s = _cluster()
    eng = _engine(s)
    plan = FaultPlan([
        FaultRule("replay.scan_dispatch", nth=1, error="memory"),
        FaultRule("replay.scan_dispatch", nth=2, error="memory"),
    ], seed=1)
    with faults.armed(plan):
        assert eng.schedule_pending() == 20
    assert eng.result_mode() == "eager_decode"
    assert _state(s) == ref


def test_probe_recovery_steps_back_up(monkeypatch):
    monkeypatch.setenv("KSS_TPU_DEGRADE_PROBE_WAVES", "2")
    s = _cluster(n_pods=6)
    eng = _engine(s)
    plan = FaultPlan([FaultRule("replay.scan_dispatch", nth=1,
                                error="memory")], seed=1)
    with faults.armed(plan):
        assert eng.schedule_pending() == 6
    # one clean wave at the degraded rung so far: still degraded
    assert eng.result_mode() == "host_resident"
    # the second clean wave reaches the probe threshold -> step back up
    s.create("pods", {
        "metadata": {"name": "late", "namespace": "default"},
        "spec": {"containers": [{"name": "c", "resources": {
            "requests": {"cpu": "100m", "memory": "64Mi"}}}]}})
    assert eng.schedule_pending() == 1
    assert eng.result_mode() == "device_resident"
    assert _counter("wave_degradations_total",
                    **{"from": "host_resident",
                       "to": "device_resident"}) >= 1


def test_env_floor_caps_recovery(monkeypatch):
    monkeypatch.setenv("KSS_TPU_HOST_RESIDENT", "1")
    eng = _engine(_cluster(n_pods=2))
    assert eng.result_mode() == "host_resident"
    assert eng._degrade("test") is True
    assert eng.result_mode() == "eager_decode"
    monkeypatch.setenv("KSS_TPU_DEGRADE_PROBE_WAVES", "1")
    eng._wave_recovered_ok()
    # recovery lands on the env floor, never above it
    assert eng.result_mode() == "host_resident"


def test_retries_exhausted_aborts_with_committed_prefix_standing(monkeypatch):
    """The _WaveCommitter.abort() baseline the protocol must not
    regress: a mid-stream replay failure leaves committed binds
    standing, lands NO binds after the failure, and the leftover pods
    reschedule cleanly on the next wave."""
    monkeypatch.setenv("KSS_TPU_WAVE_MAX_RETRIES", "0")
    s = _cluster()
    eng = _engine(s)
    # every fetch past the first fails: with retries disabled the wave
    # aborts on the first fault
    plan = FaultPlan([FaultRule("replay.decision_fetch", p=1.0, times=None,
                                nth=None)], seed=1)
    before_aborts = _counter("wave_faults_total",
                             seam="replay.decision_fetch", action="aborted")
    with faults.armed(plan):
        with pytest.raises(InjectedFault):
            eng.schedule_pending()
    assert _counter("wave_faults_total", seam="replay.decision_fetch",
                    action="aborted") > before_aborts
    # committed binds stand and form a PREFIX of pod order — nothing
    # lands after the failure point (abort drops queued chunks)
    state = _state(s)
    bound = sorted(n for n, (node, _a) in state.items() if node)
    all_names = sorted(state)
    assert bound == all_names[:len(bound)]
    # the leftover pods reschedule cleanly on the next (fault-free) wave
    monkeypatch.setenv("KSS_TPU_WAVE_MAX_RETRIES", "3")
    assert eng.schedule_pending() == 20 - len(bound)
    assert _state(s) == _reference()


def test_transient_fault_after_full_commit_keeps_bind_count():
    """An empty uncommitted suffix (every pod committed, the fault hit
    post-commit work like the reflect drain) must not abort a
    fully-committed wave: the retry settles immediately and the wave
    returns its bind count."""
    from kube_scheduler_simulator_tpu.plugins.registry import PluginSetConfig

    s = _cluster()
    # a postfilter-free config keeps the STREAMING committer on — the
    # path whose finish()-time reflect drain this test poisons
    eng = SchedulerEngine(s, chunk=8, plugin_config=PluginSetConfig(
        enabled=["NodeResourcesFit", "NodeAffinity"]))
    eng._retry_sleep = lambda _d: None
    assert eng._can_stream_commit()
    real = eng.reflector.reflect_batch
    calls = {"n": 0}

    def poisoned(items):
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("post-commit drain poison")
        return real(items)

    eng.reflector.reflect_batch = poisoned
    before = _counter("wave_retries_total")
    assert eng.schedule_pending() == 20  # binds counted, no crash
    assert _counter("wave_retries_total") > before
    assert all(node for node, _a in _state(s).values())


def test_compile_quarantine_contains_key_not_process():
    from kube_scheduler_simulator_tpu.framework.replay import (
        CompileQuarantined, _ScanCacheRegistry)

    reg = _ScanCacheRegistry()
    calls = {"n": 0}

    def bad_builder():
        calls["n"] += 1
        raise RuntimeError("injected compile failure")

    for _ in range(2):  # first failures are transient: builder re-runs
        with pytest.raises(RuntimeError):
            reg.get_or_build(("shape-a",), bad_builder)
    assert calls["n"] == 2
    # 2 consecutive failures: the KEY is quarantined — fail-fast, no
    # third doomed compile
    with pytest.raises(CompileQuarantined):
        reg.get_or_build(("shape-a",), bad_builder)
    assert calls["n"] == 2
    assert reg.stats()["quarantined"] == 1
    # other keys (other sessions' shapes) are unaffected
    assert reg.get_or_build(("shape-b",), lambda: "jit-b") == "jit-b"
    # expiry re-admits the build; success clears the failure history
    with reg._mu:
        reg._failed[("shape-a",)][1] = 0.0
    assert reg.get_or_build(("shape-a",), lambda: "jit-a") == "jit-a"
    assert reg.stats()["quarantined"] == 0
    assert reg.get_or_build(("shape-a",), bad_builder) == "jit-a"  # cached


# --------------------------------------------------- decode heal satellite


def test_decode_fault_is_visible_and_heals_on_reread():
    import os

    # eager reference bytes for the same workload
    os.environ["KSS_TPU_EAGER_DECODE"] = "1"
    try:
        ref = _reference()
    finally:
        del os.environ["KSS_TPU_EAGER_DECODE"]
    s = _cluster()
    eng = _engine(s)
    assert eng.schedule_pending() == 20  # lazy: decode deferred to read
    before = _counter("decode_failures_total", path="native_chunk") \
        + _counter("decode_failures_total", path="python")
    plan = FaultPlan([FaultRule("decode.chunk", nth=1, error="runtime")],
                     seed=1)
    with faults.armed(plan):
        with pytest.raises(InjectedFault):
            _state(s)  # first read surfaces the fault...
        healed = _state(s)  # ...and the re-read heals it
    after = _counter("decode_failures_total", path="native_chunk") \
        + _counter("decode_failures_total", path="python")
    assert after > before  # the failure was counted, not silent
    assert healed == ref  # chunk-mates unpoisoned, bytes identical


# -------------------------------------------------- reflector + retry stop


def test_injected_write_conflicts_heal_under_backoff():
    from kube_scheduler_simulator_tpu.store import annotations as ann
    from kube_scheduler_simulator_tpu.store.reflector import StoreReflector
    from kube_scheduler_simulator_tpu.store.resultstore import ResultStore

    s = ObjectStore()
    s.create("pods", {"metadata": {"name": "p", "namespace": "default"},
                      "spec": {}})
    rs = ResultStore()
    rs.add_selected_node("default", "p", "n1")
    refl = StoreReflector(s, sleep=lambda _t: None)
    refl.add_result_store(rs, "k")
    plan = FaultPlan([FaultRule("reflector.write_back", p=1.0, times=3,
                                error="conflict")], seed=1)
    with faults.armed(plan):
        refl.reflect("default", "p")
    pod = s.get("pods", "p", "default")
    assert pod["metadata"]["annotations"][ann.SELECTED_NODE] == "n1"


def test_reflect_batch_fault_degrades_to_per_pod_path():
    from kube_scheduler_simulator_tpu.store import annotations as ann
    from kube_scheduler_simulator_tpu.store.reflector import StoreReflector
    from kube_scheduler_simulator_tpu.store.resultstore import ResultStore

    s = ObjectStore()
    for n in ("a", "b"):
        s.create("pods", {"metadata": {"name": n, "namespace": "default"},
                          "spec": {}})
    rs = ResultStore()
    for n in ("a", "b"):
        rs.add_selected_node("default", n, f"n-{n}")
    refl = StoreReflector(s, sleep=lambda _t: None)
    refl.add_result_store(rs, "k")
    before = _counter("wave_faults_total", seam="reflector.write_back",
                      action="batch_fallback")
    plan = FaultPlan([FaultRule("reflector.write_back", nth=1,
                                error="runtime")], seed=1)
    with faults.armed(plan):
        refl.reflect_batch([("default", "a", None), ("default", "b", None)])
    assert _counter("wave_faults_total", seam="reflector.write_back",
                    action="batch_fallback") > before
    for n in ("a", "b"):
        pod = s.get("pods", n, "default")
        assert pod["metadata"]["annotations"][ann.SELECTED_NODE] == f"n-{n}"


def test_retry_stop_event_interrupts_backoff_fast():
    stop = threading.Event()
    calls = {"n": 0}

    def never_done():
        calls["n"] += 1
        return False, None

    threading.Timer(0.05, stop.set).start()
    t0 = time.monotonic()
    with pytest.raises(RetryAborted):
        retry_with_exponential_backoff(never_done, stop=stop)
    # the full schedule sleeps ~36s; the stop wakes it immediately
    assert time.monotonic() - t0 < 5.0
    assert calls["n"] >= 1


def test_reflector_teardown_interrupts_inflight_backoff():
    """Satellite regression: eviction/shutdown must not ride out the
    ~36s backoff of a conflicting write."""
    from kube_scheduler_simulator_tpu.store.reflector import StoreReflector
    from kube_scheduler_simulator_tpu.store.resultstore import ResultStore

    class ConflictStore(ObjectStore):
        def update(self, resource, obj, **kwargs):
            raise Conflict("always")

    s = ConflictStore()
    s.create("pods", {"metadata": {"name": "p", "namespace": "default"},
                      "spec": {}})
    rs = ResultStore()
    rs.add_selected_node("default", "p", "n1")
    refl = StoreReflector(s)  # REAL sleeps: the stop must interrupt them
    refl.add_result_store(rs, "k")
    errs: list = []

    def run():
        try:
            refl.reflect("default", "p")
        except BaseException as e:  # noqa: BLE001 — asserted below
            errs.append(e)

    t = threading.Thread(target=run, daemon=True)
    t.start()
    time.sleep(0.15)  # let it enter the backoff
    t0 = time.monotonic()
    refl.stop_event.set()
    t.join(timeout=5)
    assert not t.is_alive(), "reflect rode out the backoff past teardown"
    assert time.monotonic() - t0 < 2.0
    assert errs and isinstance(errs[0], RetryAborted)


# ------------------------------------------------------- session seams


def test_session_create_fault_releases_reservation():
    from kube_scheduler_simulator_tpu.server.sessions import SessionManager

    mgr = SessionManager(max_sessions=4, idle_ttl=0, start_scheduler=False)
    try:
        plan = FaultPlan([FaultRule("session.create", nth=1,
                                    error="runtime")], seed=1)
        with faults.armed(plan):
            with pytest.raises(InjectedFault):
                mgr.create("s1")
            sess = mgr.create("s1")  # the reservation was released
        assert sess.id == "s1"
        assert {s["id"] for s in mgr.list_sessions()} == {"default", "s1"}
    finally:
        mgr.shutdown()


def test_session_evict_fault_counted_not_wedging():
    from kube_scheduler_simulator_tpu.server.sessions import SessionManager

    mgr = SessionManager(max_sessions=4, idle_ttl=0, start_scheduler=False)
    try:
        mgr.create("s1")
        before = _counter("session_teardown_failures_total",
                          reason="explicit")
        plan = FaultPlan([FaultRule("session.evict", nth=1,
                                    error="runtime")], seed=1)
        with faults.armed(plan):
            mgr.delete("s1")  # teardown fault: counted, not raised
        assert _counter("session_teardown_failures_total",
                        reason="explicit") > before
        assert {s["id"] for s in mgr.list_sessions()} == {"default"}
        mgr.create("s1")  # admission still works
    finally:
        mgr.shutdown()


def test_sessions_surface_degraded_mode():
    from kube_scheduler_simulator_tpu.server.sessions import SessionManager

    mgr = SessionManager(max_sessions=4, idle_ttl=0, start_scheduler=False)
    try:
        info = mgr.default.info()
        assert info["resultMode"] == "device_resident"
        assert info["degraded"] is False
        mgr.default.di.engine._degrade("test")
        info = mgr.default.info()
        assert info["resultMode"] == "host_resident"
        assert info["degraded"] is True
    finally:
        mgr.shutdown()


# --------------------------------------------------------------- taps


def test_fault_taps_are_valid_exposition():
    from kube_scheduler_simulator_tpu.utils.tracing import validate_exposition

    s = _cluster(n_pods=4)
    eng = _engine(s)
    plan = FaultPlan([FaultRule("replay.scan_dispatch", nth=1,
                                error="runtime")], seed=1)
    with faults.armed(plan):
        eng.schedule_pending()
    text = TRACER.prometheus_text()
    assert "wave_retries_total" in text
    assert "fault_injected_total" in text
    validate_exposition(text)  # raises on any conformance violation
