"""PodTopologySpread v1.27+ knobs: matchLabelKeys, minDomains,
nodeAffinityPolicy / nodeTaintsPolicy (upstream
pkg/scheduler/framework/plugins/podtopologyspread; defaults Honor/Ignore).
Each case is asserted two ways: tensor replay == sequential oracle
(byte-identical annotations) AND a hand-computed placement expectation.
"""

import json

from kube_scheduler_simulator_tpu.framework.replay import replay
from kube_scheduler_simulator_tpu.reference_impl.sequential import SequentialScheduler
from kube_scheduler_simulator_tpu.state.compile import compile_workload
from kube_scheduler_simulator_tpu.store import annotations as ann
from kube_scheduler_simulator_tpu.store.decode import decode_pod_result


def node(name, zone=None, taints=None, extra_labels=None):
    labels = {"kubernetes.io/hostname": name}
    if zone:
        labels["zone"] = zone
    labels.update(extra_labels or {})
    n = {
        "apiVersion": "v1", "kind": "Node",
        "metadata": {"name": name, "labels": labels},
        "spec": {},
        "status": {"allocatable": {"cpu": "8", "memory": "32Gi", "pods": "110"},
                   "capacity": {"cpu": "8", "memory": "32Gi", "pods": "110"}},
    }
    if taints:
        n["spec"]["taints"] = taints
    return n


def pod(name, labels=None, constraints=None, tolerations=None):
    p = {
        "apiVersion": "v1", "kind": "Pod",
        "metadata": {"name": name, "namespace": "default",
                     "labels": labels or {}},
        "spec": {"containers": [{"name": "c",
                                 "resources": {"requests": {"cpu": "100m"}}}]},
    }
    if constraints:
        p["spec"]["topologySpreadConstraints"] = constraints
    if tolerations:
        p["spec"]["tolerations"] = tolerations
    return p


def assert_parity(nodes, pods, cfg_plugins=("NodeResourcesFit", "PodTopologySpread")):
    from kube_scheduler_simulator_tpu.plugins.registry import PluginSetConfig

    cfg = PluginSetConfig(enabled=list(cfg_plugins))
    seq = SequentialScheduler(nodes, pods, cfg).schedule_all()
    rr = replay(compile_workload(nodes, pods, cfg), chunk=8)
    for i, (sa, ss) in enumerate(seq):
        da = decode_pod_result(rr, i)
        assert int(rr.selected[i]) == ss, f"pod {i} selected"
        for k in sa:
            assert da[k] == sa[k], f"pod {i} {k}\n dev={da[k][:200]}\n seq={sa[k][:200]}"
    return seq, rr


SPREAD = {"maxSkew": 1, "topologyKey": "zone",
          "whenUnsatisfiable": "DoNotSchedule",
          "labelSelector": {"matchLabels": {"app": "web"}}}


def test_match_label_keys_narrows_counting():
    """Two generations of 'web' pods distinguished by pod-template-hash;
    matchLabelKeys: the new generation spreads among ITSELF, ignoring the
    old generation's placement."""
    nodes = [node("n0", zone="a"), node("n1", zone="b")]
    c = dict(SPREAD, matchLabelKeys=["pod-template-hash"])
    old = [pod(f"old-{i}", labels={"app": "web", "pod-template-hash": "v1"},
               constraints=[c]) for i in range(2)]
    new = [pod(f"new-{i}", labels={"app": "web", "pod-template-hash": "v2"},
               constraints=[c]) for i in range(3)]
    seq, _ = assert_parity(nodes, old + new)
    # without matchLabelKeys, v1 pods on both zones would constrain v2;
    # with it, v2 spreads 2/1 over zones regardless of v1 placement
    zones = {}
    for (annos, sel), p in zip(seq, old + new):
        if sel >= 0 and p["metadata"]["name"].startswith("new"):
            zones.setdefault(sel, 0)
            zones[sel] += 1
    assert sorted(zones.values()) == [1, 2]
    # and the selector recorded nothing about v1 pods blocking v2: all new
    # pods scheduled
    assert all(sel >= 0 for (_, sel) in seq)


def test_min_domains_blocks_single_domain_pileup():
    """minDomains=2 with only one zone present: the global minimum is
    treated as 0, so once maxSkew pods sit in the lone zone the next pod
    is unschedulable (without minDomains it would pile up forever)."""
    nodes = [node("n0", zone="a"), node("n1", zone="a")]
    c = dict(SPREAD, minDomains=2)
    pods = [pod(f"w-{i}", labels={"app": "web"}, constraints=[c]) for i in range(3)]
    seq, _ = assert_parity(nodes, pods)
    sels = [s for _, s in seq]
    assert sels[0] >= 0
    # second pod: count(a)=1 + self 1 - 0 = 2 > maxSkew 1 -> unschedulable
    assert sels[1] == -1 and sels[2] == -1
    annos = seq[1][0]
    fr = json.loads(annos[ann.FILTER_RESULT])
    assert "topology spread" in fr["n0"]["PodTopologySpread"]


def test_without_min_domains_single_domain_pileup_allowed():
    nodes = [node("n0", zone="a"), node("n1", zone="a")]
    pods = [pod(f"w-{i}", labels={"app": "web"}, constraints=[dict(SPREAD)])
            for i in range(3)]
    seq, _ = assert_parity(nodes, pods)
    assert all(s >= 0 for _, s in seq)  # skew vs global min 0? no: min is
    # over the only domain, which grows with each bind -> skew stays 1


def test_node_taints_policy_honor_excludes_tainted_domain():
    """nodeTaintsPolicy Honor: a zone whose only node is untolerably
    tainted doesn't count toward the minimum, so pods keep landing in the
    open zone instead of going unschedulable."""
    taint = [{"key": "dedicated", "value": "infra", "effect": "NoSchedule"}]
    nodes = [node("n0", zone="a"), node("n1", zone="b", taints=taint)]
    c = dict(SPREAD, nodeTaintsPolicy="Honor")
    pods = [pod(f"w-{i}", labels={"app": "web"}, constraints=[c])
            for i in range(2)]
    # TaintToleration makes n1 infeasible; the knob under test controls
    # whether its EMPTY zone still drags the spread minimum down
    seq, _ = assert_parity(
        nodes, pods,
        cfg_plugins=("NodeResourcesFit", "TaintToleration", "PodTopologySpread"))
    assert [s for _, s in seq] == [0, 0]  # both land on n0, no skew fail


def test_node_taints_policy_default_ignore_counts_tainted_domain():
    """Default (Ignore): the tainted zone still counts, so the second pod
    fails the skew check against the empty-but-counted zone b."""
    taint = [{"key": "dedicated", "value": "infra", "effect": "NoSchedule"}]
    nodes = [node("n0", zone="a"), node("n1", zone="b", taints=taint)]
    pods = [pod(f"w-{i}", labels={"app": "web"}, constraints=[dict(SPREAD)])
            for i in range(2)]
    seq, _ = assert_parity(
        nodes, pods,
        cfg_plugins=("NodeResourcesFit", "TaintToleration", "PodTopologySpread"))
    assert [s for _, s in seq] == [0, -1]


def test_node_affinity_policy_ignore_counts_unselectable_domain():
    """nodeAffinityPolicy Ignore: a zone excluded by the pod's own
    nodeSelector still participates in the minimum, making the second pod
    unschedulable; with the default Honor it schedules."""
    nodes = [node("n0", zone="a", extra_labels={"pool": "x"}),
             node("n1", zone="b")]
    base = {"maxSkew": 1, "topologyKey": "zone",
            "whenUnsatisfiable": "DoNotSchedule",
            "labelSelector": {"matchLabels": {"app": "web"}}}

    def with_selector(c):
        p = [pod(f"w-{i}", labels={"app": "web"}, constraints=[c])
             for i in range(2)]
        for q in p:
            q["spec"]["nodeSelector"] = {"pool": "x"}
        return p

    plugins = ("NodeResourcesFit", "NodeAffinity", "PodTopologySpread")
    seq, _ = assert_parity(nodes, with_selector(dict(base)), cfg_plugins=plugins)
    assert [s for _, s in seq] == [0, 0]  # Honor: zone b not eligible
    seq, _ = assert_parity(nodes, with_selector(dict(base, nodeAffinityPolicy="Ignore")),
                           cfg_plugins=plugins)
    assert [s for _, s in seq] == [0, -1]  # Ignore: zone b counts, skew fails


def test_min_domains_zero_eligible_domains_is_skipped():
    """Upstream: a topology key with ZERO eligible domains errors in
    minMatchNum and the constraint is skipped, not zeroed — minDomains
    must not make such pods unschedulable (review r3 finding)."""
    taint = [{"key": "dedicated", "value": "infra", "effect": "NoSchedule"}]
    # the only zoned node is untolerably tainted: with nodeTaintsPolicy
    # Honor there are 0 eligible domains for the constraint
    nodes = [node("n0", zone="a", taints=taint)]
    c = dict(SPREAD, minDomains=2, nodeTaintsPolicy="Honor")
    pods = [pod("w-0", labels={"app": "web"}, constraints=[c])]
    seq, _ = assert_parity(nodes, pods)
    assert seq[0][1] == 0  # schedulable: the constraint was skipped
