"""Custom out-of-tree plugins + plugin extenders (the WithPlugin /
WithPluginExtenders analogue), with engine-vs-oracle parity."""

import json

import pytest

from kube_scheduler_simulator_tpu.cluster.store import ObjectStore
from kube_scheduler_simulator_tpu.framework.engine import SchedulerEngine
from kube_scheduler_simulator_tpu.framework.replay import replay
from kube_scheduler_simulator_tpu.models.workloads import make_nodes, make_pods
from kube_scheduler_simulator_tpu.plugins.custom import CustomPlugin, build_custom
from kube_scheduler_simulator_tpu.plugins.registry import PluginSetConfig
from kube_scheduler_simulator_tpu.reference_impl.sequential import SequentialScheduler
from kube_scheduler_simulator_tpu.scheduler.debuggable import PluginExtender, new_scheduler_command
from kube_scheduler_simulator_tpu.state.compile import compile_workload
from kube_scheduler_simulator_tpu.store import annotations as ann
from kube_scheduler_simulator_tpu.store.decode import decode_pod_result


class EvenNodesOnly(CustomPlugin):
    """Vetoes odd-indexed nodes; prefers high node indices."""

    name = "EvenNodesOnly"
    default_weight = 2

    def filter(self, pod, node):
        idx = int(node["metadata"]["name"].rsplit("-", 1)[1])
        return None if idx % 2 == 0 else "odd nodes not allowed"

    def score(self, pod, node):
        return int(node["metadata"]["name"].rsplit("-", 1)[1])


def test_custom_plugin_parity():
    nodes = make_nodes(6, seed=20)
    pods = make_pods(8, seed=21)
    cfg = PluginSetConfig(
        enabled=["NodeResourcesFit", "EvenNodesOnly"],
        custom={"EvenNodesOnly": EvenNodesOnly()},
    )
    seq = SequentialScheduler(nodes, pods, cfg).schedule_all()
    rr = replay(compile_workload(nodes, pods, cfg), chunk=8)
    for i, (sa, ss) in enumerate(seq):
        da = decode_pod_result(rr, i)
        assert int(rr.selected[i]) == ss
        for k in sa:
            assert da[k] == sa[k], f"pod {i} {k}"
    # custom filter message appears in the annotation
    fr = json.loads(seq[0][0][ann.FILTER_RESULT])
    assert fr["node-00001"]["EvenNodesOnly"] == "odd nodes not allowed"
    # odd nodes never selected
    for _, s in seq:
        assert s % 2 == 0


def test_custom_normalize_rejected():
    class BadPlugin(CustomPlugin):
        name = "Bad"

        def score(self, pod, node):
            return 1

        def normalize(self, scores):
            return scores

    nodes = make_nodes(2, seed=22)
    from kube_scheduler_simulator_tpu.state.nodes import build_node_table
    from kube_scheduler_simulator_tpu.state.resources import ResourceSchema

    table = build_node_table(nodes, ResourceSchema())
    with pytest.raises(ValueError, match="NormalizeScore"):
        build_custom(BadPlugin(), table, [], nodes)


def test_new_scheduler_command_with_plugin_and_extender():
    seen = []

    class Marker(PluginExtender):
        def after_cycle(self, pod, annotations, result_store):
            meta = pod["metadata"]
            seen.append(meta["name"])
            result_store.add_custom_result(
                meta.get("namespace") or "default", meta["name"],
                "my-debug-annotation", "cycle-observed",
            )

    di, server = new_scheduler_command(
        with_plugins=[EvenNodesOnly()],
        with_plugin_extenders={"EvenNodesOnly": Marker()},
        start_scheduler=False,
    )
    for n in make_nodes(4, seed=23):
        di.store.create("nodes", n)
    di.store.create("pods", make_pods(1, seed=24)[0])
    assert di.engine.schedule_pending() == 1
    pod = di.store.get("pods", "pod-00000")
    assert seen == ["pod-00000"]
    annos = pod["metadata"]["annotations"]
    assert annos["my-debug-annotation"] == "cycle-observed"
    assert "EvenNodesOnly" in annos[ann.FINAL_SCORE_RESULT]
    di.shutdown()


def test_custom_plugins_survive_restart_and_reset():
    di, server = new_scheduler_command(with_plugins=[EvenNodesOnly()], start_scheduler=False)
    svc = di.scheduler_service
    # a config apply (only profiles honored) must not drop the custom plugin
    cfg = svc.get_config()
    svc.restart_scheduler(cfg)
    assert "EvenNodesOnly" in di.engine.plugin_config.custom
    assert "EvenNodesOnly" in di.engine.plugin_config.enabled
    svc.reset_scheduler()
    assert "EvenNodesOnly" in di.engine.plugin_config.custom
    di.shutdown()


def test_extender_duration_and_nodes_response():
    from kube_scheduler_simulator_tpu.scheduler.extender import ExtenderClient
    from kube_scheduler_simulator_tpu.utils.duration import parse_duration_seconds

    c = ExtenderClient({"urlPrefix": "http://x", "httpTimeout": "100ms"})
    assert abs(c.timeout - 0.1) < 1e-9
    assert parse_duration_seconds("1m30s") == 90.0
    assert parse_duration_seconds(2) == 2.0
