"""Custom out-of-tree plugins + plugin extenders (the WithPlugin /
WithPluginExtenders analogue), with engine-vs-oracle parity."""

import json

import pytest

from kube_scheduler_simulator_tpu.cluster.store import ObjectStore
from kube_scheduler_simulator_tpu.framework.engine import SchedulerEngine
from kube_scheduler_simulator_tpu.framework.replay import replay
from kube_scheduler_simulator_tpu.models.workloads import make_nodes, make_pods
from kube_scheduler_simulator_tpu.plugins.custom import CustomPlugin, build_custom
from kube_scheduler_simulator_tpu.plugins.registry import PluginSetConfig
from kube_scheduler_simulator_tpu.reference_impl.sequential import SequentialScheduler
from kube_scheduler_simulator_tpu.scheduler.debuggable import PluginExtender, new_scheduler_command
from kube_scheduler_simulator_tpu.state.compile import compile_workload
from kube_scheduler_simulator_tpu.store import annotations as ann
from kube_scheduler_simulator_tpu.store.decode import decode_pod_result


class EvenNodesOnly(CustomPlugin):
    """Vetoes odd-indexed nodes; prefers high node indices."""

    name = "EvenNodesOnly"
    default_weight = 2

    def filter(self, pod, node):
        idx = int(node["metadata"]["name"].rsplit("-", 1)[1])
        return None if idx % 2 == 0 else "odd nodes not allowed"

    def score(self, pod, node):
        return int(node["metadata"]["name"].rsplit("-", 1)[1])


def test_custom_plugin_parity():
    nodes = make_nodes(6, seed=20)
    pods = make_pods(8, seed=21)
    cfg = PluginSetConfig(
        enabled=["NodeResourcesFit", "EvenNodesOnly"],
        custom={"EvenNodesOnly": EvenNodesOnly()},
    )
    seq = SequentialScheduler(nodes, pods, cfg).schedule_all()
    rr = replay(compile_workload(nodes, pods, cfg), chunk=8)
    for i, (sa, ss) in enumerate(seq):
        da = decode_pod_result(rr, i)
        assert int(rr.selected[i]) == ss
        for k in sa:
            assert da[k] == sa[k], f"pod {i} {k}"
    # custom filter message appears in the annotation
    fr = json.loads(seq[0][0][ann.FILTER_RESULT])
    assert fr["node-00001"]["EvenNodesOnly"] == "odd nodes not allowed"
    # odd nodes never selected
    for _, s in seq:
        assert s % 2 == 0


class HalfNormalize(CustomPlugin):
    """Scores the node index; NormalizeScore halves every score."""

    name = "HalfNormalize"
    default_weight = 3

    def score(self, pod, node):
        return int(node["metadata"]["name"].rsplit("-", 1)[1]) * 10

    def normalize(self, scores):
        return [s // 2 for s in scores]


def test_custom_normalize_requires_host_path():
    """replay() (the batched scan) cannot run Python NormalizeScore and
    must refuse, pointing at the engine's host-interleaved path."""
    nodes = make_nodes(3, seed=22)
    pods = make_pods(2, seed=23)
    cfg = PluginSetConfig(
        enabled=["NodeResourcesFit", "HalfNormalize"],
        custom={"HalfNormalize": HalfNormalize()},
    )
    with pytest.raises(ValueError, match="NormalizeScore"):
        replay(compile_workload(nodes, pods, cfg), chunk=2)


def test_custom_normalize_scheduled_and_recorded():
    """The engine routes custom-NormalizeScore configs to the host path;
    finalscore-result = normalize(raw) x weight and the oracle agrees."""
    from kube_scheduler_simulator_tpu.cluster.store import ObjectStore
    from kube_scheduler_simulator_tpu.framework.engine import SchedulerEngine

    nodes = make_nodes(4, seed=24)
    pods = make_pods(3, seed=25)
    cfg = PluginSetConfig(
        enabled=["NodeResourcesFit", "HalfNormalize"],
        custom={"HalfNormalize": HalfNormalize()},
    )
    store = ObjectStore()
    for n in nodes:
        store.create("nodes", n)
    for p in pods:
        store.create("pods", p)
    engine = SchedulerEngine(store, plugin_config=cfg)
    assert engine._needs_host_path()
    n_bound = engine.schedule_pending()

    seq = SequentialScheduler(nodes, pods, cfg).schedule_all()
    assert n_bound == sum(1 for _, s in seq if s >= 0)
    for i, (sa, ss) in enumerate(seq):
        pod = store.get("pods", pods[i]["metadata"]["name"])
        annos = pod["metadata"]["annotations"]
        for k in (ann.SCORE_RESULT, ann.FINAL_SCORE_RESULT, ann.FILTER_RESULT,
                  ann.SELECTED_NODE):
            assert annos.get(k) == sa[k], f"pod {i} {k}"
        got = pod["spec"].get("nodeName") or ""
        want = nodes[ss]["metadata"]["name"] if ss >= 0 else ""
        assert got == want
    # the record really shows halved scores: raw = idx*10, final = idx*5*w
    fs = json.loads(store.get("pods", pods[0]["metadata"]["name"])
                    ["metadata"]["annotations"][ann.FINAL_SCORE_RESULT])
    sc = json.loads(store.get("pods", pods[0]["metadata"]["name"])
                    ["metadata"]["annotations"][ann.SCORE_RESULT])
    for node_name, entry in fs.items():
        idx = int(node_name.rsplit("-", 1)[1])
        assert sc[node_name]["HalfNormalize"] == str(idx * 10)
        assert entry["HalfNormalize"] == str((idx * 10 // 2) * 3)


def test_new_scheduler_command_with_plugin_and_extender():
    seen = []

    class Marker(PluginExtender):
        def after_cycle(self, pod, annotations, result_store):
            meta = pod["metadata"]
            seen.append(meta["name"])
            result_store.add_custom_result(
                meta.get("namespace") or "default", meta["name"],
                "my-debug-annotation", "cycle-observed",
            )

    di, server = new_scheduler_command(
        with_plugins=[EvenNodesOnly()],
        with_plugin_extenders={"EvenNodesOnly": Marker()},
        start_scheduler=False,
    )
    for n in make_nodes(4, seed=23):
        di.store.create("nodes", n)
    di.store.create("pods", make_pods(1, seed=24)[0])
    assert di.engine.schedule_pending() == 1
    pod = di.store.get("pods", "pod-00000")
    assert seen == ["pod-00000"]
    annos = pod["metadata"]["annotations"]
    assert annos["my-debug-annotation"] == "cycle-observed"
    assert "EvenNodesOnly" in annos[ann.FINAL_SCORE_RESULT]
    di.shutdown()


def test_custom_plugins_survive_restart_and_reset():
    di, server = new_scheduler_command(with_plugins=[EvenNodesOnly()], start_scheduler=False)
    svc = di.scheduler_service
    # a config apply (only profiles honored) must not drop the custom plugin
    cfg = svc.get_config()
    svc.restart_scheduler(cfg)
    assert "EvenNodesOnly" in di.engine.plugin_config.custom
    assert "EvenNodesOnly" in di.engine.plugin_config.enabled
    svc.reset_scheduler()
    assert "EvenNodesOnly" in di.engine.plugin_config.custom
    di.shutdown()


def test_extender_duration_and_nodes_response():
    from kube_scheduler_simulator_tpu.scheduler.extender import ExtenderClient
    from kube_scheduler_simulator_tpu.utils.duration import parse_duration_seconds

    c = ExtenderClient({"urlPrefix": "http://x", "httpTimeout": "100ms"})
    assert abs(c.timeout - 0.1) < 1e-9
    assert parse_duration_seconds("1m30s") == 90.0
    assert parse_duration_seconds(2) == 2.0


class HugeScorer(CustomPlugin):
    """Scores beyond int32 (upstream node scores are int64): the compact
    replay keeps the precompiled row host-resident ("host" group) so the
    full-width values never travel from the device at all."""

    name = "HugeScorer"
    default_weight = 1

    def score(self, pod, node):
        return (1 << 33) + int(node["metadata"]["name"].rsplit("-", 1)[1])


def test_custom_scores_beyond_int32_round_trip():
    nodes = make_nodes(4, seed=30)
    pods = make_pods(3, seed=31)
    cfg = PluginSetConfig(
        enabled=["NodeResourcesFit", "HugeScorer"],
        custom={"HugeScorer": HugeScorer()},
    )
    cw = compile_workload(nodes, pods, cfg)
    pos = cw.config.scorers().index("HugeScorer")
    assert cw.host["score_dtypes"][pos] == "host"
    assert (cw.host["static_score_rows"]["HugeScorer"] > (1 << 33) - 1).any()
    seq = SequentialScheduler(nodes, pods, cfg).schedule_all()
    rr = replay(cw, chunk=4)
    for i, (sa, ss) in enumerate(seq):
        da = decode_pod_result(rr, i)
        assert int(rr.selected[i]) == ss
        for k in sa:
            assert da[k] == sa[k], f"pod {i} {k}"
    # the huge raw survives the transfer exactly
    sr = json.loads(seq[0][0][ann.SCORE_RESULT])
    assert any(int(v["HugeScorer"]) > (1 << 33) - 1
               for v in sr.values())


def test_custom_queue_sort_replaces_priority_sort():
    """A custom plugin overriding less() controls the scheduling order
    (wrappedPluginWithQueueSort analogue, wrappedplugin.go:754-771);
    without one, PrioritySort orders by priority desc then FIFO."""
    from kube_scheduler_simulator_tpu.cluster.store import ObjectStore
    from kube_scheduler_simulator_tpu.framework.engine import SchedulerEngine
    from kube_scheduler_simulator_tpu.plugins.custom import CustomPlugin
    from kube_scheduler_simulator_tpu.plugins.registry import PluginSetConfig

    class NameSort(CustomPlugin):
        name = "NameSort"

        def less(self, a, b):  # reverse-alphabetical by name
            return a["metadata"]["name"] > b["metadata"]["name"]

    store = ObjectStore()
    store.create("nodes", {"metadata": {"name": "n1"},
                           "status": {"allocatable": {"cpu": "8", "memory": "16Gi",
                                                      "pods": "100"}}})
    for name, prio in [("a", 0), ("b", 50), ("c", 0)]:
        store.create("pods", {"metadata": {"name": name},
                              "spec": {"priority": prio,
                                       "containers": [{"name": "c"}]}})
    eng = SchedulerEngine(store, plugin_config=PluginSetConfig(
        enabled=["NodeResourcesFit", "NameSort"],
        custom={"NameSort": NameSort()}))
    assert [p["metadata"]["name"] for p in eng.pending_pods()] == ["c", "b", "a"]

    # without the custom sorter: priority desc, then FIFO
    eng2 = SchedulerEngine(store, plugin_config=PluginSetConfig(
        enabled=["NodeResourcesFit"]))
    assert [p["metadata"]["name"] for p in eng2.pending_pods()] == ["b", "a", "c"]


def test_two_queue_sort_plugins_rejected():
    """Upstream refuses to start with more than one QueueSort plugin;
    the engine rejects such configs the same way."""
    import pytest

    from kube_scheduler_simulator_tpu.cluster.store import ObjectStore
    from kube_scheduler_simulator_tpu.framework.engine import SchedulerEngine
    from kube_scheduler_simulator_tpu.plugins.custom import CustomPlugin
    from kube_scheduler_simulator_tpu.plugins.registry import PluginSetConfig

    class SortA(CustomPlugin):
        name = "SortA"

        def less(self, a, b):
            return False

    class SortB(SortA):
        name = "SortB"

    store = ObjectStore()
    store.create("pods", {"metadata": {"name": "p"},
                          "spec": {"containers": [{"name": "c"}]}})
    eng = SchedulerEngine(store, plugin_config=PluginSetConfig(
        enabled=["NodeResourcesFit", "SortA", "SortB"],
        custom={"SortA": SortA(), "SortB": SortB()}))
    with pytest.raises(ValueError, match="one QueueSort"):
        eng.pending_pods()


def test_example_plugins_work_end_to_end():
    """The shipped examples (NodeNumber, RequestedCpuRecorder) schedule
    and record through the engine like the reference's samples do."""
    import sys
    from pathlib import Path

    sys.path.insert(0, str(Path(__file__).parent.parent / "examples"))
    from nodenumber_plugin import NodeNumber
    from plugin_extender import RequestedCpuRecorder

    from kube_scheduler_simulator_tpu.cluster.store import ObjectStore
    from kube_scheduler_simulator_tpu.framework.engine import SchedulerEngine
    from kube_scheduler_simulator_tpu.plugins.registry import PluginSetConfig

    store = ObjectStore()
    for j in (1, 2):
        store.create("nodes", {"metadata": {"name": f"node{j}"},
                               "status": {"allocatable": {"cpu": "8",
                                                          "memory": "16Gi",
                                                          "pods": "10"}}})
    eng = SchedulerEngine(store, plugin_config=PluginSetConfig(
        enabled=["NodeResourcesFit", "NodeNumber"],
        custom={"NodeNumber": NodeNumber()}))
    eng.plugin_extenders = {"NodeResourcesFit": RequestedCpuRecorder()}
    store.create("pods", {"metadata": {"name": "pod2"},
                          "spec": {"containers": [{"name": "c", "resources": {
                              "requests": {"cpu": "500m"}}}]}})
    assert eng.schedule_pending() == 1
    pod = store.get("pods", "pod2", "default")
    # NodeNumber: pod2 prefers node2
    assert pod["spec"]["nodeName"] == "node2"
    anns = pod["metadata"]["annotations"]
    assert anns["sample.simulator.example.com/requested-cpu"] == "500m"
