"""Table-driven result-store semantics, mirroring the behaviors the
reference pins in its deepest suite (resultstore/store_test.go, 1.3k LoC):
score vs normalized-score interplay, weight application, post-filter
nomination shape, permit timeout, custom results, delete isolation, and
the merge-over-decoded contract.

Reference: simulator/scheduler/plugin/resultstore/store.go:423-507 (adds),
:133-198 (GetStoredResult), :509-520 (DeleteData), :617-626 (custom).
"""

import json

import pytest

from kube_scheduler_simulator_tpu.store import annotations as ann
from kube_scheduler_simulator_tpu.store.resultstore import ResultStore


def _pod(name="p1", ns="default"):
    return {"metadata": {"name": name, "namespace": ns}}


# ---------------------------------------------------------------- score math

SCORE_CASES = [
    # (weights, adds, expected score-result, expected finalscore-result)
    # AddScoreResult records the raw value AND pre-populates finalscore
    # with raw x weight (store.go:461-478 calls the normalize add itself)
    ("raw_prepopulates_final", {"P": 2},
     [("score", "n1", "P", 7)],
     {"n1": {"P": "7"}}, {"n1": {"P": "14"}}),
    # a later AddNormalizedScoreResult OVERWRITES finalscore (the plugin
    # had a NormalizeScore extension) but score-result keeps the raw
    ("normalize_overwrites_final", {"P": 2},
     [("score", "n1", "P", 7), ("norm", "n1", "P", 100)],
     {"n1": {"P": "7"}}, {"n1": {"P": "200"}}),
    # weight missing from the map multiplies by zero (Go zero-value)
    ("missing_weight_is_zero", {},
     [("score", "n1", "P", 50)],
     {"n1": {"P": "50"}}, {"n1": {"P": "0"}}),
    # negative scores pass through untouched (extenders may produce them)
    ("negative_scores", {"P": 3},
     [("score", "n1", "P", -5)],
     {"n1": {"P": "-5"}}, {"n1": {"P": "-15"}}),
    # independent nodes and plugins do not cross-contaminate
    ("per_node_per_plugin", {"A": 1, "B": 2},
     [("score", "n1", "A", 1), ("score", "n2", "A", 2),
      ("score", "n1", "B", 3), ("norm", "n1", "B", 10)],
     {"n1": {"A": "1", "B": "3"}, "n2": {"A": "2"}},
     {"n1": {"A": "1", "B": "20"}, "n2": {"A": "2"}}),
]


@pytest.mark.parametrize("name,weights,adds,want_score,want_final",
                         [(c[0], c[1], c[2], c[3], c[4]) for c in SCORE_CASES])
def test_score_tables(name, weights, adds, want_score, want_final):
    rs = ResultStore(score_plugin_weight=weights)
    for kind, node, plugin, val in adds:
        if kind == "score":
            rs.add_score_result("default", "p1", node, plugin, val)
        else:
            rs.add_normalized_score_result("default", "p1", node, plugin, val)
    out = rs.get_stored_result(_pod())
    assert json.loads(out[ann.SCORE_RESULT]) == want_score
    assert json.loads(out[ann.FINAL_SCORE_RESULT]) == want_final


# ------------------------------------------------------------- post filter

def test_postfilter_nominated_shape():
    """Every candidate node appears; only the nominated one carries the
    'preemption victim' message (store.go:443-459)."""
    rs = ResultStore()
    rs.add_post_filter_result("default", "p1", "n2", "DefaultPreemption",
                              ["n1", "n2", "n3"])
    out = rs.get_stored_result(_pod())
    assert json.loads(out[ann.POST_FILTER_RESULT]) == {
        "n1": {}, "n2": {"DefaultPreemption": "preemption victim"}, "n3": {},
    }


def test_postfilter_no_nomination_all_empty():
    rs = ResultStore()
    rs.add_post_filter_result("default", "p1", "", "DefaultPreemption",
                              ["n1", "n2"])
    out = rs.get_stored_result(_pod())
    assert json.loads(out[ann.POST_FILTER_RESULT]) == {"n1": {}, "n2": {}}


# ----------------------------------------------------------------- permit

def test_permit_records_status_and_timeout_keys():
    rs = ResultStore()
    rs.add_permit_result("default", "p1", "GateKeeper", "wait", "10s")
    out = rs.get_stored_result(_pod())
    assert json.loads(out[ann.PERMIT_STATUS_RESULT]) == {"GateKeeper": "wait"}
    assert json.loads(out[ann.PERMIT_TIMEOUT_RESULT]) == {"GateKeeper": "10s"}


# ------------------------------------------------------------- custom keys

def test_custom_results_ride_alongside_standard_keys():
    rs = ResultStore()
    rs.add_filter_result("default", "p1", "n1", "P", "passed")
    rs.add_custom_result("default", "p1", "my.example.com/depth", "3")
    rs.add_custom_result("default", "p1", "my.example.com/depth", "4")  # last wins
    out = rs.get_stored_result(_pod())
    assert out["my.example.com/depth"] == "4"
    assert json.loads(out[ann.FILTER_RESULT]) == {"n1": {"P": "passed"}}


# -------------------------------------------------------- presence contract

def test_all_thirteen_keys_present_even_when_empty():
    """GetStoredResult emits every standard key for a known pod, empty
    maps as '{}' and selected-node as '' (store.go:133-198 emits each
    add*ToMap unconditionally)."""
    rs = ResultStore()
    rs.add_pre_score_result("default", "p1", "P", "success")  # make it known
    out = rs.get_stored_result(_pod())
    for key in (ann.PRE_FILTER_RESULT, ann.PRE_FILTER_STATUS_RESULT,
                ann.FILTER_RESULT, ann.POST_FILTER_RESULT,
                ann.SCORE_RESULT, ann.FINAL_SCORE_RESULT,
                ann.RESERVE_RESULT, ann.PERMIT_STATUS_RESULT,
                ann.PERMIT_TIMEOUT_RESULT, ann.PRE_BIND_RESULT,
                ann.BIND_RESULT):
        assert out[key] == "{}", key
    assert out[ann.PRE_SCORE_RESULT] == '{"P":"success"}'
    assert out[ann.SELECTED_NODE] == ""


def test_unknown_pod_returns_none():
    rs = ResultStore()
    rs.add_filter_result("default", "p1", "n1", "P", "passed")
    assert rs.get_stored_result(_pod(name="other")) is None
    assert rs.get_stored_result(_pod(name="p1", ns="kube-system")) is None


# -------------------------------------------------------------- delete

def test_delete_data_is_per_pod_and_idempotent():
    rs = ResultStore()
    rs.add_filter_result("default", "a", "n1", "P", "passed")
    rs.add_filter_result("default", "b", "n1", "P", "passed")
    rs.delete_data(_pod(name="a"))
    assert rs.get_stored_result(_pod(name="a")) is None
    assert rs.get_stored_result(_pod(name="b")) is not None
    rs.delete_data(_pod(name="a"))  # no error on double delete
    # re-adding after delete starts clean
    rs.add_score_result("default", "a", "n1", "P", 1)
    out = rs.get_stored_result(_pod(name="a"))
    assert json.loads(out[ann.FILTER_RESULT]) == {}


# --------------------------------------------------- merge-over-decoded

def test_granular_adds_merge_over_decoded_blob():
    """A custom plugin's granular add must not erase the decoded (tensor-
    path) entries under the same key, and vice versa."""
    rs = ResultStore(score_plugin_weight={"Custom": 1})
    rs.put_decoded("default", "p1", {
        ann.FILTER_RESULT: '{"n1":{"NodeResourcesFit":"passed"}}',
        ann.RESERVE_RESULT: '{"VolumeBinding":"success"}',
    })
    rs.add_filter_result("default", "p1", "n1", "Custom", "passed")
    rs.add_reserve_result("default", "p1", "Custom", "success")
    out = rs.get_stored_result(_pod())
    assert json.loads(out[ann.FILTER_RESULT]) == {
        "n1": {"Custom": "passed", "NodeResourcesFit": "passed"}}
    assert json.loads(out[ann.RESERVE_RESULT]) == {
        "Custom": "success", "VolumeBinding": "success"}


def test_selected_node_granular_overrides_decoded():
    rs = ResultStore()
    rs.put_decoded("default", "p1", {ann.SELECTED_NODE: "n1"})
    out = rs.get_stored_result(_pod())
    assert out[ann.SELECTED_NODE] == "n1"  # decoded survives when no granular
    rs.add_selected_node("default", "p1", "n2")
    out = rs.get_stored_result(_pod())
    assert out[ann.SELECTED_NODE] == "n2"


# Extender result-store semantics live in tests/test_extender_store_tables.py
# (table-driven mirror of extender/resultstore/resultstore_test.go).


# ------------------------------------------------- per-add merge tables
#
# store_test.go pins three shapes for every node-keyed add: into an empty
# store, into an existing map for the SAME node, and alongside a map for a
# DIFFERENT node (store_test.go:34-152 filter, :284-447 score,
# :448-583 normalized).

def _filter_blob(rs):
    return json.loads(rs.get_stored_result(_pod())[ann.FILTER_RESULT])


def test_filter_add_into_empty_store():
    rs = ResultStore()
    rs.add_filter_result("default", "p1", "node1", "fakeFilterPlugin", "passed")
    assert _filter_blob(rs) == {"node1": {"fakeFilterPlugin": "passed"}}


def test_filter_add_merges_into_existing_node_map():
    rs = ResultStore()
    rs.add_filter_result("default", "p1", "node1", "pluginA", "passed")
    rs.add_filter_result("default", "p1", "node1", "pluginB", "node(s) had taints")
    assert _filter_blob(rs) == {
        "node1": {"pluginA": "passed", "pluginB": "node(s) had taints"}}


def test_filter_add_creates_second_node_map():
    rs = ResultStore()
    rs.add_filter_result("default", "p1", "node1", "pluginA", "passed")
    rs.add_filter_result("default", "p1", "node2", "pluginA", "passed")
    assert _filter_blob(rs) == {
        "node1": {"pluginA": "passed"}, "node2": {"pluginA": "passed"}}


def test_filter_add_same_plugin_same_node_overwrites():
    rs = ResultStore()
    rs.add_filter_result("default", "p1", "node1", "pluginA", "passed")
    rs.add_filter_result("default", "p1", "node1", "pluginA", "too many pods")
    assert _filter_blob(rs) == {"node1": {"pluginA": "too many pods"}}


def test_score_add_shapes_mirror_filter():
    rs = ResultStore({"A": 1, "B": 1})
    rs.add_score_result("default", "p1", "node1", "A", 10)
    rs.add_score_result("default", "p1", "node1", "B", 20)
    rs.add_score_result("default", "p1", "node2", "A", 30)
    out = rs.get_stored_result(_pod())
    assert json.loads(out[ann.SCORE_RESULT]) == {
        "node1": {"A": "10", "B": "20"}, "node2": {"A": "30"}}


def test_normalized_add_without_prior_score_creates_final_only():
    """AddNormalizedScoreResult with no preceding AddScoreResult still
    writes finalscore (store_test.go:533 'no map for the node'); the raw
    score blob stays empty for that node."""
    rs = ResultStore({"P": 3})
    rs.add_normalized_score_result("default", "p1", "node9", "P", 11)
    out = rs.get_stored_result(_pod())
    assert json.loads(out[ann.FINAL_SCORE_RESULT]) == {"node9": {"P": "33"}}
    assert json.loads(out[ann.SCORE_RESULT]) == {}


def test_prefilter_status_and_result_pair():
    """AddPreFilterResult (store_test.go:835-884): the status blob and the
    (optional) node-list blob are separate annotations."""
    rs = ResultStore()
    rs.add_pre_filter_result("default", "p1", "NodeAffinity", "success",
                             pre_filter_result=["node1", "node2"])
    rs.add_pre_filter_result("default", "p1", "NodePorts", "success")
    out = rs.get_stored_result(_pod())
    assert json.loads(out[ann.PRE_FILTER_STATUS_RESULT]) == {
        "NodeAffinity": "success", "NodePorts": "success"}
    assert json.loads(out[ann.PRE_FILTER_RESULT]) == {
        "NodeAffinity": ["node1", "node2"]}


STATUS_ADDS = [
    ("prescore", lambda rs: rs.add_pre_score_result("default", "p1", "P", "success"),
     ann.PRE_SCORE_RESULT),
    ("reserve", lambda rs: rs.add_reserve_result("default", "p1", "P", "success"),
     ann.RESERVE_RESULT),
    ("prebind", lambda rs: rs.add_pre_bind_result("default", "p1", "P", "success"),
     ann.PRE_BIND_RESULT),
    ("bind", lambda rs: rs.add_bind_result("default", "p1", "P", "success"),
     ann.BIND_RESULT),
]


@pytest.mark.parametrize("point,add,key", STATUS_ADDS, ids=[s[0] for s in STATUS_ADDS])
def test_plugin_status_adds(point, add, key):
    """AddPreScore/Reserve/PreBind/BindResult success tables
    (store_test.go:885-927, :1015-1143): plugin -> status string."""
    rs = ResultStore()
    add(rs)
    assert json.loads(rs.get_stored_result(_pod())[key]) == {"P": "success"}


def test_get_stored_result_partial_data():
    """store_test.go:770 'success without some data on store': phases
    never recorded serialize as empty maps, not missing keys."""
    rs = ResultStore({"P": 1})
    rs.add_score_result("default", "p1", "node1", "P", 5)
    out = rs.get_stored_result(_pod())
    assert json.loads(out[ann.SCORE_RESULT]) == {"node1": {"P": "5"}}
    for key in (ann.FILTER_RESULT, ann.POST_FILTER_RESULT, ann.RESERVE_RESULT,
                ann.PERMIT_STATUS_RESULT, ann.BIND_RESULT):
        assert out[key] == "{}"
    assert out[ann.SELECTED_NODE] == ""
