"""Multi-chip sharding parity on the virtual 8-device CPU mesh: the
node-sharded step must produce exactly the selections and scores of the
unsharded program (GSPMD inserts the cross-shard reductions; the math
must not change)."""

import jax
import jax.numpy as jnp
import pytest

from kube_scheduler_simulator_tpu.framework.pipeline import build_step
from kube_scheduler_simulator_tpu.framework.replay import replay
from kube_scheduler_simulator_tpu.models.workloads import make_nodes, make_pods
from kube_scheduler_simulator_tpu.parallel.mesh import (
    make_mesh, shard_workload, sharded_step, speculative_scores)
from kube_scheduler_simulator_tpu.plugins.registry import PluginSetConfig
from kube_scheduler_simulator_tpu.state.compile import compile_workload


def _workload(n_nodes=16, n_pods=12, seed=80):
    nodes = make_nodes(n_nodes, seed=seed, taint_fraction=0.25)
    pods = make_pods(n_pods, seed=seed + 1, with_affinity=True,
                     with_tolerations=True, with_spread=True)
    return nodes, pods, PluginSetConfig()


def _scan_selections(cw, step):
    carry = cw.init_carry
    sel = []
    for i in range(cw.n_pods):
        sl = jax.tree.map(lambda a: a[i] if hasattr(a, "ndim") and a.ndim else a, cw.xs)
        sl["is_pad"] = jnp.asarray(False)
        carry, out = step(carry, sl)
        sel.append(int(out.selected))
    return sel


def test_sharded_step_matches_unsharded():
    nodes, pods, cfg = _workload()
    baseline = replay(compile_workload(nodes, pods, cfg), chunk=4)
    base_sel = [int(s) for s in baseline.selected]

    cw = compile_workload(nodes, pods, cfg)
    mesh = make_mesh(8, dp=1)  # all 8 virtual devices on the node axis
    cw = shard_workload(cw, mesh)
    step = sharded_step(cw, mesh)
    assert _scan_selections(cw, step) == base_sel


def test_sharded_dp_mesh_matches_unsharded():
    nodes, pods, cfg = _workload(n_nodes=8, n_pods=8, seed=81)
    baseline = replay(compile_workload(nodes, pods, cfg), chunk=4)
    base_sel = [int(s) for s in baseline.selected]

    cw = compile_workload(nodes, pods, cfg)
    mesh = make_mesh(8, dp=2)  # 2-way speculative batch x 4-way node shard
    cw = shard_workload(cw, mesh)
    step = sharded_step(cw, mesh)
    assert _scan_selections(cw, step) == base_sel


def test_sharded_replay_annotations_byte_identical():
    """The PRODUCTION path under a mesh: replay(cw, mesh=...) over a whole
    queue (chunked lax.scan with the node axis sharded over 8 virtual
    devices) must reproduce byte-identical annotations (VERDICT round-1
    next-step #3: mesh integrated beyond the dryrun)."""
    from kube_scheduler_simulator_tpu.store.decode import decode_pod_result

    nodes, pods, cfg = _workload(n_nodes=24, n_pods=10, seed=83)
    base = replay(compile_workload(nodes, pods, cfg), chunk=4)
    mesh = make_mesh(8, dp=1)
    sharded = replay(compile_workload(nodes, pods, cfg), chunk=4, mesh=mesh)
    assert [int(s) for s in sharded.selected] == [int(s) for s in base.selected]
    for i in range(len(pods)):
        da, db = decode_pod_result(sharded, i), decode_pod_result(base, i)
        assert da == db, f"pod {i} annotations diverge under sharding"


def test_engine_schedules_with_mesh():
    """SchedulerEngine(mesh=...) binds through the sharded replay with the
    same outcome as the unsharded engine."""
    from kube_scheduler_simulator_tpu.cluster.store import ObjectStore
    from kube_scheduler_simulator_tpu.framework.engine import SchedulerEngine

    nodes, pods, cfg = _workload(n_nodes=16, n_pods=6, seed=84)

    def run(mesh):
        store = ObjectStore()
        for n in nodes:
            store.create("nodes", n)
        for p in pods:
            store.create("pods", p)
        engine = SchedulerEngine(store, plugin_config=cfg, mesh=mesh)
        bound = engine.schedule_pending()
        placements = {}
        annos = {}
        for p in pods:
            cur = store.get("pods", p["metadata"]["name"])
            placements[p["metadata"]["name"]] = (cur["spec"].get("nodeName") or "")
            annos[p["metadata"]["name"]] = dict(
                (cur["metadata"].get("annotations") or {}))
        return bound, placements, annos

    b0, p0, a0 = run(None)
    b1, p1, a1 = run(make_mesh(8, dp=1))
    assert (b1, p1) == (b0, p0)
    assert a1 == a0


def test_make_mesh_rejects_non_divisible_dp():
    # regression (PR 16): a dp that does not divide the device count used
    # to surface as an opaque numpy reshape error (or silently drop
    # devices for floor-divided node counts) — make_mesh now names the
    # constraint up front
    with pytest.raises(ValueError, match="divide"):
        make_mesh(8, dp=3)
    with pytest.raises(ValueError, match="dp must be >= 1"):
        make_mesh(8, dp=0)
    # the divisible shapes still build
    assert make_mesh(8, dp=2).shape == {"dp": 2, "nodes": 4}


def test_speculative_batch_consistent_with_step():
    nodes, pods, cfg = _workload(n_nodes=8, n_pods=4, seed=82)
    cw = compile_workload(nodes, pods, cfg)
    step = build_step(cw)

    # per-pod eval against the SAME frozen initial state
    singles = []
    for i in range(cw.n_pods):
        sl = jax.tree.map(lambda a: a[i] if hasattr(a, "ndim") and a.ndim else a, cw.xs)
        sl["is_pad"] = jnp.asarray(False)
        _, out = step(cw.init_carry, sl)
        singles.append(int(out.selected))

    batched = speculative_scores(cw)
    xs_batch = jax.tree.map(lambda a: a if hasattr(a, "ndim") and a.ndim else a, cw.xs)
    xs_batch["is_pad"] = jnp.zeros((cw.n_pods,), dtype=bool)
    outs = batched(cw.init_carry, xs_batch)
    assert [int(s) for s in outs.selected] == singles
