"""Multi-chip sharding parity on the virtual 8-device CPU mesh: the
node-sharded step must produce exactly the selections and scores of the
unsharded program (GSPMD inserts the cross-shard reductions; the math
must not change)."""

import jax
import jax.numpy as jnp
import pytest

from kube_scheduler_simulator_tpu.framework.pipeline import build_step
from kube_scheduler_simulator_tpu.framework.replay import replay
from kube_scheduler_simulator_tpu.models.workloads import make_nodes, make_pods
from kube_scheduler_simulator_tpu.parallel.mesh import (
    make_mesh, shard_workload, sharded_step, speculative_scores)
from kube_scheduler_simulator_tpu.plugins.registry import PluginSetConfig
from kube_scheduler_simulator_tpu.state.compile import compile_workload


def _workload(n_nodes=16, n_pods=12, seed=80):
    nodes = make_nodes(n_nodes, seed=seed, taint_fraction=0.25)
    pods = make_pods(n_pods, seed=seed + 1, with_affinity=True,
                     with_tolerations=True, with_spread=True)
    return nodes, pods, PluginSetConfig()


def _scan_selections(cw, step):
    carry = cw.init_carry
    sel = []
    for i in range(cw.n_pods):
        sl = jax.tree.map(lambda a: a[i] if hasattr(a, "ndim") and a.ndim else a, cw.xs)
        sl["is_pad"] = jnp.asarray(False)
        carry, out = step(carry, sl)
        sel.append(int(out.selected))
    return sel


def test_sharded_step_matches_unsharded():
    nodes, pods, cfg = _workload()
    baseline = replay(compile_workload(nodes, pods, cfg), chunk=4)
    base_sel = [int(s) for s in baseline.selected]

    cw = compile_workload(nodes, pods, cfg)
    mesh = make_mesh(8, dp=1)  # all 8 virtual devices on the node axis
    shard_workload(cw, mesh)
    step = sharded_step(cw, mesh)
    assert _scan_selections(cw, step) == base_sel


def test_sharded_dp_mesh_matches_unsharded():
    nodes, pods, cfg = _workload(n_nodes=8, n_pods=8, seed=81)
    baseline = replay(compile_workload(nodes, pods, cfg), chunk=4)
    base_sel = [int(s) for s in baseline.selected]

    cw = compile_workload(nodes, pods, cfg)
    mesh = make_mesh(8, dp=2)  # 2-way speculative batch x 4-way node shard
    shard_workload(cw, mesh)
    step = sharded_step(cw, mesh)
    assert _scan_selections(cw, step) == base_sel


def test_speculative_batch_consistent_with_step():
    nodes, pods, cfg = _workload(n_nodes=8, n_pods=4, seed=82)
    cw = compile_workload(nodes, pods, cfg)
    step = build_step(cw)

    # per-pod eval against the SAME frozen initial state
    singles = []
    for i in range(cw.n_pods):
        sl = jax.tree.map(lambda a: a[i] if hasattr(a, "ndim") and a.ndim else a, cw.xs)
        sl["is_pad"] = jnp.asarray(False)
        _, out = step(cw.init_carry, sl)
        singles.append(int(out.selected))

    batched = speculative_scores(cw)
    xs_batch = jax.tree.map(lambda a: a if hasattr(a, "ndim") and a.ndim else a, cw.xs)
    xs_batch["is_pad"] = jnp.zeros((cw.n_pods,), dtype=bool)
    outs = batched(cw.init_carry, xs_batch)
    assert [int(s) for s in outs.selected] == singles
