"""kss-analyze: seeded-violation fixtures, suppression, the ratchet
baseline, and the clean-at-HEAD gate (docs/static-analysis.md).

The fixtures under tests/fixtures/analysis/ are never imported — the
analyzers are pure AST.  Each seeded violation from the acceptance list
(lock-order inversion, self-deadlock, device-op-under-lock, pod-loop in
the hot path, unbalanced span, bad metric name) must be caught, the
allow() comment and the baseline must silence exactly what they claim
to, and the baseline must be unable to grow without --update-baseline.
"""

import json
import os
import subprocess
import sys
import time

import pytest

from tools.analysis import REPO_ROOT, run_analysis
from tools.analysis.baseline import load_baseline, partition, save_baseline
from tools.analysis.cli import main as cli_main
from tools.analysis.common import load_module_file

FIXTURES = "tests/fixtures/analysis"


def _fixture_result(name: str, purity_roots=None):
    mod = load_module_file(REPO_ROOT, f"{FIXTURES}/{name}")
    return run_analysis(modules=[mod], purity_roots=purity_roots)


def _rules(findings):
    return {f.rule for f in findings}


# ------------------------------------------------------------ lock rules


def test_lock_order_inversion_detected():
    res = _fixture_result("bad_locks.py")
    inversions = [f for f in res["findings"] if f.rule == "lock-order"]
    assert inversions, "A->B/B->A inversion must be reported"
    assert any("Inverted._a" in f.detail and "Inverted._b" in f.detail
               for f in inversions)
    # both participating sites are anchored (ab and ba)
    quals = {f.qualname for f in inversions}
    assert {"Inverted.ab", "Inverted.ba"} <= quals


def test_self_deadlock_detected():
    res = _fixture_result("bad_locks.py")
    selfs = [f for f in res["findings"] if f.rule == "self-deadlock"]
    assert any(f.qualname == "SelfDeadlock.caller" for f in selfs), \
        "helper reacquiring the caller's non-reentrant lock (the PR 3 " \
        "kubeapi shape) must be reported"


def test_blocking_device_serialize_under_lock_detected():
    res = _fixture_result("bad_locks.py")
    by_rule = {}
    for f in res["findings"]:
        by_rule.setdefault(f.rule, set()).add(f.qualname)
    assert "BlockingUnderLock.sleeps" in by_rule["blocking-under-lock"]
    assert "BlockingUnderLock.spawns" in by_rule["blocking-under-lock"]
    assert "AcquireRelease.manual" in by_rule["blocking-under-lock"], \
        "acquire()/release() holds must be tracked, not just with-blocks"
    assert "BlockingUnderLock.device_work" in by_rule["device-under-lock"]
    assert "BlockingUnderLock.serializes" in by_rule["serialize-under-lock"]


def test_allow_comment_suppresses():
    res = _fixture_result("bad_locks.py")
    assert not any(f.qualname == "BlockingUnderLock.allowed"
                   for f in res["findings"])
    assert res["suppressed"] >= 1


# ---------------------------------------------------------- purity rules


_PURITY_ROOTS = [("bad_purity", "hot_entry"), ("bad_purity", "jitted_step"),
                 ("bad_purity", "allowed_loop")]


def test_pod_loop_and_host_sync_in_hot_path():
    res = _fixture_result("bad_purity.py", purity_roots=_PURITY_ROOTS)
    loops = [f for f in res["findings"] if f.rule == "pod-loop"]
    assert any(f.qualname == "hot_entry" and "pods" in f.detail
               for f in loops)
    assert any("range(len(nodes))" in f.detail for f in loops)
    syncs = [f for f in res["findings"] if f.rule == "host-sync"]
    assert any(f.qualname == "helper" for f in syncs), \
        ".item() reached through the call graph must be reported"


def test_nondeterminism_inside_jit():
    res = _fixture_result("bad_purity.py", purity_roots=_PURITY_ROOTS)
    nd = [f for f in res["findings"] if f.rule == "nondeterminism"]
    assert any(f.qualname == "jitted_step" and "time.time" in f.detail
               for f in nd)


def test_unreachable_and_allowed_not_flagged():
    res = _fixture_result("bad_purity.py", purity_roots=_PURITY_ROOTS)
    assert not any(f.qualname == "cold_helper" for f in res["findings"])
    assert not any(f.qualname == "allowed_loop" for f in res["findings"])


def test_compact_host_sync_detected():
    """Eager np.asarray/np.ascontiguousarray on a replay compact field
    (.packed/.raw8/.raw16/.raw32) outside _CompactChunks.materialize is
    flagged: device-resident chunks must cross D2H only through
    cc.host()/materialize() (docs/wave-pipeline.md device residency)."""
    roots = _PURITY_ROOTS + [("bad_purity", "eager_compact_fetch"),
                             ("bad_purity", "contiguous_compact_fetch")]
    res = _fixture_result("bad_purity.py", purity_roots=roots)
    hits = [f for f in res["findings"] if f.rule == "compact-host-sync"]
    assert any(f.qualname == "eager_compact_fetch" and "packed" in f.detail
               for f in hits), hits
    assert any(f.qualname == "contiguous_compact_fetch"
               and "raw16" in f.detail for f in hits), hits


def test_columnar_row_loop_detected():
    """A per-row Python loop over a columnar bank's row arrays
    (cluster/columnar.py) is flagged; per-column dict iteration and
    single-row subscripts are the sanctioned forms and stay clean
    (docs/data-plane.md)."""
    roots = _PURITY_ROOTS + [("bad_purity", "row_loop_over_columns"),
                             ("bad_purity", "column_dict_loop_ok")]
    res = _fixture_result("bad_purity.py", purity_roots=roots)
    hits = [f for f in res["findings"] if f.rule == "columnar-row-loop"]
    assert any(f.qualname == "row_loop_over_columns"
               and "names" in f.detail for f in hits), hits
    assert any(f.qualname == "row_loop_over_columns"
               and "range(len(cols.rv))" in f.detail for f in hits), hits
    assert not any(f.qualname == "column_dict_loop_ok" for f in hits), hits


# ------------------------------------------------------------ span rules


def test_swallowed_exception_detected():
    """The swallowed-exception rule (tools/analysis/swallowed.py): a
    handler whose body is entirely silent (pass/continue/...) is
    flagged; handlers that tap, re-raise or record state are not; an
    allow comment suppresses with a reason on record."""
    mod = load_module_file(REPO_ROOT, f"{FIXTURES}/bad_swallow.py")
    res = run_analysis(modules=[mod],
                       swallow_modules=("bad_swallow.py",))
    sw = [f for f in res["findings"] if f.rule == "swallowed-exception"]
    flagged = {f.qualname for f in sw}
    # nested siblings keep DISTINCT qualnames (distinct ratchet
    # fingerprints — a baselined inner_a must not mask a new inner_b)
    assert flagged == {"silent_pass", "silent_continue", "bare_silent",
                       "outer_with_nested.inner_a",
                       "outer_with_nested.inner_b"}, flagged
    assert any("except bare" in f.detail for f in sw)
    # the allowed site counted as suppressed, not as a finding
    assert res["suppressed"] >= 1


def test_swallowed_exception_scoped_to_hot_modules():
    """Modules outside the hot-path manifest are not policed: the rule
    exists for the fault seams' neighborhoods, not the whole tree."""
    mod = load_module_file(REPO_ROOT, f"{FIXTURES}/bad_swallow.py")
    res = run_analysis(modules=[mod])  # default manifest: no match
    assert not [f for f in res["findings"]
                if f.rule == "swallowed-exception"]


def test_unbalanced_span_and_bad_names():
    res = _fixture_result("bad_spans.py")
    rules = _rules(res["findings"])
    assert "unbalanced-span" in rules
    assert any(f.rule == "metric-name" and "bad-metric.name" in f.detail
               for f in res["findings"])
    assert any(f.rule == "label-name" and "__reserved" in f.detail
               for f in res["findings"])
    # the with-managed span is fine
    assert not any("ok_span" in f.detail for f in res["findings"])


# ------------------------------------------------- the repo at HEAD


def test_head_is_clean_and_fast():
    """`make analyze` contract: zero NEW findings at HEAD, without a
    device, comfortably under the 30s budget."""
    t0 = time.perf_counter()
    res = run_analysis()
    dt = time.perf_counter() - t0
    new, _old, stale = partition(res["findings"], load_baseline())
    assert new == [], [f.render() for f in new]
    assert stale == [], f"stale baseline entries: {stale}"
    assert dt < 30, f"analysis took {dt:.1f}s"


def test_kubeapi_rv_lock_edge_is_acyclic():
    """The PR 3 regression, as a property: kubeapi's watch path DOES
    acquire _rv_lock under _lock (the analyzer sees the nesting), and
    that edge participates in no cycle."""
    res = run_analysis()
    edges = res["lock_edges"]
    assert any("KubeAPICluster._lock" in a and "KubeAPICluster._rv_lock" in b
               for (a, b) in edges), "expected the _lock -> _rv_lock edge"
    assert not any(f.rule in ("lock-order", "self-deadlock")
                   for f in res["findings"]), \
        "no lock-order/self-deadlock findings expected at HEAD"


# ------------------------------------------------------------ the ratchet


@pytest.fixture
def tmp_pkg(tmp_path):
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "mod.py").write_text(
        "import threading\nimport time\n\n\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._mu = threading.Lock()\n\n"
        "    def bad(self):\n"
        "        with self._mu:\n"
        "            time.sleep(1)\n")
    return tmp_path


def _cli(tmp_pkg, baseline, *extra):
    return cli_main(["--root", str(tmp_pkg), "--package", "pkg",
                     "--baseline", str(baseline), "-q", *extra])


def test_ratchet_workflow(tmp_pkg, tmp_path):
    baseline = tmp_path / "baseline.json"
    # 1. a violation with no baseline fails
    assert _cli(tmp_pkg, baseline) == 1
    # 2. --update-baseline grandfathers it; the run then exits 0
    assert _cli(tmp_pkg, baseline, "--update-baseline") == 0
    assert _cli(tmp_pkg, baseline) == 0
    entries = json.loads(baseline.read_text())["entries"]
    assert len(entries) == 1 and "blocking-under-lock" in \
        entries[0]["fingerprint"]
    # 3. the baseline cannot grow implicitly: a NEW violation fails even
    #    though the old one stays grandfathered
    mod = tmp_pkg / "pkg" / "mod.py"
    mod.write_text(mod.read_text() +
                   "\n    def worse(self):\n"
                   "        with self._mu:\n"
                   "            time.sleep(2)\n")
    assert _cli(tmp_pkg, baseline) == 1
    # 4. fixing the original violation leaves a stale entry, reported
    #    and pruned by the next --update-baseline
    mod.write_text(
        "import threading\nimport time\n\n\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._mu = threading.Lock()\n\n"
        "    def good(self):\n"
        "        time.sleep(0)\n")
    assert _cli(tmp_pkg, baseline) == 0  # stale entries never fail
    assert _cli(tmp_pkg, baseline, "--update-baseline") == 0
    assert json.loads(baseline.read_text())["entries"] == []


def test_baseline_fingerprints_are_line_free(tmp_pkg, tmp_path):
    """Unrelated edits (shifting line numbers) must not churn the
    ratchet."""
    baseline = tmp_path / "baseline.json"
    assert _cli(tmp_pkg, baseline, "--update-baseline") == 0
    mod = tmp_pkg / "pkg" / "mod.py"
    mod.write_text("# a new leading comment\n" + mod.read_text())
    assert _cli(tmp_pkg, baseline) == 0


def test_suppression_beats_baseline(tmp_pkg, tmp_path):
    """An allow() comment silences without any baseline entry."""
    baseline = tmp_path / "baseline.json"
    mod = tmp_pkg / "pkg" / "mod.py"
    mod.write_text(mod.read_text().replace(
        "time.sleep(1)",
        "time.sleep(1)  # kss-analyze: allow(blocking-under-lock)"))
    assert _cli(tmp_pkg, baseline) == 0


def test_save_and_load_roundtrip(tmp_path):
    p = tmp_path / "b.json"
    save_baseline({"rule a/b.py f detail": "why"}, str(p))
    assert load_baseline(str(p)) == {"rule a/b.py f detail": "why"}


def test_cli_json_output(tmp_pkg, tmp_path):
    baseline = tmp_path / "baseline.json"
    out = tmp_path / "out.json"
    assert _cli(tmp_pkg, baseline, "--json", str(out)) == 1
    doc = json.loads(out.read_text())
    assert doc["new"] and doc["new"][0]["rule"] == "blocking-under-lock"


def test_module_entrypoint_matches_make_analyze():
    """`python -m tools.analysis` (what `make analyze` runs) exits 0 at
    HEAD — pure AST, no JAX import needed."""
    r = subprocess.run(
        [sys.executable, "-m", "tools.analysis", "-q"],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": ""})
    assert r.returncode == 0, r.stdout + r.stderr
    assert "0 new" in r.stdout
