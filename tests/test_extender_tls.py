"""Extender TLS client config vs a real TLS server with client-cert
verification (reference: simulator/scheduler/extender/extender.go:54-84 —
tlsConfig insecure/serverName/cert/key/CA in file and inline-data forms,
plus the enableHTTPS no-CA -> insecure default)."""

from __future__ import annotations

import base64
import datetime
import http.server
import json
import ssl
import threading
import urllib.error

import pytest

from kube_scheduler_simulator_tpu.scheduler.extender import ExtenderClient

try:
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import rsa
    from cryptography.x509.oid import NameOID
except ImportError:  # pragma: no cover
    pytest.skip("cryptography unavailable", allow_module_level=True)


def _make_cert(cn: str, issuer_key=None, issuer_cert=None, *, is_ca=False,
               san_dns=(), san_ip=()):
    key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    name = x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, cn)])
    now = datetime.datetime.now(datetime.timezone.utc)
    builder = (
        x509.CertificateBuilder()
        .subject_name(name)
        .issuer_name(issuer_cert.subject if issuer_cert is not None else name)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - datetime.timedelta(minutes=5))
        .not_valid_after(now + datetime.timedelta(hours=2))
        .add_extension(x509.BasicConstraints(ca=is_ca, path_length=None),
                       critical=True)
    )
    sans = [x509.DNSName(d) for d in san_dns]
    import ipaddress

    sans += [x509.IPAddress(ipaddress.ip_address(i)) for i in san_ip]
    if sans:
        builder = builder.add_extension(
            x509.SubjectAlternativeName(sans), critical=False)
    cert = builder.sign(issuer_key if issuer_key is not None else key,
                        hashes.SHA256())
    return key, cert


def _pem_key(key) -> bytes:
    return key.private_bytes(
        serialization.Encoding.PEM,
        serialization.PrivateFormat.TraditionalOpenSSL,
        serialization.NoEncryption())


def _pem_cert(cert) -> bytes:
    return cert.public_bytes(serialization.Encoding.PEM)


class _Handler(http.server.BaseHTTPRequestHandler):
    def do_POST(self):
        body = self.rfile.read(int(self.headers.get("Content-Length", 0)))
        _ = json.loads(body or b"{}")
        out = json.dumps({"nodenames": ["n1"]}).encode()
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(out)))
        self.end_headers()
        self.wfile.write(out)

    def log_message(self, *a):  # quiet
        pass


@pytest.fixture(scope="module")
def pki(tmp_path_factory):
    d = tmp_path_factory.mktemp("pki")
    ca_key, ca_cert = _make_cert("test-ca", is_ca=True)
    srv_key, srv_cert = _make_cert(
        "extender.test", ca_key, ca_cert,
        san_dns=("extender.test", "localhost"), san_ip=("127.0.0.1",))
    cli_key, cli_cert = _make_cert("test-client", ca_key, ca_cert)
    other_ca_key, other_ca_cert = _make_cert("other-ca", is_ca=True)
    files = {}
    for name, data in (
        ("ca.pem", _pem_cert(ca_cert)),
        ("server.pem", _pem_cert(srv_cert)), ("server.key", _pem_key(srv_key)),
        ("client.pem", _pem_cert(cli_cert)), ("client.key", _pem_key(cli_key)),
        ("other-ca.pem", _pem_cert(other_ca_cert)),
    ):
        (d / name).write_bytes(data)
        files[name] = str(d / name)
    return files


@pytest.fixture(scope="module")
def tls_server(pki):
    """HTTPS server REQUIRING a client certificate signed by the test CA."""
    httpd = http.server.HTTPServer(("127.0.0.1", 0), _Handler)
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.load_cert_chain(pki["server.pem"], pki["server.key"])
    ctx.load_verify_locations(pki["ca.pem"])
    ctx.verify_mode = ssl.CERT_REQUIRED
    httpd.socket = ctx.wrap_socket(httpd.socket, server_side=True)
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    yield f"https://127.0.0.1:{httpd.server_address[1]}"
    httpd.shutdown()


def _client(url, tls_config):
    return ExtenderClient({"urlPrefix": url, "filterVerb": "filter",
                           "httpTimeout": "3s", "tlsConfig": tls_config})


def test_mutual_tls_file_form(tls_server, pki):
    c = _client(tls_server, {"caFile": pki["ca.pem"],
                             "certFile": pki["client.pem"],
                             "keyFile": pki["client.key"]})
    assert c.filter({"Pod": {}})["nodenames"] == ["n1"]


def test_mutual_tls_inline_data_form(tls_server, pki):
    b64 = lambda p: base64.b64encode(open(p, "rb").read()).decode()
    c = _client(tls_server, {"caData": b64(pki["ca.pem"]),
                             "certData": b64(pki["client.pem"]),
                             "keyData": b64(pki["client.key"])})
    assert c.filter({"Pod": {}})["nodenames"] == ["n1"]


def test_data_wins_over_file(tls_server, pki):
    """client-go precedence: *Data is used when both forms are set."""
    b64 = lambda p: base64.b64encode(open(p, "rb").read()).decode()
    c = _client(tls_server, {
        "caFile": pki["other-ca.pem"], "caData": b64(pki["ca.pem"]),
        "certFile": pki["server.pem"], "certData": b64(pki["client.pem"]),
        "keyFile": pki["server.key"], "keyData": b64(pki["client.key"])})
    assert c.filter({"Pod": {}})["nodenames"] == ["n1"]


def test_missing_client_cert_rejected(tls_server, pki):
    c = _client(tls_server, {"caFile": pki["ca.pem"]})
    with pytest.raises(Exception):
        c.filter({"Pod": {}})


def test_wrong_ca_rejected(tls_server, pki):
    c = _client(tls_server, {"caFile": pki["other-ca.pem"],
                             "certFile": pki["client.pem"],
                             "keyFile": pki["client.key"]})
    with pytest.raises((ssl.SSLError, urllib.error.URLError)):
        c.filter({"Pod": {}})


def test_server_name_override(pki):
    """A server cert carrying ONLY the DNS name extender.test verifies via
    tlsConfig.serverName when dialed by IP, and fails without it."""
    httpd = http.server.HTTPServer(("127.0.0.1", 0), _Handler)
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    import tempfile

    ca_key, ca_cert = _make_cert("sni-ca", is_ca=True)
    srv_key, srv_cert = _make_cert("extender.test", ca_key, ca_cert,
                                   san_dns=("extender.test",))
    with tempfile.NamedTemporaryFile(suffix=".pem") as cf, \
            tempfile.NamedTemporaryFile(suffix=".pem") as kf:
        cf.write(_pem_cert(srv_cert))
        cf.flush()
        kf.write(_pem_key(srv_key))
        kf.flush()
        ctx.load_cert_chain(cf.name, kf.name)
    httpd.socket = ctx.wrap_socket(httpd.socket, server_side=True)
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    url = f"https://127.0.0.1:{httpd.server_address[1]}"
    ca_b64 = base64.b64encode(_pem_cert(ca_cert)).decode()
    try:
        ok = _client(url, {"caData": ca_b64, "serverName": "extender.test"})
        assert ok.filter({"Pod": {}})["nodenames"] == ["n1"]
        bad = _client(url, {"caData": ca_b64})
        with pytest.raises((ssl.SSLError, urllib.error.URLError)):
            bad.filter({"Pod": {}})
    finally:
        httpd.shutdown()


def test_insecure_skips_verification(pki):
    """insecure: self-signed server, no CA configured — the call succeeds."""
    httpd = http.server.HTTPServer(("127.0.0.1", 0), _Handler)
    key, cert = _make_cert("nobody", san_ip=("127.0.0.1",))
    import tempfile

    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    with tempfile.NamedTemporaryFile(suffix=".pem") as cf, \
            tempfile.NamedTemporaryFile(suffix=".pem") as kf:
        cf.write(_pem_cert(cert))
        cf.flush()
        kf.write(_pem_key(key))
        kf.flush()
        ctx.load_cert_chain(cf.name, kf.name)
    httpd.socket = ctx.wrap_socket(httpd.socket, server_side=True)
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    url = f"https://127.0.0.1:{httpd.server_address[1]}"
    try:
        c = _client(url, {"insecure": True})
        assert c.filter({"Pod": {}})["nodenames"] == ["n1"]
        # enableHTTPS with no CA defaults to insecure (extender.go:66-72)
        c2 = ExtenderClient({"urlPrefix": url, "filterVerb": "filter",
                             "httpTimeout": "3s", "enableHTTPS": True})
        assert c2.filter({"Pod": {}})["nodenames"] == ["n1"]
        # but with a CA the default context verifies (and fails here)
        c3 = _client(url, {"caFile": pki["other-ca.pem"]})
        with pytest.raises((ssl.SSLError, urllib.error.URLError)):
            c3.filter({"Pod": {}})
    finally:
        httpd.shutdown()


def test_insecure_with_ca_rejected():
    with pytest.raises(ValueError):
        ExtenderClient({"urlPrefix": "https://x", "filterVerb": "filter",
                        "tlsConfig": {"insecure": True, "caData": "Zm9v"}})
