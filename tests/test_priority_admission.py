"""Priority admission analogue: .spec.priority resolved from
priorityClassName / globalDefault at pod create, as the reference's
kube-apiserver does before the scheduler ever sees the pod."""

import pytest

from kube_scheduler_simulator_tpu.cluster.store import ApiError, ObjectStore


def _pod(name, **spec):
    return {"kind": "Pod", "metadata": {"name": name},
            "spec": {"containers": [{"name": "c"}], **spec}}


def test_priority_resolved_from_class():
    s = ObjectStore()
    s.create("priorityclasses", {"metadata": {"name": "high"}, "value": 9000})
    p = s.create("pods", _pod("a", priorityClassName="high"))
    assert p["spec"]["priority"] == 9000


def test_explicit_priority_wins():
    s = ObjectStore()
    s.create("priorityclasses", {"metadata": {"name": "high"}, "value": 9000})
    p = s.create("pods", _pod("b", priorityClassName="high", priority=5))
    assert p["spec"]["priority"] == 5


def test_missing_class_rejected():
    s = ObjectStore()
    with pytest.raises(ApiError, match="no PriorityClass"):
        s.create("pods", _pod("c", priorityClassName="nope"))


def test_builtin_classes():
    s = ObjectStore()
    p = s.create("pods", _pod("d", priorityClassName="system-node-critical"))
    assert p["spec"]["priority"] == 2000001000


def test_global_default_applies():
    s = ObjectStore()
    s.create("priorityclasses", {"metadata": {"name": "dflt"}, "value": 7,
                                 "globalDefault": True})
    p = s.create("pods", _pod("e"))
    assert p["spec"]["priority"] == 7
    assert p["spec"]["priorityClassName"] == "dflt"
    # pods created BEFORE any default class exists stay unset
    s2 = ObjectStore()
    p2 = s2.create("pods", _pod("f"))
    assert "priority" not in p2["spec"]


def test_priority_orders_scheduling_queue():
    from kube_scheduler_simulator_tpu.framework.engine import SchedulerEngine
    from kube_scheduler_simulator_tpu.models.workloads import make_nodes

    s = ObjectStore()
    s.create("priorityclasses", {"metadata": {"name": "vip"}, "value": 100})
    # one-cpu node: only the higher-priority pod fits
    s.create("nodes", {"metadata": {"name": "n1"},
                       "status": {"allocatable": {"cpu": "1", "memory": "4Gi",
                                                  "pods": "10"}}})
    s.create("pods", _pod("low", containers=[{  # noqa: PIE804
        "name": "c", "resources": {"requests": {"cpu": "1"}}}]))
    s.create("pods", {"kind": "Pod", "metadata": {"name": "vip-pod"},
                      "spec": {"priorityClassName": "vip", "containers": [
                          {"name": "c", "resources": {"requests": {"cpu": "1"}}}]}})
    engine = SchedulerEngine(s)
    engine.schedule_pending()
    assert s.get("pods", "vip-pod", "default")["spec"].get("nodeName") == "n1"
    assert not s.get("pods", "low", "default")["spec"].get("nodeName")
