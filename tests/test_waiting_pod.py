"""WaitingPod determinism and concurrency (framework/waiting.py).

The permit-result-timeout annotation must be reproducible: timeout
selection is earliest deadline then plugin name, reject() settles the
handle (clears pending deadlines, first rejection wins), and
allow/reject racing from concurrent threads resolves to exactly one
consistent outcome.
"""

from __future__ import annotations

import threading
import time

from kube_scheduler_simulator_tpu.framework.waiting import WaitingPod


def _pod(name="p"):
    return {"metadata": {"name": name, "namespace": "default"}}


def test_timeout_picks_earliest_deadline_then_plugin_name():
    # B's deadline is earliest -> B is the recorded timeout plugin even
    # though A sorts first alphabetically and was inserted first
    wp = WaitingPod(_pod(), {"A": 0.2, "B": 0.01})
    assert wp.wait() == ("B", "timeout")
    # equal deadlines: plugin name breaks the tie deterministically
    wp2 = WaitingPod(_pod(), {"Zeta": 0.0, "Alpha": 0.0})
    assert wp2.wait() == ("Alpha", "timeout")


def test_timeout_settles_the_handle():
    wp = WaitingPod(_pod(), {"A": 0.0})
    first = wp.wait()
    assert first == ("A", "timeout")
    # a second wait (or a racing waiter) sees the SAME resolution, and
    # no pending plugins remain
    assert wp.wait() == first
    assert wp.pending_plugins() == []


def test_reject_clears_deadlines_and_first_rejection_wins():
    wp = WaitingPod(_pod(), {"A": 30.0, "B": 30.0})
    wp.reject("B", "veto")
    assert wp.pending_plugins() == []  # state cleared on reject
    wp.reject("A", "late veto")       # second reject cannot overwrite
    assert wp.wait() == ("B", "veto")


def test_allow_reject_race_resolves_consistently():
    """allow and reject racing from two threads: wait() returns either
    the rejection or None (all allowed), never a torn state, and the
    handle reads settled afterwards."""
    for _ in range(50):
        wp = WaitingPod(_pod(), {"A": 5.0})
        results = []
        barrier = threading.Barrier(3)

        def allower():
            barrier.wait()
            wp.allow("A")

        def rejecter():
            barrier.wait()
            wp.reject("A", "race")

        def waiter():
            barrier.wait()
            results.append(wp.wait())

        threads = [threading.Thread(target=f)
                   for f in (allower, rejecter, waiter)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(5)
        assert results and results[0] in (None, ("A", "race"))
        assert wp.pending_plugins() == []
        # a rejection, once observed, is sticky.  (A reject landing
        # after the waiter already resolved "allowed" is recorded on
        # the handle but moot — the engine pops the pod on resolution.)
        if results[0] == ("A", "race"):
            assert wp.wait() == ("A", "race")
        else:
            assert wp.wait() in (None, ("A", "race"))


def test_concurrent_allows_release_waiter():
    wp = WaitingPod(_pod(), {"A": 5.0, "B": 5.0})
    out = []
    t = threading.Thread(target=lambda: out.append(wp.wait()))
    t.start()
    time.sleep(0.02)
    ta = threading.Thread(target=lambda: wp.allow("A"))
    tb = threading.Thread(target=lambda: wp.allow("B"))
    ta.start()
    tb.start()
    for th in (ta, tb, t):
        th.join(5)
    assert out == [None]
