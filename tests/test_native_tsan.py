"""Concurrent chunk decode under ThreadSanitizer (slow; `make test-tsan`).

The chunk-granular decoder owns real hand-rolled concurrency: a
persistent work-stealing worker pool, per-call output arenas, and
per-thread FilterCaches that survive across chunks.  This runs the
4-thread concurrent-chunk soak from test_chunk_decode.py against a
`-fsanitize=thread` build of the codec in a subprocess, with the TSan
runtime preloaded ahead of an uninstrumented Python.

Two harness accommodations keep the check honest (see
kube_scheduler_simulator_tpu/native/tsan_suppressions.txt):
KSS_TPU_TSAN_LOCALIZE=1 makes the soak copy the replay buffers to
main-thread-owned memory first (preload-TSan cannot see jax's device
sync, so codec reads of XLA-allocated pages would all report), and the
suppressions file silences XLA's own internally-synchronized thread
pool.  Races between codec threads have no frames in either and fail
the subprocess.
"""

import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.slow

_SUPPRESSIONS = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "kube_scheduler_simulator_tpu", "native", "tsan_suppressions.txt")


def _toolchain_lib(name: str) -> str | None:
    try:
        out = subprocess.run(["gcc", f"-print-file-name={name}"],
                             capture_output=True, text=True, timeout=30)
    except (OSError, subprocess.TimeoutExpired):
        return None
    path = (out.stdout or "").strip()
    return path if path and os.path.isabs(path) and os.path.exists(path) else None


def test_chunk_decode_soak_under_tsan(tmp_path):
    from kube_scheduler_simulator_tpu.native import TSAN_FLAGS, build_codec

    libtsan = _toolchain_lib("libtsan.so")
    # libstdc++ must be preloaded too (same reason as the ASan harness):
    # TSan resolves its __cxa_throw interceptor at init, and an
    # uninstrumented Python only maps libstdc++ with the first C++
    # extension — without it jaxlib's first throw aborts the process
    libstdcpp = _toolchain_lib("libstdc++.so.6")
    if libtsan is None or libstdcpp is None:
        pytest.skip("no libtsan/libstdc++ on this toolchain")
    so = str(tmp_path / "_annotation_codec_tsan.so")
    try:
        build_codec(so, extra_flags=TSAN_FLAGS)
    except subprocess.CalledProcessError as e:
        pytest.skip(f"TSan build unavailable: {e.stderr!r:.200}")

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(
        os.environ,
        KSS_TPU_NATIVE_SO=so,
        KSS_TPU_TSAN_LOCALIZE="1",
        LD_PRELOAD=f"{libtsan} {libstdcpp}",
        TSAN_OPTIONS=(
            "halt_on_error=1:report_thread_leaks=0:exitcode=66:"
            f"suppressions={_SUPPRESSIONS}"),
        JAX_PLATFORMS="cpu",
    )
    r = subprocess.run(
        [sys.executable, "-m", "pytest",
         "tests/test_chunk_decode.py::test_chunk_decode_threaded_soak",
         "-q", "-p", "no:cacheprovider"],
        cwd=repo, env=env, capture_output=True, text=True, timeout=1800)
    tail = (r.stdout + "\n" + r.stderr)[-4000:]
    if r.returncode == 66:
        pytest.fail(f"ThreadSanitizer reported a race in the codec:\n{tail}")
    assert r.returncode == 0, f"soak under TSan failed:\n{tail}"
