"""Volume plugin family tests: VolumeBinding, VolumeZone,
VolumeRestrictions, NodeVolumeLimits.

Semantics sources: upstream v1.32 volume plugins, recorded through the
reference shim (reference: simulator/scheduler/plugin/wrappedplugin.go:
491-518 PreFilter status recording, :523-548 Filter recording); annotation
keys reference: simulator/scheduler/plugin/annotation/annotation.go:3-30.
"""

import json

from kube_scheduler_simulator_tpu.framework.replay import replay
from kube_scheduler_simulator_tpu.plugins import (
    nodevolumelimits, volumebinding, volumerestrictions, volumezone,
)
from kube_scheduler_simulator_tpu.plugins.registry import PluginSetConfig
from kube_scheduler_simulator_tpu.reference_impl.sequential import SequentialScheduler
from kube_scheduler_simulator_tpu.state.compile import compile_workload
from kube_scheduler_simulator_tpu.store import annotations as ann
from kube_scheduler_simulator_tpu.store.decode import decode_pod_result


def node(name, labels=None, cpu="8"):
    lab = {"kubernetes.io/hostname": name}
    lab.update(labels or {})
    return {
        "apiVersion": "v1", "kind": "Node",
        "metadata": {"name": name, "labels": lab},
        "spec": {},
        "status": {"allocatable": {"cpu": cpu, "memory": "16Gi", "pods": "110"}},
    }


def pod(name, pvcs=None, volumes=None, node_name=None):
    p = {
        "apiVersion": "v1", "kind": "Pod",
        "metadata": {"name": name, "namespace": "default"},
        "spec": {
            "containers": [{
                "name": "c", "image": "app:v1",
                "resources": {"requests": {"cpu": "100m"}},
            }],
            "volumes": [],
        },
        "status": {},
    }
    for claim in pvcs or []:
        p["spec"]["volumes"].append(
            {"name": f"v-{claim}", "persistentVolumeClaim": {"claimName": claim}}
        )
    p["spec"]["volumes"].extend(volumes or [])
    if node_name:
        p["spec"]["nodeName"] = node_name
        p["status"]["phase"] = "Running"
    return p


def pvc(name, sc=None, volume_name=None, request="1Gi", modes=("ReadWriteOnce",)):
    spec = {
        "accessModes": list(modes),
        "resources": {"requests": {"storage": request}},
    }
    if sc is not None:
        spec["storageClassName"] = sc
    if volume_name:
        spec["volumeName"] = volume_name
    return {
        "apiVersion": "v1", "kind": "PersistentVolumeClaim",
        "metadata": {"name": name, "namespace": "default"},
        "spec": spec,
    }


def pv(name, capacity="1Gi", sc="", modes=("ReadWriteOnce",), labels=None,
       node_affinity_hosts=None, claim_ref=None, csi=None):
    spec = {
        "capacity": {"storage": capacity},
        "accessModes": list(modes),
        "storageClassName": sc,
    }
    if node_affinity_hosts:
        spec["nodeAffinity"] = {"required": {"nodeSelectorTerms": [{
            "matchExpressions": [{
                "key": "kubernetes.io/hostname", "operator": "In",
                "values": list(node_affinity_hosts),
            }],
        }]}}
    if claim_ref:
        spec["claimRef"] = {"namespace": "default", "name": claim_ref}
    if csi:
        spec["csi"] = csi
    return {
        "apiVersion": "v1", "kind": "PersistentVolume",
        "metadata": {"name": name, "labels": labels or {}},
        "spec": spec,
    }


def sc(name, wffc=True, provisioner="ebs.csi.aws.com", topo_zones=None, default=False):
    obj = {
        "apiVersion": "storage.k8s.io/v1", "kind": "StorageClass",
        "metadata": {"name": name, "annotations": {}},
        "provisioner": provisioner,
        "volumeBindingMode": "WaitForFirstConsumer" if wffc else "Immediate",
    }
    if topo_zones:
        obj["allowedTopologies"] = [{"matchLabelExpressions": [{
            "key": "topology.kubernetes.io/zone", "values": list(topo_zones),
        }]}]
    if default:
        obj["metadata"]["annotations"]["storageclass.kubernetes.io/is-default-class"] = "true"
    return obj


VOL_CFG = PluginSetConfig(enabled=[
    "NodeResourcesFit", "VolumeRestrictions", "NodeVolumeLimits",
    "VolumeBinding", "VolumeZone",
])


def parity(nodes, pods, volumes, cfg=None, chunk=4):
    cfg = cfg or VOL_CFG
    seq = SequentialScheduler(nodes, pods, PluginSetConfig(
        enabled=list(cfg.enabled), weights=dict(cfg.weights)), volumes=volumes,
    ).schedule_all()
    rr = replay(compile_workload(nodes, pods, cfg, volumes=volumes), chunk=chunk)
    for i, (sa, _) in enumerate(seq):
        da = decode_pod_result(rr, i)
        for k in sa:
            assert da[k] == sa[k], f"pod {i} key {k}\n dev={da[k]}\n seq={sa[k]}"
    return rr, seq


def filter_entry(annotations, node_name):
    return json.loads(annotations[ann.FILTER_RESULT]).get(node_name, {})


# --------------------------------------------------------------------------
# VolumeZone


def test_volume_zone_conflict_and_skip():
    nodes = [
        node("n-east", {"topology.kubernetes.io/zone": "east"}),
        node("n-west", {"topology.kubernetes.io/zone": "west"}),
    ]
    volumes = {
        "pvcs": [pvc("data", sc="", volume_name="pv-east")],
        "pvs": [pv("pv-east", labels={"topology.kubernetes.io/zone": "east"})],
    }
    pods = [pod("p1", pvcs=["data"]), pod("p2")]
    rr, seq = parity(nodes, pods, volumes)
    a0 = seq[0][0]
    assert filter_entry(a0, "n-west")["VolumeZone"] == volumezone.ERR_VOLUME_ZONE_CONFLICT
    assert filter_entry(a0, "n-east")["VolumeZone"] == ann.PASSED_FILTER_MESSAGE
    # p2 has no PVCs: VolumeZone prefilter Skips ("")
    pf = json.loads(seq[1][0][ann.PRE_FILTER_STATUS_RESULT])
    assert pf["VolumeZone"] == ""
    # comma-separated multi-zone value set passes any listed zone
    volumes2 = {
        "pvcs": [pvc("data", sc="", volume_name="pv-multi")],
        "pvs": [pv("pv-multi", labels={"topology.kubernetes.io/zone": "west, east"})],
    }
    _, seq2 = parity(nodes, [pod("p1", pvcs=["data"])], volumes2)
    a = seq2[0][0]
    assert filter_entry(a, "n-west")["VolumeZone"] == ann.PASSED_FILTER_MESSAGE


# --------------------------------------------------------------------------
# VolumeBinding: bound PVs


def test_bound_pv_node_affinity_conflict():
    nodes = [node("n1"), node("n2")]
    volumes = {
        "pvcs": [pvc("data", sc="", volume_name="pv1")],
        "pvs": [pv("pv1", node_affinity_hosts=["n1"])],
    }
    rr, seq = parity(nodes, [pod("p1", pvcs=["data"])], volumes)
    a = seq[0][0]
    assert filter_entry(a, "n2")["VolumeBinding"] == volumebinding.ERR_NODE_CONFLICT
    assert a[ann.SELECTED_NODE] == "n1"
    # Reserve/PreBind record VolumeBinding success on the happy path
    assert json.loads(a[ann.RESERVE_RESULT]) == {"VolumeBinding": "success"}
    assert json.loads(a[ann.PRE_BIND_RESULT]) == {"VolumeBinding": "success"}


def test_bound_pvc_missing_pv():
    nodes = [node("n1")]
    volumes = {"pvcs": [pvc("data", sc="", volume_name="ghost")], "pvs": []}
    rr, seq = parity(nodes, [pod("p1", pvcs=["data"])], volumes)
    a = seq[0][0]
    assert filter_entry(a, "n1")["VolumeBinding"] == volumebinding.ERR_PV_NOT_EXIST
    assert a[ann.SELECTED_NODE] == ""


# --------------------------------------------------------------------------
# VolumeBinding: unbound WFFC claims, greedy claiming across the queue


def test_wffc_static_binding_claims_smallest_pv_and_is_consumed():
    nodes = [node("n1"), node("n2")]
    volumes = {
        "pvcs": [pvc("c1", sc="wffc-sc"), pvc("c2", sc="wffc-sc")],
        "pvs": [
            pv("pv-big", capacity="10Gi", sc="wffc-sc"),
            pv("pv-small", capacity="2Gi", sc="wffc-sc"),
        ],
        "storageclasses": [sc("wffc-sc", wffc=True, provisioner="kubernetes.io/no-provisioner")],
    }
    pods = [pod("p1", pvcs=["c1"]), pod("p2", pvcs=["c2"]), ]
    rr, seq = parity(nodes, pods, volumes)
    # both bind (greedy: p1 takes pv-small, p2 takes pv-big)
    assert seq[0][0][ann.SELECTED_NODE] != ""
    assert seq[1][0][ann.SELECTED_NODE] != ""
    # a third claimant finds no PV left and no provisioner
    pods3 = pods + [pod("p3", pvcs=["c3"])]
    volumes3 = dict(volumes)
    volumes3["pvcs"] = volumes["pvcs"] + [pvc("c3", sc="wffc-sc")]
    rr3, seq3 = parity(nodes, pods3, volumes3)
    a3 = seq3[2][0]
    assert filter_entry(a3, "n1")["VolumeBinding"] == volumebinding.ERR_BIND_CONFLICT
    assert a3[ann.SELECTED_NODE] == ""


def test_wffc_pv_node_affinity_restricts_placement():
    nodes = [node("n1"), node("n2")]
    volumes = {
        "pvcs": [pvc("c1", sc="local-sc")],
        "pvs": [pv("pv-n2", sc="local-sc", node_affinity_hosts=["n2"])],
        "storageclasses": [sc("local-sc", wffc=True, provisioner="kubernetes.io/no-provisioner")],
    }
    rr, seq = parity(nodes, [pod("p1", pvcs=["c1"])], volumes)
    a = seq[0][0]
    assert filter_entry(a, "n1")["VolumeBinding"] == volumebinding.ERR_BIND_CONFLICT
    assert a[ann.SELECTED_NODE] == "n2"


def test_wffc_dynamic_provisioning_allowed_topologies():
    nodes = [
        node("n-east", {"topology.kubernetes.io/zone": "east"}),
        node("n-west", {"topology.kubernetes.io/zone": "west"}),
    ]
    volumes = {
        "pvcs": [pvc("c1", sc="prov-sc")],
        "pvs": [],
        "storageclasses": [sc("prov-sc", wffc=True, topo_zones=["east"])],
    }
    rr, seq = parity(nodes, [pod("p1", pvcs=["c1"])], volumes)
    a = seq[0][0]
    assert filter_entry(a, "n-west")["VolumeBinding"] == volumebinding.ERR_BIND_CONFLICT
    assert a[ann.SELECTED_NODE] == "n-east"


def test_prebound_pv_claimref_matches_only_its_claim():
    nodes = [node("n1")]
    volumes = {
        "pvcs": [pvc("mine", sc="wffc-sc"), pvc("other", sc="wffc-sc")],
        "pvs": [pv("pv1", sc="wffc-sc", claim_ref="mine")],
        "storageclasses": [sc("wffc-sc", wffc=True, provisioner="kubernetes.io/no-provisioner")],
    }
    # claimRef'd PVs are pre-claimed (claimed0): "other" cannot take pv1
    rr, seq = parity(nodes, [pod("p-other", pvcs=["other"])], volumes)
    a = seq[0][0]
    assert filter_entry(a, "n1")["VolumeBinding"] == volumebinding.ERR_BIND_CONFLICT


# --------------------------------------------------------------------------
# PreFilter rejects


def test_unbound_immediate_pvc_rejects_at_prefilter():
    nodes = [node("n1")]
    volumes = {
        "pvcs": [pvc("c1", sc="imm-sc")],
        "storageclasses": [sc("imm-sc", wffc=False)],
    }
    rr, seq = parity(nodes, [pod("p1", pvcs=["c1"])], volumes)
    a = seq[0][0]
    pf = json.loads(a[ann.PRE_FILTER_STATUS_RESULT])
    assert pf["VolumeBinding"] == volumebinding.ERR_UNBOUND_IMMEDIATE
    # cycle aborted: no filter/score/bind results, no entries after the
    # rejecting plugin
    assert json.loads(a[ann.FILTER_RESULT]) == {}
    assert json.loads(a[ann.BIND_RESULT]) == {}
    assert a[ann.SELECTED_NODE] == ""


def test_missing_pvc_rejects_at_volumerestrictions():
    nodes = [node("n1")]
    rr, seq = parity(nodes, [pod("p1", pvcs=["ghost"])], {"pvcs": []})
    a = seq[0][0]
    pf = json.loads(a[ann.PRE_FILTER_STATUS_RESULT])
    # VolumeRestrictions' PreFilter does the PVC lister lookup first
    assert pf["VolumeRestrictions"] == 'persistentvolumeclaim "ghost" not found'
    assert "VolumeBinding" not in pf


def test_rwop_conflict_is_dynamic_across_the_queue():
    nodes = [node("n1"), node("n2")]
    volumes = {
        "pvcs": [pvc("exclusive", sc="", volume_name="pv1", modes=("ReadWriteOncePod",))],
        "pvs": [pv("pv1", modes=("ReadWriteOncePod",), claim_ref="exclusive")],
    }
    pods = [pod("p1", pvcs=["exclusive"]), pod("p2", pvcs=["exclusive"])]
    rr, seq = parity(nodes, pods, volumes)
    assert seq[0][0][ann.SELECTED_NODE] != ""
    a2 = seq[1][0]
    pf = json.loads(a2[ann.PRE_FILTER_STATUS_RESULT])
    assert pf["VolumeRestrictions"] == volumerestrictions.ERR_RWOP_CONFLICT
    assert a2[ann.SELECTED_NODE] == ""
    assert int(rr.prefilter_reject[1]) & 1


# --------------------------------------------------------------------------
# VolumeRestrictions: inline disks


def test_inline_gce_disk_conflict_readonly_exemption():
    nodes = [node("n1")]
    gce_rw = {"name": "d", "gcePersistentDisk": {"pdName": "disk-1"}}
    gce_ro = {"name": "d", "gcePersistentDisk": {"pdName": "disk-1", "readOnly": True}}
    # writer on node, second writer conflicts
    pods = [pod("p1", volumes=[gce_rw]), pod("p2", volumes=[gce_rw])]
    rr, seq = parity(nodes, pods, {})
    a2 = seq[1][0]
    assert filter_entry(a2, "n1")["VolumeRestrictions"] == volumerestrictions.ERR_DISK_CONFLICT
    # both read-only: no conflict
    pods_ro = [pod("p1", volumes=[gce_ro]), pod("p2", volumes=[gce_ro])]
    rr2, seq2 = parity(nodes, pods_ro, {})
    assert seq2[1][0][ann.SELECTED_NODE] == "n1"
    # AWS EBS conflicts even read-only vs read-only
    ebs_ro = {"name": "d", "awsElasticBlockStore": {"volumeID": "vol-1", "readOnly": True}}
    pods_ebs = [pod("p1", volumes=[ebs_ro]), pod("p2", volumes=[ebs_ro])]
    rr3, seq3 = parity(nodes, pods_ebs, {})
    assert (
        filter_entry(seq3[1][0], "n1")["VolumeRestrictions"]
        == volumerestrictions.ERR_DISK_CONFLICT
    )


# --------------------------------------------------------------------------
# NodeVolumeLimits


def test_csi_volume_limits():
    nodes = [node("n1"), node("n2")]
    csinode = {
        "apiVersion": "storage.k8s.io/v1", "kind": "CSINode",
        "metadata": {"name": "n1"},
        "spec": {"drivers": [{"name": "ebs.csi.aws.com", "allocatable": {"count": 1}}]},
    }
    volumes = {
        "pvcs": [
            pvc("c1", sc="", volume_name="pv1"),
            pvc("c2", sc="", volume_name="pv2"),
        ],
        "pvs": [
            pv("pv1", claim_ref="c1", csi={"driver": "ebs.csi.aws.com", "volumeHandle": "h1"}),
            pv("pv2", claim_ref="c2", csi={"driver": "ebs.csi.aws.com", "volumeHandle": "h2"}),
        ],
        "csinodes": [csinode],
    }
    pods = [pod("p1", pvcs=["c1"]), pod("p2", pvcs=["c2"])]
    rr, seq = parity(nodes, pods, volumes)
    # p1 takes n1 or n2; p2 must avoid whichever holds a volume if limit 1
    a1, a2 = seq[0][0], seq[1][0]
    assert a1[ann.SELECTED_NODE] != ""
    assert a2[ann.SELECTED_NODE] != ""
    if a1[ann.SELECTED_NODE] == "n1":
        assert (
            filter_entry(a2, "n1").get("NodeVolumeLimits")
            == nodevolumelimits.ERR_MAX_VOLUME_COUNT
        )
        assert a2[ann.SELECTED_NODE] == "n2"
    # n2 has no CSINode: never limited
    assert "NodeVolumeLimits" not in filter_entry(a1, "n2") or \
        filter_entry(a1, "n2")["NodeVolumeLimits"] == ann.PASSED_FILTER_MESSAGE


def test_same_volume_shared_counts_once():
    nodes = [node("n1")]
    csinode = {
        "apiVersion": "storage.k8s.io/v1", "kind": "CSINode",
        "metadata": {"name": "n1"},
        "spec": {"drivers": [{"name": "ebs.csi.aws.com", "allocatable": {"count": 1}}]},
    }
    volumes = {
        "pvcs": [pvc("shared", sc="", volume_name="pv1", modes=("ReadWriteMany",))],
        "pvs": [pv("pv1", modes=("ReadWriteMany",), claim_ref="shared",
                   csi={"driver": "ebs.csi.aws.com", "volumeHandle": "h1"})],
        "csinodes": [csinode],
    }
    pods = [pod("p1", pvcs=["shared"]), pod("p2", pvcs=["shared"])]
    rr, seq = parity(nodes, pods, volumes)
    # the same volume on the node counts once: p2 still fits
    assert seq[0][0][ann.SELECTED_NODE] == "n1"
    assert seq[1][0][ann.SELECTED_NODE] == "n1"


# --------------------------------------------------------------------------
# default StorageClass resolution + full-default-config parity


def test_bound_pod_wffc_claims_survive_recompile():
    """A pod bound in an earlier wave re-claims its greedy PV choice when
    the workload recompiles (prime_claims), so a later pod can't take it."""
    nodes = [node("n1")]
    volumes = {
        "pvcs": [pvc("c1", sc="wffc-sc"), pvc("c2", sc="wffc-sc")],
        "pvs": [pv("pv-only", sc="wffc-sc")],
        "storageclasses": [sc("wffc-sc", wffc=True, provisioner="kubernetes.io/no-provisioner")],
    }
    bound = [(pod("p1", pvcs=["c1"], node_name="n1"), "n1")]
    pods = [pod("p2", pvcs=["c2"])]
    seq = SequentialScheduler(
        nodes, pods, PluginSetConfig(enabled=list(VOL_CFG.enabled)),
        bound_pods=bound, volumes=volumes,
    ).schedule_all()
    rr = replay(
        compile_workload(nodes, pods, VOL_CFG, bound_pods=bound, volumes=volumes),
        chunk=1,
    )
    a = seq[0][0]
    assert filter_entry(a, "n1")["VolumeBinding"] == volumebinding.ERR_BIND_CONFLICT
    assert a[ann.SELECTED_NODE] == ""
    da = decode_pod_result(rr, 0)
    assert da[ann.FILTER_RESULT] == a[ann.FILTER_RESULT]
    assert int(rr.selected[0]) == -1


def test_csi_limit_overfull_node_accepts_no_new_volume_pods():
    """A node already over its CSINode limit still accepts pods that add
    no new volume for that driver (upstream checks newVolumes only)."""
    nodes = [node("n1")]
    csinode = {
        "apiVersion": "storage.k8s.io/v1", "kind": "CSINode",
        "metadata": {"name": "n1"},
        "spec": {"drivers": [{"name": "ebs.csi.aws.com", "allocatable": {"count": 1}}]},
    }
    volumes = {
        "pvcs": [
            pvc("a", sc="", volume_name="pv-a"),
            pvc("b", sc="", volume_name="pv-b"),
            pvc("shared", sc="", volume_name="pv-a", modes=("ReadWriteMany",)),
        ],
        "pvs": [
            pv("pv-a", modes=("ReadWriteMany",),
               csi={"driver": "ebs.csi.aws.com", "volumeHandle": "h-a"}),
            pv("pv-b", csi={"driver": "ebs.csi.aws.com", "volumeHandle": "h-b"}),
        ],
        "csinodes": [csinode],
    }
    # two bound pods put the node at 2 volumes > limit 1 (bound pods bypass
    # filters); a pod reusing volume h-a adds nothing new and still fits
    bound = [
        (pod("pa", pvcs=["a"], node_name="n1"), "n1"),
        (pod("pb", pvcs=["b"], node_name="n1"), "n1"),
    ]
    pods = [pod("p-reuse", pvcs=["shared"])]
    seq = SequentialScheduler(
        nodes, pods, PluginSetConfig(enabled=list(VOL_CFG.enabled)),
        bound_pods=bound, volumes=volumes,
    ).schedule_all()
    rr = replay(
        compile_workload(nodes, pods, VOL_CFG, bound_pods=bound, volumes=volumes),
        chunk=1,
    )
    assert seq[0][0][ann.SELECTED_NODE] == "n1"
    assert int(rr.selected[0]) == 0


def test_default_storageclass_applies_to_nil_class_pvc():
    nodes = [node("n1")]
    volumes = {
        "pvcs": [pvc("c1")],  # no storageClassName
        "storageclasses": [sc("the-default", wffc=True, default=True)],
    }
    rr, seq = parity(nodes, [pod("p1", pvcs=["c1"])], volumes)
    # default class is WFFC with a provisioner: pod schedules via provisioning
    assert seq[0][0][ann.SELECTED_NODE] == "n1"


def test_volume_plugins_in_default_config_parity():
    """Full default plugin set over a mixed volume workload."""
    nodes = [
        node("n1", {"topology.kubernetes.io/zone": "east"}),
        node("n2", {"topology.kubernetes.io/zone": "west"}),
        node("n3", {"topology.kubernetes.io/zone": "east"}),
    ]
    volumes = {
        "pvcs": [
            pvc("bound-east", sc="", volume_name="pv-east"),
            pvc("wffc-1", sc="wffc-sc"),
            pvc("wffc-2", sc="wffc-sc"),
        ],
        "pvs": [
            pv("pv-east", labels={"topology.kubernetes.io/zone": "east"},
               node_affinity_hosts=["n1", "n3"], claim_ref="bound-east"),
            pv("pv-free", sc="wffc-sc", capacity="5Gi"),
        ],
        "storageclasses": [sc("wffc-sc", wffc=True, provisioner="kubernetes.io/no-provisioner")],
    }
    pods = [
        pod("p-zone", pvcs=["bound-east"]),
        pod("p-w1", pvcs=["wffc-1"]),
        pod("p-w2", pvcs=["wffc-2"]),
        pod("p-plain"),
    ]
    parity(nodes, pods, volumes, cfg=PluginSetConfig(), chunk=2)
