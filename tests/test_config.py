"""Simulator-config tables: yaml fields, env-over-yaml precedence, and
the feature-exclusivity rule (reference: simulator/config/config.go —
env overrides per field at :148-159, exclusivity at :94-96, initial
scheduler config load at :232-257)."""

import pytest
import yaml

from kube_scheduler_simulator_tpu.config.config import (
    SimulatorConfiguration,
    load_config,
)


@pytest.fixture
def clean_env(monkeypatch):
    for var in ("PORT", "KUBE_APISERVER_URL", "KUBE_SCHEDULER_SIMULATOR_ETCD_URL",
                "CORS_ALLOWED_ORIGIN_LIST", "KUBE_SCHEDULER_CONFIG_PATH",
                "EXTERNAL_IMPORT_ENABLED", "RESOURCE_SYNC_ENABLED",
                "REPLAYER_ENABLED", "RECORD_FILE_PATH",
                "EXTERNAL_SCHEDULER_ENABLED"):
        monkeypatch.delenv(var, raising=False)
    return monkeypatch


def _write(tmp_path, data):
    p = tmp_path / "config.yaml"
    p.write_text(yaml.safe_dump(data))
    return str(p)


def test_defaults_without_file(clean_env, tmp_path):
    cfg = load_config(str(tmp_path / "missing.yaml"))
    assert cfg.port == 1212
    assert not cfg.external_import_enabled
    assert not cfg.resource_sync_enabled
    assert not cfg.replayer_enabled
    assert cfg.cors_allowed_origin_list == []


def test_yaml_fields_load(clean_env, tmp_path):
    cfg = load_config(_write(tmp_path, {
        "port": 4000,
        "etcdURL": "http://etcd:2379",
        "kubeApiServerUrl": "http://api:3131",
        "corsAllowedOriginList": ["http://a", "http://b"],
        "kubeSchedulerConfigPath": "/tmp/sched.yaml",
        "recordFilePath": "/tmp/rec.jsonl",
        "externalSchedulerEnabled": True,
    }))
    assert cfg.port == 4000
    assert cfg.etcd_url == "http://etcd:2379"
    assert cfg.kube_api_server_url == "http://api:3131"
    assert cfg.cors_allowed_origin_list == ["http://a", "http://b"]
    assert cfg.kube_scheduler_config_path == "/tmp/sched.yaml"
    assert cfg.record_file_path == "/tmp/rec.jsonl"
    assert cfg.external_scheduler_enabled


def test_env_overrides_yaml(clean_env, tmp_path):
    clean_env.setenv("PORT", "5555")
    clean_env.setenv("CORS_ALLOWED_ORIGIN_LIST", "http://x,http://y")
    clean_env.setenv("RECORD_FILE_PATH", "/env/rec.jsonl")
    clean_env.setenv("REPLAYER_ENABLED", "true")
    cfg = load_config(_write(tmp_path, {
        "port": 4000,
        "corsAllowedOriginList": ["http://a"],
        "recordFilePath": "/yaml/rec.jsonl",
    }))
    assert cfg.port == 5555
    assert cfg.cors_allowed_origin_list == ["http://x", "http://y"]
    assert cfg.record_file_path == "/env/rec.jsonl"
    assert cfg.replayer_enabled


def test_env_bool_accepts_go_style_values(clean_env, tmp_path):
    for v, want in [("1", True), ("true", True), ("TRUE", True),
                    ("yes", True), ("0", False), ("false", False), ("", False)]:
        clean_env.setenv("EXTERNAL_IMPORT_ENABLED", v)
        cfg = load_config(str(tmp_path / "missing.yaml"))
        assert cfg.external_import_enabled is want, v


def test_env_false_overrides_yaml_true(clean_env, tmp_path):
    clean_env.setenv("RESOURCE_SYNC_ENABLED", "false")
    cfg = load_config(_write(tmp_path, {"resourceSyncEnabled": True}))
    assert not cfg.resource_sync_enabled


@pytest.mark.parametrize("pair", [
    {"externalImportEnabled": True, "resourceSyncEnabled": True},
    {"externalImportEnabled": True, "replayEnabled": True},
    {"resourceSyncEnabled": True, "replayEnabled": True},
])
def test_import_sync_replay_mutually_exclusive(clean_env, tmp_path, pair):
    with pytest.raises(ValueError, match="simultaneous"):
        load_config(_write(tmp_path, pair))


def test_replay_enabled_accepts_both_yaml_keys(clean_env, tmp_path):
    assert load_config(_write(tmp_path, {"replayEnabled": True})).replayer_enabled
    assert load_config(_write(tmp_path, {"replayerEnabled": True})).replayer_enabled


def test_initial_scheduler_config_loads_yaml(clean_env, tmp_path):
    sched = tmp_path / "sched.yaml"
    sched.write_text(yaml.safe_dump({
        "kind": "KubeSchedulerConfiguration",
        "profiles": [{"schedulerName": "my-scheduler"}],
    }))
    cfg = SimulatorConfiguration(kube_scheduler_config_path=str(sched))
    loaded = cfg.initial_scheduler_config()
    assert loaded["profiles"][0]["schedulerName"] == "my-scheduler"
    assert SimulatorConfiguration().initial_scheduler_config() is None
