"""Test env: force CPU with 8 virtual devices BEFORE jax initialises.

Multi-chip sharding tests run on a virtual 8-device CPU mesh (the driver
separately dry-runs the multi-chip path; real TPU is reserved for bench).
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from kube_scheduler_simulator_tpu.utils.platform import force_cpu  # noqa: E402

force_cpu(n_virtual_devices=8)


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: long-running / tooling-heavy tests (excluded from tier-1, "
        "which runs -m 'not slow'); e.g. the codec-suite-under-ASan run")
