"""Test env: force CPU with 8 virtual devices BEFORE jax initialises.

Multi-chip sharding tests run on a virtual 8-device CPU mesh (the driver
separately dry-runs the multi-chip path; real TPU is reserved for bench).
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from kube_scheduler_simulator_tpu.utils.platform import force_cpu  # noqa: E402

force_cpu(n_virtual_devices=8)

# lock-witness mode (docs/static-analysis.md): KSS_TPU_LOCK_WITNESS=1
# wraps every lock created from here on in the lockdep-style witness;
# the concurrency/engine soak modules then FAIL on any acquisition-order
# cycle, even when the interleaving didn't actually deadlock.  Installed
# before any test module imports so engines/stores built inside tests
# get witnessed locks.
_WITNESS = None
if os.environ.get("KSS_TPU_LOCK_WITNESS") == "1":
    from tools.analysis import lockwitness  # noqa: E402

    _WITNESS = lockwitness.install()

_WITNESS_MODULES = {"test_concurrency_soak", "test_engine_soak"}


def pytest_runtest_teardown(item):
    if _WITNESS is None:
        return
    mod = getattr(item, "module", None)
    name = getattr(mod, "__name__", "").rpartition(".")[2]
    if name in _WITNESS_MODULES:
        _WITNESS.assert_no_cycles()


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: long-running / tooling-heavy tests (excluded from tier-1, "
        "which runs -m 'not slow'); e.g. the codec-suite-under-ASan run")
