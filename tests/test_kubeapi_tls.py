"""KubeAPICluster kubeconfig TLS against a live mTLS apiserver stand-in.

The reference's import/sync/record sources authenticate through
client-go's kubeconfig machinery (cluster CA, client certificates,
insecure-skip-tls-verify — simulator/docs/import-cluster-resources.md);
here the same kubeconfig fields drive a real TLS handshake: an HTTPS
server requiring client certificates serves /api/v1/nodes, and
cluster/kubeapi.load_kubeconfig must produce an SSL context that (a)
verifies the server against inline CA data, (b) presents the inline
client cert/key, and (c) never leaves the decoded key material on disk.
"""

from __future__ import annotations

import base64
import http.server
import json
import ssl
import tempfile
import threading

import pytest

try:
    from test_extender_tls import _make_cert, _pem_cert, _pem_key
except ImportError:  # pragma: no cover
    pytest.skip("cryptography unavailable", allow_module_level=True)

from kube_scheduler_simulator_tpu.cluster.kubeapi import KubeAPICluster


def _b64(b: bytes) -> str:
    return base64.b64encode(b).decode()


class _APIServer(http.server.BaseHTTPRequestHandler):
    def do_GET(self):
        body = json.dumps({
            "kind": "NodeList", "apiVersion": "v1",
            "metadata": {"resourceVersion": "77"},
            "items": [{"metadata": {"name": "tls-node",
                                    "resourceVersion": "42"}}],
        }).encode()
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *a):
        pass


@pytest.fixture(scope="module")
def mtls_server(tmp_path_factory):
    d = tmp_path_factory.mktemp("kubeapi-pki")
    ca_key, ca_cert = _make_cert("kube-ca", is_ca=True)
    srv_key, srv_cert = _make_cert("kubeapi.test", ca_key, ca_cert,
                                   san_dns=("localhost",),
                                   san_ip=("127.0.0.1",))
    cli_key, cli_cert = _make_cert("kube-client", ca_key, ca_cert)
    paths = {}
    for name, data in (("ca.pem", _pem_cert(ca_cert)),
                       ("server.pem", _pem_cert(srv_cert)),
                       ("server.key", _pem_key(srv_key))):
        (d / name).write_bytes(data)
        paths[name] = str(d / name)

    sslctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    sslctx.load_cert_chain(paths["server.pem"], paths["server.key"])
    sslctx.load_verify_locations(paths["ca.pem"])
    sslctx.verify_mode = ssl.CERT_REQUIRED  # mTLS: client cert mandatory

    httpd = http.server.ThreadingHTTPServer(("127.0.0.1", 0), _APIServer)
    httpd.socket = sslctx.wrap_socket(httpd.socket, server_side=True)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    yield {
        "url": f"https://127.0.0.1:{httpd.server_address[1]}",
        "ca": _pem_cert(ca_cert),
        "client_cert": _pem_cert(cli_cert),
        "client_key": _pem_key(cli_key),
    }
    httpd.shutdown()
    httpd.server_close()


def _kubeconfig(tmp_path, server, **user):
    kc = {
        "current-context": "t",
        "contexts": [{"name": "t", "context": {"cluster": "c", "user": "u"}}],
        "clusters": [{"name": "c", "cluster": server}],
        "users": [{"name": "u", "user": user}],
    }
    p = tmp_path / "kubeconfig.yaml"
    p.write_text(json.dumps(kc))
    return str(p)


def test_mtls_roundtrip_with_inline_data(mtls_server, tmp_path, monkeypatch):
    kc = _kubeconfig(
        tmp_path,
        {"server": mtls_server["url"],
         "certificate-authority-data": _b64(mtls_server["ca"])},
        **{"client-certificate-data": _b64(mtls_server["client_cert"]),
           "client-key-data": _b64(mtls_server["client_key"])},
    )
    # private tempdir: the no-key-material-left-behind assertion must not
    # race other processes' /tmp churn
    leakdir = tmp_path / "leakcheck"
    leakdir.mkdir()
    monkeypatch.setattr(tempfile, "tempdir", str(leakdir))
    c = KubeAPICluster(kubeconfig=kc)
    # a full verified+client-authenticated list over the wire
    items, rv = c.list("nodes")
    assert [o["metadata"]["name"] for o in items] == ["tls-node"]
    assert rv == 77
    # inline cert/key temp files were unlinked as soon as ssl loaded them
    assert list(leakdir.iterdir()) == []


def test_mtls_rejects_client_without_cert(mtls_server, tmp_path):
    kc = _kubeconfig(
        tmp_path,
        {"server": mtls_server["url"],
         "certificate-authority-data": _b64(mtls_server["ca"])},
    )
    c = KubeAPICluster(kubeconfig=kc)
    with pytest.raises(OSError):  # TLS alert: certificate required
        c.list("nodes")


def test_server_cert_rejected_without_ca(mtls_server, tmp_path):
    kc = _kubeconfig(
        tmp_path,
        {"server": mtls_server["url"]},  # default trust store: unknown CA
        **{"client-certificate-data": _b64(mtls_server["client_cert"]),
           "client-key-data": _b64(mtls_server["client_key"])},
    )
    c = KubeAPICluster(kubeconfig=kc)
    with pytest.raises(OSError):
        c.list("nodes")


def test_insecure_skip_verify_accepts_unknown_ca(mtls_server, tmp_path):
    kc = _kubeconfig(
        tmp_path,
        {"server": mtls_server["url"], "insecure-skip-tls-verify": True},
        **{"client-certificate-data": _b64(mtls_server["client_cert"]),
           "client-key-data": _b64(mtls_server["client_key"])},
    )
    c = KubeAPICluster(kubeconfig=kc)
    items, _ = c.list("nodes")
    assert items[0]["metadata"]["name"] == "tls-node"
