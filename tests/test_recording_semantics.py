"""Per-extension-point recording semantics through the engine — the
behaviors the reference pins in wrappedplugin_test.go (2k LoC) plus the
upstream scheduleOne fast paths that shape what gets recorded:

* Filter stops at the first failing plugin per node: the failure message
  is recorded, earlier plugins record "passed", later plugins record
  NOTHING for that node (upstream RunFilterPlugins early-return).
* Scoring is skipped entirely when <=1 node is feasible (upstream
  schedulePod early-returns before PreScore/Score); selected-node is
  still set and the pod still binds.
* A PreFilter Skip records "" (the Skip status has an empty message,
  wrappedplugin.go:507-516) and suppresses that plugin's Filter on every
  node.
"""

import json

from kube_scheduler_simulator_tpu.cluster.store import ObjectStore
from kube_scheduler_simulator_tpu.framework.engine import SchedulerEngine
from kube_scheduler_simulator_tpu.plugins.registry import PluginSetConfig
from kube_scheduler_simulator_tpu.store import annotations as ann


def _run(nodes, pods, enabled):
    store = ObjectStore()
    for n in nodes:
        store.create("nodes", n)
    engine = SchedulerEngine(store)
    engine.set_plugin_config(PluginSetConfig(enabled=enabled))
    for p in pods:
        store.create("pods", p)
    engine.schedule_pending()
    return {p["metadata"]["name"]: p["metadata"].get("annotations", {})
            for p in store.list("pods")[0]}


def _node(name, cpu="4", taints=None, labels=None):
    n = {"metadata": {"name": name},
         "status": {"allocatable": {"cpu": cpu, "memory": "8Gi",
                                    "pods": "110"}}}
    if taints:
        n["spec"] = {"taints": taints}
    if labels:
        n["metadata"]["labels"] = labels
    return n


def test_filter_stops_at_first_failing_plugin_per_node():
    """A node failing TaintToleration (earlier in the filter order) must
    not carry a NodeResourcesFit entry at all — the framework never ran
    it there — while a node failing only NodeResourcesFit records
    TaintToleration "passed" first."""
    anns = _run(
        nodes=[
            _node("n-tainted", taints=[{"key": "k", "value": "v",
                                        "effect": "NoSchedule"}]),
            _node("n-small", cpu="1"),
            _node("n-good"),
        ],
        pods=[{"metadata": {"name": "p"},
               "spec": {"containers": [{"name": "c", "resources": {
                   "requests": {"cpu": "2"}}}]}}],
        enabled=["TaintToleration", "NodeResourcesFit"],
    )
    fr = json.loads(anns["p"][ann.FILTER_RESULT])
    assert fr["n-tainted"] == {
        "TaintToleration": "node(s) had untolerated taint {k: v}"}
    assert fr["n-small"] == {"TaintToleration": "passed",
                             "NodeResourcesFit": "Insufficient cpu"}
    assert fr["n-good"] == {"TaintToleration": "passed",
                            "NodeResourcesFit": "passed"}
    assert anns["p"][ann.SELECTED_NODE] == "n-good"


def test_single_feasible_node_skips_scoring_entirely():
    """feasibleNodes == 1 -> upstream returns before PreScore/Score: no
    score/prescore/finalscore records, but the pod binds and
    selected-node is set."""
    anns = _run(
        nodes=[_node("only")],
        pods=[{"metadata": {"name": "p"},
               "spec": {"containers": [{"name": "c"}]}}],
        enabled=["NodeResourcesFit", "NodeResourcesBalancedAllocation"],
    )
    a = anns["p"]
    assert a[ann.SCORE_RESULT] == "{}"
    assert a[ann.FINAL_SCORE_RESULT] == "{}"
    assert a[ann.PRE_SCORE_RESULT] == "{}"
    assert a[ann.SELECTED_NODE] == "only"
    assert json.loads(a[ann.BIND_RESULT]) == {"DefaultBinder": "success"}


def test_prefilter_skip_records_empty_and_suppresses_filter():
    """NodeAffinity with no required affinity returns Skip from PreFilter:
    prefilter-result-status records "" and no node carries a NodeAffinity
    filter entry (the framework skips the plugin's Filter)."""
    anns = _run(
        nodes=[_node("a"), _node("b")],
        pods=[{"metadata": {"name": "p"},
               "spec": {"containers": [{"name": "c"}]}}],
        enabled=["NodeAffinity", "NodeResourcesFit"],
    )
    a = anns["p"]
    pf = json.loads(a[ann.PRE_FILTER_STATUS_RESULT])
    assert pf["NodeAffinity"] == ""
    assert pf["NodeResourcesFit"] == "success"
    fr = json.loads(a[ann.FILTER_RESULT])
    for node_entry in fr.values():
        assert "NodeAffinity" not in node_entry
        assert node_entry["NodeResourcesFit"] == "passed"


def test_prescore_skip_records_empty_and_suppresses_score():
    """TaintToleration PreScore with nothing to score (no preferred
    taints anywhere): upstream still scores (count 0); but NodeAffinity
    with no preferred terms SKIPS PreScore -> "" recorded, no score rows."""
    anns = _run(
        nodes=[_node("a"), _node("b")],
        pods=[{"metadata": {"name": "p"},
               "spec": {"containers": [{"name": "c"}]}}],
        enabled=["NodeAffinity", "NodeResourcesFit"],
    )
    a = anns["p"]
    ps = json.loads(a[ann.PRE_SCORE_RESULT])
    assert ps.get("NodeAffinity") == ""
    sr = json.loads(a[ann.SCORE_RESULT])
    for node_entry in sr.values():
        assert "NodeAffinity" not in node_entry
        assert "NodeResourcesFit" in node_entry


def test_all_nodes_infeasible_records_empty_selected_and_no_scores():
    anns = _run(
        nodes=[_node("small", cpu="1")],
        pods=[{"metadata": {"name": "p"},
               "spec": {"containers": [{"name": "c", "resources": {
                   "requests": {"cpu": "8"}}}]}}],
        enabled=["NodeResourcesFit"],
    )
    a = anns["p"]
    assert a[ann.SELECTED_NODE] == ""
    assert a[ann.SCORE_RESULT] == "{}"
    assert json.loads(a[ann.FILTER_RESULT])["small"] == {
        "NodeResourcesFit": "Insufficient cpu"}
    assert a[ann.BIND_RESULT] == "{}"


def test_records_merge_into_result_history_per_cycle():
    """Each completed cycle appends one record to result-history; an
    unschedulable attempt records too (the reflector runs on every
    cycle, storereflector.go:87-161)."""
    store = ObjectStore()
    store.create("nodes", _node("n", cpu="2"))
    engine = SchedulerEngine(store)
    engine.set_plugin_config(PluginSetConfig(enabled=["NodeResourcesFit"]))
    store.create("pods", {"metadata": {"name": "p"},
                          "spec": {"containers": [{"name": "c", "resources": {
                              "requests": {"cpu": "4"}}}]}})
    engine.schedule_pending()  # infeasible
    a1 = store.get("pods", "p", "default")["metadata"]["annotations"]
    h1 = json.loads(a1[ann.RESULT_HISTORY])
    assert len(h1) == 1 and h1[0][ann.SELECTED_NODE] == ""
    # grow the node so a second cycle succeeds
    n = store.get("nodes", "n")
    n["status"]["allocatable"]["cpu"] = "8"
    store.update("nodes", n)
    engine.schedule_pending()
    a2 = store.get("pods", "p", "default")["metadata"]["annotations"]
    h2 = json.loads(a2[ann.RESULT_HISTORY])
    assert len(h2) == 2
    assert h2[0][ann.SELECTED_NODE] == "" and h2[1][ann.SELECTED_NODE] == "n"
    assert a2[ann.SELECTED_NODE] == "n"
