"""DefaultPreemption (PostFilter) tests.

Modeled on upstream defaultpreemption table tests as recorded by the
reference (reference: simulator/scheduler/plugin/wrappedplugin.go:550-583
PostFilter recording; resultstore/store.go:439-458 annotation shape).
"""

import json

import pytest

from kube_scheduler_simulator_tpu.cluster.store import NotFound, ObjectStore
from kube_scheduler_simulator_tpu.framework.engine import SchedulerEngine
from kube_scheduler_simulator_tpu.store import annotations as ann


def node(name, cpu="1", mem="1Gi", taints=None):
    n = {
        "apiVersion": "v1", "kind": "Node",
        "metadata": {"name": name, "labels": {"kubernetes.io/hostname": name}},
        "spec": {},
        "status": {
            "allocatable": {"cpu": cpu, "memory": mem, "pods": "110"},
            "capacity": {"cpu": cpu, "memory": mem, "pods": "110"},
        },
    }
    if taints:
        n["spec"]["taints"] = taints
    return n


def pod(name, cpu="100m", priority=0, node_name=None, policy=None, created=None):
    p = {
        "apiVersion": "v1", "kind": "Pod",
        "metadata": {"name": name, "namespace": "default"},
        "spec": {
            "priority": priority,
            "containers": [{"name": "c", "resources": {"requests": {"cpu": cpu}}}],
        },
        "status": {},
    }
    if node_name:
        p["spec"]["nodeName"] = node_name
        p["status"]["phase"] = "Running"
    if policy:
        p["spec"]["preemptionPolicy"] = policy
    if created:
        p["metadata"]["creationTimestamp"] = created
    return p


def first_history_entry(store, name):
    p = store.get("pods", name)
    return json.loads(p["metadata"]["annotations"][ann.RESULT_HISTORY])[0]


def test_preempts_lower_priority_victim():
    s = ObjectStore()
    s.create("nodes", node("n1", cpu="1"))
    s.create("pods", pod("victim", cpu="800m", priority=0, node_name="n1"))
    s.create("pods", pod("pri", cpu="500m", priority=10))
    engine = SchedulerEngine(s)
    assert engine.schedule_pending() == 1

    # victim evicted, preemptor bound to the freed node
    try:
        s.get("pods", "victim")
        assert False, "victim should be deleted"
    except NotFound:
        pass
    p = s.get("pods", "pri")
    assert p["spec"]["nodeName"] == "n1"

    # first cycle's postfilter-result records the nominated node
    h0 = first_history_entry(s, "pri")
    pf = json.loads(h0[ann.POST_FILTER_RESULT])
    assert pf == {"n1": {"DefaultPreemption": "preemption victim"}}


def test_no_preemption_when_policy_never():
    s = ObjectStore()
    s.create("nodes", node("n1", cpu="1"))
    s.create("pods", pod("victim", cpu="800m", priority=0, node_name="n1"))
    s.create("pods", pod("pri", cpu="500m", priority=10, policy="Never"))
    engine = SchedulerEngine(s)
    assert engine.schedule_pending() == 0
    assert s.get("pods", "victim")  # untouched
    pf = json.loads(
        s.get("pods", "pri")["metadata"]["annotations"][ann.POST_FILTER_RESULT]
    )
    assert pf == {"n1": {}}  # evaluated but nothing nominated


def test_no_preemption_for_equal_priority():
    s = ObjectStore()
    s.create("nodes", node("n1", cpu="1"))
    s.create("pods", pod("victim", cpu="800m", priority=10, node_name="n1"))
    s.create("pods", pod("pri", cpu="500m", priority=10))
    engine = SchedulerEngine(s)
    assert engine.schedule_pending() == 0
    assert s.get("pods", "victim")


def test_unresolvable_failure_not_a_candidate():
    # node rejected by taint (UnschedulableAndUnresolvable upstream):
    # deleting pods can't help, so no preemption even though a lower-
    # priority pod is present
    s = ObjectStore()
    s.create("nodes", node("n1", cpu="1", taints=[
        {"key": "k", "value": "v", "effect": "NoSchedule"},
    ]))
    s.create("pods", pod("victim", cpu="100m", priority=0, node_name="n1"))
    s.create("pods", pod("pri", cpu="500m", priority=10))
    engine = SchedulerEngine(s)
    assert engine.schedule_pending() == 0
    assert s.get("pods", "victim")
    pf = json.loads(
        s.get("pods", "pri")["metadata"]["annotations"][ann.POST_FILTER_RESULT]
    )
    assert pf == {"n1": {}}


def test_reprieve_keeps_higher_priority_victim():
    # removing only the prio-1 pod suffices; the prio-2 pod is reprieved
    s = ObjectStore()
    s.create("nodes", node("n1", cpu="1"))
    s.create("pods", pod("v-lo", cpu="400m", priority=1, node_name="n1"))
    s.create("pods", pod("v-hi", cpu="400m", priority=2, node_name="n1"))
    s.create("pods", pod("pri", cpu="500m", priority=10))
    engine = SchedulerEngine(s)
    assert engine.schedule_pending() == 1
    assert s.get("pods", "v-hi")  # reprieved
    try:
        s.get("pods", "v-lo")
        assert False, "lower-priority victim should be evicted"
    except NotFound:
        pass
    assert s.get("pods", "pri")["spec"]["nodeName"] == "n1"


def test_candidate_selection_prefers_lower_victim_priority():
    s = ObjectStore()
    s.create("nodes", node("a", cpu="1"))
    s.create("nodes", node("b", cpu="1"))
    s.create("pods", pod("victim-hi", cpu="800m", priority=5, node_name="a"))
    s.create("pods", pod("victim-lo", cpu="800m", priority=1, node_name="b"))
    s.create("pods", pod("pri", cpu="500m", priority=10))
    engine = SchedulerEngine(s)
    assert engine.schedule_pending() == 1
    assert s.get("pods", "pri")["spec"]["nodeName"] == "b"
    assert s.get("pods", "victim-hi")  # untouched
    try:
        s.get("pods", "victim-lo")
        assert False
    except NotFound:
        pass


def test_nominated_node_recorded_on_status():
    s = ObjectStore()
    s.create("nodes", node("n1", cpu="1"))
    s.create("pods", pod("victim", cpu="800m", priority=0, node_name="n1"))
    s.create("pods", pod("pri", cpu="500m", priority=10))
    engine = SchedulerEngine(s)
    engine.schedule_pending()
    # by the end the pod is bound; nominatedNodeName was set in between and
    # survives on status
    p = s.get("pods", "pri")
    assert p["status"].get("nominatedNodeName") == "n1"


def test_preemption_runs_in_extender_path():
    # an extender is configured but the failure is a plugin FitError —
    # preemption must still run (upstream runs PostFilter on any FitError)
    import threading
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    from kube_scheduler_simulator_tpu.scheduler.extender import ExtenderService

    class PassThrough(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_POST(self):
            body = json.loads(self.rfile.read(int(self.headers["Content-Length"])))
            resp = {"NodeNames": body.get("NodeNames") or []}
            data = json.dumps(resp).encode()
            self.send_response(200)
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

    httpd = ThreadingHTTPServer(("127.0.0.1", 0), PassThrough)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        url = f"http://127.0.0.1:{httpd.server_address[1]}"
        s = ObjectStore()
        s.create("nodes", node("n1", cpu="1"))
        s.create("pods", pod("victim", cpu="800m", priority=0, node_name="n1"))
        s.create("pods", pod("pri", cpu="500m", priority=10))
        engine = SchedulerEngine(s)
        engine.set_extenders(ExtenderService([{"urlPrefix": url, "filterVerb": "filter"}]))
        assert engine.schedule_pending() == 1
        assert s.get("pods", "pri")["spec"]["nodeName"] == "n1"
        h0 = first_history_entry(s, "pri")
        pf = json.loads(h0[ann.POST_FILTER_RESULT])
        assert pf == {"n1": {"DefaultPreemption": "preemption victim"}}
    finally:
        httpd.shutdown()


def _pdb(name, match_labels, disruptions_allowed, namespace="default"):
    return {
        "apiVersion": "policy/v1", "kind": "PodDisruptionBudget",
        "metadata": {"name": name, "namespace": namespace},
        "spec": {"selector": {"matchLabels": match_labels}},
        "status": {"disruptionsAllowed": disruptions_allowed},
    }


def _labeled(p, labels):
    p["metadata"]["labels"] = labels
    return p


def test_pdb_violations_break_candidate_ties():
    """Two equivalent candidate nodes; the victim on n1 is protected by an
    exhausted PDB, the one on n2 is not — upstream pickOneNodeForPreemption
    ranks by fewest PDB violations FIRST, so n2 must win even though node
    order favors n1."""
    s = ObjectStore()
    s.create("nodes", node("n1", cpu="1"))
    s.create("nodes", node("n2", cpu="1"))
    s.create("pods", _labeled(pod("guarded", cpu="800m", node_name="n1"),
                              {"app": "guarded"}))
    s.create("pods", pod("plain", cpu="800m", node_name="n2"))
    s.create("poddisruptionbudgets", _pdb("pdb", {"app": "guarded"}, 0))
    s.create("pods", pod("pri", cpu="500m", priority=10))
    engine = SchedulerEngine(s)
    engine.schedule_pending()
    with pytest.raises(NotFound):
        s.get("pods", "plain")          # evicted
    assert s.get("pods", "guarded")     # spared by its budget
    assert s.get("pods", "pri")["spec"].get("nodeName") == "n2"


def test_pdb_with_budget_does_not_count_as_violation():
    """disruptionsAllowed=1 covers one eviction: no violation recorded, the
    guarded pod is evictable like any other."""
    s = ObjectStore()
    s.create("nodes", node("n1", cpu="1"))
    s.create("pods", _labeled(pod("guarded", cpu="800m", node_name="n1"),
                              {"app": "guarded"}))
    s.create("poddisruptionbudgets", _pdb("pdb", {"app": "guarded"}, 1))
    s.create("pods", pod("pri", cpu="500m", priority=10))
    engine = SchedulerEngine(s)
    engine.schedule_pending()
    with pytest.raises(NotFound):
        s.get("pods", "guarded")
    assert s.get("pods", "pri")["spec"].get("nodeName") == "n1"


def test_pdb_reprieve_prefers_sparing_violating_pods():
    """On one node with two equal victims where only one is PDB-protected,
    the reprieve pass tries violating pods first — the unprotected pod is
    the one evicted when evicting either would suffice."""
    s = ObjectStore()
    s.create("nodes", node("n1", cpu="2"))
    s.create("pods", _labeled(pod("guarded", cpu="900m", node_name="n1",
                                  created="2024-01-01T00:00:00Z"),
                              {"app": "guarded"}))
    s.create("pods", pod("plain", cpu="900m", node_name="n1",
                         created="2024-01-01T00:00:00Z"))
    s.create("poddisruptionbudgets", _pdb("pdb", {"app": "guarded"}, 0))
    s.create("pods", pod("pri", cpu="900m", priority=10))
    engine = SchedulerEngine(s)
    engine.schedule_pending()
    with pytest.raises(NotFound):
        s.get("pods", "plain")
    assert s.get("pods", "guarded")
    assert s.get("pods", "pri")["spec"].get("nodeName") == "n1"


def test_pdb_filter_split_budget_accounting():
    """filterPodsWithPDBViolation: the budget is consumed in pod order —
    with disruptionsAllowed=1 and two matching pods, only the second is
    violating."""
    from kube_scheduler_simulator_tpu.framework.preemption import (
        filter_pods_with_pdb_violation,
    )

    pods = [
        _labeled(pod("a"), {"app": "x"}),
        _labeled(pod("b"), {"app": "x"}),
        pod("c"),
    ]
    violating, ok = filter_pods_with_pdb_violation(
        pods, [_pdb("pdb", {"app": "x"}, 1)])
    assert [p["metadata"]["name"] for p in violating] == ["b"]
    assert [p["metadata"]["name"] for p in ok] == ["a", "c"]


def test_default_preemption_args_plumbed_from_plugin_config():
    """DefaultPreemptionArgs (minCandidateNodesPercentage/Absolute) reach
    the Preemptor's candidate budget (upstream DefaultPreemptionArgs
    defaulting: 10% / 100)."""
    from kube_scheduler_simulator_tpu.framework.preemption import Preemptor
    from kube_scheduler_simulator_tpu.plugins.registry import PluginSetConfig

    cfg = PluginSetConfig(args={"DefaultPreemption": {
        "minCandidateNodesPercentage": 50, "minCandidateNodesAbsolute": 2}})
    p = Preemptor(ObjectStore(), cfg)
    assert (p.min_candidate_pct, p.min_candidate_abs) == (50, 2)
    # defaults when unconfigured
    d = Preemptor(ObjectStore(), PluginSetConfig())
    assert (d.min_candidate_pct, d.min_candidate_abs) == (10, 100)
    # budget math honors the configured knobs: 10 nodes at 50%/abs2 -> 5
    from kube_scheduler_simulator_tpu.framework.preemption import _num_candidates
    assert _num_candidates(10, p.min_candidate_pct, p.min_candidate_abs) == 5
