"""Control-plane tests: store, applier, snapshot/reset, recorder/replayer,
importer/syncer, reflector, engine, scheduler service.

Modeled on the reference's table-driven service tests (SURVEY.md §4):
fake-clientset-style scenarios become direct ObjectStore manipulation.
"""

import json
import time

import pytest

from kube_scheduler_simulator_tpu.cluster.store import (
    ADDED, AlreadyExists, Conflict, DELETED, MODIFIED, NotFound, ObjectStore,
)
from kube_scheduler_simulator_tpu.framework.engine import SchedulerEngine
from kube_scheduler_simulator_tpu.models.workloads import make_nodes, make_pods
from kube_scheduler_simulator_tpu.plugins.registry import PluginSetConfig
from kube_scheduler_simulator_tpu.scheduler.convert import (
    convert_configuration_for_simulator,
    default_scheduler_config,
    parse_plugin_set,
)
from kube_scheduler_simulator_tpu.scheduler.service import SchedulerService
from kube_scheduler_simulator_tpu.services.importer import FileSource, OneShotImporter
from kube_scheduler_simulator_tpu.services.recorder import RecorderService
from kube_scheduler_simulator_tpu.services.replayer import ReplayerService
from kube_scheduler_simulator_tpu.services.reset import ResetService
from kube_scheduler_simulator_tpu.services.resourceapplier import ResourceApplier
from kube_scheduler_simulator_tpu.services.snapshot import SnapshotService
from kube_scheduler_simulator_tpu.services.syncer import SyncerService
from kube_scheduler_simulator_tpu.store import annotations as ann
from kube_scheduler_simulator_tpu.store.reflector import StoreReflector, update_result_history
from kube_scheduler_simulator_tpu.store.resultstore import ResultStore


def pod(name, ns="default", node=None, labels=None):
    p = {
        "apiVersion": "v1", "kind": "Pod",
        "metadata": {"name": name, "namespace": ns, "labels": labels or {}},
        "spec": {"containers": [{"name": "c", "resources": {"requests": {"cpu": "100m"}}}]},
    }
    if node:
        p["spec"]["nodeName"] = node
    return p


def node(name):
    return {
        "apiVersion": "v1", "kind": "Node",
        "metadata": {"name": name},
        "status": {"allocatable": {"cpu": "4", "memory": "8Gi", "pods": "110"}},
    }


# ---------------------------------------------------------------- store

class TestObjectStore:
    def test_crud_and_rv(self):
        s = ObjectStore()
        created = s.create("pods", pod("a"))
        assert created["metadata"]["uid"]
        rv1 = int(created["metadata"]["resourceVersion"])
        got = s.get("pods", "a")
        assert got["metadata"]["name"] == "a"
        got["spec"]["nodeName"] = "n1"
        updated = s.update("pods", got)
        assert int(updated["metadata"]["resourceVersion"]) > rv1
        with pytest.raises(AlreadyExists):
            s.create("pods", pod("a"))
        s.delete("pods", "a")
        with pytest.raises(NotFound):
            s.get("pods", "a")

    def test_conflict_on_stale_rv(self):
        s = ObjectStore()
        s.create("pods", pod("a"))
        p1 = s.get("pods", "a")
        p2 = s.get("pods", "a")
        s.update("pods", p1)
        with pytest.raises(Conflict):
            s.update("pods", p2)

    def test_watch_replay_and_live(self):
        s = ObjectStore()
        s.create("pods", pod("a"))
        q = s.watch("pods", since_rv=0)
        rv, et, obj = q.get(timeout=1)
        assert et == ADDED and obj["metadata"]["name"] == "a"
        s.create("pods", pod("b"))
        rv, et, obj = q.get(timeout=1)
        assert et == ADDED and obj["metadata"]["name"] == "b"
        p = s.get("pods", "a")
        s.update("pods", p)
        assert q.get(timeout=1)[1] == MODIFIED
        s.delete("pods", "b")
        assert q.get(timeout=1)[1] == DELETED

    def test_dump_restore(self):
        s = ObjectStore()
        s.create("pods", pod("a"))
        snap = s.dump()
        s.create("pods", pod("b"))
        s.restore(snap)
        items, _ = s.list("pods")
        assert [i["metadata"]["name"] for i in items] == ["a"]


# ---------------------------------------------------------------- applier

class TestResourceApplier:
    def test_strips_immutable_and_drops_owner(self):
        s = ObjectStore()
        a = ResourceApplier(s)
        p = pod("a")
        p["metadata"]["uid"] = "stale-uid"
        p["metadata"]["resourceVersion"] = "999"
        p["metadata"]["ownerReferences"] = [{"kind": "ReplicaSet"}]
        p["spec"]["serviceAccountName"] = "sa"
        created = a.create("pods", p)
        assert created["metadata"]["uid"] != "stale-uid"
        assert "ownerReferences" not in created["metadata"]
        assert "serviceAccountName" not in created["spec"]

    def test_scheduled_pod_update_filtered(self):
        s = ObjectStore()
        a = ResourceApplier(s)
        a.create("pods", pod("a", node="n1"))
        changed = pod("a", node="n1")
        changed["metadata"]["labels"] = {"x": "y"}
        assert a.update("pods", changed) is None  # skipped
        assert s.get("pods", "a")["metadata"]["labels"] == {}

    def test_pv_claimref_uid_resolution(self):
        s = ObjectStore()
        a = ResourceApplier(s)
        pvc = {"metadata": {"name": "claim", "namespace": "default"}, "spec": {}}
        created_pvc = s.create("persistentvolumeclaims", pvc)
        pv = {
            "metadata": {"name": "pv1"},
            "spec": {"claimRef": {"name": "claim", "namespace": "default", "uid": "old"}},
        }
        created = a.create("persistentvolumes", pv)
        assert created["spec"]["claimRef"]["uid"] == created_pvc["metadata"]["uid"]


# ---------------------------------------------------------------- snapshot / reset

class FakeSchedulerService:
    def __init__(self):
        self.cfg = {"profiles": [{"schedulerName": "default-scheduler"}]}
        self.restarts = []

    def get_config(self):
        return dict(self.cfg)

    def restart_scheduler(self, cfg):
        self.restarts.append(cfg)
        if cfg is not None:
            self.cfg = dict(cfg)


class TestSnapshot:
    def test_snap_load_roundtrip(self):
        s = ObjectStore()
        s.create("namespaces", {"metadata": {"name": "prod"}})
        s.create("namespaces", {"metadata": {"name": "kube-system"}})
        s.create("priorityclasses", {"metadata": {"name": "system-node-critical"}})
        s.create("priorityclasses", {"metadata": {"name": "biz-critical"}})
        s.create("nodes", node("n1"))
        s.create("pods", pod("a"))
        svc = SnapshotService(s, FakeSchedulerService())
        snap = svc.snap()
        assert [n["metadata"]["name"] for n in snap["namespaces"]] == ["prod"]
        assert [c["metadata"]["name"] for c in snap["priorityClasses"]] == ["biz-critical"]
        assert "schedulerConfig" in snap

        s2 = ObjectStore()
        sched2 = FakeSchedulerService()
        svc2 = SnapshotService(s2, sched2)
        svc2.load(json.loads(json.dumps(snap)))
        assert sched2.restarts  # scheduler restarted with snapshot config
        assert s2.get("nodes", "n1")
        assert s2.get("pods", "a")

    def test_reset_restores_boot_state(self):
        s = ObjectStore()
        s.create("nodes", node("n1"))
        sched = FakeSchedulerService()
        reset = ResetService(s, sched)
        s.create("nodes", node("n2"))
        s.delete("nodes", "n1")
        reset.reset()
        items, _ = s.list("nodes")
        assert [i["metadata"]["name"] for i in items] == ["n1"]
        assert sched.restarts


# ---------------------------------------------------------------- record / replay

class TestRecordReplay:
    def test_record_then_replay(self, tmp_path):
        src = ObjectStore()
        rec = RecorderService(src, str(tmp_path / "rec.jsonl"), flush_interval=0.05)
        rec.run()
        src.create("nodes", node("n1"))
        src.create("pods", pod("a"))
        p = src.get("pods", "a")
        p["metadata"]["labels"] = {"x": "1"}
        src.update("pods", p)
        src.create("pods", pod("gone"))
        src.delete("pods", "gone")
        time.sleep(0.3)
        rec.stop()

        lines = [json.loads(l) for l in open(tmp_path / "rec.jsonl")]
        events = [(r["event"], r["resource"]["kind"]) for r in lines]
        assert ("Add", "Node") in events and ("Update", "Pod") in events
        assert ("Delete", "Pod") in events
        delete_rec = next(r for r in lines if r["event"] == "Delete")
        assert set(delete_rec["resource"].keys()) == {"apiVersion", "kind", "metadata"}

        dst = ObjectStore()
        replayer = ReplayerService(ResourceApplier(dst), str(tmp_path / "rec.jsonl"))
        n = replayer.replay()
        assert n == len(lines)
        assert dst.get("nodes", "n1")
        assert dst.get("pods", "a")["metadata"]["labels"] == {"x": "1"}
        with pytest.raises(NotFound):
            dst.get("pods", "gone")


# ---------------------------------------------------------------- import / sync

class TestImportSync:
    def test_oneshot_import_with_selector(self):
        src = ObjectStore()
        src.create("nodes", node("n1"))
        src.create("pods", pod("keep", labels={"team": "a"}))
        src.create("pods", pod("skip", labels={"team": "b"}))
        dst = ObjectStore()
        imp = OneShotImporter(src, ResourceApplier(dst))
        imp.import_cluster_resources({"matchLabels": {"team": "a"}})
        items, _ = dst.list("pods")
        assert [i["metadata"]["name"] for i in items] == ["keep"]

    def test_file_source_import(self):
        snap = {"nodes": [node("n1")], "pods": [pod("a")]}
        dst = ObjectStore()
        imp = OneShotImporter(FileSource(snap), ResourceApplier(dst))
        assert imp.import_cluster_resources() == 2

    def test_syncer_streams_and_keeps_scheduler_authority(self):
        src, dst = ObjectStore(), ObjectStore()
        syncer = SyncerService(src, ResourceApplier(dst))
        src.create("nodes", node("n1"))
        syncer.run()
        src.create("pods", pod("a"))
        deadline = time.time() + 2
        while time.time() < deadline:
            try:
                dst.get("pods", "a")
                break
            except NotFound:
                time.sleep(0.01)
        assert dst.get("nodes", "n1")
        # simulator schedules the pod; a source update must NOT clobber it
        p = dst.get("pods", "a")
        p["spec"]["nodeName"] = "n1"
        dst.update("pods", p)
        sp = src.get("pods", "a")
        sp["metadata"]["labels"] = {"changed": "yes"}
        src.update("pods", sp)
        time.sleep(0.2)
        assert dst.get("pods", "a")["metadata"].get("labels") == {}
        syncer.stop()


# ---------------------------------------------------------------- reflector

class TestReflector:
    def test_reflect_merges_and_history(self):
        s = ObjectStore()
        s.create("pods", pod("a"))
        rs = ResultStore({"NodeResourcesFit": 1})
        rs.put_decoded("default", "a", {ann.SELECTED_NODE: "n1"})
        refl = StoreReflector(s, sleep=lambda _: None)
        refl.add_result_store(rs, "k")
        refl.reflect("default", "a")
        p = s.get("pods", "a")
        assert p["metadata"]["annotations"][ann.SELECTED_NODE] == "n1"
        history = json.loads(p["metadata"]["annotations"][ann.RESULT_HISTORY])
        assert len(history) == 1 and history[0][ann.SELECTED_NODE] == "n1"
        # store entry deleted after success
        assert rs.get_stored_result(p) is None

    def test_history_trims_oldest(self):
        p = {"metadata": {"annotations": {}}}
        big = "x" * 60000
        for i in range(6):
            update_result_history(p, {"payload": big, "i": str(i)})
        history = json.loads(p["metadata"]["annotations"][ann.RESULT_HISTORY])
        assert len(history) < 6  # trimmed from the oldest side
        assert history[-1]["i"] == "5"
        assert len(p["metadata"]["annotations"][ann.RESULT_HISTORY]) <= ann.TOTAL_ANNOTATION_SIZE_LIMIT

    def test_history_rejects_non_object_elements(self):
        # the reference unmarshals into []map[string]string, which errors
        # on valid-JSON arrays of non-objects (storereflector.go:169-171)
        # and on non-string values; '[{"a":"b"},3,{"c":"d"}]' keeps the
        # '[{"..."}]' shell so it exercises the splice fast path's
        # object-boundary scan specifically
        for raw in ('[1,2]', '["a"]', '[{"k":"v"},3]', '{"k":"v"}', 'nope[',
                    '[{"a":"b"},3,{"c":"d"}]', '[{"k":1}]'):
            p = {"metadata": {"annotations": {ann.RESULT_HISTORY: raw}}}
            with pytest.raises(ValueError):
                update_result_history(p, {"k": "v"})
        # legit values containing "}," fall to the slow path and splice
        # correctly
        p = {"metadata": {"annotations":
                          {ann.RESULT_HISTORY: '[{"a":"x},3"}]'}}}
        update_result_history(p, {"k": "v"})
        hist = json.loads(p["metadata"]["annotations"][ann.RESULT_HISTORY])
        assert hist == [{"a": "x},3"}, {"k": "v"}]


# ---------------------------------------------------------------- engine + service

class TestEngineAndService:
    def test_schedule_pending_binds_and_annotates(self):
        s = ObjectStore()
        for n in make_nodes(4, seed=5):
            s.create("nodes", n)
        for p in make_pods(6, seed=6):
            s.create("pods", p)
        engine = SchedulerEngine(s)
        bound = engine.schedule_pending()
        assert bound == 6
        p = s.get("pods", "pod-00000")
        assert p["spec"]["nodeName"]
        annos = p["metadata"]["annotations"]
        assert annos[ann.SELECTED_NODE] == p["spec"]["nodeName"]
        assert ann.FINAL_SCORE_RESULT in annos
        assert json.loads(annos[ann.RESULT_HISTORY])

    def test_unschedulable_pod_gets_condition(self):
        s = ObjectStore()
        for n in make_nodes(2, seed=5):
            s.create("nodes", n)
        huge = pod("huge")
        huge["spec"]["containers"][0]["resources"]["requests"]["cpu"] = "100000"
        s.create("pods", huge)
        engine = SchedulerEngine(s)
        assert engine.schedule_pending() == 0
        p = s.get("pods", "huge")
        cond = p["status"]["conditions"][0]
        assert cond["status"] == "False" and cond["reason"] == "Unschedulable"
        assert p["metadata"]["annotations"][ann.SELECTED_NODE] == ""

    def test_priority_order(self):
        s = ObjectStore()
        for n in make_nodes(2, seed=1):
            s.create("nodes", n)
        low = pod("low")
        high = pod("high")
        high["spec"]["priority"] = 1000
        s.create("pods", low)
        s.create("pods", high)
        engine = SchedulerEngine(s)
        assert [p["metadata"]["name"] for p in engine.pending_pods()] == ["high", "low"]

    def test_scheduler_service_rollback(self):
        engine = SchedulerEngine(ObjectStore())
        svc = SchedulerService(engine)
        good = svc.get_config()
        bad = {"profiles": [{"plugins": {"multiPoint": {"enabled": 42}}}]}
        with pytest.raises(Exception):
            svc.restart_scheduler(bad)
        assert svc.get_config() == good


# ---------------------------------------------------------------- config conversion

class TestConvert:
    def test_default_config_has_all_plugins(self):
        cfg = default_scheduler_config()
        names = [p["name"] for p in cfg["profiles"][0]["plugins"]["multiPoint"]["enabled"]]
        assert "NodeResourcesFit" in names and "PodTopologySpread" in names

    def test_convert_wraps_and_disables_star(self):
        cfg = convert_configuration_for_simulator({"profiles": [{
            "plugins": {"multiPoint": {"enabled": [{"name": "NodeResourcesFit", "weight": 2}]}},
        }]})
        mp = cfg["profiles"][0]["plugins"]["multiPoint"]
        names = [p["name"] for p in mp["enabled"]]
        assert all(n.endswith("Wrapped") for n in names)
        assert "NodeResourcesFitWrapped" in names
        assert mp["disabled"] == [{"name": "*"}]
        # re-configured default keeps its position but takes the weight
        fit = next(p for p in mp["enabled"] if p["name"] == "NodeResourcesFitWrapped")
        assert fit["weight"] == 2

    def test_parse_plugin_set_weights(self):
        ps = parse_plugin_set({"profiles": [{"plugins": {"multiPoint": {"enabled": [
            {"name": "NodeResourcesFit", "weight": 5},
            {"name": "TaintToleration"},  # weight 0 -> 1
        ], "disabled": [{"name": "*"}]}}}]})
        assert ps.enabled == ["TaintToleration", "NodeResourcesFit"]
        assert ps.weight("NodeResourcesFit") == 5
        assert ps.weight("TaintToleration") == 1

    def test_parse_default(self):
        ps = parse_plugin_set(None)
        assert ps.weight("TaintToleration") == 3
        assert ps.weight("NodeAffinity") == 2
