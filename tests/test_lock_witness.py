"""Lock-witness mode (tools/analysis/lockwitness.py): the lockdep-style
runtime detector the conftest installs for the soak suites under
KSS_TPU_LOCK_WITNESS=1.

Covers: A->B/B->A inversion in a fixture thread pair is detected even
though the interleaving never deadlocks; consistent ordering and RLock
reentrancy stay clean; Condition wait/notify keeps the held-set correct
through the release-reacquire; and a witnessed engine run produces
bit-identical annotations to an unwitnessed one (the golden/parity
contract with witness mode on).
"""

import threading

import pytest

from tools.analysis import lockwitness
from tools.analysis.lockwitness import LockOrderViolation


@pytest.fixture
def witness():
    w = lockwitness.install()
    w.reset()
    try:
        yield w
    finally:
        lockwitness.uninstall()


def test_inversion_detected_across_thread_pair(witness):
    """The acceptance fixture: thread 1 takes A then B, thread 2 takes
    B then A, with a barrier guaranteeing NO actual deadlock (thread 2
    starts only after thread 1 released everything).  The witness still
    reports the cycle — order, not luck, is the property."""
    lock_a = threading.Lock()
    lock_b = threading.Lock()
    done = threading.Event()

    def t1():
        with lock_a:
            with lock_b:
                pass
        done.set()

    def t2():
        done.wait(5)
        with lock_b:
            with lock_a:
                pass

    th1 = threading.Thread(target=t1, name="witness-t1")
    th2 = threading.Thread(target=t2, name="witness-t2")
    th1.start()
    th2.start()
    th1.join(5)
    th2.join(5)
    assert not th1.is_alive() and not th2.is_alive()

    with pytest.raises(LockOrderViolation) as ei:
        witness.assert_no_cycles()
    msg = str(ei.value)
    assert "cycle" in msg and "witness-t1" in msg and "witness-t2" in msg


def test_consistent_order_is_clean(witness):
    lock_a = threading.Lock()
    lock_b = threading.Lock()

    def worker():
        for _ in range(50):
            with lock_a:
                with lock_b:
                    pass

    ths = [threading.Thread(target=worker) for _ in range(4)]
    for t in ths:
        t.start()
    for t in ths:
        t.join(10)
    witness.assert_no_cycles()
    assert any(a != b for (a, b) in witness.edges), \
        "the consistent A->B edge should have been recorded"


def test_nonreentrant_reacquire_is_a_cycle(witness):
    """The PR 3 kubeapi._rv_int shape, single-lock variant: a helper
    that re-takes the caller's non-reentrant lock.  Two instances from
    the same creation site keep it from ACTUALLY deadlocking here; the
    witness flags the site regardless."""
    def make():
        return threading.Lock()  # one site: same lock identity

    outer, inner = make(), make()
    with outer:
        with inner:
            pass
    with pytest.raises(LockOrderViolation):
        witness.assert_no_cycles()


def test_rlock_reentrancy_clean(witness):
    r = threading.RLock()
    with r:
        with r:
            pass
    witness.assert_no_cycles()


def test_condition_wait_releases_held_set(witness):
    """cv.wait() drops the cv lock from the waiter's held set: a helper
    lock taken by the NOTIFIER while the waiter sleeps inside wait()
    must not produce edges from the cv to it on the waiter's thread."""
    cv = threading.Condition()
    other = threading.Lock()
    ready = threading.Event()
    woke = threading.Event()

    def waiter():
        with cv:
            ready.set()
            cv.wait(5)
        woke.set()

    t = threading.Thread(target=waiter, name="cv-waiter")
    t.start()
    assert ready.wait(5)
    with other:
        with cv:
            cv.notify_all()
    assert woke.wait(5)
    t.join(5)
    witness.assert_no_cycles()
    # and the waiter's post-wait held set drained (release after wake)
    assert witness._held() == []


def test_queue_and_event_builtin_locks_still_work(witness):
    import queue

    q = queue.Queue()
    q.put(1)
    assert q.get(timeout=1) == 1
    ev = threading.Event()
    ev.set()
    assert ev.wait(1)
    witness.assert_no_cycles()


def test_witnessed_engine_wave_bit_identical():
    """Golden/parity contract with witness mode on: the same workload
    scheduled with and without the witness produces byte-identical
    annotations and bind order, and the witnessed run records no
    acquisition-order cycle."""
    from kube_scheduler_simulator_tpu.cluster.store import ObjectStore
    from kube_scheduler_simulator_tpu.framework.engine import SchedulerEngine
    from kube_scheduler_simulator_tpu.models.workloads import make_nodes
    from kube_scheduler_simulator_tpu.plugins.registry import PluginSetConfig

    def run():
        store = ObjectStore()
        for n in make_nodes(6, seed=7):
            store.create("nodes", n)
        for i in range(12):
            store.create("pods", {
                "metadata": {"name": f"w-{i}", "namespace": "default"},
                "spec": {"containers": [{"name": "c", "resources": {
                    "requests": {"cpu": "100m"}}}]}})
        engine = SchedulerEngine(store, plugin_config=PluginSetConfig(
            enabled=["NodeResourcesFit",
                     "NodeResourcesBalancedAllocation"]))
        engine.schedule_pending()
        pods, _ = store.list("pods")
        return {p["metadata"]["name"]:
                (p["spec"].get("nodeName"),
                 tuple(sorted((p["metadata"].get("annotations")
                               or {}).items())))
                for p in pods}

    baseline = run()
    w = lockwitness.install()
    w.reset()
    try:
        witnessed = run()
        w.assert_no_cycles()
    finally:
        lockwitness.uninstall()
    assert witnessed == baseline


def test_uninstall_restores_threading():
    before = (threading.Lock, threading.RLock, threading.Condition)
    lockwitness.install()
    lockwitness.uninstall()
    assert (threading.Lock, threading.RLock,
            threading.Condition) == before
