"""SLO-driven autopilot (control/, docs/autopilot.md).

Covers the closed loop end to end: the fail-safe env switch, qos
admission, each effector driven with synthetic telemetry through
direct tick() calls (speculative hysteresis without thrash, HBM weight
raise/decay/donate, shed with the 0.8x recovery band), the weighted
budget-share enforcement spilling only the fat session's own chunks,
the HTTP 429 + Retry-After contract through a real server, byte-parity
of a scheduling run with the controls registry empty vs populated for
an unrelated session, the fail-safe full revert on a faulted tick, the
autopilot.decide black-box schema, idle eviction under pressure
(tier order, critical never), and churn-workload determinism.
"""

from __future__ import annotations

import copy
import json
import time
import urllib.error
import urllib.request

import pytest

from kube_scheduler_simulator_tpu.config.config import SimulatorConfiguration
from kube_scheduler_simulator_tpu.control import CONTROLS, QOS_TIERS
from kube_scheduler_simulator_tpu.control.autopilot import (
    HYSTERESIS_TICKS, _SPEC_MID_TICKS, Autopilot, autopilot_enabled,
    shed_qos_tiers)
from kube_scheduler_simulator_tpu.framework.replay import _DeviceResultBudget
from kube_scheduler_simulator_tpu.models.workloads import (
    make_churn_workload, make_nodes, make_pods)
from kube_scheduler_simulator_tpu.plugins.registry import PluginSetConfig
from kube_scheduler_simulator_tpu.server.di import DIContainer
from kube_scheduler_simulator_tpu.server.server import SimulatorServer
from kube_scheduler_simulator_tpu.server.sessions import (
    SessionError, SessionManager)
from kube_scheduler_simulator_tpu.utils.blackbox import (
    BLACKBOX, SLO, validate_dump)
from kube_scheduler_simulator_tpu.utils.tracing import TRACER

ENABLED = ["NodeResourcesFit", "NodeResourcesBalancedAllocation",
           "NodeAffinity", "TaintToleration", "PodTopologySpread"]


@pytest.fixture(autouse=True)
def _clean_controls():
    """Every test starts and ends at the parity baseline — leaked
    overrides would silently reshape unrelated suites' budgets."""
    CONTROLS.reset()
    yield
    CONTROLS.reset()


def _mgr(**kw) -> SessionManager:
    kw.setdefault("cfg", SimulatorConfiguration(port=0))
    kw.setdefault("start_scheduler", False)
    kw.setdefault("idle_ttl", 0)
    return SessionManager(**kw)


def _fill_slo(session: str, seconds: float, n: int = 70) -> None:
    """Saturate the session's rolling window so p99 IS `seconds`."""
    for _ in range(n):
        SLO.observe_wave(session, seconds, pods=10)


# ------------------------------------------------- env knob fail-safety


def test_autopilot_env_switch_fails_off_on_garbage(monkeypatch):
    monkeypatch.delenv("KSS_TPU_AUTOPILOT", raising=False)
    assert autopilot_enabled() is True
    for raw, want in (("1", True), ("true", True), ("on", True),
                      ("0", False), ("false", False), ("off", False),
                      ("maybe", False), ("2", False)):
        monkeypatch.setenv("KSS_TPU_AUTOPILOT", raw)
        assert autopilot_enabled() is want, raw


def test_shed_qos_tiers_parse_fail_safe(monkeypatch):
    monkeypatch.delenv("KSS_TPU_AUTOPILOT_SHED_QOS", raising=False)
    assert shed_qos_tiers() == ("best-effort", "standard")
    monkeypatch.setenv("KSS_TPU_AUTOPILOT_SHED_QOS", "best-effort")
    assert shed_qos_tiers() == ("best-effort",)
    # unknown tokens drop; critical is never sheddable
    monkeypatch.setenv("KSS_TPU_AUTOPILOT_SHED_QOS", "bogus, standard")
    assert shed_qos_tiers() == ("standard",)
    monkeypatch.setenv("KSS_TPU_AUTOPILOT_SHED_QOS", "critical,bogus")
    assert shed_qos_tiers() == ("best-effort", "standard")


def test_session_qos_validated_on_create():
    mgr = _mgr(max_sessions=4)
    try:
        sess = mgr.create("q-crit", qos="critical")
        assert sess.info()["qos"] == "critical"
        assert mgr.create("q-def").info()["qos"] == "standard"
        with pytest.raises(SessionError):
            mgr.create("q-bad", qos="turbo")
        briefs = {sid: qos for sid, qos, _t, _b in mgr.sessions_brief()}
        assert briefs["q-crit"] == "critical"
        assert all(q in QOS_TIERS for q in briefs.values())
    finally:
        mgr.shutdown()


# ------------------------------------------- effector: speculative tuning


def test_speculative_effector_hysteresis_no_thrash():
    mgr = _mgr(max_sessions=4)
    ap = Autopilot(mgr, interval=3600, slo_target=0)  # shed effector off
    try:
        mgr.create("ap-spec")

        def rounds(accepted: int, rolled: int) -> None:
            if accepted:
                TRACER.inc("speculative_accepted_total", accepted,
                           session="ap-spec")
            if rolled:
                TRACER.inc("speculative_rolled_back_total", rolled,
                           session="ap-spec")

        ap.tick()   # baseline tick: no evidence, no decision
        assert CONTROLS.spec_overrides("ap-spec") == (None, None)
        rounds(90, 10)
        ap.tick()   # streak 1 of HYSTERESIS_TICKS: still default
        assert CONTROLS.spec_overrides("ap-spec") == (None, None)
        rounds(95, 5)
        ap.tick()
        # sustained high accept fraction: top rung, doubled candidates
        assert CONTROLS.spec_overrides("ap-spec") == (-1, 256)

        # alternating good/bad waves never build a streak: no thrash
        for _ in range(HYSTERESIS_TICKS * 2):
            rounds(10, 90)
            ap.tick()
            rounds(90, 10)
            ap.tick()
        assert CONTROLS.spec_overrides("ap-spec") == (-1, 256)

        rounds(10, 90)
        ap.tick()
        rounds(5, 95)
        ap.tick()
        # sustained collapse: bottom rung, halved candidates
        assert CONTROLS.spec_overrides("ap-spec") == (0, 64)
        assert ap.stats()["decisions"] == 2
    finally:
        mgr.shutdown()


def test_speculative_profile_decays_to_default_on_mid_band():
    """A profile is not forever: a sustained mid-band accept fraction
    (no hi/lo evidence either way) decays the session back to the
    static default, mirroring the budget effector's calm-tick decay."""
    mgr = _mgr(max_sessions=4)
    ap = Autopilot(mgr, interval=3600, slo_target=0)
    try:
        mgr.create("ap-mid")

        def rounds(accepted: int, rolled: int) -> None:
            TRACER.inc("speculative_accepted_total", accepted,
                       session="ap-mid")
            TRACER.inc("speculative_rolled_back_total", rolled,
                       session="ap-mid")

        ap.tick()   # baseline
        for _ in range(HYSTERESIS_TICKS):
            rounds(95, 5)
            ap.tick()
        assert CONTROLS.spec_overrides("ap-mid") == (-1, 256)
        # mid-band rounds: no transition until the decay streak fills
        for _ in range(_SPEC_MID_TICKS - 1):
            rounds(70, 30)
            ap.tick()
            assert CONTROLS.spec_overrides("ap-mid") == (-1, 256)
        rounds(70, 30)
        ap.tick()
        assert CONTROLS.spec_overrides("ap-mid") == (None, None)
    finally:
        mgr.shutdown()


def test_speculative_candidates_scale_operator_baseline(monkeypatch):
    """The profile multipliers scale KSS_TPU_SPECULATIVE_CANDIDATES as
    the operator set it — aggressive on a 512 baseline means 1024,
    never a silent cut back to 2x the built-in 128."""
    monkeypatch.setenv("KSS_TPU_SPECULATIVE_CANDIDATES", "512")
    mgr = _mgr(max_sessions=4)
    ap = Autopilot(mgr, interval=3600, slo_target=0)
    try:
        mgr.create("ap-env")

        def rounds(accepted: int, rolled: int) -> None:
            TRACER.inc("speculative_accepted_total", accepted,
                       session="ap-env")
            TRACER.inc("speculative_rolled_back_total", rolled,
                       session="ap-env")

        ap.tick()   # baseline
        for _ in range(HYSTERESIS_TICKS):
            rounds(95, 5)
            ap.tick()
        assert CONTROLS.spec_overrides("ap-env") == (-1, 1024)
        for _ in range(HYSTERESIS_TICKS):
            rounds(5, 95)
            ap.tick()
        assert CONTROLS.spec_overrides("ap-env") == (0, 256)
    finally:
        mgr.shutdown()


# ----------------------------------------------- effector: HBM rebalance


def test_budget_effector_raises_decays_and_donates(monkeypatch):
    monkeypatch.setenv("KSS_TPU_DEVICE_RESULT_BUDGET_MB", "8")
    mgr = _mgr(max_sessions=4)
    ap = Autopilot(mgr, interval=3600, slo_target=0)
    try:
        mgr.create("ap-fat")
        mgr.create("ap-lean")
        for _ in range(2):
            TRACER.inc("device_chunks_spilled_total", 3, session="ap-fat")
            ap.tick()
        # two spilling ticks: +0.5 weight each
        assert CONTROLS.budget_milliweights()["ap-fat"] == 2000
        for _ in range(2):
            ap.tick()
        mw = CONTROLS.budget_milliweights()
        assert mw["ap-fat"] == 2000   # calm but not yet CALM_TICKS
        # lean session retained nothing for CALM_TICKS: donates headroom
        assert mw["ap-lean"] == 500
        for _ in range(3):
            ap.tick()
        # fat session decays back to the equal split once calm
        assert CONTROLS.budget_milliweights().get("ap-fat", 1000) == 1000
    finally:
        mgr.shutdown()


class _FakeCC:
    """Stands in for _CompactChunks: records which chunks the budget
    chose to spill and releases them like the real materialize."""

    def __init__(self, budget):
        self.budget = budget
        self.spilled: list[int] = []

    def materialize(self, ci: int, spill: bool = False):
        self.spilled.append(ci)
        self.budget.release(self, ci)


def test_weighted_shares_spill_only_the_fat_sessions_chunks(monkeypatch):
    monkeypatch.setenv("KSS_TPU_DEVICE_RESULT_BUDGET_MB", "1")
    chunk = 200 << 10   # 200 KiB

    def run(fat_weight: float | None) -> tuple[list[int], list[int]]:
        CONTROLS.reset()
        if fat_weight is not None:
            CONTROLS.set_budget_weight("bw-fat", fat_weight)
        budget = _DeviceResultBudget()
        fat, lean = _FakeCC(budget), _FakeCC(budget)
        with TRACER.session_scope("bw-fat"):
            for ci in range(4):           # 800 KiB
                budget.retain(fat, ci, chunk)
        with TRACER.session_scope("bw-lean"):
            budget.retain(lean, 0, chunk // 2)   # 100 KiB
        budget.drain()
        if budget._pool is not None:   # don't leak spill threads
            budget._pool.shutdown(wait=True)
        return fat.spilled, lean.spilled

    # equal split: each share is 512 KiB, the fat session spills its own
    # two least-recent chunks and never touches the lean neighbor
    fat_spilled, lean_spilled = run(None)
    assert fat_spilled == [0, 1] and lean_spilled == []
    # autopilot raised the fat session's weight to 3.0: its share grows
    # to 768 KiB, one spill suffices — the lean session still untouched
    fat_spilled, lean_spilled = run(3.0)
    assert fat_spilled == [0] and lean_spilled == []


# --------------------------------------------- effector: overload / shed


def test_shed_effector_hysteresis_and_recovery_band():
    mgr = _mgr(max_sessions=8)
    ap = Autopilot(mgr, interval=3600, slo_target=0.1)
    try:
        mgr.create("ap-shed", qos="best-effort")
        mgr.create("ap-crit", qos="critical")
        _fill_slo("ap-shed", 1.0)
        _fill_slo("ap-crit", 1.0)
        ap.tick()
        assert CONTROLS.shed_state("ap-shed") == (False, 0)  # streak 1
        ap.tick()
        shedding, retry = CONTROLS.shed_state("ap-shed")
        assert shedding and retry == 2   # ceil(2 * p99)
        # critical breaches identically but is never shed
        assert CONTROLS.shed_state("ap-crit") == (False, 0)
        # hovering inside the recovery band (0.8x..1x target) must not
        # flap the gate open — live waves keep arriving (in-flight
        # backlog still runs while shed), each tick sees fresh evidence
        _fill_slo("ap-shed", 0.09)
        for _ in range(4):
            _fill_slo("ap-shed", 0.09, n=1)
            ap.tick()
        assert CONTROLS.shed_state("ap-shed")[0] is True
        # a genuine recovery under 0.8x target lifts the shed
        _fill_slo("ap-shed", 0.01)
        ap.tick()
        ap.tick()
        assert CONTROLS.shed_state("ap-shed")[0] is False
        eff = ap.stats()["decisionsByEffector"]
        assert eff.get("shed", 0) >= 2   # one shed + one unshed landed
    finally:
        mgr.shutdown()


def test_shed_lifts_after_quiescence_and_can_reshed():
    """The anti-latch contract: once shed, the 429 gate stops inflow,
    the count-based SLO window freezes at its breach-era p99, and no
    recovery evidence can ever arrive through it.  Ticks where a
    shedding session ran ZERO new waves must therefore count toward
    recovery — and a client that floods again after the lift is shed
    again from fresh evidence."""
    mgr = _mgr(max_sessions=4)
    ap = Autopilot(mgr, interval=3600, slo_target=0.1)
    try:
        mgr.create("ap-quiet", qos="best-effort")
        _fill_slo("ap-quiet", 1.0)
        for _ in range(HYSTERESIS_TICKS):
            ap.tick()
        assert CONTROLS.shed_state("ap-quiet")[0] is True
        # inflow stops (clients back off per Retry-After): the window
        # still reads p99=1.0s, but with no new waves the shed must
        # lift after HYSTERESIS_TICKS quiet ticks, not latch forever
        ap.tick()
        assert CONTROLS.shed_state("ap-quiet")[0] is True   # streak 1
        ap.tick()
        assert CONTROLS.shed_state("ap-quiet")[0] is False
        # the returning flood is fresh breach evidence: shed again
        for _ in range(HYSTERESIS_TICKS):
            _fill_slo("ap-quiet", 1.0, n=1)
            ap.tick()
        assert CONTROLS.shed_state("ap-quiet")[0] is True
    finally:
        mgr.shutdown()


def test_failsafe_reverts_every_effector_and_recovers():
    mgr = _mgr(max_sessions=4)
    ap = Autopilot(mgr, interval=3600, slo_target=0.1)
    try:
        mgr.create("ap-fs", qos="best-effort")
        _fill_slo("ap-fs", 1.0)
        ap.tick()
        ap.tick()
        assert CONTROLS.shed_state("ap-fs")[0] is True
        CONTROLS.set_budget_weight("ap-fs", 2.0)

        real_brief = mgr.sessions_brief

        def boom():
            raise RuntimeError("telemetry plane unavailable")

        mgr.sessions_brief = boom
        assert ap.tick() == 0
        mgr.sessions_brief = real_brief
        # the fail-safe contract: EVERY override reverted in one step,
        # controller memory cleared, the loop keeps ticking
        assert ap.stats()["failsafes"] == 1
        assert CONTROLS.stats() == {}
        assert CONTROLS.shed_state("ap-fs") == (False, 0)
        ap.tick()   # clean slate: breach evidence rebuilds from zero
        assert CONTROLS.shed_state("ap-fs")[0] is False
        ap.tick()
        assert CONTROLS.shed_state("ap-fs")[0] is True
    finally:
        mgr.shutdown()


def test_autopilot_decide_events_survive_blackbox_schema():
    mgr = _mgr(max_sessions=4)
    ap = Autopilot(mgr, interval=3600, slo_target=0.1)
    try:
        mgr.create("ap-bb", qos="best-effort")
        _fill_slo("ap-bb", 1.0)
        ap.tick()
        ap.tick()
        assert CONTROLS.shed_state("ap-bb")[0] is True
        bundle, path = BLACKBOX.dump("test-autopilot", write=False)
        assert path is None
        kinds = validate_dump(bundle)["kinds"]
        assert kinds.get("autopilot.decide", 0) >= 1
        decides = [e for e in bundle["events"]
                   if e["kind"] == "autopilot.decide"]
        assert all({"effector", "session", "from", "to", "reason"}
                   <= set(e) for e in decides)
        # a decision without its evidence fields must fail validation
        bad = json.loads(json.dumps(bundle))
        bad["events"].append({"kind": "autopilot.decide", "t": 0.0,
                              "seq": 10 ** 9, "effector": "shed"})
        with pytest.raises(ValueError, match="autopilot.decide missing"):
            validate_dump(bad)
    finally:
        mgr.shutdown()


# ------------------------- decision provenance + the history ring


def test_shed_cycle_reconstructs_from_history_ring():
    """The causal-reconstruction contract (docs/metrics.md "History &
    correlation"): the full breach -> shed -> recovery arc reads back
    out of the columnar ring, and every shed decision's evidence
    matches the ring AT ITS RECORDED INDEX bit-for-bit (the controller
    plans from the exact planes the feeder sampled)."""
    from kube_scheduler_simulator_tpu.utils import history
    from kube_scheduler_simulator_tpu.utils.blackbox import FEEDER
    from kube_scheduler_simulator_tpu.utils.history import HISTORY

    prev = history.set_enabled(True)
    HISTORY.reset()
    FEEDER.reset()
    mgr = _mgr(max_sessions=4)
    ap = Autopilot(mgr, interval=3600, slo_target=0.1)
    sid = "ap-ring"
    try:
        mgr.create(sid, qos="best-effort")
        _fill_slo(sid, 1.0)
        ap.tick()                     # breach streak 1 (one ring row)
        ap.tick()                     # streak 2 -> shed applied
        assert CONTROLS.shed_state(sid)[0] is True
        ap.tick()                     # quiesced streak 1
        ap.tick()                     # streak 2 -> shed lifted
        assert CONTROLS.shed_state(sid)[0] is False
        ap.tick()                     # one more row records the lift

        win = HISTORY.window(series=["slo.p99", "autopilot.shed"],
                             session=sid, since=0)
        p99 = win["series"][f"slo.p99{{session={sid}}}"]
        shed = win["series"][f"autopilot.shed{{session={sid}}}"]
        first = next(i for i, v in enumerate(shed) if v == 1.0)
        # breach at or before the first shed sample; the flag returns
        # to 0 later — the whole arc is reconstructible from columns
        assert any(v is not None and v > 0.1 for v in p99[:first + 1])
        assert any(v == 0.0 for v in shed[first:])

        sheds = [d for d in ap.stats()["lastDecisions"][sid]
                 if d["effector"] == "shed"]
        assert len(sheds) == 2
        for d in sheds:
            evd = d["evidence"]
            idx = evd["historyIndex"]
            # the cited ring row holds exactly the p99 the planner read
            assert (HISTORY.value(f"slo.p99{{session={sid}}}", idx)
                    == evd["p99WaveSeconds"])
            # the row was sampled before the decision applied: it shows
            # the pre-transition shed state
            assert (HISTORY.value(f"autopilot.shed{{session={sid}}}", idx)
                    == (0.0 if d["to"] == "shedding" else 1.0))
            assert evd["sloWindow"]["p99WaveSeconds"] \
                == evd["p99WaveSeconds"]
        on, off = sheds
        assert (on["from"], on["to"]) == ("open", "shedding")
        assert (off["from"], off["to"]) == ("shedding", "open")
        assert on["evidence"]["breachStreak"] >= HYSTERESIS_TICKS
        assert off["evidence"]["okStreak"] >= HYSTERESIS_TICKS
    finally:
        history.set_enabled(prev)
        mgr.shutdown()


def test_evidence_omits_history_index_when_disabled():
    """KSS_TPU_HISTORY=0 parity: the planner still reads the same
    one-gather-per-tick planes and decides identically — the evidence
    just cites no ring index (there is no ring row to cite)."""
    from kube_scheduler_simulator_tpu.utils import history

    prev = history.set_enabled(False)
    mgr = _mgr(max_sessions=4)
    ap = Autopilot(mgr, interval=3600, slo_target=0.1)
    try:
        mgr.create("ap-nohist", qos="best-effort")
        _fill_slo("ap-nohist", 1.0)
        for _ in range(HYSTERESIS_TICKS):
            ap.tick()
        assert CONTROLS.shed_state("ap-nohist")[0] is True
        d = ap.stats()["lastDecisions"]["ap-nohist"][-1]
        assert d["effector"] == "shed" and d["to"] == "shedding"
        assert "historyIndex" not in d["evidence"]
        assert d["evidence"]["p99WaveSeconds"] == 1.0
    finally:
        history.set_enabled(prev)
        mgr.shutdown()


# -------------------------------------------------- idle-eviction pressure


def test_evict_idle_under_pressure_tier_order_never_critical():
    mgr = _mgr(max_sessions=8)
    try:
        for sid, qos in (("ev-be", "best-effort"), ("ev-std", "standard"),
                         ("ev-crit", "critical")):
            mgr.create(sid, qos=qos)
            mgr.get(sid, touch=False).last_used = time.time() - 100
        assert mgr.evict_idle_under_pressure(grace_s=1) == 1
        live = {sid for sid, _q, _t, _b in mgr.sessions_brief()}
        assert "ev-be" not in live   # best-effort goes first
        assert mgr.evict_idle_under_pressure(grace_s=1) == 1
        live = {sid for sid, _q, _t, _b in mgr.sessions_brief()}
        assert "ev-std" not in live
        # critical and the pinned default are never pressure-evicted
        assert mgr.evict_idle_under_pressure(grace_s=1) == 0
        live = {sid for sid, _q, _t, _b in mgr.sessions_brief()}
        assert {"ev-crit", "default"} <= live
    finally:
        mgr.shutdown()


# --------------------------------------------------- HTTP 429 contract


@pytest.fixture()
def server(monkeypatch):
    # a slow controller interval keeps the background autopilot from
    # un-shedding the manually-gated session mid-test
    monkeypatch.setenv("KSS_TPU_AUTOPILOT_INTERVAL_S", "60")
    cfg = SimulatorConfiguration(port=0)
    di = DIContainer(cfg)
    srv = SimulatorServer(di, port=0)
    srv.start(block=False)
    yield srv
    srv.shutdown()


def hreq(srv, method, path, body=None):
    """(status, headers, parsed body) — the shed contract needs the
    Retry-After HEADER, not just the JSON."""
    url = f"http://127.0.0.1:{srv.port}{path}"
    data = json.dumps(body).encode() if body is not None else None
    r = urllib.request.Request(url, data=data, method=method,
                               headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(r, timeout=10) as resp:
            raw = resp.read()
            return (resp.status, dict(resp.headers),
                    json.loads(raw) if raw else None)
    except urllib.error.HTTPError as e:
        raw = e.read()
        return e.code, dict(e.headers), json.loads(raw) if raw else None


def test_http_shed_gate_429_with_retry_after(server):
    code, _h, made = hreq(server, "POST", "/api/v1/sessions",
                          {"id": "shed-http", "qos": "best-effort"})
    assert code == 201 and made["qos"] == "best-effort"
    code, _h, _b = hreq(server, "POST", "/api/v1/sessions",
                        {"id": "open-http"})
    assert code == 201
    pod = make_pods(1, seed=21)[0]
    CONTROLS.set_shed("shed-http", True, 7)
    try:
        code, headers, body = hreq(
            server, "POST", "/api/v1/sessions/shed-http/pods", pod)
        assert code == 429
        assert headers.get("Retry-After") == "7"
        assert body["reason"] == "Overloaded"
        assert body["retryAfterSeconds"] == 7
        # only workload-submitting POSTs shed: reads stay up, and the
        # un-shed neighbor session is untouched
        code, _h, _b = hreq(server, "GET",
                            "/api/v1/sessions/shed-http/pods")
        assert code == 200
        code, _h, _b = hreq(server, "POST",
                            "/api/v1/sessions/open-http/pods",
                            copy.deepcopy(pod))
        assert code == 201
        code, _h, ready = hreq(server, "GET", "/readyz")
        assert code == 200
        assert ready["autopilot"]["shedding"] == ["shed-http"]
        code, _h, listing = hreq(server, "GET", "/api/v1/sessions")
        assert code == 200
        assert listing["autopilot"]["controls"]["shed-http"]["shed"] is True
        # the decision-provenance surface rides the same block (a
        # manual CONTROLS.set_shed is not an autopilot decision, so
        # the per-session lists may be empty — the key must exist)
        assert isinstance(listing["autopilot"]["lastDecisions"], dict)
    finally:
        CONTROLS.set_shed("shed-http", False)
    code, _h, _b = hreq(server, "POST",
                        "/api/v1/sessions/shed-http/pods",
                        copy.deepcopy(pod))
    assert code == 201


# ------------------------------------------------------- byte parity


def test_parity_empty_registry_vs_unrelated_overrides():
    """The opt-out claim (docs/autopilot.md): an empty controls
    registry — and one populated only for OTHER sessions — schedules
    byte-identically to the static-knob baseline."""
    mgr = _mgr(max_sessions=4)
    try:
        nodes = make_nodes(8, seed=31)
        pods = make_pods(48, seed=32)

        def run(sid: str) -> dict:
            sess = mgr.create(sid)
            sess.di.engine.set_profiles(None)
            sess.di.engine.plugin_config = PluginSetConfig(
                enabled=list(ENABLED))
            sess.di.engine.chunk = 16
            for n in nodes:
                sess.di.store.create("nodes", copy.deepcopy(n))
            for p in pods:
                sess.di.store.create("pods", copy.deepcopy(p))
            sess.di.engine.schedule_pending()
            return {p["metadata"]["name"]:
                    (p["spec"].get("nodeName"),
                     dict(p["metadata"].get("annotations") or {}))
                    for p in sess.di.store.list("pods")[0]}

        baseline = run("par-a")
        CONTROLS.set_spec("par-other", -1, 256)
        CONTROLS.set_budget_weight("par-other", 3.0)
        CONTROLS.set_shed("par-other", True, 9)
        contended = run("par-b")
        assert contended == baseline
        # the aggressive profile applied to the RUNNING session is also
        # byte-invariant: rung/kcand only repartition the same rounds
        CONTROLS.set_spec("par-c", -1, 256)
        aggressive = run("par-c")
        assert aggressive == baseline
    finally:
        mgr.shutdown()


# ------------------------------------------------- churn workload seed


def test_make_churn_workload_deterministic_and_consistent():
    nodes_a, sched_a = make_churn_workload(12, ticks=20, seed=5)
    nodes_b, sched_b = make_churn_workload(12, ticks=20, seed=5)
    assert json.dumps(sched_a) == json.dumps(sched_b)
    assert json.dumps(nodes_a) == json.dumps(nodes_b)
    assert len(sched_a) == 20
    _nodes_c, sched_c = make_churn_workload(12, ticks=20, seed=6)
    assert json.dumps(sched_c) != json.dumps(sched_a)
    # departures only name pods created in an EARLIER tick, never twice
    live: set[str] = set()
    seen_deletes: set[str] = set()
    for step in sched_a:
        for name in step["delete"]:
            assert name in live and name not in seen_deletes
            live.discard(name)
            seen_deletes.add(name)
        for pod in step["create"]:
            # steady-shape contract for the scan cache: no affinity pins
            assert "affinity" not in pod["spec"]
            live.add(pod["metadata"]["name"])
