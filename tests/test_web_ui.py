"""Web UI asset serving + the YAML codec algorithm.

No JS runtime exists in this image, so web/yaml.js cannot be executed
directly; instead `_dump`/`_parse` below are line-for-line Python
transcriptions of the JS algorithm, validated two ways over a corpus of
real manifests: (1) round-trip equality, (2) the emitted text parses
identically under PyYAML (i.e. the format the editor shows is standard
YAML, so manifests users paste from elsewhere parse the same way).
"""

import json
import re

import pytest
import yaml as pyyaml

from kube_scheduler_simulator_tpu.web import index_html, static_file

# ---------------------------------------------------------------- assets

ASSETS = ["yaml.js", "api.js", "store.js", "components.js", "forms.js",
          "app.js"]


def test_static_assets_exist_and_are_typed():
    for name in ASSETS:
        body, ctype = static_file(name)
        assert body, name
        assert ctype.startswith("text/javascript")


def test_index_references_all_assets():
    html = index_html().decode()
    for name in ASSETS:
        assert f"/web/{name}" in html


@pytest.mark.parametrize("bad", [
    "../__init__.py", "..%2f..%2fetc", ".hidden.js", "sub/dir.js",
    "index.html", "yaml.py", "missing.js",
])
def test_static_rejects_traversal_and_unknown(bad):
    body, _ = static_file(bad)
    assert body is None


def test_js_brace_balance_smoke():
    """Crude syntax gate: braces/brackets/parens balance outside strings,
    comments, and regex-literal contexts."""
    for name in ASSETS:
        src, _ = static_file(name)
        depth = {"{": 0, "[": 0, "(": 0}
        close = {"}": "{", "]": "[", ")": "("}
        in_str = None
        esc = False
        in_line_comment = in_block_comment = False
        prev = ""
        skip_regex = False
        text = src.decode()
        for i, c in enumerate(text):
            nxt = text[i + 1] if i + 1 < len(text) else ""
            if in_line_comment:
                if c == "\n":
                    in_line_comment = False
            elif in_block_comment:
                if prev == "*" and c == "/":
                    in_block_comment = False
            elif in_str:
                if esc:
                    esc = False
                elif c == "\\":
                    esc = True
                elif c == in_str:
                    in_str = None
            elif skip_regex:
                if esc:
                    esc = False
                elif c == "\\":
                    esc = True
                elif c == "/":
                    skip_regex = False
            elif c == "/" and nxt == "/":
                in_line_comment = True
            elif c == "/" and nxt == "*":
                in_block_comment = True
            elif c == "/" and re.match(r"[=(,:\[!&|?+\n ]", prev or "\n"):
                skip_regex = True
            elif c in "\"'`":
                in_str = c
            elif c in depth:
                depth[c] += 1
            elif c in close:
                depth[close[c]] -= 1
                assert depth[close[c]] >= 0, f"{name}: unbalanced {c} at {i}"
            prev = c
        assert all(v == 0 for v in depth.values()), f"{name}: {depth}"


# ------------------------------------------- YAML algorithm (JS mirror)

PLAIN_OK = re.compile(r"^[A-Za-z0-9_][A-Za-z0-9_./-]*$")
RESERVED = {"null", "true", "false", "yes", "no", "on", "off"}


def _scalar(v):
    if v is None:
        return "null"
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, (int, float)):
        return json.dumps(v)
    s = str(v)
    if s == "":
        return '""'
    if (PLAIN_OK.match(s) and s.lower() not in RESERVED
            and not re.match(r"^[\d.+-]", s)):
        return s
    return json.dumps(s)


def _dump(v, indent=0):
    pad = "  " * indent
    if isinstance(v, list):
        if not v:
            return pad + "[]"
        out = []
        for item in v:
            if isinstance(item, (dict, list)) and len(item):
                body = _dump(item, indent + 1)
                out.append(pad + "-" + body[len(pad) + 1:])
            else:
                leaf = ("[]" if isinstance(item, list)
                        else "{}" if isinstance(item, dict) else _scalar(item))
                out.append(pad + "- " + leaf)
        return "\n".join(out)
    if isinstance(v, dict):
        if not v:
            return pad + "{}"
        out = []
        for k, val in v.items():
            key = k if PLAIN_OK.match(k) else json.dumps(k)
            if isinstance(val, (dict, list)) and len(val):
                out.append(pad + key + ":\n" + _dump(val, indent + 1))
            elif isinstance(val, str) and "\n" in val:
                block = "|" if val.endswith("\n") else "|-"
                body = val[:-1] if val.endswith("\n") else val
                out.append(pad + key + ": " + block + "\n" + "\n".join(
                    pad + "  " + line for line in body.split("\n")))
            else:
                leaf = ("[]" if isinstance(val, list)
                        else "{}" if isinstance(val, dict) else _scalar(val))
                out.append(pad + key + ": " + leaf)
        return "\n".join(out)
    return pad + _scalar(v)


def dump(v):
    return _dump(v) + "\n"


MAP_RE = re.compile(r'^("(?:[^"\\]|\\.)*"|[^:]+):(?: (.*))?$')


def _parse_scalar(tok):
    tok = tok.strip()
    if tok in ("", "~", "null"):
        return None
    if tok == "true":
        return True
    if tok == "false":
        return False
    if tok == "[]":
        return []
    if tok == "{}":
        return {}
    if tok[0] == '"':
        return json.loads(tok)
    if tok[0] == "'":
        return tok[1:-1].replace("''", "'")
    if tok[0] in "[{":
        return _parse_flow(tok)
    if re.match(r"^[+-]?\d+$", tok):
        return int(tok)
    if re.match(r"^[+-]?(\d+\.\d*|\.\d+|\d+)([eE][+-]?\d+)?$", tok):
        return float(tok)
    return tok


def _parse_flow(s):
    out, word = "", ""
    in_str = esc = False

    def flush(word, out):
        w = word.strip()
        if w:
            out += json.dumps(_parse_scalar(w))
        return out

    for c in s:
        if in_str:
            out += c
            if esc:
                esc = False
            elif c == "\\":
                esc = True
            elif c == '"':
                in_str = False
        elif c == '"':
            out = flush(word, out)
            word = ""
            out += c
            in_str = True
        elif c in "[]{},:":
            out = flush(word, out)
            word = ""
            out += c
        else:
            word += c
    out = flush(word, out)
    return json.loads(out)


def parse(text):
    lines = [l for l in text.split("\n")
             if not re.match(r"^\s*(#|$)", l) and l.strip() != "---"]
    pos = [0]

    def indent_of(line):
        return len(line) - len(line.lstrip(" "))

    def parse_block(min_indent):
        if pos[0] >= len(lines):
            return None
        ind = indent_of(lines[pos[0]])
        if ind < min_indent:
            return None
        t = lines[pos[0]].strip()
        if t.startswith("- ") or t == "-":
            return parse_seq(ind)
        return parse_map(ind)

    def literal_block(parent_indent, keep_newline):
        body, block_ind = [], None
        while pos[0] < len(lines):
            line = lines[pos[0]]
            if line.strip() == "":
                body.append("")
                pos[0] += 1
                continue
            ind = indent_of(line)
            if ind <= parent_indent:
                break
            if block_ind is None:
                block_ind = ind
            body.append(line[block_ind:])
            pos[0] += 1
        while body and body[-1] == "":
            body.pop()
        return "\n".join(body) + ("\n" if keep_newline else "")

    def parse_map(ind):
        obj = {}
        while pos[0] < len(lines):
            line = lines[pos[0]]
            if line.strip() == "":
                pos[0] += 1
                continue
            if indent_of(line) != ind:
                break
            m = MAP_RE.match(line.strip())
            if not m:
                raise ValueError("bad mapping line: " + line.strip())
            key = json.loads(m.group(1)) if m.group(1)[0] == '"' else m.group(1).strip()
            rest = (m.group(2) or "").strip()
            pos[0] += 1
            if rest in ("|", "|-"):
                obj[key] = literal_block(ind, rest == "|")
            elif rest == "":
                obj[key] = parse_block(ind + 1)
            else:
                obj[key] = _parse_scalar(rest)
        return obj

    def parse_seq(ind):
        arr = []
        while pos[0] < len(lines):
            line = lines[pos[0]]
            if line.strip() == "":
                pos[0] += 1
                continue
            t = line.strip()
            if indent_of(line) != ind or not (t.startswith("- ") or t == "-"):
                break
            rest = "" if t == "-" else t[2:]
            if rest == "":
                pos[0] += 1
                arr.append(parse_block(ind + 1))
            elif (re.match(r'^"(?:[^"\\]|\\.)*":(?: .*)?$', rest)
                  if rest[0] == '"' else
                  (not re.match(r"^['\[{]", rest)
                   and re.match(r"^[^:]+:(?: .*)?$", rest))):
                item_indent = ind + 2
                lines[pos[0]] = " " * item_indent + rest
                arr.append(parse_map(item_indent))
            else:
                pos[0] += 1
                arr.append(_parse_scalar(rest))
        return arr

    v = parse_block(0)
    if pos[0] < len(lines):
        raise ValueError("unparsed content at line: " + lines[pos[0]].strip())
    return v


def _corpus():
    from kube_scheduler_simulator_tpu.models.workloads import make_nodes, make_pods
    from kube_scheduler_simulator_tpu.scheduler.convert import default_scheduler_config

    cases = [
        {"kind": "Pod", "apiVersion": "v1",
         "metadata": {"name": "p", "namespace": "default",
                      "labels": {"app.kubernetes.io/name": "x"},
                      "annotations": {"kube-scheduler-simulator.sigs.k8s.io/filter-result": '{"n":{"P":"passed"}}'}},
         "spec": {"containers": [{"name": "c", "image": "nginx:1.25",
                                  "resources": {"requests": {"cpu": "500m", "memory": "1Gi"}}}],
                  "nodeSelector": {}, "tolerations": []}},
        {"empty_map": {}, "empty_list": [], "null_v": None, "b": True,
         "f": 1.5, "neg": -3, "colon": "a: b", "hash": "#notcomment",
         "multiline": "line1\nline2\n", "no_trail": "a\nb",
         "reserved": "true", "numstr": "0755",
         "tricky_list": ["x: y", {"a:b": 1}, {"plain": "v"}]},
        make_nodes(3, seed=9, taint_fraction=0.5),
        make_pods(4, seed=10, with_affinity=True, with_tolerations=True,
                  with_spread=True, with_interpod=True),
        default_scheduler_config(),
    ]
    return cases


@pytest.mark.parametrize("i,case", list(enumerate(_corpus())))
def test_yaml_roundtrip_and_pyyaml_compat(i, case):
    text = dump(case)
    assert parse(text) == case, f"case {i}: mirror round-trip"
    assert pyyaml.safe_load(text) == case, f"case {i}: standard-YAML compat"
    # dump is deterministic / normal-form stable
    assert dump(parse(text)) == text


def test_yaml_parse_handwritten_manifest():
    text = """\
# a hand-written manifest with flow styles and comments
kind: Pod
apiVersion: v1
metadata:
  name: demo
  namespace: team-a
spec:
  containers:
    - name: c
      image: "nginx:1.25"
      ports: [{containerPort: 80}]
  nodeSelector: {zone: z1}
  priority: 1000
"""
    obj = parse(text)
    assert obj["spec"]["containers"][0]["image"] == "nginx:1.25"
    assert obj["spec"]["containers"][0]["ports"] == [{"containerPort": 80}]
    assert obj["spec"]["nodeSelector"] == {"zone": "z1"}
    assert obj["spec"]["priority"] == 1000
    assert obj == pyyaml.safe_load(text)


def test_mirror_matches_js_source_expectations():
    """Spot-check that the JS source encodes the same special cases the
    mirror implements (guards against the transcription drifting)."""
    src, _ = static_file("yaml.js")
    js = src.decode()
    for marker in [
        'PLAIN_OK = /^[A-Za-z0-9_][A-Za-z0-9_.\\/-]*$/',
        '"null", "true", "false", "yes", "no", "on", "off"',
        '/^[\\d.+-]/',
        '/^("(?:[^"\\\\]|\\\\.)*"|[^:]+):(?: (.*))?$/',
        'val.endsWith("\\n") ? "|" : "|-"',
    ]:
        assert marker in js, f"yaml.js drifted from mirror: {marker!r} missing"


# ------------------------------------------------- structured form dialogs

def _forms_js() -> str:
    src, _ = static_file("forms.js")
    return src.decode()


def test_form_fields_cover_creatable_kinds():
    """Every kind with a structured creation dialog is one the server can
    actually create (FORM_FIELDS keys are resource paths)."""
    from kube_scheduler_simulator_tpu.cluster.store import RESOURCES

    src = _forms_js()
    kinds = re.findall(r"^  (\w+): \[", src, re.M)
    assert set(kinds) <= set(RESOURCES), kinds
    # the seven simulator GVRs all get a dialog
    assert {"pods", "nodes", "namespaces", "persistentvolumes",
            "persistentvolumeclaims", "storageclasses",
            "priorityclasses"} <= set(kinds)


def test_plugin_table_matches_registry():
    """The UI's structured plugin table must not drift from the server's
    plugin registry: same names/order, same filter/score points, same
    default weights (plugins/registry.py DEFAULT_ORDER)."""
    from kube_scheduler_simulator_tpu.plugins.registry import (
        DEFAULT_ORDER, PLUGIN_REGISTRY)

    src = _forms_js()
    rows = re.findall(
        r'\["(\w+)", (true|false), (true|false), (\d+)\]', src)
    assert [r[0] for r in rows] == DEFAULT_ORDER
    for name, has_f, has_s, weight in rows:
        desc = PLUGIN_REGISTRY[name]
        assert (has_f == "true") == desc.has_filter, name
        assert (has_s == "true") == desc.has_score, name
        if desc.has_score:
            assert int(weight) == desc.default_weight, name


def test_form_manifest_builder_paths():
    """The JS form->manifest builder writes the spec paths the scheduler
    engine reads (a Python mirror of buildManifest's field routing)."""
    src = _forms_js()
    # pod fields land under spec / container 0
    for needle in ["spec.nodeSelector = sel", "spec.priorityClassName",
                   "spec.schedulerName", "spec.tolerations = tol",
                   "c0.resources.requests.cpu",
                   "c0.resources.requests.memory"]:
        assert needle.split(" = ")[0].split(".")[-1] in src, needle
    assert "obj.status.capacity" in src and "obj.status.allocatable" in src
    assert ".taints = taints" in src
    assert "volumeBindingMode" in src and "globalDefault" in src


def test_plugin_apply_diff_semantics_mirror():
    """Python transcription of forms.js applyPluginStateToConfig's diff
    algebra, checked over the wildcard/per-point cases the JS must
    preserve: an untouched Apply is a no-op; disabling adds a multiPoint
    disable and strips enabled entries; enabling under a wildcard lists
    the plugin; weight changes upsert into score.enabled."""
    src = _forms_js()

    # the mirror follows the JS block-for-block; drift in the JS shows up
    # as a failing textual anchor below before the semantics can diverge
    for anchor in ["st.enabled !== init.enabled",
                   "wildcardOff && !(mp.enabled || [])",
                   "+st.weight !== +init.weight",
                   "sc.enabled = sc.enabled || []"]:
        assert anchor in src, anchor

    def apply_diff(cfg, state, initial, table):
        profiles = cfg.setdefault("profiles", [{"schedulerName": "d"}])
        plugins = profiles[0].setdefault("plugins", {})
        mp = plugins.setdefault("multiPoint", {})
        wildcard_off = any(d.get("name") == "*"
                           for d in mp.get("disabled", []))
        for name, has_score in table:
            st, init = state[name], initial[name]
            if st["enabled"] != init["enabled"]:
                if not st["enabled"]:
                    for point in plugins.values():
                        if point.get("enabled"):
                            point["enabled"] = [
                                e for e in point["enabled"]
                                if e["name"] != name]
                    if not wildcard_off and not any(
                            d.get("name") == name
                            for d in mp.get("disabled", [])):
                        mp.setdefault("disabled", []).append({"name": name})
                else:
                    for point in plugins.values():
                        if point.get("disabled"):
                            point["disabled"] = [
                                d for d in point["disabled"]
                                if d["name"] != name]
                    if wildcard_off and not any(
                            e.get("name") == name
                            for e in mp.get("enabled", [])):
                        mp.setdefault("enabled", []).append({"name": name})
            if has_score and st["enabled"] and st["weight"] != init["weight"]:
                sc = plugins.setdefault("score", {})
                entry = next((e for e in sc.setdefault("enabled", [])
                              if e["name"] == name), None)
                if entry:
                    entry["weight"] = st["weight"]
                else:
                    sc["enabled"].append({"name": name,
                                          "weight": st["weight"]})
        return cfg

    table = [("A", True), ("B", False), ("C", True)]

    # 1) untouched Apply preserves a wildcard + enabled-list config
    cfg = {"profiles": [{"plugins": {"multiPoint": {
        "disabled": [{"name": "*"}], "enabled": [{"name": "A"}]}}}]}
    init = {"A": {"enabled": True, "weight": 1},
            "B": {"enabled": False, "weight": 0},
            "C": {"enabled": False, "weight": 1}}
    state = {k: dict(v) for k, v in init.items()}
    out = apply_diff(json.loads(json.dumps(cfg)), state, init, table)
    assert out == cfg  # byte-identical: nothing was touched

    # 2) enabling C under the wildcard lists it; A stays listed
    state["C"] = {"enabled": True, "weight": 1}
    out = apply_diff(json.loads(json.dumps(cfg)), state, init, table)
    mp = out["profiles"][0]["plugins"]["multiPoint"]
    assert {"name": "*"} in mp["disabled"]
    assert {"name": "C"} in mp["enabled"] and {"name": "A"} in mp["enabled"]

    # 3) disabling A in a NON-wildcard config adds one disable and strips
    #    its per-point enabled entry
    cfg2 = {"profiles": [{"plugins": {"score": {
        "enabled": [{"name": "A", "weight": 5}]}}}]}
    init2 = {"A": {"enabled": True, "weight": 5},
             "B": {"enabled": True, "weight": 0},
             "C": {"enabled": True, "weight": 1}}
    st2 = {k: dict(v) for k, v in init2.items()}
    st2["A"]["enabled"] = False
    out2 = apply_diff(json.loads(json.dumps(cfg2)), st2, init2, table)
    p2 = out2["profiles"][0]["plugins"]
    assert p2["multiPoint"]["disabled"] == [{"name": "A"}]
    assert p2["score"]["enabled"] == []

    # 4) weight change upserts into score.enabled without other edits
    st3 = {k: dict(v) for k, v in init2.items()}
    st3["C"]["weight"] = 7
    out3 = apply_diff(json.loads(json.dumps(cfg2)), st3, init2, table)
    sc = out3["profiles"][0]["plugins"]["score"]["enabled"]
    assert {"name": "C", "weight": 7} in sc
    assert {"name": "A", "weight": 5} in sc
