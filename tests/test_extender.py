"""Extender round-trip tests: a real HTTP extender server, the recording
proxy, the phased engine path, and the 4 extender annotations.

Mirrors the reference extender flow (SURVEY.md §3.3): scheduler -> proxy
-> real extender -> record -> respond.
"""

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from kube_scheduler_simulator_tpu.cluster.store import ObjectStore
from kube_scheduler_simulator_tpu.framework.engine import SchedulerEngine
from kube_scheduler_simulator_tpu.models.workloads import make_nodes, make_pods
from kube_scheduler_simulator_tpu.scheduler.extender import (
    ExtenderService,
    override_extenders_cfg_to_simulator,
)
from kube_scheduler_simulator_tpu.scheduler.service import SchedulerService
from kube_scheduler_simulator_tpu.store import annotations as ann


class FakeExtender(BaseHTTPRequestHandler):
    """A user extender that vetoes node index 0 and boosts the last node."""

    calls: list[tuple[str, dict]] = []

    def log_message(self, *a):
        pass

    def do_POST(self):
        body = json.loads(self.rfile.read(int(self.headers["Content-Length"])))
        FakeExtender.calls.append((self.path, body))
        names = body.get("NodeNames") or []
        if self.path.endswith("/filter"):
            kept = [n for n in names if not n.endswith("00000")]
            resp = {"NodeNames": kept, "FailedNodes": {n: "vetoed by extender"
                                                       for n in names if n.endswith("00000")}}
        elif self.path.endswith("/prioritize"):
            resp = [{"Host": n, "Score": 10 if n == names[-1] else 0} for n in names]
        elif self.path.endswith("/bind"):
            resp = {}
        elif self.path.endswith("/preempt"):
            # keep only the lexicographically LAST candidate node; answer
            # with the canonical ExtenderPreemptionResult contract:
            # nodeNameToMetaVictims carrying MetaPod uids
            victims = body.get("NodeNameToVictims") or {}
            keep = max(victims) if victims else None
            resp = {"nodeNameToMetaVictims": {
                keep: {"pods": [
                    {"uid": (v.get("metadata") or {}).get("uid", "")}
                    for v in victims[keep].get("Pods") or []]}} if keep else {}}
        else:
            resp = {}
        data = json.dumps(resp).encode()
        self.send_response(200)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)


@pytest.fixture()
def fake_extender():
    FakeExtender.calls = []
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), FakeExtender)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    yield f"http://127.0.0.1:{httpd.server_address[1]}"
    httpd.shutdown()


def extender_cfg(url):
    return {"urlPrefix": url, "filterVerb": "filter", "prioritizeVerb": "prioritize",
            "weight": 2}


def test_override_cfg_rewrites_urls():
    cfg = {"extenders": [extender_cfg("http://real-extender:8080/api")]}
    out = override_extenders_cfg_to_simulator(cfg, 1212)
    e = out["extenders"][0]
    assert e["urlPrefix"] == "http://localhost:1212/api/v1/extender"
    assert e["filterVerb"] == "filter/0"
    assert e["prioritizeVerb"] == "prioritize/0"


def test_extender_proxy_records(fake_extender):
    svc = ExtenderService([extender_cfg(fake_extender)])
    pod = {"metadata": {"name": "p", "namespace": "default"}}
    result = svc.handle("filter", 0, {"Pod": pod, "NodeNames": ["node-00000", "node-00001"]})
    assert result["NodeNames"] == ["node-00001"]
    stored = svc.result_store.get_stored_result(pod)
    blob = json.loads(stored[ann.EXTENDER_FILTER_RESULT])
    host = list(blob)[0]
    assert blob[host]["failedNodes"]["node-00000"] == "vetoed by extender"


def test_engine_phased_path_with_extender(fake_extender):
    store = ObjectStore()
    for n in make_nodes(3, seed=9):
        store.create("nodes", n)
    for p in make_pods(2, seed=10):
        store.create("pods", p)
    engine = SchedulerEngine(store)
    svc = SchedulerService(engine)
    cfg = svc.get_config()
    cfg["extenders"] = [extender_cfg(fake_extender)]
    svc.restart_scheduler(cfg)

    bound = engine.schedule_pending()
    assert bound == 2
    p = store.get("pods", "pod-00000")
    # extender vetoed node-00000 -> never selected
    assert p["spec"]["nodeName"] != "node-00000"
    annos = p["metadata"]["annotations"]
    ext_filter = json.loads(annos[ann.EXTENDER_FILTER_RESULT])
    assert any("vetoed by extender" in json.dumps(v) for v in ext_filter.values())
    assert ann.EXTENDER_PRIORITIZE_RESULT in annos
    # plugin annotations still present alongside extender ones
    assert ann.FILTER_RESULT in annos
    # score maps cover only post-extender feasible nodes
    fs = json.loads(annos[ann.FINAL_SCORE_RESULT])
    assert "node-00000" not in fs


def test_filter_response_error_field_fails_unless_ignorable():
    """An ExtenderFilterResult carrying Error is a failed call even over
    HTTP 200 (upstream HTTPExtender.Filter): unignorable -> the pod's
    cycle aborts; ignorable -> the extender is skipped."""
    import numpy as np

    from kube_scheduler_simulator_tpu.framework.engine import SchedulerEngine

    class FakeExt:
        filter_verb = "filter"
        weight = 1

        def __init__(self, ignorable):
            self.ignorable = ignorable

        def is_interested(self, pod):
            return True

    class FakeSvc:
        def __init__(self, ignorable):
            self.extenders = [FakeExt(ignorable)]

        def handle(self, verb, idx, args):
            return {"NodeNames": None, "Error": "extender exploded"}

    for ignorable, want_abort in ((False, True), (True, False)):
        eng = SchedulerEngine(ObjectStore())
        eng.extender_service = FakeSvc(ignorable)
        feasible = np.array([True, True])
        aborted = eng._webhook_filter({}, ["n0", "n1"], {"n0": 0, "n1": 1},
                                      feasible)
        assert aborted is want_abort, f"ignorable={ignorable}"
        assert feasible.all()  # an errored extender never narrows nodes


def test_prioritize_scores_scaled_weight_times_ten():
    """reference extender.go:145: Score x weight x (MaxNodeScore /
    MaxExtenderPriority) — an extender priority of 1 at weight 1 adds 10
    node-score points, enough to beat a 9-point plugin-score edge."""
    import numpy as np

    from kube_scheduler_simulator_tpu.framework.engine import SchedulerEngine

    class FakeExt:
        prioritize_verb = "prioritize"
        weight = 1

        def is_interested(self, pod):
            return True

    class FakeSvc:
        extenders = [FakeExt()]

        def handle(self, verb, idx, args):
            assert verb == "prioritize"
            return [{"Host": "n0", "Score": 1}]   # max extender pref: small raw

    eng = SchedulerEngine(ObjectStore())
    eng.extender_service = FakeSvc()
    names = ["n0", "n1"]
    total = np.array([0, 9], dtype=np.int64)      # n1 ahead by 9 plugin points
    eng._webhook_prioritize({}, names, {"n0": 0, "n1": 1},
                            np.array([True, True]), total)
    assert total.tolist() == [10, 9]              # x10 rescale flips the winner


def test_managed_resources_interest_gate():
    """Upstream HTTPExtender.IsInterested: an extender declaring
    managedResources is only called for pods requesting one of them
    (containers or initContainers, requests or limits)."""
    from kube_scheduler_simulator_tpu.scheduler.extender import ExtenderClient

    ext = ExtenderClient({"urlPrefix": "http://x", "filterVerb": "filter",
                          "managedResources": [{"name": "example.com/gpu"}]})
    plain = {"spec": {"containers": [
        {"name": "c", "resources": {"requests": {"cpu": "1"}}}]}}
    gpu = {"spec": {"containers": [
        {"name": "c", "resources": {"limits": {"example.com/gpu": "2"}}}]}}
    init_gpu = {"spec": {"containers": [{"name": "c"}],
                         "initContainers": [{"name": "i", "resources": {
                             "requests": {"example.com/gpu": "1"}}}]}}
    assert not ext.is_interested(plain)
    assert ext.is_interested(gpu)
    assert ext.is_interested(init_gpu)
    # no managedResources -> interested in every pod
    ext_all = ExtenderClient({"urlPrefix": "http://x", "filterVerb": "filter"})
    assert ext_all.is_interested(plain)


def _capacity_node(name):
    return {"metadata": {"name": name},
            "status": {"allocatable": {"cpu": "2", "memory": "4Gi", "pods": "10"}}}


def _prio_pod(name, prio, cpu="2", node=None):
    spec = {"priority": prio, "containers": [
        {"name": "c", "resources": {"requests": {"cpu": cpu, "memory": "1Gi"}}}]}
    if node:
        spec["nodeName"] = node
    return {"kind": "Pod", "metadata": {"name": name}, "spec": spec}


def test_extender_preempt_round_trip(fake_extender):
    """A preemptVerb extender narrows the candidate set during a
    preemption wave (upstream callExtenders), and the round-trip lands in
    the extender-preempt-result annotation (VERDICT round-1 missing #4)."""
    store = ObjectStore()
    for name in ("node-a", "node-b"):
        store.create("nodes", _capacity_node(name))
        store.create("pods", _prio_pod(f"victim-{name}", 0, node=name))
    store.create("pods", _prio_pod("urgent", 100))

    engine = SchedulerEngine(store)
    svc = SchedulerService(engine)
    cfg = svc.get_config()
    cfg["extenders"] = [{"urlPrefix": fake_extender, "preemptVerb": "preempt"}]
    svc.restart_scheduler(cfg)

    assert engine.schedule_pending() == 1
    urgent = store.get("pods", "urgent")
    # without the extender, pickOneNode's node-order tie-break nominates
    # node-a; the extender kept only the LAST candidate -> node-b
    assert urgent["spec"].get("nodeName") == "node-b"
    with pytest.raises(Exception):
        store.get("pods", "victim-node-b")  # the victim was deleted
    store.get("pods", "victim-node-a")      # the other survived
    annos = urgent["metadata"]["annotations"]
    preempt_blob = json.loads(annos[ann.EXTENDER_PREEMPT_RESULT])
    host = list(preempt_blob)[0]
    # the recorded result is the canonical wire form of the response
    assert preempt_blob[host]["nodeNameToMetaVictims"].keys() == {"node-b"}
    assert preempt_blob[host]["nodeNameToMetaVictims"]["node-b"]["pods"][0]["uid"]
    # the nomination cycle's postfilter-result lives in the first
    # result-history entry (the retry cycle overwrote the live keys)
    history = json.loads(annos[ann.RESULT_HISTORY])
    pf = json.loads(history[0][ann.POST_FILTER_RESULT])
    assert pf["node-b"] == {"DefaultPreemption": "preemption victim"}


def test_extender_preempt_unignorable_error_aborts(fake_extender):
    from kube_scheduler_simulator_tpu.framework.preemption import Preemptor
    from kube_scheduler_simulator_tpu.plugins.registry import PluginSetConfig

    store = ObjectStore()
    store.create("nodes", _capacity_node("node-a"))
    store.create("pods", _prio_pod("victim", 0, node="node-a"))
    dead = ExtenderService([{"urlPrefix": "http://127.0.0.1:1", "preemptVerb": "preempt"}])
    pre = Preemptor(store, PluginSetConfig(enabled=["NodeResourcesFit"]),
                    extender_service=dead)
    out = pre.preempt(_prio_pod("urgent", 100),
                      [("node-a", "NodeResourcesFit")])
    assert out.nominated_node == ""  # aborted, not nominated

    # ignorable: same failure is skipped and preemption proceeds
    lenient = ExtenderService([{"urlPrefix": "http://127.0.0.1:1",
                                "preemptVerb": "preempt", "ignorable": True}])
    pre2 = Preemptor(store, PluginSetConfig(enabled=["NodeResourcesFit"]),
                     extender_service=lenient)
    out2 = pre2.preempt(_prio_pod("urgent", 100),
                        [("node-a", "NodeResourcesFit")])
    assert out2.nominated_node == "node-a"


def test_ignorable_extender_failure():
    svc = ExtenderService([
        {"urlPrefix": "http://127.0.0.1:1", "filterVerb": "filter", "ignorable": True}
    ])
    store = ObjectStore()
    for n in make_nodes(2, seed=11):
        store.create("nodes", n)
    store.create("pods", make_pods(1, seed=12)[0])
    engine = SchedulerEngine(store)
    engine.set_extenders(svc)
    assert engine.schedule_pending() == 1  # unreachable but ignorable


def test_bind_extender_replaces_default_binder_record(fake_extender):
    """With a bindVerb extender, upstream's extendersBinding runs instead
    of the Bind plugins: bind-result stays {} while extender-bind-result
    records the round-trip."""
    store = ObjectStore()
    for n in make_nodes(2, seed=21):
        store.create("nodes", n)
    for p in make_pods(1, seed=22):
        store.create("pods", p)
    engine = SchedulerEngine(store)
    svc = SchedulerService(engine)
    cfg = svc.get_config()
    cfg["extenders"] = [{"urlPrefix": fake_extender, "bindVerb": "bind",
                         "filterVerb": "filter", "weight": 1}]
    svc.restart_scheduler(cfg)
    assert engine.schedule_pending() == 1
    p = store.get("pods", "pod-00000")
    annos = p["metadata"]["annotations"]
    assert p["spec"]["nodeName"]
    assert annos[ann.BIND_RESULT] == "{}"
    assert json.loads(annos[ann.EXTENDER_BIND_RESULT])  # round-trip recorded


def test_service_routing_edges(fake_extender):
    """service_test.go routing: per-index dispatch; out-of-range index and
    unknown verb are errors (the HTTP handler turns them into 4xx)."""
    svc = ExtenderService([extender_cfg(fake_extender)])
    with pytest.raises(IndexError):
        svc.handle("filter", 1, {"Pod": {}, "NodeNames": []})
    with pytest.raises(IndexError):
        svc.handle("filter", -1, {"Pod": {}, "NodeNames": []})
    with pytest.raises(ValueError):
        svc.handle("frobnicate", 0, {})
