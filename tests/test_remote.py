"""Remote-cluster client + standalone scheduler/recorder process topology.

Exercises the out-of-process architecture of the reference (scheduler and
sched-recorder as separate processes talking to the apiserver over HTTP,
reference: compose.yml:1-73) — here, RemoteCluster against a live
SimulatorServer running with the in-process scheduler disabled (the KWOK
disableKubeScheduler analogue).
"""

import json
import time

import pytest

from kube_scheduler_simulator_tpu.cluster.remote import RemoteCluster
from kube_scheduler_simulator_tpu.cluster.store import AlreadyExists, Conflict, NotFound
from kube_scheduler_simulator_tpu.config.config import SimulatorConfiguration
from kube_scheduler_simulator_tpu.framework.engine import SchedulerEngine
from kube_scheduler_simulator_tpu.models.workloads import make_nodes, make_pods
from kube_scheduler_simulator_tpu.server.di import DIContainer
from kube_scheduler_simulator_tpu.server.server import SimulatorServer
from kube_scheduler_simulator_tpu.services.recorder import RecorderService
from kube_scheduler_simulator_tpu.store import annotations as ann


@pytest.fixture()
def sim():
    """Server with the in-process scheduling loop OFF."""
    cfg = SimulatorConfiguration(port=0, external_scheduler_enabled=True)
    di = DIContainer(cfg, start_scheduler=not cfg.external_scheduler_enabled)
    srv = SimulatorServer(di, port=0)
    srv.start(block=False)
    remote = RemoteCluster(f"http://127.0.0.1:{srv.port}")
    yield srv, remote
    remote.close()
    srv.shutdown()


def test_remote_crud_and_errors(sim):
    srv, remote = sim
    node = make_nodes(1, seed=5)[0]
    created = remote.create("nodes", node)
    assert created["metadata"]["uid"]
    with pytest.raises(AlreadyExists):
        remote.create("nodes", node)

    got = remote.get("nodes", node["metadata"]["name"])
    assert got["metadata"]["name"] == node["metadata"]["name"]

    got["metadata"]["labels"] = {"zone": "z1"}
    updated = remote.update("nodes", got)
    assert updated["metadata"]["labels"]["zone"] == "z1"

    # stale-rv write → Conflict, like the apiserver
    got["metadata"]["resourceVersion"] = "1"
    with pytest.raises(Conflict):
        remote.update("nodes", got)

    items, rv = remote.list("nodes")
    assert len(items) == 1 and rv > 0
    items, _ = remote.list("nodes", label_selector={"matchLabels": {"zone": "z1"}})
    assert len(items) == 1
    items, _ = remote.list("nodes", label_selector={"matchLabels": {"zone": "nope"}})
    assert items == []

    remote.delete("nodes", node["metadata"]["name"])
    with pytest.raises(NotFound):
        remote.get("nodes", node["metadata"]["name"])


def test_remote_watch_stream(sim):
    srv, remote = sim
    q = remote.watch("pods")
    pod = make_pods(1, seed=6)[0]
    remote.create("pods", pod)
    rv, event_type, obj = q.get(timeout=10)
    assert event_type == "ADDED"
    assert obj["metadata"]["name"] == pod["metadata"]["name"]
    remote.unwatch("pods", q)


def test_remote_watch_no_duplicate_initial_events(sim):
    """An object that existed before the stream connected arrives exactly
    once (listing ADDED), not twice (listing + event-ring replay)."""
    srv, remote = sim
    node = make_nodes(1, seed=61)[0]
    remote.create("nodes", node)
    q = remote.watch("nodes")
    events = []
    deadline = time.time() + 3
    while time.time() < deadline:
        try:
            events.append(q.get(timeout=0.3))
        except Exception:
            pass
    added = [e for e in events
             if e[1] == "ADDED" and e[2]["metadata"]["name"] == node["metadata"]["name"]]
    assert len(added) == 1, f"expected 1 ADDED, got {len(added)}"
    remote.unwatch("nodes", q)


def test_remote_watch_late_registration_replays_initial_state(sim):
    """A watcher registered after the shared stream already delivered the
    initial listing still sees it (buffered replay) — the recorder
    subscribes to 7 kinds sequentially and must miss none."""
    srv, remote = sim
    node = make_nodes(1, seed=60)[0]
    remote.create("nodes", node)
    q_pods = remote.watch("pods")  # starts the shared stream
    # wait until the stream has delivered the nodes listing
    deadline = time.time() + 10
    while time.time() < deadline:
        with remote._lock:
            if remote._events["nodes"]:
                break
        time.sleep(0.05)
    q_nodes = remote.watch("nodes")  # late: after the initial listing
    rv, event_type, obj = q_nodes.get(timeout=10)
    assert event_type == "ADDED"
    assert obj["metadata"]["name"] == node["metadata"]["name"]
    remote.unwatch("pods", q_pods)
    remote.unwatch("nodes", q_nodes)


def test_standalone_scheduler_over_http(sim):
    """The cmd/scheduler flow: engine in 'another process' drives the
    simulator over HTTP; bindings and annotations land via PUT."""
    srv, remote = sim
    for n in make_nodes(3, seed=7):
        remote.create("nodes", n)
    pods = make_pods(4, seed=8)
    for p in pods:
        remote.create("pods", p)

    engine = SchedulerEngine(remote)  # own reflector over the remote store
    n = engine.schedule_pending()
    assert n == 4

    for p in pods:
        got = remote.get("pods", p["metadata"]["name"],
                         p["metadata"].get("namespace"))
        assert got["spec"].get("nodeName")
        anns = got["metadata"]["annotations"]
        assert ann.SELECTED_NODE in anns
        assert ann.FINAL_SCORE_RESULT in anns
        json.loads(anns[ann.FINAL_SCORE_RESULT])


def test_remote_watch_reconnect_resumes_without_duplicates(sim):
    """After a dropped stream, the client reconnects with per-kind
    *LastResourceVersion params: pre-drop objects are NOT re-delivered as
    ADDED, and post-drop events still arrive."""
    srv, remote = sim
    node = make_nodes(2, seed=62)[0]
    remote.create("nodes", node)
    q = remote.watch("nodes")
    rv, et, obj = q.get(timeout=10)
    assert et == "ADDED"

    remote._abort_stream()  # simulate a dropped connection
    time.sleep(1.0)         # reconnect loop (0.5s backoff)

    node2 = make_nodes(2, seed=62)[1]
    remote.create("nodes", node2)
    events = []
    deadline = time.time() + 10
    while time.time() < deadline and len(events) < 1:
        try:
            events.append(q.get(timeout=0.5))
        except Exception:
            pass
    # drain briefly to catch any duplicate re-listing
    deadline = time.time() + 1.5
    while time.time() < deadline:
        try:
            events.append(q.get(timeout=0.3))
        except Exception:
            pass
    names = [e[2]["metadata"]["name"] for e in events if e[1] == "ADDED"]
    assert node2["metadata"]["name"] in names
    assert node["metadata"]["name"] not in names, "pre-drop object re-delivered"


def test_recorder_over_remote(sim, tmp_path):
    srv, remote = sim
    path = tmp_path / "record.jsonl"
    rec = RecorderService(remote, str(path), flush_interval=0.1)
    rec.run()
    node = make_nodes(1, seed=9)[0]
    remote.create("nodes", node)
    remote.delete("nodes", node["metadata"]["name"])
    deadline = time.time() + 10
    while time.time() < deadline:
        lines = [json.loads(l) for l in path.read_text().splitlines()] if path.exists() else []
        if len(lines) >= 2:
            break
        time.sleep(0.1)
    rec.stop()
    events = [l["event"] for l in lines]
    assert "Add" in events and "Delete" in events
    dels = [l for l in lines if l["event"] == "Delete"]
    # delete records keep only identity fields (recorder.go:121-133)
    assert set(dels[0]["resource"].keys()) == {"apiVersion", "kind", "metadata"}
