"""Web-UI verification at the highest level this environment allows.

The reference ships a Nuxt app driven by a real browser; this image has
NO JavaScript runtime (no node/bun/chromium, no selenium/playwright), so
true DOM execution is impossible here.  Two layers compensate:

1. test_browser_drive — the real headless-browser test (create node+pod,
   assert the score/filter tables and history drawer render from live
   annotations).  It runs whenever selenium + a chromium binary are
   present and SKIPS with instructions otherwise, so any environment
   with a browser exercises the shipped JS end-to-end:
       pip install selenium && apt install chromium-driver
       python -m pytest tests/test_web_ui_browser.py -k browser
2. test_ui_contract_* — executable-contract tests against the LIVE
   server: every asset index.html loads resolves; every endpoint api.js
   calls answers; the pod payload carries exactly the annotation keys
   components.js reads (ANN + selected-node / finalscore-result /
   result-history, components.js:223-260) with the JSON shapes the
   render code indexes into.
"""

from __future__ import annotations

import json
import re
import threading
import urllib.request

import pytest

from kube_scheduler_simulator_tpu.config.config import SimulatorConfiguration
from kube_scheduler_simulator_tpu.server.di import DIContainer
from kube_scheduler_simulator_tpu.server.server import SimulatorServer

ANN = "kube-scheduler-simulator.sigs.k8s.io/"


@pytest.fixture()
def live_server():
    di = DIContainer(SimulatorConfiguration(), start_scheduler=True)
    srv = SimulatorServer(di, port=0)
    srv.start(block=False)
    base = f"http://localhost:{srv.port}"
    _post(base, "/api/v1/nodes", {
        "metadata": {"name": "node-a"},
        "status": {"allocatable": {"cpu": "4", "memory": "8Gi", "pods": "10"}}})
    _post(base, "/api/v1/nodes", {
        "metadata": {"name": "node-b"},
        "status": {"allocatable": {"cpu": "2", "memory": "4Gi", "pods": "10"}}})
    _post(base, "/api/v1/pods", {
        "metadata": {"name": "ui-pod", "namespace": "default"},
        "spec": {"containers": [
            {"name": "c", "resources": {"requests": {"cpu": "1",
                                                     "memory": "1Gi"}}}]}})
    # wait for the scheduling loop to bind + reflect
    import time

    for _ in range(80):
        pod = _get(base, "/api/v1/pods/default/ui-pod")
        if (pod.get("spec") or {}).get("nodeName"):
            break
        time.sleep(0.1)
    yield base
    srv.httpd.shutdown()
    di.shutdown()


def _get(base, path):
    with urllib.request.urlopen(base + path, timeout=10) as r:
        body = r.read()
        return json.loads(body) if body.strip().startswith(b"{") else body


def _post(base, path, obj):
    req = urllib.request.Request(
        base + path, data=json.dumps(obj).encode(), method="POST",
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=10) as r:
        return json.loads(r.read())


def _browser_available():
    try:
        import selenium  # noqa: F401
    except ImportError:
        return False
    import shutil

    return any(shutil.which(b) for b in
               ("chromium", "chromium-browser", "google-chrome"))


@pytest.mark.skipif(not _browser_available(),
                    reason="no selenium+chromium in this image; see module "
                           "docstring for how to run the browser layer")
def test_browser_drive(live_server):
    """Real-DOM drive: the pods table renders, clicking the scheduled pod
    opens the result drawer with filter/score tables and the history
    viewer, all fed from live annotations."""
    from selenium import webdriver
    from selenium.webdriver.common.by import By
    from selenium.webdriver.support.ui import WebDriverWait

    opts = webdriver.ChromeOptions()
    opts.add_argument("--headless=new")
    opts.add_argument("--no-sandbox")
    driver = webdriver.Chrome(options=opts)
    try:
        driver.get(live_server + "/")
        wait = WebDriverWait(driver, 15)
        wait.until(lambda d: "ui-pod" in d.page_source)
        row = driver.find_element(By.XPATH, "//td[contains(.,'ui-pod')]")
        row.click()
        wait.until(lambda d: d.find_element(By.ID, "drawer").is_displayed())
        drawer = driver.find_element(By.ID, "drawer").text
        assert "finalscore" in drawer.lower() or "score" in drawer.lower()
        assert "node-a" in drawer or "node-b" in drawer
        assert "history" in drawer.lower()
    finally:
        driver.quit()


def test_ui_contract_assets_resolve(live_server):
    """Every script/style index.html references is actually served."""
    html = _get(live_server, "/").decode()
    refs = re.findall(r'(?:src|href)="(/[^"]+)"', html)
    assert refs, "index.html references no local assets?"
    for ref in refs:
        body = _get(live_server, ref)
        assert body, f"empty asset {ref}"
    for el_id in ("nav", "content", "drawer", "livedot"):
        assert f'id="{el_id}"' in html


def test_ui_contract_api_surface(live_server):
    """Every endpoint api.js calls answers with the shape the JS indexes."""
    # API.list(r) for the resource tables
    for r in ("nodes", "pods"):
        out = _get(live_server, f"/api/v1/{r}")
        assert isinstance(out["items"], list)
    assert "profiles" in _get(live_server, "/api/v1/schedulerconfiguration")
    snap = _get(live_server, "/api/v1/export")
    assert {"nodes", "pods", "schedulerConfig"} <= set(snap)
    metrics = _get(live_server, "/api/v1/metrics")
    assert metrics
    scenarios = _get(live_server, "/api/v1/scenarios")
    assert scenarios is not None


def test_ui_contract_annotations_feed_the_drawer(live_server):
    """The pod object carries every annotation key components.js reads,
    in the exact shapes its render code indexes (components.js:223-260:
    selected-node string; finalscore-result {node: {plugin: "int"}};
    result-history JSON array of records with selected-node)."""
    pod = _get(live_server, "/api/v1/pods/default/ui-pod")
    assert pod["spec"]["nodeName"] in ("node-a", "node-b")
    anns = pod["metadata"]["annotations"]
    assert anns[ANN + "selected-node"] == pod["spec"]["nodeName"]

    final = json.loads(anns[ANN + "finalscore-result"])
    assert set(final) == {"node-a", "node-b"}
    for node, per_plugin in final.items():
        for plugin, val in per_plugin.items():
            int(val)  # the UI renders these as numeric cells

    filt = json.loads(anns[ANN + "filter-result"])
    assert set(filt) == {"node-a", "node-b"}
    for per_plugin in filt.values():
        assert all(isinstance(v, str) for v in per_plugin.values())

    hist = json.loads(anns[ANN + "result-history"])
    assert isinstance(hist, list) and hist
    assert hist[-1][ANN + "selected-node"] == pod["spec"]["nodeName"]
