"""Causal telemetry plane (docs/metrics.md "History & correlation").

Covers the columnar history ring's unit contract (append/window
round-trip, absolute-index cursors across wraparound, stride, series
and session filters, NaN -> null, value(), drop_session), the feeder
(counter deltas, per-session SLO/effector columns, the disabled no-op
parity shape), trace correlation (trace_scope nesting, span stamping,
the consume-once session -> trace handoff, Perfetto's trace_id filter
with black-box instants), the X-KSS-Trace-Id HTTP contract end to end
against a live server, the `/api/v1/history` surface + sessions alias,
the KSS_TPU_TRACER_CAPACITY knob with its /readyz drop counter, and
the history window embedded in post-mortem bundles.
"""

from __future__ import annotations

import json
import urllib.error
import urllib.request

import pytest

from kube_scheduler_simulator_tpu.config.config import SimulatorConfiguration
from kube_scheduler_simulator_tpu.server.server import SimulatorServer
from kube_scheduler_simulator_tpu.server.sessions import SessionManager
from kube_scheduler_simulator_tpu.utils import history
from kube_scheduler_simulator_tpu.utils.blackbox import (
    BLACKBOX, FEEDER, SLO, validate_dump)
from kube_scheduler_simulator_tpu.utils.history import (
    HISTORY, TelemetryHistory)
from kube_scheduler_simulator_tpu.utils.tracing import TRACER, Tracer


@pytest.fixture(autouse=True)
def _enabled_clean_ring():
    """Every test sees an enabled, empty singleton ring and fresh
    feeder baselines; leaked rows would shift other tests' indices."""
    prev = history.set_enabled(True)
    HISTORY.reset()
    FEEDER.reset()
    yield
    HISTORY.reset()
    FEEDER.reset()
    history.set_enabled(prev)


# ------------------------------------------------------- ring contract


def test_append_window_roundtrip_and_nan_null():
    h = TelemetryHistory(capacity=16)
    assert h.append({"a": 1.0, "b": 2.0}, t_us=1_000_000) == 0
    assert h.append({"a": 3.0}, t_us=2_000_000) == 1
    win = h.window()
    assert win["index"] == [0, 1]
    assert win["t"] == [1.0, 2.0]
    # series b was absent at sample 1: NaN stored, null served
    assert win["series"]["a"] == [1.0, 3.0]
    assert win["series"]["b"] == [2.0, None]
    assert win["nextIndex"] == 2 and win["capacity"] == 16
    # a series born late reads null for its pre-history
    h.append({"c": 9.0}, t_us=3_000_000)
    assert h.window()["series"]["c"] == [None, None, 9.0]


def test_absolute_indices_survive_wraparound():
    h = TelemetryHistory(capacity=16)
    for i in range(40):
        h.append({"x": float(i)}, t_us=i)
    win = h.window(since=0)
    # the ring holds the newest 16; indices stay absolute — a cursor
    # that fell behind sees the floor move, never recycled rows
    assert win["index"] == list(range(24, 40))
    assert win["series"]["x"] == [float(i) for i in range(24, 40)]
    assert win["nextIndex"] == 40
    # cursors: since= inside the ring honors it exactly
    assert h.window(since=30)["index"] == list(range(30, 40))
    # value() refuses scrolled-out indices instead of aliasing slots
    assert h.value("x", 23) is None
    assert h.value("x", 24) == 24.0
    assert h.value("x", 39) == 39.0
    assert h.value("x", 40) is None
    assert h.value("nope", 39) is None


def test_window_stride_limit_series_and_session_filters():
    h = TelemetryHistory(capacity=64)
    for i in range(10):
        h.append({"g": float(i),
                  "slo.p99{session=a}": float(i) / 10,
                  "slo.p99{session=b}": float(i) / 100}, t_us=i)
    assert h.window(stride=3)["index"] == [0, 3, 6, 9]
    assert h.window(limit=2)["index"] == [8, 9]
    # bare prefix matches every session's labeled column
    assert set(h.window(series=["slo.p99"])["series"]) == {
        "slo.p99{session=a}", "slo.p99{session=b}"}
    # full name matches exactly one
    assert set(h.window(series=["slo.p99{session=b}"])["series"]) == {
        "slo.p99{session=b}"}
    # session filter keeps that session's columns plus the globals
    assert set(h.window(session="a")["series"]) == {
        "g", "slo.p99{session=a}"}
    h.drop_session("a")
    assert set(h.window()["series"]) == {"g", "slo.p99{session=b}"}


def test_disabled_ring_appends_nothing_and_reports_it():
    h = TelemetryHistory(capacity=16)
    h.append({"x": 1.0}, t_us=1)
    prev = history.set_enabled(False)
    try:
        assert h.append({"x": 2.0}, t_us=2) == -1
        win = h.window()
        assert win["enabled"] is False
        assert win["index"] == [0]   # the pre-disable row survives
    finally:
        history.set_enabled(prev)


# ------------------------------------------------------------- feeder


def test_feeder_counter_deltas_and_session_columns():
    sid = "hist-feed"
    TRACER.inc("speculative_accepted_total", 90, session=sid)
    TRACER.inc("speculative_rolled_back_total", 10, session=sid)
    SLO.observe_wave(sid, 0.5, pods=10)
    idx, planes = FEEDER.sample()
    assert idx >= 0
    assert planes["slo"][sid]["p99WaveSeconds"] == 0.5
    assert HISTORY.value(f"spec.accept{{session={sid}}}", idx) == 0.9
    assert HISTORY.value(f"slo.p99{{session={sid}}}", idx) == 0.5
    # no controls overrides: the effector columns record the explicit
    # default state, not a gap
    assert HISTORY.value(f"autopilot.shed{{session={sid}}}", idx) == 0.0
    assert HISTORY.value(
        f"autopilot.budget_weight{{session={sid}}}", idx) == 1.0
    # deltas, not totals: a sample with no new rounds has no accept
    # fraction (None), and the spill delta resets to 0
    idx2, _planes = FEEDER.sample()
    assert HISTORY.value(f"spec.accept{{session={sid}}}", idx2) is None


def test_feeder_disabled_returns_planes_without_sampling():
    """The KSS_TPU_HISTORY=0 shape: one code path — the autopilot still
    plans from the same gathered planes, only the ring write drops."""
    sid = "hist-off"
    SLO.observe_wave(sid, 0.25, pods=5)
    prev = history.set_enabled(False)
    try:
        before = HISTORY.last_index()
        idx, planes = FEEDER.sample()
        assert idx == -1
        assert planes["slo"][sid]["p99WaveSeconds"] == 0.25
        assert HISTORY.last_index() == before
    finally:
        history.set_enabled(prev)


# -------------------------------------------------- trace correlation


def test_trace_scope_nesting_and_span_stamping():
    assert TRACER.current_trace() is None
    with TRACER.trace_scope("t-outer"):
        assert TRACER.current_trace() == "t-outer"
        with TRACER.trace_scope(None):   # None is a no-op, not a mask
            assert TRACER.current_trace() == "t-outer"
        with TRACER.trace_scope("t-inner"):
            assert TRACER.current_trace() == "t-inner"
            with TRACER.span("hist-span"):
                pass
        assert TRACER.current_trace() == "t-outer"
    assert TRACER.current_trace() is None
    ev = [e for e in TRACER.events(limit=50) if e["name"] == "hist-span"][-1]
    assert ev["trace_id"] == "t-inner"


def test_session_trace_handoff_is_consume_once():
    TRACER.note_session_trace("ho-sess", "t-once")
    assert TRACER.claim_session_trace("ho-sess") == "t-once"
    assert TRACER.claim_session_trace("ho-sess") is None
    assert TRACER.claim_session_trace(None) is None
    # latest note wins — a second request before the wave re-stamps
    TRACER.note_session_trace("ho-sess", "t-a")
    TRACER.note_session_trace("ho-sess", "t-b")
    assert TRACER.claim_session_trace("ho-sess") == "t-b"


def test_perfetto_filters_by_trace_id_with_blackbox_instants():
    with TRACER.trace_scope("t-pf"):
        with TRACER.span("pf-span"):
            BLACKBOX.record("pf.event", detail=1)
    with TRACER.trace_scope("t-other"):
        with TRACER.span("pf-other"):
            BLACKBOX.record("pf.other")
    # a fused dispatch carries EVERY participant's id in `traces`
    BLACKBOX.record("fuse.dispatch", result="fused", k=2,
                    traces=["t-pf", "t-third"])

    pf = TRACER.perfetto(trace_id="t-pf")
    spans = [e for e in pf["traceEvents"] if e.get("ph") == "X"]
    instants = [e for e in pf["traceEvents"] if e.get("ph") == "i"]
    assert [e["name"] for e in spans] == ["pf-span"]
    names = [e["name"] for e in instants]
    assert "pf.event" in names
    assert "fuse.dispatch" in names     # matched via the traces list
    assert "pf.other" not in names
    assert all(e["cat"] == "blackbox" and e["s"] == "p" for e in instants)
    # instants sit on the span timeline (non-negative µs since epoch)
    assert all(isinstance(e["ts"], int) and e["ts"] >= 0
               for e in instants)


# --------------------------------------------------- tracer capacity


def test_tracer_capacity_knob_and_drop_counter(monkeypatch):
    monkeypatch.setenv("KSS_TPU_TRACER_CAPACITY", "64")
    t = Tracer()
    assert t._events.maxlen == 64
    assert t.dropped_events() == 0
    for _ in range(70):
        with t.span("cap-span"):
            pass
    assert t.dropped_events() == 6
    assert t.counter_totals()["tracer_events_dropped_total"] == 6
    # the floor: a hostile tiny value can't wedge the flight recorder
    monkeypatch.setenv("KSS_TPU_TRACER_CAPACITY", "1")
    assert Tracer()._events.maxlen == 64


# --------------------------------------------------- HTTP end to end


@pytest.fixture()
def server(monkeypatch):
    # no background scheduler / slow autopilot: the test drives waves
    # itself so the trace handoff is deterministic
    monkeypatch.setenv("KSS_TPU_AUTOPILOT_INTERVAL_S", "60")
    mgr = SessionManager(cfg=SimulatorConfiguration(port=0),
                         max_sessions=4, start_scheduler=False,
                         idle_ttl=0)
    srv = SimulatorServer(mgr, port=0)
    srv.start(block=False)
    yield srv, mgr
    srv.shutdown()


def hreq(srv, method, path, body=None, headers=None):
    url = f"http://127.0.0.1:{srv.port}{path}"
    data = json.dumps(body).encode() if body is not None else None
    hdrs = {"Content-Type": "application/json"}
    hdrs.update(headers or {})
    r = urllib.request.Request(url, data=data, method=method, headers=hdrs)
    try:
        with urllib.request.urlopen(r, timeout=10) as resp:
            raw = resp.read()
            return (resp.status, dict(resp.headers),
                    json.loads(raw) if raw else None)
    except urllib.error.HTTPError as e:
        raw = e.read()
        return e.code, dict(e.headers), json.loads(raw) if raw else None


def _pod(name: str) -> dict:
    return {"apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": name, "namespace": "default"},
            "spec": {"containers": [{
                "name": "main", "image": "registry.k8s.io/pause:3.9",
                "resources": {"requests": {"cpu": "100m",
                                           "memory": str(128 << 20)}}}]}}


def test_http_trace_id_stamped_carried_and_retrievable(server):
    srv, mgr = server
    code, _h, _b = hreq(srv, "POST", "/api/v1/sessions", {"id": "tr-s"})
    assert code == 201
    sess = mgr.get("tr-s")
    for n in range(2):
        sess.di.store.create("nodes", {
            "apiVersion": "v1", "kind": "Node",
            "metadata": {"name": f"tr-n{n}"},
            "status": {"allocatable": {"cpu": "4",
                                       "memory": str(8 << 30),
                                       "pods": "110"}}})

    # inbound X-KSS-Trace-Id honored and echoed
    code, hdrs, _b = hreq(srv, "POST", "/api/v1/sessions/tr-s/pods",
                          _pod("tr-p0"),
                          headers={"X-KSS-Trace-Id": "t-http-42"})
    assert code == 201
    assert hdrs.get("X-KSS-Trace-Id") == "t-http-42"
    # the wave that schedules the submission claims the id
    sess.di.engine.schedule_pending()
    traced = [e for e in TRACER.events(limit=200)
              if e.get("trace_id") == "t-http-42"]
    assert traced and all(e.get("session") == "tr-s" for e in traced)
    code, _h, pf = hreq(srv, "GET", "/api/v1/trace?trace_id=t-http-42")
    assert code == 200
    evs = [e for e in pf["traceEvents"] if e.get("ph") in ("X", "i")]
    assert evs and all(
        e["args"].get("trace_id") == "t-http-42"
        or "t-http-42" in (e["args"].get("traces") or ())
        for e in evs)

    # no inbound header: the server mints one and echoes it
    code, hdrs, _b = hreq(srv, "POST", "/api/v1/sessions/tr-s/pods",
                          _pod("tr-p1"))
    assert code == 201
    minted = hdrs.get("X-KSS-Trace-Id")
    assert minted and minted.startswith("t-")
    # GETs are not stamped
    code, hdrs, _b = hreq(srv, "GET", "/api/v1/sessions/tr-s/pods")
    assert code == 200
    assert "X-KSS-Trace-Id" not in hdrs


def test_http_history_endpoint_and_sessions_alias(server):
    srv, _mgr = server
    code, _h, _b = hreq(srv, "POST", "/api/v1/sessions", {"id": "hi-s"})
    assert code == 201
    SLO.observe_wave("hi-s", 0.125, pods=4)
    idx, _planes = FEEDER.sample()
    FEEDER.sample()

    code, _h, win = hreq(srv, "GET", "/api/v1/history")
    assert code == 200
    assert win["enabled"] is True and idx in win["index"]
    assert win["series"][f"slo.p99{{session=hi-s}}"][
        win["index"].index(idx)] == 0.125

    # cursor + stride + series filtering through the query surface
    code, _h, win2 = hreq(
        srv, "GET", f"/api/v1/history?since={idx + 1}&series=slo.p99")
    assert code == 200
    assert win2["index"] == [idx + 1]
    # the bare prefix matches every session's labeled column (other
    # suites' sessions may still sit in the process-global SLO window)
    assert "slo.p99{session=hi-s}" in win2["series"]
    assert all(nm.startswith("slo.p99") for nm in win2["series"])

    # the sessions alias scopes like ?session=
    code, _h, win3 = hreq(srv, "GET", "/api/v1/sessions/hi-s/history")
    assert code == 200
    assert all("{" not in nm or nm.endswith("{session=hi-s}")
               for nm in win3["series"])

    code, _h, body = hreq(srv, "GET", "/api/v1/history?since=x")
    assert code == 400 and "integers" in body["message"]


def test_readyz_surfaces_tracer_dropped_events(server):
    # no scheduler loop in this fixture, so readiness is 503 — the
    # body (and the drop counter on it) is served either way
    srv, _mgr = server
    code, _h, ready = hreq(srv, "GET", "/readyz")
    assert code in (200, 503)
    base = ready.get("tracerDroppedEvents", 0)
    cap = TRACER._events.maxlen
    # fill the remainder of the ring, then overflow it by ten
    for _ in range(cap - len(TRACER.events(limit=cap)) + 10):
        with TRACER.span("drop-span"):
            pass
    _code, _h, ready = hreq(srv, "GET", "/readyz")
    assert ready["tracerDroppedEvents"] > base


# ------------------------------------------------- post-mortem window


def test_bundle_embeds_validating_history_window():
    SLO.observe_wave("pm-s", 0.2, pods=4)
    FEEDER.sample()
    doc, path = BLACKBOX.dump("test-history", write=False)
    assert path is None
    validate_dump(doc)
    hist = doc["history"]
    assert hist["index"] and isinstance(hist["series"], dict)
    assert len(hist["t"]) == len(hist["index"])
    # a ragged column must fail the schema check
    bad = json.loads(json.dumps(doc))
    first = next(iter(bad["history"]["series"]))
    bad["history"]["series"][first] = \
        bad["history"]["series"][first] + [0.0]
    with pytest.raises(ValueError, match="history"):
        validate_dump(bad)
