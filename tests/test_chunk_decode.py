"""Chunk-granular native decode: parity, edge pods, re-delivery, threads.

The three decoder rungs (chunk-granular ctx_decode_chunk -> per-pod fused
ctx_decode_pod -> pure Python) must be byte-identical on every pod,
including the shapes the chunk call special-cases: prefilter-rejected
pods (Python early-out owns them), empty-active-mask pods, host-resident
score columns, ranges that start mid-chunk, width-tier re-delivery, and
concurrent chunk calls (per-call arenas must not be shared)."""

import os
import threading

import numpy as np
import pytest

from kube_scheduler_simulator_tpu.framework.replay import replay
from kube_scheduler_simulator_tpu.models.workloads import (
    baseline_config, make_nodes, make_pods)
from kube_scheduler_simulator_tpu.native import get_lib
from kube_scheduler_simulator_tpu.plugins.registry import PluginSetConfig
from kube_scheduler_simulator_tpu.state.compile import compile_workload
from kube_scheduler_simulator_tpu.store.decode import (
    decode_chunk_into, decode_pod_result)

pytestmark = pytest.mark.skipif(get_lib() is None, reason="no native toolchain")


def _decode_three_ways(rr, n, monkeypatch):
    """(chunk, per-pod fused, pure-Python) annotation lists for pods 0..n."""
    chunk: list = [None] * n
    decode_chunk_into(rr, 0, n, chunk)
    fused = [decode_pod_result(rr, i) for i in range(n)]
    monkeypatch.setenv("KSS_TPU_DISABLE_NATIVE", "1")
    try:
        pure = [decode_pod_result(rr, i) for i in range(n)]
    finally:
        monkeypatch.delenv("KSS_TPU_DISABLE_NATIVE")
    return chunk, fused, pure


def _assert_all_equal(chunk, fused, pure):
    for i, (ca, fa, pa) in enumerate(zip(chunk, fused, pure)):
        for k in pa:
            assert ca[k] == pa[k], (
                f"pod {i} key {k} (chunk vs pure)\n chunk={ca[k][:300]}\n"
                f" pure={pa[k][:300]}")
            assert fa[k] == pa[k], f"pod {i} key {k} (fused vs pure)"


def test_chunk_decode_parity_with_rejects_and_host_columns(monkeypatch):
    """Workload mixing prefilter-rejected pods (missing PVC), plain and
    affinity pods, taints, and host-resident score columns (NodeAffinity
    + VolumeBinding): all three decoder rungs byte-identical."""
    from kube_scheduler_simulator_tpu.store import annotations as ann

    nodes = make_nodes(25, seed=3, taint_fraction=0.3)
    pods = make_pods(40, seed=4, with_affinity=True, with_tolerations=True)
    # two prefilter-rejected pods (VolumeBinding: PVC does not exist),
    # placed mid-queue so chunk ranges mix rejected and decoded pods
    for j, at in enumerate((7, 23)):
        pods.insert(at, {
            "metadata": {"name": f"pvc-pod-{j}", "namespace": "default"},
            "spec": {
                "containers": [{"name": "c", "resources": {
                    "requests": {"cpu": "100m"}}}],
                "volumes": [{"name": "v", "persistentVolumeClaim": {
                    "claimName": f"missing-{j}"}}],
            },
        })
    cfg = PluginSetConfig(enabled=[
        "NodeResourcesFit", "NodeAffinity", "TaintToleration",
        "VolumeBinding"])
    cw = compile_workload(nodes, pods, cfg)
    assert "host" in cw.host["score_dtypes"]  # host column exercised
    assert "prefilter_reject" in cw.host      # reject path exercised
    rr = replay(cw, chunk=16)

    chunk, fused, pure = _decode_three_ways(rr, len(pods), monkeypatch)
    _assert_all_equal(chunk, fused, pure)
    # the rejected pods really took the early-out: empty filter blob +
    # the rejecting plugin recorded in prefilter-status
    for j, at in enumerate((7, 23)):
        assert chunk[at][ann.FILTER_RESULT] == "{}"
        assert "missing-" + str(j) in chunk[at][ann.PRE_FILTER_STATUS_RESULT] \
            or "VolumeBinding" in chunk[at][ann.PRE_FILTER_STATUS_RESULT]


def test_chunk_decode_parity_empty_active_mask(monkeypatch):
    """Pods whose every enabled Filter is PreFilter-skipped (plain pods
    under a NodeAffinity-only lineup) emit filter-result == {} with the
    score maps still populated from the host-resident column."""
    from kube_scheduler_simulator_tpu.store import annotations as ann

    nodes = make_nodes(12, seed=5)
    pods = make_pods(20, seed=6)  # no affinity: NodeAffinity skips
    cfg = PluginSetConfig(enabled=["NodeAffinity"])
    cw = compile_workload(nodes, pods, cfg)
    assert all(cw.host["filter_skip"]["NodeAffinity"])  # masks truly empty
    rr = replay(cw, chunk=8)
    chunk, fused, pure = _decode_three_ways(rr, len(pods), monkeypatch)
    _assert_all_equal(chunk, fused, pure)
    assert chunk[0][ann.FILTER_RESULT] == "{}"
    assert chunk[0][ann.SELECTED_NODE] != ""


def test_chunk_decode_width_tier_redelivery(monkeypatch):
    """A score-width overflow makes replay() re-deliver chunks from pod 0
    at a wider dtype; the chunk decoder's per-index writes must be
    idempotent and the final annotations identical to pure Python."""
    from kube_scheduler_simulator_tpu.utils.tracing import TRACER

    import sys

    # the framework package re-exports replay() under the same name, so
    # reach the MODULE through sys.modules
    replay_mod = sys.modules["kube_scheduler_simulator_tpu.framework.replay"]

    nodes, pods, cfg = baseline_config(4, scale=0.02, seed=11)
    cw = compile_workload(nodes, pods, cfg)
    # flip the overflow flag on the 3rd fetched chunk of the FIRST tier:
    # the real ladder then re-runs the scan at i32 and re-delivers every
    # chunk from pod 0 (same values — nothing actually overflowed), which
    # is exactly the re-delivery the decoder must absorb idempotently
    real_fetch = replay_mod._fetch_chunk
    state = {"fired": False, "count": 0}

    def inject_overflow(out_dev):
        c = real_fetch(out_dev)
        state["count"] += 1
        if not state["fired"] and state["count"] == 3 and "raw_overflow" in c:
            c["raw_overflow"] = np.asarray(True)
            state["fired"] = True
        return c

    monkeypatch.setattr(replay_mod, "_fetch_chunk", inject_overflow)

    out: list = [None] * len(pods)
    deliveries: list = []

    def on_chunk(rr_, lo, hi):
        deliveries.append((lo, hi))
        decode_chunk_into(rr_, lo, hi, out)

    before = TRACER.summary()["counters"].get("replay_width_retries_total", 0)
    rr = replay(cw, chunk=32, on_chunk=on_chunk)
    retries = TRACER.summary()["counters"].get(
        "replay_width_retries_total", 0) - before
    assert retries >= 1, f"no width retry triggered (deliveries={deliveries})"
    assert deliveries.count(deliveries[0]) >= 2  # chunk 0 re-delivered

    monkeypatch.setenv("KSS_TPU_DISABLE_NATIVE", "1")
    try:
        pure = [decode_pod_result(rr, i) for i in range(len(pods))]
    finally:
        monkeypatch.delenv("KSS_TPU_DISABLE_NATIVE")
    for i, (ca, pa) in enumerate(zip(out, pure)):
        assert ca == pa, f"pod {i} diverged after width-tier re-delivery"


def test_chunk_decode_width_tier_redelivery_deferred_path(monkeypatch):
    """Regression: on single-effective-core hosts replay buffers on_chunk
    callbacks until the scan drains (deferred delivery).  When a width
    tier overflows mid-stream, the buffered pre-overflow chunks must
    still be delivered BEFORE the wider rerun re-delivers them — the
    deferred path observes the same redelivery contract as the immediate
    path, so idempotent consumers see >= 2 deliveries of chunk 0."""
    import sys

    from kube_scheduler_simulator_tpu.utils import platform as plat_mod

    replay_mod = sys.modules["kube_scheduler_simulator_tpu.framework.replay"]
    monkeypatch.setattr(plat_mod, "effective_cpu_count", lambda: 1)

    nodes, pods, cfg = baseline_config(4, scale=0.02, seed=11)
    cw = compile_workload(nodes, pods, cfg)
    real_fetch = replay_mod._fetch_chunk
    state = {"fired": False, "count": 0}

    def inject_overflow(out_dev):
        c = real_fetch(out_dev)
        state["count"] += 1
        if not state["fired"] and state["count"] == 3 and "raw_overflow" in c:
            c["raw_overflow"] = np.asarray(True)
            state["fired"] = True
        return c

    monkeypatch.setattr(replay_mod, "_fetch_chunk", inject_overflow)

    out: list = [None] * len(pods)
    deliveries: list = []

    def on_chunk(rr_, lo, hi):
        deliveries.append((lo, hi))
        decode_chunk_into(rr_, lo, hi, out)

    rr = replay(cw, chunk=32, on_chunk=on_chunk)
    assert deliveries.count(deliveries[0]) >= 2, (
        f"deferred path suppressed pre-overflow re-delivery: {deliveries}")

    monkeypatch.setenv("KSS_TPU_DISABLE_NATIVE", "1")
    try:
        pure = [decode_pod_result(rr, i) for i in range(len(pods))]
    finally:
        monkeypatch.delenv("KSS_TPU_DISABLE_NATIVE")
    for i, (ca, pa) in enumerate(zip(out, pure)):
        assert ca == pa, f"pod {i} diverged after deferred re-delivery"


def _localize_ndarrays(root) -> None:
    """Replace every numpy array reachable from `root` with a
    main-thread-owned copy.  The TSan harness (tests/test_native_tsan.py)
    sets KSS_TPU_TSAN_LOCALIZE=1 so the codec's input buffers are no
    longer the XLA-allocated pages jaxlib's (uninstrumented) device sync
    handed over — preload-TSan cannot see that happens-before and would
    report every input read as a race against the device memset.  The
    copy keeps the codec's OWN concurrency (worker pool, arenas, caches,
    output arrays) fully checked."""
    seen: set[int] = set()
    stack = [root]
    while stack:
        obj = stack.pop()
        if id(obj) in seen or obj is None:
            continue
        seen.add(id(obj))
        tmod = type(obj).__module__ or ""
        if tmod.partition(".")[0] in ("jax", "jaxlib", "builtins") \
                and not isinstance(obj, (dict, list)):
            continue  # never introspect device arrays / jax internals
        if isinstance(obj, dict):
            for k, v in list(obj.items()):
                if isinstance(v, np.ndarray):
                    obj[k] = np.array(v, copy=True)
                elif isinstance(v, (dict, list)) or hasattr(v, "__dict__") \
                        or hasattr(v, "__slots__"):
                    stack.append(v)
        elif isinstance(obj, list):
            for i, v in enumerate(obj):
                if isinstance(v, np.ndarray):
                    obj[i] = np.array(v, copy=True)
                else:
                    stack.append(v)
        elif isinstance(obj, (tuple, set, frozenset, str, bytes)):
            continue
        else:
            names = list(getattr(obj, "__dict__", {}) or ())
            for cls in type(obj).__mro__:
                names.extend(getattr(cls, "__slots__", ()))
            for k in names:
                try:
                    v = getattr(obj, k)
                except AttributeError:
                    continue
                if isinstance(v, np.ndarray):
                    setattr(obj, k, np.array(v, copy=True))
                elif isinstance(v, (dict, list)) or hasattr(v, "__dict__") \
                        or hasattr(v, "__slots__"):
                    stack.append(v)


def test_chunk_decode_threaded_soak():
    """Concurrent chunk calls over the same ReplayResult: every call gets
    its own arena, so parallel decoders (pipelined commit + a bench
    sampler, or several engines sharing a process) must never observe
    another chunk's blobs.  Ranges deliberately start mid-chunk."""
    nodes, pods, cfg = baseline_config(4, scale=0.02, seed=13)
    cw = compile_workload(nodes, pods, cfg)
    rr = replay(cw, chunk=32)
    if os.environ.get("KSS_TPU_TSAN_LOCALIZE") == "1":
        _localize_ndarrays(rr)
    n = len(pods)
    expected: list = [None] * n
    decode_chunk_into(rr, 0, n, expected)

    errors: list = []
    rng = np.random.RandomState(0)
    ranges = []
    for _ in range(24):
        lo = int(rng.randint(0, n - 1))
        hi = int(min(n, lo + 1 + rng.randint(0, 40)))
        ranges.append((lo, hi))

    def worker(my_ranges):
        try:
            for lo, hi in my_ranges:
                sink: list = [None] * (hi - lo)
                decode_chunk_into(rr, lo, hi, sink, base=lo)
                for j, a in enumerate(sink):
                    if a != expected[lo + j]:
                        errors.append(
                            f"pod {lo + j} (range {lo}..{hi}) diverged")
                        return
        except Exception as e:  # noqa: BLE001
            errors.append(repr(e))

    threads = [threading.Thread(target=worker, args=(ranges[k::4],))
               for k in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors[:3]
