"""Wave flight recorder (utils/tracing.py, docs/metrics.md): histogram
bucket math, labeled-counter merge, cross-thread span parenting, the
Perfetto export, the SSE/health endpoints, per-plugin attribution from
the replay tensors, and the proof that instrumentation never changes an
annotation byte."""

import json
import threading
import urllib.error
import urllib.request

import pytest

from kube_scheduler_simulator_tpu.cluster.store import ObjectStore
from kube_scheduler_simulator_tpu.framework.engine import SchedulerEngine
from kube_scheduler_simulator_tpu.framework.replay import (
    plugin_attribution, replay)
from kube_scheduler_simulator_tpu.models.workloads import (
    make_gang_workload, make_nodes, make_pods)
from kube_scheduler_simulator_tpu.plugins.registry import PluginSetConfig
from kube_scheduler_simulator_tpu.state.compile import compile_workload
from kube_scheduler_simulator_tpu.store import annotations as ann
from kube_scheduler_simulator_tpu.store.decode import decode_pod_result
from kube_scheduler_simulator_tpu.utils.tracing import (
    BUCKETS, TRACER, Tracer, sanitize_metric_name, validate_exposition)


# ---------------------------------------------------------------- core


def test_histogram_bucket_math():
    t = Tracer()
    bounds = BUCKETS["scheduling_attempt_duration_seconds"]
    # le semantics: a value equal to a bound lands IN that bucket
    t.observe("scheduling_attempt_duration_seconds", bounds[0],
              result="scheduled")
    # strictly above the first bound -> second bucket
    t.observe("scheduling_attempt_duration_seconds", bounds[0] * 1.5,
              result="scheduled")
    # beyond the last bound -> the +Inf bucket; n amortizes a batched wave
    t.observe("scheduling_attempt_duration_seconds", bounds[-1] * 10, n=5,
              result="scheduled")
    snap = t.snapshot()
    h = snap["histograms"]["scheduling_attempt_duration_seconds"]
    assert h["buckets"] == list(bounds)
    (series,) = h["series"]
    assert series["labels"] == {"result": "scheduled"}
    assert series["counts"][0] == 1
    assert series["counts"][1] == 1
    assert series["counts"][-1] == 5
    assert series["count"] == 7
    assert series["sum"] == pytest.approx(
        bounds[0] + bounds[0] * 1.5 + 5 * bounds[-1] * 10)
    # exposition: cumulative buckets ending at +Inf, _count == +Inf bucket
    fams = validate_exposition(t.prometheus_text())
    fam = fams["kss_tpu_scheduling_attempt_duration_seconds"]
    assert fam["type"] == "histogram"
    buckets = [s for s in fam["samples"] if s[0].endswith("_bucket")]
    assert buckets[-1][1]["le"] == "+Inf"
    counts = [float(s[2]) for s in buckets]
    assert counts == sorted(counts) and counts[-1] == 7


def test_histogram_unknown_name_uses_default_buckets():
    t = Tracer()
    t.observe("some_custom_seconds", 0.5)
    h = t.snapshot()["histograms"]["some_custom_seconds"]
    assert len(h["buckets"]) == 15  # the default exponential ladder
    validate_exposition(t.prometheus_text())


def test_labeled_counter_merge_is_order_insensitive():
    t = Tracer()
    t.inc("plugin_execution_total", 2, plugin="Fit", extension_point="filter")
    t.inc("plugin_execution_total", 3, extension_point="filter", plugin="Fit")
    t.inc("plugin_execution_total", 1, plugin="Fit", extension_point="score")
    series = t.snapshot()["labeled_counters"]["plugin_execution_total"]
    by_labels = {tuple(sorted(s["labels"].items())): s["value"]
                 for s in series}
    assert by_labels[(("extension_point", "filter"), ("plugin", "Fit"))] == 5
    assert by_labels[(("extension_point", "score"), ("plugin", "Fit"))] == 1


def test_metric_name_sanitization_and_help_lines():
    assert sanitize_metric_name("a-b.c d") == "a_b_c_d"
    assert sanitize_metric_name("9lives") == "_9lives"
    t = Tracer()
    with t.span("weird-span.name with space"):
        pass
    t.count("dashed-counter.total")
    t.inc("labeled-weird.total", 1, result='quo"te\\back\nline')
    text = t.prometheus_text()
    fams = validate_exposition(text)  # raises on any invalid line
    assert "kss_tpu_dashed_counter_total" in fams
    assert "kss_tpu_span_weird_span_name_with_space_seconds_total" in fams
    for f in fams.values():
        assert f["help"] is not None and f["type"] is not None
    # the escaped label value round-trips through the validator's parser
    (sample,) = fams["kss_tpu_labeled_weird_total"]["samples"]
    assert sample[1]["result"] == 'quo"te\\back\nline'


@pytest.mark.parametrize("bad", [
    "no_final_newline 1",                                    # missing \n
    "1bad_name 2\n",                                         # invalid name
    'm{l="v} 1\n',                                           # unterminated
    'm{l="a",l="b"} 1\n',                                    # dup label
    "# TYPE h histogram\nh_bucket{le=\"+Inf\"} 1\nh_count 1\n",  # no _sum
    "# TYPE h histogram\nh_bucket{le=\"1\"} 1\nh_sum 1\nh_count 1\n",
    "# TYPE h histogram\nh_bucket{le=\"1\"} 5\n"
    "h_bucket{le=\"+Inf\"} 3\nh_sum 1\nh_count 3\n",         # not cumulative
    "a 1\nb 2\na 3\n",                                       # interleaved
])
def test_exposition_validator_rejects(bad):
    with pytest.raises(ValueError):
        validate_exposition(bad)


# ------------------------------------------------- engine span tree


def _pipelined_wave(n_pods=48, n_nodes=6, chunk=16):
    TRACER.reset()
    store = ObjectStore()
    for n in make_nodes(n_nodes, seed=11):
        store.create("nodes", n)
    for p in make_pods(n_pods, seed=12):
        store.create("pods", p)
    # no PostFilter in the lineup so the wave takes the streaming-commit
    # path (_can_stream_commit; the default set's preemption forces the
    # sequential post-pass)
    cfg = PluginSetConfig(enabled=[
        "NodeResourcesFit", "NodeResourcesBalancedAllocation",
        "NodeAffinity", "TaintToleration", "PodTopologySpread"])
    engine = SchedulerEngine(store, plugin_config=cfg, chunk=chunk,
                             pipeline_commit=True)
    assert engine._can_stream_commit()
    bound = engine.schedule_pending()
    assert bound > 0
    return TRACER.events(limit=1000)


def test_span_tree_parents_across_commit_worker_thread():
    evs = _pipelined_wave()
    replays = [e for e in evs if e["name"] == "replay_and_decode_stream"]
    assert replays, [e["name"] for e in evs]
    replay_ev = replays[-1]
    commits = [e for e in evs if e["name"] == "commit_stream"]
    assert commits, "streaming commit did not run"
    for c in commits:
        # explicit cross-thread parenting: the worker's spans hang off
        # the wave's replay span, recorded on a different thread
        assert c["parent_id"] == replay_ev["span_id"]
        assert c["tid"] != replay_ev["tid"]
    # the commit tail parents implicitly on the engine thread
    tails = [e for e in evs if e["name"] == "commit_and_reflect"]
    assert tails and tails[-1]["tid"] == replay_ev["tid"]


def test_perfetto_export_schema_and_pipeline_overlap():
    _pipelined_wave()
    doc = TRACER.perfetto()
    evs = doc["traceEvents"]
    metas = [e for e in evs if e["ph"] == "M"]
    assert any(e["name"] == "process_name" for e in metas)
    assert any(e["name"] == "thread_name" for e in metas)
    xs = [e for e in evs if e["ph"] == "X"]
    assert xs
    for e in xs:
        for field in ("name", "cat", "ts", "dur", "pid", "tid", "args"):
            assert field in e, f"{field} missing from {e}"
        assert isinstance(e["ts"], int) and isinstance(e["dur"], int)
        assert e["dur"] >= 1
    parent = next(e for e in xs if e["name"] == "replay_and_decode_stream")
    kids = [e for e in xs
            if e["args"].get("parent_id") == parent["args"]["span_id"]
            and e["name"] == "commit_stream"]
    assert kids, "no commit_stream children under the replay span"
    # the PR-2 pipeline overlap, visible in one browser load: commit
    # worker spans START inside the replay span's window.  (The FINAL
    # chunk may drain after the replay span closes — finish() joins the
    # worker — so the proof is "some", not "all".)
    assert any(parent["ts"] <= k["ts"] <= parent["ts"] + parent["dur"]
               for k in kids)
    # json-serializable end to end
    json.dumps(doc)


def test_perfetto_limit():
    _pipelined_wave()
    doc = TRACER.perfetto(limit=2)
    assert len([e for e in doc["traceEvents"] if e["ph"] == "X"]) == 2
    # limit=0 means zero spans, not "all" (evs[-0:] would be the whole
    # ring buffer)
    doc = TRACER.perfetto(limit=0)
    assert [e for e in doc["traceEvents"] if e["ph"] == "X"] == []


def test_stop_profile_wraps_external_runtime_error(monkeypatch):
    import jax

    from kube_scheduler_simulator_tpu.utils.tracing import ProfileStateError

    t = Tracer()
    monkeypatch.setattr(jax.profiler, "start_trace", lambda d: None)

    def dead_stop():
        raise RuntimeError("no profiler session running")

    monkeypatch.setattr(jax.profiler, "stop_trace", dead_stop)
    t.start_xla_profile("/tmp/kss-test-prof")
    # the session died outside the Tracer: still a 409-able state
    # conflict, and our state clears so a new start can succeed
    with pytest.raises(ProfileStateError):
        t.stop_xla_profile()
    assert not t.profiling


# ------------------------------------------------- attribution


def _small_replay(n_pods=24, n_nodes=6):
    nodes = make_nodes(n_nodes, seed=21, taint_fraction=0.3)
    pods = make_pods(n_pods, seed=22, with_affinity=True,
                     with_tolerations=True, with_spread=True)
    cfg = PluginSetConfig(enabled=[
        "NodeResourcesFit", "NodeResourcesBalancedAllocation",
        "NodeAffinity", "TaintToleration", "PodTopologySpread"])
    cw = compile_workload(nodes, pods, cfg)
    return replay(cw, chunk=8), cw


def test_plugin_attribution_matches_annotations():
    rr, cw = _small_replay()
    anns = [decode_pod_result(rr, i) for i in range(cw.n_pods)]
    att = plugin_attribution(rr)
    filters = cw.config.filters()
    ran = {n: 0 for n in filters}
    rejects = {n: 0 for n in filters}
    score_sum = {n: 0 for n in cw.config.scorers()}
    for a in anns:
        for entries in json.loads(a[ann.FILTER_RESULT]).values():
            for name, msg in entries.items():
                ran[name] += 1
                if msg != ann.PASSED_FILTER_MESSAGE:
                    rejects[name] += 1
        for entries in json.loads(a[ann.SCORE_RESULT]).values():
            for name, v in entries.items():
                score_sum[name] += int(v)
    for name in filters:
        assert att["filter"][name]["evaluated"] == ran[name], name
        assert att["filter"][name]["rejects"] == rejects[name], name
    for name, want in score_sum.items():
        assert att["score"][name]["sum"] == want, name
    for name, d in att["prefilter"].items():
        assert 0 <= d["evaluated"] <= cw.n_pods
        assert d["screened"] == 0  # this workload has no prefilter rejects


def test_attribution_full_array_layout_without_filters():
    """The full-array (speculative) layout with ZERO filter plugins must
    still attribute scores/prefilters — argmax over the empty filter
    axis used to raise and silently drop the whole wave's attribution."""
    import types

    import numpy as np

    nodes = make_nodes(4, seed=23)
    pods = make_pods(6, seed=24)
    cfg = PluginSetConfig(enabled=["NodeResourcesBalancedAllocation"])
    cw = compile_workload(nodes, pods, cfg)
    p, n = cw.n_pods, cw.n_nodes
    s = len(cfg.scorers())
    raw = np.arange(p * s * n, dtype=np.int64).reshape(p, s, n)
    rr = types.SimpleNamespace(
        cw=cw, _compact=None, _filter_codes=None, _score_raw=raw,
        prefilter_reject=np.zeros(p, np.int64),
        feasible_count=np.full(p, n, np.int32))
    att = plugin_attribution(rr)
    assert att is not None and not att["filter"]
    for i, name in enumerate(cfg.scorers()):
        assert att["score"][name]["sum"] == int(raw[:, i, :].sum())
        assert att["score"][name]["evaluated"] == p * n


def test_attribution_changes_no_annotation_bytes():
    """The golden proof: reading the replay tensors for attribution
    leaves every decoded annotation byte-identical."""
    rr, cw = _small_replay(n_pods=12)
    before = [decode_pod_result(rr, i) for i in range(cw.n_pods)]
    assert plugin_attribution(rr) is not None
    after = [decode_pod_result(rr, i) for i in range(cw.n_pods)]
    assert before == after


def test_engine_wave_populates_upstream_histograms():
    TRACER.reset()
    store = ObjectStore()
    for n in make_nodes(4, seed=41):
        store.create("nodes", n)
    pods = make_pods(12, seed=42)
    # one impossible pod so both result= series appear
    pods[0]["spec"]["containers"][0]["resources"]["requests"]["cpu"] = \
        "9999999m"
    for p in pods:
        store.create("pods", p)
    SchedulerEngine(store).schedule_pending()
    snap = TRACER.snapshot()
    hists = snap["histograms"]
    att = hists["scheduling_attempt_duration_seconds"]["series"]
    results = {s["labels"]["result"]: s["count"] for s in att}
    assert results.get("scheduled") == 11
    assert results.get("unschedulable") == 1
    points = {s["labels"]["extension_point"] for s in
              hists["framework_extension_point_duration_seconds"]["series"]}
    assert {"prefilter", "filter", "score", "bind"} <= points
    plugin_points = {(s["labels"]["plugin"], s["labels"]["extension_point"])
                     for s in
                     hists["plugin_execution_duration_seconds"]["series"]}
    assert any(p == "NodeResourcesFit" and e == "filter"
               for p, e in plugin_points)
    assert any(e == "score" for _, e in plugin_points)
    assert any(e == "prefilter" for _, e in plugin_points)
    # decoder-ladder attribution: the wave defers decode to first read
    # (store/lazy.py), so drain a read before asserting that every
    # decoded pod lands on some ladder path
    store.list("pods")
    snap = TRACER.snapshot()
    decode_paths = snap["labeled_counters"]["decode_path_total"]
    assert sum(s["value"] for s in decode_paths) >= 12


def test_gang_quorum_labeled_counter():
    TRACER.reset()
    from kube_scheduler_simulator_tpu.plugins.coscheduling import (
        Coscheduling, ensure_podgroup_resource)

    store = ObjectStore()
    ensure_podgroup_resource(store)
    for n in make_nodes(8, seed=51):
        store.create("nodes", n)
    pgs, pods = make_gang_workload(2, 3, seed=52)
    ppgs, ppods = make_gang_workload(1, 3, seed=53, name_prefix="parked")
    for p in ppods:
        if p["metadata"]["name"].endswith("-member-000"):
            p["spec"]["containers"][0]["resources"]["requests"]["cpu"] = \
                "9999999m"
    for pg in pgs + ppgs:
        store.create("podgroups", pg)
    for p in pods + ppods:
        store.create("pods", p)
    cfg = PluginSetConfig(
        enabled=["NodeResourcesFit", "Coscheduling"],
        custom={"Coscheduling": Coscheduling()})
    SchedulerEngine(store, plugin_config=cfg).schedule_pending()
    series = TRACER.snapshot()["labeled_counters"]["gang_quorum_groups_total"]
    decisions = {s["labels"]["decision"]: s["value"] for s in series}
    assert decisions.get("admit", 0) >= 2
    assert decisions.get("park", 0) >= 1
    # the span tree has the quorum child spans
    assert any(e["name"] == "gang_quorum" for e in TRACER.events(1000))


def test_host_path_plugin_wall_time():
    """Host-path lifecycle plugins get REAL per-plugin wall time (the
    time half of docs/metrics.md's attribution split)."""
    from kube_scheduler_simulator_tpu.plugins.custom import CustomPlugin

    class Waiter(CustomPlugin):
        name = "Waiter"

        def reserve(self, pod, node):
            return None

        def permit(self, pod, node):
            return None

    TRACER.reset()
    store = ObjectStore()
    for n in make_nodes(3, seed=61):
        store.create("nodes", n)
    for p in make_pods(2, seed=62):
        store.create("pods", p)
    cfg = PluginSetConfig(enabled=["NodeResourcesFit", "Waiter"],
                          custom={"Waiter": Waiter()})
    bound = SchedulerEngine(store, plugin_config=cfg).schedule_pending()
    assert bound == 2
    series = TRACER.snapshot()["histograms"][
        "plugin_execution_duration_seconds"]["series"]
    got = {(s["labels"]["plugin"], s["labels"]["extension_point"],
            s["labels"]["status"]): s["count"] for s in series}
    assert got.get(("Waiter", "reserve", "Success")) == 2
    assert got.get(("Waiter", "permit", "Success")) == 2


# ------------------------------------------------- HTTP surface


@pytest.fixture(scope="module")
def live_server():
    from kube_scheduler_simulator_tpu.config.config import (
        SimulatorConfiguration)
    from kube_scheduler_simulator_tpu.server.di import DIContainer
    from kube_scheduler_simulator_tpu.server.server import SimulatorServer

    di = DIContainer(SimulatorConfiguration(port=0), start_scheduler=True)
    srv = SimulatorServer(di, port=0)
    srv.start(block=False)
    yield di, f"http://127.0.0.1:{srv.port}"
    srv.shutdown()


def _get_json(url, timeout=10):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.status, json.load(r)


def test_health_endpoints(live_server):
    _, base = live_server
    status, body = _get_json(base + "/healthz")
    assert status == 200 and body["status"] == "ok"
    status, body = _get_json(base + "/readyz")
    assert status == 200 and body["status"] == "ready"


def test_metrics_endpoint_passes_validator_on_scheduled_wave(live_server):
    di, base = live_server
    TRACER.reset()
    for n in make_nodes(3, seed=71):
        di.store.create("nodes", n)
    for p in make_pods(8, seed=72):
        di.store.create("pods", p)
    deadline = threading.Event()
    for _ in range(100):  # the scheduling loop debounces ~50ms
        if not [p for p in di.store.list("pods")[0]
                if not (p.get("spec") or {}).get("nodeName")]:
            break
        deadline.wait(0.1)
    with urllib.request.urlopen(base + "/metrics", timeout=10) as r:
        assert r.headers["Content-Type"].startswith("text/plain")
        fams = validate_exposition(r.read().decode())
    for name in ("kss_tpu_scheduling_attempt_duration_seconds",
                 "kss_tpu_framework_extension_point_duration_seconds",
                 "kss_tpu_plugin_execution_duration_seconds"):
        assert fams[name]["type"] == "histogram", name
    points = {s[1].get("extension_point")
              for s in fams["kss_tpu_plugin_execution_duration_seconds"]
              ["samples"]}
    assert {"filter", "score", "prefilter"} <= points
    # the JSON snapshot carries the same families
    _, snap = _get_json(base + "/api/v1/metrics")
    assert {"spans", "counters", "labeled_counters", "histograms"} \
        <= set(snap)


def test_trace_endpoint(live_server):
    _, base = live_server
    status, doc = _get_json(base + "/api/v1/trace?limit=5")
    assert status == 200
    assert "traceEvents" in doc
    assert len([e for e in doc["traceEvents"] if e["ph"] == "X"]) <= 5
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(base + "/api/v1/trace?limit=bogus", timeout=10)
    assert ei.value.code == 400


def test_metrics_stream_sse(live_server):
    _, base = live_server
    with urllib.request.urlopen(
            base + "/api/v1/metrics/stream?interval=0.05&count=3",
            timeout=10) as r:
        assert r.headers["Content-Type"] == "text/event-stream"
        body = r.read().decode()
    events = [json.loads(line[6:]) for line in body.split("\n")
              if line.startswith("data: ")]
    assert len(events) >= 2
    for snap in events:
        assert "counters" in snap and "histograms" in snap


def test_profile_conflicts_return_409(live_server, monkeypatch):
    import jax

    _, base = live_server
    monkeypatch.setattr(jax.profiler, "start_trace", lambda d: None)
    monkeypatch.setattr(jax.profiler, "stop_trace", lambda: None)

    def post(action):
        req = urllib.request.Request(
            base + "/api/v1/profile",
            data=json.dumps({"action": action}).encode(), method="POST",
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=10) as r:
                return r.status, json.load(r)
        except urllib.error.HTTPError as e:
            return e.code, json.load(e)

    # stop without start -> 409 with a JSON error body, not a raw 500
    code, body = post("stop")
    assert code == 409 and body["reason"] == "Conflict" and body["message"]
    code, _ = post("start")
    assert code == 200
    try:
        # double start -> 409
        code, body = post("start")
        assert code == 409 and "already running" in body["message"]
    finally:
        code, _ = post("stop")
        assert code == 200


# --------------------------------------- worker exception span balance


def test_mid_chunk_exception_leaves_tracer_balanced(monkeypatch):
    """A mid-chunk failure on the commit-worker thread must not leak
    spans: the raising chunk's commit_stream span closes (with-statement
    unwind), finish()'s commit_and_reflect tail closes before the worker
    error re-raises on the engine thread, and the /api/v1/trace document
    stays well-formed (docs/static-analysis.md, unbalanced-span rule)."""
    TRACER.reset()
    # this test poisons put_decoded mid-chunk: pin the EAGER commit
    # worker (lazy mode deposits handles and never calls it in-wave),
    # and disable the wave failure protocol's retry so the ABORT path —
    # what this test pins — still surfaces the raise (with retries on,
    # the one-shot poison heals via the uncommitted-suffix retry:
    # tests/test_faults.py covers that)
    monkeypatch.setenv("KSS_TPU_EAGER_DECODE", "1")
    monkeypatch.setenv("KSS_TPU_WAVE_MAX_RETRIES", "0")
    store = ObjectStore()
    for n in make_nodes(6, seed=31):
        store.create("nodes", n)
    for p in make_pods(48, seed=32):
        store.create("pods", p)
    cfg = PluginSetConfig(enabled=[
        "NodeResourcesFit", "NodeResourcesBalancedAllocation",
        "NodeAffinity", "TaintToleration", "PodTopologySpread"])
    engine = SchedulerEngine(store, plugin_config=cfg, chunk=16,
                             pipeline_commit=True)
    assert engine._can_stream_commit()

    real = engine.result_store.put_decoded
    calls = {"n": 0}

    def poisoned(ns, name, annotations):
        calls["n"] += 1
        if calls["n"] == 20:  # second chunk, pod 4 of 16: MID-chunk
            raise RuntimeError("mid-chunk poison")
        return real(ns, name, annotations)

    monkeypatch.setattr(engine.result_store, "put_decoded", poisoned)
    with pytest.raises(RuntimeError, match="mid-chunk poison"):
        engine.schedule_pending()

    evs = TRACER.events(limit=1000)
    # the span the worker was inside when it raised was still recorded
    commits = [e for e in evs if e["name"] == "commit_stream"]
    assert commits, "raising commit_stream span was dropped"
    assert [e for e in evs if e["name"] == "commit_and_reflect"]
    # both thread stacks unwound: the engine thread's stack is empty and
    # every recorded parent_id resolves to a recorded span (a leaked
    # open span would leave a dangling reference)
    assert TRACER.current_span_id() is None
    doc = TRACER.perfetto()
    xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    ids = {e["args"]["span_id"] for e in xs}
    for e in xs:
        for field in ("name", "cat", "ts", "dur", "pid", "tid", "args"):
            assert field in e, f"{field} missing from {e}"
        parent = e["args"].get("parent_id")
        assert parent is None or parent in ids, \
            f"{e['name']} parents under an unrecorded span {parent}"
    json.dumps(doc)  # the /api/v1/trace body end to end

    # the recorder (and engine) are not wedged: the next wave schedules
    # normally and stays balanced
    before = calls["n"]
    assert engine.schedule_pending() > 0
    assert calls["n"] > before
    assert TRACER.current_span_id() is None
