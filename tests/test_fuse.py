"""Cross-session fused wave dispatch (parallel/fuse.py): coordinator
protocol units plus the engine-level golden parity bar — each session's
annotations and bind order byte-identical fused vs `KSS_TPU_FUSE=0`
solo, including a gang-bearing session fused with a plain-pod session
and a mid-dispatch injected fault retrying only the faulted session's
suffix (docs/wave-pipeline.md fused-dispatch stage)."""

from __future__ import annotations

import copy
import threading
import time

import jax.numpy as jnp

from kube_scheduler_simulator_tpu.models.workloads import (
    make_slot_pinned_workload)
from kube_scheduler_simulator_tpu.parallel.fuse import (
    FUSE, FuseCoordinator, session_admitted)
from kube_scheduler_simulator_tpu.plugins.registry import PluginSetConfig
from kube_scheduler_simulator_tpu.server.sessions import SessionManager
from kube_scheduler_simulator_tpu.utils.tracing import TRACER

ENABLED = ["NodeResourcesFit", "NodeResourcesBalancedAllocation",
           "NodeAffinity"]


# ------------------------------------------------- coordinator protocol


def _solo_fn(c, x):
    return c + x, (c * x).sum()


def test_dispatch_timeshares_without_a_live_partner(monkeypatch):
    monkeypatch.setenv("KSS_TPU_FUSE_WINDOW_MS", "5000")
    c = FuseCoordinator()
    s = c.stream_open("fam-alone")
    out = c.dispatch(s, ("fam-alone", "k1"), _solo_fn,
                     (jnp.arange(4), jnp.ones(4)))
    assert jnp.array_equal(out[0], jnp.arange(4) + 1)
    # a benched stream never joins batches either, even with partners
    s2 = c.stream_open("fam-alone")
    benched = c.stream_open("fam-alone", admitted=False)
    out = c.dispatch(benched, ("fam-alone", "k1"), _solo_fn,
                     (jnp.arange(4), jnp.ones(4)))
    assert jnp.array_equal(out[0], jnp.arange(4) + 1)
    assert c.stats()["dispatches"]["timeshared"] == 2
    assert c.stats()["fusedDeviceCalls"] == 0
    for st in (s, s2, benched):
        c.stream_close(st)
    assert c.stats()["openFamilies"] == 0


def test_leader_times_out_when_partner_never_dispatches(monkeypatch):
    monkeypatch.setenv("KSS_TPU_FUSE_WINDOW_MS", "40")
    c = FuseCoordinator()
    s1 = c.stream_open("fam-to")
    s2 = c.stream_open("fam-to")  # live partner that never calls
    t0 = time.monotonic()
    out = c.dispatch(s1, ("fam-to", "k1"), _solo_fn,
                     (jnp.arange(3), jnp.ones(3)))
    waited = time.monotonic() - t0
    assert jnp.array_equal(out[0], jnp.arange(3) + 1)
    assert waited >= 0.03, "leader should have waited out the window"
    assert c.stats()["dispatches"]["window_timeout"] == 1
    c.stream_close(s1)
    c.stream_close(s2)


def test_two_streams_fuse_one_device_call(monkeypatch):
    monkeypatch.setenv("KSS_TPU_FUSE_WINDOW_MS", "5000")
    c = FuseCoordinator()
    streams = [c.stream_open("fam-2"), c.stream_open("fam-2")]
    rows = [(jnp.arange(4) + 10 * i, jnp.full(4, float(i + 1)))
            for i in range(2)]
    outs: dict = {}

    def run(i):
        outs[i] = c.dispatch(streams[i], ("fam-2", "kA"), _solo_fn, rows[i])

    threads = [threading.Thread(target=run, args=(i,)) for i in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    for i in range(2):
        solo = _solo_fn(*rows[i])
        assert jnp.array_equal(outs[i][0], solo[0]), f"row {i} diverged"
        assert jnp.array_equal(outs[i][1], solo[1])
    st = c.stats()
    assert st["fusedDeviceCalls"] == 1
    assert st["dispatches"]["fused"] == 2
    assert st["meanSessionsPerFusedCall"] == 2.0
    for s in streams:
        c.stream_close(s)


def test_mutual_leader_deadlock_breaks_and_realigns(monkeypatch):
    """Two streams whose round ladders slipped out of phase: stream B
    arriving at a DIFFERENT key while A leads must run solo immediately
    (not sleep out the window), then fuse with A when it re-arrives at
    A's key — the ladder-realignment rescue."""
    monkeypatch.setenv("KSS_TPU_FUSE_WINDOW_MS", "10000")
    c = FuseCoordinator()
    sa, sb = c.stream_open("fam-dl"), c.stream_open("fam-dl")
    args = (jnp.arange(4), jnp.ones(4))
    out_a: list = []

    ta = threading.Thread(
        target=lambda: out_a.append(
            c.dispatch(sa, ("fam-dl", "k1"), _solo_fn, args)))
    ta.start()
    time.sleep(0.2)  # A is now the registered leader at k1, waiting

    t0 = time.monotonic()
    out_b1 = c.dispatch(sb, ("fam-dl", "k2"), _solo_fn, args)
    assert time.monotonic() - t0 < 5.0, (
        "second leader at a different key slept toward the window "
        "instead of breaking the mutual-leader deadlock")
    # B catches up to A's rung: joins A's still-open batch, both fuse
    out_b2 = c.dispatch(sb, ("fam-dl", "k1"), _solo_fn, args)
    ta.join(timeout=30)
    assert not ta.is_alive(), "leader A never completed"
    solo = _solo_fn(*args)
    for out in (out_a[0], out_b1, out_b2):
        assert jnp.array_equal(out[0], solo[0])
    st = c.stats()
    assert st["fusedDeviceCalls"] == 1
    assert st["dispatches"]["window_timeout"] == 1  # B's k2 solo
    assert st["dispatches"]["fused"] == 2
    c.stream_close(sa)
    c.stream_close(sb)


def test_fused_call_failure_surfaces_to_every_member(monkeypatch):
    monkeypatch.setenv("KSS_TPU_FUSE_WINDOW_MS", "5000")
    c = FuseCoordinator()
    streams = [c.stream_open("fam-err"), c.stream_open("fam-err")]

    def boom(carry, xs):
        raise ValueError("device fell over")

    errs: dict = {}

    def run(i):
        try:
            c.dispatch(streams[i], ("fam-err", "kE"), boom,
                       (jnp.ones(2), jnp.ones(2)))
        except ValueError as e:
            errs[i] = str(e)

    threads = [threading.Thread(target=run, args=(i,)) for i in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert errs == {0: "device fell over", 1: "device fell over"}
    assert c.stats()["dispatches"]["fused"] == 0
    for s in streams:
        c.stream_close(s)


def test_admission_reads_session_accept_rates(monkeypatch):
    monkeypatch.setenv("KSS_TPU_FUSE_MIN_ACCEPT", "0.25")
    TRACER.reset()
    with TRACER.session_scope("adm-hot"):
        TRACER.inc("speculative_accepted_total", 9)
        TRACER.inc("speculative_rolled_back_total", 1)
    with TRACER.session_scope("adm-cold"):
        TRACER.inc("speculative_accepted_total", 1)
        TRACER.inc("speculative_rolled_back_total", 9)
    assert session_admitted("adm-hot")
    assert not session_admitted("adm-cold")
    assert session_admitted("adm-never-seen")  # no history: optimistic


# ----------------------------------------------- engine golden parity


def _mk_sessions(specs):
    """specs: [(name, nodes, config, podgroups)] -> (mgr, {name: sess},
    {name: bind-order list})."""
    mgr = SessionManager(max_sessions=len(specs) + 1, idle_ttl=0,
                         start_scheduler=False)
    sessions, orders = {}, {}
    for name, nodes, cfg, pgs in specs:
        sess = mgr.create(name)
        eng = sess.di.engine
        eng.set_profiles(None)
        eng.plugin_config = cfg
        if pgs is not None:
            from kube_scheduler_simulator_tpu.plugins.coscheduling import (
                ensure_podgroup_resource)

            ensure_podgroup_resource(sess.di.store)
            for pg in pgs:
                sess.di.store.create("podgroups", copy.deepcopy(pg))
        for n in nodes:
            sess.di.store.create("nodes", copy.deepcopy(n))
        order: list = []
        orig_batch, orig_bind = eng._commit_pod_batch, eng._bind

        def batch_spy(items, _orig=orig_batch, _order=order):
            _order.extend((ns, n, node) for ns, n, node in items if node)
            return _orig(items)

        def bind_spy(ns, n, node, _orig=orig_bind, _order=order):
            _order.append((ns, n, node))
            return _orig(ns, n, node)

        eng._commit_pod_batch = batch_spy
        eng._bind = bind_spy
        sessions[name] = sess
        orders[name] = order
    return mgr, sessions, orders


def _run_arm(monkeypatch, sessions, orders, pods_by_session, fuse_on,
             window_ms=4000):
    """One concurrent wave across all sessions; returns per-session
    (state, bind order) where state maps pod -> (nodeName, annotations)."""
    monkeypatch.setenv("KSS_TPU_SPECULATIVE", "1")
    monkeypatch.setenv("KSS_TPU_FUSE", "1" if fuse_on else "0")
    monkeypatch.setenv("KSS_TPU_FUSE_WINDOW_MS", str(window_ms))
    for name, sess in sessions.items():
        for p in pods_by_session[name]:
            sess.di.store.create("pods", copy.deepcopy(p))
        orders[name].clear()
    barrier = threading.Barrier(len(sessions))
    errs: list = []

    def run(sess):
        try:
            barrier.wait()
            sess.di.engine.schedule_pending()
        except Exception as e:  # noqa: BLE001 — surfaced below
            errs.append(f"{type(e).__name__}: {e}")

    threads = [threading.Thread(target=run, args=(s,), daemon=True)
               for s in sessions.values()]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=300)
    assert not errs, errs
    result = {}
    for name, sess in sessions.items():
        state = {}
        for p in sess.di.store.list("pods", copy_objects=False)[0]:
            meta = p["metadata"]
            state[meta["name"]] = (
                (p.get("spec") or {}).get("nodeName"),
                tuple(sorted((meta.get("annotations") or {}).items())))
        result[name] = (state, list(orders[name]))
        for p in sess.di.store.list("pods", copy_objects=False)[0][:]:
            meta = p["metadata"]
            sess.di.store.delete("pods", meta["name"],
                                 meta.get("namespace"))
    return result


def _assert_arms_identical(fused, solo):
    for name in solo:
        fs, fo = fused[name]
        ss, so = solo[name]
        diff = sorted(k for k in ss if ss[k] != fs.get(k))
        assert fs == ss, f"{name}: state diverged at {diff[:4]}"
        assert fo == so, f"{name}: bind order diverged"


def test_fused_sessions_byte_identical_to_solo(monkeypatch):
    """The flagship bar: two sessions with DIFFERENT pods over the same
    fleet fuse into shared device calls, and every annotation byte and
    bind order matches their KSS_TPU_FUSE=0 runs — plus the fused
    metric families land validator-clean."""
    from kube_scheduler_simulator_tpu.utils.tracing import (
        validate_exposition)

    nodes, pods_a = make_slot_pinned_workload(24, 12, seed=71)
    pods_b = make_slot_pinned_workload(24, 12, seed=72)[1]
    cfg = lambda: PluginSetConfig(enabled=list(ENABLED))  # noqa: E731
    mgr, sessions, orders = _mk_sessions(
        [("fz-a", nodes, cfg(), None), ("fz-b", nodes, cfg(), None)])
    try:
        pods = {"fz-a": pods_a, "fz-b": pods_b}
        before = FUSE.stats()["fusedDeviceCalls"]
        fused = _run_arm(monkeypatch, sessions, orders, pods, fuse_on=True)
        assert FUSE.stats()["fusedDeviceCalls"] - before >= 1, (
            "the fused arm never stacked a cross-session batch")
        solo = _run_arm(monkeypatch, sessions, orders, pods, fuse_on=False)
        _assert_arms_identical(fused, solo)
        assert all(v[0] for st, _o in fused.values() for v in st.values()), \
            "slot-pinned workload should bind every pod"
        fams = validate_exposition(TRACER.prometheus_text())
        assert fams["kss_tpu_fused_dispatch_total"]["type"] == "counter"
        assert fams["kss_tpu_fused_sessions_per_dispatch"]["type"] == \
            "histogram"
    finally:
        mgr.shutdown()


def test_gang_bearing_session_fuses_with_plain_session(monkeypatch):
    """A gang-bearing session and a plain-pod session share one fused
    batch (same fleet, same config — the shared Coscheduling instance
    keeps the compile-cache family identical; the vectorized quorum
    pass never consults the instance's engine binding) and both stay
    byte-identical to their solo runs, gang admission included."""
    from kube_scheduler_simulator_tpu.framework.gang import (
        POD_GROUP_API_VERSION, POD_GROUP_LABEL)
    from kube_scheduler_simulator_tpu.plugins.coscheduling import (
        Coscheduling)

    nodes, base_pods = make_slot_pinned_workload(16, 8, seed=81)
    gang_pods = copy.deepcopy(base_pods)
    pgs = []
    for g, lo in enumerate((0, 3)):
        gname = f"fzgang-{g}"
        pgs.append({"apiVersion": POD_GROUP_API_VERSION,
                    "kind": "PodGroup",
                    "metadata": {"name": gname, "namespace": "default"},
                    "spec": {"minMember": 3,
                             "scheduleTimeoutSeconds": 30}})
        for p in gang_pods[lo:lo + 3]:
            p["metadata"].setdefault("labels", {})[POD_GROUP_LABEL] = gname
    cos = Coscheduling()
    enabled = ["NodeResourcesFit", "Coscheduling"]
    cfg = lambda: PluginSetConfig(  # noqa: E731
        enabled=list(enabled), custom={"Coscheduling": cos})
    mgr, sessions, orders = _mk_sessions(
        [("fz-gang", nodes, cfg(), pgs), ("fz-plain", nodes, cfg(), [])])
    try:
        pods = {"fz-gang": gang_pods, "fz-plain": base_pods}
        before = FUSE.stats()["fusedDeviceCalls"]
        fused = _run_arm(monkeypatch, sessions, orders, pods, fuse_on=True)
        assert FUSE.stats()["fusedDeviceCalls"] - before >= 1, (
            "gang-bearing and plain sessions never fused")
        solo = _run_arm(monkeypatch, sessions, orders, pods, fuse_on=False)
        _assert_arms_identical(fused, solo)
        gang_state = fused["fz-gang"][0]
        members = {}
        for p in pods["fz-gang"]:
            g = (p["metadata"].get("labels") or {}).get(POD_GROUP_LABEL)
            if g:
                members.setdefault(g, []).append(p["metadata"]["name"])
        for g, names in members.items():
            bound = [n for n in names if gang_state[n][0]]
            assert len(bound) == 3, f"{g}: admitted gang must bind whole"
    finally:
        mgr.shutdown()


def test_mid_dispatch_fault_retries_only_faulted_session(monkeypatch):
    """An injected fault at the fuse.dispatch seam scoped to one session
    aborts only that session's wave (suffix retry through the wave
    failure protocol); its batch-mate proceeds untouched, and BOTH end
    byte-identical to the fault-free solo runs — neighbor isolation."""
    from kube_scheduler_simulator_tpu.utils import faults

    nodes, pods_a = make_slot_pinned_workload(24, 12, seed=91)
    pods_b = make_slot_pinned_workload(24, 12, seed=92)[1]
    cfg = lambda: PluginSetConfig(enabled=list(ENABLED))  # noqa: E731
    mgr, sessions, orders = _mk_sessions(
        [("fz-f0", nodes, cfg(), None), ("fz-f1", nodes, cfg(), None)])
    try:
        pods = {"fz-f0": pods_a, "fz-f1": pods_b}
        solo = _run_arm(monkeypatch, sessions, orders, pods, fuse_on=False)
        TRACER.reset()
        plan = faults.FaultPlan([
            faults.FaultRule("fuse.dispatch", nth=2, error="runtime",
                             sessions=["fz-f0"]),
        ], seed=3)
        with faults.armed(plan):
            faulted = _run_arm(monkeypatch, sessions, orders, pods,
                               fuse_on=True, window_ms=500)
        assert plan.stats()["rules"][0]["trips"] == 1, "fault never fired"
        retried = TRACER.snapshot(session="fz-f0")["counters"]
        neighbor = TRACER.snapshot(session="fz-f1")["counters"]
        assert retried.get("wave_retries_total", 0) >= 1, retried
        assert neighbor.get("wave_retries_total", 0) == 0, (
            "the fault leaked into the batch-mate's wave", neighbor)
        _assert_arms_identical(faulted, solo)
    finally:
        mgr.shutdown()
