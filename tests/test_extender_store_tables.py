"""Table-driven ExtenderResultStore semantics, mirroring the reference's
extender result-store test tables (simulator/scheduler/extender/resultstore/
resultstore_test.go:16-1195): GetStoredResult with full/partial/absent data,
per-verb overwrite keyed by (pod key, extender host), and DeleteData.
"""

import json

import pytest

from kube_scheduler_simulator_tpu.scheduler.extender import ExtenderResultStore
from kube_scheduler_simulator_tpu.store import annotations as ann


def pod(ns="default", name="pod1"):
    return {"metadata": {"namespace": ns, "name": name}}


def args_for(ns="default", name="pod1"):
    return {"Pod": {"metadata": {"namespace": ns, "name": name}}}


FILTER_RES = {"Nodes": None, "NodeNames": ["node1"], "FailedNodes": {}, "Error": ""}
PRIO_RES = [{"Host": "node1", "Score": 1}]
PREEMPT_RES = {"NodeNameToMetaVictims": {"node1": {"Pods": []}}}
BIND_RES = {"Error": ""}


class TestGetStoredResult:
    # resultstore_test.go:27 "success"
    def test_success_all_verbs(self):
        s = ExtenderResultStore()
        s.add_filter_result(args_for(), FILTER_RES, "extenderserver")
        s.add_prioritize_result(args_for(), PRIO_RES, "extenderserver")
        s.add_preempt_result(args_for(), PREEMPT_RES, "extenderserver")
        s.add_bind_result(
            {"PodNamespace": "default", "PodName": "pod1"}, BIND_RES, "extenderserver")
        got = s.get_stored_result(pod())
        assert set(got) == {
            ann.EXTENDER_FILTER_RESULT, ann.EXTENDER_PRIORITIZE_RESULT,
            ann.EXTENDER_PREEMPT_RESULT, ann.EXTENDER_BIND_RESULT,
        }
        assert json.loads(got[ann.EXTENDER_FILTER_RESULT]) == {
            "extenderserver": FILTER_RES}
        assert json.loads(got[ann.EXTENDER_PRIORITIZE_RESULT]) == {
            "extenderserver": PRIO_RES}

    # resultstore_test.go:112 "do nothing if store doesn't have data"
    def test_absent_pod_returns_none(self):
        s = ExtenderResultStore()
        assert s.get_stored_result(pod()) is None
        # a result for a DIFFERENT pod must not leak
        s.add_filter_result(args_for(name="other"), FILTER_RES, "e1")
        assert s.get_stored_result(pod()) is None

    # resultstore_test.go:122 "success without some data on store":
    # verbs never recorded still serialize, as empty maps
    def test_partial_data_serializes_empty_maps(self):
        s = ExtenderResultStore()
        s.add_filter_result(args_for(), FILTER_RES, "extenderserver")
        got = s.get_stored_result(pod())
        assert json.loads(got[ann.EXTENDER_FILTER_RESULT]) == {
            "extenderserver": FILTER_RES}
        for key in (ann.EXTENDER_PRIORITIZE_RESULT, ann.EXTENDER_PREEMPT_RESULT,
                    ann.EXTENDER_BIND_RESULT):
            assert got[key] == "{}"


ADD_CASES = [
    ("filter", lambda s, a, r, h: s.add_filter_result(a, r, h),
     FILTER_RES, {"Nodes": None, "NodeNames": ["node2"], "FailedNodes": {}, "Error": ""},
     ann.EXTENDER_FILTER_RESULT),
    ("prioritize", lambda s, a, r, h: s.add_prioritize_result(a, r, h),
     PRIO_RES, [{"Host": "node2", "Score": 7}], ann.EXTENDER_PRIORITIZE_RESULT),
    ("preempt", lambda s, a, r, h: s.add_preempt_result(a, r, h),
     PREEMPT_RES, {"NodeNameToMetaVictims": {}}, ann.EXTENDER_PREEMPT_RESULT),
]


@pytest.mark.parametrize("verb,add,res1,res2,anno_key",
                         ADD_CASES, ids=[c[0] for c in ADD_CASES])
class TestAddResultTables:
    # "overwrite to the already stored data which has the same key and hostname"
    def test_same_key_same_host_overwrites(self, verb, add, res1, res2, anno_key):
        s = ExtenderResultStore()
        add(s, args_for(), res1, "extenderserver")
        add(s, args_for(), res2, "extenderserver")
        got = json.loads(s.get_stored_result(pod())[anno_key])
        assert got == {"extenderserver": res2}

    # "shouldn't overwrite ... same key and different hostname"
    def test_same_key_different_host_keeps_both(self, verb, add, res1, res2, anno_key):
        s = ExtenderResultStore()
        add(s, args_for(), res1, "extender-a")
        add(s, args_for(), res2, "extender-b")
        got = json.loads(s.get_stored_result(pod())[anno_key])
        assert got == {"extender-a": res1, "extender-b": res2}

    # "overwrite to the already stored data which has the different key and
    # same hostname" — results are per-pod; another pod's entry is untouched
    def test_different_key_same_host_independent(self, verb, add, res1, res2, anno_key):
        s = ExtenderResultStore()
        add(s, args_for(name="pod1"), res1, "extenderserver")
        add(s, args_for(name="pod2"), res2, "extenderserver")
        assert json.loads(s.get_stored_result(pod(name="pod1"))[anno_key]) == {
            "extenderserver": res1}
        assert json.loads(s.get_stored_result(pod(name="pod2"))[anno_key]) == {
            "extenderserver": res2}


class TestAddBindResult:
    # bind args carry PodNamespace/PodName directly (ExtenderBindingArgs)
    def test_bind_key_from_binding_args(self):
        s = ExtenderResultStore()
        s.add_bind_result(
            {"PodNamespace": "ns1", "PodName": "p"}, BIND_RES, "extenderserver")
        got = s.get_stored_result(pod(ns="ns1", name="p"))
        assert json.loads(got[ann.EXTENDER_BIND_RESULT]) == {
            "extenderserver": BIND_RES}

    def test_bind_overwrite_same_host(self):
        s = ExtenderResultStore()
        s.add_bind_result({"PodNamespace": "ns1", "PodName": "p"},
                          {"Error": "first"}, "e")
        s.add_bind_result({"PodNamespace": "ns1", "PodName": "p"},
                          {"Error": "second"}, "e")
        got = json.loads(s.get_stored_result(pod(ns="ns1", name="p"))[
            ann.EXTENDER_BIND_RESULT])
        assert got == {"e": {"Error": "second"}}

    def test_bind_two_hosts(self):
        s = ExtenderResultStore()
        s.add_bind_result({"PodNamespace": "ns1", "PodName": "p"}, {"Error": ""}, "e1")
        s.add_bind_result({"PodNamespace": "ns1", "PodName": "p"}, {"Error": "x"}, "e2")
        got = json.loads(s.get_stored_result(pod(ns="ns1", name="p"))[
            ann.EXTENDER_BIND_RESULT])
        assert got == {"e1": {"Error": ""}, "e2": {"Error": "x"}}


class TestDeleteData:
    # resultstore_test.go:1011 "success to delete the stored data which has
    # the specified key" — only that pod's entry goes away
    def test_delete_specified_key_only(self):
        s = ExtenderResultStore()
        s.add_filter_result(args_for(name="pod1"), FILTER_RES, "e")
        s.add_filter_result(args_for(name="pod2"), FILTER_RES, "e")
        s.delete_data(pod(name="pod1"))
        assert s.get_stored_result(pod(name="pod1")) is None
        assert s.get_stored_result(pod(name="pod2")) is not None

    # resultstore_test.go:1111 "do nothing if store doesn't have the data"
    def test_delete_absent_is_noop(self):
        s = ExtenderResultStore()
        s.add_filter_result(args_for(name="pod2"), FILTER_RES, "e")
        s.delete_data(pod(name="absent"))
        assert s.get_stored_result(pod(name="pod2")) is not None

    def test_readd_after_delete(self):
        s = ExtenderResultStore()
        s.add_filter_result(args_for(), FILTER_RES, "e")
        s.delete_data(pod())
        s.add_prioritize_result(args_for(), PRIO_RES, "e")
        got = s.get_stored_result(pod())
        # filter blob is empty again: delete dropped the whole entry
        assert got[ann.EXTENDER_FILTER_RESULT] == "{}"
        assert json.loads(got[ann.EXTENDER_PRIORITIZE_RESULT]) == {"e": PRIO_RES}


class TestWireFormat:
    def test_annotation_keys_exact(self):
        prefix = "kube-scheduler-simulator.sigs.k8s.io/"
        assert ann.EXTENDER_FILTER_RESULT == prefix + "extender-filter-result"
        assert ann.EXTENDER_PRIORITIZE_RESULT == prefix + "extender-prioritize-result"
        assert ann.EXTENDER_PREEMPT_RESULT == prefix + "extender-preempt-result"
        assert ann.EXTENDER_BIND_RESULT == prefix + "extender-bind-result"

    def test_go_compact_json(self):
        s = ExtenderResultStore()
        s.add_filter_result(args_for(), FILTER_RES, "e")
        blob = s.get_stored_result(pod())[ann.EXTENDER_FILTER_RESULT]
        # Go json.Marshal: compact (no spaces), deterministic key order
        assert ": " not in blob and ", " not in blob
        assert blob == ann.marshal({"e": FILTER_RES})

    def test_default_namespace_fallback(self):
        s = ExtenderResultStore()
        s.add_filter_result({"Pod": {"metadata": {"name": "p"}}}, FILTER_RES, "e")
        assert s.get_stored_result({"metadata": {"name": "p"}}) is not None
