"""Table-driven ExtenderResultStore semantics, mirroring the reference's
extender result-store test tables (simulator/scheduler/extender/resultstore/
resultstore_test.go:16-1195): GetStoredResult with full/partial/absent data,
per-verb overwrite keyed by (pod key, extender host), and DeleteData.
"""

import json

import pytest

from kube_scheduler_simulator_tpu.scheduler.extender import ExtenderResultStore
from kube_scheduler_simulator_tpu.store import annotations as ann


def pod(ns="default", name="pod1"):
    return {"metadata": {"namespace": ns, "name": name}}


def args_for(ns="default", name="pod1"):
    return {"Pod": {"metadata": {"namespace": ns, "name": name}}}


# inputs in Go-struct casing (as a hand-rolled extender might answer);
# the store canonicalizes to the extender/v1 JSON-tag wire form exactly as
# the reference's struct round-trip does
FILTER_RES = {"Nodes": None, "NodeNames": ["node1"], "FailedNodes": {}, "Error": ""}
FILTER_WIRE = {"nodenames": ["node1"]}
PRIO_RES = [{"Host": "node1", "Score": 1}]
PRIO_WIRE = [{"host": "node1", "score": 1}]
PREEMPT_RES = {"NodeNameToMetaVictims": {"node1": {"Pods": []}}}
PREEMPT_WIRE = {"nodeNameToMetaVictims": {"node1": {}}}
BIND_RES = {"Error": ""}
BIND_WIRE = {}


class TestGetStoredResult:
    # resultstore_test.go:27 "success"
    def test_success_all_verbs(self):
        s = ExtenderResultStore()
        s.add_filter_result(args_for(), FILTER_RES, "extenderserver")
        s.add_prioritize_result(args_for(), PRIO_RES, "extenderserver")
        s.add_preempt_result(args_for(), PREEMPT_RES, "extenderserver")
        s.add_bind_result(
            {"PodNamespace": "default", "PodName": "pod1"}, BIND_RES, "extenderserver")
        got = s.get_stored_result(pod())
        assert set(got) == {
            ann.EXTENDER_FILTER_RESULT, ann.EXTENDER_PRIORITIZE_RESULT,
            ann.EXTENDER_PREEMPT_RESULT, ann.EXTENDER_BIND_RESULT,
        }
        assert json.loads(got[ann.EXTENDER_FILTER_RESULT]) == {
            "extenderserver": FILTER_WIRE}
        assert json.loads(got[ann.EXTENDER_PRIORITIZE_RESULT]) == {
            "extenderserver": PRIO_WIRE}

    # resultstore_test.go:112 "do nothing if store doesn't have data"
    def test_absent_pod_returns_none(self):
        s = ExtenderResultStore()
        assert s.get_stored_result(pod()) is None
        # a result for a DIFFERENT pod must not leak
        s.add_filter_result(args_for(name="other"), FILTER_RES, "e1")
        assert s.get_stored_result(pod()) is None

    # resultstore_test.go:122 "success without some data on store":
    # verbs never recorded still serialize, as empty maps
    def test_partial_data_serializes_empty_maps(self):
        s = ExtenderResultStore()
        s.add_filter_result(args_for(), FILTER_RES, "extenderserver")
        got = s.get_stored_result(pod())
        assert json.loads(got[ann.EXTENDER_FILTER_RESULT]) == {
            "extenderserver": FILTER_WIRE}
        for key in (ann.EXTENDER_PRIORITIZE_RESULT, ann.EXTENDER_PREEMPT_RESULT,
                    ann.EXTENDER_BIND_RESULT):
            assert got[key] == "{}"


ADD_CASES = [
    ("filter", lambda s, a, r, h: s.add_filter_result(a, r, h),
     FILTER_RES, FILTER_WIRE,
     {"Nodes": None, "NodeNames": ["node2"], "FailedNodes": {}, "Error": ""},
     {"nodenames": ["node2"]},
     ann.EXTENDER_FILTER_RESULT),
    ("prioritize", lambda s, a, r, h: s.add_prioritize_result(a, r, h),
     PRIO_RES, PRIO_WIRE,
     [{"Host": "node2", "Score": 7}], [{"host": "node2", "score": 7}],
     ann.EXTENDER_PRIORITIZE_RESULT),
    ("preempt", lambda s, a, r, h: s.add_preempt_result(a, r, h),
     PREEMPT_RES, PREEMPT_WIRE,
     {"NodeNameToMetaVictims": {"n2": {"NumPDBViolations": 2}}},
     {"nodeNameToMetaVictims": {"n2": {"numPDBViolations": 2}}},
     ann.EXTENDER_PREEMPT_RESULT),
]


@pytest.mark.parametrize("verb,add,res1,wire1,res2,wire2,anno_key",
                         ADD_CASES, ids=[c[0] for c in ADD_CASES])
class TestAddResultTables:
    # "overwrite to the already stored data which has the same key and hostname"
    def test_same_key_same_host_overwrites(self, verb, add, res1, wire1, res2,
                                           wire2, anno_key):
        s = ExtenderResultStore()
        add(s, args_for(), res1, "extenderserver")
        add(s, args_for(), res2, "extenderserver")
        got = json.loads(s.get_stored_result(pod())[anno_key])
        assert got == {"extenderserver": wire2}

    # "shouldn't overwrite ... same key and different hostname"
    def test_same_key_different_host_keeps_both(self, verb, add, res1, wire1,
                                                res2, wire2, anno_key):
        s = ExtenderResultStore()
        add(s, args_for(), res1, "extender-a")
        add(s, args_for(), res2, "extender-b")
        got = json.loads(s.get_stored_result(pod())[anno_key])
        assert got == {"extender-a": wire1, "extender-b": wire2}

    # "overwrite to the already stored data which has the different key and
    # same hostname" — results are per-pod; another pod's entry is untouched
    def test_different_key_same_host_independent(self, verb, add, res1, wire1,
                                                 res2, wire2, anno_key):
        s = ExtenderResultStore()
        add(s, args_for(name="pod1"), res1, "extenderserver")
        add(s, args_for(name="pod2"), res2, "extenderserver")
        assert json.loads(s.get_stored_result(pod(name="pod1"))[anno_key]) == {
            "extenderserver": wire1}
        assert json.loads(s.get_stored_result(pod(name="pod2"))[anno_key]) == {
            "extenderserver": wire2}


class TestAddBindResult:
    # bind args carry PodNamespace/PodName directly (ExtenderBindingArgs)
    def test_bind_key_from_binding_args(self):
        s = ExtenderResultStore()
        s.add_bind_result(
            {"PodNamespace": "ns1", "PodName": "p"}, BIND_RES, "extenderserver")
        got = s.get_stored_result(pod(ns="ns1", name="p"))
        assert json.loads(got[ann.EXTENDER_BIND_RESULT]) == {
            "extenderserver": BIND_WIRE}

    def test_bind_overwrite_same_host(self):
        s = ExtenderResultStore()
        s.add_bind_result({"PodNamespace": "ns1", "PodName": "p"},
                          {"Error": "first"}, "e")
        s.add_bind_result({"PodNamespace": "ns1", "PodName": "p"},
                          {"Error": "second"}, "e")
        got = json.loads(s.get_stored_result(pod(ns="ns1", name="p"))[
            ann.EXTENDER_BIND_RESULT])
        assert got == {"e": {"error": "second"}}

    def test_bind_two_hosts(self):
        s = ExtenderResultStore()
        s.add_bind_result({"PodNamespace": "ns1", "PodName": "p"}, {"Error": ""}, "e1")
        s.add_bind_result({"PodNamespace": "ns1", "PodName": "p"}, {"Error": "x"}, "e2")
        got = json.loads(s.get_stored_result(pod(ns="ns1", name="p"))[
            ann.EXTENDER_BIND_RESULT])
        assert got == {"e1": {}, "e2": {"error": "x"}}


class TestDeleteData:
    # resultstore_test.go:1011 "success to delete the stored data which has
    # the specified key" — only that pod's entry goes away
    def test_delete_specified_key_only(self):
        s = ExtenderResultStore()
        s.add_filter_result(args_for(name="pod1"), FILTER_RES, "e")
        s.add_filter_result(args_for(name="pod2"), FILTER_RES, "e")
        s.delete_data(pod(name="pod1"))
        assert s.get_stored_result(pod(name="pod1")) is None
        assert s.get_stored_result(pod(name="pod2")) is not None

    # resultstore_test.go:1111 "do nothing if store doesn't have the data"
    def test_delete_absent_is_noop(self):
        s = ExtenderResultStore()
        s.add_filter_result(args_for(name="pod2"), FILTER_RES, "e")
        s.delete_data(pod(name="absent"))
        assert s.get_stored_result(pod(name="pod2")) is not None

    def test_readd_after_delete(self):
        s = ExtenderResultStore()
        s.add_filter_result(args_for(), FILTER_RES, "e")
        s.delete_data(pod())
        s.add_prioritize_result(args_for(), PRIO_RES, "e")
        got = s.get_stored_result(pod())
        # filter blob is empty again: delete dropped the whole entry
        assert got[ann.EXTENDER_FILTER_RESULT] == "{}"
        assert json.loads(got[ann.EXTENDER_PRIORITIZE_RESULT]) == {"e": PRIO_WIRE}


class TestCanonicalization:
    """The wire bytes a Go struct round-trip would produce: declaration
    order (NOT alphabetical), omitempty, unknown fields dropped, map keys
    sorted (hand-derived from k8s.io/kube-scheduler/extender/v1 types)."""

    def test_filter_declaration_order_beats_alphabetical(self):
        from kube_scheduler_simulator_tpu.scheduler.extender import (
            canonicalize_result, marshal_wire)

        res = {"NodeNames": ["n1"], "Nodes": None,
               "Error": "boom", "FailedNodes": {"zz": "no", "aa": "no"}}
        wire = marshal_wire({"h": canonicalize_result("filter", res)})
        # struct order: nodes, nodenames, failedNodes, ..., error —
        # "nodenames" would sort BEFORE "nodes" alphabetically; failedNodes
        # map keys sorted; nil *NodeList dropped by omitempty
        assert wire == ('{"h":{"nodenames":["n1"],'
                        '"failedNodes":{"aa":"no","zz":"no"},'
                        '"error":"boom"}}')

    def test_non_nil_nodes_object_passes_through(self):
        from kube_scheduler_simulator_tpu.scheduler.extender import (
            canonicalize_result)

        # a non-nil *NodeList is emitted (omitempty only skips nil
        # pointers); its inner v1.Node objects travel verbatim
        got = canonicalize_result("filter", {"Nodes": {"items": []}})
        assert got == {"nodes": {"items": []}}

    def test_meta_victims_declaration_order(self):
        from kube_scheduler_simulator_tpu.scheduler.extender import (
            canonicalize_result, marshal_wire)

        res = {"NodeNameToMetaVictims": {
            "n1": {"NumPDBViolations": 1, "Pods": [{"UID": "u1"}]}}}
        wire = marshal_wire({"h": canonicalize_result("preempt", res)})
        # MetaVictims declares pods BEFORE numPDBViolations
        assert wire == ('{"h":{"nodeNameToMetaVictims":'
                        '{"n1":{"pods":[{"uid":"u1"}],"numPDBViolations":1}}}}')

    def test_host_priority_no_omitempty(self):
        from kube_scheduler_simulator_tpu.scheduler.extender import (
            canonicalize_result, marshal_wire)

        # zero score and empty host are still emitted (no omitempty tags)
        wire = marshal_wire({"h": canonicalize_result("prioritize",
                                                      [{"Score": 0}])})
        assert wire == '{"h":[{"host":"","score":0}]}'

    def test_unknown_fields_dropped(self):
        from kube_scheduler_simulator_tpu.scheduler.extender import (
            canonicalize_result)

        got = canonicalize_result("filter", {"nodenames": ["n1"],
                                             "x-debug": "internal"})
        assert got == {"nodenames": ["n1"]}

    def test_empty_nodenames_slice_is_emitted(self):
        """*[]string omitempty drops only nil: {\"nodenames\": []} is a
        nodeCacheCapable 'reject every node' and must survive into the
        record, distinct from 'no restriction'."""
        from kube_scheduler_simulator_tpu.scheduler.extender import (
            canonicalize_result, marshal_wire)

        wire = marshal_wire({"h": canonicalize_result(
            "filter", {"nodenames": [], "Error": ""})})
        assert wire == '{"h":{"nodenames":[]}}'

    def test_lenient_preempt_victims_recorded_as_meta(self):
        """A NodeNameToVictims answer (full pod objects) narrows
        preemption, so the record must show it — converted to the
        canonical nodeNameToMetaVictims (uids) form."""
        from kube_scheduler_simulator_tpu.scheduler.extender import (
            canonicalize_result)

        got = canonicalize_result("preempt", {"nodeNameToVictims": {
            "n1": {"Pods": [{"metadata": {"name": "v", "uid": "u-1"}}],
                   "NumPDBViolations": 2}}})
        assert got == {"nodeNameToMetaVictims": {
            "n1": {"pods": [{"uid": "u-1"}], "numPDBViolations": 2}}}

    def test_hosts_sorted_in_blob(self):
        from kube_scheduler_simulator_tpu.scheduler.extender import marshal_wire

        wire = marshal_wire({"zz": {}, "aa": {}})
        assert wire == '{"aa":{},"zz":{}}'


class TestWireFormat:
    def test_annotation_keys_exact(self):
        prefix = "kube-scheduler-simulator.sigs.k8s.io/"
        assert ann.EXTENDER_FILTER_RESULT == prefix + "extender-filter-result"
        assert ann.EXTENDER_PRIORITIZE_RESULT == prefix + "extender-prioritize-result"
        assert ann.EXTENDER_PREEMPT_RESULT == prefix + "extender-preempt-result"
        assert ann.EXTENDER_BIND_RESULT == prefix + "extender-bind-result"

    def test_go_compact_json(self):
        s = ExtenderResultStore()
        s.add_filter_result(args_for(), FILTER_RES, "e")
        blob = s.get_stored_result(pod())[ann.EXTENDER_FILTER_RESULT]
        # Go json.Marshal: compact (no spaces), canonical tags, omitempty
        assert ": " not in blob and ", " not in blob
        assert blob == '{"e":{"nodenames":["node1"]}}'

    def test_default_namespace_fallback(self):
        s = ExtenderResultStore()
        s.add_filter_result({"Pod": {"metadata": {"name": "p"}}}, FILTER_RES, "e")
        assert s.get_stored_result({"metadata": {"name": "p"}}) is not None
