"""KubeAPICluster: the real kube-apiserver source adapter, driven against
an in-process fake apiserver speaking the kube wire protocol (list /
labelSelector / streaming watch with resume, bookmarks, and 410 Gone /
auth headers) — the fixture stands in for the real cluster the
reference's importer/syncer/recorder dial via client-go (reference:
simulator/oneshotimporter/importer.go:29-37, syncer/syncer.go:53-74,
cmd/sched-recorder/recorder.go:69-93)."""

from __future__ import annotations

import base64
import http.server
import json
import queue
import ssl
import threading
import time

import pytest

from kube_scheduler_simulator_tpu.cluster.kubeapi import (
    KubeAPICluster, connect_source, load_kubeconfig, _label_selector_str)
from kube_scheduler_simulator_tpu.cluster.remote import RemoteCluster
from kube_scheduler_simulator_tpu.cluster.store import ADDED, MODIFIED, ObjectStore
from kube_scheduler_simulator_tpu.services.importer import OneShotImporter
from kube_scheduler_simulator_tpu.services.resourceapplier import ResourceApplier


def _pod(name, ns="default", rv="101", labels=None):
    return {"metadata": {"name": name, "namespace": ns,
                         "resourceVersion": rv,
                         **({"labels": labels} if labels else {})},
            "spec": {"containers": [{"name": "c"}]}}


class _FakeAPIServer:
    """Minimal kube-apiserver: /apis discovery, typed list endpoints with
    labelSelector, streaming watch fed from a per-resource script queue."""

    def __init__(self):
        self.objects = {"pods": [], "nodes": [], "namespaces": [],
                        "priorityclasses": [], "storageclasses": [],
                        "persistentvolumes": [], "persistentvolumeclaims": []}
        self.list_rv = "1000"
        self.watch_script: dict[str, queue.Queue] = {}
        self.requests: list[tuple[str, str, dict]] = []  # (method, path, query)
        self.auth_seen: list[str | None] = []
        srv = self

        class Handler(http.server.BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def _send_json(self, obj, code=200):
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                from urllib.parse import parse_qs, urlparse

                u = urlparse(self.path)
                q = {k: v[0] for k, v in parse_qs(u.query).items()}
                srv.requests.append(("GET", u.path, q))
                srv.auth_seen.append(self.headers.get("Authorization"))
                if u.path == "/apis":
                    return self._send_json({"kind": "APIGroupList",
                                            "groups": [{"name": "apps"}]})
                resource = u.path.rsplit("/", 1)[-1]
                if resource not in srv.objects:
                    return self._send_json({"kind": "Status", "code": 404},
                                           404)
                if q.get("watch") == "true":
                    return self._stream_watch(resource)
                items = srv.objects[resource]
                sel = q.get("labelSelector")
                if sel:
                    want = dict(p.split("=", 1) for p in sel.split(",")
                                if "=" in p and " " not in p)
                    items = [o for o in items
                             if all(((o.get("metadata") or {})
                                     .get("labels") or {}).get(k) == v
                                    for k, v in want.items())]
                kind = resource[:-1].capitalize() + "List"
                return self._send_json(
                    {"kind": kind, "apiVersion": "v1",
                     "metadata": {"resourceVersion": srv.list_rv},
                     "items": items})

            def _stream_watch(self, resource):
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Transfer-Encoding", "chunked")
                self.end_headers()
                script = srv.watch_script.get(resource)
                while script is not None:
                    try:
                        ev = script.get(timeout=5)
                    except queue.Empty:
                        break
                    if ev is None:  # close the stream
                        break
                    data = json.dumps(ev).encode() + b"\n"
                    self.wfile.write(hex(len(data))[2:].encode() + b"\r\n"
                                     + data + b"\r\n")
                    self.wfile.flush()
                self.wfile.write(b"0\r\n\r\n")

            def do_POST(self):
                n = int(self.headers.get("Content-Length") or 0)
                obj = json.loads(self.rfile.read(n) or b"{}")
                srv.requests.append(("POST", self.path, {}))
                resource = self.path.rsplit("/", 1)[-1]
                if resource in srv.objects:
                    srv.objects[resource].append(obj)
                self._send_json(obj, 201)

        self.httpd = http.server.ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.port = self.httpd.server_address[1]
        self.url = f"http://127.0.0.1:{self.port}"
        threading.Thread(target=self.httpd.serve_forever, daemon=True).start()

    def close(self):
        self.httpd.shutdown()
        self.httpd.server_close()


@pytest.fixture()
def api():
    srv = _FakeAPIServer()
    yield srv
    srv.close()


def test_label_selector_forms():
    assert _label_selector_str({"app": "web"}) == "app=web"
    assert _label_selector_str(
        {"matchLabels": {"a": "1"},
         "matchExpressions": [
             {"key": "tier", "operator": "In", "values": ["fe", "be"]},
             {"key": "gone", "operator": "DoesNotExist"}]}
    ) == "a=1,tier in (fe,be),!gone"
    assert _label_selector_str("raw=str") == "raw=str"


def test_list_and_label_selector(api):
    api.objects["pods"] = [_pod("a", labels={"app": "web"}),
                           _pod("b", labels={"app": "db"})]
    c = KubeAPICluster(base_url=api.url)
    items, rv = c.list("pods")
    assert [o["metadata"]["name"] for o in items] == ["a", "b"]
    assert rv == 1000
    # list items get kind/apiVersion stamped like dynamic listers
    assert items[0]["kind"] == "Pod" and items[0]["apiVersion"] == "v1"
    only_web, _ = c.list("pods", label_selector={"app": "web"})
    assert [o["metadata"]["name"] for o in only_web] == ["a"]
    sent = [q for m, p, q in api.requests if p.endswith("/pods") and q]
    assert sent[-1]["labelSelector"] == "app=web"


def test_api_group_paths(api):
    c = KubeAPICluster(base_url=api.url)
    c.list("priorityclasses")
    c.list("storageclasses")
    paths = [p for _, p, _ in api.requests]
    assert "/apis/scheduling.k8s.io/v1/priorityclasses" in paths
    assert "/apis/storage.k8s.io/v1/storageclasses" in paths


def test_connect_source_probes_apis(api):
    src = connect_source(api.url)
    assert isinstance(src, KubeAPICluster)


def test_connect_source_falls_back_to_simulator():
    # a server without /apis (the simulator) -> RemoteCluster
    class H(http.server.BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_GET(self):
            self.send_response(404)
            self.send_header("Content-Length", "0")
            self.end_headers()

    httpd = http.server.ThreadingHTTPServer(("127.0.0.1", 0), H)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        src = connect_source(f"http://127.0.0.1:{httpd.server_address[1]}")
        assert isinstance(src, RemoteCluster)
    finally:
        httpd.shutdown()
        httpd.server_close()


def test_kubeconfig_token_auth(api, tmp_path):
    kc = {
        "current-context": "test",
        "contexts": [{"name": "test",
                      "context": {"cluster": "c1", "user": "u1"}}],
        "clusters": [{"name": "c1", "cluster": {"server": api.url}}],
        "users": [{"name": "u1", "user": {"token": "sekret-token"}}],
    }
    p = tmp_path / "kubeconfig.yaml"
    p.write_text(json.dumps(kc))  # JSON is valid YAML
    c = KubeAPICluster(kubeconfig=str(p))
    c.list("nodes")
    assert "Bearer sekret-token" in api.auth_seen


def test_kubeconfig_basic_auth_and_ca_data(tmp_path):
    ca_pem = b"-----BEGIN CERTIFICATE-----\nnotreal\n-----END CERTIFICATE-----\n"
    kc = {
        "current-context": "test",
        "contexts": [{"name": "test",
                      "context": {"cluster": "c1", "user": "u1"}}],
        "clusters": [{"name": "c1", "cluster": {
            "server": "https://example:6443",
            "insecure-skip-tls-verify": True,
            "certificate-authority-data":
                base64.b64encode(ca_pem).decode()}}],
        "users": [{"name": "u1", "user": {"username": "admin",
                                          "password": "pw"}}],
    }
    p = tmp_path / "kc.yaml"
    p.write_text(json.dumps(kc))
    server, sslctx, headers = load_kubeconfig(str(p))
    assert server == "https://example:6443"
    assert sslctx is not None and sslctx.verify_mode == ssl.CERT_NONE
    cred = base64.b64decode(headers["Authorization"].split()[1]).decode()
    assert cred == "admin:pw"


def test_kubeconfig_missing_context_raises(tmp_path):
    p = tmp_path / "kc.yaml"
    p.write_text(json.dumps({"clusters": [], "users": [], "contexts": []}))
    with pytest.raises(ValueError):
        load_kubeconfig(str(p))


def _drain(q, n, timeout=10.0):
    out = []
    deadline = time.time() + timeout
    while len(out) < n and time.time() < deadline:
        try:
            out.append(q.get(timeout=0.2))
        except queue.Empty:
            pass
    return out


def test_watch_list_then_events_then_resume(api):
    api.objects["pods"] = [_pod("pre", rv="50")]
    script = api.watch_script["pods"] = queue.Queue()
    c = KubeAPICluster(base_url=api.url)
    q = c.watch("pods")
    # initial state arrives as ADDED (client-go ListAndWatch semantics)
    (rv0, t0, o0), = _drain(q, 1)
    assert t0 == ADDED and o0["metadata"]["name"] == "pre" and rv0 == 50
    script.put({"type": "BOOKMARK",
                "object": {"metadata": {"resourceVersion": "1200"}}})
    script.put({"type": "MODIFIED", "object": _pod("pre", rv="1201")})
    script.put(None)  # server closes; client must RECONNECT with resume rv
    (rv1, t1, o1), = _drain(q, 1)
    assert t1 == MODIFIED and rv1 == 1201
    deadline = time.time() + 10
    while time.time() < deadline:
        rvs = [qd.get("resourceVersion") for m, p, qd in api.requests
               if qd.get("watch") == "true"]
        if "1201" in rvs:
            break
        time.sleep(0.1)
    assert "1201" in rvs, f"no resumed watch seen: {rvs}"
    c.unwatch("pods", q)
    c.stop()


def test_watch_410_relists(api):
    api.objects["pods"] = [_pod("x", rv="7")]
    script = api.watch_script["pods"] = queue.Queue()
    c = KubeAPICluster(base_url=api.url)
    q = c.watch("pods")
    _drain(q, 1)  # initial ADDED
    script.put({"type": "ERROR",
                "object": {"kind": "Status", "code": 410, "reason": "Gone"}})
    # Gone -> full re-list: the object comes around again as ADDED
    (rv, t, o), = _drain(q, 1)
    assert t == ADDED and o["metadata"]["name"] == "x"
    c.unwatch("pods", q)
    c.stop()


def test_importer_from_real_apiserver(api):
    api.objects["namespaces"] = [
        {"metadata": {"name": "team-a", "resourceVersion": "1"}}]
    api.objects["nodes"] = [
        {"metadata": {"name": "n1", "resourceVersion": "2"},
         "status": {"allocatable": {"cpu": "4", "memory": "8Gi"},
                    "capacity": {"cpu": "4", "memory": "8Gi"}}}]
    api.objects["pods"] = [_pod("p1", ns="team-a")]
    store = ObjectStore()
    importer = OneShotImporter(KubeAPICluster(base_url=api.url),
                               ResourceApplier(store))
    n = importer.import_cluster_resources()
    assert n == 3
    assert store.get("nodes", "n1")["metadata"]["name"] == "n1"
    assert store.get("pods", "p1", "team-a")["metadata"]["name"] == "p1"


def test_recorder_from_real_apiserver(api, tmp_path):
    from kube_scheduler_simulator_tpu.services.recorder import RecorderService

    api.objects["nodes"] = [
        {"metadata": {"name": "n1", "resourceVersion": "2"}}]
    for r in api.objects:
        api.watch_script[r] = queue.Queue()
    path = tmp_path / "record.jsonl"
    rec = RecorderService(KubeAPICluster(base_url=api.url), str(path),
                          flush_interval=0.1)
    rec.run()
    api.watch_script["pods"].put(
        {"type": "ADDED", "object": _pod("newpod", rv="88")})
    deadline = time.time() + 10
    want = {("Add", "n1"), ("Add", "newpod")}
    got = set()
    while time.time() < deadline and not want <= got:
        time.sleep(0.15)
        lines = [json.loads(x) for x in
                 path.read_text().splitlines() if x.strip()]
        got = {(r["event"], r["resource"]["metadata"]["name"])
               for r in lines}
    rec.stop()
    assert want <= got, got


def test_watch_survives_last_unwatch_during_late_subscribe(api, monkeypatch):
    """The late-subscriber buffer registration happens under the same
    lock hold as the loop-thread check: if the only existing subscriber
    unwatches while the newcomer is doing its ADDED-replay list, the
    shared loop thread must stay alive (the newcomer's buffer already
    holds the fan-out slot) and live events keep flowing — the two-lock
    version left the newcomer attached to a dead fan-out (ADVICE
    round-5)."""
    api.objects["pods"] = [_pod("pre", rv="50")]
    script = api.watch_script["pods"] = queue.Queue()
    c = KubeAPICluster(base_url=api.url)
    q1 = c.watch("pods")
    _drain(q1, 1)  # initial ADDED replay

    # second subscriber: drop the FIRST subscriber during the newcomer's
    # replay list — exactly the window where the old code's second lock
    # acquisition registered the buffer after the loop had been stopped
    real_list = c._list_raw

    def racing_list(resource, namespace=None, label_selector=None):
        c.unwatch("pods", q1)
        return real_list(resource, namespace, label_selector)

    monkeypatch.setattr(c, "_list_raw", racing_list)
    q2 = c.watch("pods")
    monkeypatch.setattr(c, "_list_raw", real_list)
    (rv0, t0, o0), = _drain(q2, 1)  # the newcomer's own ADDED replay
    assert t0 == ADDED and o0["metadata"]["name"] == "pre"

    # live events must still arrive: the shared loop was not stopped
    script.put({"type": "MODIFIED", "object": _pod("pre", rv="1300")})
    (rv1, t1, o1), = _drain(q2, 1)
    assert t1 == MODIFIED and rv1 == 1300
    c.unwatch("pods", q2)
    c.stop()


def test_late_subscriber_dedup_uses_exact_rv_strings(api, monkeypatch):
    """Late-subscriber handover dedup compares the server's EXACT
    resourceVersion strings, not the synthesized _rv_int counters: with
    non-integer rvs the counters are assigned in arrival order — the
    listed snapshot's counters are minted AFTER the buffered events', so
    every buffered event compared "older" and a legitimately NEWER update
    (different rv string) was silently dropped (ADVICE round-5 #3).  A
    buffered event whose rv EQUALS the listed object's is the very state
    the snapshot carries and stays deduped; unlisted keys pass through."""
    api.objects["pods"] = [_pod("pre", rv="rv-snapshot")]
    api.watch_script["pods"] = queue.Queue()
    c = KubeAPICluster(base_url=api.url)
    q1 = c.watch("pods")
    _drain(q1, 1)  # initial ADDED replay

    # second subscriber: while its replay list is in flight, three events
    # land in its handover buffer (the fan-out already carries it)
    real_list = c._list_raw

    def racing_list(resource, namespace=None, label_selector=None):
        out = real_list(resource, namespace, label_selector)
        c._fanout("pods", (c._rv_int("rv-mid"), MODIFIED,
                           _pod("pre", rv="rv-mid")))       # pre-snapshot
        c._fanout("pods", (c._rv_int("rv-snapshot"), MODIFIED,
                           _pod("pre", rv="rv-snapshot")))  # = snapshot
        c._fanout("pods", (c._rv_int("rv-newer"), MODIFIED,
                           _pod("pre", rv="rv-newer")))     # newer update
        c._fanout("pods", (c._rv_int("rv-ghost"), ADDED,
                           _pod("ghost", rv="rv-ghost")))   # unlisted key
        return out

    monkeypatch.setattr(c, "_list_raw", racing_list)
    q2 = c.watch("pods")
    monkeypatch.setattr(c, "_list_raw", real_list)

    got = _drain(q2, 3, timeout=5.0)
    seen = [(t, o["metadata"]["name"], o["metadata"]["resourceVersion"])
            for _, t, o in got]
    assert (ADDED, "pre", "rv-snapshot") in seen        # the snapshot
    assert (MODIFIED, "pre", "rv-newer") in seen        # NOT dropped
    assert (ADDED, "ghost", "rv-ghost") in seen         # unlisted key
    # the equal-rv buffered event was deduped against the snapshot, and
    # so was the OLDER intermediate that preceded it in the buffer —
    # re-delivering it would regress the subscriber behind the ADDED
    assert (MODIFIED, "pre", "rv-snapshot") not in seen
    assert (MODIFIED, "pre", "rv-mid") not in seen
    c.unwatch("pods", q1)
    c.unwatch("pods", q2)
    c.stop()


def test_late_subscriber_handover_delete_recreate_incarnations(api, monkeypatch):
    """Delete+recreate racing the handover, discriminated by uid: events
    of an incarnation OLDER than the listed object (different uid before
    the listed one's DELETED) are dropped — their DELETED must not remove
    the live object — while a post-list recreate (different uid AFTER the
    listed incarnation's DELETED) is delivered, or the subscriber never
    learns the new object exists."""
    def _upod(name, rv, uid):
        p = _pod(name, rv=rv)
        p["metadata"]["uid"] = uid
        return p

    api.objects["pods"] = [_upod("pre", "rv-snapshot", "uid-A")]
    api.watch_script["pods"] = queue.Queue()
    c = KubeAPICluster(base_url=api.url)
    q1 = c.watch("pods")
    _drain(q1, 1)

    from kube_scheduler_simulator_tpu.cluster.store import DELETED

    real_list = c._list_raw

    def racing_list(resource, namespace=None, label_selector=None):
        out = real_list(resource, namespace, label_selector)
        # an OLDER incarnation's tail (uid-Z predates the listed uid-A)
        c._fanout("pods", (c._rv_int("rv-z1"), MODIFIED,
                           _upod("pre", "rv-z1", "uid-Z")))
        c._fanout("pods", (c._rv_int("rv-z2"), DELETED,
                           _upod("pre", "rv-z2", "uid-Z")))
        # the listed incarnation dies post-list, then a recreate
        c._fanout("pods", (c._rv_int("rv-del"), DELETED,
                           _upod("pre", "rv-del", "uid-A")))
        c._fanout("pods", (c._rv_int("rv-new"), ADDED,
                           _upod("pre", "rv-new", "uid-B")))
        return out

    monkeypatch.setattr(c, "_list_raw", racing_list)
    q2 = c.watch("pods")
    monkeypatch.setattr(c, "_list_raw", real_list)

    got = _drain(q2, 3, timeout=5.0)
    seen = [(t, o["metadata"]["uid"], o["metadata"]["resourceVersion"])
            for _, t, o in got]
    assert seen[0] == (ADDED, "uid-A", "rv-snapshot")      # the snapshot
    assert (DELETED, "uid-A", "rv-del") in seen            # real deletion
    assert (ADDED, "uid-B", "rv-new") in seen              # the recreate
    # the older incarnation's events never reach the subscriber
    assert not any(uid == "uid-Z" for _, uid, _rv in seen)
    c.unwatch("pods", q1)
    c.unwatch("pods", q2)
    c.stop()
