"""Focused plugin-semantics regressions (cases found in review, each a
divergence risk vs upstream v1.32 behavior)."""

import json

from kube_scheduler_simulator_tpu.framework.replay import replay
from kube_scheduler_simulator_tpu.models.workloads import make_nodes
from kube_scheduler_simulator_tpu.plugins.registry import PluginSetConfig
from kube_scheduler_simulator_tpu.reference_impl.sequential import SequentialScheduler
from kube_scheduler_simulator_tpu.state.compile import compile_workload
from kube_scheduler_simulator_tpu.store import annotations as ann
from kube_scheduler_simulator_tpu.store.decode import decode_pod_result


def run_both(nodes, pods, cfg, bound=None):
    seq = SequentialScheduler(nodes, pods, cfg, bound_pods=bound).schedule_all()
    rr = replay(compile_workload(nodes, pods, cfg, bound_pods=bound), chunk=16)
    dev = [(decode_pod_result(rr, i), int(rr.selected[i])) for i in range(len(pods))]
    for i, ((sa, ss), (da, ds)) in enumerate(zip(seq, dev)):
        assert ss == ds, f"pod {i} selection: seq={ss} dev={ds}"
        assert sa == da, f"pod {i} annotations diverge"
    return seq


def mini_pod(name, cpu="100m", labels=None, **spec_extra):
    spec = {"containers": [{"name": "c", "resources": {"requests": {"cpu": cpu}}}]}
    spec.update(spec_extra)
    return {"metadata": {"name": name, "namespace": "default", "labels": labels or {}},
            "spec": spec}


def small_nodes(n=3):
    return [
        {"metadata": {"name": f"n{i}", "labels": {"zone": f"z{i % 2}"}},
         "status": {"allocatable": {"cpu": "1", "memory": "2Gi", "pods": "110"}}}
        for i in range(n)
    ]


def test_zero_request_pod_on_overcommitted_node():
    """Upstream fitsRequest early-returns for zero-request pods; an
    overcommitted node (bound pods exceed allocatable) must still accept
    them — only 'Too many pods' can fail."""
    nodes = small_nodes(2)
    # overcommit n0 beyond allocatable via bound pods
    bound = [(mini_pod(f"big{i}", cpu="900m"), "n0") for i in range(3)]
    zero = {"metadata": {"name": "zero", "namespace": "default"},
            "spec": {"containers": [{"name": "c"}]}}
    cfg = PluginSetConfig(enabled=["NodeResourcesFit"])
    seq = run_both(nodes, [zero], cfg, bound=bound)
    fr = json.loads(seq[0][0][ann.FILTER_RESULT])
    assert fr["n0"]["NodeResourcesFit"] == "passed"


def test_nodename_always_records():
    """NodeName has no PreFilter: it must appear in filter-result for pods
    without spec.nodeName too (upstream records 'passed' everywhere)."""
    nodes = small_nodes(2)
    cfg = PluginSetConfig(enabled=["NodeName", "NodeResourcesFit"])
    seq = run_both(nodes, [mini_pod("p")], cfg)
    fr = json.loads(seq[0][0][ann.FILTER_RESULT])
    assert fr["n0"]["NodeName"] == "passed"


def test_nodename_pinned():
    nodes = small_nodes(3)
    cfg = PluginSetConfig(enabled=["NodeName", "NodeResourcesFit"])
    seq = run_both(nodes, [mini_pod("p", nodeName="n2")], cfg)
    assert seq[0][0][ann.SELECTED_NODE] == "n2"
    fr = json.loads(seq[0][0][ann.FILTER_RESULT])
    assert fr["n0"]["NodeName"] == "node(s) didn't match the requested node name"


def test_gt_expression_invalid_values_never_match():
    nodes = [
        {"metadata": {"name": "n0", "labels": {"gpu-count": "4"}},
         "status": {"allocatable": {"cpu": "1", "memory": "1Gi", "pods": "10"}}},
    ]
    aff = {"nodeAffinity": {"requiredDuringSchedulingIgnoredDuringExecution": {
        "nodeSelectorTerms": [{"matchExpressions": [
            {"key": "gpu-count", "operator": "Gt", "values": []}  # invalid
        ]}]}}}
    cfg = PluginSetConfig(enabled=["NodeAffinity", "NodeResourcesFit"])
    seq = run_both(nodes, [mini_pod("p", affinity=aff)], cfg)
    assert seq[0][1] == -1  # invalid Gt matches nothing -> unschedulable


def test_first_pod_self_affinity_escape_ignores_unkeyed_nodes():
    """A bound pod on a node WITHOUT the term's topologyKey must not block
    the first-pod-in-series affinity escape (upstream only counts keyed
    nodes in affinityCounts)."""
    nodes = [
        {"metadata": {"name": "keyed", "labels": {"zone": "z1"}},
         "status": {"allocatable": {"cpu": "4", "memory": "8Gi", "pods": "110"}}},
        {"metadata": {"name": "unkeyed"},  # no zone label
         "status": {"allocatable": {"cpu": "4", "memory": "8Gi", "pods": "110"}}},
    ]
    # bound pod matching the selector sits on the UNKEYED node
    bound = [(mini_pod("existing", labels={"app": "db"}), "unkeyed")]
    aff = {"podAffinity": {"requiredDuringSchedulingIgnoredDuringExecution": [
        {"topologyKey": "zone", "labelSelector": {"matchLabels": {"app": "db"}}},
    ]}}
    incoming = mini_pod("incoming", labels={"app": "db"}, affinity=aff)
    cfg = PluginSetConfig(enabled=["NodeResourcesFit", "InterPodAffinity"])
    seq = run_both(nodes, [incoming], cfg, bound=bound)
    # escape applies on the keyed node: schedulable there
    assert seq[0][0][ann.SELECTED_NODE] == "keyed"
