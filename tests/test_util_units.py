"""Unit tables for the utility layer and the deprecated entry alias:
SemaphoredErrGroup (reference: util/semaphored_errgroup.go:17-41),
RetryWithExponentialBackOff (util/retry.go:10-27), and the deprecated
pkg/externalscheduler analogue."""

import threading
import time

import pytest

from kube_scheduler_simulator_tpu.utils.errgroup import SemaphoredErrGroup
from kube_scheduler_simulator_tpu.utils.retry import (
    RetryTimeout,
    retry_with_exponential_backoff,
)


# ---------------------------------------------------------------- errgroup

def test_errgroup_runs_all_and_waits():
    done = []
    g = SemaphoredErrGroup(limit=4)
    for i in range(10):
        g.go(done.append, i)
    g.wait()
    assert sorted(done) == list(range(10))


def test_errgroup_bounds_concurrency():
    active = 0
    peak = 0
    lock = threading.Lock()

    def task():
        nonlocal active, peak
        with lock:
            active += 1
            peak = max(peak, active)
        time.sleep(0.01)
        with lock:
            active -= 1

    g = SemaphoredErrGroup(limit=3)
    for _ in range(12):
        g.go(task)
    g.wait()
    assert peak <= 3


def test_errgroup_reraises_first_error_in_submission_order():
    g = SemaphoredErrGroup(limit=1)
    g.go(lambda: None)
    g.go(lambda: (_ for _ in ()).throw(ValueError("first")))
    g.go(lambda: (_ for _ in ()).throw(KeyError("second")))
    with pytest.raises(ValueError, match="first"):
        g.wait()


# ------------------------------------------------------------------ retry

def test_retry_returns_after_transient_failures():
    calls = []

    def attempt():
        calls.append(1)
        return (len(calls) >= 3, None)

    retry_with_exponential_backoff(attempt, sleep=lambda _t: None)
    assert len(calls) == 3


def test_retry_exhaustion_raises_timeout():
    slept = []

    def attempt():
        return (False, None)

    with pytest.raises(RetryTimeout):
        retry_with_exponential_backoff(attempt, sleep=slept.append)
    # 100ms * 3^n, 6 attempts -> 5 inter-attempt sleeps (util/retry.go:10-27)
    assert len(slept) == 5
    assert slept[0] == pytest.approx(0.1)
    assert slept[1] == pytest.approx(0.3)
    assert slept[-1] == pytest.approx(8.1)


def test_retry_propagates_fatal_error():
    def attempt():
        return (False, RuntimeError("fatal"))

    with pytest.raises(RuntimeError, match="fatal"):
        retry_with_exponential_backoff(attempt, sleep=lambda _t: None)


# -------------------------------------------------- deprecated entry alias

def test_externalscheduler_alias_warns_and_validates():
    from kube_scheduler_simulator_tpu.plugins.custom import CustomPlugin
    from kube_scheduler_simulator_tpu.scheduler.external import (
        create_option_for_out_of_tree_plugin,
    )

    class P(CustomPlugin):
        name = "P"

        def score(self, pod, node):
            return 1

    with pytest.warns(DeprecationWarning):
        assert create_option_for_out_of_tree_plugin(P()) is not None
    with pytest.warns(DeprecationWarning):
        with pytest.raises(TypeError):
            create_option_for_out_of_tree_plugin(object())
