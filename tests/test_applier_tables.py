"""Resource-applier hook-chain tables, mirroring the reference suite
(resourceapplier/resourceapplier_test.go, resource.go): user filter/mutate
chains run in registration order ahead of the mandatory hooks, filters
short-circuit, immutable metadata is stripped, and the PV claimRef UID is
re-resolved against the destination's PVC.
"""

import pytest

from kube_scheduler_simulator_tpu.cluster.store import NotFound, ObjectStore
from kube_scheduler_simulator_tpu.services.resourceapplier import (
    ApplierOptions,
    ResourceApplier,
)


def pod(name, ns="default", **spec):
    return {"metadata": {"name": name, "namespace": ns}, "spec": dict(spec)}


class TestHookChains:
    def test_user_filter_rejects_create(self):
        s = ObjectStore()
        a = ResourceApplier(s, ApplierOptions(filter_before_creating={
            "pods": [lambda r, o: not o["metadata"]["name"].startswith("deny-")]}))
        assert a.create("pods", pod("deny-me")) is None
        with pytest.raises(NotFound):
            s.get("pods", "deny-me")
        assert a.create("pods", pod("ok")) is not None

    def test_filter_chain_short_circuits(self):
        calls = []

        def f1(r, o):
            calls.append("f1")
            return False

        def f2(r, o):
            calls.append("f2")
            return True

        s = ObjectStore()
        a = ResourceApplier(s, ApplierOptions(
            filter_before_creating={"pods": [f1, f2]}))
        assert a.create("pods", pod("x")) is None
        assert calls == ["f1"]  # later filters never run

    def test_mutate_chain_runs_in_order(self):
        s = ObjectStore()
        a = ResourceApplier(s, ApplierOptions(mutate_before_creating={
            "pods": [
                lambda r, o: {**o, "metadata": {**o["metadata"],
                                                "labels": {"step": "one"}}},
                lambda r, o: {**o, "metadata": {**o["metadata"],
                                                "labels": {"step": "two"}}},
            ]}))
        a.create("pods", pod("p"))
        assert s.get("pods", "p")["metadata"]["labels"] == {"step": "two"}

    def test_mandatory_pod_mutate_runs_after_user_mutates(self):
        """User mutates cannot smuggle serviceAccount/ownerReferences past
        the mandatory hook (registered last, resource.go:65-81)."""
        s = ObjectStore()
        a = ResourceApplier(s, ApplierOptions(mutate_before_creating={
            "pods": [lambda r, o: {**o, "spec": {**o["spec"],
                                                 "serviceAccountName": "sneak"}}]}))
        a.create("pods", pod("p"))
        got = s.get("pods", "p")
        assert "serviceAccountName" not in got["spec"]

    def test_hooks_are_per_resource(self):
        s = ObjectStore()
        a = ResourceApplier(s, ApplierOptions(filter_before_creating={
            "pods": [lambda r, o: False]}))
        assert a.create("pods", pod("p")) is None
        assert a.create("nodes", {"metadata": {"name": "n"}, "spec": {}}) is not None


class TestMandatoryHooks:
    def test_strip_immutable_on_create(self):
        s = ObjectStore()
        a = ResourceApplier(s)
        src = pod("p")
        src["metadata"].update({"uid": "src-uid", "resourceVersion": "999",
                                "generation": 7,
                                "creationTimestamp": "2020-01-01T00:00:00Z"})
        a.create("pods", src)
        got = s.get("pods", "p")
        assert got["metadata"]["uid"] != "src-uid"       # destination-assigned
        assert got["metadata"].get("generation") is None

    def test_pod_owner_references_dropped(self):
        s = ObjectStore()
        a = ResourceApplier(s)
        src = pod("p")
        src["metadata"]["ownerReferences"] = [{"kind": "ReplicaSet", "name": "rs"}]
        a.create("pods", src)
        assert "ownerReferences" not in s.get("pods", "p")["metadata"]

    def test_pv_claimref_reresolved_against_destination(self):
        s = ObjectStore()
        a = ResourceApplier(s)
        a.create("persistentvolumeclaims",
                 {"metadata": {"name": "pvc1", "namespace": "default"}, "spec": {}})
        dst_uid = s.get("persistentvolumeclaims", "pvc1")["metadata"]["uid"]
        a.create("persistentvolumes", {
            "metadata": {"name": "pv1"},
            "spec": {"claimRef": {"name": "pvc1", "namespace": "default",
                                  "uid": "stale-src-uid"}}})
        assert s.get("persistentvolumes", "pv1")["spec"]["claimRef"]["uid"] == dst_uid

    def test_pv_claimref_uid_dropped_when_pvc_missing(self):
        s = ObjectStore()
        a = ResourceApplier(s)
        a.create("persistentvolumes", {
            "metadata": {"name": "pv1"},
            "spec": {"claimRef": {"name": "ghost", "namespace": "default",
                                  "uid": "stale"}}})
        assert "uid" not in s.get("persistentvolumes", "pv1")["spec"]["claimRef"]

    def test_scheduled_pod_update_filtered_unscheduled_passes(self):
        s = ObjectStore()
        a = ResourceApplier(s)
        a.create("pods", pod("p"))
        scheduled = pod("p", nodeName="n1")
        assert a.update("pods", scheduled) is None       # filtered
        relabeled = pod("p")
        relabeled["metadata"]["labels"] = {"v": "2"}
        assert a.update("pods", relabeled) is not None   # passes
        assert s.get("pods", "p")["metadata"]["labels"] == {"v": "2"}

    def test_delete_by_identity(self):
        s = ObjectStore()
        a = ResourceApplier(s)
        a.create("pods", pod("p", ns="ns1"))
        a.delete("pods", {"metadata": {"name": "p", "namespace": "ns1"}})
        with pytest.raises(NotFound):
            s.get("pods", "p", "ns1")
