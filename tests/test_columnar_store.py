"""Golden byte-parity: the columnar data plane vs the dict baseline.

The ColumnarStatusStore (cluster/columnar.py) backs nodes/pods with
numpy hot-field columns while the dict CRUD/watch/dump surface stays a
compat shim — these tests pin the shim to the PRE-columnar store
byte-for-byte.  Every suite runs the same operation sequence against a
columnar store (KSS_TPU_COLUMNAR=1, the default) and a dict-baseline
store (KSS_TPU_COLUMNAR=0) with uuid/time pinned, and compares the raw
`json.dumps` bytes (insertion order included) of every read surface:
get, list, watch events, dump, snapshot export.  The chaos seam
`store.columnar_sync` proves a mid-sync fault leaves the shim
consistent: the row goes opaque, the manifest stays authoritative, and
the columnar node-table build re-parses it (docs/data-plane.md).

Uid pinning: lazy rows draw their uid on FIRST READ, the eager path at
create — so each store runs its ops as a phase with the pinned uuid
counter reset at the phase start, and materializes its lazy rows in row
order (materialize_reads) so both phases assign uid k to the same
logical object.
"""

from __future__ import annotations

import itertools
import json
import time
import uuid

import numpy as np
import pytest

from kube_scheduler_simulator_tpu.cluster.columnar import LazyManifest
from kube_scheduler_simulator_tpu.cluster.store import ObjectStore, list_shared
from kube_scheduler_simulator_tpu.models.workloads import (
    make_nodes_columnar, make_pods_columnar)
from kube_scheduler_simulator_tpu.utils import faults
from kube_scheduler_simulator_tpu.utils.faults import (
    FaultPlan, FaultRule)


class _UuidPin:
    def __init__(self):
        self.reset()

    def reset(self):
        self._c = itertools.count()

    def __call__(self):
        return f"00000000-0000-4000-8000-{next(self._c):012d}"


@pytest.fixture
def pin(monkeypatch):
    """Pin uuid.uuid4 (resettable counter) and the store's
    creationTimestamp clock so both stores stamp identical bytes for
    identical per-phase operation sequences."""
    p = _UuidPin()
    monkeypatch.setattr(uuid, "uuid4", p)
    monkeypatch.setattr(time, "gmtime", lambda *a: time.struct_time(
        (2026, 1, 1, 0, 0, 0, 3, 1, 0)))
    return p


def make_store(monkeypatch, columnar: bool) -> ObjectStore:
    monkeypatch.setenv("KSS_TPU_COLUMNAR", "1" if columnar else "0")
    store = ObjectStore()
    monkeypatch.delenv("KSS_TPU_COLUMNAR")
    return store


def raw(obj) -> str:
    """Raw (insertion-ordered) JSON bytes of a possibly-lazy manifest,
    materialized the way real serializers must (json's C encoder walks
    dict storage, bypassing LazyManifest's overrides)."""
    LazyManifest.ensure(obj)
    return json.dumps(obj)


def load_population(s: ObjectStore, n_nodes=40, n_pods=25):
    s.load_columnar("nodes", make_nodes_columnar(
        n_nodes, seed=3, taint_fraction=0.2, unschedulable_fraction=0.1))
    s.load_columnar("pods", make_pods_columnar(
        n_pods, seed=4, with_affinity=True))


def load_both(pin, monkeypatch, materialize=True, **kw):
    """(columnar store, dict store) holding the same generated
    population, uid-aligned: each load runs as its own pinned phase, and
    the columnar store materializes its lazy rows in row order — the
    same order the dict store's eager fallback created them."""
    a = make_store(monkeypatch, True)
    pin.reset()
    load_population(a, **kw)
    if materialize:
        a.materialize_reads()
    b = make_store(monkeypatch, False)
    pin.reset()
    load_population(b, **kw)
    return a, b


NODE = {
    "metadata": {"name": "crud-node", "labels": {"zone": "z1"}},
    "spec": {"taints": [{"key": "k", "value": "v", "effect": "NoSchedule"}]},
    "status": {"allocatable": {"cpu": "8000m", "memory": "1073741824",
                               "example.com/gpu": "4", "pods": "110"}},
}
POD = {
    "metadata": {"name": "crud-pod", "labels": {"app": "a0"}},
    "spec": {"containers": [{"name": "c", "resources": {
        "requests": {"cpu": "250m", "memory": "2097152"}}}]},
}


def crud_sequence(s: ObjectStore) -> None:
    """The golden op sequence: create, update, delete, re-create."""
    s.create("nodes", json.loads(json.dumps(NODE)))
    s.create("pods", json.loads(json.dumps(POD)))
    nd = s.get("nodes", "crud-node")
    nd["status"]["allocatable"]["cpu"] = "16000m"
    nd["metadata"]["labels"]["zone"] = "z2"
    s.update("nodes", nd)
    s.delete("pods", "crud-pod")
    s.create("pods", json.loads(json.dumps(POD)))


def test_crud_surface_byte_parity(pin, monkeypatch):
    a = make_store(monkeypatch, True)
    b = make_store(monkeypatch, False)
    qa, qb = a.watch("nodes"), b.watch("nodes")
    for s in (a, b):
        pin.reset()
        crud_sequence(s)
    assert raw(a.get("nodes", "crud-node")) == raw(b.get("nodes", "crud-node"))
    assert raw(a.get("pods", "crud-pod")) == raw(b.get("pods", "crud-pod"))
    la, rva = a.list("nodes")
    lb, rvb = b.list("nodes")
    assert rva == rvb and [raw(o) for o in la] == [raw(o) for o in lb]
    assert raw(a.dump()) == raw(b.dump())
    # identical watch streams, rv for rv
    ev_a = [qa.get_nowait() for _ in range(qa.qsize())]
    ev_b = [qb.get_nowait() for _ in range(qb.qsize())]
    assert ([(rv, t, raw(o)) for rv, t, o in ev_a]
            == [(rv, t, raw(o)) for rv, t, o in ev_b])


def test_lazy_rows_byte_identical_to_eager_path(pin, monkeypatch):
    """load_columnar's LAZY rows must synthesize the same bytes — raw
    insertion order included — the eager fallback stores."""
    a, b = load_both(pin, monkeypatch)
    for resource in ("nodes", "pods"):
        la, rva = a.list(resource)
        lb, rvb = b.list(resource)
        assert rva == rvb
        assert [raw(o) for o in la] == [raw(o) for o in lb]
    assert (raw(a.get("nodes", "node-00007"))
            == raw(b.get("nodes", "node-00007")))
    assert (raw(a.get("pods", "pod-00003"))
            == raw(b.get("pods", "pod-00003")))
    assert raw(a.dump()) == raw(b.dump())


def test_watch_events_from_bulk_load_match_eager(pin, monkeypatch):
    a = make_store(monkeypatch, True)
    b = make_store(monkeypatch, False)
    qa, qb = a.watch("nodes"), b.watch("nodes")
    pin.reset()
    a.load_columnar("nodes", make_nodes_columnar(12, seed=3))
    a.materialize_reads()
    pin.reset()
    b.load_columnar("nodes", make_nodes_columnar(12, seed=3))
    ev_a = [qa.get_nowait() for _ in range(qa.qsize())]
    ev_b = [qb.get_nowait() for _ in range(qb.qsize())]
    assert len(ev_a) == 12
    assert ([(rv, t, raw(o)) for rv, t, o in ev_a]
            == [(rv, t, raw(o)) for rv, t, o in ev_b])


def test_update_and_delete_of_lazy_rows(pin, monkeypatch):
    """Mutating a lazy row (update / delete / re-create) keeps the shim
    on the dict baseline: rv sequencing, tombstoned reads, final bytes."""
    a, b = load_both(pin, monkeypatch)
    for s in (a, b):
        pin.reset()
        nd = s.get("nodes", "node-00003")
        nd["status"]["allocatable"]["cpu"] = "123000m"
        s.update("nodes", nd)
        s.delete("nodes", "node-00005")
        s.create("nodes", {"metadata": {"name": "node-00005"},
                           "status": {"allocatable": {"cpu": "1000m",
                                                      "pods": "10"}}})
        with pytest.raises(Exception):
            s.get("nodes", "node-00099")
    la, rva = a.list("nodes")
    lb, rvb = b.list("nodes")
    assert rva == rvb
    assert [raw(o) for o in la] == [raw(o) for o in lb]
    # re-created row carries a fresh rv, identical on both sides
    assert (a.get("nodes", "node-00005")["metadata"]["resourceVersion"]
            == b.get("nodes", "node-00005")["metadata"]["resourceVersion"])


def test_materialize_reads_fills_lazy_rows(pin, monkeypatch):
    """The read-hook flush surface: shared (no-copy) listings hand out
    lazy rows whose dict storage is EMPTY until filled — json's C
    encoder would serialize {}.  materialize_reads() is the documented
    pre-serialization flush and must leave the shared objects carrying
    full bytes."""
    a, b = load_both(pin, monkeypatch, materialize=False)
    sa = list_shared(a, "nodes")
    lazy = [o for o in sa if type(o) is LazyManifest and not dict.__len__(o)]
    assert lazy, "expected unfilled lazy rows before the flush"
    assert json.dumps(lazy[0]) == "{}"  # the bypass materialize guards
    pin.reset()
    a.materialize_reads()
    assert all(dict.__len__(o) for o in list_shared(a, "nodes"))
    assert ([json.dumps(o) for o in list_shared(a, "nodes")]
            == [raw(o) for o in list_shared(b, "nodes")])


def test_snapshot_export_byte_parity(pin, monkeypatch):
    from kube_scheduler_simulator_tpu.services.snapshot import SnapshotService

    class _Sched:
        def get_config(self):
            return {"profiles": []}

    a, b = load_both(pin, monkeypatch, n_nodes=15, n_pods=10)
    # snap() returns SHARED manifests; its materialize_reads() pass must
    # fill every lazy row, so callers' direct json.dumps is byte-safe
    snap_a = SnapshotService(a, _Sched()).snap()
    snap_b = SnapshotService(b, _Sched()).snap()
    assert json.dumps(snap_a) == json.dumps(snap_b)


def test_columnar_off_pins_dict_baseline(monkeypatch):
    s = make_store(monkeypatch, False)
    assert not s._banks
    n = s.load_columnar("nodes", make_nodes_columnar(8, seed=1))
    assert n == 8
    assert all(type(o) is dict for o in list_shared(s, "nodes"))


def test_columnar_sync_fault_leaves_shim_consistent(pin, monkeypatch):
    """A fault injected at the store.columnar_sync seam mid-update must
    never surface to the writer: the row goes opaque, the manifest stays
    authoritative, and every read surface — including the columnar
    node-table build — matches the dict baseline."""
    from kube_scheduler_simulator_tpu.plugins.registry import PluginSetConfig
    from kube_scheduler_simulator_tpu.state.compile import compile_workload

    a, b = load_both(pin, monkeypatch, n_nodes=20, n_pods=5)

    def edit(s):
        pin.reset()
        nd = s.get("nodes", "node-00004")
        nd["status"]["allocatable"]["cpu"] = "99000m"
        s.update("nodes", nd)

    plan = FaultPlan([FaultRule("store.columnar_sync", nth=1)], seed=0)
    with faults.armed(plan):
        edit(a)
    edit(b)
    assert plan.stats()["rules"][0]["trips"] == 1
    bank = a._banks["nodes"]
    assert bank.opaque[bank.row_of["node-00004"]]
    # shim byte-parity survives the faulted sync
    assert (raw(a.get("nodes", "node-00004"))
            == raw(b.get("nodes", "node-00004")))
    assert raw(a.dump()) == raw(b.dump())
    # the columnar build re-parses the opaque row's manifest: identical
    # allocatable to the dict-path build
    cfg = PluginSetConfig(enabled=["NodeResourcesFit"])
    na, _ = a.list("nodes", copy_objects=False)
    nb, _ = b.list("nodes", copy_objects=False)
    pa, _ = a.list("pods", copy_objects=False)
    cw_a = compile_workload(na, list(pa), cfg,
                            pod_columns=getattr(pa, "columns", None))
    cw_b = compile_workload([dict(o) for o in nb], list(pa), cfg)
    assert list(cw_a.node_table.names) == list(cw_b.node_table.names)
    assert np.array_equal(cw_a.node_table.allocatable,
                          cw_b.node_table.allocatable)
    row = list(cw_a.node_table.names).index("node-00004")
    cpu_col = list(cw_a.schema.columns).index("cpu")
    assert cw_a.node_table.allocatable[row, cpu_col] == 99000
