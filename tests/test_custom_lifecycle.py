"""Custom-plugin Reserve/Permit/PreBind/PostBind lifecycle through the
engine — the ordering semantics of the reference's wrapped plugin
(simulator/scheduler/plugin/wrappedplugin.go:588-752): all Reserves, then
all Permits (with real wait parking), then all PreBinds; Unreserve runs
for ALL reserve plugins in reverse order on any failure; PostBind only
after a successful bind.

These paths shipped untested in round 1 (VERDICT weak #2: an `ann`
NameError at engine.py:205 crashed any has_lifecycle plugin at bind time).
"""

import json
import threading

from kube_scheduler_simulator_tpu.cluster.store import ObjectStore
from kube_scheduler_simulator_tpu.framework.engine import SchedulerEngine
from kube_scheduler_simulator_tpu.models.workloads import make_nodes, make_pods
from kube_scheduler_simulator_tpu.plugins.custom import CustomPlugin
from kube_scheduler_simulator_tpu.plugins.registry import PluginSetConfig
from kube_scheduler_simulator_tpu.store import annotations as ann


class LifecyclePlugin(CustomPlugin):
    """Records every lifecycle call into a shared event log."""

    def __init__(self, name, log, reserve_msg=None, permit_out=None,
                 pre_bind_msg=None):
        self.name = name
        self.log = log
        self._reserve_msg = reserve_msg
        self._permit_out = permit_out
        self._pre_bind_msg = pre_bind_msg

    def reserve(self, pod, node):
        self.log.append((self.name, "reserve"))
        return self._reserve_msg

    def unreserve(self, pod, node):
        self.log.append((self.name, "unreserve"))

    def permit(self, pod, node):
        self.log.append((self.name, "permit"))
        return self._permit_out

    def pre_bind(self, pod, node):
        self.log.append((self.name, "pre_bind"))
        return self._pre_bind_msg

    def post_bind(self, pod, node):
        self.log.append((self.name, "post_bind"))


def _engine(plugins, n_nodes=3, n_pods=1):
    store = ObjectStore()
    for n in make_nodes(n_nodes, seed=31):
        store.create("nodes", n)
    for p in make_pods(n_pods, seed=32):
        store.create("pods", p)
    cfg = PluginSetConfig(
        enabled=["NodeResourcesFit"] + [p.name for p in plugins],
        custom={p.name: p for p in plugins},
    )
    return SchedulerEngine(store, plugin_config=cfg), store


def _pod_annotations(store, name="pod-00000"):
    return store.get("pods", name)["metadata"].get("annotations") or {}


def test_happy_path_records_all_phases_and_postbind():
    log = []
    a, b = LifecyclePlugin("A", log), LifecyclePlugin("B", log)
    engine, store = _engine([a, b])
    assert engine.schedule_pending() == 1
    # phase ordering: all Reserves, then all Permits, then all PreBinds,
    # then PostBind after the bind (scheduleOne)
    assert log == [
        ("A", "reserve"), ("B", "reserve"),
        ("A", "permit"), ("B", "permit"),
        ("A", "pre_bind"), ("B", "pre_bind"),
        ("A", "post_bind"), ("B", "post_bind"),
    ]
    annos = _pod_annotations(store)
    assert json.loads(annos[ann.RESERVE_RESULT]) == {"A": "success", "B": "success"}
    assert json.loads(annos[ann.PERMIT_STATUS_RESULT]) == {"A": "success", "B": "success"}
    assert json.loads(annos[ann.PRE_BIND_RESULT]) == {"A": "success", "B": "success"}
    assert store.get("pods", "pod-00000")["spec"].get("nodeName")


def test_reserve_failure_unreserves_all_in_reverse_order():
    log = []
    a = LifecyclePlugin("A", log)
    b = LifecyclePlugin("B", log, reserve_msg="no capacity token")
    c = LifecyclePlugin("C", log)
    engine, store = _engine([a, b, c])
    assert engine.schedule_pending() == 0
    # upstream RunReservePluginsUnreserve: ALL reserve plugins unreserve in
    # reverse order, including ones whose Reserve never ran (C)
    assert log == [
        ("A", "reserve"), ("B", "reserve"),
        ("C", "unreserve"), ("B", "unreserve"), ("A", "unreserve"),
    ]
    annos = _pod_annotations(store)
    assert json.loads(annos[ann.RESERVE_RESULT])["B"] == "no capacity token"
    pod = store.get("pods", "pod-00000")
    assert not pod["spec"].get("nodeName")
    conds = {c["type"]: c for c in pod["status"]["conditions"]}
    assert conds["PodScheduled"]["reason"] == "Unschedulable"


def test_permit_deny_unreserves_and_fails_bind():
    log = []
    a = LifecyclePlugin("A", log)
    b = LifecyclePlugin("B", log, permit_out="quota exceeded")
    engine, store = _engine([a, b])
    assert engine.schedule_pending() == 0
    assert log == [
        ("A", "reserve"), ("B", "reserve"),
        ("A", "permit"), ("B", "permit"),
        ("B", "unreserve"), ("A", "unreserve"),
    ]
    annos = _pod_annotations(store)
    permits = json.loads(annos[ann.PERMIT_STATUS_RESULT])
    assert permits == {"A": "success", "B": "quota exceeded"}


def test_prebind_failure_unreserves_and_fails_bind():
    log = []
    a = LifecyclePlugin("A", log)
    b = LifecyclePlugin("B", log, pre_bind_msg="volume attach failed")
    engine, store = _engine([a, b])
    assert engine.schedule_pending() == 0
    assert ("B", "unreserve") in log and ("A", "unreserve") in log
    assert log.index(("B", "unreserve")) < log.index(("A", "unreserve"))
    assert ("A", "post_bind") not in log
    annos = _pod_annotations(store)
    assert json.loads(annos[ann.PRE_BIND_RESULT])["B"] == "volume attach failed"


def test_permit_wait_timeout_rejects():
    log = []
    a = LifecyclePlugin("A", log, permit_out=("wait", "10ms"))
    engine, store = _engine([a])
    assert engine.schedule_pending() == 0
    # wait was recorded with its timeout, then the expiry rejected the pod
    annos = _pod_annotations(store)
    assert json.loads(annos[ann.PERMIT_TIMEOUT_RESULT])["A"] == "10ms"
    permits = json.loads(annos[ann.PERMIT_STATUS_RESULT])
    assert permits["A"] == "timeout"
    assert ("A", "unreserve") in log


def test_permit_wait_allowed_by_handle():
    log = []

    class Waiter(LifecyclePlugin):
        def on_waiting(self, waiting_pod):
            # the analogue of another goroutine holding the framework
            # handle: allow the pod immediately
            waiting_pod.allow(self.name)

    a = Waiter("A", log, permit_out=("wait", "30s"))
    engine, store = _engine([a])
    assert engine.schedule_pending() == 1
    assert store.get("pods", "pod-00000")["spec"].get("nodeName")
    annos = _pod_annotations(store)
    assert json.loads(annos[ann.PERMIT_STATUS_RESULT])["A"] == "wait"
    assert json.loads(annos[ann.PERMIT_TIMEOUT_RESULT])["A"] == "30s"


def test_permit_wait_allowed_from_thread():
    log = []
    released = threading.Event()

    class Waiter(LifecyclePlugin):
        def on_waiting(self, waiting_pod):
            def _later():
                released.wait(5)
                waiting_pod.allow(self.name)

            threading.Thread(target=_later, daemon=True).start()
            released.set()

    a = Waiter("A", log, permit_out=("wait", "30s"))
    engine, store = _engine([a])
    assert engine.schedule_pending() == 1
    assert (None, "pod-00000") != (None, store.get("pods", "pod-00000")["spec"].get("nodeName"))
    assert engine.waiting_pods == {}


def test_permit_wait_rejected_by_handle():
    log = []

    class Rejecter(LifecyclePlugin):
        def on_waiting(self, waiting_pod):
            waiting_pod.reject(self.name, "external veto")

    a = Rejecter("A", log, permit_out=("wait", "30s"))
    engine, store = _engine([a])
    assert engine.schedule_pending() == 0
    annos = _pod_annotations(store)
    assert json.loads(annos[ann.PERMIT_STATUS_RESULT])["A"] == "external veto"
    assert ("A", "unreserve") in log


def test_lifecycle_rejection_reruns_wave_for_later_pods():
    """A rejection after the device replay folded the pod into the carry
    must not poison later pods in the same wave: the wave re-runs and the
    remaining pods schedule against true state (ADVICE round-1 low #4)."""
    log = []

    class RejectOne(LifecyclePlugin):
        def reserve(self, pod, node):
            self.log.append((pod["metadata"]["name"], "reserve"))
            if pod["metadata"]["name"] == "pod-00000":
                return "rejected by policy"
            return None

    a = RejectOne("A", log)
    engine, store = _engine([a], n_nodes=3, n_pods=4)
    bound = engine.schedule_pending()
    assert bound == 3
    assert not store.get("pods", "pod-00000")["spec"].get("nodeName")
    for i in (1, 2, 3):
        assert store.get("pods", f"pod-0000{i}")["spec"].get("nodeName")
    # pod-00000's reserve ran exactly once: subsequent waves exclude it
    assert log.count(("pod-00000", "reserve")) == 1


def test_permit_wait_does_not_stall_other_pods():
    """A waiting pod must not block the wave: B/C bind immediately while A
    waits; A binds on resolution (upstream binding-cycle goroutines block
    in WaitOnPermit while scheduleOne keeps scheduling; VERDICT r2 #6)."""
    import time

    log = []
    bound_before_allow = {}

    class SlowWaiter(LifecyclePlugin):
        def permit(self, pod, node):
            self.log.append((self.name, "permit"))
            if pod["metadata"]["name"] == "pod-00000":
                return ("wait", "10s")
            return None

        def on_waiting(self, waiting_pod):
            wp = waiting_pod

            def later():
                time.sleep(0.5)
                # observe how many OTHER pods bound while we waited
                pods, _ = self.store_ref.list("pods")
                bound_before_allow["n"] = sum(
                    1 for p in pods
                    if (p.get("spec") or {}).get("nodeName")
                    and p["metadata"]["name"] != "pod-00000"
                )
                wp.allow(self.name)

            threading.Thread(target=later, daemon=True).start()

    a = SlowWaiter("A", log)
    engine, store = _engine([a], n_pods=3)
    a.store_ref = store
    t0 = time.time()
    assert engine.schedule_pending() == 3
    elapsed = time.time() - t0
    # pod A's 0.5s wait overlapped the rest of the wave, and B/C were
    # already bound when A was allowed
    assert bound_before_allow["n"] == 2
    assert elapsed < 5, f"wave stalled on the waiter: {elapsed:.1f}s"
    for name in ("pod-00000", "pod-00001", "pod-00002"):
        assert (store.get("pods", name)["spec"]).get("nodeName")
    annos = _pod_annotations(store)
    assert json.loads(annos[ann.PERMIT_STATUS_RESULT])["A"] == "wait"


def test_mutating_plugin_cannot_corrupt_store_state():
    """Third-party plugin code receives private copies: a plugin that
    mutates the pod it is handed must not change live cluster state
    (the engine's fast-path listings share the stored manifests)."""
    class Mutator(LifecyclePlugin):
        def reserve(self, pod, node):
            pod.setdefault("metadata", {}).setdefault(
                "labels", {})["rogue"] = "yes"
            if node is not None:
                node.setdefault("metadata", {}).setdefault(
                    "labels", {})["rogue"] = "yes"
            return None

        def post_bind(self, pod, node):
            pod["spec"]["nodeName"] = "hijacked"

    engine, store = _engine([Mutator("M", [])])
    assert engine.schedule_pending() == 1
    pod = store.get("pods", "pod-00000")
    assert "rogue" not in (pod["metadata"].get("labels") or {})
    assert pod["spec"]["nodeName"] != "hijacked"
    for n in store.list("nodes")[0]:
        assert "rogue" not in (n["metadata"].get("labels") or {})


def test_host_path_runs_postbind_after_successful_bind():
    """The host-interleaved path (forced here by a cycle hook) must run
    PostBind after a successful bind, like the batched wave path and the
    async waiter path do."""
    from kube_scheduler_simulator_tpu.scheduler.debuggable import PluginExtender

    class NoopHook(PluginExtender):
        def before_filter(self, pod, node_name):
            return None

    log = []
    engine, store = _engine([LifecyclePlugin("A", log)])
    engine.plugin_extenders = {"NodeResourcesFit": NoopHook()}
    assert engine._needs_host_path()
    assert engine.schedule_pending() == 1
    assert ("A", "post_bind") in log
    assert store.get("pods", "pod-00000")["spec"].get("nodeName")


def test_bind_extender_failure_unreserves_custom_plugins():
    """Upstream runs RunReservePluginsUnreserve on ANY failure after a
    successful Reserve — including a bind-verb extender failing the
    binding cycle (host path)."""
    from kube_scheduler_simulator_tpu.scheduler.extender import ExtenderService

    log = []
    engine, store = _engine([LifecyclePlugin("A", log)])
    # bindVerb on an unreachable host: the bind call fails the cycle
    svc = ExtenderService([{"urlPrefix": "http://127.0.0.1:1",
                            "bindVerb": "bind"}])
    engine.set_extenders(svc)
    assert engine.schedule_pending() == 0
    # reserve ran, bind failed at the extender -> unreserve must run
    assert ("A", "reserve") in log
    assert ("A", "unreserve") in log
    assert ("A", "post_bind") not in log
    assert not store.get("pods", "pod-00000")["spec"].get("nodeName")
