"""Lazy annotation materialization (store/lazy.py): byte parity with
eager mode across decoder rungs and wave shapes, exactly-once chunk
decode under concurrent cold reads, and the flight-recorder taps.

The parity rule (docs/wave-pipeline.md lazy-decode stage): whatever a
reader observes — pod annotations, result-history, bind order, parked
gangs — must be bit-identical between the default lazy mode,
KSS_TPU_EAGER_DECODE=1, and lazy over the pure-Python decoder rung
(KSS_TPU_DISABLE_NATIVE=1), including pods nobody reads until after a
later wave has overwritten their result-store entry.
"""

from __future__ import annotations

import copy
import json
import queue as queue_mod
import threading

import pytest

from kube_scheduler_simulator_tpu.cluster.store import ObjectStore, list_shared
from kube_scheduler_simulator_tpu.framework.engine import SchedulerEngine
from kube_scheduler_simulator_tpu.models.workloads import (
    make_gang_workload, make_nodes, make_pods)
from kube_scheduler_simulator_tpu.plugins.registry import PluginSetConfig
from kube_scheduler_simulator_tpu.store import annotations as ann
from kube_scheduler_simulator_tpu.utils.tracing import TRACER

ENABLED = ["NodeResourcesFit", "NodeResourcesBalancedAllocation",
           "NodeAffinity", "TaintToleration", "VolumeBinding"]


def _mode(monkeypatch, mode: str) -> None:
    monkeypatch.delenv("KSS_TPU_EAGER_DECODE", raising=False)
    monkeypatch.delenv("KSS_TPU_DISABLE_NATIVE", raising=False)
    if mode == "eager":
        monkeypatch.setenv("KSS_TPU_EAGER_DECODE", "1")
    elif mode == "lazy_python":
        monkeypatch.setenv("KSS_TPU_DISABLE_NATIVE", "1")
    else:
        assert mode == "lazy"


def _mixed_workload():
    """Plain + affinity/toleration pods, taints, host score columns AND
    two prefilter-rejected pods (missing PVC) mid-queue — the shapes the
    chunk decode special-cases (tests/test_chunk_decode.py recipe)."""
    nodes = make_nodes(18, seed=3, taint_fraction=0.3)
    pods = make_pods(50, seed=4, with_affinity=True, with_tolerations=True)
    for j, at in enumerate((7, 33)):
        pods.insert(at, {
            "metadata": {"name": f"pvc-pod-{j}", "namespace": "default"},
            "spec": {
                "containers": [{"name": "c", "resources": {
                    "requests": {"cpu": "100m"}}}],
                "volumes": [{"name": "v", "persistentVolumeClaim": {
                    "claimName": f"missing-{j}"}}],
            },
        })
    for i, p in enumerate(pods):
        p["spec"]["priority"] = (i % 3) * 100
    return nodes, pods


def _run_wave(nodes, pods, pipeline=True, chunk=16):
    """Schedule once; -> (engine, store, bound, bind_order)."""
    store = ObjectStore()
    for n in nodes:
        store.create("nodes", copy.deepcopy(n))
    for p in pods:
        store.create("pods", copy.deepcopy(p))
    q = store.watch("pods")
    engine = SchedulerEngine(store, plugin_config=PluginSetConfig(
        enabled=list(ENABLED)), chunk=chunk, pipeline_commit=pipeline)
    bound = engine.schedule_pending()
    bind_order, seen = [], set()
    while True:
        try:
            _rv, event_type, obj = q.get_nowait()
        except queue_mod.Empty:
            break
        name = obj["metadata"]["name"]
        if (event_type == "MODIFIED"
                and (obj.get("spec") or {}).get("nodeName")
                and name not in seen):
            seen.add(name)
            bind_order.append(name)
    store.unwatch("pods", q)
    return engine, store, bound, bind_order


def _read_all(store) -> dict[str, dict]:
    return {p["metadata"]["name"]: p["metadata"].get("annotations") or {}
            for p in store.list("pods")[0]}


def _assert_same(anns_a: dict, anns_b: dict, what: str) -> None:
    assert anns_a.keys() == anns_b.keys()
    for name in anns_a:
        for key in set(anns_a[name]) | set(anns_b[name]):
            assert anns_a[name].get(key) == anns_b[name].get(key), (
                f"pod {name} key {key} diverged ({what})")


@pytest.mark.parametrize("pipeline", [True, False])
def test_lazy_eager_parity_mixed_wave(monkeypatch, pipeline):
    """Lazy (native), lazy (pure-Python rung) and eager runs of the
    same mixed wave — prefilter rejects included — are byte-identical
    in annotations, result-history, bind count and bind order, on both
    the streaming-commit and sequential post-pass paths."""
    nodes, pods = _mixed_workload()
    results = {}
    for mode in ("lazy", "eager", "lazy_python"):
        _mode(monkeypatch, mode)
        engine, store, bound, order = _run_wave(nodes, pods,
                                                pipeline=pipeline)
        if mode.startswith("lazy"):
            # deferral really happened: shared reads see no annotations
            assert not any((p["metadata"].get("annotations") or {})
                           for p in list_shared(store, "pods"))
            reg = engine.reflector._lazy
            assert reg is not None and reg.pending_count() == len(pods)
        results[mode] = (bound, order, _read_all(store))
        if mode.startswith("lazy"):
            assert engine.reflector._lazy.pending_count() == 0
    b0, o0, a0 = results["eager"]
    for mode in ("lazy", "lazy_python"):
        b, o, a = results[mode]
        assert b == b0 and o == o0
        _assert_same(a, a0, f"{mode} vs eager")
    # the rejected pods took the early-out in every mode
    for j in range(2):
        assert a0[f"pvc-pod-{j}"][ann.FILTER_RESULT] == "{}"


def test_lazy_gang_wave_parity(monkeypatch):
    """Gang waves defer too: an admitted gang, a below-quorum (parked)
    gang and plain pods produce identical annotations (permit-result /
    permit-result-timeout included), bind order and parked set between
    lazy and eager runs of the streaming gang-atomic commit."""
    from kube_scheduler_simulator_tpu.framework.gang import POD_GROUP_LABEL
    from kube_scheduler_simulator_tpu.plugins.coscheduling import (
        Coscheduling, ensure_podgroup_resource)

    nodes = make_nodes(14, seed=21, taint_fraction=0.2)
    pgs, gpods = make_gang_workload(3, 5, seed=22)
    for p in gpods:
        if (p["metadata"]["labels"][POD_GROUP_LABEL] == "gang-0001"
                and p["metadata"]["name"].endswith(("003", "004"))):
            p["spec"]["containers"][0]["resources"]["requests"]["cpu"] = \
                "9999999m"
    plain = make_pods(30, seed=23, with_affinity=True, with_tolerations=True)

    def run():
        store = ObjectStore()
        ensure_podgroup_resource(store)
        for n in nodes:
            store.create("nodes", copy.deepcopy(n))
        for pg in pgs:
            store.create("podgroups", copy.deepcopy(pg))
        for p in gpods + plain:
            store.create("pods", copy.deepcopy(p))
        cfg = PluginSetConfig(
            enabled=["NodeResourcesFit", "NodeAffinity", "TaintToleration",
                     "Coscheduling"],
            custom={"Coscheduling": Coscheduling()},
        )
        engine = SchedulerEngine(store, plugin_config=cfg, chunk=8)
        bound = engine.schedule_pending()
        parked = sorted(engine.gang_parked)
        return bound, parked, _read_all(store)

    _mode(monkeypatch, "lazy")
    bound_l, parked_l, anns_l = run()
    _mode(monkeypatch, "eager")
    bound_e, parked_e, anns_e = run()
    assert bound_l == bound_e
    assert parked_l == parked_e and len(parked_l) == 3
    _assert_same(anns_l, anns_e, "lazy vs eager gang wave")


def test_unread_pods_survive_later_wave_overwrite(monkeypatch):
    """A pod scheduled by wave 1 and RE-scheduled by wave 2 before
    anyone reads it materializes both records in order: annotations =
    wave 2's bytes, result-history = [wave-1 record, wave-2 record] —
    exactly what eager mode wrote."""
    nodes = [{"metadata": {"name": "n1"},
              "status": {"allocatable": {"cpu": "2", "memory": "4Gi",
                                         "pods": "10"}}}]
    pods = [{"metadata": {"name": f"p{i}"}, "spec": {"containers": [
        {"name": "c", "resources": {"requests": {"cpu": "1",
                                                 "memory": "1Gi"}}}]}}
            for i in range(4)]
    extra_node = {"metadata": {"name": "n2"},
                  "status": {"allocatable": {"cpu": "8", "memory": "16Gi",
                                             "pods": "10"}}}

    def run():
        store = ObjectStore()
        for n in nodes:
            store.create("nodes", copy.deepcopy(n))
        for p in pods:
            store.create("pods", copy.deepcopy(p))
        engine = SchedulerEngine(store, plugin_config=PluginSetConfig(
            enabled=["NodeResourcesFit",
                     "NodeResourcesBalancedAllocation"]))
        b1 = engine.schedule_pending()   # capacity for 2: rest pending
        store.create("nodes", copy.deepcopy(extra_node))
        b2 = engine.schedule_pending()   # retried pods get a 2nd record
        return store, b1, b2

    _mode(monkeypatch, "lazy")
    store_l, b1_l, b2_l = run()
    _mode(monkeypatch, "eager")
    store_e, b1_e, b2_e = run()
    assert (b1_l, b2_l) == (b1_e, b2_e) and b2_l > 0
    anns_l, anns_e = _read_all(store_l), _read_all(store_e)
    _assert_same(anns_l, anns_e, "overwrite-before-read")
    # the retried pods carry BOTH wave records, oldest first
    multi = [n for n, a in anns_e.items()
             if len(json.loads(a.get(ann.RESULT_HISTORY, "[]"))) >= 2]
    assert multi, "expected at least one pod with a two-record history"


def test_concurrent_first_reads_decode_each_chunk_once(monkeypatch):
    """The multi-thread first-read soak: many concurrent cold readers
    across several chunks; every read returns eager-identical bytes and
    each chunk decodes EXACTLY once (one decode_lazy span per chunk —
    concurrent readers of a chunk wait on the owner instead of decoding
    again)."""
    nodes, pods = _mixed_workload()
    _mode(monkeypatch, "eager")
    _, store_e, _, _ = _run_wave(nodes, pods)
    baseline = _read_all(store_e)

    _mode(monkeypatch, "lazy")
    engine, store, _, _ = _run_wave(nodes, pods, chunk=16)
    n_chunks = (len(pods) + 15) // 16
    TRACER.reset()

    names = [p["metadata"]["name"] for p in list_shared(store, "pods")]
    errors: list = []
    results: dict[str, dict] = {}
    res_mu = threading.Lock()
    start = threading.Barrier(8)

    def reader(k):
        try:
            start.wait()
            # stripe across the queue so every chunk gets concurrent
            # cold readers from several threads
            for name in names[k::2]:
                a = store.get("pods", name, "default")["metadata"] \
                    .get("annotations") or {}
                with res_mu:
                    prev = results.setdefault(name, a)
                assert prev == a
        except BaseException as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=reader, args=(k % 2,))
               for k in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors
    for name, a in results.items():
        for key in baseline[name]:
            assert a.get(key) == baseline[name][key], (name, key)
    spans = TRACER.summary()["spans"]
    assert spans.get("decode_lazy", {}).get("count") == n_chunks, (
        f"expected exactly {n_chunks} chunk decodes, got "
        f"{spans.get('decode_lazy')}")


def test_lazy_flight_recorder_taps(monkeypatch):
    """decode_on_demand_total{result=hit|miss}, the cold first-read
    histogram and the decode_lazy span all record, and the exposition
    stays strictly valid."""
    from kube_scheduler_simulator_tpu.utils.tracing import validate_exposition

    nodes, pods = _mixed_workload()
    _mode(monkeypatch, "lazy")
    engine, store, _, _ = _run_wave(nodes, pods, chunk=16)
    TRACER.reset()
    store.get("pods", pods[0]["metadata"]["name"], "default")   # cold
    store.list("pods")  # drains the rest: chunk-mates are warm hits
    snap = TRACER.snapshot()
    od = {tuple(sorted(s["labels"].items())): s["value"]
          for s in snap["labeled_counters"]["decode_on_demand_total"]}
    assert od[(("result", "miss"),)] >= 1
    assert od[(("result", "hit"),)] >= 1
    hist = snap["histograms"]["lazy_decode_cold_read_seconds"]
    assert hist["series"][0]["count"] >= 1
    assert "decode_lazy" in snap["spans"]
    validate_exposition(TRACER.prometheus_text())


def test_export_and_dump_carry_deferred_annotations(monkeypatch):
    """Snapshot fidelity: dump() (the reset/export surface) drains the
    deferred write-backs, so the snapshot carries the same annotation
    bytes an eager wave would have written."""
    nodes, pods = _mixed_workload()
    _mode(monkeypatch, "lazy")
    engine, store, _, _ = _run_wave(nodes, pods)
    assert engine.reflector._lazy.pending_count() == len(pods)
    snap = store.dump()
    assert engine.reflector._lazy.pending_count() == 0
    annotated = sum(
        1 for obj in snap["pods"].values()
        if (obj["metadata"].get("annotations") or {}).get(ann.SELECTED_NODE)
        is not None)
    assert annotated == len(pods)


def test_unsealed_wave_records_never_stall_readers():
    """A record queued by a still-streaming wave (unsealed LazyWave) is
    SKIPPED by drains — a GET or watch-pump flush mid-wave returns
    immediately instead of blocking until the replay finishes — and
    lands on the first flush after the seal."""
    from kube_scheduler_simulator_tpu.store.reflector import LazyReflections

    store = ObjectStore()
    store.create("pods", {"metadata": {"name": "p"},
                          "spec": {"containers": [{"name": "c"}]}})
    uid = store.get("pods", "p")["metadata"]["uid"]

    class _Part:  # DeferredResult stand-in backed by an unsealed wave
        def __init__(self):
            self.sealed = False

        def ready(self):
            return self.sealed

        def result_set(self):
            assert self.sealed, "materialized before the wave sealed"
            return {ann.SELECTED_NODE: "n1"}

    part = _Part()
    reg = LazyReflections(store)
    reg.add("default", "p", uid, [part])
    reg.flush("pods", "p", "default")        # mid-wave: must not block
    assert reg.pending_count() == 1          # record survived, unapplied
    reg.flush("pods")                        # whole-resource: same
    assert reg.pending_count() == 1
    part.sealed = True                       # wave seals
    reg.flush("pods")
    assert reg.pending_count() == 0
    a = store.get("pods", "p")["metadata"].get("annotations") or {}
    assert a.get(ann.SELECTED_NODE) == "n1"


def test_deleted_pod_drops_deferred_records(monkeypatch):
    """Deleting a pod discards its deferred records (they stop pinning
    the wave's replay buffers) without disturbing its neighbors."""
    nodes, pods = _mixed_workload()
    _mode(monkeypatch, "lazy")
    engine, store, _, _ = _run_wave(nodes, pods)
    reg = engine.reflector._lazy
    n0 = reg.pending_count()
    victim = pods[5]["metadata"]["name"]
    store.delete("pods", victim, "default")
    assert reg.pending_count() == n0 - 1
    # neighbors still materialize fine
    a = store.get("pods", pods[6]["metadata"]["name"],
                  "default")["metadata"].get("annotations") or {}
    assert ann.SELECTED_NODE in a
