"""Seeded lock-discipline violations for tests/test_analyze.py.

NEVER imported — analyzed as AST only.  Each class seeds one rule:
an A->B / B->A lock-order inversion (the PR 3 kubeapi deadlock shape,
two-lock variant), a helper that reacquires its caller's non-reentrant
lock (the single-lock variant), blocking/device/serialize work under a
lock, and a suppressed site proving the allow() comment works.
"""

import copy
import json
import subprocess
import threading
import time

import jax.numpy as jnp


class Inverted:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()

    def ab(self):
        with self._a:
            with self._b:
                return 1

    def ba(self):
        with self._b:
            with self._a:
                return 2


class SelfDeadlock:
    """The kubeapi._rv_int shape: a helper that re-takes the lock its
    caller already holds."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counter = 0

    def _helper(self):
        with self._lock:
            self._counter += 1
            return self._counter

    def caller(self):
        with self._lock:
            return self._helper()


class BlockingUnderLock:
    def __init__(self):
        self._mu = threading.Lock()

    def sleeps(self):
        with self._mu:
            time.sleep(0.1)

    def spawns(self):
        with self._mu:
            subprocess.run(["true"])

    def device_work(self):
        with self._mu:
            return jnp.zeros((4,)).sum()

    def serializes(self):
        with self._mu:
            return json.dumps({"k": copy.deepcopy({"v": 1})})

    def allowed(self):
        with self._mu:
            time.sleep(0.01)  # kss-analyze: allow(blocking-under-lock)


class AcquireRelease:
    """acquire()/release() style holds are tracked too."""

    def __init__(self):
        self._mu = threading.Lock()

    def manual(self):
        self._mu.acquire()
        time.sleep(0.05)
        self._mu.release()
