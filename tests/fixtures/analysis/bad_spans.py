"""Seeded observability-conformance violations (AST only): a span
started outside `with` (no guaranteed end on exception paths), a metric
name that fails the Prometheus rules, and a reserved label.
"""

from kube_scheduler_simulator_tpu.utils.tracing import TRACER


def unbalanced(work):
    sp = TRACER.span("manual_span")   # unbalanced-span
    sp.__enter__()
    work()
    sp.__exit__(None, None, None)     # not reached if work() raises


def balanced(work):
    with TRACER.span("ok_span"):
        work()


def bad_names():
    TRACER.count("bad-metric.name")            # metric-name
    TRACER.inc("ok_total", **{"__reserved": "x"})   # label-name
    TRACER.observe("ok_seconds", 0.1)
