"""Seeded device-purity violations (analyzed as AST only, roots declared
by the test's manifest): a per-pod Python loop in the hot path, a
host-sync `.item()`, and trace-time nondeterminism inside jitted code.
"""

import time

import jax
import jax.numpy as jnp


def hot_entry(pods, nodes, table):
    total = 0
    for pod in pods:               # pod-loop
        total += helper(pod, table)
    for i in range(len(nodes)):    # pod-loop (range(len(nodes)))
        total += i
    return total


def helper(pod, table):
    score = table[pod]
    return score.item()            # host-sync


@jax.jit
def jitted_step(x):
    noise = time.time()            # nondeterminism inside jit
    return jnp.sum(x) + noise


def cold_helper(pods):
    # NOT reachable from the manifest root: must not be flagged
    return [p for p in pods]


def allowed_loop(pods):
    out = 0
    # kss-analyze: allow(pod-loop)
    for p in pods:
        out += 1
    return out


import numpy as np  # noqa: E402


def eager_compact_fetch(cc, ci):
    # compact-host-sync: an eager D2H of a replay compact field outside
    # _CompactChunks.materialize re-pins the heavy tensors on host
    return np.asarray(cc.packed[ci])


def contiguous_compact_fetch(cc, ci):
    return np.ascontiguousarray(cc.raw16[ci][:8])


def row_loop_over_columns(cols):
    # columnar-row-loop: per-row Python iteration over a bank's row
    # arrays undoes the vectorization the columns exist for
    out = []
    for name in cols.names:
        out.append(name)
    for i in range(len(cols.rv)):
        out.append(i)
    return out


def column_dict_loop_ok(bank, row):
    # NOT flagged: per-COLUMN dict iteration and single-row subscripts
    # are the sanctioned forms
    total = 0
    for key, col in bank.label_cols.items():
        total += col[row] is not None
    for t in bank.taints[row]:
        total += 1
    return total
