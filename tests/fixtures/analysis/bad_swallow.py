"""kss-analyze fixture: seeded swallowed-exception violations.

Never imported; parsed by tests/test_analyze.py through
load_module_file + run_analysis(swallow_modules=...).
"""


def silent_pass():
    try:
        risky()
    except Exception:
        pass


def silent_continue():
    for _ in range(3):
        try:
            risky()
        except (ValueError, OSError):
            continue


def bare_silent():
    try:
        risky()
    except:  # noqa: E722
        ...


def handled_with_tap():
    try:
        risky()
    except Exception:
        TRACER.inc("failures_total")  # noqa: F821 — fixture


def handled_with_reraise():
    try:
        risky()
    except Exception as e:
        raise RuntimeError("wrapped") from e


def handled_with_state():
    err = None
    try:
        risky()
    except Exception as e:
        err = e
    return err


def allowed_silent():
    try:
        risky()
    # kss-analyze: allow(swallowed-exception)
    except Exception:
        pass


def outer_with_nested():
    def inner_a():
        try:
            risky()
        except Exception:
            pass

    def inner_b():
        try:
            risky()
        except Exception:
            pass

    return inner_a, inner_b


def risky():
    raise ValueError("fixture")


TRACER = None
