"""Platform forcing for CPU-only runs (tests, multi-chip dryrun).

This image's TPU is an out-of-tree PJRT plugin ("axon") registered by a
sitecustomize hook in every interpreter; its register() overrides
jax_platforms, so JAX_PLATFORMS=cpu in the environment is NOT sufficient —
jax.devices() still tries to initialise the TPU client and blocks on the
tunnel when no chip grant is available.  force_cpu() makes CPU-only runs
hermetic: pin jax_platforms back to cpu and drop the plugin's backend
factory before any backend is initialised.
"""

from __future__ import annotations

import os


def force_cpu(n_virtual_devices: int | None = None) -> None:
    """Call before any jax computation (and ideally before backends init)."""
    if n_virtual_devices is not None:
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                f"{flags} --xla_force_host_platform_device_count={n_virtual_devices}".strip()
            )
    import jax

    jax.config.update("jax_platforms", "cpu")
    try:
        from jax._src import xla_bridge as xb

        xb._backend_factories.pop("axon", None)
    except Exception:
        pass  # jax internals moved; env var path may still suffice


def apply_env_platform() -> None:
    """Entry-point guard: honor JAX_PLATFORMS=cpu hermetically.

    Process mains call this first so a CPU-only run (CI, laptops, a
    wedged accelerator tunnel) never blocks trying to initialise the
    TPU client — the sitecustomize-registered plugin ignores the plain
    env var (see module docstring)."""
    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        force_cpu()


def effective_cpu_count() -> int:
    """CPUs actually usable by THIS process: the scheduler affinity mask
    (cgroup cpusets / taskset) when available, else os.cpu_count().
    os.cpu_count() alone reports host logical cores, so a 1-CPU container
    on an 8-core host would wrongly enable the multi-core code paths."""
    try:
        return len(os.sched_getaffinity(0)) or 1
    except (AttributeError, OSError):
        return os.cpu_count() or 1


def tune_host_allocator() -> bool:
    """Keep glibc from returning freed large blocks to the kernel.

    The annotation product cycles multi-MB JSON strings; above the default
    mmap threshold (128 KiB) each one is mmap'd and munmap'd, so every
    build page-faults fresh pages — ruinous on hosts whose first-touch
    bandwidth collapses at high resident set (this bench host: ~10x past
    ~8 GB, docs/bench/r04-host-page-backing.json).  Raising the thresholds
    makes the arena REUSE freed pages: steady-state string churn touches
    already-backed memory and never faults.  For BATCH processes (the
    bench, one-shot replays) only — with trim disabled a long-lived
    server would hold its peak heap forever.  Returns True when applied
    (glibc only; silently a no-op elsewhere)."""
    import ctypes

    try:
        libc = ctypes.CDLL(None, use_errno=True)
        mallopt = libc.mallopt
    except (OSError, AttributeError):
        return False
    M_TRIM_THRESHOLD, M_MMAP_THRESHOLD = -1, -3
    ok = mallopt(M_MMAP_THRESHOLD, 1 << 30)   # strings stay in the arena
    ok &= mallopt(M_TRIM_THRESHOLD, 1 << 30)  # arena keeps freed pages
    return bool(ok)
