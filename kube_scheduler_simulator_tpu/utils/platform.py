"""Platform forcing for CPU-only runs (tests, multi-chip dryrun).

This image's TPU is an out-of-tree PJRT plugin ("axon") registered by a
sitecustomize hook in every interpreter; its register() overrides
jax_platforms, so JAX_PLATFORMS=cpu in the environment is NOT sufficient —
jax.devices() still tries to initialise the TPU client and blocks on the
tunnel when no chip grant is available.  force_cpu() makes CPU-only runs
hermetic: pin jax_platforms back to cpu and drop the plugin's backend
factory before any backend is initialised.
"""

from __future__ import annotations

import os


def force_cpu(n_virtual_devices: int | None = None) -> None:
    """Call before any jax computation (and ideally before backends init)."""
    if n_virtual_devices is not None:
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                f"{flags} --xla_force_host_platform_device_count={n_virtual_devices}".strip()
            )
    import jax

    jax.config.update("jax_platforms", "cpu")
    try:
        from jax._src import xla_bridge as xb

        xb._backend_factories.pop("axon", None)
    except Exception:
        pass  # jax internals moved; env var path may still suffice


def apply_env_platform() -> None:
    """Entry-point guard: honor JAX_PLATFORMS=cpu hermetically.

    Process mains call this first so a CPU-only run (CI, laptops, a
    wedged accelerator tunnel) never blocks trying to initialise the
    TPU client — the sitecustomize-registered plugin ignores the plain
    env var (see module docstring)."""
    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        force_cpu()


def effective_cpu_count() -> int:
    """CPUs actually usable by THIS process: the scheduler affinity mask
    (cgroup cpusets / taskset) when available, else os.cpu_count().
    os.cpu_count() alone reports host logical cores, so a 1-CPU container
    on an 8-core host would wrongly enable the multi-core code paths."""
    try:
        return len(os.sched_getaffinity(0)) or 1
    except (AttributeError, OSError):
        return os.cpu_count() or 1


def ensure_malloc_hugepages() -> bool:
    """Re-exec this process once with GLIBC_TUNABLES=glibc.malloc.hugetlb=1
    so glibc madvise(MADV_HUGEPAGE)s its arenas.

    The annotation product is tens of GB of live strings at the full
    benchmark shape; with 4 KiB pages the first touch of every page is a
    fault, and this class of host collapses to ~200 MB/s fault bandwidth
    past ~8 GB resident (docs/bench/r04-host-page-backing.json).  THP
    cuts faults ~512x: measured 450 -> 575 engine cycles/s at 10k x 5k
    on the bench host.  The tunable is only read by glibc at process
    start, hence the re-exec; callers must invoke this FIRST in main(),
    before heavy imports.  Returns False when already active or not
    applicable (non-Linux, THP 'never', KSS_NO_HUGEPAGE_REEXEC=1) — on
    success the process is replaced and the call never returns."""
    import sys

    if not sys.platform.startswith("linux"):
        return False
    cur = os.environ.get("GLIBC_TUNABLES", "")
    if ("glibc.malloc.hugetlb" in cur
            or os.environ.get("KSS_NO_HUGEPAGE_REEXEC") == "1"):
        return False
    try:
        with open("/sys/kernel/mm/transparent_hugepage/enabled") as f:
            if "[never]" in f.read():
                return False
    except OSError:
        return False
    env = dict(os.environ)
    env["GLIBC_TUNABLES"] = ((cur + ":") if cur else "") + "glibc.malloc.hugetlb=1"
    env["KSS_NO_HUGEPAGE_REEXEC"] = "1"  # belt+braces against exec loops
    # `python -m pkg.mod` must re-exec as -m (argv[0] is the module FILE,
    # and running it directly breaks the package's relative imports)
    main_spec = getattr(sys.modules.get("__main__"), "__spec__", None)
    if main_spec is not None and main_spec.name:
        argv = [sys.executable, "-m", main_spec.name] + sys.argv[1:]
    else:
        argv = [sys.executable] + sys.argv
    try:
        os.execve(sys.executable, argv, env)
    except OSError:
        return False


def tune_host_allocator() -> bool:
    """Keep glibc from returning freed large blocks to the kernel.

    The annotation product cycles multi-MB JSON strings; above the default
    mmap threshold (128 KiB) each one is mmap'd and munmap'd, so every
    build page-faults fresh pages — ruinous on hosts whose first-touch
    bandwidth collapses at high resident set (this bench host: ~10x past
    ~8 GB, docs/bench/r04-host-page-backing.json).  Raising the thresholds
    makes the arena REUSE freed pages: steady-state string churn touches
    already-backed memory and never faults.  For BATCH processes (the
    bench, one-shot replays) only — with trim disabled a long-lived
    server would hold its peak heap forever.  Returns True when applied
    (glibc only; silently a no-op elsewhere)."""
    import ctypes

    try:
        libc = ctypes.CDLL(None, use_errno=True)
        mallopt = libc.mallopt
    except (OSError, AttributeError):
        return False
    M_TRIM_THRESHOLD, M_MMAP_THRESHOLD = -1, -3
    ok = mallopt(M_MMAP_THRESHOLD, 1 << 30)   # strings stay in the arena
    ok &= mallopt(M_TRIM_THRESHOLD, 1 << 30)  # arena keeps freed pages
    return bool(ok)
