"""Go time.Duration string parsing ("300ms", "1m30s", "2h45m")."""

from __future__ import annotations

import re

_UNITS = {"ns": 1e-9, "us": 1e-6, "µs": 1e-6, "ms": 1e-3, "s": 1.0, "m": 60.0, "h": 3600.0}
_TOKEN = re.compile(r"(\d+(?:\.\d+)?)(ns|us|µs|ms|s|m|h)")


def parse_duration_seconds(value) -> float:
    """Duration -> seconds. Accepts numbers (already seconds), Go duration
    strings, and plain numeric strings."""
    if isinstance(value, (int, float)):
        return float(value)
    s = str(value).strip()
    try:
        return float(s)
    except ValueError:
        pass
    total, pos = 0.0, 0
    for m in _TOKEN.finditer(s):
        if m.start() != pos:
            raise ValueError(f"invalid duration {value!r}")
        total += float(m.group(1)) * _UNITS[m.group(2)]
        pos = m.end()
    if pos != len(s) or pos == 0:
        raise ValueError(f"invalid duration {value!r}")
    return total
