"""Columnar telemetry history ring (docs/metrics.md "History &
correlation").

Every observability surface before this was a point-in-time snapshot:
`/api/v1/metrics` shows totals NOW, `/api/v1/sessions` shows the SLO
window NOW, and the minute of telemetry that led up to a shed or a wave
abort evaporates between scrapes.  `TelemetryHistory` is the repo's
time axis: a fixed-capacity ring of samples where each tracked series
is ONE float64 numpy column and timestamps are ONE int64 column (the
PR 17 columnar idiom — appending a sample writes one slot per column,
reading a window slices arrays; no per-sample dicts anywhere).

Samples come from two producers sharing one ring (utils/blackbox.py):

  * the `DeviceTelemetry` sampler thread appends every
    KSS_TPU_HISTORY_SAMPLE_S seconds (default 2);
  * every autopilot tick appends one sample built from the exact
    planes the controller planned from, so a decision's `evidence`
    block cites a ring index whose values match bit-for-bit
    (control/autopilot.py decision provenance).

Series naming follows the flattened-counter convention
(`utils/tracing.py counter_totals`): global series are bare names
(counter deltas per sample), per-session series carry a
`{session=<id>}` suffix (`slo.p99{session=tenant-a}`).  A series
absent at a tick stores NaN, which the JSON surfaces emit as null.

Knobs: KSS_TPU_HISTORY=0 turns sampling into a no-op (the bench A/B
baseline), KSS_TPU_HISTORY_CAPACITY sizes the ring (default 1024
samples), KSS_TPU_HISTORY_SAMPLE_S the sampler cadence.  Import
discipline: stdlib + numpy + utils.env only — everything records INTO
this module, never the other way around.
"""

from __future__ import annotations

import os
import threading

import numpy as np

from .env import env_float, env_int

# KSS_TPU_HISTORY=0 reduces sampling to one global load + compare, the
# same zero-overhead shape as KSS_TPU_BLACKBOX=0.  Module global so the
# check never chases a pointer; set_enabled() is the bench A/B's lever.
_ENABLED = os.environ.get("KSS_TPU_HISTORY", "1") != "0"


def enabled() -> bool:
    return _ENABLED


def set_enabled(on: bool) -> bool:
    """Toggle sampling (the bench overhead A/B's same-process lever;
    operators use KSS_TPU_HISTORY=0).  Returns the previous value."""
    global _ENABLED
    prev = _ENABLED
    _ENABLED = bool(on)
    return prev


def sample_interval() -> float:
    """KSS_TPU_HISTORY_SAMPLE_S: background sampler cadence in seconds
    (default 2; <=0 disables the background producer — autopilot ticks
    still append)."""
    return env_float("KSS_TPU_HISTORY_SAMPLE_S", 2.0)


def _capacity() -> int:
    return max(env_int("KSS_TPU_HISTORY_CAPACITY", 1024), 16)


class TelemetryHistory:
    """The ring itself: int64 timestamp column + one float64 column per
    series, addressed by ABSOLUTE sample index (monotonic since reset)
    so `since=` cursors survive wraparound — a reader who falls behind
    sees the window's floor move, never silently-recycled rows."""

    def __init__(self, capacity: int | None = None):
        self._cap = capacity if capacity is not None else _capacity()
        self._mu = threading.Lock()
        # microseconds since the epoch: int64 per the columnar idiom
        # (float64 seconds would quantize at ~0.1us near 2e9 anyway,
        # but the integer column keeps timestamps exact and compact)
        self._ts = np.zeros(self._cap, dtype=np.int64)
        self._cols: dict[str, np.ndarray] = {}
        self._n = 0  # absolute samples written (next write index)

    # --------------------------------------------------------- write

    def append(self, values: dict[str, float], t_us: int) -> int:
        """Write one sample (series -> value); returns its absolute
        index, or -1 when sampling is disabled.  Series not in
        `values` store NaN for this slot; a never-seen series gets a
        fresh NaN-filled column (its pre-history reads as null)."""
        if not _ENABLED:
            return -1
        with self._mu:
            slot = self._n % self._cap
            self._ts[slot] = int(t_us)
            for name, col in self._cols.items():
                col[slot] = values.get(name, np.nan)
            for name in values.keys() - self._cols.keys():
                col = np.full(self._cap, np.nan)
                col[slot] = values[name]
                self._cols[name] = col
            idx = self._n
            self._n += 1
        return idx

    # ---------------------------------------------------------- read

    @staticmethod
    def _match(name: str, session: str | None, wanted: set | None) -> bool:
        if session is not None:
            # a session filter keeps that session's labeled series plus
            # the global (unlabeled) ones — the same scoping rule as
            # /api/v1/metrics?session=
            if "{" in name and not name.endswith(f"{{session={session}}}"):
                return False
        if wanted is not None:
            return name in wanted or name.split("{", 1)[0] in wanted
        return True

    def window(self, series: list[str] | None = None, since: int = 0,
               stride: int = 1, session: str | None = None,
               limit: int | None = None) -> dict:
        """Columnar window read: samples with absolute index >= `since`
        (clamped to what the ring still holds), every `stride`-th one,
        newest-last.  Returns {index: [...], t: [...seconds...],
        series: {name: [...]}, nextIndex, capacity, enabled} — arrays,
        never one dict per sample.  `series` filters by full name or
        bare (label-less) prefix; `session` keeps one session's labeled
        series plus the globals."""
        wanted = set(series) if series else None
        with self._mu:
            n, cap = self._n, self._cap
            lo = max(int(since), n - cap, 0)
            idxs = list(range(lo, n, max(int(stride), 1)))
            if limit is not None and len(idxs) > int(limit):
                idxs = idxs[-int(limit):]
            slots = [i % cap for i in idxs]
            names = [nm for nm in sorted(self._cols)
                     if self._match(nm, session, wanted)]
            cols = {nm: self._cols[nm][slots] for nm in names}
            ts = self._ts[slots]
        return {
            "index": idxs,
            "t": [round(int(v) / 1e6, 6) for v in ts],
            "series": {
                nm: [None if np.isnan(v) else float(v) for v in col]
                for nm, col in cols.items()
            },
            "nextIndex": n,
            "capacity": cap,
            "enabled": _ENABLED,
        }

    def tail(self, k: int = 64, session: str | None = None) -> dict:
        """The trailing k samples — what wave-abort bundles embed so a
        dump answers "what was trending before this" by itself."""
        with self._mu:
            n = self._n
        return self.window(since=max(n - int(k), 0), session=session)

    def value(self, name: str, index: int) -> float | None:
        """One series' value at one absolute index (None when the index
        scrolled out of the ring, the series doesn't exist, or the slot
        holds NaN) — the evidence-matches-ring check in the tests."""
        with self._mu:
            if index < 0 or index >= self._n or index < self._n - self._cap:
                return None
            col = self._cols.get(name)
            if col is None:
                return None
            v = col[index % self._cap]
        return None if np.isnan(v) else float(v)

    def last_index(self) -> int:
        """Absolute index of the newest sample (-1 when empty)."""
        with self._mu:
            return self._n - 1

    # ----------------------------------------------------- lifecycle

    def drop_session(self, session: str | None) -> None:
        """Release a torn-down session's columns (server/sessions.py
        _teardown — per-session series must not outlive the session on
        a churning server; the global columns stay)."""
        if session is None:
            return
        tag = f"{{session={session}}}"
        with self._mu:
            for nm in [nm for nm in self._cols if nm.endswith(tag)]:
                del self._cols[nm]

    def reset(self) -> None:
        """Tests only: clear every column and the index counter."""
        with self._mu:
            self._cols.clear()
            self._ts[:] = 0
            self._n = 0


HISTORY = TelemetryHistory()
