"""Deterministic fault injection: named, seeded chaos seams.

The simulator's failure story used to be "abort and hope": a replay
fault mid-wave stopped the streaming committer and left the backlog to
an undefined next wave.  Before the engine can *survive* injected
failures with provable invariants (the wave failure protocol in
framework/engine.py, docs/fault-injection.md), it needs a way to
*produce* those failures deterministically.  This module is that seam
layer:

  * `fault_point(seam)` — a named injection point threaded through the
    real failure seams (scan dispatch, decision fetch, D2H
    materialization, budget spill, chunk decode, reflector write-back,
    compile-cache build, session create/evict).  With no plan armed it
    is ONE module-global load and compare — zero overhead on the hot
    path, measured by the bench A/B the chaos gate requires.
  * `FaultPlan` — a set of rules (seam x trigger x error type), armed
    programmatically (`arm`/`armed`) or from the environment
    (`KSS_TPU_FAULT_PLAN`: inline JSON, or `@/path/to/plan.json`).
    Triggers are deterministic: `nth` trips on exactly the nth hit of
    the seam; `p` trips a Bernoulli draw from a per-rule RNG seeded by
    (plan seed, rule index, seam) — the same plan replays the same
    trips for the same sequence of seam hits.  Under CONCURRENT hits
    (the chaos harness's parallel sessions and fetch threads) the hit
    sequence itself depends on thread interleaving, so exact trip
    *placement* is best-effort reproducible — the seed pins the plan,
    RNG streams and workload, and the chaos invariants are
    interleaving-independent (byte parity vs the fault-free run holds
    wherever the fault lands).
  * error types (`_ERROR_TYPES`) modeling the real failure classes:
    transient runtime/io/timeout faults, store write `conflict`s (the
    reflector's backoff machinery retries those like real conflicts),
    and structural `memory` faults (the HBM-exhaustion class the
    engine's degradation ladder answers — docs/fault-injection.md).
  * `classify_fault(exc)` — the wave failure protocol's triage:
    "transient" (retry the uncommitted suffix), "structural" (step down
    the residency ladder), or "fatal" (surface immediately: interrupts,
    retry exhaustion — re-retrying a bounded-retry failure multiplies
    the bound).

Every trip counts `fault_injected_total{seam=...}` so chaos runs can
assert the plan actually fired.
"""

from __future__ import annotations

import json
import os
import random
import threading
import zlib
from contextlib import contextmanager

from .retry import RetryTimeout
from .tracing import TRACER

# the documented seam names (docs/fault-injection.md); fault_point
# accepts any string, but plans referencing unknown seams never fire —
# FaultPlan validates against this list so a typo'd plan fails loudly
SEAMS = (
    "replay.scan_dispatch",    # per-chunk device dispatch (framework/replay.py)
    "replay.decision_fetch",   # per-chunk D2H fetch (decisions or full outputs)
    "speculative.round",       # per-round top of the speculative stream
                               # (parallel/speculative.py)
    "fuse.dispatch",           # cross-session fused dispatch, fired on the
                               # REQUESTING thread before it joins a batch
                               # so a trip faults one session only
                               # (parallel/fuse.py)
    "replay.materialize",      # on-demand D2H of a device-resident chunk
    "replay.budget_spill",     # background HBM-budget spill of a chunk
    "decode.chunk",            # native/python chunk decode (store/decode.py)
    "reflector.write_back",    # annotation write-back (store/reflector.py)
    "compile.build",           # XLA scan build (_ScanCacheRegistry)
    "session.create",          # session admission (server/sessions.py)
    "session.evict",           # session teardown/eviction
    "store.columnar_sync",     # columnar bank write mirror — a trip
                               # marks the row opaque; the manifest
                               # stays authoritative (cluster/store.py)
    "autopilot.decide",        # autopilot decision application — a trip
                               # reverts every effector to the static
                               # defaults (control/autopilot.py fail-safe)
)


class InjectedFault(Exception):
    """Base for injected errors: carries the seam it fired at and the
    structural flag the wave failure protocol classifies on."""

    structural = False

    def __init__(self, message: str = "injected fault", seam: str = ""):
        super().__init__(message)
        self.seam = seam


class InjectedRuntimeFault(InjectedFault, RuntimeError):
    """Transient runtime failure (a flaky device call)."""


class InjectedIOFault(InjectedFault, OSError):
    """Transient I/O failure (a dropped transfer)."""


class InjectedTimeout(InjectedFault, TimeoutError):
    """Transient timeout (a stalled link)."""


class InjectedOOM(InjectedFault, MemoryError):
    """Structural device-memory exhaustion (the HBM RESOURCE_EXHAUSTED
    class): the degradation ladder's trigger, not a retry candidate."""

    structural = True


_CONFLICT_CLS: type | None = None


def _conflict_cls() -> type:
    """Injected store-write conflict, built lazily so utils never
    imports cluster at module load (cluster.store imports utils)."""
    global _CONFLICT_CLS
    if _CONFLICT_CLS is None:
        from ..cluster.store import Conflict

        class InjectedConflict(InjectedFault, Conflict):
            """Transient write conflict: heals under the same
            exponential backoff real conflicts do."""

        _CONFLICT_CLS = InjectedConflict
    return _CONFLICT_CLS


def _make_error(kind: str, seam: str, message: str | None):
    msg = message or f"injected {kind} fault at {seam}"
    if kind == "conflict":
        return _conflict_cls()(msg, seam=seam)
    cls = {
        "runtime": InjectedRuntimeFault,
        "io": InjectedIOFault,
        "timeout": InjectedTimeout,
        "memory": InjectedOOM,
    }.get(kind)
    if cls is None:
        raise ValueError(f"unknown fault error type {kind!r}")
    return cls(msg, seam=seam)


_ERROR_TYPES = ("runtime", "io", "timeout", "memory", "conflict")


class FaultRule:
    """One seam's trigger: `nth` (trip on exactly the nth hit) or `p`
    (per-hit Bernoulli from the rule's own seeded RNG).  `times` bounds
    total trips (default 1 for nth rules, unbounded for p rules);
    `sessions` restricts the rule to hits made under those sessions'
    tracer scopes (the chaos isolation invariant: fault one tenant,
    prove the neighbor undisturbed)."""

    __slots__ = ("seam", "error", "nth", "p", "times", "sessions",
                 "message", "hits", "trips", "rng")

    def __init__(self, seam: str, error: str = "runtime",
                 nth: int | None = None, p: float | None = None,
                 times: int | None = None, sessions=None,
                 message: str | None = None):
        if seam not in SEAMS:
            raise ValueError(f"unknown fault seam {seam!r} (want one of "
                             f"{', '.join(SEAMS)})")
        if error not in _ERROR_TYPES:
            raise ValueError(f"unknown fault error type {error!r} (want one "
                             f"of {', '.join(_ERROR_TYPES)})")
        if (nth is None) == (p is None):
            raise ValueError(
                f"rule for {seam!r} needs exactly one of nth= or p=")
        if nth is not None and nth < 1:
            raise ValueError(f"nth must be >= 1, got {nth}")
        if p is not None and not (0.0 <= p <= 1.0):
            raise ValueError(f"p must be in [0, 1], got {p}")
        self.seam = seam
        self.error = error
        self.nth = nth
        self.p = p
        self.times = times if times is not None else (1 if nth else None)
        self.sessions = frozenset(sessions) if sessions else None
        self.message = message
        self.hits = 0
        self.trips = 0
        self.rng: random.Random | None = None  # seeded by the plan


class FaultPlan:
    """A seeded set of FaultRules.  `check(seam)` is called under the
    plan's lock by `fault_point`; rule state (hit counters, RNG draws)
    advances deterministically, so the same plan + the same sequence of
    seam hits trips the same faults."""

    def __init__(self, rules: list[FaultRule], seed: int = 0):
        self.seed = int(seed)
        self.rules = list(rules)
        self._mu = threading.Lock()
        for i, r in enumerate(self.rules):
            r.rng = random.Random(
                (self.seed << 20) ^ (i << 8) ^ zlib.crc32(r.seam.encode()))

    # ------------------------------------------------------------- load

    @classmethod
    def from_dict(cls, doc: dict) -> "FaultPlan":
        rules = [
            FaultRule(
                seam=r["seam"], error=r.get("error", "runtime"),
                nth=r.get("nth"), p=r.get("p"), times=r.get("times"),
                sessions=r.get("sessions"), message=r.get("message"))
            for r in doc.get("rules", ())
        ]
        return cls(rules, seed=doc.get("seed", 0))

    @classmethod
    def from_env(cls) -> "FaultPlan | None":
        """KSS_TPU_FAULT_PLAN: inline JSON, or `@/path` to a JSON file.
        Unset/empty -> None.  A malformed plan raises — arming chaos is
        an explicit operator action and a typo must fail loudly, not
        silently run fault-free."""
        raw = os.environ.get("KSS_TPU_FAULT_PLAN")
        if not raw:
            return None
        if raw.startswith("@"):
            with open(raw[1:], encoding="utf-8") as fh:
                raw = fh.read()
        return cls.from_dict(json.loads(raw))

    # ------------------------------------------------------------ check

    def check(self, seam: str) -> Exception | None:
        """Advance every matching rule's state; return the first
        tripped rule's exception (or None).  Session filters read the
        caller's tracer scope BEFORE taking the plan lock."""
        session = TRACER.current_session()
        with self._mu:
            for r in self.rules:
                if r.seam != seam:
                    continue
                if r.sessions is not None and session not in r.sessions:
                    continue
                r.hits += 1
                if r.times is not None and r.trips >= r.times:
                    continue
                trip = (r.hits == r.nth) if r.nth is not None \
                    else (r.rng.random() < r.p)
                if trip:
                    r.trips += 1
                    return _make_error(r.error, seam, r.message)
        return None

    def stats(self) -> dict:
        with self._mu:
            return {
                "seed": self.seed,
                "rules": [
                    {"seam": r.seam, "error": r.error, "hits": r.hits,
                     "trips": r.trips}
                    for r in self.rules
                ],
            }


# the armed plan: a single module global so the unarmed fast path is one
# load + is-None compare (the chaos gate's zero-overhead requirement)
_PLAN: FaultPlan | None = FaultPlan.from_env()


def fault_point(seam: str) -> None:
    """Named injection point.  No plan armed: near-zero cost.  Armed:
    advances the plan deterministically and raises the rule's error on
    a trip (counted as fault_injected_total{seam=...})."""
    plan = _PLAN
    if plan is None:
        return
    exc = plan.check(seam)
    if exc is not None:
        TRACER.inc("fault_injected_total", seam=seam)
        # black-box evidence: the trip, where it fired, and how the
        # wave failure protocol will triage it (utils/blackbox.py) —
        # imported lazily so the unarmed fast path pays nothing
        from .blackbox import BLACKBOX

        BLACKBOX.record("fault.trip", seam=seam,
                        error=type(exc).__name__,
                        classification=classify_fault(exc))
        raise exc


def arm(plan: FaultPlan) -> FaultPlan:
    global _PLAN
    _PLAN = plan
    return plan


def disarm() -> None:
    global _PLAN
    _PLAN = None


def current_plan() -> FaultPlan | None:
    return _PLAN


@contextmanager
def armed(plan: FaultPlan):
    """Arm `plan` for the duration of a with block (tests, chaos runs).
    Not reentrant: the previous plan (normally None) is restored."""
    global _PLAN
    prev = _PLAN
    _PLAN = plan
    try:
        yield plan
    finally:
        _PLAN = prev


def classify_fault(exc: BaseException) -> str:
    """The wave failure protocol's triage (docs/fault-injection.md):

      * "fatal"      — never retried: non-Exception BaseExceptions
        (interrupts), and RetryTimeout/RetryAborted — an exhausted
        bounded retry must surface, re-retrying multiplies the bound;
      * "structural" — device-memory exhaustion (MemoryError, XLA
        RESOURCE_EXHAUSTED, injected OOM): answered by the degradation
        ladder, not a retry (the wave would just OOM again);
      * "transient"  — everything else: retry the uncommitted suffix
        with bounded backoff.
    """
    if not isinstance(exc, Exception):
        return "fatal"
    if isinstance(exc, RetryTimeout):
        return "fatal"
    if isinstance(exc, InjectedFault):
        return "structural" if exc.structural else "transient"
    if isinstance(exc, MemoryError):
        return "structural"
    if (type(exc).__name__ == "XlaRuntimeError"
            and "RESOURCE_EXHAUSTED" in str(exc)):
        return "structural"
    return "transient"
