from .quantity import parse_quantity, parse_cpu_milli, parse_memory_bytes  # noqa: F401
