"""Wave black box: crash-consistent post-mortem capture + device telemetry.

The engine's own behavior is its least observable part exactly when it
matters most: the degradation ladder (PR 12) and the speculative round
loop (PR 13) make load-bearing decisions — retry, degrade, fall back to
the sequential scan — whose evidence evaporates the moment they fire,
and nothing ever read device memory even though the HBM budget actively
spills chunks.  This module is the always-on flight-data recorder:

  * `BlackBox` — a fixed-size, lock-light ring of structured engine
    events (wave start/end, speculative rounds with batch size / accept
    fraction / ladder rung, fault trips with seam + classification,
    degradation transitions, retry suffixes, budget spills, compile
    builds/quarantines, session admission/eviction).  Recording is one
    short lock hold and a dict append; `KSS_TPU_BLACKBOX=0` turns it
    into a single global load + compare (the bench A/B asserts the
    enabled overhead stays within noise).
  * post-mortem **bundles**: on `_WaveAbort`, a degradation step, a
    chaos-gate failure or an explicit `GET /api/v1/debug/dump`, the
    ring is snapshotted together with the tracer's OPEN spans at the
    time of fault, the labeled-counter deltas since the wave started,
    the armed fault plan, every `KSS_TPU_*` env knob and a device-state
    fingerprint (per-device `memory_stats()`), JSON-immutable.  Wave
    aborts auto-write the bundle to `KSS_TPU_BLACKBOX_DIR` so a crashed
    wave ships its own evidence (docs/fault-injection.md).
  * `validate_dump()` — the schema check `make blackbox-smoke`, the
    chaos harness and the tests share.
  * `SLOTracker` — rolling per-session p50/p99 wave latency and
    cycles/s over a `KSS_TPU_SLO_WINDOW` window, surfaced on
    `/api/v1/sessions` and `/readyz` (docs/metrics.md).
  * `DeviceTelemetry` — a background sampler reading
    `jax.local_devices()[*].memory_stats()` into `hbm_bytes_in_use` /
    `hbm_peak_bytes` gauges (per-device labels + an aggregate), with an
    EXPLICIT `hbm_stats_available 0` no-op where the backend has no
    memory stats (the CPU backend) instead of silently absent gauges.

Import discipline: this module depends only on utils.tracing,
utils.history and utils.env — everything above it (engine, replay,
speculative, faults, sessions) records INTO it, never the other way
around.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque

from . import history as _history
from .env import env_float, env_int
from .history import HISTORY
from .tracing import TRACER

DUMP_VERSION = 1

# KSS_TPU_BLACKBOX=0 turns record() into one global load + compare —
# the same zero-overhead shape as the unarmed fault_point.  Module
# global (not an instance attr) so the hot-path check never chases a
# pointer; set_enabled() is the bench A/B's lever.
_ENABLED = os.environ.get("KSS_TPU_BLACKBOX", "1") != "0"


def enabled() -> bool:
    return _ENABLED


def set_enabled(on: bool) -> bool:
    """Toggle recording (the bench overhead A/B's same-process lever;
    operators use KSS_TPU_BLACKBOX=0).  Returns the previous value.
    The tracer's open-span bookkeeping follows the same flag."""
    global _ENABLED
    prev = _ENABLED
    _ENABLED = bool(on)
    from . import tracing as _tracing

    _tracing.BLACKBOX_OPEN_SPANS = bool(on)
    return prev


def _env_knobs() -> dict[str, str]:
    """Every KSS_TPU_* knob in force — part of every bundle, so a dump
    is reproducible without asking the operator what they had set."""
    return {k: v for k, v in sorted(os.environ.items())
            if k.startswith("KSS_TPU")}


def describe_exception(exc: BaseException | None) -> dict | None:
    """{type, message, seam, classification} for a bundle's cause."""
    if exc is None:
        return None
    from .faults import classify_fault

    out = {"type": type(exc).__name__,
           "message": str(exc)[:500],
           "classification": classify_fault(exc)}
    seam = getattr(exc, "seam", None)
    if seam:
        out["seam"] = seam
    return out


def device_fingerprint() -> dict:
    """Per-device state at dump/sample time: platform, kind, and the
    backend's memory_stats() (bytes in use / peak / limit) when the
    backend exposes them.  `hbm_available` is an EXPLICIT flag: on the
    CPU backend memory_stats() is absent and the fingerprint says so
    instead of silently omitting the numbers."""
    try:
        import jax

        devs = jax.local_devices()
        backend = jax.default_backend()
    except Exception as e:  # jax not initialized / no backend
        return {"available": False, "hbm_available": False,
                "error": f"{type(e).__name__}: {e}"[:200]}
    out = {"available": True, "backend": backend, "hbm_available": False,
           "devices": []}
    for d in devs:
        ent = {"id": int(getattr(d, "id", 0)),
               "platform": str(getattr(d, "platform", "")),
               "kind": str(getattr(d, "device_kind", ""))}
        stats = None
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if stats:
            ent["memory"] = {
                k: int(stats[k]) for k in
                ("bytes_in_use", "peak_bytes_in_use", "bytes_limit",
                 "bytes_reserved", "largest_free_block_bytes")
                if k in stats
            }
            if "bytes_in_use" in (ent["memory"] or {}):
                out["hbm_available"] = True
        else:
            ent["memory"] = None
        out["devices"].append(ent)
    return out


class BlackBox:
    """The event ring + bundle builder.  One instance per process
    (`BLACKBOX`); events carry the recording thread's tracer session
    scope so multi-session dumps stay attributable."""

    def __init__(self, capacity: int | None = None):
        self._cap = (capacity if capacity is not None
                     else max(env_int("KSS_TPU_BLACKBOX_CAPACITY", 4096), 64))
        self._mu = threading.Lock()
        self._ring: deque = deque(maxlen=self._cap)
        self._dropped = 0
        self._seq = 0
        # per-session counter baselines captured at wave start, so a
        # dump reports the DELTAS over the failing wave, not process
        # lifetime totals (None = sessionless direct engine use)
        self._baselines: dict[str | None, dict[str, float]] = {}
        # the most recent stored bundles (dump()); immutable via a JSON
        # round trip so a dump never aliases live engine state
        self._dumps: deque = deque(maxlen=8)
        self._dump_n = 0  # filename uniquifier, allocated under _mu

    # ---------------------------------------------------------- record

    def record(self, kind: str, **fields) -> None:
        """Append one structured event.  Disabled: one global load."""
        if not _ENABLED:
            return
        ev = {"kind": kind, "t": round(time.time(), 6)}
        sid = TRACER.current_session()
        if sid is not None:
            ev["session"] = sid
        tid = TRACER.current_trace()
        if tid is not None:
            ev["trace_id"] = tid
        ev.update(fields)
        with self._mu:
            self._seq += 1
            ev["seq"] = self._seq
            if len(self._ring) == self._cap:
                self._dropped += 1
            self._ring.append(ev)

    def wave_start(self, session: str | None, **fields) -> None:
        """Mark a wave's start: records the event AND captures the
        counter baseline the wave's dump computes deltas against."""
        if not _ENABLED:
            return
        base = TRACER.counter_totals()
        with self._mu:
            self._baselines[session] = base
        self.record("wave.start", **fields)

    # ------------------------------------------------------------ read

    def events(self, session: str | None = None,
               limit: int | None = None) -> list[dict]:
        with self._mu:
            evs = list(self._ring)
        if session is not None:
            evs = [e for e in evs if e.get("session") == session]
        return evs[-limit:] if limit else evs

    def dropped(self) -> int:
        with self._mu:
            return self._dropped

    def counter_deltas(self, session: str | None = None) -> dict[str, float]:
        """Flight-recorder counter movement since the session's last
        wave_start (plain + flattened labeled counters; zero-delta
        entries omitted)."""
        with self._mu:
            base = dict(self._baselines.get(session) or {})
        cur = TRACER.counter_totals()
        return {k: round(v - base.get(k, 0), 6)
                for k, v in cur.items() if v != base.get(k, 0)}

    # ------------------------------------------------------------ dump

    def bundle(self, reason: str, cause: BaseException | None = None,
               session: str | None = None) -> dict:
        """Build (but do not store) a post-mortem bundle."""
        from .faults import current_plan

        plan = current_plan()
        # open spans AT THE TIME OF FAULT: the tracer stashes the
        # open-span tree on the exception at the innermost span it
        # unwinds through — by the time the wave protocol builds this
        # bundle every span has closed, so the live view would be empty
        open_spans = getattr(cause, "_kss_open_spans", None)
        if open_spans is None:
            open_spans = TRACER.open_spans()
        if session is not None:
            # same isolation rule as the event ring: a session-scoped
            # bundle must not show a neighbor's in-flight spans
            open_spans = [s for s in open_spans
                          if s.get("session") == session]
        doc = {
            "version": DUMP_VERSION,
            "reason": reason,
            "time": round(time.time(), 6),
            "session": session,
            "cause": describe_exception(cause),
            # session-scoped bundles carry ONLY that session's events —
            # in multi-tenant serving one tenant's dump must not leak a
            # neighbor's activity (the per-session /debug/dump alias)
            "events": self.events(session=session),
            "events_dropped": self.dropped(),
            "open_spans": open_spans,
            "counter_deltas": self.counter_deltas(session),
            "fault_plan": plan.stats() if plan is not None else None,
            "env": _env_knobs(),
            "device": device_fingerprint(),
            # the trailing telemetry-history window (utils/history.py):
            # a wave-abort dump answers "what was trending before this"
            # by itself — p99 creep, spill bursts, autopilot moves.
            # Session-scoped bundles keep only that session's series
            # (the same isolation rule as events/open_spans above).
            "history": HISTORY.tail(64, session=session),
        }
        # JSON round trip: the bundle must be immutable evidence, never
        # an aliased view of live dicts a later wave keeps mutating
        return json.loads(json.dumps(doc, default=str))

    def dump(self, reason: str, cause: BaseException | None = None,
             session: str | None = None, write: bool = False,
             directory: str | None = None) -> tuple[dict, str | None]:
        """Snapshot a bundle, store it in the recent-dumps ring, and —
        when `write` and a directory is available (`directory` arg or
        KSS_TPU_BLACKBOX_DIR) — persist it to disk.  Returns
        (bundle, path-or-None).  Never raises: a failing dump must not
        mask the fault it describes."""
        try:
            doc = self.bundle(reason, cause=cause, session=session)
        except Exception as e:  # pragma: no cover - defensive
            doc = {"version": DUMP_VERSION, "reason": reason,
                   "time": time.time(), "session": session,
                   "error": f"bundle failed: {type(e).__name__}: {e}"[:300]}
        path = None
        if write:
            d = directory or os.environ.get("KSS_TPU_BLACKBOX_DIR")
            if d:
                try:
                    os.makedirs(d, exist_ok=True)
                    stamp = time.strftime("%Y%m%d-%H%M%S")
                    # pid + a locked counter: two aborts in the same
                    # second (or two processes sharing the dir) must
                    # never overwrite each other's evidence
                    with self._mu:
                        self._dump_n += 1
                        n = self._dump_n
                    fname = (f"blackbox-{stamp}-{os.getpid()}-{n}"
                             f"-{reason}.json")
                    path = os.path.join(d, fname)
                    with open(path, "w", encoding="utf-8") as fh:
                        json.dump(doc, fh, indent=1)
                # a full disk / bad dir must not mask the wave fault
                # kss-analyze: allow(swallowed-exception)
                except OSError:
                    path = None
        doc["path"] = path
        with self._mu:
            self._dumps.append(doc)
        TRACER.inc("blackbox_dumps_total", reason=reason)
        return doc, path

    def recent_dumps(self) -> list[dict]:
        """Metadata of stored bundles, newest last (the full bundle is
        on disk at `path`, or retrievable live via bundle())."""
        with self._mu:
            dumps = list(self._dumps)
        return [{k: d.get(k) for k in
                 ("reason", "time", "session", "cause", "path")}
                for d in dumps]

    def last_dump(self) -> dict | None:
        with self._mu:
            return self._dumps[-1] if self._dumps else None

    def drop_session(self, session: str | None) -> None:
        """Release a torn-down session's counter baseline (session
        eviction calls this — per-session state must not outlive the
        session on a churning server)."""
        with self._mu:
            self._baselines.pop(session, None)

    def reset(self) -> None:
        """Tests only: clear the ring, baselines and stored dumps."""
        with self._mu:
            self._ring.clear()
            self._dumps.clear()
            self._baselines.clear()
            self._dropped = 0


BLACKBOX = BlackBox()


# ------------------------------------------------------- dump validation


_REQUIRED_KEYS = ("version", "reason", "time", "events", "open_spans",
                  "counter_deltas", "env", "device")


def validate_dump(doc: dict, require_fault: bool = False,
                  require_rounds: bool = False) -> dict:
    """Schema check for a post-mortem bundle — shared by the tests,
    `make blackbox-smoke` and the chaos harness.  Raises ValueError
    with the first violation; returns {kinds: {kind: count}} on
    success.  `require_fault` additionally asserts a fault trip with
    seam + classification and a cause; `require_rounds` asserts the
    speculative round history survived into the dump."""
    for k in _REQUIRED_KEYS:
        if k not in doc:
            raise ValueError(f"dump missing key {k!r}")
    if doc["version"] != DUMP_VERSION:
        raise ValueError(f"dump version {doc['version']!r} != {DUMP_VERSION}")
    if not isinstance(doc["events"], list):
        raise ValueError("dump events is not a list")
    kinds: dict[str, int] = {}
    for ev in doc["events"]:
        if "kind" not in ev or "t" not in ev or "seq" not in ev:
            raise ValueError(f"malformed event {ev!r}")
        kinds[ev["kind"]] = kinds.get(ev["kind"], 0) + 1
        if ev["kind"] == "fault.trip":
            for field in ("seam", "classification", "error"):
                if field not in ev:
                    raise ValueError(f"fault.trip missing {field!r}: {ev!r}")
        if ev["kind"] == "speculative.round":
            for field in ("batch", "accepted", "rung", "accept_fraction"):
                if field not in ev:
                    raise ValueError(
                        f"speculative.round missing {field!r}: {ev!r}")
        if ev["kind"] == "autopilot.decide":
            # every autopilot decision is structured evidence
            # (control/autopilot.py): which effector moved which
            # session from what to what, and why
            for field in ("effector", "session", "from", "to", "reason"):
                if field not in ev:
                    raise ValueError(
                        f"autopilot.decide missing {field!r}: {ev!r}")
            # provenance: when the decision carries an evidence block
            # it must be structured (the planes the effector read) and
            # any cited history index must be an integer
            evd = ev.get("evidence")
            if evd is not None:
                if not isinstance(evd, dict):
                    raise ValueError(
                        f"autopilot.decide evidence not a dict: {ev!r}")
                hidx = evd.get("historyIndex")
                if hidx is not None and not isinstance(hidx, int):
                    raise ValueError(
                        f"evidence historyIndex not an int: {ev!r}")
    if not isinstance(doc["counter_deltas"], dict):
        raise ValueError("counter_deltas is not a dict")
    hist = doc.get("history")
    if hist is not None:
        # the embedded trailing window must be the columnar shape
        # (utils/history.py): index/t arrays plus equal-length series
        # columns — never one dict per sample
        if (not isinstance(hist, dict) or "index" not in hist
                or "series" not in hist):
            raise ValueError("history window missing index/series")
        n_rows = len(hist["index"])
        if len(hist.get("t") or []) != n_rows:
            raise ValueError("history t column length != index length")
        if not isinstance(hist["series"], dict):
            raise ValueError("history series is not a dict of columns")
        for nm, col in hist["series"].items():
            if len(col) != n_rows:
                raise ValueError(
                    f"history column {nm!r} length {len(col)} != {n_rows}")
    dev = doc["device"]
    if not isinstance(dev, dict) or "hbm_available" not in dev:
        raise ValueError("device fingerprint missing hbm_available")
    if require_fault:
        if not kinds.get("fault.trip"):
            raise ValueError("dump has no fault.trip event")
        cause = doc.get("cause")
        if not cause or "classification" not in cause:
            raise ValueError("dump has no classified cause")
        # the action the protocol took must be on the record too
        if not (kinds.get("wave.retry") or kinds.get("wave.abort")
                or kinds.get("degrade")):
            raise ValueError("dump records no protocol action "
                             "(wave.retry / wave.abort / degrade)")
        if not doc["counter_deltas"]:
            raise ValueError("dump has empty counter deltas for the wave")
    if require_rounds and not kinds.get("speculative.round"):
        raise ValueError("dump has no speculative.round history")
    return {"kinds": kinds}


# ------------------------------------------------------------ SLO plane


class SLOTracker:
    """Rolling per-session wave SLOs: p50/p99 wave latency and
    cycles/s over the last KSS_TPU_SLO_WINDOW waves (default 64).
    observe_wave() is one deque append under a short lock — cheap
    enough to stay on for every wave; percentiles sort the (small)
    window only when read (/api/v1/sessions, /readyz)."""

    def __init__(self, window: int | None = None):
        self._window = (window if window is not None
                        else max(env_int("KSS_TPU_SLO_WINDOW", 64), 4))
        self._mu = threading.Lock()
        self._waves: dict[str | None, deque] = {}
        # monotonic per-session wave count: the window above freezes
        # when inflow stops, so consumers judging liveness (the
        # autopilot's shed recovery) need a counter that only moves
        # when waves actually run
        self._totals: dict[str | None, int] = {}

    def observe_wave(self, session: str | None, seconds: float,
                     pods: int) -> None:
        if pods <= 0:
            return
        with self._mu:
            dq = self._waves.get(session)
            if dq is None:
                dq = self._waves[session] = deque(maxlen=self._window)
            dq.append((seconds, pods))
            self._totals[session] = self._totals.get(session, 0) + 1

    @staticmethod
    def _pct(sorted_vals: list[float], q: float) -> float:
        i = min(int(q * len(sorted_vals)), len(sorted_vals) - 1)
        return sorted_vals[i]

    def stats(self, session: str | None) -> dict | None:
        """{waves, totalWaves, p50WaveSeconds, p99WaveSeconds,
        cyclesPerSec} over the window, or None when the session never
        ran a wave.  `totalWaves` is the lifetime count — unlike
        `waves` (window occupancy, saturates at `window`) it keeps
        moving while traffic flows, so a frozen window is detectable."""
        with self._mu:
            dq = self._waves.get(session)
            entries = list(dq) if dq else None
            total = self._totals.get(session, 0)
        if not entries:
            return None
        secs = sorted(s for s, _ in entries)
        total_s = sum(s for s, _ in entries)
        total_p = sum(p for _, p in entries)
        return {
            "waves": len(entries),
            "totalWaves": total,
            "window": self._window,
            "p50WaveSeconds": round(self._pct(secs, 0.50), 6),
            "p99WaveSeconds": round(self._pct(secs, 0.99), 6),
            "cyclesPerSec": round(total_p / total_s, 1) if total_s else None,
        }

    def drop_session(self, session: str | None) -> None:
        """Release a torn-down session's window (session eviction)."""
        with self._mu:
            self._waves.pop(session, None)
            self._totals.pop(session, None)

    def snapshot(self) -> dict[str, dict]:
        """{session ("" = sessionless): stats} for every session with
        waves in the window — the /readyz surface."""
        with self._mu:
            keys = list(self._waves.keys())
        out = {}
        for k in keys:
            s = self.stats(k)
            if s is not None:
                out[k if k is not None else ""] = s
        return out

    def reset(self) -> None:
        with self._mu:
            self._waves.clear()
            self._totals.clear()


SLO = SLOTracker()


# ------------------------------------------------------- history feeder


class HistoryFeeder:
    """One tick of the observability planes -> one columnar history
    sample (utils/history.py).

    gather() reads every plane ONCE into plain dicts — SLO windows,
    per-session speculative/spill counter totals, the control-plane
    override state — and sample() derives the ring columns from them.
    The autopilot plans FROM the same returned dicts, so a decision's
    evidence cites a ring index whose values match what the effector
    read bit-for-bit (control/autopilot.py decision provenance), and
    with KSS_TPU_HISTORY=0 the planes are still returned (index -1):
    one code path, parity preserved.

    Global series are per-sample counter DELTAS (the feeder keeps its
    own baselines); per-session series are window stats / fractions at
    sample time.
    """

    # plain (unlabeled) counters whose per-sample deltas become global
    # columns; the labeled speculative/spill families are summed from
    # the per-session planes instead
    _PLAIN = ("pods_scheduled_total", "pods_unschedulable_total",
              "scheduling_waves_total")

    def __init__(self):
        self._mu = threading.Lock()
        self._base: dict[str, float] = {}

    def gather(self) -> dict:
        from ..control import CONTROLS

        return {
            "slo": SLO.snapshot(),
            "accepted": TRACER.labeled_totals(
                "speculative_accepted_total", "session"),
            "rolled": TRACER.labeled_totals(
                "speculative_rolled_back_total", "session"),
            "spilled": TRACER.labeled_totals(
                "device_chunks_spilled_total", "session"),
            "controls": CONTROLS.stats(),
        }

    def sample(self) -> tuple[int, dict]:
        """Gather the planes and append one ring sample.  Returns
        (absolute ring index or -1 when history is off, planes)."""
        planes = self.gather()
        if not _history.enabled():
            return -1, planes
        totals = TRACER.counter_totals()
        values: dict[str, float] = {}
        sums = {
            "speculative_accepted_total":
                sum(planes["accepted"].values()),
            "speculative_rolled_back_total":
                sum(planes["rolled"].values()),
            "device_chunks_spilled_total":
                sum(planes["spilled"].values()),
        }
        with self._mu:
            for name in self._PLAIN:
                cur = float(totals.get(name, 0.0))
                values[name] = cur - self._base.get(name, 0.0)
                self._base[name] = cur
            for name, cur in sums.items():
                values[name] = cur - self._base.get(name, 0.0)
                self._base[name] = cur
            # per-session accept fraction / spill delta this sample
            # (baselines keyed per session; a torn-down session's keys
            # are pruned when its counters vanish from the planes)
            for sid in set(planes["accepted"]) | set(planes["rolled"]):
                a = planes["accepted"].get(sid, 0.0)
                r = planes["rolled"].get(sid, 0.0)
                a_d = a - self._base.get(f"a\x00{sid}", 0.0)
                r_d = r - self._base.get(f"r\x00{sid}", 0.0)
                self._base[f"a\x00{sid}"] = a
                self._base[f"r\x00{sid}"] = r
                if a_d + r_d > 0:
                    values[f"spec.accept{{session={sid}}}"] = round(
                        a_d / (a_d + r_d), 6)
            for sid, sp in planes["spilled"].items():
                sp_d = sp - self._base.get(f"s\x00{sid}", 0.0)
                self._base[f"s\x00{sid}"] = sp
                values[f"spill.delta{{session={sid}}}"] = sp_d
        for sid, stats in planes["slo"].items():
            tag = f"{{session={sid}}}"
            values[f"slo.p50{tag}"] = float(stats["p50WaveSeconds"])
            values[f"slo.p99{tag}"] = float(stats["p99WaveSeconds"])
            cps = stats.get("cyclesPerSec")
            if cps is not None:
                values[f"slo.cps{tag}"] = float(cps)
        # autopilot effector state, explicit for every ACTIVE session
        # (any the SLO plane has seen plus any the control plane is
        # steering): CONTROLS.stats() omits default-state sessions, but
        # the ring must record 0.0 / 1.0 there rather than a gap — a
        # shed on/off transition reconstructs from the columns without
        # guessing what a missing row meant
        ctls = planes["controls"]
        for sid in {s for s in planes["slo"] if s} | set(ctls):
            ctl = ctls.get(sid) or {}
            tag = f"{{session={sid}}}"
            values[f"autopilot.shed{tag}"] = 1.0 if ctl.get("shed") else 0.0
            values[f"autopilot.budget_weight{tag}"] = float(
                ctl.get("budgetWeight") or 1.0)
        idx = HISTORY.append(values, t_us=int(time.time() * 1e6))
        return idx, planes

    def reset(self) -> None:
        """Tests only: forget the delta baselines."""
        with self._mu:
            self._base.clear()


FEEDER = HistoryFeeder()


# ----------------------------------------------------- device telemetry


class DeviceTelemetry:
    """Background HBM sampler: every KSS_TPU_HBM_SAMPLE_S seconds
    (default 5) read each local device's memory_stats() into

      * hbm_bytes_in_use{device=<id>} / hbm_peak_bytes{device=<id>}
        labeled gauges, plus unlabeled aggregates (sums across devices);
      * hbm_stats_available — 1 where the backend reports memory stats,
        0 as the EXPLICIT no-op marker on backends that don't (CPU).

    start() is idempotent; the thread is a daemon and samples once
    immediately, so /api/v1/metrics shows the gauges right after server
    boot.  sample_once() is the direct surface bench and tests use."""

    def __init__(self):
        self._mu = threading.Lock()
        self._thread: threading.Thread | None = None
        # each start() mints a fresh stop event captured by its loop, so
        # a stale stop() can never kill a newer sampler thread
        self._stop: threading.Event | None = None
        # start()/stop() refcount: the sampler is process-global but
        # started per server — the last stopping server ends it, an
        # earlier one must not kill a still-running neighbor's sampling
        self._refs = 0
        self._last: dict | None = None

    def sample_once(self) -> dict:
        fp = device_fingerprint()
        available = bool(fp.get("hbm_available"))
        TRACER.gauge("hbm_stats_available", 1 if available else 0)
        total_use = 0
        total_peak = 0
        if available:
            for ent in fp.get("devices", ()):
                mem = ent.get("memory") or {}
                use = mem.get("bytes_in_use")
                if use is None:
                    continue
                peak = mem.get("peak_bytes_in_use", use)
                TRACER.gauge("hbm_bytes_in_use", use,
                             device=str(ent["id"]))
                TRACER.gauge("hbm_peak_bytes", peak,
                             device=str(ent["id"]))
                total_use += use
                total_peak += peak
            TRACER.gauge("hbm_bytes_in_use", total_use)
            TRACER.gauge("hbm_peak_bytes", total_peak)
        out = {"available": available,
               "backend": fp.get("backend"),
               "bytes_in_use": total_use if available else None,
               "peak_bytes": total_peak if available else None,
               "devices": len(fp.get("devices", ()))}
        with self._mu:
            self._last = out
        return out

    def last(self) -> dict | None:
        with self._mu:
            return self._last

    def start(self, interval: float | None = None) -> None:
        """Start the sampler (idempotent).  interval <= 0 (or
        KSS_TPU_HBM_SAMPLE_S=0) disables the HBM leg; the same thread
        also feeds the telemetry history ring every
        KSS_TPU_HISTORY_SAMPLE_S seconds (utils/history.py) — two
        cadences, one thread, each with its own next-due clock.  No
        thread starts when both legs are off.  The whole start decision
        runs under the lock so two concurrent start() calls can never
        spawn two samplers, and a fresh stop event per thread means a
        racing stop() never leaves a newly started sampler dead."""
        if interval is None:
            interval = env_float("KSS_TPU_HBM_SAMPLE_S", 5.0)
        hist_iv = _history.sample_interval() if _history.enabled() else 0.0
        t = None
        with self._mu:
            self._refs += 1
            # _thread is the INTENT marker (set before start(), cleared
            # only by the last stop()): an is_alive() check would let a
            # second caller slip in between thread creation and start()
            if self._thread is None:
                if interval > 0 or hist_iv > 0:
                    stop = self._stop = threading.Event()

                    def loop():
                        inf = float("inf")
                        hbm_iv = interval if interval > 0 else inf
                        h_iv = hist_iv if hist_iv > 0 else inf
                        now = time.monotonic()
                        next_hbm = now + hbm_iv
                        next_hist = now + h_iv
                        while True:
                            wake = min(next_hbm, next_hist)
                            if stop.wait(max(wake - time.monotonic(),
                                             0.01)):
                                return
                            now = time.monotonic()
                            if now >= next_hbm:
                                try:
                                    self.sample_once()
                                # survive a backend teardown race
                                # kss-analyze: allow(swallowed-exception)
                                except Exception:
                                    pass
                                next_hbm = now + hbm_iv
                            if now >= next_hist:
                                try:
                                    FEEDER.sample()
                                # same contract as the HBM leg
                                # kss-analyze: allow(swallowed-exception)
                                except Exception:
                                    pass
                                next_hist = now + h_iv

                    t = self._thread = threading.Thread(
                        target=loop, daemon=True, name="hbm-sampler")
        self.sample_once()
        if t is not None:
            t.start()

    def stop(self) -> None:
        """Release one start() hold; the sampler thread ends when the
        last holder stops (server shutdown calls this)."""
        with self._mu:
            self._refs = max(self._refs - 1, 0)
            if self._refs:
                return
            if self._stop is not None:
                self._stop.set()
            self._thread = None


TELEMETRY = DeviceTelemetry()
