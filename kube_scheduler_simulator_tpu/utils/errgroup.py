"""Bounded concurrent fan-out with first-error propagation.

Capability parity with the reference's SemaphoredErrGroup (reference:
simulator/util/semaphored_errgroup.go:17-41 — an errgroup whose Go()
acquires one of GOMAXPROCS semaphore permits), used for snapshot
list/apply fan-out and etcd restore (snapshot.go:103-136,
reset/reset.go:63-78)."""

from __future__ import annotations

import os
from concurrent.futures import Future, ThreadPoolExecutor


class SemaphoredErrGroup:
    def __init__(self, limit: int | None = None):
        self._pool = ThreadPoolExecutor(max_workers=limit or os.cpu_count() or 4)
        self._futures: list[Future] = []

    def go(self, fn, *args, **kwargs) -> None:
        """Submit fn; at most `limit` run at once (pool-bounded, so a
        100k-object snapshot does not spawn 100k OS threads)."""
        self._futures.append(self._pool.submit(fn, *args, **kwargs))

    def wait(self) -> None:
        """Block until all submitted work finishes; re-raise the FIRST
        error in submission order (errgroup.Wait)."""
        futures, self._futures = self._futures, []
        first_err: BaseException | None = None
        for f in futures:
            try:
                f.result()
            except BaseException as e:  # noqa: BLE001 — errgroup captures all
                if first_err is None:
                    first_err = e
        self._pool.shutdown(wait=True)
        if first_err is not None:
            raise first_err
