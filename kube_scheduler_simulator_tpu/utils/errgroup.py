"""Bounded concurrent fan-out with first-error propagation.

Capability parity with the reference's SemaphoredErrGroup (reference:
simulator/util/semaphored_errgroup.go:17-41 — an errgroup whose Go()
acquires one of GOMAXPROCS semaphore permits), used for snapshot
list/apply fan-out and etcd restore (snapshot.go:103-136,
reset/reset.go:63-78)."""

from __future__ import annotations

import os
import threading


class SemaphoredErrGroup:
    def __init__(self, limit: int | None = None):
        self._sem = threading.Semaphore(limit or os.cpu_count() or 4)
        self._threads: list[threading.Thread] = []
        self._err_lock = threading.Lock()
        self._first_err: BaseException | None = None

    def go(self, fn, *args, **kwargs) -> None:
        """Run fn concurrently, holding one permit for its duration."""

        def run():
            try:
                fn(*args, **kwargs)
            except BaseException as e:  # noqa: BLE001 — errgroup captures all
                with self._err_lock:
                    if self._first_err is None:
                        self._first_err = e
            finally:
                self._sem.release()

        self._sem.acquire()
        t = threading.Thread(target=run, daemon=True)
        t.start()
        self._threads.append(t)

    def wait(self) -> None:
        """Join everything; re-raise the first error (errgroup.Wait)."""
        for t in self._threads:
            t.join()
        self._threads.clear()
        if self._first_err is not None:
            err, self._first_err = self._first_err, None
            raise err
