"""Retry with exponential backoff.

Same schedule as the reference's util.RetryWithExponentialBackOff
(reference: simulator/util/retry.go:10-27): initial 100ms, factor 3.0,
6 steps.  fn returns (done, error): done=True stops; an error aborts;
(False, None) retries after the next backoff.
"""

from __future__ import annotations

import time
from typing import Callable

INITIAL_DURATION = 0.1
FACTOR = 3.0
STEPS = 6


class RetryTimeout(Exception):
    pass


def retry_with_exponential_backoff(fn: Callable[[], tuple[bool, Exception | None]],
                                   sleep=time.sleep) -> None:
    delay = INITIAL_DURATION
    for step in range(STEPS):
        done, err = fn()
        if err is not None:
            raise err
        if done:
            return
        if step < STEPS - 1:
            sleep(delay)
            delay *= FACTOR
    raise RetryTimeout("timed out waiting for the condition")
