"""Retry with exponential backoff.

Same schedule as the reference's util.RetryWithExponentialBackOff
(reference: simulator/util/retry.go:10-27): initial 100ms, factor 3.0,
6 steps.  fn returns (done, error): done=True stops; an error aborts;
(False, None) retries after the next backoff.

The full schedule sleeps up to ~36s.  A caller being torn down
(session eviction, server shutdown — server/sessions.py) must not ride
that out: pass `stop` (a threading.Event) and the in-flight backoff
wakes the moment it fires, raising RetryAborted instead of sleeping
the teardown through the remaining steps.
"""

from __future__ import annotations

import time
from typing import Callable

INITIAL_DURATION = 0.1
FACTOR = 3.0
STEPS = 6


class RetryTimeout(Exception):
    pass


class RetryAborted(RetryTimeout):
    """The caller's stop event fired mid-retry: the condition was
    neither met nor refuted — the owner is shutting down.  A subclass
    of RetryTimeout so existing exhaustion handling applies."""


def retry_with_exponential_backoff(fn: Callable[[], tuple[bool, Exception | None]],
                                   sleep=time.sleep, stop=None) -> None:
    delay = INITIAL_DURATION
    for step in range(STEPS):
        if stop is not None and stop.is_set():
            raise RetryAborted("retry interrupted by stop event")
        done, err = fn()
        if err is not None:
            raise err
        if done:
            return
        if step < STEPS - 1:
            if stop is not None and sleep is time.sleep:
                # interruptible wait: wakes the instant stop fires
                if stop.wait(delay):
                    raise RetryAborted("retry interrupted by stop event")
            else:
                # injected sleeps (tests) keep their call schedule; the
                # stop check after still bounds a set-mid-sleep teardown
                sleep(delay)
                if stop is not None and stop.is_set():
                    raise RetryAborted("retry interrupted by stop event")
            delay *= FACTOR
    raise RetryTimeout("timed out waiting for the condition")
