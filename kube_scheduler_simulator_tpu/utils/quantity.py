"""Kubernetes resource.Quantity parsing.

Implements the subset of apimachinery's resource.Quantity grammar that node
allocatable / pod request manifests use: plain decimals, the binary-SI
suffixes (Ki Mi Gi Ti Pi Ei) and decimal-SI suffixes (n u m k M G T P E).

CPU is canonicalised to integer millicores, memory/storage/extended
resources to integer base units, matching how the scheduler compares
requests to allocatable (upstream computes MilliCPU/Memory int64 fields in
framework.Resource; the reference feeds those through
simulator/scheduler/plugin/wrappedplugin.go:523-548 untouched).
"""

from __future__ import annotations

import functools as _functools
from fractions import Fraction

_BINARY_SUFFIX = {
    "Ki": 1024,
    "Mi": 1024**2,
    "Gi": 1024**3,
    "Ti": 1024**4,
    "Pi": 1024**5,
    "Ei": 1024**6,
}
_DECIMAL_SUFFIX = {
    "n": Fraction(1, 10**9),
    "u": Fraction(1, 10**6),
    "m": Fraction(1, 1000),
    "": Fraction(1),
    "k": 10**3,
    "M": 10**6,
    "G": 10**9,
    "T": 10**12,
    "P": 10**15,
    "E": 10**18,
}


def _split(s: str) -> tuple[Fraction, Fraction]:
    s = s.strip()
    if not s:
        raise ValueError("empty quantity")
    for suf, mult in _BINARY_SUFFIX.items():
        if s.endswith(suf):
            return Fraction(s[: -len(suf)]), Fraction(mult)
    # decimal suffixes are single-char; check exponent form first ("12e3")
    if s[-1] in _DECIMAL_SUFFIX and not s[-1].isdigit():
        return Fraction(s[:-1]), Fraction(_DECIMAL_SUFFIX[s[-1]])
    return Fraction(s), Fraction(1)


def parse_quantity(value) -> Fraction:
    """Parse a quantity into an exact Fraction of base units."""
    if isinstance(value, (int, float)):
        return Fraction(value)
    num, mult = _split(str(value))
    return num * mult


def parse_cpu_milli(value) -> int:
    """CPU quantity -> integer millicores (ceil, as upstream ScaledValue does)."""
    if type(value) is str:
        return _cpu_milli_str(value)
    q = parse_quantity(value) * 1000
    return int(-(-q.numerator // q.denominator))  # ceil


def parse_memory_bytes(value) -> int:
    """Memory/storage quantity -> integer bytes (ceil)."""
    if type(value) is str:
        return _memory_bytes_str(value)
    q = parse_quantity(value)
    return int(-(-q.numerator // q.denominator))


# quantity strings repeat massively across a pod queue ("1", "500m",
# "1Gi", ...); caching the string->int parse removes the Fraction
# construction from compile_workload's per-pod hot path (measured ~1s of
# a 10k-pod compile).  Strings only — int/float values skip the cache.
@_functools.lru_cache(maxsize=4096)
def _cpu_milli_str(value: str) -> int:
    q = parse_quantity(value) * 1000
    return int(-(-q.numerator // q.denominator))  # ceil


@_functools.lru_cache(maxsize=4096)
def _memory_bytes_str(value: str) -> int:
    q = parse_quantity(value)
    return int(-(-q.numerator // q.denominator))
