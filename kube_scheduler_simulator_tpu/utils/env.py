"""Shared env-var knob parsing.

One coercion rule for every KSS_TPU_* numeric knob (engine failure
protocol, compile quarantine, session admission): unset/empty or
unparsable (including "inf"/"nan" for int knobs) falls back to the
default — an operator typo degrades to documented behavior instead of
crashing a wave.
"""

from __future__ import annotations

import os


def env_int(name: str, default: int) -> int:
    raw = os.environ.get(name)
    if not raw:
        return default
    try:
        return int(float(raw))
    except (ValueError, OverflowError):
        return default


def env_float(name: str, default: float) -> float:
    raw = os.environ.get(name)
    if not raw:
        return default
    try:
        return float(raw)
    except ValueError:
        return default


def env_bool(name: str, default: bool) -> bool:
    """Boolean knob: "0"/"false"/"no"/"off" (any case) is False,
    "1"/"true"/"yes"/"on" is True; unset/empty/unparsable falls back."""
    raw = os.environ.get(name)
    if not raw:
        return default
    v = raw.strip().lower()
    if v in ("0", "false", "no", "off"):
        return False
    if v in ("1", "true", "yes", "on"):
        return True
    return default


def env_switch(name: str, default: bool) -> bool:
    """Boolean knob for subsystems that must fail OFF: unset/empty
    falls back to the default, but an UNRECOGNIZED value disables the
    feature instead of silently keeping it on.  The autopilot
    (control/autopilot.py) rides this — a typo'd KSS_TPU_AUTOPILOT
    yields the static-knob parity baseline, never a half-configured
    controller thread."""
    raw = os.environ.get(name)
    if not raw:
        return default
    return raw.strip().lower() in ("1", "true", "yes", "on")
