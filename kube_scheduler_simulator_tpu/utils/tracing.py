"""Tracing and metrics for the scheduling engine.

Additive over the reference (SURVEY.md §5: the reference has no tracing
beyond the per-Pod annotation record; the upstream scheduler only
blank-imports Prometheus registration, cmd/scheduler/scheduler.go:9-11).
Here the TPU path gets real observability:

- span timings (compile, device eval, bind, reflect, full wave) in a
  bounded ring buffer with per-name aggregates;
- counters (pods scheduled/unschedulable, preemptions, waves);
- Prometheus text exposition + JSON, served at /metrics and
  /api/v1/metrics by the simulator server;
- optional XLA profile capture via jax.profiler (trace start/stop to a
  directory TensorBoard/xprof can read).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from contextlib import contextmanager

_PREFIX = "kss_tpu"


class Tracer:
    def __init__(self, capacity: int = 4096):
        self._lock = threading.Lock()
        self._events: deque = deque(maxlen=capacity)
        self._agg: dict[str, dict] = {}
        self._counters: dict[str, float] = {}
        self._profile_dir: str | None = None

    # ------------------------------------------------------------- spans

    @contextmanager
    def span(self, name: str, **attrs):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            with self._lock:
                self._events.append(
                    {"name": name, "t": time.time(), "seconds": dt, **attrs}
                )
                a = self._agg.setdefault(
                    name, {"count": 0, "total_seconds": 0.0, "max_seconds": 0.0}
                )
                a["count"] += 1
                a["total_seconds"] += dt
                a["max_seconds"] = max(a["max_seconds"], dt)

    def count(self, name: str, n: float = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + n

    # ------------------------------------------------------------ export

    def events(self, limit: int = 200) -> list[dict]:
        with self._lock:
            evs = list(self._events)
        return evs[-limit:]

    def summary(self) -> dict:
        with self._lock:
            spans = {
                k: {**v, "avg_seconds": v["total_seconds"] / max(v["count"], 1)}
                for k, v in self._agg.items()
            }
            return {"spans": spans, "counters": dict(self._counters)}

    def prometheus_text(self) -> str:
        """Prometheus exposition format (the observable analogue of the
        upstream scheduler's /metrics)."""
        s = self.summary()
        out = []
        for name, v in sorted(s["counters"].items()):
            m = f"{_PREFIX}_{name}"
            out.append(f"# TYPE {m} counter")
            out.append(f"{m} {v}")
        for name, a in sorted(s["spans"].items()):
            m = f"{_PREFIX}_span_{name}"
            out.append(f"# TYPE {m}_seconds_total counter")
            out.append(f"{m}_seconds_total {a['total_seconds']}")
            out.append(f"# TYPE {m}_count counter")
            out.append(f"{m}_count {a['count']}")
            out.append(f"# TYPE {m}_seconds_max gauge")
            out.append(f"{m}_seconds_max {a['max_seconds']}")
        return "\n".join(out) + "\n"

    def reset(self) -> None:
        with self._lock:
            self._events.clear()
            self._agg.clear()
            self._counters.clear()

    # -------------------------------------------------------- XLA profile

    def start_xla_profile(self, log_dir: str) -> None:
        import jax

        if self._profile_dir is not None:
            raise RuntimeError(f"profile already running into {self._profile_dir}")
        jax.profiler.start_trace(log_dir)
        self._profile_dir = log_dir

    def stop_xla_profile(self) -> str:
        import jax

        if self._profile_dir is None:
            raise RuntimeError("no profile running")
        jax.profiler.stop_trace()
        d, self._profile_dir = self._profile_dir, None
        return d

    @property
    def profiling(self) -> bool:
        return self._profile_dir is not None


TRACER = Tracer()
